"""External-id → uid assignment for loaders — disk-backed sharded LRU.

Reference semantics: xidmap/xidmap.go:30 — loaders map RDF node names
(blank nodes, IRIs) to uids, leasing uid ranges from Zero; names that parse
as uids ("0x2a", "123") pass through and advance the lease so later leased
blocks can never collide. The reference shards an LRU over badger; this
build mirrors that shape directly:

  - HASH SHARDS: crc32(xid) picks one of N shards; each shard is a plain
    dict while resident and a framed file (`shard_NNNN.xs`) on disk.
  - BOUNDED LRU: with `cache_entries` set, least-recently-used shards
    flush to disk and drop from RAM — live-load xid cardinality is no
    longer capped by host memory (VERDICT gap #3).
  - APPEND LOG (`wal_path`): every NEW mapping appends one fsynced record
    (`sync()` per committed batch); `open()` replays it, so a crashed load
    RESUMES with every identity it had already assigned. `flush()` makes
    the shard files durable and truncates the log — the log only ever
    holds the tail since the last flush, not the whole history.

A map built with neither dirpath nor cache bound degenerates to the old
single-dict behavior (1 shard, no hashing on the hot path).

The whole-map JSON `save`/`load` pair is DEPRECATED in favor of the
sharded on-disk format; `migrate()` converts old files one-shot, and
`load()` keeps reading them so existing bulk outputs stay usable.
"""

from __future__ import annotations

import json
import os
import struct
import warnings
import zlib
from collections import OrderedDict
from dataclasses import dataclass

from dgraph_tpu.coord.zero import LEASE_BLOCK, UidLease

_SHARD_MAGIC = b"DGXS1"
_REC = struct.Struct("<IQ")        # key len, uid
DEFAULT_SHARDS = 32


def parse_uid_literal(xid: str) -> int | None:
    """'0x2a' / '123' → uid, else None (a name to map)."""
    try:
        u = int(xid, 0)
    except ValueError:
        return None
    return u if u > 0 else None


@dataclass
class XidMapStats:
    """LRU observability (satellite: xidmap hit rate on /metrics)."""

    lookups: int = 0
    shard_loads: int = 0           # disk loads (LRU misses)
    evictions: int = 0

    @property
    def hit_rate(self) -> float:
        if not self.lookups:
            return 1.0
        return 1.0 - self.shard_loads / self.lookups


class XidMap:
    def __init__(self, lease: UidLease, block: int = LEASE_BLOCK, *,
                 dirpath: str | None = None,
                 cache_entries: int | None = None,
                 shards: int | None = None) -> None:
        if cache_entries is not None and dirpath is None:
            raise ValueError("a bounded xidmap cache needs a dirpath to "
                             "evict shards into")
        self._lease = lease
        self._block = block
        self._dir = dirpath
        self._nshards = shards if shards is not None else (
            DEFAULT_SHARDS if dirpath is not None else 1)
        self.cache_entries = cache_entries
        # explicit uids that fall inside the CURRENT leased block (never
        # hand out). Bounded O(block): bump_to fences every later block
        # above all previously-seen explicit uids, so entries below a new
        # block's start can never collide again and are pruned — an
        # all-literal-uid input must not grow an O(distinct uids) set
        # that the --xidmap_cache_mb bound can't see
        self._taken: set[int] = set()
        self._next = 0
        self._end = -1   # exhausted
        self._wal = None   # set ONLY by open(): appending to an existing
        # log without replaying it would mint divergent duplicate uids
        self._max_uid = 0
        self.stats = XidMapStats()
        self._dirty: set[int] = set()
        self._lru: OrderedDict[int, None] = OrderedDict()
        self._resident = 0
        if dirpath is not None:
            os.makedirs(dirpath, exist_ok=True)
            meta = self._read_meta()
            if meta:
                self._nshards = int(meta.get("shards", self._nshards))
                self._max_uid = int(meta.get("max_uid", 0))
                self._counts = [int(c) for c in meta.get(
                    "counts", [0] * self._nshards)]
                if not meta.get("clean"):
                    # crashed before flush(): shard files may carry uids
                    # past the meta's last-eviction snapshot
                    self._recover_ceiling_from_shards()
                    if len(self._counts) < self._nshards:
                        self._counts += [0] * (self._nshards
                                               - len(self._counts))
                if self._max_uid:
                    lease.bump_to(self._max_uid)
            else:
                self._counts = [0] * self._nshards
                # dirs from before the eager meta write (or a crash inside
                # the very first eviction): best effort — widen the shard
                # count to cover every file present, recover the ceiling
                self._recover_ceiling_from_shards()
                if self._max_uid:
                    lease.bump_to(self._max_uid)
                self._counts = [0] * self._nshards
                # pin the shard count + shape immediately: a later crash
                # must never re-attach with a DIFFERENT modulus (wrong
                # shard lookup -> missed mapping -> duplicate uid)
                self._write_meta(clean=False)
        else:
            self._counts = [0] * self._nshards
        self._shards: list[dict | None] = [None] * self._nshards

    def _recover_ceiling_from_shards(self) -> None:
        """Crash window: LRU evictions wrote shard files but the crash
        landed before a meta write recorded their ceiling. Attaching those
        shards WITHOUT recovering max_uid would leave the lease low and
        mint already-assigned uids for new xids (silent entity merging) —
        scan the files once, bump the ceiling, and widen the shard count
        past every file index seen."""
        import glob as _glob

        files = sorted(_glob.glob(os.path.join(self._dir, "shard_*.xs")))
        if not files:
            return
        top = max(int(os.path.basename(p)[6:10]) for p in files)
        if top >= self._nshards:
            self._nshards = top + 1
        for path in files:
            with open(path, "rb") as f:
                raw = f.read()
            if raw[:5] != _SHARD_MAGIC:
                continue
            off = 5
            while off + _REC.size <= len(raw):
                klen, uid = _REC.unpack_from(raw, off)
                off += _REC.size + klen
                if uid > self._max_uid:
                    self._max_uid = uid
        if self._max_uid:
            self._lease.bump_to(self._max_uid)

    # -- shard residency ----------------------------------------------------

    def _meta_path(self) -> str:
        return os.path.join(self._dir, "meta.json")

    def _read_meta(self) -> dict | None:
        try:
            with open(self._meta_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _shard_path(self, i: int) -> str:
        return os.path.join(self._dir, f"shard_{i:04d}.xs")

    def _shard_of(self, xid: str) -> int:
        if self._nshards == 1:
            return 0
        return zlib.crc32(xid.encode("utf-8")) % self._nshards

    def _shard(self, i: int) -> dict:
        sh = self._shards[i]
        if sh is None:
            sh = self._load_shard(i)
        if self.cache_entries is not None:
            self._lru[i] = None
            self._lru.move_to_end(i)
            if self._resident > self.cache_entries:
                self._evict(keep=i)
        return sh

    def _load_shard(self, i: int) -> dict:
        sh: dict[str, int] = {}
        if self._dir is not None:
            path = self._shard_path(i)
            if os.path.exists(path):
                self.stats.shard_loads += 1
                with open(path, "rb") as f:
                    raw = f.read()
                assert raw[:5] == _SHARD_MAGIC, f"bad shard magic in {path}"
                off = 5
                while off + _REC.size <= len(raw):
                    klen, uid = _REC.unpack_from(raw, off)
                    off += _REC.size
                    sh[raw[off: off + klen].decode("utf-8")] = uid
                    off += klen
        self._shards[i] = sh
        self._counts[i] = len(sh)
        self._resident += len(sh)
        return sh

    def _write_shard(self, i: int) -> None:
        sh = self._shards[i]
        path = self._shard_path(i)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_SHARD_MAGIC)
            for xid, uid in sh.items():
                kb = xid.encode("utf-8")
                f.write(_REC.pack(len(kb), uid))
                f.write(kb)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _evict(self, keep: int) -> None:
        """Flush + drop least-recently-used shards until under the cache
        bound (never the shard being served, never the last one)."""
        wrote = False
        while self._resident > self.cache_entries and len(self._lru) > 1:
            j, _ = self._lru.popitem(last=False)
            if j == keep:              # newest-by-definition; re-add, stop
                self._lru[j] = None
                break
            if j in self._dirty:
                self._write_shard(j)
                self._dirty.discard(j)
                wrote = True
            self._resident -= self._counts[j]
            self._shards[j] = None
            self.stats.evictions += 1
        if wrote:
            # keep the ceiling on disk ahead of the shard files: a crash
            # after this point re-attaches with max_uid covering every
            # assignment made so far (clean=False -> attach double-checks
            # the shards anyway)
            self._write_meta(clean=False)

    # -- durability ---------------------------------------------------------

    @classmethod
    def open(cls, wal_path: str, lease: UidLease,
             block: int = LEASE_BLOCK, *,
             cache_entries: int | None = None,
             shards: int | None = None) -> "XidMap":
        """Crash-resumable map: attach the shard dir (if one exists or a
        cache bound asks for one), replay the assignment log, then append.
        A torn trailing record (crash mid-write) is dropped — its xid was
        never acked, so the loader re-assigns it."""
        dirpath = wal_path + ".shards"
        if cache_entries is None and not os.path.isdir(dirpath):
            dirpath = None             # legacy pure-log mode
        xm = cls(lease, block, dirpath=dirpath,
                 cache_entries=cache_entries, shards=shards)
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                raw = f.read()
            # a record is durable only when newline-terminated: ANY
            # unterminated tail is torn (a truncated uid still parses as
            # a valid shorter number — parseability cannot detect it) and
            # must be truncated away so the next append cannot fuse onto it
            keep_upto = raw.rfind(b"\n") + 1
            for line in raw[:keep_upto].split(b"\n"):
                if not line:
                    continue
                try:
                    xid_b, uid_b = line.rsplit(b"\t", 1)
                    xid, uid = xid_b.decode("utf-8"), int(uid_b)
                except (ValueError, UnicodeDecodeError):
                    continue         # unparseable complete line: skip
                i = xm._shard_of(xid)
                sh = xm._shard(i)
                if xid not in sh:    # may already live in a flushed shard
                    sh[xid] = uid
                    xm._counts[i] += 1
                    xm._resident += 1
                    xm._dirty.add(i)
                xm._max_uid = max(xm._max_uid, uid)
            if keep_upto < len(raw):
                with open(wal_path, "r+b") as f:
                    f.truncate(keep_upto)
            if xm._max_uid:
                lease.bump_to(xm._max_uid)
        xm._wal = open(wal_path, "ab")
        return xm

    def _log(self, xid: str, uid: int) -> None:
        if self._wal is not None:
            self._wal.write(xid.encode("utf-8") + b"\t" +
                            str(uid).encode() + b"\n")

    def sync(self) -> None:
        """Make all assignments so far durable (call per committed batch:
        an identity must never be re-assigned after its txn was acked)."""
        if self._wal is not None:
            self._wal.flush()
            os.fsync(self._wal.fileno())

    def _write_meta(self, clean: bool) -> None:
        meta = {"shards": self._nshards, "max_uid": self._max_uid,
                "counts": self._counts, "clean": clean}
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())

    def flush(self) -> None:
        """Persist every dirty resident shard + the meta record, THEN
        truncate the append log — shard durability must land before the
        log entries covering it go away."""
        if self._dir is None:
            return
        for i in sorted(self._dirty):
            if self._shards[i] is not None:
                self._write_shard(i)
        self._dirty.clear()
        self._write_meta(clean=True)
        if self._wal is not None:
            self._wal.flush()
            self._wal.truncate(0)
            os.fsync(self._wal.fileno())

    def close(self) -> None:
        if self._dir is not None:
            self.flush()
        if self._wal is not None:
            self.sync()
            self._wal.close()
            self._wal = None

    # -- assignment ---------------------------------------------------------

    def uid(self, xid: str) -> int:
        self.stats.lookups += 1
        i = self._shard_of(xid)
        sh = self._shard(i)
        u = sh.get(xid)
        if u is not None:
            return u
        explicit = parse_uid_literal(xid)
        if explicit is not None:
            # reserve: the uid may only collide if it falls inside the
            # block we're currently consuming (future blocks start past
            # the bump ceiling). Memoize like named nodes — graph data
            # repeats each uid ~degree times, and re-parsing + re-locking
            # the lease per occurrence was the bulk loader's hottest line
            if self._next <= explicit <= self._end:
                self._taken.add(explicit)
            self._lease.bump_to(explicit)
            sh[xid] = explicit           # literal uids need no log (stateless)
            self._counts[i] += 1
            self._resident += 1
            self._dirty.add(i)
            if explicit > self._max_uid:
                self._max_uid = explicit
            return explicit
        while True:
            if self._next > self._end:
                self._next, self._end = self._lease.assign(self._block)
                # the new block starts above every explicit uid seen so
                # far (bump_to fencing): stale reservations are dead
                self._taken = {u for u in self._taken if u >= self._next}
            u = self._next
            self._next += 1
            if u not in self._taken:
                break
        sh[xid] = u
        self._counts[i] += 1
        self._resident += 1
        self._dirty.add(i)
        if u > self._max_uid:
            self._max_uid = u
        self._log(xid, u)
        return u

    def __len__(self) -> int:
        return sum(self._counts)

    # -- deprecated whole-map persistence + migration -----------------------

    def _iter_all(self):
        for i in range(self._nshards):
            resident = self._shards[i] is not None
            sh = self._shards[i] if resident else self._load_shard(i)
            yield from sh.items()
            if not resident and self.cache_entries is not None:
                # transient visit: don't let a full scan blow the cache
                self._resident -= self._counts[i]
                self._shards[i] = None

    def save(self, path: str) -> None:
        """DEPRECATED: whole-map JSON (pre-r10 format). Prefer the sharded
        on-disk dir (construct with dirpath=... and call flush())."""
        warnings.warn("XidMap.save writes the deprecated whole-map JSON "
                      "format; use a dirpath-backed map + flush() instead",
                      DeprecationWarning, stacklevel=2)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(dict(self._iter_all()), f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, lease: UidLease,
             block: int = LEASE_BLOCK) -> "XidMap":
        """Read a deprecated whole-map JSON file (kept so old bulk outputs
        stay loadable; see migrate() for the one-shot conversion)."""
        xm = cls(lease, block)
        sh = xm._shard(0)
        with open(path) as f:
            sh.update({k: int(v) for k, v in json.load(f).items()})
        xm._counts[0] = len(sh)
        xm._resident = len(sh)
        if sh:
            xm._max_uid = max(sh.values())
            lease.bump_to(xm._max_uid)
        return xm

    @classmethod
    def migrate(cls, json_path: str, dirpath: str, lease: UidLease,
                block: int = LEASE_BLOCK) -> "XidMap":
        """One-shot migration: deprecated whole-map JSON → sharded dir.
        Returns the attached sharded map (the JSON file is left in place)."""
        xm = cls(lease, block, dirpath=dirpath)
        with open(json_path) as f:
            for k, v in json.load(f).items():
                i = xm._shard_of(k)
                sh = xm._shard(i)
                if k not in sh:
                    sh[k] = int(v)
                    xm._counts[i] += 1
                    xm._resident += 1
                    xm._dirty.add(i)
                if int(v) > xm._max_uid:
                    xm._max_uid = int(v)
        if xm._max_uid:
            lease.bump_to(xm._max_uid)
        xm.flush()
        return xm
