"""RDF export: posting store → N-Quads (+ schema file), gzip-able.

Reference semantics: worker/export.go:198-359 — each group's leader walks
its tablets converting posting lists back to N-Quads (uids as <0x..>, typed
literals, lang tags, facets) plus a schema file, gzipped. Here the walk is
over the store's DATA tablets at a read_ts; output round-trips through the
bulk loader to an identical store (tests/test_loader.py).
"""

from __future__ import annotations

import base64
import gzip
from dataclasses import dataclass

from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.postings import VALUE_UID
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.types import TypeID, Val, marshal

_TYPE_TAG = {
    TypeID.INT: "xs:int",
    TypeID.FLOAT: "xs:float",
    TypeID.BOOL: "xs:boolean",
    TypeID.DATETIME: "xs:dateTime",
    TypeID.STRING: "xs:string",
    TypeID.GEO: "geo:geojson",
    TypeID.PASSWORD: "pwd:hashed",     # raw hash — re-imports without re-hash
    TypeID.BINARY: "xs:base64Binary",
    TypeID.VECTOR: "xs:float32vector",
}


def _escape(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n").replace("\t", "\\t"))


def _val_literal(v: Val, lang: str) -> str:
    if v.tid == TypeID.DEFAULT:
        body = f'"{_escape(str(v.value))}"'
        return body + (f"@{lang}" if lang else "")
    if v.tid == TypeID.BINARY:
        text = base64.b64encode(marshal(v)).decode("ascii")
    elif v.tid == TypeID.BOOL:
        text = "true" if v.value else "false"
    elif v.tid == TypeID.DATETIME:
        text = v.value.isoformat()
    elif v.tid == TypeID.GEO:
        import json

        text = json.dumps(v.value, separators=(",", ":"))
    elif v.tid == TypeID.VECTOR:
        from dgraph_tpu.utils.types import vector_str

        text = vector_str(v.value)
    else:
        text = str(v.value)
    if lang:
        return f'"{_escape(text)}"@{lang}'
    return f'"{_escape(text)}"^^<{_TYPE_TAG[v.tid]}>'


def _facet_str(facets) -> str:
    parts = []
    for name, fv in facets:
        if fv.tid == TypeID.BOOL:
            parts.append(f"{name}={'true' if fv.value else 'false'}")
        elif fv.tid == TypeID.DATETIME:
            parts.append(f"{name}={fv.value.isoformat()}")
        elif fv.tid in (TypeID.INT, TypeID.FLOAT):
            parts.append(f"{name}={fv.value}")
        else:
            # strings (and anything else) quoted + escaped so the facet
            # grammar round-trips quotes, commas, and parens
            parts.append(f'{name}="{_escape(str(fv.value))}"')
    return " (" + ", ".join(parts) + ")"


@dataclass
class ExportStats:
    quads: int = 0
    predicates: int = 0


def export_rdf(store: Store, out_path: str, read_ts: int | None = None,
               schema_path: str | None = None) -> ExportStats:
    """Write every visible posting at read_ts as N-Quads."""
    read_ts = read_ts if read_ts is not None else store.max_seen_commit_ts
    stats = ExportStats()
    op = gzip.open if out_path.endswith(".gz") else open
    attrs = store.predicates()
    with op(out_path, "wt", encoding="utf-8") as f:
        for attr in attrs:
            stats.predicates += 1
            pred = f"<{attr}>"
            for kb in store.keys_of(K.KeyKind.DATA, attr):
                key = K.parse_key(kb)
                subj = f"<0x{key.uid:x}>"
                for p in store.lists[kb].postings(read_ts):
                    fac = _facet_str(p.facets) if p.facets else ""
                    if p.value is None:
                        if p.uid == VALUE_UID:
                            continue   # placeholder
                        f.write(f"{subj} {pred} <0x{p.uid:x}>{fac} .\n")
                    else:
                        f.write(f"{subj} {pred} "
                                f"{_val_literal(p.value, p.lang)}{fac} .\n")
                    stats.quads += 1
    if schema_path:
        with open(schema_path, "w") as f:
            f.write(store.schema.to_text())
    return stats
