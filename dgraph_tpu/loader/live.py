"""Online (live) loader: batched mutations through a running node.

Reference semantics: dgraph/cmd/live/run.go + batch.go — parse RDF, batch N
quads per txn, M concurrent in-flight txns with retry on ABORTED, xidmap for
blank nodes/IRIs shared across batches so identities stay stable. Here the
loader drives an embedded Node (the in-process analog of the gRPC client);
batches run through the normal Mutate/Commit path, so indexes, conflict
detection, and the WAL all apply — the durable-but-slower sibling of
loader/bulk.py.
"""

from __future__ import annotations

from dataclasses import dataclass

from dgraph_tpu.coord.zero import TxnConflict
from dgraph_tpu.loader.bulk import iter_quads
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.query.rdf import NQuad
from dgraph_tpu.utils.retry import RetryPolicy


@dataclass
class LiveStats:
    quads: int = 0
    txns: int = 0
    aborts: int = 0


def live_load(node, rdf_paths: str | list[str], *, batch: int = 1000,
              retries: int = 3, workers: int = 1,
              xm: XidMap | None = None, xidmap_path: str | None = None,
              xidmap_cache: int | None = None,
              progress=None) -> LiveStats:
    """Stream RDF file(s) into a node as committed transactions.

    xidmap_path: crash-resumable identity log (xidmap/xidmap.go's
    badger-persisted map, in append-log form) — assignments are fsynced
    BEFORE each txn commits, so a re-run of an interrupted load reuses
    every identity it had already assigned instead of minting duplicates.

    xidmap_cache: resident-entry bound for the sharded identity map
    (requires xidmap_path; shards page to <xidmap_path>.shards/): external
    id cardinality is no longer capped by host RAM — the reference's
    badger-backed sharded LRU, xidmap/xidmap.go:30-80.
    """
    paths = [rdf_paths] if isinstance(rdf_paths, str) else list(rdf_paths)
    own_xm = xm is None
    if own_xm:
        if xidmap_cache is not None and not xidmap_path:
            raise ValueError("xidmap_cache needs xidmap_path (the shard "
                             "dir lives next to the log)")
        xm = (XidMap.open(xidmap_path, node.zero.uids,
                          cache_entries=xidmap_cache) if xidmap_path
              else XidMap(node.zero.uids))
    stats = LiveStats()
    # snapshot so a SHARED xm across resumed loads reports per-call deltas,
    # not its cumulative lifetime totals again
    stats0 = (xm.stats.lookups, xm.stats.shard_loads, xm.stats.evictions)
    pending: list = []

    # aborted-txn retries ride the unified policy (utils/retry): full-
    # jitter exponential backoff instead of the old immediate hot loop,
    # deadline-aware (never sleeps past an active budget, never retries
    # DeadlineExceeded/CommitAmbiguous), and the attempts show up on the
    # node's dgraph_retry_total
    policy = RetryPolicy(max_attempts=retries + 1, name="live_load",
                         metrics=getattr(node, "metrics", None))

    def flush():
        if not pending:
            return
        xm.sync()   # identities durable before the txn that uses them

        def attempt():
            try:
                # commit_now routes each batch through the node's group-
                # commit window (storage/writebatch.py): concurrent
                # loader workers share fsyncs and conflict passes
                node.mutate_quads(pending, commit_now=True)
            except TxnConflict:
                stats.aborts += 1
                raise

        policy.run(attempt, retryable=(TxnConflict,))
        stats.txns += 1
        pending.clear()

    for subj, pred, obj, val, lang, facets, star in iter_quads(paths, workers):
        # pin identities through the shared xidmap: same name in different
        # batches must hit the same uid (live/batch.go uid lookups)
        pending.append(NQuad(
            subject=f"0x{xm.uid(subj):x}", predicate=pred,
            object_id=f"0x{xm.uid(obj):x}" if obj else "",
            object_value=val, lang=lang,
            facets=list(facets) if facets else [], star=star))
        stats.quads += 1
        if len(pending) >= batch:
            flush()
            if progress and stats.quads % 100000 < batch:
                progress(stats.quads)
    flush()
    if own_xm:
        xm.close()
    reg = getattr(node, "metrics", None)
    if reg is not None:    # xidmap LRU behavior shows on the node's /metrics
        reg.counter("dgraph_xidmap_lookups_total").inc(
            xm.stats.lookups - stats0[0])
        reg.counter("dgraph_xidmap_shard_loads_total").inc(
            xm.stats.shard_loads - stats0[1])
        reg.counter("dgraph_xidmap_evictions_total").inc(
            xm.stats.evictions - stats0[2])
    return stats
