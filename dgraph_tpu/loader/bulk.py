"""Offline bulk loader: RDF(.gz) → packed posting snapshot, WAL bypassed.

Reference semantics: dgraph/cmd/bulk — a local map/shuffle/reduce:
  map    (mapper.go:121)  parallel RDF chunk parse → (key, posting) entries
  shuffle (shuffle.go)    group by predicate
  reduce (reduce.go:36)   k-way merge per key → bp128-packed PostingList
                          written straight to badger SSTs (no Raft/WAL)
plus xidmap for node names and a schema file.

TPU redesign: the reduce target is this package's packed SoA posting format
(storage/packed.py) installed as PostingList bases at one commit_ts, with
token/reverse/count indexes built directly from numpy-grouped edge arrays —
then one `Store.checkpoint` makes the snapshot durable. A `Node` opened on
the output dir serves queries immediately (uid lease + ts recovery are the
normal restart path, api/server.py Node.__init__).
"""

from __future__ import annotations

import gzip
import os
import pickle
import time
from concurrent.futures import ProcessPoolExecutor
from dataclasses import dataclass

import numpy as np

from dgraph_tpu.coord.zero import UidLease
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage import native, packed
from dgraph_tpu.storage.index import index_tokens
from dgraph_tpu.storage.postings import (Op, Posting, PostingList, lang_uid,
                                         value_fingerprint)
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val, convert


class BulkError(ValueError):
    pass


@dataclass
class BulkStats:
    edges: int = 0            # total postings written (uid + value)
    uid_edges: int = 0
    values: int = 0
    nodes: int = 0            # distinct subjects
    predicates: int = 0
    xids: int = 0             # mapped external ids
    seconds: float = 0.0


CHUNK_LINES = 65536


def _parse_chunk(payload: bytes) -> bytes:
    """Worker: parse one text chunk → pickled column lists (spawn-safe:
    imports stay inside so workers never touch jax/TPU state).

    Columns instead of NQuad objects: unpickling a million dataclasses in
    the parent dominated load time (~40s/M); flat str/None lists unpickle
    ~8x faster (the map/reduce handoff of mapper.go is also a flat
    MapEntry stream, not parsed structs)."""
    from dgraph_tpu.query import rdf
    from dgraph_tpu.utils.types import TypeID, Val

    subs, preds, objs, vals, langs, facets, stars = [], [], [], [], [], [], []
    for line in payload.decode("utf-8").splitlines():
        # fast path for the dominant bulk shape `<s> <p> <o> .` / blank nodes
        # with no literals/facets — 3-4x the full-grammar regex
        if '"' not in line and "(" not in line:
            parts = line.split()
            if (len(parts) == 4 and parts[3] == "."
                    and parts[0][0] in "<_" and parts[1][0] == "<"
                    and parts[2][0] in "<_"):
                subs.append(parts[0][1:-1] if parts[0][0] == "<" else parts[0])
                preds.append(parts[1][1:-1])
                objs.append(parts[2][1:-1] if parts[2][0] == "<" else parts[2])
                vals.append(None)
                langs.append("")
                facets.append(None)
                stars.append(False)
                continue
            if not line.strip() or line.lstrip().startswith("#"):
                continue
        elif "(" not in line and "\\" not in line and line.count('"') == 2:
            # fast path for plain string literals `<s> <p> "text" .` (no
            # escapes/lang/type/facets) — the other dominant bulk shape
            lq = line.index('"')
            rq = line.rindex('"')
            head = line[:lq].split()
            tail = line[rq + 1:].split()
            if (len(head) == 2 and tail == ["."]
                    and (head[0][0] == "_"
                         or (head[0][0] == "<" and head[0][-1] == ">"))
                    and head[1][0] == "<" and head[1][-1] == ">"):
                subs.append(head[0][1:-1] if head[0][0] == "<" else head[0])
                preds.append(head[1][1:-1])
                objs.append("")
                vals.append(Val(TypeID.DEFAULT, line[lq + 1:rq]))
                langs.append("")
                facets.append(None)
                stars.append(False)
                continue
        for q in rdf.parse(line):
            subs.append(q.subject)
            preds.append(q.predicate)
            objs.append(q.object_id)
            vals.append(q.object_value)
            langs.append(q.lang)
            facets.append(tuple(sorted(q.facets)) if q.facets else None)
            stars.append(q.star)
    return pickle.dumps((subs, preds, objs, vals, langs, facets, stars),
                        protocol=pickle.HIGHEST_PROTOCOL)


def _read_chunks(path: str):
    op = gzip.open if path.endswith(".gz") else open
    buf: list[str] = []
    with op(path, "rt", encoding="utf-8") as f:
        for line in f:
            buf.append(line)
            if len(buf) >= CHUNK_LINES:
                yield "".join(buf).encode("utf-8")
                buf = []
    if buf:
        yield "".join(buf).encode("utf-8")


def _map_stage(paths: list[str], workers: int):
    """Parallel parse (the reference's map goroutines, mapper.go:121).

    Yields (subject, predicate, object_id, object_value, lang, facets, star)
    column tuples per chunk."""
    chunks = (c for p in paths for c in _read_chunks(p))
    if workers <= 1:
        for c in chunks:
            yield pickle.loads(_parse_chunk(c))
        return
    import multiprocessing as mp

    ctx = mp.get_context("spawn")   # never fork a process holding TPU state
    # strip TPU-plugin site dirs (exact dir name match, not substring) from
    # the workers' env: their sitecustomize imports jax at interpreter
    # startup (seconds per worker, and pointless — parse workers are
    # pure-CPU string work). Restored in finally; the window where another
    # thread could spawn a subprocess with the reduced path is accepted.
    old_pp = os.environ.get("PYTHONPATH")
    if old_pp is not None:
        os.environ["PYTHONPATH"] = os.pathsep.join(
            p for p in old_pp.split(os.pathsep)
            if os.path.basename(p.rstrip("/")) != ".axon_site")
    try:
        with ProcessPoolExecutor(max_workers=workers, mp_context=ctx) as ex:
            for blob in ex.map(_parse_chunk, chunks):
                yield pickle.loads(blob)
    finally:
        if old_pp is not None:
            os.environ["PYTHONPATH"] = old_pp


def iter_quads(paths: list[str], workers: int):
    """Row iterator over _map_stage for consumers that want NQuad-shaped
    tuples: (subject, predicate, object_id, object_value, lang, facets, star)."""
    for cols in _map_stage(paths, workers):
        yield from zip(*cols)


def _group_rows(subs: np.ndarray, objs: np.ndarray):
    """Sort (subject, object) edge arrays and yield (subject, sorted unique
    object array) per subject — the reduce step's k-way merge, vectorized."""
    order = np.lexsort((objs, subs))
    subs, objs = subs[order], objs[order]
    # global dedupe on the sorted pairs: per-row np.unique calls dominated
    # the reduce step at bulk scale
    if len(subs):
        keep = np.ones(len(subs), bool)
        keep[1:] = (subs[1:] != subs[:-1]) | (objs[1:] != objs[:-1])
        subs, objs = subs[keep], objs[keep]
    uq, starts = np.unique(subs, return_index=True)
    bounds = np.append(starts, len(subs))
    for i, s in enumerate(uq):
        yield int(s), objs[bounds[i]:bounds[i + 1]]


def bulk_load(rdf_paths: str | list[str], schema_text: str, out_dir: str, *,
              workers: int | None = None, commit_ts: int = 1,
              progress=None) -> BulkStats:
    """Load RDF file(s) into a fresh posting snapshot at out_dir."""
    t0 = time.perf_counter()
    paths = [rdf_paths] if isinstance(rdf_paths, str) else list(rdf_paths)
    for p in paths:
        if not os.path.exists(p):
            raise BulkError(f"no such file: {p}")
    store = Store(out_dir)
    if store.lists:
        store.close()
        raise BulkError(f"{out_dir} already contains a posting store")
    workers = workers if workers is not None else min(8, os.cpu_count() or 1)

    lease = UidLease()
    xm = XidMap(lease)
    stats = BulkStats()

    # -- map + shuffle: group parsed quads by predicate ----------------------
    uid_sub: dict[str, list[int]] = {}
    uid_obj: dict[str, list[int]] = {}
    uid_facets: dict[str, dict[tuple[int, int], tuple]] = {}
    val_rows: dict[str, dict[int, list]] = {}   # attr -> subj -> [(lang, Val, facets)]
    n = 0
    xid = xm.uid
    for subs_c, preds_c, objs_c, vals_c, langs_c, facets_c, stars_c in \
            _map_stage(paths, workers):
        for subj, pred, obj, val, lang, facets, star in \
                zip(subs_c, preds_c, objs_c, vals_c, langs_c, facets_c, stars_c):
            if star or pred == "*":
                raise BulkError("deletes are not valid in a bulk load")
            s = xid(subj)
            if obj:
                uid_sub.setdefault(pred, []).append(s)
                uid_obj.setdefault(pred, []).append(xid(obj))
                if facets:
                    uid_facets.setdefault(pred, {})[(s, uid_obj[pred][-1])] = facets
            else:
                val_rows.setdefault(pred, {}).setdefault(s, []).append(
                    (lang, val, facets or ()))
        n += len(subs_c)
        if progress and n % 500000 < len(subs_c):
            progress(n)

    with store.suspend_wal():
        for e in parse_schema(schema_text or ""):
            store.set_schema(e)
        lists: dict[bytes, PostingList] = {}
        subjects_seen: set[int] = set()
        batch_keys: list[bytes] = []        # packed in one pack_many pass
        batch_rows: list[np.ndarray] = []
        batch_postings: dict[bytes, dict[int, Posting]] = {}

        def emit(kb: bytes, row: np.ndarray,
                 postings: dict[int, Posting] | None = None) -> None:
            batch_keys.append(kb)
            batch_rows.append(row)
            if postings:
                batch_postings[kb] = postings

        # -- reduce: uid predicates → packed CSR-style bases -----------------
        for attr in sorted(uid_sub):
            entry = store.schema.ensure(attr, TypeID.UID)
            subs = np.asarray(uid_sub[attr], dtype=np.int64)
            objs = np.asarray(uid_obj[attr], dtype=np.int64)
            facets = uid_facets.get(attr, {})
            rev_sub: dict[int, list[int]] = {}
            deg_pairs: list[tuple[int, int]] = []
            for s, row in _group_rows(subs, objs):
                postings = None
                if facets:
                    postings = {o: Posting(o, Op.SET, facets=facets[(s, o)])
                                for o in row.tolist() if (s, o) in facets}
                emit(K.data_key(attr, s).encode(), row, postings)
                subjects_seen.add(s)
                stats.uid_edges += len(row)
                if entry.reverse:
                    for o in row.tolist():
                        rev_sub.setdefault(int(o), []).append(s)
                if entry.count:
                    deg_pairs.append((len(row), s))
            for o, srcs in rev_sub.items():
                emit(K.reverse_key(attr, o).encode(),
                     np.unique(np.asarray(srcs, dtype=np.int64)))
            if entry.count:
                by_deg: dict[int, list[int]] = {}
                for d, s in deg_pairs:
                    by_deg.setdefault(d, []).append(s)
                for d, ss in by_deg.items():
                    emit(K.count_key(attr, d).encode(),
                         np.unique(np.asarray(ss, dtype=np.int64)))

        # -- reduce: value predicates → value bases + token indexes ----------
        for attr in sorted(val_rows):
            if attr in uid_sub:
                raise BulkError(
                    f"predicate <{attr}> carries both uid edges and literal "
                    f"values in the input — pick one representation")
            first_val = next(iter(val_rows[attr].values()))[0][1]
            entry = store.schema.ensure(attr, first_val.tid)
            tokens: dict[bytes, list[int]] = {}
            for s, triples in val_rows[attr].items():
                slots, postings = [], {}
                for lang, v, fa in triples:
                    if entry.type_id not in (TypeID.DEFAULT, v.tid):
                        try:
                            v = convert(v, entry.type_id)
                        except ValueError as e:
                            raise BulkError(
                                f"predicate <{attr}>, subject 0x{s:x}: "
                                f"{e}") from e
                    slot = value_fingerprint(v) if entry.is_list \
                        else lang_uid(lang)
                    slots.append(slot)
                    postings[slot] = Posting(slot, Op.SET, v, lang, fa)
                    if entry.indexed:
                        for tk in index_tokens(entry, v, lang):
                            tokens.setdefault(tk, []).append(s)
                    stats.values += 1
                emit(K.data_key(attr, s).encode(),
                     np.unique(np.asarray(slots, dtype=np.uint64)), postings)
                subjects_seen.add(s)
            for tk, ss in tokens.items():
                emit(K.index_key(attr, tk).encode(),
                     np.unique(np.asarray(ss, dtype=np.int64)))

        # one vectorized pack across every list (reduce.go's per-key pack,
        # batched for numpy)
        for kb, pu in zip(batch_keys, native.pack_many(batch_rows)):
            pl = PostingList()
            pl.base_ts = commit_ts
            pl.base_packed = pu
            pl.base_postings = batch_postings.get(kb, {})
            lists[kb] = pl

        store.bulk_install(lists, commit_ts)
        stats.nodes = len(subjects_seen)
        stats.predicates = len(uid_sub) + len(val_rows)
        stats.xids = len(xm)
        stats.edges = stats.uid_edges + stats.values
    store.checkpoint(commit_ts)
    if out_dir:
        xm.save(os.path.join(out_dir, "xidmap.json"))
    store.close()
    stats.seconds = time.perf_counter() - t0
    return stats
