"""Offline bulk loader: RDF(.gz) → packed posting snapshot, WAL bypassed.

Reference semantics: dgraph/cmd/bulk — a local map/shuffle/reduce:
  map    (mapper.go:121)  parallel RDF chunk parse → (key, posting) entries
  shuffle (shuffle.go)    group by predicate
  reduce (reduce.go:36)   k-way merge per key → bp128-packed PostingList
                          written straight to badger SSTs (no Raft/WAL)
plus xidmap for node names and a schema file.

TPU redesign: the reduce target is this package's packed SoA posting format
(storage/packed.py) installed as PostingList bases at one commit_ts, with
token/reverse/count indexes built directly from numpy-grouped edge arrays —
then one `Store.checkpoint` makes the snapshot durable. A `Node` opened on
the output dir serves queries immediately (uid lease + ts recovery are the
normal restart path, api/server.py Node.__init__).

Two reduce tiers share one map stage and one snapshot writer:

  - in-RAM (default): all parsed columns group in dicts, one vectorized
    pack, `bulk_install` + `Store.checkpoint` — fastest when the dataset
    fits in host memory.
  - OUT-OF-CORE (`spill_mb`): mapped edges spill as sorted per-predicate
    runs (ingest/spill.py, the reference's mapper.go:121-175 shape), a
    streaming k-way merge feeds the reduce, and packed rows stream
    straight into DGTS3 tablet sections (ingest/snapwrite.py) — peak RAM
    is the spill budget + merge buffers, independent of graph size, and
    the output is BYTE-IDENTICAL to the in-RAM path.
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import shutil
import time
from concurrent.futures import ProcessPoolExecutor
from dataclasses import dataclass

import numpy as np

from dgraph_tpu.coord.zero import UidLease
from dgraph_tpu.ingest import spill as _spill
from dgraph_tpu.ingest.snapwrite import SnapshotWriter
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage import native, packed
from dgraph_tpu.storage.index import index_tokens
from dgraph_tpu.storage.postings import (Op, Posting, PostingList, lang_uid,
                                         value_fingerprint)
from dgraph_tpu.storage.store import Store, posting_to_json
from dgraph_tpu.utils import log
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val, convert


def _check_vector_dim(entry, v, attr: str, s: int) -> None:
    """float32vector literal vs the schema's @index(vector(dim: D)) —
    reject the load with a typed error instead of folding a ragged row
    (NaN components were already rejected at parse, types.parse_vector)."""
    if v.tid == TypeID.VECTOR and entry.vector is not None and \
            len(v.value) != entry.vector.dim:
        raise BulkError(
            f"predicate <{attr}>, subject 0x{s:x}: vector dimension "
            f"{len(v.value)} != schema dim {entry.vector.dim}")


class BulkError(ValueError):
    pass


@dataclass
class BulkStats:
    edges: int = 0            # total postings written (uid + value)
    uid_edges: int = 0
    values: int = 0
    nodes: int = 0            # distinct subjects
    predicates: int = 0
    xids: int = 0             # mapped external ids
    seconds: float = 0.0
    # out-of-core tier (spill_mb): ingest observability satellite
    spill_bytes: int = 0      # bytes written to sorted run files
    spill_runs: int = 0       # run files written
    merge_fanin: int = 0      # max runs k-way-merged for one channel
    buffered_peak: int = 0    # max in-RAM map-buffer estimate
    xidmap_hit_rate: float = 1.0


CHUNK_LINES = 65536


def _parse_chunk(payload: bytes) -> bytes:
    """Worker: parse one text chunk → pickled column lists (spawn-safe:
    imports stay inside so workers never touch jax/TPU state).

    Columns instead of NQuad objects: unpickling a million dataclasses in
    the parent dominated load time (~40s/M); flat str/None lists unpickle
    ~8x faster (the map/reduce handoff of mapper.go is also a flat
    MapEntry stream, not parsed structs)."""
    from dgraph_tpu.query import rdf
    from dgraph_tpu.utils.types import TypeID, Val

    subs, preds, objs, vals, langs, facets, stars = [], [], [], [], [], [], []
    for line in payload.decode("utf-8").splitlines():
        # fast path for the dominant bulk shape `<s> <p> <o> .` / blank nodes
        # with no literals/facets — 3-4x the full-grammar regex
        if '"' not in line and "(" not in line:
            parts = line.split()
            if (len(parts) == 4 and parts[3] == "."
                    and parts[0][0] in "<_" and parts[1][0] == "<"
                    and parts[2][0] in "<_"):
                subs.append(parts[0][1:-1] if parts[0][0] == "<" else parts[0])
                preds.append(parts[1][1:-1])
                objs.append(parts[2][1:-1] if parts[2][0] == "<" else parts[2])
                vals.append(None)
                langs.append("")
                facets.append(None)
                stars.append(False)
                continue
            if not line.strip() or line.lstrip().startswith("#"):
                continue
        elif "(" not in line and "\\" not in line and line.count('"') == 2:
            # fast path for plain string literals `<s> <p> "text" .` (no
            # escapes/lang/type/facets) — the other dominant bulk shape
            lq = line.index('"')
            rq = line.rindex('"')
            head = line[:lq].split()
            tail = line[rq + 1:].split()
            if (len(head) == 2 and tail == ["."]
                    and (head[0][0] == "_"
                         or (head[0][0] == "<" and head[0][-1] == ">"))
                    and head[1][0] == "<" and head[1][-1] == ">"):
                subs.append(head[0][1:-1] if head[0][0] == "<" else head[0])
                preds.append(head[1][1:-1])
                objs.append("")
                vals.append(Val(TypeID.DEFAULT, line[lq + 1:rq]))
                langs.append("")
                facets.append(None)
                stars.append(False)
                continue
        for q in rdf.parse(line):
            subs.append(q.subject)
            preds.append(q.predicate)
            objs.append(q.object_id)
            vals.append(q.object_value)
            langs.append(q.lang)
            facets.append(tuple(sorted(q.facets)) if q.facets else None)
            stars.append(q.star)
    return pickle.dumps((subs, preds, objs, vals, langs, facets, stars),
                        protocol=pickle.HIGHEST_PROTOCOL)


def _read_chunks(path: str):
    op = gzip.open if path.endswith(".gz") else open
    buf: list[str] = []
    with op(path, "rt", encoding="utf-8") as f:
        for line in f:
            buf.append(line)
            if len(buf) >= CHUNK_LINES:
                yield "".join(buf).encode("utf-8")
                buf = []
    if buf:
        yield "".join(buf).encode("utf-8")


def _map_stage(paths: list[str], workers: int):
    """Parallel parse (the reference's map goroutines, mapper.go:121).

    Yields (subject, predicate, object_id, object_value, lang, facets, star)
    column tuples per chunk."""
    chunks = (c for p in paths for c in _read_chunks(p))
    if workers <= 1:
        for c in chunks:
            yield pickle.loads(_parse_chunk(c))
        return
    import multiprocessing as mp

    ctx = mp.get_context("spawn")   # never fork a process holding TPU state
    # strip TPU-plugin site dirs (exact dir name match, not substring) from
    # the workers' env: their sitecustomize imports jax at interpreter
    # startup (seconds per worker, and pointless — parse workers are
    # pure-CPU string work). Restored in finally; the window where another
    # thread could spawn a subprocess with the reduced path is accepted.
    old_pp = os.environ.get("PYTHONPATH")
    if old_pp is not None:
        os.environ["PYTHONPATH"] = os.pathsep.join(
            p for p in old_pp.split(os.pathsep)
            if os.path.basename(p.rstrip("/")) != ".axon_site")
    try:
        with ProcessPoolExecutor(max_workers=workers, mp_context=ctx) as ex:
            for blob in ex.map(_parse_chunk, chunks):
                yield pickle.loads(blob)
    finally:
        if old_pp is not None:
            os.environ["PYTHONPATH"] = old_pp


def iter_quads(paths: list[str], workers: int):
    """Row iterator over _map_stage for consumers that want NQuad-shaped
    tuples: (subject, predicate, object_id, object_value, lang, facets, star)."""
    for cols in _map_stage(paths, workers):
        yield from zip(*cols)


def _group_rows(subs: np.ndarray, objs: np.ndarray):
    """Sort (subject, object) edge arrays and yield (subject, sorted unique
    object array) per subject — the reduce step's k-way merge, vectorized."""
    order = np.lexsort((objs, subs))
    subs, objs = subs[order], objs[order]
    # global dedupe on the sorted pairs: per-row np.unique calls dominated
    # the reduce step at bulk scale
    if len(subs):
        keep = np.ones(len(subs), bool)
        keep[1:] = (subs[1:] != subs[:-1]) | (objs[1:] != objs[:-1])
        subs, objs = subs[keep], objs[keep]
    uq, starts = np.unique(subs, return_index=True)
    bounds = np.append(starts, len(subs))
    for i, s in enumerate(uq):
        yield int(s), objs[bounds[i]:bounds[i + 1]]


def bulk_load(rdf_paths: str | list[str], schema_text: str, out_dir: str, *,
              workers: int | None = None, commit_ts: int = 1,
              progress=None, spill_mb: float | None = None,
              xidmap_cache: int | None = None, metrics=None) -> BulkStats:
    """Load RDF file(s) into a fresh posting snapshot at out_dir.

    spill_mb: in-RAM map-buffer budget in MB — when set, the out-of-core
    tier runs (sorted spill runs + streaming merge/reduce; byte-identical
    output, bounded RSS). xidmap_cache: resident xid→uid entry bound for
    the sharded identity map (None = unbounded). metrics: optional
    utils/metrics.Registry — in-process (embedded-node) loads feed the
    dgraph_ingest_*/dgraph_xidmap_* counters so they show on /metrics."""
    t0 = time.perf_counter()
    paths = [rdf_paths] if isinstance(rdf_paths, str) else list(rdf_paths)
    for p in paths:
        if not os.path.exists(p):
            raise BulkError(f"no such file: {p}")
    store = Store(out_dir)
    if store.lists:
        store.close()
        raise BulkError(f"{out_dir} already contains a posting store")
    workers = workers if workers is not None else min(8, os.cpu_count() or 1)
    if spill_mb:
        if not out_dir:
            store.close()
            raise BulkError("spill_mb needs a durable out_dir for run files")
        return _bulk_load_spill(paths, schema_text, out_dir, store, workers,
                                commit_ts, progress,
                                int(spill_mb * (1 << 20)), xidmap_cache, t0,
                                metrics)

    lease = UidLease()
    xm = XidMap(lease, dirpath=os.path.join(out_dir, "xidmap")
                if out_dir else None, cache_entries=xidmap_cache)
    stats = BulkStats()

    # -- map + shuffle: group parsed quads by predicate ----------------------
    uid_sub: dict[str, list[int]] = {}
    uid_obj: dict[str, list[int]] = {}
    uid_facets: dict[str, dict[tuple[int, int], tuple]] = {}
    val_rows: dict[str, dict[int, list]] = {}   # attr -> subj -> [(lang, Val, facets)]
    n = 0
    xid = xm.uid
    for subs_c, preds_c, objs_c, vals_c, langs_c, facets_c, stars_c in \
            _map_stage(paths, workers):
        for subj, pred, obj, val, lang, facets, star in \
                zip(subs_c, preds_c, objs_c, vals_c, langs_c, facets_c, stars_c):
            if star or pred == "*":
                raise BulkError("deletes are not valid in a bulk load")
            s = xid(subj)
            if obj:
                uid_sub.setdefault(pred, []).append(s)
                uid_obj.setdefault(pred, []).append(xid(obj))
                if facets:
                    uid_facets.setdefault(pred, {})[(s, uid_obj[pred][-1])] = facets
            else:
                val_rows.setdefault(pred, {}).setdefault(s, []).append(
                    (lang, val, facets or ()))
        n += len(subs_c)
        if progress and n % 500000 < len(subs_c):
            progress(n)

    with store.suspend_wal():
        for e in parse_schema(schema_text or ""):
            store.set_schema(e)
        lists: dict[bytes, PostingList] = {}
        subjects_seen: set[int] = set()
        batch_keys: list[bytes] = []        # packed in one pack_many pass
        batch_rows: list[np.ndarray] = []
        batch_postings: dict[bytes, dict[int, Posting]] = {}

        def emit(kb: bytes, row: np.ndarray,
                 postings: dict[int, Posting] | None = None) -> None:
            batch_keys.append(kb)
            batch_rows.append(row)
            if postings:
                batch_postings[kb] = postings

        # -- reduce: uid predicates → packed CSR-style bases -----------------
        for attr in sorted(uid_sub):
            entry = store.schema.ensure(attr, TypeID.UID)
            subs = np.asarray(uid_sub[attr], dtype=np.int64)
            objs = np.asarray(uid_obj[attr], dtype=np.int64)
            facets = uid_facets.get(attr, {})
            rev_sub: dict[int, list[int]] = {}
            deg_pairs: list[tuple[int, int]] = []
            for s, row in _group_rows(subs, objs):
                postings = None
                if facets:
                    postings = {o: Posting(o, Op.SET, facets=facets[(s, o)])
                                for o in row.tolist() if (s, o) in facets}
                emit(K.data_key(attr, s).encode(), row, postings)
                subjects_seen.add(s)
                stats.uid_edges += len(row)
                if entry.reverse:
                    for o in row.tolist():
                        rev_sub.setdefault(int(o), []).append(s)
                if entry.count:
                    deg_pairs.append((len(row), s))
            for o, srcs in rev_sub.items():
                emit(K.reverse_key(attr, o).encode(),
                     np.unique(np.asarray(srcs, dtype=np.int64)))
            if entry.count:
                by_deg: dict[int, list[int]] = {}
                for d, s in deg_pairs:
                    by_deg.setdefault(d, []).append(s)
                for d, ss in by_deg.items():
                    emit(K.count_key(attr, d).encode(),
                         np.unique(np.asarray(ss, dtype=np.int64)))

        # -- reduce: value predicates → value bases + token indexes ----------
        for attr in sorted(val_rows):
            if attr in uid_sub:
                raise BulkError(
                    f"predicate <{attr}> carries both uid edges and literal "
                    f"values in the input — pick one representation")
            first_val = next(iter(val_rows[attr].values()))[0][1]
            entry = store.schema.ensure(attr, first_val.tid)
            tokens: dict[bytes, list[int]] = {}
            for s, triples in val_rows[attr].items():
                slots, postings = [], {}
                for lang, v, fa in triples:
                    if entry.type_id not in (TypeID.DEFAULT, v.tid):
                        try:
                            v = convert(v, entry.type_id)
                        except ValueError as e:
                            raise BulkError(
                                f"predicate <{attr}>, subject 0x{s:x}: "
                                f"{e}") from e
                    _check_vector_dim(entry, v, attr, s)
                    slot = value_fingerprint(v) if entry.is_list \
                        else lang_uid(lang)
                    slots.append(slot)
                    postings[slot] = Posting(slot, Op.SET, v, lang, fa)
                    if entry.indexed:
                        for tk in index_tokens(entry, v, lang):
                            tokens.setdefault(tk, []).append(s)
                    stats.values += 1
                emit(K.data_key(attr, s).encode(),
                     np.unique(np.asarray(slots, dtype=np.uint64)), postings)
                subjects_seen.add(s)
            for tk, ss in tokens.items():
                emit(K.index_key(attr, tk).encode(),
                     np.unique(np.asarray(ss, dtype=np.int64)))

        # one vectorized pack across every list (reduce.go's per-key pack,
        # batched for numpy)
        for kb, pu in zip(batch_keys, native.pack_many(batch_rows)):
            pl = PostingList()
            pl.base_ts = commit_ts
            pl.base_packed = pu
            pl.base_postings = batch_postings.get(kb, {})
            lists[kb] = pl

        store.bulk_install(lists, commit_ts)
        stats.nodes = len(subjects_seen)
        stats.predicates = len(uid_sub) + len(val_rows)
        stats.xids = len(xm)
        stats.edges = stats.uid_edges + stats.values
    store.checkpoint(commit_ts)
    xm.close()     # sharded identity map lands next to the snapshot
    store.close()
    stats.xidmap_hit_rate = xm.stats.hit_rate
    stats.seconds = time.perf_counter() - t0
    _ingest_metrics(metrics, stats, xm)
    return stats


def _ingest_metrics(reg, stats: BulkStats, xm: XidMap) -> None:
    """Feed an embedded node's registry (satellite: ingest counters on
    /metrics). The offline CLI has no registry — there the same numbers
    ride BulkStats and the structured 'bulk load done' log event."""
    if reg is None:
        return
    reg.counter("dgraph_ingest_spill_bytes_total").inc(stats.spill_bytes)
    reg.counter("dgraph_ingest_spill_runs_total").inc(stats.spill_runs)
    if stats.merge_fanin:
        reg.counter("dgraph_ingest_merge_fanin").set(stats.merge_fanin)
    reg.counter("dgraph_xidmap_lookups_total").inc(xm.stats.lookups)
    reg.counter("dgraph_xidmap_shard_loads_total").inc(xm.stats.shard_loads)
    reg.counter("dgraph_xidmap_evictions_total").inc(xm.stats.evictions)


# -- out-of-core tier ---------------------------------------------------------

_ROW_BATCH = 4096          # rows per pack_many call in the streaming reduce


class _SectionBatch:
    """Stream rows into one tablet section, packing in bounded batches —
    pack()/pack_many() are per-row independent, so any batching yields the
    byte-identical columns the in-RAM path's single global pack produces."""

    __slots__ = ("sec", "ts", "keys", "rows", "posts")

    def __init__(self, sec, base_ts: int) -> None:
        self.sec = sec
        self.ts = base_ts
        self.keys: list[bytes] = []
        self.rows: list[np.ndarray] = []
        self.posts: list[bytes] = []

    def add(self, kb: bytes, row: np.ndarray, post: bytes = b"") -> None:
        self.keys.append(kb)
        self.rows.append(row)
        self.posts.append(post)
        if len(self.keys) >= _ROW_BATCH:
            self.flush()

    def flush(self) -> None:
        if not self.keys:
            return
        for kb, pu, post in zip(self.keys, native.pack_many(self.rows),
                                self.posts):
            self.sec.add_row(kb, self.ts, pu, post)
        self.keys.clear()
        self.rows.clear()
        self.posts.clear()


def _post_json(postings: dict[int, Posting] | None) -> bytes:
    """Same serialization Store's checkpoint uses for base_postings — the
    byte-identity contract between the two reduce tiers."""
    if not postings:
        return b""
    return json.dumps([posting_to_json(p) for p in postings.values()]).encode()


def _bulk_load_spill(paths: list[str], schema_text: str, out_dir: str,
                     store: Store, workers: int, commit_ts: int, progress,
                     spill_bytes: int, xidmap_cache: int | None,
                     t0: float, metrics=None) -> BulkStats:
    """External-memory bulk load (reference cmd/bulk shape): map spills
    sorted per-predicate runs, the reduce k-way-merges them and streams
    packed rows straight into DGTS3 tablet sections. RAM is bounded by
    the spill budget + merge chunk buffers + the xidmap cache — never by
    graph size."""
    try:
        return _bulk_load_spill_inner(
            paths, schema_text, out_dir, store, workers, commit_ts,
            progress, spill_bytes, xidmap_cache, t0, metrics)
    except BaseException:
        # embedded callers live on past a BulkError: release the store's
        # WAL fd and reap the graph-sized run files + half-written snapshot
        store.close()
        shutil.rmtree(os.path.join(out_dir, ".spill"), ignore_errors=True)
        try:
            os.unlink(os.path.join(out_dir, "snapshot.bin.tmp"))
        except OSError:
            pass
        raise


def _bulk_load_spill_inner(paths: list[str], schema_text: str, out_dir: str,
                           store: Store, workers: int, commit_ts: int,
                           progress, spill_bytes: int,
                           xidmap_cache: int | None,
                           t0: float, metrics=None) -> BulkStats:
    lg = log.get_logger("bulk")
    lease = UidLease()
    xm = XidMap(lease, dirpath=os.path.join(out_dir, "xidmap"),
                cache_entries=xidmap_cache)
    stats = BulkStats()
    tmp_dir = os.path.join(out_dir, ".spill")
    sstats = _spill.SpillStats()
    pool = _spill.SpillSet(tmp_dir, spill_bytes, sstats)
    pool.on_flush = lambda st: lg.info(
        "spill", runs=st.spill_runs, bytes=st.spill_bytes)
    pairs = _spill.UidPairSpiller(pool)
    frames = _spill.FramedSpiller(pool)
    with store.suspend_wal():   # schema durability comes from snapshot meta
        for e in parse_schema(schema_text or ""):
            store.set_schema(e)

    # -- map: parse + xid + spill into per-(kind, predicate) channels -------
    uid_preds: set[str] = set()
    val_preds: dict[str, TypeID] = {}   # pred -> first-seen value type
    n = 0
    xid = xm.uid
    u64 = lambda u: u.to_bytes(8, "big")  # noqa: E731 — sort-key encoding
    for subs_c, preds_c, objs_c, vals_c, langs_c, facets_c, stars_c in \
            _map_stage(paths, workers):
        for subj, pred, obj, val, lang, facets, star in \
                zip(subs_c, preds_c, objs_c, vals_c, langs_c, facets_c,
                    stars_c):
            if star or pred == "*":
                raise BulkError("deletes are not valid in a bulk load")
            s = xid(subj)
            if obj:
                if pred in val_preds:
                    raise BulkError(
                        f"predicate <{pred}> carries both uid edges and "
                        f"literal values in the input — pick one "
                        f"representation")
                uid_preds.add(pred)
                o = xid(obj)
                pairs.add(("d", pred), s, o)
                entry = store.schema.get(pred)
                if entry is not None and entry.reverse:
                    pairs.add(("r", pred), o, s)
                if facets:
                    frames.add(("f", pred), u64(s) + u64(o),
                               pickle.dumps(facets,
                                            pickle.HIGHEST_PROTOCOL))
            else:
                if pred in uid_preds:
                    raise BulkError(
                        f"predicate <{pred}> carries both uid edges and "
                        f"literal values in the input — pick one "
                        f"representation")
                if pred not in val_preds:
                    val_preds[pred] = val.tid
                frames.add(("v", pred), u64(s),
                           pickle.dumps((lang, val, facets or ()),
                                        pickle.HIGHEST_PROTOCOL))
        n += len(subs_c)
        if progress and n % 500000 < len(subs_c):
            progress(n)
    pool.flush()
    lg.info("map done", quads=n, spill_runs=sstats.spill_runs,
            spill_mb=round(sstats.spill_bytes / (1 << 20), 1))

    # -- reduce: merge runs, stream packed rows into tablet sections --------
    subj_ch = ("s", "")              # distinct-subject accounting channel
    snap_tmp = os.path.join(out_dir, "snapshot.bin.tmp")
    with open(snap_tmp, "wb") as f:
        w = SnapshotWriter(f, commit_ts, spool_max=store.SNAP_SPOOL_MAX)

        for attr in sorted(uid_preds):
            entry = store.schema.ensure(attr, TypeID.UID)
            batch = _SectionBatch(
                w.section(int(K.KeyKind.DATA), attr), commit_ts)
            facet_it = iter(_spill.merge_framed(frames.runs(("f", attr)),
                                                sstats))
            fpend = next(facet_it, None)

            def facets_for(s: int):
                nonlocal fpend
                out = {}
                skey = u64(s)
                while fpend is not None and fpend[0][:8] <= skey:
                    if fpend[0][:8] == skey:
                        out[int.from_bytes(fpend[0][8:], "big")] = \
                            pickle.loads(fpend[2])   # last occurrence wins
                    fpend = next(facet_it, None)
                return out

            for s, row in _spill.merge_pairs(pairs.runs(("d", attr)),
                                             sstats):
                fmap = facets_for(s)
                postings = {int(o): Posting(int(o), Op.SET,
                                            facets=fmap[int(o)])
                            for o in row.tolist()
                            if int(o) in fmap} if fmap else None
                batch.add(K.data_key(attr, s).encode(), row,
                          _post_json(postings))
                stats.uid_edges += len(row)
                pairs.add(subj_ch, s, 0)
                if entry.count:
                    pairs.add(("c", attr), len(row), s)
            batch.flush()
            pairs.discard(("d", attr))
            frames.discard(("f", attr))
            if entry.reverse:
                rbatch = _SectionBatch(
                    w.section(int(K.KeyKind.REVERSE), attr), commit_ts)
                for o, srcs in _spill.merge_pairs(pairs.runs(("r", attr)),
                                                  sstats):
                    rbatch.add(K.reverse_key(attr, o).encode(), srcs)
                rbatch.flush()
                pairs.discard(("r", attr))
            if entry.count:
                pool.flush()
                cbatch = _SectionBatch(
                    w.section(int(K.KeyKind.COUNT), attr), commit_ts)
                for d, ss in _spill.merge_pairs(pairs.runs(("c", attr)),
                                                sstats):
                    cbatch.add(K.count_key(attr, d).encode(), ss)
                cbatch.flush()
                pairs.discard(("c", attr))

        for attr in sorted(val_preds):
            entry = store.schema.ensure(attr, val_preds[attr])
            batch = _SectionBatch(
                w.section(int(K.KeyKind.DATA), attr), commit_ts)
            tok_ch = ("t", attr)
            saw_tokens = False
            for key, payloads in _spill.group_framed(
                    _spill.merge_framed(frames.runs(("v", attr)), sstats)):
                s = int.from_bytes(key, "big")
                slots, postings = [], {}
                for pb in payloads:
                    lang, v, fa = pickle.loads(pb)
                    if entry.type_id not in (TypeID.DEFAULT, v.tid):
                        try:
                            v = convert(v, entry.type_id)
                        except ValueError as e:
                            raise BulkError(
                                f"predicate <{attr}>, subject 0x{s:x}: "
                                f"{e}") from e
                    _check_vector_dim(entry, v, attr, s)
                    slot = value_fingerprint(v) if entry.is_list \
                        else lang_uid(lang)
                    slots.append(slot)
                    postings[slot] = Posting(slot, Op.SET, v, lang, fa)
                    if entry.indexed:
                        for tk in index_tokens(entry, v, lang):
                            frames.add(tok_ch, tk, u64(s))
                            saw_tokens = True
                    stats.values += 1
                batch.add(K.data_key(attr, s).encode(),
                          np.unique(np.asarray(slots, dtype=np.uint64)),
                          _post_json(postings))
                pairs.add(subj_ch, s, 0)
            batch.flush()
            frames.discard(("v", attr))
            if saw_tokens:
                pool.flush()
                ibatch = _SectionBatch(
                    w.section(int(K.KeyKind.INDEX), attr), commit_ts)
                for tk, subs in _spill.group_framed(
                        _spill.merge_framed(frames.runs(tok_ch), sstats)):
                    ss = np.unique(np.frombuffer(
                        b"".join(subs), dtype=">u8").astype(np.int64))
                    ibatch.add(K.index_key(attr, tk).encode(), ss)
                ibatch.flush()
                frames.discard(tok_ch)

        # distinct subjects across every DATA tablet (stats.nodes), via the
        # same merge machinery — no resident subject set
        pool.flush()
        stats.nodes = sum(1 for _ in _spill.merge_pairs(
            pairs.runs(subj_ch), sstats))
        pairs.discard(subj_ch)

        w.finish({"schema": store.schema.to_text(),
                  "max_commit_ts": commit_ts})
    os.replace(snap_tmp, os.path.join(out_dir, "snapshot.bin"))
    shutil.rmtree(tmp_dir, ignore_errors=True)

    stats.predicates = len(uid_preds) + len(val_preds)
    stats.xids = len(xm)
    stats.edges = stats.uid_edges + stats.values
    stats.spill_bytes = sstats.spill_bytes
    stats.spill_runs = sstats.spill_runs
    stats.merge_fanin = sstats.merge_fanin
    stats.buffered_peak = sstats.buffered_peak
    stats.xidmap_hit_rate = xm.stats.hit_rate
    xm.close()
    store.close()
    stats.seconds = time.perf_counter() - t0
    _ingest_metrics(metrics, stats, xm)
    lg.info("reduce done", rows=w.rows,
            peak_transient_mb=round(w.peak_transient / (1 << 20), 1),
            merge_fanin=stats.merge_fanin,
            xidmap_hit_rate=round(stats.xidmap_hit_rate, 4))
    return stats
