"""Per-request resource cost ledger + the /debug/top sliding-window profiler.

The observability gap after PR 4: device time, transfer bytes, and
traversed edges existed only as GLOBAL counters (utils/metrics.py) and
per-span annotations (obs/otrace.py, sampled). Neither answers "what did
THIS query cost" or "which plan shape is burning the device" — the
questions the SF100 scale gate and multi-tenant QoS both need.

Model (Dapper-style, like otrace): a request entry point (Node.query,
ClusterClient.query, worker serve_task) mints a CostLedger and installs
it on a contextvar; every execution seam below — Executor._traced_dispatch
(per-task attribution), the device-kernel sites in query/task.py,
DeviceBatcher (batched kernel cost apportioned to members by slot size),
MeshExecutor fused programs, ResidencyManager uploads, DispatchGate waits
and sheds — charges the current ledger. Workers ship their ledger BACK to
the querying node in gRPC trailing metadata (WIRE_KEY, next to the span
payload), so the root assembles ONE cluster-wide cost record with
per-group sub-records; there is no out-of-band collector.

The unarmed fast path is one contextvar read returning None: a node
started with --no_cost_ledger must measure nothing (bench.py `obs` gates
the armed overhead < 2% on the warm mixed battery).

Completed records land in a CostBook: a bounded sliding window that
powers GET /debug/top (rank plan shapes / predicates / endpoints by
device ms, bytes, edges over the trailing window) and keeps a per-shape
EWMA baseline of device cost — a record whose device_ms exceeds
k x baseline is flagged as a cost regression into the slow-query ring
even when the query finishes under --slow_query_ms.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import threading
import time
from collections import deque

from . import devprof

# gRPC trailing-metadata key for the shipped record (-bin carries bytes)
WIRE_KEY = "dgt-cost-bin"

_current: contextvars.ContextVar["CostLedger | None"] = \
    contextvars.ContextVar("dgt_cost_ledger", default=None)


def current() -> "CostLedger | None":
    """The active ledger on this execution context, or None (unarmed)."""
    return _current.get()


class scope:
    """Install a ledger (or None) for the dynamic extent of a request.
    Re-entrant and thread-correct: the contextvar token restores whatever
    the enclosing frame had, so a batch leader can suppress gate-level
    attribution with scope(None) while apportioning manually."""

    __slots__ = ("_lg", "_token")

    def __init__(self, lg: "CostLedger | None") -> None:
        self._lg = lg

    def __enter__(self):
        self._token = _current.set(self._lg)
        return self._lg

    def __exit__(self, *a):
        _current.reset(self._token)
        return False


class _TaskScope:
    """Attributes nested kernel charges to one predicate (a stack: the
    fused ANN pipeline dispatches a filter task inside a root task)."""

    __slots__ = ("_lg", "_attr")

    def __init__(self, lg: "CostLedger", attr: str) -> None:
        self._lg = lg
        self._attr = attr

    def __enter__(self):
        self._lg._push_attr(self._attr)
        return self

    def __exit__(self, *a):
        self._lg._pop_attr()
        return False


class CostLedger:
    """One request's resource cost accumulator.

    All mutators take the ledger's own lock: hedged RPCs and batch
    leaders charge a ledger from threads other than the request's own
    (contextvars are copied into the hedge pool; batch runners hold
    explicit references captured at submit time)."""

    __slots__ = ("_lock", "endpoint", "shape", "tenant", "t0", "wall_ms",
                 "device_ms", "h2d_bytes", "d2h_bytes", "upload_bytes",
                 "edges", "rows", "tasks", "gate_wait_ms", "compile_ms",
                 "subs", "outcomes", "per_pred", "kernels", "groups",
                 "_attrs", "_kernel_depth")

    def __init__(self, endpoint: str = "", shape: str = "",
                 tenant: str = "") -> None:
        self._lock = threading.Lock()
        self.endpoint = endpoint
        self.shape = shape
        self.tenant = tenant          # requesting namespace ("" = default)
        self.t0 = time.perf_counter()
        self.wall_ms = 0.0
        self.device_ms = 0.0          # device-kernel wall ms (fenced sites)
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.upload_bytes = 0         # residency warm->HBM uploads at serve
        self.edges = 0                # traversed edges
        self.rows = 0                 # value/index rows scanned host-side
        self.tasks = 0                # dispatched tasks
        self.gate_wait_ms = 0.0       # dispatch-gate queueing
        self.compile_ms = 0.0         # XLA compiles this request triggered
        self.subs: tuple = ()         # subscription ids (endpoint="live")
        self.outcomes: dict[str, int] = {}
        # attr -> [device_ms, edges, bytes, tasks]
        self.per_pred: dict[str, list] = {}
        self.kernels: dict[str, float] = {}   # kernel name -> device ms
        # worker addr -> merged remote record dict (the shipped payload)
        self.groups: dict[str, dict] = {}
        self._attrs: list[str] = []
        self._kernel_depth = 0       # open _KernelTimer windows

    # ---------------------------------------------------------------- scopes

    def task(self, attr: str) -> _TaskScope:
        return _TaskScope(self, attr)

    def _push_attr(self, attr: str) -> None:
        with self._lock:
            self._attrs.append(attr)

    def _pop_attr(self) -> None:
        with self._lock:
            if self._attrs:
                self._attrs.pop()

    def _pred_locked(self, attr: str) -> list:
        row = self.per_pred.get(attr)
        if row is None:
            row = self.per_pred[attr] = [0.0, 0, 0, 0]
        return row

    # -------------------------------------------------------------- charging

    def add_kernel(self, kernel: str, ms: float, h2d: int = 0,
                   d2h: int = 0, attr: str | None = None) -> None:
        """One device-kernel execution: fenced wall ms + transfer bytes,
        attributed to the current task's predicate (or `attr`)."""
        with self._lock:
            self.device_ms += ms
            self.h2d_bytes += int(h2d)
            self.d2h_bytes += int(d2h)
            self.kernels[kernel] = self.kernels.get(kernel, 0.0) + ms
            a = attr if attr is not None else \
                (self._attrs[-1] if self._attrs else "")
            if a.startswith("~"):
                a = a[1:]            # reverse reads charge the tablet
            if a:
                row = self._pred_locked(a)
                row[0] += ms
                row[2] += int(h2d) + int(d2h)

    def add_task(self, attr: str, edges: int) -> None:
        """One dispatched task completed (cache tiers + gate inside)."""
        with self._lock:
            self.tasks += 1
            self.edges += int(edges)
            row = self._pred_locked(attr)
            row[1] += int(edges)
            row[3] += 1

    def add_rows(self, n: int) -> None:
        with self._lock:
            self.rows += int(n)

    def attribute_pred_ms(self, attr: str, ms: float) -> None:
        """Re-attribute already-counted device ms to a predicate row
        WITHOUT touching the totals — for fused multi-predicate programs
        (mesh.plan) whose one launch is apportioned across hops after
        the per-hop edge counts are known."""
        if attr.startswith("~"):
            attr = attr[1:]
        if not attr or ms <= 0:
            return
        with self._lock:
            self._pred_locked(attr)[0] += ms

    def add_gate_wait(self, ms: float) -> None:
        with self._lock:
            self.gate_wait_ms += ms

    def add_compile(self, ms: float) -> None:
        """XLA compile wall ms this request triggered (the devprof
        jax.monitoring listener books it) — kept SEPARATE from device_ms
        so a first-touch compile doesn't poison the shape's EWMA
        regression baseline, while /debug/top?by=compile_ms still ranks
        the shapes paying for retraces."""
        with self._lock:
            self.compile_ms += ms

    def in_kernel(self) -> bool:
        """True while a kernel-timing window is open on this ledger — the
        dispatch gate consults it so injected device-latency faults are
        not charged a second time inside an enclosing kernel timer."""
        return self._kernel_depth > 0

    @contextlib.contextmanager
    def kernel_window(self):
        """Open a bare kernel-timing window (no charge of its own): the
        batcher's _timed_gate_run uses it so the gate's injected-fault
        charges are suppressed while the batched dt — which already
        contains them and is apportioned to every member — is measured."""
        with self._lock:
            self._kernel_depth += 1
        try:
            yield
        finally:
            with self._lock:
                self._kernel_depth -= 1

    def add_upload(self, nbytes: int) -> None:
        with self._lock:
            self.upload_bytes += int(nbytes)
            self.h2d_bytes += int(nbytes)

    def note(self, outcome: str, n: int = 1) -> None:
        """Count one cache/batch/shed/retry outcome."""
        with self._lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + n

    # ---------------------------------------------------- remote assembly

    def merge_remote(self, addr: str, rec: dict) -> None:
        """Graft a callee's shipped record under this ledger (one entry
        per worker address; repeated RPCs to the same worker sum)."""
        if not rec:
            return
        with self._lock:
            g = self.groups.get(addr)
            if g is None:
                self.groups[addr] = dict(rec)
                # per-addr sub-dicts must be owned, not aliased
                for k in ("out", "pred", "kern"):
                    if k in rec:
                        self.groups[addr][k] = {
                            a: (list(v) if isinstance(v, list) else v)
                            for a, v in rec[k].items()}
                return
            for k in ("device_ms", "wall_ms", "gate_wait_ms",
                      "compile_ms"):
                g[k] = g.get(k, 0.0) + rec.get(k, 0.0)
            for k in ("h2d", "d2h", "upload", "edges", "rows", "tasks"):
                g[k] = g.get(k, 0) + rec.get(k, 0)
            for o, n in rec.get("out", {}).items():
                g.setdefault("out", {})
                g["out"][o] = g["out"].get(o, 0) + n
            for a, row in rec.get("pred", {}).items():
                g.setdefault("pred", {})
                cur = g["pred"].get(a)
                if cur is None:
                    g["pred"][a] = list(row)
                else:
                    for i in range(4):
                        cur[i] += row[i]
            for kn, ms in rec.get("kern", {}).items():
                g.setdefault("kern", {})
                g["kern"][kn] = g["kern"].get(kn, 0.0) + ms

    # ------------------------------------------------------------- totals

    def finish(self) -> None:
        self.wall_ms = (time.perf_counter() - self.t0) * 1e3

    def _local_locked(self) -> dict:
        return {"wall_ms": round(self.wall_ms, 3),
                "device_ms": round(self.device_ms, 3),
                "gate_wait_ms": round(self.gate_wait_ms, 3),
                "compile_ms": round(self.compile_ms, 3),
                "h2d": self.h2d_bytes, "d2h": self.d2h_bytes,
                "upload": self.upload_bytes,
                "edges": self.edges, "rows": self.rows,
                "tasks": self.tasks,
                "out": dict(self.outcomes),
                "pred": {a: [round(r[0], 3), r[1], r[2], r[3]]
                         for a, r in self.per_pred.items()},
                "kern": {k: round(v, 3) for k, v in self.kernels.items()}}

    def to_wire(self) -> bytes:
        """Compact shipped payload (a worker's local record only — the
        caller grafts it under its own groups map)."""
        with self._lock:
            return json.dumps(self._local_locked(),
                              separators=(",", ":")).encode()

    @staticmethod
    def from_wire(raw: bytes) -> dict:
        try:
            out = json.loads(raw.decode())
            return out if isinstance(out, dict) else {}
        except (ValueError, UnicodeDecodeError):
            return {}

    def to_dict(self) -> dict:
        """The assembled cluster-wide record: this node's local charges
        plus every shipped per-group record, with rolled-up totals.

        Physical costs (device ms, bytes, gate waits) SUM across local +
        groups — nobody else paid them. Logical counts (edges, tasks)
        take max(local, sum of groups): the querying node already
        attributes every dispatched task — including remote ones, whose
        traversed_edges ride the TaskResponse — so adding the workers'
        counts on top would double-book the same edges."""
        with self._lock:
            local = self._local_locked()
            groups = {a: dict(g) for a, g in self.groups.items()}
        total = dict(local)
        pred = {a: list(r) for a, r in local["pred"].items()}
        out = dict(local["out"])
        kern = dict(local["kern"])
        gsum = {k: 0 for k in ("edges", "tasks")}
        gpred: dict[str, list] = {}
        for g in groups.values():
            total["device_ms"] = round(
                total["device_ms"] + g.get("device_ms", 0.0), 3)
            total["gate_wait_ms"] = round(
                total["gate_wait_ms"] + g.get("gate_wait_ms", 0.0), 3)
            total["compile_ms"] = round(
                total["compile_ms"] + g.get("compile_ms", 0.0), 3)
            for k in ("h2d", "d2h", "upload", "rows"):
                total[k] += g.get(k, 0)
            for k in gsum:
                gsum[k] += g.get(k, 0)
            for o, n in g.get("out", {}).items():
                out[o] = out.get(o, 0) + n
            for a, row in g.get("pred", {}).items():
                cur = gpred.get(a)
                if cur is None:
                    gpred[a] = list(row)
                else:
                    for i in range(4):
                        cur[i] += row[i]
            for kn, ms in g.get("kern", {}).items():
                kern[kn] = round(kern.get(kn, 0.0) + ms, 3)
        for k in gsum:
            total[k] = max(total[k], gsum[k])
        for a, row in gpred.items():
            cur = pred.get(a)
            if cur is None:
                pred[a] = list(row)
            else:
                cur[0] += row[0]                 # device ms: physical
                cur[2] += row[2]                 # bytes: physical
                cur[1] = max(cur[1], row[1])     # edges: logical
                cur[3] = max(cur[3], row[3])     # tasks: logical
        total["pred"] = {a: [round(r[0], 3), r[1], r[2], r[3]]
                         for a, r in pred.items()}
        total["out"] = out
        total["kern"] = kern
        out2 = {"endpoint": self.endpoint, "shape": self.shape,
                "total": total, "local": local, "groups": groups}
        if self.tenant:
            out2["tenant"] = self.tenant
        if self.subs:
            out2["subs"] = list(self.subs)
        return out2


class _KernelTimer:
    """`with costs.kernel("csr.expand") as ck:` — times the enclosed
    device execution against the current ledger; a no-op (still yielding
    a settable object) when no ledger is armed. Bytes attach via
    ck.set(h2d=, d2h=). Exceptions still charge the elapsed time (a
    faulted upload consumed the wall clock it consumed).

    Several sites wrap a GATED call (the timer must bracket the lazy
    device value's host materialization, which happens after the gate
    releases), so dispatch-gate QUEUE time can fall inside the window.
    That wait is already booked as gate_wait_ms — counting it as device
    ms too would make every shape on a contended node look regressed —
    so the timer subtracts whatever gate wait the same ledger accrued
    during its window (same-thread nesting makes the delta exact;
    clamped at zero against concurrent hedge-thread waits)."""

    __slots__ = ("_lg", "_kernel", "_attr", "_t0", "_gw0", "h2d", "d2h",
                 "ms", "_pushed")

    def __init__(self, kernel: str, attr: str | None = None) -> None:
        self._lg = _current.get()
        self._kernel = kernel
        self._attr = attr
        self.h2d = 0
        self.d2h = 0
        self.ms = 0.0          # charged wall ms, readable after exit
        self._pushed = False

    def __enter__(self):
        lg = self._lg
        if lg is not None:
            with lg._lock:
                lg._kernel_depth += 1
                self._gw0 = lg.gate_wait_ms
            # devprof armed: the kernel name IS the program family — the
            # thread-local stack lets the dispatch timeline and the XLA
            # compile listener attribute their records to "mesh.plan" /
            # "csr.expand" instead of the coarse gate class. One empty-
            # tuple truthiness check when the observatory is off.
            if devprof._PROFILERS:
                devprof.push_family(self._kernel)
                self._pushed = True
            self._t0 = time.perf_counter()
        return self

    def set(self, h2d: int = 0, d2h: int = 0) -> None:
        self.h2d += int(h2d)
        self.d2h += int(d2h)

    def __exit__(self, *a):
        lg = self._lg
        if lg is not None:
            dt = (time.perf_counter() - self._t0) * 1e3
            if self._pushed:
                devprof.pop_family()
            with lg._lock:
                lg._kernel_depth -= 1
                waited = lg.gate_wait_ms - self._gw0
            self.ms = max(dt - waited, 0.0)
            lg.add_kernel(self._kernel, self.ms,
                          h2d=self.h2d, d2h=self.d2h, attr=self._attr)
        return False


def kernel(name: str, attr: str | None = None) -> _KernelTimer:
    return _KernelTimer(name, attr)


def note(outcome: str, n: int = 1) -> None:
    """Charge one outcome to the current ledger, if armed (the helper for
    modules that shouldn't know about ledgers: qcache, retry, gate)."""
    lg = _current.get()
    if lg is not None:
        lg.note(outcome, n)


def add_rows(n: int) -> None:
    lg = _current.get()
    if lg is not None:
        lg.add_rows(n)


def add_upload(nbytes: int) -> None:
    lg = _current.get()
    if lg is not None:
        lg.add_upload(nbytes)


def add_gate_wait(ms: float) -> None:
    lg = _current.get()
    if lg is not None:
        lg.add_gate_wait(ms)


# ---------------------------------------------------------------------------
# the /debug/top sliding-window profiler
# ---------------------------------------------------------------------------

class CostBook:
    """Bounded window of completed cost records + per-shape EWMA
    baselines.

    record() returns a regression flag dict when the record's device_ms
    exceeds `regression_factor` x the shape's warmed baseline — the
    caller (Node.query) routes it into the slow-query ring, which is how
    a shape that regressed from 2ms to 40ms surfaces even under a 500ms
    --slow_query_ms threshold. Baselines need `MIN_SAMPLES` observations
    before they flag (a cold shape's first compile is not a regression).
    """

    MIN_SAMPLES = 8
    EWMA_ALPHA = 0.2
    # baseline floor (ms): a pure-host shape's baseline is ~0, and 4 x ~0
    # would flag the first microsecond of device work — regressions are
    # only meaningful above this much device time
    BASELINE_FLOOR_MS = 0.05

    def __init__(self, keep: int = 4096,
                 regression_factor: float = 4.0) -> None:
        from collections import OrderedDict

        self._lock = threading.Lock()
        self._ring: deque[tuple[float, str, str, str, dict]] = \
            deque(maxlen=keep)
        # shape -> [ewma_device_ms, samples]; LRU-bounded — shapes are
        # raw DQL text, and clients that inline literals instead of
        # variables mint a new shape per request, so an unbounded map
        # would grow RSS forever on a long-running node
        self._baseline: "OrderedDict[str, list]" = OrderedDict()
        self._baseline_cap = max(int(keep), 16)
        self.regression_factor = float(regression_factor)
        self.flagged = 0

    def record(self, shape: str, endpoint: str, trace_id: str,
               rec: dict) -> dict | None:
        """Admit one assembled record (rec = CostLedger.to_dict()).
        Returns the regression-flag entry or None."""
        total = rec.get("total", {})
        dms = float(total.get("device_ms", 0.0))
        now = time.monotonic()
        flag = None
        with self._lock:
            self._ring.append((now, shape, endpoint, trace_id, rec))
            b = self._baseline.get(shape)
            if b is None:
                self._baseline[shape] = [dms, 1]
                while len(self._baseline) > self._baseline_cap:
                    self._baseline.popitem(last=False)
            else:
                self._baseline.move_to_end(shape)
                if b[1] >= self.MIN_SAMPLES and \
                        dms > self.regression_factor * \
                        max(b[0], self.BASELINE_FLOOR_MS):
                    self.flagged += 1
                    flag = {"reason": "cost_regression",
                            "shape": shape[:200],
                            "endpoint": endpoint,
                            "trace_id": trace_id,
                            "device_ms": round(dms, 3),
                            "baseline_ms": round(b[0], 3),
                            "factor": round(dms / max(b[0], 1e-3), 1),
                            "edges": total.get("edges", 0),
                            "bytes": total.get("h2d", 0)
                            + total.get("d2h", 0)}
                # the EWMA keeps learning (a real shift becomes the new
                # baseline instead of flagging forever)
                b[0] = (1 - self.EWMA_ALPHA) * b[0] \
                    + self.EWMA_ALPHA * dms
                b[1] += 1
        return flag

    def baseline(self, shape: str) -> tuple[float, int]:
        with self._lock:
            b = self._baseline.get(shape)
            return (b[0], b[1]) if b is not None else (0.0, 0)

    def last(self) -> dict | None:
        """The newest assembled record, per-group sub-records included."""
        with self._lock:
            if not self._ring:
                return None
            _ts, shape, ep, tid, rec = self._ring[-1]
            return {"shape": shape, "endpoint": ep, "trace_id": tid,
                    **rec}

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def top(self, window_s: float = 60.0, by: str = "device_ms",
            group: str = "shape", n: int = 20,
            endpoint: str | None = None) -> dict:
        """Rank shapes/predicates/endpoints by summed cost over the
        trailing window. The /debug/top payload. `endpoint` restricts the
        window to records from that endpoint first (?endpoint=live ranks
        standing-subscription re-evals by shape, next to — but separable
        from — foreground query load)."""
        cutoff = time.monotonic() - max(window_s, 0.0)
        agg: dict[str, dict] = {}
        seen = 0
        with self._lock:
            entries = [e for e in self._ring
                       if e[0] >= cutoff
                       and (endpoint is None or e[2] == endpoint)]
            baselines = {s: (b[0], b[1])
                         for s, b in self._baseline.items()}
        for _ts, shape, ep, tid, rec in entries:
            total = rec.get("total", {})
            seen += 1
            if group == "sub":
                # per-subscription attribution (ISSUE 19 satellite of
                # the PR 18 leftover): a live re-eval record carries the
                # ids of every subscription its coalesced group served —
                # the shared eval's cost apportions equally among them,
                # so 10k standing copies of one feed don't multiply the
                # booked device time
                sids = rec.get("subs") or ()
                if not sids:
                    continue
                share = 1.0 / len(sids)
                for sid in sids:
                    a = agg.setdefault(sid, {
                        "device_ms": 0.0, "wall_ms": 0.0,
                        "compile_ms": 0.0, "edges": 0.0, "bytes": 0.0,
                        "records": 0, "shape": ""})
                    a["device_ms"] = round(
                        a["device_ms"]
                        + float(total.get("device_ms", 0.0)) * share, 3)
                    a["wall_ms"] = round(
                        a["wall_ms"]
                        + float(total.get("wall_ms", 0.0)) * share, 3)
                    a["compile_ms"] = round(
                        a["compile_ms"]
                        + float(total.get("compile_ms", 0.0)) * share, 3)
                    a["edges"] = round(
                        a["edges"]
                        + int(total.get("edges", 0)) * share, 1)
                    a["bytes"] = round(
                        a["bytes"] + (int(total.get("h2d", 0))
                                      + int(total.get("d2h", 0)))
                        * share, 1)
                    a["records"] += 1
                    a["shape"] = shape[:200]
                continue
            if group == "pred":
                for attr, row in total.get("pred", {}).items():
                    a = agg.setdefault(attr, {
                        "device_ms": 0.0, "edges": 0, "bytes": 0,
                        "tasks": 0, "records": 0})
                    a["device_ms"] = round(a["device_ms"] + row[0], 3)
                    a["edges"] += row[1]
                    a["bytes"] += row[2]
                    a["tasks"] += row[3]
                    a["records"] += 1
                continue
            if group == "tenant":
                # /debug/top?group=tenant — per-namespace attribution
                # (ISSUE 20): every record is stamped with its minting
                # tenant; unstamped records are the default namespace
                gkey = rec.get("tenant") or "default"
            else:
                gkey = ep if group == "endpoint" else shape
            a = agg.setdefault(gkey, {
                "device_ms": 0.0, "wall_ms": 0.0, "compile_ms": 0.0,
                "edges": 0, "bytes": 0, "records": 0, "trace_id": ""})
            a["device_ms"] = round(
                a["device_ms"] + float(total.get("device_ms", 0.0)), 3)
            a["wall_ms"] = round(
                a["wall_ms"] + float(total.get("wall_ms", 0.0)), 3)
            a["compile_ms"] = round(
                a["compile_ms"] + float(total.get("compile_ms", 0.0)), 3)
            a["edges"] += int(total.get("edges", 0))
            a["bytes"] += int(total.get("h2d", 0)) + \
                int(total.get("d2h", 0))
            a["records"] += 1
            if tid:
                a["trace_id"] = tid      # newest sampled exemplar wins
        rank_key = {"device_ms": "device_ms", "edges": "edges",
                    "bytes": "bytes", "wall_ms": "wall_ms",
                    "compile_ms": "compile_ms"}.get(by, "device_ms")
        if group == "pred" and rank_key in ("wall_ms", "compile_ms"):
            rank_key = "device_ms"     # pred rows carry neither
        ranked = sorted(agg.items(), key=lambda kv: kv[1].get(rank_key, 0),
                        reverse=True)[: max(n, 1)]
        out = []
        for k, v in ranked:
            row = {"key": k[:200], **v}
            if group == "shape":
                bl = baselines.get(k)
                if bl is not None:
                    row["baseline_device_ms"] = round(bl[0], 3)
                    row["baseline_samples"] = bl[1]
                    mean = v["device_ms"] / max(v["records"], 1)
                    row["regressed"] = bool(
                        bl[1] >= self.MIN_SAMPLES
                        and mean > self.regression_factor
                        * max(bl[0], self.BASELINE_FLOOR_MS))
            out.append(row)
        return {"window_s": window_s, "by": by, "group": group,
                "endpoint": endpoint,
                "records_in_window": seen, "flagged_total": self.flagged,
                "top": out}
