"""Span-based distributed tracing + device profiling (Dapper-style).

Model: a sampled request mints a trace_id at its root span; every unit of
work below it is a span carrying (span_id, parent_id). Within a process
the active span rides a contextvar, so instrumentation points
(`otrace.span(...)`) need no plumbing; across processes the context rides
gRPC invocation metadata (`WIRE_KEY`, "trace_id:parent_span_id") and the
callee ships its collected spans BACK in trailing metadata (`SPANS_KEY`),
so the caller assembles one tree server-side — there is no out-of-band
collector to deploy.

The not-sampled fast path is one contextvar read returning NULL_SPAN
(falsy, no-op everywhere): tracing at 0% must cost nothing measurable
(bench.py `trace` gates <2% QPS overhead at 1% sampling).

Completed traces land in a bounded TraceSink ring and export as Chrome
trace-event JSON (loadable in Perfetto / chrome://tracing) at
/debug/traces/<id>.
"""

from __future__ import annotations

import contextvars
import json
import random
import threading
import time
from collections import deque

# gRPC metadata keys (lowercase per the gRPC spec; -bin carries bytes)
WIRE_KEY = "dgt-trace"
SPANS_KEY = "dgt-spans-bin"

# a join()ed trace whose spans are never take()n (caller died mid-RPC)
# must not pin the buffer map forever
_MAX_ACTIVE = 256

_current: contextvars.ContextVar["Span | None"] = \
    contextvars.ContextVar("dgt_current_span", default=None)


class _NullSpan:
    """Unsampled requests get this: falsy, allocation-free no-ops."""

    __slots__ = ()
    trace_id = ""
    span_id = ""

    def __bool__(self) -> bool:
        return False

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def set(self, **kw) -> None:
        pass

    def event(self, name: str, **attrs) -> None:
        pass

    def finish(self, error: str = "") -> None:
        pass


NULL_SPAN = _NullSpan()


def current() -> "Span | None":
    """The innermost active span on this execution context, or None."""
    return _current.get()


def span(name: str, **attrs):
    """Child span of the current one; NULL_SPAN when nothing is sampled.
    The instrumentation-point helper: modules that shouldn't know about
    tracers (query/task.py device dispatch) call this unconditionally."""
    parent = _current.get()
    if parent is None:
        return NULL_SPAN
    return parent.tracer.start(name, parent=parent, attrs=attrs)


def event(name: str, **attrs) -> None:
    """Zero-duration annotation on the current span (breadcrumb analog)."""
    sp = _current.get()
    if sp is not None:
        sp.event(name, **attrs)


def wire_context() -> str | None:
    """Serialized context for an outgoing RPC, or None when unsampled."""
    sp = _current.get()
    if sp is None:
        return None
    return f"{sp.trace_id}:{sp.span_id}"


class Span:
    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "kind", "proc", "wall0", "t0", "dur", "attrs", "events_",
                 "error", "_token", "_finished")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: str, name: str, kind: str, proc: str,
                 attrs: dict) -> None:
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.proc = proc
        self.wall0 = time.time()
        self.t0 = time.perf_counter()
        self.dur = 0.0
        self.attrs = attrs
        self.events_: list[tuple[float, str, dict]] = []
        self.error = ""
        self._token = None
        self._finished = False

    def __bool__(self) -> bool:
        return True

    def set(self, **kw) -> None:
        self.attrs.update(kw)

    def event(self, name: str, **attrs) -> None:
        self.events_.append((time.perf_counter() - self.t0, name, attrs))

    def __enter__(self) -> "Span":
        self._token = _current.set(self)
        return self

    def __exit__(self, et, ev, tb):
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        self.finish(error="" if ev is None else f"{type(ev).__name__}: {ev}")
        return False

    def finish(self, error: str = "") -> None:
        if self._finished:
            return
        self._finished = True
        self.dur = time.perf_counter() - self.t0
        if error:
            self.error = error
        self.tracer._record(self)

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "kind": self.kind, "proc": self.proc,
             "start": self.wall0, "dur": round(self.dur, 9),
             "attrs": self.attrs}
        if self.error:
            d["error"] = self.error
        if self.events_:
            d["events"] = [{"t": round(t, 9), "name": n, "attrs": a}
                           for t, n, a in self.events_]
        return d


class TraceSink:
    """Completed traces, newest-first bounded ring, addressable by id."""

    def __init__(self, keep: int = 64) -> None:
        self._lock = threading.Lock()
        self._order: deque[str] = deque()
        self._by_id: dict[str, dict] = {}
        self.keep = keep

    def add(self, root: dict, spans: list[dict]) -> None:
        rec = {"trace_id": root["trace_id"], "root": root["name"],
               "proc": root["proc"], "start": root["start"],
               "elapsed_s": root["dur"], "error": root.get("error", ""),
               "nspans": len(spans), "spans": spans}
        with self._lock:
            if rec["trace_id"] in self._by_id:
                self._order.remove(rec["trace_id"])
            self._by_id[rec["trace_id"]] = rec
            self._order.appendleft(rec["trace_id"])
            while len(self._order) > self.keep:
                self._by_id.pop(self._order.pop(), None)

    def index(self, n: int = 32) -> list[dict]:
        with self._lock:
            ids = list(self._order)[:n]
            return [{k: v for k, v in self._by_id[t].items()
                     if k != "spans"} for t in ids]

    def get(self, trace_id: str) -> dict | None:
        with self._lock:
            return self._by_id.get(trace_id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)


class Tracer:
    """Per-process span factory + per-trace assembly buffer.

    Sampling happens ONCE, at root(): a joined trace (propagated over the
    wire) is always recorded because the root already paid the coin flip.
    The rng is injectable so tests sample deterministically."""

    def __init__(self, fraction: float = 0.0, proc: str = "node",
                 keep: int = 64, rng=None, slowlog=None) -> None:
        self.fraction = fraction
        self.proc = proc
        self.rng = rng if rng is not None else random
        self.sink = TraceSink(keep)
        self.slowlog = slowlog
        self._lock = threading.Lock()
        self._active: dict[str, list[dict]] = {}
        self._joined: set[str] = set()

    def _new_id(self) -> str:
        return f"{self.rng.getrandbits(64):016x}"

    # -- span creation -------------------------------------------------------

    def root(self, name: str, kind: str = "server",
             attrs: dict | None = None, force: bool = False) -> "Span":
        """Start a NEW trace; the sampling decision lives here."""
        if not force and (self.fraction <= 0
                          or self.rng.random() >= self.fraction):
            return NULL_SPAN
        tid = self._new_id()
        with self._lock:
            self._evict_locked()
            self._active[tid] = []
        return Span(self, tid, self._new_id(), "", name, kind, self.proc,
                    dict(attrs) if attrs else {})

    def start(self, name: str, parent: "Span | None" = None,
              kind: str = "internal", attrs: dict | None = None) -> "Span":
        parent = parent if parent is not None else _current.get()
        if parent is None or not parent:
            return NULL_SPAN
        return Span(self, parent.trace_id, self._new_id(), parent.span_id,
                    name, kind, self.proc, dict(attrs) if attrs else {})

    def join(self, wire: str, name: str, kind: str = "server",
             attrs: dict | None = None) -> "Span":
        """Continue a trace whose context arrived over the wire. The
        returned span's subtree is buffered locally; the RPC handler ships
        it back to the caller with take() after the span finishes."""
        tid, _, parent_id = wire.partition(":")
        if not tid:
            return NULL_SPAN
        with self._lock:
            self._evict_locked()
            self._active.setdefault(tid, [])
            self._joined.add(tid)
        return Span(self, tid, self._new_id(), parent_id, name, kind,
                    self.proc, dict(attrs) if attrs else {})

    def _evict_locked(self) -> None:
        while len(self._active) >= _MAX_ACTIVE:
            stale = next(iter(self._active))
            self._active.pop(stale, None)
            self._joined.discard(stale)

    # -- assembly ------------------------------------------------------------

    def take(self, trace_id: str) -> list[dict]:
        """Drain a joined trace's buffered spans (RPC handler exit)."""
        with self._lock:
            self._joined.discard(trace_id)
            return self._active.pop(trace_id, [])

    def add_remote(self, spans: list[dict]) -> None:
        """Merge spans shipped back by a callee into their live trace
        (silently dropped when the trace already assembled — a hedged
        RPC's straggler response must not resurrect a finished trace)."""
        if not spans:
            return
        tid = spans[0].get("trace_id", "")
        with self._lock:
            buf = self._active.get(tid)
            if buf is not None:
                buf.extend(spans)

    def _record(self, sp: Span) -> None:
        d = sp.to_dict()
        done = None
        with self._lock:
            buf = self._active.get(sp.trace_id)
            if buf is None:
                return                     # trace already assembled/evicted
            buf.append(d)
            if not sp.parent_id and sp.trace_id not in self._joined:
                # local root finished: assemble NOW, even if remote spans
                # never arrived (failed fan-out must not leak the buffer)
                done = self._active.pop(sp.trace_id)
        if done is not None:
            self.sink.add(d, done)
            if self.slowlog is not None:
                self.slowlog.observe(d, done)

    def active_traces(self) -> int:
        with self._lock:
            return len(self._active)


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------

def chrome_trace(rec: dict) -> dict:
    """One assembled trace -> the Chrome trace-event JSON object format:
    complete ("X") events per span, instant ("i") events per span event,
    one tid per process label with thread_name metadata. Timestamps are
    rebased to the trace start, in microseconds (the format's unit)."""
    spans = rec.get("spans", [])
    t0 = min((s["start"] for s in spans), default=0.0)
    tids: dict[str, int] = {}
    events: list[dict] = []
    for s in spans:
        tids.setdefault(s.get("proc") or "?", len(tids) + 1)
    for proc, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": proc}})
    for s in spans:
        tid = tids[s.get("proc") or "?"]
        args = {"span_id": s["span_id"], "parent_id": s["parent_id"]}
        args.update(s.get("attrs", {}))
        if s.get("error"):
            args["error"] = s["error"]
        ts = (s["start"] - t0) * 1e6
        events.append({"name": s["name"], "cat": s.get("kind", "internal"),
                       "ph": "X", "ts": round(ts, 3),
                       "dur": round(max(s["dur"] * 1e6, 0.001), 3),
                       "pid": 1, "tid": tid, "args": args})
        for ev in s.get("events", ()):
            events.append({"name": ev["name"], "ph": "i", "s": "t",
                           "ts": round(ts + ev["t"] * 1e6, 3),
                           "pid": 1, "tid": tid, "args": ev.get("attrs", {})})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": rec.get("trace_id", ""),
                          "root": rec.get("root", ""),
                          "error": rec.get("error", "")}}


def span_tree(rec: dict) -> dict:
    """Nested parent->child view of one assembled trace (the slow-query
    log's payload; also a structural sanity check for tests)."""
    spans = rec.get("spans", [])
    by_parent: dict[str, list[dict]] = {}
    by_id = {s["span_id"]: s for s in spans}
    roots = []
    for s in spans:
        if s["parent_id"] and s["parent_id"] in by_id:
            by_parent.setdefault(s["parent_id"], []).append(s)
        else:
            roots.append(s)

    def node(s: dict) -> dict:
        kids = sorted(by_parent.get(s["span_id"], ()),
                      key=lambda x: x["start"])
        out = {"name": s["name"], "proc": s["proc"], "kind": s["kind"],
               "dur_ms": round(s["dur"] * 1e3, 3), "attrs": s.get("attrs", {})}
        if s.get("error"):
            out["error"] = s["error"]
        if kids:
            out["children"] = [node(k) for k in kids]
        return out

    roots.sort(key=lambda s: s["start"])
    return {"trace_id": rec.get("trace_id", ""),
            "tree": [node(s) for s in roots]}


# wire payload ceiling for shipped span lists: stays comfortably under the
# raised grpc.max_metadata_size (4 MB) even after base64-ish inflation
_MAX_SHIP_BYTES = 1 << 20


def encode_spans(spans: list[dict]) -> bytes:
    out = json.dumps(spans, separators=(",", ":"), default=str).encode()
    while len(out) > _MAX_SHIP_BYTES and len(spans) > 1:
        # pathological trace: keep the longest spans (the ones that answer
        # "where did the time go") and note the truncation on the last
        spans = sorted(spans, key=lambda s: s.get("dur", 0.0),
                       reverse=True)[: max(len(spans) // 2, 1)]
        spans[-1] = dict(spans[-1])
        spans[-1].setdefault("attrs", {})
        spans[-1]["attrs"] = dict(spans[-1]["attrs"], truncated=True)
        out = json.dumps(spans, separators=(",", ":"), default=str).encode()
    return out


def decode_spans(raw: bytes) -> list[dict]:
    try:
        out = json.loads(raw.decode())
        return out if isinstance(out, list) else []
    except (ValueError, UnicodeDecodeError):
        return []
