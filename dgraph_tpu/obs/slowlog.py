"""Threshold-gated slow-query log.

A query whose root span exceeds `threshold_ms` records an entry carrying
the query text, elapsed time, the assembled span tree (with per-span
cardinality/cache/device attrs), and — when the planner ran — the plan
summary with estimated cardinalities. Entries live in a bounded ring
(`/debug/slow`) and optionally append to a JSONL file for offline
digestion (one JSON object per line; rotation is the operator's job).

The ring is also the landing zone for COST REGRESSIONS (ISSUE 13): the
cost ledger flags a query whose device cost exceeds k x its plan-shape's
EWMA baseline via record() directly — bypassing the threshold gate on
purpose, because a 2ms shape regressing to 40ms never crosses a 500ms
--slow_query_ms. Those entries carry root="cost_regression" plus
device_ms/baseline_ms/factor (obs/costs.CostBook).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from dgraph_tpu.obs import otrace


class SlowQueryLog:
    def __init__(self, threshold_ms: float = 0.0, keep: int = 64,
                 path: str | None = None) -> None:
        """threshold_ms <= 0 disables the log entirely."""
        self.threshold_ms = float(threshold_ms)
        self._ring: deque[dict] = deque(maxlen=keep)
        self._lock = threading.Lock()
        self._path = path
        self._file = None
        self.dropped_writes = 0

    @property
    def enabled(self) -> bool:
        return self.threshold_ms > 0

    def observe(self, root: dict, spans: list[dict]) -> None:
        """Tracer assembly hook: called with every completed local trace."""
        if not self.enabled or root["dur"] * 1e3 < self.threshold_ms:
            return
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
            "trace_id": root["trace_id"],
            "root": root["name"],
            "elapsed_ms": round(root["dur"] * 1e3, 3),
            "error": root.get("error", ""),
            "query": root.get("attrs", {}).get("query", ""),
            "plan": root.get("attrs", {}).get("plan"),
            "spans": len(spans),
            "tree": otrace.span_tree(
                {"trace_id": root["trace_id"], "spans": spans})["tree"],
        }
        self.record(entry)

    def record(self, entry: dict) -> None:
        with self._lock:
            self._ring.appendleft(entry)
            if self._path is not None:
                try:
                    if self._file is None:
                        self._file = open(self._path, "a")
                    self._file.write(
                        json.dumps(entry, default=str,
                                   separators=(",", ":")) + "\n")
                    self._file.flush()
                except OSError:
                    # a full/yanked disk must never fail the query path
                    self.dropped_writes += 1

    def recent(self, n: int = 32) -> list[dict]:
        with self._lock:
            return [e for i, e in enumerate(self._ring) if i < n]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
