"""Device-runtime observatory (ISSUE 19): XLA compile/retrace tracking,
HBM telemetry, and a dispatch-timeline utilization profiler.

The gap after PR 13: the cost ledger answers "what did THIS query cost"
in wall/device ms, but not WHY — LDBC_r15.json shows mesh losing to host
at SF0.1 and nothing on /debug decomposes that into compiles vs queue
gaps vs kernel time. Three surfaces close it:

  * compile observatory — every jitted-program build site (mesh_exec's
    program cache, dist.py's lru builders) notes its build through a
    registering seam that attributes build count + triggering shape
    signature to a named PROGRAM FAMILY (the costs.kernel vocabulary:
    mesh.plan, csr.expand, batch.recurse, ...). Real XLA compile wall
    ms rides jax.monitoring's backend_compile event listener, attributed
    to the family on the profiler's thread-local stack (pushed by
    costs._KernelTimer while armed) — `jax.jit` is lazy, so timing the
    build call site would measure nothing. A family recompiling under
    shape churn within a window is a RETRACE STORM: flagged into the
    PR 13 regression slowlog (root="retrace_storm") and counted on
    dgraph_xla_retrace_storms_total. GET /debug/compiles serves
    per-family builds/compiles/cumulative ms/last-trigger shapes plus
    the live program-cache sizes.
  * HBM telemetry — per-dispatch live/peak device-byte sampling:
    jax device.memory_stats() where the backend reports it (TPU/GPU;
    capability probed once — CPU returns None), the ResidencyManager's
    tier accounting as the always-available spine. High-water marks per
    tier land on dgraph_devprof_hbm_highwater_bytes{tier=...}; peak
    crossing the --device_budget_mb headroom raises a pressure flag
    (counter + span event on the causing dispatch).
  * dispatch timeline — a bounded ring of (program family, queue-entry,
    launch, fence-complete, bytes moved) records fed from
    DispatchGate.run — the one chokepoint every device dispatch (solo
    task, DeviceBatcher leader, analytics, mesh program) passes through
    — exported as Chrome trace-event JSON at /debug/timeline (same
    format as /debug/traces/<id>, loadable in Perfetto) plus the
    derived dgraph_device_utilization / queue-gap / dispatch-ms meters.

Disarm contract (--no_devprof): zero overhead by construction. The gate
checks one attribute (None), the kernel timer checks one module tuple
(empty), and the jax.monitoring listener is never even registered until
the first profiler arms — pre-19 behavior is byte-identical.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils import locks

# -- global registration seam ------------------------------------------------
#
# Per-node profilers attach directly where a node owns the seam
# (DispatchGate.profiler, MeshExecutor._prof). Process-global build sites
# (dist.py's lru_cache program builders) fan out through this
# copy-on-write tuple instead: reads are one load of an (almost always
# empty) tuple, writes swap the whole tuple under the lock.

_PROFILERS: tuple = ()
_reg_lock = threading.Lock()
_listener_installed = False

# thread-local program-family stack: costs._KernelTimer pushes its kernel
# name here while any profiler is armed, so compile events and timeline
# records pick up the fine-grained family ("mesh.plan", "csr.expand")
# instead of the coarse gate class
_tls = threading.local()


def armed() -> bool:
    return bool(_PROFILERS)


def push_family(name: str) -> None:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    st.append(name)


def pop_family() -> None:
    st = getattr(_tls, "stack", None)
    if st:
        st.pop()


def current_family(default: str | None = None) -> str | None:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else default


def note_build(family: str, key=None) -> None:
    """A process-global build site (dist.py lru builder) constructed one
    jitted program. One tuple load when nothing is armed."""
    for p in _PROFILERS:
        p.on_build(family, key)


def register(p: "DevProfiler") -> None:
    global _PROFILERS
    with _reg_lock:
        if p not in _PROFILERS:
            _PROFILERS = _PROFILERS + (p,)
    _install_listener_once()


def unregister(p: "DevProfiler") -> None:
    global _PROFILERS
    with _reg_lock:
        _PROFILERS = tuple(x for x in _PROFILERS if x is not p)


# -- jax.monitoring compile listener -----------------------------------------
#
# jax.jit is LAZY: tracing + XLA compilation happen at the first call with
# a new signature, inside the dispatch — not at the build site. The only
# faithful compile-ms source is jax.monitoring's event-duration stream
# (/jax/core/compile/backend_compile_duration fires once per XLA
# compile). Registered exactly once, on the FIRST profiler arm ever —
# a --no_devprof process never registers it — and the callback's first
# check is the armed tuple, so a later disarm costs one load per compile.

_COMPILE_EVENT = "backend_compile_duration"


def _on_duration_event(event: str, duration: float, **kw) -> None:
    profs = _PROFILERS
    if not profs or duration is None:
        return
    if not event.endswith(_COMPILE_EVENT):
        return
    ms = float(duration) * 1e3
    fam = current_family("unattributed")
    for p in profs:
        p.on_compile(fam, ms)
    from . import costs

    lg = costs.current()
    if lg is not None:
        lg.add_compile(ms)


def _install_listener_once() -> None:
    global _listener_installed
    if _listener_installed:
        return
    _listener_installed = True
    try:
        from jax import monitoring
    except Exception:
        return
    try:
        monitoring.register_event_duration_secs_listener(_on_duration_event)
    except Exception:
        pass


def _sig(key) -> str:
    """Compact shape signature of one build trigger."""
    if key is None:
        return ""
    s = repr(key)
    return s if len(s) <= 200 else s[:197] + "..."


class DevProfiler:
    """One node's device-runtime observatory (all three surfaces).

    Constructed by Node when devprof is on, attached as
    DispatchGate.profiler / MeshExecutor._prof and registered on the
    module fan-out; never constructed under --no_devprof.
    """

    # retrace-storm detection: >= STORM_MIN_BUILDS compile/build events
    # of ONE family with >= STORM_MIN_SHAPES distinct trigger signatures
    # inside STORM_WINDOW_S, flagged at most once per window per family.
    # (A fresh program cache warming N distinct keys is normal; churn
    # past these floors means shapes are NOT converging to the cache.)
    STORM_WINDOW_S = 30.0
    STORM_MIN_BUILDS = 4
    STORM_MIN_SHAPES = 3
    # HBM pressure: peak over this fraction of the device budget
    PRESSURE_HEADROOM = 0.9
    # utilization gauge refresh cadence (dispatches)
    UTIL_REFRESH = 32

    def __init__(self, metrics, slow_log=None, budget_bytes: int = 0,
                 residency=None, ring_size: int = 2048) -> None:
        self._m = metrics
        self._slow_log = slow_log
        self._residency = residency
        self.budget_bytes = int(budget_bytes)
        self._lock = locks.Lock("devprof.DevProfiler._lock")
        # family -> {"builds", "compiles", "compile_ms", "storms",
        #            "shapes": deque[(mono_ts, sig)], "last": str,
        #            "storm_at": float}
        self._fams: dict[str, dict] = {}
        # timeline ring: (seq, mono_ts, family, klass, queue_ms, run_ms,
        #                 bytes_moved)
        self._ring: deque = deque(maxlen=max(int(ring_size), 16))
        self._seq = 0
        self._busy_ms = 0.0              # cumulative fenced run ms
        self._born = time.monotonic()
        self._cache_probes: list[tuple[str, object]] = []
        self._hbm_capable: bool | None = None
        self._high_water: dict[str, int] = {}
        self._pressure_latched = False
        # metric objects cached once — record_dispatch is the hot path
        self._c_compiles = metrics.counter("dgraph_xla_compiles_total")
        self._c_storms = metrics.counter(
            "dgraph_xla_retrace_storms_total")
        self._c_disp = metrics.counter("dgraph_devprof_dispatches_total")
        self._c_pressure = metrics.counter(
            "dgraph_devprof_hbm_pressure_total")
        self._g_util = metrics.counter("dgraph_device_utilization")
        self._g_budget = metrics.counter("dgraph_devprof_hbm_budget_bytes")
        self._k_hbm = metrics.keyed("dgraph_devprof_hbm_highwater_bytes",
                                    labels=("tier",))
        self._h_compile = metrics.histogram("dgraph_xla_compile_ms")
        self._h_gap = metrics.histogram("dgraph_device_queue_gap_ms")
        self._h_disp = metrics.histogram("dgraph_device_dispatch_ms")
        self._g_budget.set(self.budget_bytes)

    # -- compile observatory -------------------------------------------------

    def _fam_locked(self, family: str) -> dict:
        f = self._fams.get(family)
        if f is None:
            f = self._fams[family] = {
                "builds": 0, "compiles": 0, "compile_ms": 0.0,
                "storms": 0, "shapes": deque(maxlen=64), "last": "",
                "storm_at": 0.0}
        return f

    def on_build(self, family: str, key=None) -> None:
        """One program-cache miss built a new jitted program (mesh_exec
        stores, dist lru builders) — the shape signature is the cache
        key that missed."""
        self._note_event(family, _sig(key), compile_ms=None)

    def on_compile(self, family: str, ms: float) -> None:
        """One real XLA compile completed (jax.monitoring listener). The
        trigger signature is synthetic — each compile of an already-seen
        family IS a fresh signature by definition (the jit cache
        missed)."""
        self._c_compiles.inc()
        self._h_compile.observe(ms)
        self._note_event(family, None, compile_ms=ms)

    def _note_event(self, family: str, sig: str | None,
                    compile_ms: float | None) -> None:
        now = time.monotonic()
        storm = None
        with self._lock:
            f = self._fam_locked(family)
            if compile_ms is None:
                f["builds"] += 1
            else:
                f["compiles"] += 1
                f["compile_ms"] += compile_ms
                sig = f"compile#{f['compiles']}"
            if sig:
                f["last"] = sig
            f["shapes"].append((now, sig or ""))
            recent = [s for t, s in f["shapes"]
                      if now - t <= self.STORM_WINDOW_S]
            if (len(recent) >= self.STORM_MIN_BUILDS
                    and len(set(recent)) >= self.STORM_MIN_SHAPES
                    and now - f["storm_at"] > self.STORM_WINDOW_S):
                f["storm_at"] = now
                f["storms"] += 1
                storm = {"family": family, "builds_in_window": len(recent),
                         "distinct_shapes": len(set(recent)),
                         "window_s": self.STORM_WINDOW_S,
                         "last_shape": f["last"]}
        if storm is not None:
            self._c_storms.inc()
            if self._slow_log is not None:
                self._slow_log.record({
                    "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                        time.gmtime()),
                    "root": "retrace_storm",
                    "reason": "retrace_storm",
                    "elapsed_ms": 0.0,
                    **storm})

    def add_cache_probe(self, name: str, fn) -> None:
        """Register a live program-cache size callable for
        /debug/compiles (mesh_exec._progs, dist lru caches, ops jit
        caches). Probes must be cheap and exception-safe is handled
        here."""
        with self._lock:
            self._cache_probes.append((name, fn))

    def compiles_snapshot(self) -> dict:
        """GET /debug/compiles payload."""
        with self._lock:
            fams = {
                name: {"builds": f["builds"], "compiles": f["compiles"],
                       "compile_ms": round(f["compile_ms"], 3),
                       "storms": f["storms"], "last_shape": f["last"],
                       "recent_shapes": [s for _t, s in f["shapes"]][-8:]}
                for name, f in sorted(self._fams.items())}
            probes = list(self._cache_probes)
        caches = {}
        for name, fn in probes:
            try:
                v = fn()
            except Exception:
                caches[name] = -1
                continue
            if isinstance(v, dict):
                # one probe may report a whole group of caches (the ops
                # modules' JIT_PROGRAMS registries, keyed by family)
                for k, x in v.items():
                    caches[str(k)] = int(x)
            else:
                caches[name] = int(v)
        return {
            "enabled": True,
            "families": fams,
            "cache_sizes": caches,
            "compiles": self._c_compiles.value,
            "compile_ms_total": round(sum(
                f["compile_ms"] for f in fams.values()), 3),
            "retrace_storms": self._c_storms.value,
        }

    # -- HBM telemetry -------------------------------------------------------

    def _probe_hbm_locked(self) -> None:
        """One-time capability probe: device.memory_stats() returns a
        dict on TPU/GPU backends and None on CPU."""
        self._hbm_capable = False
        try:
            import jax

            for d in jax.local_devices():
                if d.memory_stats() is not None:
                    self._hbm_capable = True
                    break
        except Exception:
            pass

    def _device_bytes(self) -> tuple[int, int]:
        """(live, peak) device bytes from the backend, 0s when the
        backend doesn't report them."""
        if not self._hbm_capable:
            return 0, 0
        live = peak = 0
        try:
            import jax

            for d in jax.local_devices():
                st = d.memory_stats() or {}
                live += int(st.get("bytes_in_use", 0))
                peak += int(st.get("peak_bytes_in_use",
                                   st.get("bytes_in_use", 0)))
        except Exception:
            return 0, 0
        return live, peak

    def _sample_hbm_locked(self) -> dict | None:
        """Per-dispatch tier sample: returns a pressure event dict when
        peak newly crosses the budget headroom, else None. The keyed
        high-water gauge is only touched on a new high-water mark, so
        the steady-state cost is dict lookups."""
        if self._hbm_capable is None:
            self._probe_hbm_locked()
        tiers: dict[str, int] = {}
        live, peak = self._device_bytes()
        if live or peak:
            tiers["device"] = peak or live
        r = self._residency
        if r is not None:
            try:
                tiers["hbm"] = int(r.usage())
                tiers["host"] = int(r.host_bytes())
            except Exception:
                pass
        hw_peak = 0
        for tier, v in tiers.items():
            if v > self._high_water.get(tier, -1):
                self._high_water[tier] = v
                self._k_hbm.set(tier, v)
        hw_peak = max(tiers.get("device", 0), tiers.get("hbm", 0))
        if not self.budget_bytes:
            return None
        threshold = self.PRESSURE_HEADROOM * self.budget_bytes
        if hw_peak > threshold:
            if not self._pressure_latched:
                self._pressure_latched = True
                return {"peak_bytes": hw_peak,
                        "budget_bytes": self.budget_bytes,
                        "headroom": self.PRESSURE_HEADROOM}
        elif hw_peak < 0.8 * self.budget_bytes:
            self._pressure_latched = False   # re-arm after back-off
        return None

    def hbm_snapshot(self) -> dict:
        with self._lock:
            return {"capable": bool(self._hbm_capable),
                    "budget_bytes": self.budget_bytes,
                    "high_water": dict(self._high_water),
                    "pressure_events": self._c_pressure.value}

    # -- dispatch timeline ---------------------------------------------------

    def record_dispatch(self, klass: str | None, t_queue: float,
                        t_launch: float, t_fence: float,
                        bytes_moved: int = 0) -> None:
        """One gated device dispatch completed (called from
        DispatchGate.run's finally — every solo task, batch leader,
        analytics run, and mesh program passes exactly once). Timestamps
        are perf_counter values from the gate itself."""
        family = current_family(None) or (klass or "device")
        queue_ms = max((t_launch - t_queue) * 1e3, 0.0)
        run_ms = max((t_fence - t_launch) * 1e3, 0.0)
        pressure = None
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._ring.append((seq, time.monotonic(), family,
                               klass or "", queue_ms, run_ms,
                               int(bytes_moved)))
            self._busy_ms += run_ms
            pressure = self._sample_hbm_locked()
            refresh = seq % self.UTIL_REFRESH == 0
        self._c_disp.inc()
        self._h_gap.observe(queue_ms)
        self._h_disp.observe(run_ms)
        if refresh:
            self._refresh_utilization()
        if pressure is not None:
            self._c_pressure.inc()
            from . import otrace

            otrace.event("hbm_pressure", family=family, **pressure)

    def _refresh_utilization(self) -> None:
        """Derived occupancy gauge: fenced device-busy ms over the
        trailing ring window, as a 0-100 percentage (can exceed 100 on a
        gate wider than 1 — concurrent dispatches overlap)."""
        with self._lock:
            if not self._ring:
                self._g_util.set(0.0)
                return
            oldest = self._ring[0][1]
            busy = sum(r[5] for r in self._ring)
        wall_ms = max((time.monotonic() - oldest) * 1e3, 1e-3)
        self._g_util.set(round(min(busy / wall_ms, 10.0) * 100.0, 2))

    def timeline_snapshot(self, n: int = 256) -> list[dict]:
        with self._lock:
            recs = list(self._ring)[-max(int(n), 1):]
        return [{"seq": s, "ts": ts, "family": fam, "klass": kl,
                 "queue_ms": round(qm, 3), "run_ms": round(rm, 3),
                 "bytes": b}
                for s, ts, fam, kl, qm, rm, b in recs]

    def timeline_chrome(self) -> dict:
        """The /debug/timeline payload: Chrome trace-event JSON in the
        same envelope as /debug/traces/<id> (obs/otrace.chrome_trace),
        so it drops into the existing Perfetto workflow. Two tracks per
        record: queue wait and fenced execution."""
        with self._lock:
            recs = list(self._ring)
            busy = self._busy_ms
        events: list[dict] = [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "device.queue"}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 2,
             "args": {"name": "device.run"}},
        ]
        if recs:
            t0 = recs[0][1]
            for seq, ts, fam, kl, qm, rm, b in recs:
                # ts is the FENCE time (appended at completion): rebase
                # launch = fence - run, queue-entry = launch - queue
                fence_us = (ts - t0) * 1e6
                launch_us = fence_us - rm * 1e3
                queue_us = launch_us - qm * 1e3
                args = {"seq": seq, "family": fam, "klass": kl,
                        "bytes": b}
                if qm > 0:
                    events.append({"name": f"{fam} (queued)", "ph": "X",
                                   "pid": 1, "tid": 1,
                                   "ts": round(queue_us, 1),
                                   "dur": round(qm * 1e3, 1),
                                   "cat": "queue", "args": args})
                events.append({"name": fam, "ph": "X", "pid": 1,
                               "tid": 2, "ts": round(launch_us, 1),
                               "dur": round(max(rm, 1e-3) * 1e3, 1),
                               "cat": "dispatch", "args": args})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"records": len(recs),
                              "dispatches": self._c_disp.value,
                              "busy_ms_total": round(busy, 3),
                              "utilization": self._g_util.value}}

    # -- roll-up -------------------------------------------------------------

    def summary(self) -> dict:
        """The /debug/metrics `devprof` section."""
        self._refresh_utilization()
        with self._lock:
            n_fams = len(self._fams)
            storms = sum(f["storms"] for f in self._fams.values())
            compile_ms = sum(f["compile_ms"] for f in self._fams.values())
            ring = len(self._ring)
        return {
            "enabled": True,
            "dispatches": self._c_disp.value,
            "ring_records": ring,
            "utilization_pct": self._g_util.value,
            "queue_gap_ms": self._m.histogram(
                "dgraph_device_queue_gap_ms").snapshot(),
            "dispatch_ms": self._m.histogram(
                "dgraph_device_dispatch_ms").snapshot(),
            "compiles": self._c_compiles.value,
            "compile_ms_total": round(compile_ms, 3),
            "program_families": n_fams,
            "retrace_storms": storms,
            "hbm": self.hbm_snapshot(),
        }
