"""Prometheus text exposition (version 0.0.4) of a metrics.Registry.

Mapping:
  Counter       -> `counter` when the name ends in _total, else `gauge`
                   (the registry uses Counter.set for gauge-shaped values
                   like dgraph_memory_bytes, matching the reference's
                   expvar dual use).
  Histogram     -> a summary: `{quantile="0.5|0.95|0.99"}` rows over the
                   recent-window ring plus _sum/_count lifetime series.
  Meter         -> gauge `dgraph_endpoint_qps{endpoint="<name>"}`.
  KeyedGauge    -> gauge with a `key` label per entry.

Names already follow the dgraph_* vocabulary and are valid Prometheus
metric names; keys/labels are escaped per the text-format rules.
"""

from __future__ import annotations

import re

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# registry names that END in _total but are inc/dec LEVELS, not monotonic
# counters (the reference's expvar dual-use) — a counter TYPE would make
# every decrease read as a reset, so rate()/increase() would spike
_LEVEL_TOTALS = frozenset({"dgraph_pending_queries_total",
                           "dgraph_active_mutations_total"})


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _num(v) -> str:
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


def _safe(name: str) -> str:
    return name if _NAME_OK.match(name) else \
        re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def render(registry) -> str:
    """The /metrics payload. The registry's metric MAPS are copied under
    its lock (a concurrent first-use setdefault must not resize them
    mid-iteration); the per-metric reads below use each metric's own
    locking."""
    lock = getattr(registry, "_lock", None)
    if lock is not None:
        with lock:
            counters = dict(registry.counters)
            histograms = dict(registry.histograms)
            meters = dict(registry.meters)
            keyed = dict(registry.keyed_gauges)
    else:
        counters, histograms = dict(registry.counters), \
            dict(registry.histograms)
        meters, keyed = dict(registry.meters), dict(registry.keyed_gauges)
    out: list[str] = []

    for name, c in sorted(counters.items()):
        name = _safe(name)
        kind = "counter" if name.endswith("_total") \
            and name not in _LEVEL_TOTALS else "gauge"
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {_num(c.value)}")

    for name, h in sorted(histograms.items()):
        name = _safe(name)
        s = h.snapshot()
        out.append(f"# TYPE {name} summary")
        for q in ("p50", "p95", "p99"):
            if q in s:
                out.append(f'{name}{{quantile="0.{q[1:]}"}} {_num(s[q])}')
        out.append(f"{name}_sum {_num(h.total)}")
        out.append(f"{name}_count {_num(s['count'])}")

    if meters:
        out.append("# TYPE dgraph_endpoint_qps gauge")
        for name, m in sorted(meters.items()):
            out.append(f'dgraph_endpoint_qps{{endpoint="{_esc(name)}"}} '
                       f"{_num(m.rate())}")

    for name, g in sorted(keyed.items()):
        name = _safe(name)
        out.append(f"# TYPE {name} gauge")
        labels = getattr(g, "labels", None)
        for key, v in sorted(g.snapshot().items()):
            if labels:
                parts = key.split("|", len(labels) - 1)
                if len(parts) == len(labels):
                    lbl = ",".join(f'{n}="{_esc(p)}"'
                                   for n, p in zip(labels, parts))
                    out.append(f"{name}{{{lbl}}} {_num(v)}")
                    continue
            out.append(f'{name}{{key="{_esc(key)}"}} {_num(v)}')

    return "\n".join(out) + "\n"


def parse(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Minimal text-format parse check: returns {metric: [(labels, value)]}
    and raises ValueError on any malformed line. Used by tests and
    contrib/scripts/smoke_trace.sh to validate the exposition — not a
    full Prometheus client."""
    series: dict[str, list[tuple[dict, float]]] = {}
    typed: dict[str, str] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                if not _NAME_OK.match(parts[2]):
                    raise ValueError(f"line {ln}: bad metric name {parts[2]}")
                if parts[3] not in ("counter", "gauge", "summary",
                                    "histogram", "untyped"):
                    raise ValueError(f"line {ln}: bad type {parts[3]}")
                typed[parts[2]] = parts[3]
            continue
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{([^}]*)\})?\s+(\S+)$", line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample {line!r}")
        name, labels_raw, value = m.groups()
        labels: dict[str, str] = {}
        if labels_raw:
            for item in re.finditer(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                    labels_raw):
                labels[item.group(1)] = item.group(2)
            if not labels:
                raise ValueError(f"line {ln}: malformed labels {labels_raw!r}")
        try:
            fv = float(value)
        except ValueError:
            raise ValueError(f"line {ln}: non-numeric value {value!r}")
        base = re.sub(r"_(sum|count)$", "", name)
        if base not in typed and name not in typed:
            raise ValueError(f"line {ln}: sample {name} without # TYPE")
        series.setdefault(name, []).append((labels, fv))
    return series
