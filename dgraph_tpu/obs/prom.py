"""Prometheus text exposition of a metrics.Registry.

Mapping:
  Counter       -> `counter` when the name ends in _total, else `gauge`
                   (the registry uses Counter.set for gauge-shaped values
                   like dgraph_memory_bytes, matching the reference's
                   expvar dual use).
  Histogram     -> a real `histogram`: cumulative `{le="..."}` buckets
                   over the FIXED exponential bounds plus _sum/_count —
                   aggregatable across nodes and time, unlike the old
                   quantile-label summary rows (removed from /metrics in
                   ISSUE 13; the ring percentiles stay on /debug/metrics).
                   Buckets carry OpenMetrics trace EXEMPLARS
                   (`# {trace_id="..."} value ts`) sampling the trace
                   that landed in each bucket — resolvable at
                   /debug/traces/<id>.
  Meter         -> gauge `dgraph_endpoint_qps{endpoint="<name>"}`.
  KeyedGauge    -> gauge with a `key` label per entry.

Names already follow the dgraph_* vocabulary and are valid Prometheus
metric names; keys/labels are escaped per the text-format rules.
"""

from __future__ import annotations

import re

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# registry names that END in _total but are inc/dec LEVELS, not monotonic
# counters (the reference's expvar dual-use) — a counter TYPE would make
# every decrease read as a reset, so rate()/increase() would spike
_LEVEL_TOTALS = frozenset({"dgraph_pending_queries_total",
                           "dgraph_active_mutations_total"})


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _num(v) -> str:
    f = float(v)
    return repr(int(f)) if f.is_integer() else repr(f)


def _safe(name: str) -> str:
    return name if _NAME_OK.match(name) else \
        re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _render_histogram(name: str, ex: dict,
                      exemplars_on: bool = True) -> list[str]:
    """Cumulative le-bucket exposition of one exported histogram, with
    OpenMetrics exemplars on the bucket that sampled a trace (suppressed
    for classic text-format scrapes — see render())."""
    out = [f"# TYPE {name} histogram"]
    bounds = ex.get("bounds", [])
    counts = ex.get("counts", [])
    exemplars = ex.get("exemplars", []) if exemplars_on else []
    cum = 0
    for i, le in enumerate(bounds):
        cum += counts[i] if i < len(counts) else 0
        line = f'{name}_bucket{{le="{_num(le)}"}} {cum}'
        e = exemplars[i] if i < len(exemplars) else None
        if e:
            line += (f' # {{trace_id="{_esc(str(e[0]))}"}} '
                     f"{_num(e[1])} {_num(round(float(e[2]), 3))}")
        out.append(line)
    total = int(ex.get("count", 0))
    line = f'{name}_bucket{{le="+Inf"}} {total}'
    e = exemplars[len(bounds)] if len(exemplars) > len(bounds) else None
    if e:
        line += (f' # {{trace_id="{_esc(str(e[0]))}"}} '
                 f"{_num(e[1])} {_num(round(float(e[2]), 3))}")
    out.append(line)
    out.append(f"{name}_sum {_num(ex.get('sum', 0.0))}")
    out.append(f"{name}_count {total}")
    return out


# content types for the two exposition flavors. Exemplar syntax is ONLY
# legal under OpenMetrics: a classic text-format (0.0.4) parser treats
# the trailing '# {...}' as a malformed timestamp and real Prometheus
# would discard the WHOLE scrape — so the HTTP surfaces negotiate on the
# Accept header (wants_openmetrics) and render() only emits exemplars
# when asked.
CONTENT_TYPE_TEXT = "text/plain; version=0.0.4; charset=utf-8"
CONTENT_TYPE_OPENMETRICS = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def wants_openmetrics(accept: str | None) -> bool:
    return bool(accept) and "application/openmetrics-text" in accept


def negotiated(accept: str | None, render_fn) -> tuple[bytes, str]:
    """(body, content_type) for one scrape, negotiated on the Accept
    header — the ONE implementation both /metrics (api/http.py) and
    Zero's /metrics/fleet share, so the OM suffix/content-type rules
    cannot drift apart. render_fn(exemplars: bool) -> str."""
    om = wants_openmetrics(accept)
    text = render_fn(om)
    if om:
        text += "# EOF\n"
    return text.encode(), (CONTENT_TYPE_OPENMETRICS if om
                           else CONTENT_TYPE_TEXT)


def render(registry, exemplars: bool = False) -> str:
    """The /metrics payload. The registry's metric MAPS are copied under
    its lock (a concurrent first-use setdefault must not resize them
    mid-iteration); the per-metric reads below use each metric's own
    locking."""
    lock = getattr(registry, "_lock", None)
    if lock is not None:
        with lock:
            counters = dict(registry.counters)
            histograms = dict(registry.histograms)
            meters = dict(registry.meters)
            keyed = dict(registry.keyed_gauges)
    else:
        counters, histograms = dict(registry.counters), \
            dict(registry.histograms)
        meters, keyed = dict(registry.meters), dict(registry.keyed_gauges)
    out: list[str] = []

    for name, c in sorted(counters.items()):
        name = _safe(name)
        kind = "counter" if name.endswith("_total") \
            and name not in _LEVEL_TOTALS else "gauge"
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {_num(c.value)}")

    for name, h in sorted(histograms.items()):
        name = _safe(name)
        out.extend(_render_histogram(name, h.export(),
                                     exemplars_on=exemplars))

    if meters:
        out.append("# TYPE dgraph_endpoint_qps gauge")
        for name, m in sorted(meters.items()):
            out.append(f'dgraph_endpoint_qps{{endpoint="{_esc(name)}"}} '
                       f"{_num(m.rate())}")

    for name, g in sorted(keyed.items()):
        name = _safe(name)
        out.append(f"# TYPE {name} gauge")
        labels = getattr(g, "labels", None)
        for key, v in sorted(g.snapshot().items()):
            if labels:
                parts = key.split("|", len(labels) - 1)
                if len(parts) == len(labels):
                    lbl = ",".join(f'{n}="{_esc(p)}"'
                                   for n, p in zip(labels, parts))
                    out.append(f"{name}{{{lbl}}} {_num(v)}")
                    continue
            out.append(f'{name}{{key="{_esc(key)}"}} {_num(v)}')

    return "\n".join(out) + "\n"


def render_export(export: dict, exemplars: bool = False) -> str:
    """Prometheus text exposition of a Registry.export() snapshot — the
    merged-fleet payload Zero serves at /metrics/fleet. Counter/gauge
    typing follows the same name rules as render(); histograms render
    their merged buckets (exact across nodes: fixed bounds)."""
    out: list[str] = []
    for name, v in sorted(export.get("counters", {}).items()):
        name = _safe(name)
        kind = "counter" if name.endswith("_total") \
            and name not in _LEVEL_TOTALS else "gauge"
        out.append(f"# TYPE {name} {kind}")
        out.append(f"{name} {_num(v)}")
    for name, h in sorted(export.get("histograms", {}).items()):
        out.extend(_render_histogram(_safe(name), h,
                                     exemplars_on=exemplars))
    for name, g in sorted(export.get("keyed", {}).items()):
        name = _safe(name)
        out.append(f"# TYPE {name} gauge")
        labels = g.get("labels")
        for key, v in sorted(g.get("vals", {}).items()):
            if labels:
                parts = key.split("|", len(labels) - 1)
                if len(parts) == len(labels):
                    lbl = ",".join(f'{n}="{_esc(p)}"'
                                   for n, p in zip(labels, parts))
                    out.append(f"{name}{{{lbl}}} {_num(v)}")
                    continue
            out.append(f'{name}{{key="{_esc(key)}"}} {_num(v)}')
    return "\n".join(out) + "\n"


# an exemplar suffix on a bucket sample (OpenMetrics):
#   # {trace_id="..."} value [timestamp]
_EXEMPLAR_RE = re.compile(
    r"\s+#\s+\{([^}]*)\}\s+(\S+)(?:\s+(\S+))?$")


def parse(text: str) -> dict[str, list[tuple[dict, float]]]:
    """Minimal text-format parse check: returns {metric: [(labels, value)]}
    and raises ValueError on any malformed line. Bucket samples may carry
    OpenMetrics exemplars — parsed off and exposed as an `__exemplar__`
    pseudo-label so tests can round-trip a trace id. Used by tests and
    contrib/scripts smoke checks — not a full Prometheus client."""
    series: dict[str, list[tuple[dict, float]]] = {}
    typed: dict[str, str] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                if not _NAME_OK.match(parts[2]):
                    raise ValueError(f"line {ln}: bad metric name {parts[2]}")
                if parts[3] not in ("counter", "gauge", "summary",
                                    "histogram", "untyped"):
                    raise ValueError(f"line {ln}: bad type {parts[3]}")
                typed[parts[2]] = parts[3]
            continue
        exemplar = None
        em = _EXEMPLAR_RE.search(line)
        if em is not None:
            ex_labels: dict[str, str] = {}
            for item in re.finditer(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                    em.group(1)):
                ex_labels[item.group(1)] = item.group(2)
            try:
                float(em.group(2))
            except ValueError:
                raise ValueError(
                    f"line {ln}: non-numeric exemplar value {em.group(2)!r}")
            exemplar = ex_labels
            line = line[: em.start()]
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                     r"(?:\{([^}]*)\})?\s+(\S+)$", line)
        if m is None:
            raise ValueError(f"line {ln}: malformed sample {line!r}")
        name, labels_raw, value = m.groups()
        labels: dict[str, str] = {}
        if labels_raw:
            for item in re.finditer(
                    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                    labels_raw):
                labels[item.group(1)] = item.group(2)
            if not labels:
                raise ValueError(f"line {ln}: malformed labels {labels_raw!r}")
        try:
            fv = float(value)
        except ValueError:
            raise ValueError(f"line {ln}: non-numeric value {value!r}")
        base = re.sub(r"_(sum|count|bucket)$", "", name)
        if base not in typed and name not in typed:
            raise ValueError(f"line {ln}: sample {name} without # TYPE")
        if exemplar is not None:
            labels = dict(labels)
            labels["__exemplar__"] = exemplar.get("trace_id", "")
        series.setdefault(name, []).append((labels, fv))
    return series
