"""Observability subsystem: distributed span tracing + device profiling
(obs/otrace.py), Prometheus text exposition of the metrics Registry
(obs/prom.py), and the threshold-gated slow-query log (obs/slowlog.py).

The span model is Dapper's (Sigelman et al., 2010): every sampled request
gets a trace_id; every unit of work a (span_id, parent_id) pair; context
rides gRPC metadata across the cross-shard fan-out and rides a contextvar
within a process, so one query's tree covers client dispatch, every
per-group serve_task, Zero coordinator calls, and the device kernels.
"""
