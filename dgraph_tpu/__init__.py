"""dgraph_tpu — a TPU-native distributed graph query engine.

A brand-new framework with the capabilities of Dgraph v1.0.4 (the reference at
/root/reference): a distributed, transactional graph database with a GraphQL-like
query language (DQL / "GraphQL+-"), predicate-sharded storage, secondary indexes,
reverse edges, traversal algorithms (@recurse, shortest path), @groupby and
aggregations, and snapshot-isolation transactions — re-designed TPU-first:

- Posting lists live as HBM-resident per-predicate CSR graphs
  (descendant of the reference's bp128 blocks, bp128/bp128.go).
- Sorted-uid set algebra (reference: algo/uidlist.go) is vectorized jnp/Pallas.
- Multi-hop traversal is iterative SpMSpV under jit (reference: query/recurse.go,
  query/shortest.go ran host-side Dijkstra over hash maps).
- Cross-shard fan-out (reference: worker/task.go ProcessTaskOverNetwork over gRPC)
  is shard_map + ICI collectives over a jax.sharding.Mesh.

Layout:
  ops/       device kernels: uid-set algebra, CSR expand, segmented reductions, Pallas
  storage/   host-side storage: key scheme, packed posting codec, posting store, CSR build
  query/     DQL parser, SubGraph plan, ProcessGraph engine, traversals, JSON encoding
  parallel/  mesh construction, sharded CSR, frontier collectives
  models/    graph generators & datasets for tests/benchmarks (RMAT, film graph, LDBC-ish)
  utils/     value types, conversion matrix, tokenizers, watermark, config
"""

__version__ = "0.1.0"
