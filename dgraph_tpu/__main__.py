"""CLI: `python -m dgraph_tpu <subcommand>`.

Reference semantics: dgraph/cmd/root.go cobra subcommands (server, zero,
live, bulk, version). The embedded node runs server+zero in one process
(the reference's test topology); multi-group clusters are the mesh's job,
not separate OS processes (SURVEY.md §7).
"""

from __future__ import annotations

import argparse
import sys

VERSION = "dgraph-tpu 0.2.0"


def cmd_serve(args) -> int:
    from dgraph_tpu.api.http import make_server
    from dgraph_tpu.api.server import Node

    node = Node(dirpath=args.postings)
    if args.schema:
        with open(args.schema) as f:
            node.alter(schema_text=f.read())
    srv = make_server(node, args.host, args.port)
    print(f"serving HTTP on {args.host}:{args.port} "
          f"(postings={args.postings or '<memory>'})", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        node.close()
    return 0


def cmd_version(_args) -> int:
    print(VERSION)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dgraph_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve", help="run the embedded server (HTTP API)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8080)
    sp.add_argument("-p", "--postings", default=None,
                    help="durable posting dir (default: in-memory)")
    sp.add_argument("--schema", default=None, help="schema file to apply")
    sp.set_defaults(fn=cmd_serve)

    vp = sub.add_parser("version", help="print version")
    vp.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
