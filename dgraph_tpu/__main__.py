"""CLI: `python -m dgraph_tpu <subcommand>`.

Reference semantics: dgraph/cmd/root.go cobra subcommands (server, zero,
live, bulk, version). The embedded node runs server+zero in one process
(the reference's test topology); multi-group clusters are the mesh's job,
not separate OS processes (SURVEY.md §7).
"""

from __future__ import annotations

import argparse
import sys

from dgraph_tpu.utils import log

VERSION = "dgraph-tpu 0.2.0"


def cmd_serve(args) -> int:
    import threading

    from dgraph_tpu.api.http import make_server
    from dgraph_tpu.api.server import Node

    lg = log.get_logger("serve")
    node = Node(dirpath=args.postings, trace_fraction=args.trace,
                memory_mb=args.memory_mb or None,
                plan_cache_size=args.plan_cache,
                task_cache_mb=args.task_cache_mb,
                result_cache_mb=args.result_cache_mb,
                dispatch_width=args.dispatch_width,
                batching=not args.no_batch,
                batch_window_ms=args.batch_window_ms,
                batch_max=args.batch_max,
                write_batch=not args.no_write_batch,
                write_window_ms=args.write_window_ms,
                write_batch_max=args.write_batch_max,
                overlay=not args.no_overlay,
                overlay_max_keys=args.overlay_max_keys,
                overlay_max_age_s=args.overlay_max_age_s,
                background_rollup=not args.no_background_rollup,
                fold_workers=args.fold_workers or None,
                planner=not args.no_planner,
                stats_top_k=args.stats_top_k,
                span_sample=args.span_sample,
                slow_query_ms=args.slow_query_ms,
                slow_query_log=args.slow_query_log,
                mesh_devices=(args.mesh_devices or (-1 if args.mesh else 0)),
                mesh_min_edges=args.mesh_min_edges or None,
                default_timeout_ms=args.default_timeout_ms,
                vector_nprobe=args.vector_nprobe,
                vector_centroids=args.vector_centroids,
                vector_ivf_min_rows=args.vector_ivf_min_rows,
                device_budget_mb=args.device_budget_mb,
                residency_pin=args.residency_pin,
                cost_ledger=not args.no_cost_ledger,
                cost_regression_factor=args.cost_regression_factor,
                devprof=not args.no_devprof,
                lazy_folds=not args.no_lazy_folds,
                delta_journal_max_keys=args.delta_journal_max_keys or None,
                qos=not args.no_qos,
                tenants=args.tenants or None)
    if args.faults or args.faults_seed is not None:
        from dgraph_tpu.utils import faults as faults_mod

        if args.faults_seed is not None:    # 0 is a valid seed
            faults_mod.GLOBAL.reseed(args.faults_seed)
        if args.faults:
            faults_mod.GLOBAL.configure(args.faults)
        lg.info("fault injection armed", points=args.faults or "",
                seed=args.faults_seed)
    if args.memory_mb:
        node.set_memory_budget(args.memory_mb * (1 << 20))
    if args.schema:
        with open(args.schema) as f:
            node.alter(schema_text=f.read())
    grpc_srv = None
    if args.grpc_port:
        from dgraph_tpu.api.grpc_server import serve_grpc
        grpc_srv, gport = serve_grpc(node, f"{args.host}:{args.grpc_port}",
                                     tls_cert=args.tls_cert,
                                     tls_key=args.tls_key)
        # startup banners keep the "<role> serving ... on host:port" shape:
        # tests and contrib/scripts parse the bound port out of text mode
        lg.info(f"serving gRPC on {args.host}:{gport}",
                tls=bool(args.tls_cert))
    srv = make_server(node, args.host, args.port,
                      tls_cert=args.tls_cert, tls_key=args.tls_key)
    lg.info(f"serving HTTP{'S' if args.tls_cert else ''} on "
            f"{args.host}:{srv.server_address[1]}",
            postings=args.postings or "<memory>")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        if grpc_srv is not None:
            grpc_srv.stop(0)
        node.close()
    return 0


def cmd_version(_args) -> int:
    log.get_logger("version").info(VERSION)
    return 0


def cmd_bulk(args) -> int:
    from dgraph_tpu.loader.bulk import bulk_load

    lg = log.get_logger("bulk")
    schema = ""
    if args.schema:
        with open(args.schema) as f:
            schema = f.read()
    stats = bulk_load(args.files, schema, args.out, workers=args.workers,
                      spill_mb=args.spill_mb or None,
                      xidmap_cache=_xidmap_entries(args.xidmap_cache_mb),
                      progress=lambda n: lg.info("parsing", quads=n))
    fields = dict(postings=stats.edges, uid_edges=stats.uid_edges,
                  values=stats.values, nodes=stats.nodes,
                  predicates=stats.predicates,
                  seconds=round(stats.seconds, 1), out=args.out)
    if args.spill_mb:
        fields.update(spill_runs=stats.spill_runs,
                      spill_mb=round(stats.spill_bytes / (1 << 20), 1),
                      merge_fanin=stats.merge_fanin,
                      xidmap_hit_rate=round(stats.xidmap_hit_rate, 4))
    lg.info("bulk load done", **fields)
    return 0


def _xidmap_entries(cache_mb) -> int | None:
    """--xidmap_cache_mb → resident-entry bound (~96B per mapping: short
    key string + dict slot + uid)."""
    if not cache_mb:
        return None
    return max(1, int(cache_mb * (1 << 20)) // 96)


def cmd_export(args) -> int:
    from dgraph_tpu.loader.export import export_rdf
    from dgraph_tpu.storage.store import Store

    store = Store(args.postings)
    stats = export_rdf(store, args.out, schema_path=args.out_schema)
    store.close()
    log.get_logger("export").info("export done", quads=stats.quads,
                                  predicates=stats.predicates, out=args.out)
    return 0


def cmd_live(args) -> int:
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.loader.live import live_load

    lg = log.get_logger("live")
    node = Node(dirpath=args.postings)
    if args.schema:
        with open(args.schema) as f:
            node.alter(schema_text=f.read())
    try:
        stats = live_load(node, args.files, batch=args.batch,
                          xidmap_path=args.xidmap,
                          xidmap_cache=_xidmap_entries(args.xidmap_cache_mb),
                          progress=lambda n: lg.info("loading", quads=n))
    finally:
        node.close()
    lg.info("live load done", quads=stats.quads, txns=stats.txns,
            retried_aborts=stats.aborts, postings=args.postings)
    return 0


def cmd_worker(args) -> int:
    """Serve one group's tablets over the internal wire protocol
    (the reference's worker gRPC on port 7080). With --zero it registers
    with the cluster coordinator (worker/groups.go:62 StartRaftNodes's
    connect step); replication roles arrive via the Promote RPC."""
    import time

    from dgraph_tpu.parallel.remote import serve_worker
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.utils.schema import parse_schema

    lg = log.get_logger("worker")
    store = Store(args.postings,
                  max_delta_keys=args.delta_journal_max_keys or None)
    if args.schema:
        with open(args.schema) as f:
            for e in parse_schema(f.read()):
                store.set_schema(e)
    server, port = serve_worker(store, f"{args.host}:{args.port}",
                                elections=True,
                                advertise_host=args.advertise_host,
                                batching=not args.no_batch,
                                batch_window_ms=args.batch_window_ms,
                                batch_max=args.batch_max,
                                cost_ledger=not args.no_cost_ledger,
                                lazy_folds=not args.no_lazy_folds)
    if args.zero:
        import threading

        from dgraph_tpu.coord.zero_service import ZeroClient

        zc = ZeroClient(args.zero)
        svc = server.dgt_svc
        my_addr = svc.advertise_addr
        # a worker booting while the zeros are still electing (multi-zero
        # bootstrap) must wait for a leader, not die: retry the initial
        # registration against transient transport / not-leader rejections
        deadline = time.monotonic() + 60
        while True:
            try:
                group, rid = zc.connect(my_addr, args.group)
                break
            except Exception as e:      # noqa: BLE001 — startup retry
                if time.monotonic() >= deadline:
                    raise
                lg.info("zero not ready; retrying connect",
                        error=type(e).__name__)
                time.sleep(0.5)
        lg.info("worker joined group", group=group, replica=rid)

        def _learn_members():
            # seed the wire-election membership from Zero's registry so a
            # replica set can self-elect even when the control plane later
            # dies (the members list keeps working from cache)
            st = zc.state()
            members = st.get("groups", {}).get(str(group), {}) \
                        .get("members", [])
            if members:
                svc.group_members = sorted(set(members) | {my_addr})

        try:
            _learn_members()
        except Exception:
            pass

        def membership_loop():
            # periodic re-registration (worker/groups.go:454
            # periodicMembershipUpdate): survives a zero restart and keeps
            # the registry a liveness signal, not a one-shot record
            while True:
                time.sleep(args.membership_interval)
                try:
                    zc.connect(my_addr, group)
                    _learn_members()
                except Exception:
                    pass                   # zero down: next tick retries

        if args.membership_interval > 0:
            # dgraph: allow(ctxvar-copy) detached membership bg loop
            threading.Thread(target=membership_loop, daemon=True).start()
    lg.info(f"worker serving {len(store.predicates())} tablets on "
            f"{args.host}:{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop(0)
        store.close()
    return 0


def cmd_zero(args) -> int:
    """Run the cluster coordinator as its own process (reference
    `dgraph zero`, dgraph/cmd/zero/run.go:58): timestamp/uid leases, the
    SSI oracle, and the tablet map over the internal protocol."""
    import threading
    import time

    from dgraph_tpu.coord.zero import Zero
    from dgraph_tpu.coord.zero_service import (ZeroOps, serve_zero,
                                               serve_zero_http)

    lg = log.get_logger("zero")
    zero = Zero(n_groups=args.groups, dirpath=args.wal)
    from dgraph_tpu.coord.zero_service import ZeroReplica, ZeroService

    svc = ZeroService(zero)
    replica = None
    if args.peers:
        if not args.wal:
            raise SystemExit("--peers (multi-zero) requires --wal")
        members = [a.strip() for a in args.peers.split(",") if a.strip()]
        advertise = members[args.idx]
        replica = ZeroReplica(svc, args.wal, advertise, members,
                              bootstrap_leader=args.idx == 0)
    server, port, svc = serve_zero(zero, f"{args.host}:{args.port}", svc=svc)
    if replica is not None:
        replica.start()
        lg.info("zero replica up", idx=args.idx,
                members=len(replica.members), leader=replica.is_leader)
    ops = ZeroOps(svc)
    controller = None
    if args.rebalance_interval_s > 0 and not args.no_rebalance:
        # load-aware placement controller (coord/placement.py): scores
        # tablets by size x measured load from the workers' Status
        # reports and heals skew with moves + hot-tablet read replicas
        from dgraph_tpu.coord.placement import (PlacementConfig,
                                                PlacementController,
                                                ZeroOpsExecutor,
                                                wire_collect)

        class _DynamicZero:
            # multi-zero promotion swaps svc.zero; always read through ops
            def tablets(self):
                return ops.zero.tablets()

            def replicas(self):
                return ops.zero.replicas()

            def moving_tablets(self):
                return ops.zero.moving_tablets()

        cfg = PlacementConfig(threshold=args.rebalance_threshold,
                              max_replicas=args.max_replicas)
        controller = PlacementController(
            _DynamicZero(), wire_collect(ops), ZeroOpsExecutor(ops),
            cfg=cfg, logger=lg)
        controller.start(args.rebalance_interval_s)
        lg.info("placement controller up",
                interval_s=args.rebalance_interval_s,
                threshold=args.rebalance_threshold,
                max_replicas=args.max_replicas)
    httpd, hport = serve_zero_http(svc, ops, args.host, args.http_port,
                                   controller=controller)
    lg.info(f"zero ops HTTP on {args.host}:{hport}")
    if args.rebalance_interval > 0 and not args.no_rebalance:
        def loop():
            while True:
                time.sleep(args.rebalance_interval)
                try:
                    out = ops.rebalance_once()
                    if out:
                        lg.info("rebalanced", **out)
                except Exception as e:       # noqa: BLE001 — next tick retries
                    lg.error("rebalance error", error=str(e))
        # dgraph: allow(ctxvar-copy) detached console-stats bg loop
        threading.Thread(target=loop, daemon=True).start()
    lg.info(f"zero serving {args.groups} groups on {args.host}:{port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        server.stop(0)
    return 0


def cmd_ldbc_gen(args) -> int:
    """Deterministic LDBC-SNB-shaped synthetic CSV dump (ISSUE 15):
    `ldbc_gen --sf 1 --out dump/` then `convert --ldbc dump/` then
    `bulk -f` is the scale battery's zero-dependency ingest path."""
    from dgraph_tpu.models.ldbc import generate_ldbc

    lg = log.get_logger("ldbc_gen")
    st = generate_ldbc(args.out, sf=args.sf, seed=args.seed)
    lg.info("ldbc_gen done", sf=st.sf, persons=st.persons, knows=st.knows,
            posts=st.posts, comments=st.comments, edges=st.edges,
            out=args.out)
    return 0


def cmd_convert(args) -> int:
    lg = log.get_logger("convert")
    if args.ldbc:
        from dgraph_tpu.loader.convert import convert_ldbc

        stats = convert_ldbc(args.ldbc, args.out)
        lg.info("ldbc convert done", persons=stats.persons,
                knows=stats.knows, posts=stats.posts,
                triples=stats.triples, out=args.out)
        return 0
    if not args.geo:
        raise SystemExit("convert needs --geo <file> or --ldbc <dir>")
    from dgraph_tpu.loader.convert import convert_geojson

    stats = convert_geojson(args.geo, args.out, geopred=args.geopred)
    lg.info("convert done", features=stats.features,
            triples=stats.triples, out=args.out)
    return 0


def _apply_env_defaults(sp: argparse.ArgumentParser) -> None:
    """DGRAPH_TPU_<FLAG> environment variables override flag defaults
    (the reference's viper env binding: every cobra flag doubles as an env
    key). Explicit command-line values still win."""
    import os

    for action in sp._actions:
        if not action.option_strings or action.dest == "help":
            continue
        env = os.environ.get(f"DGRAPH_TPU_{action.dest.upper()}")
        if env is None:
            continue
        if action.type is int:
            action.default = int(env)
        elif action.type is float:
            action.default = float(env)
        elif isinstance(action, argparse._StoreTrueAction):
            action.default = env.lower() in ("1", "true", "yes")
        elif action.nargs in ("+", "*"):
            action.default = env.split(",")
        else:
            action.default = env
        action.required = False


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dgraph_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("serve", help="run the embedded server (HTTP API)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8080)
    sp.add_argument("--grpc_port", type=int, default=9080,
                    help="gRPC api.Dgraph port (0 disables)")
    sp.add_argument("-p", "--postings", default=None,
                    help="durable posting dir (default: in-memory)")
    sp.add_argument("--schema", default=None, help="schema file to apply")
    sp.add_argument("--trace", type=float, default=1.0,
                    help="fraction of requests to trace (/debug/requests)")
    sp.add_argument("--span_sample", type=float, default=0.01,
                    help="fraction of requests getting a full span trace "
                         "(/debug/traces; Chrome trace JSON per trace; "
                         "set 1.0 when debugging a specific query)")
    sp.add_argument("--slow_query_ms", type=float, default=0.0,
                    help="log queries slower than this to /debug/slow "
                         "(plan + span tree; 0 disables)")
    sp.add_argument("--slow_query_log", default=None,
                    help="also append slow-query entries to this JSONL file")
    sp.add_argument("--no_cost_ledger", action="store_true",
                    help="disable the per-request cost ledger (/debug/top "
                         "profiler, dgraph_query_cost_* histograms, "
                         "regression flags; <2%% overhead armed)")
    sp.add_argument("--cost_regression_factor", type=float, default=4.0,
                    help="flag a query into /debug/slow when its device "
                         "cost exceeds this multiple of its plan-shape's "
                         "EWMA baseline (needs 8 warmup samples)")
    sp.add_argument("--no_devprof", action="store_true",
                    help="disable the device-runtime observatory (XLA "
                         "compile/retrace tracking, HBM telemetry, "
                         "/debug/compiles + /debug/timeline; zero overhead "
                         "when off)")
    sp.add_argument("--plan_cache", type=int, default=256,
                    help="parsed-plan cache entries (0 disables)")
    sp.add_argument("--task_cache_mb", type=int, default=64,
                    help="task-result cache budget in MB (0 disables)")
    sp.add_argument("--result_cache_mb", type=int, default=32,
                    help="query-result cache budget in MB (0 disables)")
    sp.add_argument("--batch_window_ms", type=float, default=2.0,
                    help="batched-dispatch collect window in ms; a batch "
                         "fires immediately when the device is idle")
    sp.add_argument("--batch_max", type=int, default=16,
                    help="max tasks packed into one batched device kernel")
    sp.add_argument("--no_batch", action="store_true",
                    help="disable batched multi-query device execution "
                         "(exact per-task dispatch)")
    sp.add_argument("--write_window_ms", type=float, default=2.0,
                    help="group-commit collect window in ms; a window "
                         "fires immediately when the journal is idle")
    sp.add_argument("--write_batch_max", type=int, default=64,
                    help="max txns committed per group-commit window "
                         "(one WAL append + one fsync per window)")
    sp.add_argument("--no_write_batch", action="store_true",
                    help="disable group-commit write batching (exact "
                         "per-commit WAL append + fsync)")
    sp.add_argument("--dispatch_width", type=int, default=4,
                    help="max simultaneous device dispatches")
    sp.add_argument("--no_overlay", action="store_true",
                    help="disable delta-overlay stamping (commits re-fold "
                         "their whole tablet)")
    sp.add_argument("--overlay_max_keys", type=int, default=None,
                    help="overlay depth ceiling before inline compaction "
                         "(default 512)")
    sp.add_argument("--overlay_max_age_s", type=float, default=None,
                    help="overlay age before background rollup (default 30)")
    sp.add_argument("--no_background_rollup", action="store_true",
                    help="disable the background overlay compaction loop")
    sp.add_argument("--delta_journal_max_keys", type=int, default=0,
                    help="per-predicate delta-journal key bound (0 = "
                         "default 8192); size to the working set a live "
                         "subscriber may fall behind by — overflow forces "
                         "affected subscriptions through a full resync")
    sp.add_argument("--fold_workers", type=int, default=0,
                    help="parallel tablet-fold threads (0 = auto)")
    sp.add_argument("--no_lazy_folds", action="store_true",
                    help="fold every tablet eagerly at snapshot assembly "
                         "(the pre-ISSUE-15 cold path) instead of "
                         "on-demand at first read")
    sp.add_argument("--no_planner", action="store_true",
                    help="disable the cost-based query planner "
                         "(restores parse-order execution)")
    sp.add_argument("--stats_top_k", type=int, default=8,
                    help="top-K term-frequency sketch size per index "
                         "tokenizer (EXPLAIN / stats readout)")
    sp.add_argument("--mesh", action="store_true",
                    help="mesh deployment mode: shard large tablets across "
                         "every visible device and fuse multi-hop "
                         "traversals into one jitted dispatch (per-hop "
                         "frontier exchange over ICI; docs/ops.md)")
    sp.add_argument("--mesh_devices", type=int, default=0,
                    help="shard over the first N devices instead of all "
                         "(implies --mesh; 0 = follow --mesh)")
    sp.add_argument("--mesh_min_edges", type=int, default=0,
                    help="tablets below this edge count stay replicated on "
                         "the classic path (0 = default 65536)")
    sp.add_argument("--vector_nprobe", type=int, default=0,
                    help="IVF coarse lists scanned per similar_to probe "
                         "(0 = default 8; higher = recall, lower = speed)")
    sp.add_argument("--vector_centroids", type=int, default=-1,
                    help="IVF centroid count built at snapshot fold "
                         "(-1 = auto ~sqrt(rows), clamped to [8, 1024])")
    sp.add_argument("--vector_ivf_min_rows", type=int, default=0,
                    help="embedding tablets below this row count stay "
                         "brute-force exact (0 = default 4096)")
    sp.add_argument("--device_budget_mb", type=int, default=0,
                    help="device (HBM) byte budget for the working-set "
                         "manager; tablets admit/evict by load score and "
                         "graphs larger than the budget serve through the "
                         "host tiers (0 = unbounded)")
    sp.add_argument("--residency_pin", default="",
                    help="comma-separated predicates pinned in the HBM "
                         "tier (never evicted by the working-set manager)")
    sp.add_argument("--memory_mb", type=int, default=0,
                    help="posting-list memory budget; periodic rollup + "
                         "cache drop keeps usage under it (0 = unbounded)")
    sp.add_argument("--default_timeout_ms", type=float, default=0,
                    help="end-to-end deadline budget for requests without "
                         "an explicit ?timeoutMs= — consumed at every wait "
                         "point, typed DeadlineExceeded on overrun, never "
                         "a hang (0 = unbudgeted)")
    sp.add_argument("--tenants", default=None,
                    help="tenant QoS table: a JSON file path or inline "
                         'JSON {"tenants": {name: {weight, '
                         "device_ms_per_s, edges_per_s, bytes_per_s, "
                         "burst_s, max_subs, sub_queue_max}}}; hot-"
                         "reloadable via POST /admin/tenant")
    sp.add_argument("--no_qos", action="store_true",
                    help="disarm quota admission + weighted-fair device "
                         "scheduling (namespaces stay active; a single-"
                         "tenant deployment is byte-identical either way)")
    sp.add_argument("--faults", default=None,
                    help="arm fault injection: 'name:mode:p[:delay_s]"
                         "[:count],...' over the points in docs/ops.md "
                         "(modes error/delay/drop; chaos testing only)")
    sp.add_argument("--faults_seed", type=int, default=None,
                    help="deterministic PRNG seed for --faults schedules "
                         "(same seed replays the same fault sequence; "
                         "0 is a valid seed)")
    sp.add_argument("--tls_cert", default=None,
                    help="PEM certificate: serve HTTP and gRPC over TLS")
    sp.add_argument("--tls_key", default=None, help="PEM private key")
    sp.set_defaults(fn=cmd_serve)

    vp = sub.add_parser("version", help="print version")
    vp.set_defaults(fn=cmd_version)

    bp = sub.add_parser("bulk", help="offline bulk load RDF(.gz) -> snapshot")
    bp.add_argument("-f", "--files", nargs="+", required=True)
    bp.add_argument("-s", "--schema", default=None)
    bp.add_argument("-o", "--out", required=True, help="output posting dir")
    bp.add_argument("-j", "--workers", type=int, default=None)
    bp.add_argument("--spill_mb", type=float, default=0,
                    help="out-of-core map buffer budget in MB: mapped edges "
                         "spill as sorted runs and the reduce streams a "
                         "k-way merge — peak RAM stops scaling with graph "
                         "size, output byte-identical (0 = all in RAM)")
    bp.add_argument("--xidmap_cache_mb", type=float, default=0,
                    help="resident bound for the sharded xid→uid map; "
                         "cold shards page to disk (0 = unbounded)")
    bp.set_defaults(fn=cmd_bulk)

    ep = sub.add_parser("export", help="export a posting dir to RDF(.gz)")
    ep.add_argument("-p", "--postings", required=True)
    ep.add_argument("-o", "--out", required=True)
    ep.add_argument("--out-schema", default=None)
    ep.set_defaults(fn=cmd_export)

    lp = sub.add_parser("live", help="online load RDF through transactions")
    lp.add_argument("-f", "--files", nargs="+", required=True)
    lp.add_argument("-s", "--schema", default=None)
    lp.add_argument("-p", "--postings", required=True,
                    help="durable posting dir (an in-memory load would be "
                         "discarded at exit)")
    lp.add_argument("--batch", type=int, default=1000)
    lp.add_argument("--xidmap", default=None,
                    help="crash-resumable identity log: re-running an "
                         "interrupted load reuses already-assigned uids")
    lp.add_argument("--xidmap_cache_mb", type=float, default=0,
                    help="resident bound for the sharded xid→uid map "
                         "(needs --xidmap; cold shards page to "
                         "<xidmap>.shards/; 0 = unbounded)")
    lp.set_defaults(fn=cmd_live)

    wp = sub.add_parser("worker", help="serve one group's tablets over the "
                                       "internal worker protocol")
    wp.add_argument("--host", default="127.0.0.1")
    wp.add_argument("--port", type=int, default=7080)
    wp.add_argument("-p", "--postings", required=True)
    wp.add_argument("--schema", default=None, help="schema file to apply")
    wp.add_argument("--zero", default=None,
                    help="zero address to register with (host:port)")
    wp.add_argument("--group", type=int, default=-1,
                    help="group to join (-1 = let zero assign)")
    wp.add_argument("--advertise_host", default=None,
                    help="host peers should dial back (needed when binding "
                         "0.0.0.0, e.g. in containers)")
    wp.add_argument("--membership_interval", type=float, default=30,
                    help="seconds between membership re-registrations with "
                         "zero (0 = register once)")
    wp.add_argument("--batch_window_ms", type=float, default=2.0,
                    help="batched-dispatch collect window in ms; a batch "
                         "fires immediately when the device is idle")
    wp.add_argument("--batch_max", type=int, default=16,
                    help="max tasks packed into one batched device kernel")
    wp.add_argument("--no_batch", action="store_true",
                    help="disable batched multi-query device execution "
                         "(exact per-task dispatch)")
    wp.add_argument("--no_cost_ledger", action="store_true",
                    help="disable per-RPC cost accounting + the cost "
                         "record shipped back in ServeTask trailing "
                         "metadata")
    wp.add_argument("--no_lazy_folds", action="store_true",
                    help="fold every tablet eagerly at snapshot assembly "
                         "instead of on-demand at first read")
    wp.add_argument("--delta_journal_max_keys", type=int, default=0,
                    help="per-predicate delta-journal key bound (0 = "
                         "default 8192)")
    wp.set_defaults(fn=cmd_worker)

    zp = sub.add_parser("zero", help="run the cluster coordinator process")
    zp.add_argument("--host", default="127.0.0.1")
    zp.add_argument("--port", type=int, default=5080)
    zp.add_argument("--http_port", type=int, default=0,
                    help="ops HTTP port: /state /moveTablet /removeNode "
                         "(0 = ephemeral)")
    zp.add_argument("--groups", type=int, default=1,
                    help="number of server groups to balance tablets over")
    zp.add_argument("-w", "--wal", default=None,
                    help="durable state dir: lease ceilings + tablet map "
                         "survive restarts (a crash skips at most one "
                         "10k lease block, assign.go semantics)")
    zp.add_argument("--rebalance_interval", type=float, default=0,
                    help="seconds between LEGACY size-based rebalance ticks "
                         "(tablet.go:60-74; 0 = off)")
    zp.add_argument("--rebalance_interval_s", type=float, default=0,
                    help="seconds between load-aware placement controller "
                         "ticks (coord/placement.py: scores tablets by "
                         "size x measured load, heals skew with moves + "
                         "hot-tablet read replicas; 0 = off)")
    zp.add_argument("--rebalance_threshold", type=float, default=0.35,
                    help="group utilization spread (max-min)/max above "
                         "which the controller acts")
    zp.add_argument("--max_replicas", type=int, default=2,
                    help="read-replica holders per tablet (0 disables "
                         "replication; moves still run)")
    zp.add_argument("--no_rebalance", action="store_true",
                    help="disable ALL automatic placement (both the "
                         "size-based tick and the load controller): "
                         "placement stays exactly as manual moves left it")
    zp.add_argument("--peers", default="",
                    help="multi-zero: comma-separated addresses of ALL "
                         "zeros (incl. this one); state replicates to a "
                         "quorum and standbys elect on leader failure "
                         "(reference --peer, dgraph/cmd/zero/run.go)")
    zp.add_argument("--idx", type=int, default=0,
                    help="this zero's position in --peers (0 bootstraps "
                         "as leader)")
    zp.set_defaults(fn=cmd_zero)

    gp = sub.add_parser("ldbc_gen",
                        help="deterministic LDBC-SNB-shaped synthetic "
                             "CSV dump (feed to `convert --ldbc`)")
    gp.add_argument("--sf", type=float, default=0.1,
                    help="scale factor (persons ~ 10000*sf^0.85)")
    gp.add_argument("--out", required=True, help="output CSV dump dir")
    gp.add_argument("--seed", type=int, default=20260804,
                    help="generator seed (same sf+seed => same bytes)")
    gp.set_defaults(fn=cmd_ldbc_gen)

    cp = sub.add_parser("convert",
                        help="GeoJSON or LDBC-SNB CSV -> RDF (.rdf.gz)")
    cp.add_argument("--geo", default=None,
                    help="GeoJSON file (optionally .gz)")
    cp.add_argument("--ldbc", default=None,
                    help="LDBC-SNB interactive CSV dump dir (persons/"
                         "knows/posts subset mapped to N-Quads)")
    cp.add_argument("--out", default="output.rdf.gz")
    cp.add_argument("--geopred", default="loc",
                    help="predicate for geometries")
    cp.set_defaults(fn=cmd_convert)

    for sp_ in (sp, bp, ep, lp, cp, wp, zp):
        sp_.add_argument("--log_json", action="store_true",
                         help="structured single-line JSON logs instead of "
                              "text (log shippers ingest these directly)")
        _apply_env_defaults(sp_)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "log_json", False):
        log.configure(json_mode=True)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
