// Native block-packed sorted-uid codec — C++ twin of storage/packed.py.
//
// Role: the reference's hot codec is 146k lines of generated SSE2 asm
// (bp128/unpack_amd64.s) behind a Go shim; ours is one branch-light scalar
// loop the compiler auto-vectorizes, because the FORMAT was redesigned so a
// single kernel handles every bit width (see storage/packed.py's header).
// Wire format is bit-identical to the numpy codec: 128-lane blocks,
// struct-of-arrays metadata {first, last, count, width, word offset},
// little-endian deltas in a uint32 word stream, width-64 raw escape.
//
// Flat C ABI for ctypes (no pybind11 in this image). All buffers are
// caller-allocated numpy arrays:
//   nb        = ceil(n / 128)
//   words cap = 256 * nb          (raw-escape worst case)
//
// Build: `make -C native` (g++ -O3 -shared); loaded by storage/native.py.

#include <cstdint>
#include <cstring>

namespace {

constexpr int64_t kBlock = 128;

inline int width_for(uint64_t maxd) {
  int w = 0;
  while (maxd >> w && w < 64) w++;
  return w > 32 ? 64 : w;
}

// Pack one 128-lane block whose deltas and count are prepared.
// Returns words consumed.
inline int64_t pack_block(const uint64_t* deltas, int w, uint32_t* words) {
  if (w == 64) {
    for (int i = 0; i < kBlock; i++) {
      words[2 * i] = (uint32_t)(deltas[i] & 0xFFFFFFFFu);
      words[2 * i + 1] = (uint32_t)(deltas[i] >> 32);
    }
    return 2 * kBlock;
  }
  if (w == 0) return 0;
  int64_t nwords = (kBlock * (int64_t)w) / 32;  // 128*w is always 32-aligned
  std::memset(words, 0, (size_t)nwords * 4);
  for (int i = 0; i < kBlock; i++) {
    int64_t bitpos = (int64_t)i * w;
    int64_t wi = bitpos >> 5;
    int sh = (int)(bitpos & 31);
    uint64_t v = deltas[i];
    words[wi] |= (uint32_t)((v << sh) & 0xFFFFFFFFu);
    uint32_t hi = (uint32_t)(v >> (32 - sh));  // sh==0 → v>>32 == 0 (w<=32)
    if (hi) words[wi + 1] |= hi;               // last lane never spills
  }
  return nwords;
}

inline int64_t pack_one(const uint64_t* uids, int64_t n, uint64_t* bfirst,
                        uint64_t* blast, int32_t* bcount, int32_t* bwidth,
                        int64_t* boff, uint32_t* words, int64_t woff0) {
  int64_t nb = (n + kBlock - 1) / kBlock;
  int64_t woff = woff0;
  uint64_t deltas[kBlock];
  for (int64_t b = 0; b < nb; b++) {
    int64_t s = b * kBlock;
    int64_t cnt = (s + kBlock <= n) ? kBlock : (n - s);
    deltas[0] = 0;
    uint64_t maxd = 0;
    for (int64_t i = 1; i < cnt; i++) {
      uint64_t d = uids[s + i] - uids[s + i - 1];
      deltas[i] = d;
      if (d > maxd) maxd = d;
    }
    for (int64_t i = cnt; i < kBlock; i++) deltas[i] = 0;
    int w = width_for(maxd);
    bfirst[b] = uids[s];
    blast[b] = uids[s + cnt - 1];
    bcount[b] = (int32_t)cnt;
    bwidth[b] = w;
    boff[b] = woff;
    woff += pack_block(deltas, w, words + woff);
  }
  return woff - woff0;
}

// Decode one block's deltas into acc-prefixed uids. `ws` must have one
// readable word past the block's packed span (caller pads the stream).
inline int64_t unpack_one(const uint64_t* bfirst, const int32_t* bcount,
                          const int32_t* bwidth, const int64_t* boff,
                          const uint32_t* words, int64_t nb, uint64_t* out) {
  int64_t k = 0;
  for (int64_t b = 0; b < nb; b++) {
    int w = bwidth[b];
    int cnt = bcount[b];
    uint64_t acc = bfirst[b];
    const uint32_t* ws = words + boff[b];
    out[k++] = acc;
    if (w == 64) {
      for (int i = 1; i < cnt; i++) {
        acc += (uint64_t)ws[2 * i] | ((uint64_t)ws[2 * i + 1] << 32);
        out[k++] = acc;
      }
    } else if (w == 0) {
      for (int i = 1; i < cnt; i++) out[k++] = acc;
    } else {
      uint64_t mask = (w >= 32) ? 0xFFFFFFFFull : ((1ull << w) - 1);
      for (int i = 1; i < cnt; i++) {
        int64_t bitpos = (int64_t)i * w;
        int64_t wi = bitpos >> 5;
        int sh = (int)(bitpos & 31);
        uint64_t pair = (uint64_t)ws[wi] | ((uint64_t)ws[wi + 1] << 32);
        acc += (pair >> sh) & mask;
        out[k++] = acc;
      }
    }
  }
  return k;
}

}  // namespace

extern "C" {

// Returns total words written (metadata arrays sized nb = ceil(n/128)).
int64_t dgt_pack(const uint64_t* uids, int64_t n, uint64_t* bfirst,
                 uint64_t* blast, int32_t* bcount, int32_t* bwidth,
                 int64_t* boff, uint32_t* words) {
  if (n == 0) return 0;
  return pack_one(uids, n, bfirst, blast, bcount, bwidth, boff, words, 0);
}

// words must carry >= 1 pad word past the packed span. Returns uids written.
int64_t dgt_unpack(const uint64_t* bfirst, const int32_t* bcount,
                   const int32_t* bwidth, const int64_t* boff,
                   const uint32_t* words, int64_t nb, uint64_t* out) {
  return unpack_one(bfirst, bcount, bwidth, boff, words, nb, out);
}

// Batched pack over R rows of a concatenated uid stream.
//   row_len[r]         length of row r
//   row_block_start[r] block index where row r's metadata begins (precomputed
//                      exclusive prefix sum of ceil(len/128))
// Global boff entries are row-relative (match pack_many's slicing contract);
// row_word_start[r] receives each row's base into the shared word stream.
// Returns total words written.
int64_t dgt_pack_many(const uint64_t* uids, const int64_t* row_len,
                      const int64_t* row_block_start, int64_t R,
                      uint64_t* bfirst, uint64_t* blast, int32_t* bcount,
                      int32_t* bwidth, int64_t* boff, uint32_t* words,
                      int64_t* row_word_start) {
  int64_t uoff = 0, woff = 0;
  for (int64_t r = 0; r < R; r++) {
    int64_t n = row_len[r];
    row_word_start[r] = woff;
    if (n == 0) continue;
    int64_t b0 = row_block_start[r];
    woff += pack_one(uids + uoff, n, bfirst + b0, blast + b0, bcount + b0,
                     bwidth + b0, boff + b0, words + woff, 0);
    uoff += n;
  }
  return woff;
}

// Batched unpack over R rows (shared metadata arrays laid out row-major,
// row_nb[r] blocks each; each row's boff entries are relative to its own
// word span starting at row_word_start[r]). words must carry >=1 pad word.
// Returns total uids written.
int64_t dgt_unpack_many(const uint64_t* bfirst, const int32_t* bcount,
                        const int32_t* bwidth, const int64_t* boff,
                        const uint32_t* words, const int64_t* row_nb,
                        const int64_t* row_word_start, int64_t R,
                        uint64_t* out) {
  int64_t k = 0, b0 = 0;
  for (int64_t r = 0; r < R; r++) {
    int64_t nb = row_nb[r];
    if (nb == 0) continue;
    k += unpack_one(bfirst + b0, bcount + b0, bwidth + b0, boff + b0,
                    words + row_word_start[r], nb, out + k);
    b0 += nb;
  }
  return k;
}

}  // extern "C"
