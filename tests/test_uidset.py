"""uid-set algebra vs numpy ground truth.

Mirrors the reference's algo/uidlist_test.go (set-op correctness over random lists
of many sizes and overlap ratios, :289-343).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from dgraph_tpu.ops import uidset as us


def np_set(a):
    return us.to_numpy(a)


def random_sorted(rng, n, lo=0, hi=10_000):
    return np.unique(rng.integers(lo, hi, size=n))


@pytest.mark.parametrize("na,nb,hi", [(5, 5, 20), (100, 100, 300), (10, 1000, 5000),
                                      (1000, 10, 5000), (0, 50, 100), (500, 500, 600)])
def test_intersect_difference_merge(rng, na, nb, hi):
    a_np = random_sorted(rng, na, hi=hi) if na else np.array([], dtype=np.int64)
    b_np = random_sorted(rng, nb, hi=hi) if nb else np.array([], dtype=np.int64)
    a = us.make_set(a_np, capacity=max(na, 1) + 7)
    b = us.make_set(b_np, capacity=max(nb, 1) + 3)

    np.testing.assert_array_equal(np_set(us.intersect(a, b)), np.intersect1d(a_np, b_np))
    np.testing.assert_array_equal(np_set(us.compact(us.difference(a, b))),
                                  np.setdiff1d(a_np, b_np))
    np.testing.assert_array_equal(np_set(us.merge(a, b)), np.union1d(a_np, b_np))


def test_intersect_many(rng):
    lists = [random_sorted(rng, 200, hi=500) for _ in range(4)]
    cap = 256
    mat = jnp.stack([us.make_set(l, capacity=cap) for l in lists])
    want = lists[0]
    for l in lists[1:]:
        want = np.intersect1d(want, l)
    np.testing.assert_array_equal(np_set(us.intersect_many(mat)), want)
    # single row passes through
    one = us.intersect_many(mat[:1])
    np.testing.assert_array_equal(np_set(one), lists[0])


def test_merge_many(rng):
    lists = [random_sorted(rng, 50, hi=2000) for _ in range(6)]
    mat = jnp.stack([us.make_set(l, capacity=64) for l in lists])
    want = lists[0]
    for l in lists[1:]:
        want = np.union1d(want, l)
    np.testing.assert_array_equal(np_set(us.merge_many(mat)), want)


def test_apply_filter_and_paginate():
    a = us.make_set([2, 4, 6, 8, 10], capacity=8)
    mask = jnp.asarray([True, False, True, True, False, False, False, False])
    np.testing.assert_array_equal(np_set(us.compact(us.apply_filter(a, mask))), [2, 6, 8])

    np.testing.assert_array_equal(np_set(us.paginate(a, 1, 2)), [4, 6])
    np.testing.assert_array_equal(np_set(us.paginate(a, 0, -1)), [2, 4, 6, 8, 10])
    np.testing.assert_array_equal(np_set(us.paginate(a, 3, 100)), [8, 10])
    # negative offset counts from the end (x/x.go:191 PageRange)
    np.testing.assert_array_equal(np_set(us.paginate(a, -2, -1)), [8, 10])


def test_index_of_and_membership():
    a = us.make_set([5, 7, 11, 13], capacity=6)
    assert int(us.index_of(a, 11)) == 2
    assert int(us.index_of(a, 6)) == -1
    assert int(us.index_of(a, 13)) == 3
    mask = us.is_member(a, us.make_set([7, 13, 99], capacity=4))
    np.testing.assert_array_equal(np.asarray(mask)[:4], [False, True, False, True])


def test_size_and_resize():
    a = us.make_set([1, 2, 3], capacity=10)
    assert int(us.size(a)) == 3
    grown = us.resize(a, 16)
    assert grown.shape == (16,) and int(us.size(grown)) == 3
    shrunk = us.resize(a, 2)
    np.testing.assert_array_equal(np_set(shrunk), [1, 2])


def test_int64_requires_x64():
    # uid space is uint64 in the reference; int64 device sets need jax x64 mode,
    # otherwise the sentinel would silently truncate to -1 and become a "uid".
    import jax

    if jax.config.jax_enable_x64:
        a = us.make_set([1, 2, 3], capacity=4, dtype=jnp.int64)
        b = us.make_set([2, 3, 4], capacity=4, dtype=jnp.int64)
        np.testing.assert_array_equal(np_set(us.intersect(a, b)), [2, 3])
    else:
        with pytest.raises(ValueError, match="x64"):
            us.make_set([1, 2, 3], capacity=4, dtype=jnp.int64)


def test_intersect_output_is_valid_set():
    # regression: results must be compacted so downstream binary searches work
    c = us.intersect(us.make_set([1, 5, 9], capacity=3), us.make_set([5], capacity=1))
    assert bool(us.is_member(us.make_set([5], capacity=1), c)[0])
    d = us.difference(us.make_set([1, 5, 9], capacity=3), us.make_set([5], capacity=1))
    np.testing.assert_array_equal(np_set(d), [1, 9])
    assert int(us.index_of(us.make_set([1, 5], capacity=4), int(us.SENTINEL32))) == -1
