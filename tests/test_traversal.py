"""Device traversal kernels vs networkx-free host ground truth."""

import numpy as np
import jax.numpy as jnp
import pytest

from dgraph_tpu.ops import traversal, uidset as us


def make_graph(rng, n_nodes, n_edges, weighted=False):
    edges = {(int(a), int(b)) for a, b in rng.integers(0, n_nodes, size=(n_edges, 2))
             if a != b}
    edges = sorted(edges)
    subjects = sorted({a for a, _ in edges})
    sub_idx = {s: i for i, s in enumerate(subjects)}
    indptr = np.zeros(len(subjects) + 1, dtype=np.int32)
    for a, _ in edges:
        indptr[sub_idx[a] + 1] += 1
    np.cumsum(indptr, out=indptr)
    indices = np.asarray([b for _, b in edges], dtype=np.int32)
    w = None
    if weighted:
        w = rng.uniform(0.1, 5.0, size=len(edges)).astype(np.float32)
    return (np.asarray(subjects, dtype=np.int32), indptr, indices, w,
            {(a, b): i for i, (a, b) in enumerate(edges)})


def host_bfs(edges_map, seeds, hops):
    adj = {}
    for (a, b) in edges_map:
        adj.setdefault(a, []).append(b)
    visited = set(seeds)
    frontier = set(seeds)
    traversed = 0
    for _ in range(hops):
        nxt = set()
        for u in frontier:
            for v in adj.get(u, ()):
                traversed += 1
                if v not in visited:
                    nxt.add(v)
        visited |= nxt
        frontier = nxt
    return visited, frontier, traversed


def test_k_hop_vs_host(rng):
    subjects, indptr, indices, _, emap = make_graph(rng, 300, 1500)
    seeds_np = [0, 5, 17]
    seeds = us.make_set(seeds_np, capacity=8)
    res = traversal.k_hop(jnp.asarray(subjects), jnp.asarray(indptr),
                          jnp.asarray(indices), seeds,
                          hops=3, frontier_cap=4096, num_nodes=300)
    want_vis, want_frontier, want_trav = host_bfs(emap, seeds_np, 3)
    got_vis = set(np.nonzero(np.asarray(res.visited))[0].tolist())
    assert got_vis == want_vis
    np.testing.assert_array_equal(us.to_numpy(res.frontier), sorted(want_frontier))
    assert int(res.traversed) == want_trav


def test_k_hop_exhausts(rng):
    # a simple chain 0->1->2->3: after 10 hops frontier is empty
    subjects = np.asarray([0, 1, 2], dtype=np.int32)
    indptr = np.asarray([0, 1, 2, 3], dtype=np.int32)
    indices = np.asarray([1, 2, 3], dtype=np.int32)
    seeds = us.make_set([0], capacity=4)
    res = traversal.k_hop(jnp.asarray(subjects), jnp.asarray(indptr),
                          jnp.asarray(indices), seeds,
                          hops=10, frontier_cap=16, num_nodes=5)
    assert int(us.size(res.frontier)) == 0
    assert int(res.traversed) == 3
    np.testing.assert_array_equal(np.asarray(res.frontier_sizes)[:4], [1, 1, 1, 0])


def host_dijkstra(edges_map, w, src, n):
    import heapq

    adj = {}
    for (a, b), i in edges_map.items():
        adj.setdefault(a, []).append((b, float(w[i]) if w is not None else 1.0))
    dist = {src: 0.0}
    pq = [(0.0, src)]
    while pq:
        d, u = heapq.heappop(pq)
        if d > dist.get(u, np.inf):
            continue
        for v, c in adj.get(u, ()):
            if d + c < dist.get(v, np.inf):
                dist[v] = d + c
                heapq.heappush(pq, (d + c, v))
    out = np.full(n, np.inf, dtype=np.float32)
    for u, d in dist.items():
        out[u] = d
    return out


@pytest.mark.parametrize("weighted", [False, True])
def test_sssp_vs_dijkstra(rng, weighted):
    subjects, indptr, indices, w, emap = make_graph(rng, 200, 1000, weighted)
    res = traversal.sssp(jnp.asarray(subjects), jnp.asarray(indptr),
                         jnp.asarray(indices),
                         jnp.asarray(w) if w is not None else None,
                         jnp.int32(0), num_nodes=200, max_iters=64)
    want = host_dijkstra(emap, w, 0, 200)
    np.testing.assert_allclose(np.asarray(res.dist), want, rtol=1e-5)
    # parent consistency: dist[u] == dist[parent[u]] + w(parent[u] -> u)
    dist = np.asarray(res.dist)
    parent = np.asarray(res.parent)
    for u in range(200):
        p = parent[u]
        if p < 0:
            continue
        cost = float(w[emap[(int(p), u)]]) if w is not None else 1.0
        assert dist[u] == pytest.approx(dist[p] + cost, rel=1e-5)
