"""Differential aggregation property test (ISSUE 17 satellite): the
device segmented-reduce suite (ops/segments) against the reference typed
aggregator (query/aggregator.aggregate) across an int / float / datetime /
empty-group / missing-value grid; pins the f32-exactness crossover
(all-int values, |sum| < 2**24) that gates the device path in
query/groupby._batch_aggregates, and the NaN-for-empty contract."""

import datetime as dt
from types import SimpleNamespace

import numpy as np
import pytest

from dgraph_tpu.ops import segments as segs
from dgraph_tpu.query import groupby as gbmod
from dgraph_tpu.query.aggregator import aggregate
from dgraph_tpu.utils.types import TypeID, Val, to_device_scalar

OPS = ("sum", "min", "max", "avg", "count")


def _scenarios():
    rng = np.random.default_rng(24)
    out = []
    for kind in ("int_small", "int_edge", "float", "missing"):
        groups = []
        for g in range(9):
            k = int(rng.integers(0, 7))      # group 0.. may be empty
            vals = []
            for _ in range(k):
                if kind == "int_small":
                    vals.append(Val(TypeID.INT, int(rng.integers(-1000, 1000))))
                elif kind == "int_edge":
                    vals.append(Val(TypeID.INT, int(rng.integers(0, 1 << 20))))
                elif kind == "float":
                    vals.append(Val(TypeID.FLOAT,
                                    float(rng.normal()) * 10.0))
                else:   # missing: ~40% of members carry no value
                    vals.append(None if rng.random() < 0.4 else
                                Val(TypeID.INT, int(rng.integers(0, 100))))
            groups.append(vals)
        out.append((kind, groups))
    return out


def _device(op, groups):
    """groups of Val|None → fused_group_reduce over the NaN-coded flat
    vector, exactly as groupby._batch_aggregates feeds it."""
    lens = [len(g) for g in groups]
    flat = np.asarray([np.nan if v is None else float(to_device_scalar(v))
                       for g in groups for v in g], dtype=np.float64)
    return segs.fused_group_reduce((op,), flat, lens, len(groups))[op]


@pytest.mark.parametrize("op", OPS)
@pytest.mark.parametrize("kind,groups", _scenarios(),
                         ids=[k for k, _ in _scenarios()])
def test_device_matches_reference_aggregator(op, kind, groups):
    got = _device(op, groups)
    for g, vals in enumerate(groups):
        live = [v for v in vals if v is not None]
        if op == "count":
            assert got[g] == len(live)       # count: 0 for empty, exact
            continue
        ref = aggregate(op, vals)
        if ref is None:
            assert np.isnan(got[g]), "empty group must yield NaN"
            continue
        want = float(ref.value)
        all_int = all(v.tid == TypeID.INT for v in live)
        exact = all_int and sum(abs(float(v.value)) for v in live) < 2 ** 24
        if exact:
            assert got[g] == want, (op, kind, g)
        else:
            assert got[g] == pytest.approx(want, rel=1e-5, abs=1e-5)


def test_group_reduce_matches_fused_path():
    """The single-op host-segment-id entry agrees with the fused
    device-derived-segment-id entry bit-for-bit."""
    _kind, groups = _scenarios()[0]
    lens = np.asarray([len(g) for g in groups])
    seg_ids = np.repeat(np.arange(len(groups)), lens)
    flat = np.asarray([float(to_device_scalar(v)) for g in groups
                       for v in g])
    for op in OPS:
        a = np.asarray(segs.group_reduce(op, seg_ids, flat, len(groups)),
                       np.float64)
        b = np.asarray(_device(op, groups), np.float64)
        assert np.array_equal(a, b, equal_nan=True), op


def test_datetime_min_max_stays_on_reference_path():
    """min/max over datetimes returns the original Val (the device f32
    lattice can't); the epoch ordering still matches, so the device
    candidate — if it ever ran — would pick the same element."""
    vals = [Val(TypeID.DATETIME, dt.datetime(2020 + i, 3, 1 + 2 * i))
            for i in (3, 0, 5, 1)]
    ref = aggregate("min", vals)
    assert ref.tid == TypeID.DATETIME and ref.value.year == 2020
    epochs = [to_device_scalar(v) for v in vals]
    got = _device("min", [vals])
    assert float(got[0]) == pytest.approx(min(epochs))
    # groupby's execution gate: min/max over non-numeric tids skips the
    # device branch so the original Val survives in the response
    assert not ({v.tid for v in vals} <= {TypeID.INT, TypeID.FLOAT})


def test_f32_crossover_pin():
    """|sum| >= 2**24 is exactly where f32 accumulation starts dropping
    units — the gate in groupby._batch_aggregates must sit there."""
    assert gbmod._HOST_AGG_MAX == 1 << 17
    below = [Val(TypeID.INT, (1 << 24) - 2), Val(TypeID.INT, 1)]
    above = [Val(TypeID.INT, 1 << 24), Val(TypeID.INT, 1)]
    assert float(np.float32((1 << 24) - 2) + np.float32(1)) == \
        float((1 << 24) - 1)
    assert float(np.float32(1 << 24) + np.float32(1)) != (1 << 24) + 1
    s_below = sum(abs(float(v.value)) for v in below)
    s_above = sum(abs(float(v.value)) for v in above)
    assert s_below < 2 ** 24 <= s_above
    # the fused device path itself is exact right up to the boundary
    assert _device("sum", [below])[0] == (1 << 24) - 1


def _fake_ex(vals_by_uid, metrics=None):
    vv = SimpleNamespace(vals=vals_by_uid)
    return SimpleNamespace(vars={"x": vv},
                           snap=SimpleNamespace(metrics=metrics))


def _agg_child(op):
    return SimpleNamespace(attr=f"__agg_{op}", val_ref="x", alias=None,
                           is_uid_node=False, is_count=False)


class _Counter:
    def __init__(self):
        self.n = {}

    def counter(self, name):
        c = self.n.setdefault(name, SimpleNamespace(v=0))
        return SimpleNamespace(inc=lambda k=1, c=c: setattr(c, "v", c.v + k))


def test_batch_aggregates_routes_device_vs_host(monkeypatch):
    """Below the crossover (and past the size floor) the device reduce
    answers; at/above it, or for float values, the f64 host lattice
    does — observable via the device/host reduce counters."""
    monkeypatch.setattr(gbmod, "_HOST_AGG_MAX", 0)
    members = [np.asarray([1, 2], np.int64), np.asarray([3], np.int64)]

    m = _Counter()
    ex = _fake_ex({1: Val(TypeID.INT, 5), 2: Val(TypeID.INT, 7),
                   3: Val(TypeID.INT, 11)}, metrics=m)
    child = _agg_child("sum")
    out = gbmod._batch_aggregates(ex, [child], members)
    rows = out[id(child)]
    assert rows[0] == {"sum(val(x))": 12} and rows[1] == {"sum(val(x))": 11}
    assert m.n["dgraph_agg_device_reduces_total"].v == 1

    m2 = _Counter()
    ex2 = _fake_ex({1: Val(TypeID.INT, 1 << 24), 2: Val(TypeID.INT, 1),
                    3: Val(TypeID.INT, 2)}, metrics=m2)
    child2 = _agg_child("sum")
    out2 = gbmod._batch_aggregates(ex2, [child2], members)
    assert out2[id(child2)][0] == {"sum(val(x))": (1 << 24) + 1}
    assert "dgraph_agg_device_reduces_total" not in m2.n
    assert m2.n["dgraph_agg_host_reduces_total"].v == 1


def test_batch_aggregates_empty_group_omits_row(monkeypatch):
    """NaN-for-empty surfaces as an empty row dict — the aggregate key is
    absent, matching the reference's 'aggregate of nothing is absent'."""
    monkeypatch.setattr(gbmod, "_HOST_AGG_MAX", 0)
    members = [np.asarray([1], np.int64), np.asarray([9], np.int64)]
    ex = _fake_ex({1: Val(TypeID.FLOAT, 2.5)})   # uid 9 carries no value
    for op in ("sum", "min", "max", "avg"):
        child = _agg_child(op)
        rows = gbmod._batch_aggregates(ex, [child], members)[id(child)]
        assert rows[1] == {}, op
        assert list(rows[0].values()) == [2.5], op
