"""Replication contract: quorum WAL shipping, leader failover, catch-up.

Round-2 verdict item 8: 3-node in-process cluster — write, kill leader,
fail over, read: the bank invariant holds and nothing committed is lost.
Reference: worker/draft.go:190/:485-624, conn/node.go:47-105,
raftwal/wal.go:31, retrieveSnapshot :452; the bank hammer mirrors
contrib/integration/bank.
"""

import numpy as np
import pytest

from dgraph_tpu.coord.replication import NoQuorum, ReplicaGroup, StaleLeader
from dgraph_tpu.coord.zero import TxnConflict

N_ACCOUNTS = 8
START = 100


def _seed_bank(node):
    node.alter(schema_text="bal: int .\nacct: string @index(exact) .")
    quads = [f'<0x{i:x}> <acct> "a{i}" .\n<0x{i:x}> <bal> "{START}"^^<xs:int> .'
             for i in range(1, N_ACCOUNTS + 1)]
    node.mutate(set_nquads="\n".join(quads), commit_now=True)


def _balances(node) -> dict[int, int]:
    out, _ = node.query('{ q(func: has(acct)) { uid bal } }')
    return {int(r["uid"], 16): r["bal"] for r in out.get("q", [])}


def _transfer(node, rng) -> bool:
    a, b = rng.choice(np.arange(1, N_ACCOUNTS + 1), size=2, replace=False)
    ctx = node.new_txn()
    try:
        bals = _balances(node)
        amt = int(rng.integers(1, 20))
        node.mutate(
            set_nquads=f'<0x{a:x}> <bal> "{bals[int(a)] - amt}"^^<xs:int> .\n'
                       f'<0x{b:x}> <bal> "{bals[int(b)] + amt}"^^<xs:int> .',
            start_ts=ctx.start_ts)
        node.commit(ctx.start_ts)
        return True
    except TxnConflict:
        return False


def test_kill_leader_loses_nothing(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    _seed_bank(g.node)
    rng = np.random.default_rng(3)
    for _ in range(25):
        _transfer(g.node, rng)
    before = _balances(g.node)
    assert sum(before.values()) == N_ACCOUNTS * START

    old_leader = g.leader_id
    g.kill(old_leader)                       # crash the primary
    assert g.leader_id != old_leader
    after = _balances(g.node)
    assert after == before, "committed state lost in failover"

    # the promoted leader keeps serving writes (quorum 2/3 still alive)
    for _ in range(10):
        _transfer(g.node, rng)
    assert sum(_balances(g.node).values()) == N_ACCOUNTS * START
    g.close()


def test_second_leader_loss_breaks_quorum(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    _seed_bank(g.node)
    g.kill(g.leader_id)
    with pytest.raises(NoQuorum):
        g.kill(g.leader_id)                  # 1 live member < quorum 2


def test_follower_loss_then_writes_then_rejoin(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    _seed_bank(g.node)
    rng = np.random.default_rng(9)
    dead = next(m.id for m in g.members if m.id != g.leader_id)
    g.kill(dead)                             # follower down: 2/3 still quorum
    for _ in range(10):
        _transfer(g.node, rng)
    snapshot_bals = _balances(g.node)

    g.rejoin(dead)                           # snapshot + tail catch-up
    # fail over onto the rejoined member's cohort: kill the leader, the
    # promoted member must carry everything incl. post-outage commits
    g.kill(g.leader_id)
    assert _balances(g.node) == snapshot_bals
    assert sum(_balances(g.node).values()) == N_ACCOUNTS * START
    g.close()


def test_no_quorum_blocks_commits(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    _seed_bank(g.node)
    for m in g.members:
        if m.id != g.leader_id:
            g.kill(m.id)                     # both followers down
    with pytest.raises(NoQuorum):
        g.node.mutate(set_nquads='<0x1> <bal> "1"^^<xs:int> .',
                      commit_now=True)
    g.close()


def test_stale_leader_fenced(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=3)
    _seed_bank(g.node)
    # a member observes a newer term (as if another leader was elected)
    for m in g.members:
        if m.id != g.leader_id:
            m.set_term(g.term + 1)
            break
    with pytest.raises(StaleLeader):
        g.node.mutate(set_nquads='<0x1> <bal> "0"^^<xs:int> .',
                      commit_now=True)
    g.close()


def test_single_replica_degenerate(tmp_path):
    g = ReplicaGroup(str(tmp_path), n=1)
    _seed_bank(g.node)
    assert sum(_balances(g.node).values()) == N_ACCOUNTS * START
    g.close()


# -- hedged reads (worker/task.go:75-132 backup requests) --------------------

def _mk_read_group(tmp_path, n=3):
    from dgraph_tpu.coord.replication import ReplicaGroup
    g = ReplicaGroup(str(tmp_path / "grp"), n=n, serve_reads=True)
    g.node.alter(schema_text="name: string @index(exact) .\nbal: int .")
    g.node.mutate(set_nquads='_:a <name> "hedge" .\n_:a <bal> "10" .',
                  commit_now=True)
    return g


def test_fast_leader_serves_read(tmp_path):
    g = _mk_read_group(tmp_path)
    g.node.query('{ q(func: eq(name, "hedge")) { bal } }')  # warm the snapshot
    src, out = g.read('{ q(func: eq(name, "hedge")) { bal } }', hedge_after=5)
    assert src == "leader" and out["q"][0]["bal"] == 10
    assert g.hedged_reads == 0
    g.close()


def test_slow_leader_hedges_to_follower(tmp_path):
    import time as _time
    g = _mk_read_group(tmp_path)
    real_query = g.node.query

    def slow_query(*a, **kw):
        _time.sleep(0.5)
        return real_query(*a, **kw)

    g.node.query = slow_query
    t0 = _time.perf_counter()
    src, out = g.read('{ q(func: eq(name, "hedge")) { bal } }',
                      hedge_after=0.02)
    dt = _time.perf_counter() - t0
    assert src.startswith("follower")
    assert out["q"][0]["bal"] == 10       # quorum-acked data is visible
    assert dt < 0.45                      # did not wait for the slow leader
    assert g.hedged_reads == 1
    g.close()


def test_dead_leader_read_from_follower(tmp_path):
    g = _mk_read_group(tmp_path)
    # mark dead WITHOUT failover (the window before election completes)
    g.members[g.leader_id].alive = False
    src, out = g.read('{ q(func: eq(name, "hedge")) { bal } }')
    assert src.startswith("follower")
    assert out["q"][0]["bal"] == 10
    g.close()


def test_follower_reader_tracks_new_commits(tmp_path):
    g = _mk_read_group(tmp_path)
    g.node.mutate(set_nquads='_:b <name> "late" .', commit_now=True)
    fid = next(m.id for m in g._followers() if m.reader is not None)
    out = g.members[fid].reader.query('{ q(func: eq(name, "late")) { name } }')
    assert out == {"q": [{"name": "late"}]}
    g.close()


def test_rejoined_member_reader_reseeds(tmp_path):
    g = _mk_read_group(tmp_path)
    victim = next(m.id for m in g._followers())
    g.kill(victim)
    g.node.mutate(set_nquads='_:c <name> "while-dead" .', commit_now=True)
    g.rejoin(victim)
    out = g.members[victim].reader.query(
        '{ q(func: eq(name, "while-dead")) { name } }')
    assert out == {"q": [{"name": "while-dead"}]}
    g.close()


def test_read_raises_when_nothing_can_serve(tmp_path):
    from dgraph_tpu.coord.replication import NoQuorum, ReplicaGroup
    g = ReplicaGroup(str(tmp_path / "g2"), n=3)   # serve_reads=False
    g.members[g.leader_id].alive = False
    with pytest.raises(NoQuorum):
        g.read("{ q(func: has(name)) { name } }")
    g.close()


def test_follower_sees_shipped_predicate_drop(tmp_path):
    g = _mk_read_group(tmp_path)
    fid = next(m.id for m in g._followers() if m.reader is not None)
    rd = g.members[fid].reader
    assert rd.query('{ q(func: has(name)) { name } }')["q"]
    g.node.store.delete_predicate("name")   # ships a "dp" record
    assert rd.query('{ q(func: has(name)) { name } }') == {}
    g.close()
