"""Batched multi-query device execution (ISSUE 9, query/batch.py).

Covers: byte-identity of batched vs solo execution across the golden
corpus under forced batching, the dedup-vs-batch split with the
singleflight tier, deadline-constrained window bypass, de-multiplex under
a mid-batch per-task failure, metrics/span surfaces, and the gate's
per-kernel-class EWMA shed decisions.
"""

import threading
import time

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import recurse as recmod
from dgraph_tpu.query import task as taskmod
from dgraph_tpu.query.batch import DeviceBatcher, classify, kernel_klass
from dgraph_tpu.query.qcache import DispatchGate
from dgraph_tpu.query.task import TaskQuery
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils.deadline import DeadlineExceeded, ResourceExhausted


@pytest.fixture
def device_expand(monkeypatch):
    """Tiny test graphs never cross the real 64k host/device cutover —
    force every expand into the device class so it classifies batchable."""
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 0)


def _graph_node(**kw) -> Node:
    kw.setdefault("planner", False)     # keep the static cutover in charge
    kw.setdefault("task_cache_mb", 0)
    kw.setdefault("result_cache_mb", 0)
    node = Node(**kw)
    node.alter(schema_text="name: string @index(exact) .\n"
                           "follows: [uid] .")
    quads = []
    for i in range(1, 160):
        quads.append(f'<0x{i:x}> <name> "p{i}" .')
        for j in range(1, 6):
            quads.append(f'<0x{i:x}> <follows> <0x{(i * j) % 159 + 1:x}> .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    return node


def _force_batcher(node, max_batch=8, window_ms=1500) -> DeviceBatcher:
    """Deterministic batching: no idle fire + a window long enough that a
    barrier-released wave always lands in one batch (the batch fires early
    the moment it fills to max_batch)."""
    node.batcher = DeviceBatcher(node.dispatch_gate, node.metrics,
                                 window_ms=window_ms, max_batch=max_batch,
                                 idle_fire=False)
    return node.batcher


def _concurrent(node, queries, timeout=60):
    outs = [None] * len(queries)
    errs = [None] * len(queries)
    barrier = threading.Barrier(len(queries))

    def run(i):
        barrier.wait(timeout=30)
        try:
            outs[i] = node.query(queries[i])[0]
        except BaseException as e:     # noqa: BLE001 — surfaced to assert
            errs[i] = e

    ts = [threading.Thread(target=run, args=(i,))
          for i in range(len(queries))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    return outs, errs


# ---------------------------------------------------------------------------
# byte-identity
# ---------------------------------------------------------------------------

def test_concurrent_distinct_queries_batch_byte_identical(device_expand):
    node = _graph_node()
    queries = [f'{{ q(func: uid(0x{3 * i + 1:x}, 0x{3 * i + 2:x}, '
               f'0x{3 * i + 3:x})) {{ follows {{ uid }} }} }}'
               for i in range(8)]
    node.batcher = None
    solo = [node.query(q)[0] for q in queries]
    _force_batcher(node, max_batch=8)
    outs, errs = _concurrent(node, queries)
    assert not any(errs), errs
    assert outs == solo
    m = node.metrics
    assert m.counter("dgraph_batch_tasks_total").value == 8
    occ = m.histogram("dgraph_batch_occupancy").snapshot()
    assert occ["max"] > 1, occ
    node.close()


def test_golden_corpus_byte_identical_under_forced_batching(device_expand):
    """The full golden battery, replayed in concurrent waves with batching
    forced (long window, no idle fire): every output must equal the solo
    run byte for byte — filters, facets, reverse edges, pagination, lang,
    cascade, recurse, shortest, groupby, vars, geo all demux correctly."""
    import test_golden as tg

    node = Node(planner=False, task_cache_mb=0, result_cache_mb=0)
    node.alter(schema_text=tg.SCHEMA)
    node.mutate(set_nquads=tg._dataset(), commit_now=True)
    queries = [q for _name, q in tg.QUERIES]
    node.batcher = None
    solo = [node.query(q)[0] for q in queries]
    _force_batcher(node, max_batch=8, window_ms=150)
    outs = []
    for lo in range(0, len(queries), 8):          # concurrent waves
        wave = queries[lo: lo + 8]
        got, errs = _concurrent(node, wave)
        assert not any(errs), errs
        outs.extend(got)
    assert outs == solo
    assert node.metrics.counter("dgraph_batch_formed_total").value > 0
    node.close()


def test_recurse_fused_batches_byte_identical(device_expand, monkeypatch):
    monkeypatch.setattr(recmod, "KERNEL_MIN_EDGES", 0)
    node = _graph_node()
    queries = [f'{{ q(func: uid(0x{i + 1:x})) @recurse(depth: 3) '
               '{ follows } }' for i in range(4)]
    node.batcher = None
    solo = [node.query(q)[0] for q in queries]
    _force_batcher(node, max_batch=4)
    outs, errs = _concurrent(node, queries)
    assert not any(errs), errs
    assert outs == solo
    occ = node.metrics.histogram("dgraph_batch_occupancy").snapshot()
    assert occ["max"] == 4, occ     # one multi-source dispatch took all 4
    node.close()


def test_vector_topk_batches_byte_identical(monkeypatch):
    from dgraph_tpu.storage import vecindex as vecmod

    monkeypatch.setattr(vecmod, "HOST_SCAN_MAX", 1)  # device-class scans
    node = Node(planner=False, task_cache_mb=0, result_cache_mb=0)
    node.alter(schema_text="emb: float32vector @index(vector(dim: 8)) .")
    rng = np.random.default_rng(7)
    quads = []
    for i in range(1, 80):
        v = rng.normal(size=8).round(3).tolist()
        quads.append(f'<0x{i:x}> <emb> "{v}"^^<xs:float32vector> .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    queries = []
    for _ in range(4):
        v = rng.normal(size=8).round(3).tolist()
        queries.append('{ q(func: similar_to(emb, "%s", 5)) { uid } }' % v)
    node.batcher = None
    solo = [node.query(q)[0] for q in queries]
    _force_batcher(node, max_batch=4)
    outs, errs = _concurrent(node, queries)
    assert not any(errs), errs
    assert outs == solo
    occ = node.metrics.histogram("dgraph_batch_occupancy").snapshot()
    assert occ["max"] == 4, occ
    node.close()


# ---------------------------------------------------------------------------
# composition with singleflight
# ---------------------------------------------------------------------------

def test_singleflight_dedupes_identical_batcher_packs_distinct(device_expand):
    """Two IDENTICAL queries coalesce in the task cache's singleflight
    (one underlying dispatch); a third DISTINCT one packs with the flight
    leader into a 2-task batch — dedup and batching compose, they don't
    compete."""
    node = _graph_node(task_cache_mb=16)    # singleflight tier ON
    same = '{ q(func: uid(0x1, 0x2)) { follows { uid } } }'
    diff = '{ q(func: uid(0x5, 0x6)) { follows { uid } } }'
    node.batcher = None
    want_same = node.query(same)[0]
    want_diff = node.query(diff)[0]
    node.task_cache.clear()
    _force_batcher(node, max_batch=2)
    outs, errs = _concurrent(node, [same, same, diff])
    assert not any(errs), errs
    assert outs == [want_same, want_same, want_diff]
    m = node.metrics
    assert m.counter("dgraph_task_cache_inflight_waits_total").value >= 1
    # exactly one batch of the two DISTINCT tasks — the coalesced follower
    # never reached the batcher
    assert m.counter("dgraph_batch_tasks_total").value == 2
    occ = m.histogram("dgraph_batch_occupancy").snapshot()
    assert occ["max"] == 2, occ
    node.close()


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_constrained_task_bypasses_window(device_expand):
    """A task whose remaining budget cannot cover window + expected step
    dispatches solo immediately instead of waiting out the window."""
    node = _graph_node()
    batcher = DeviceBatcher(node.dispatch_gate, node.metrics,
                            window_ms=500, max_batch=8, idle_fire=False)
    snap = node.snapshot()
    q = TaskQuery("follows", frontier=np.asarray([1, 2], dtype=np.int64))
    ran = []

    def solo(tq, klass=None):
        ran.append(tq)
        return taskmod.process_task(snap, tq, node.store.schema)

    t0 = time.monotonic()
    with dl.scope(0.05):
        res = batcher.dispatch(snap, node.store.schema, q, solo)
    assert time.monotonic() - t0 < 0.4          # never waited the window
    assert ran, "bypass must run the solo path"
    assert len(res.uid_matrix) == 2
    assert node.metrics.counter(
        "dgraph_batch_deadline_bypass_total").value == 1
    node.close()


def test_batch_runs_under_most_permissive_member_deadline():
    """A multi-entry batch acts for SEVERAL callers: the kernel must run
    under the most permissive member's budget (unbudgeted if any member
    is), not whichever member happened to lead — a tight-budget leader's
    context must not shed work the other members had ample time for."""
    from dgraph_tpu.utils.metrics import Registry

    seen = []

    def runner(entries):
        seen.append(dl.remaining())
        for e in entries:
            e.result = "ok"

    def pair(budget_a, budget_b):
        b = DeviceBatcher(None, Registry(), window_ms=2000, max_batch=2,
                          idle_fire=False)
        outs = {}
        barrier = threading.Barrier(2)

        def run(name, budget):
            barrier.wait(timeout=10)
            with dl.scope(budget):
                outs[name] = b._submit(("k",), "expand", None, runner,
                                       solo=lambda: "solo")

        ts = [threading.Thread(target=run, args=("a", budget_a)),
              threading.Thread(target=run, args=("b", budget_b))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert outs == {"a": "ok", "b": "ok"}

    pair(5.0, None)                 # one unbudgeted member: batch unbudgeted
    assert seen.pop() is None
    pair(0.5, 30.0)                 # else: the max remaining across members
    assert seen.pop() > 10.0


def test_host_fallbacks_feed_host_ewma_class_not_expand():
    """Host-path/value-pred solo fallbacks must record into the gate's
    "host" EWMA class: sub-ms host gathers polluting the device "expand"
    estimate is the two-tail misestimation the per-class split fixes."""
    node = _graph_node()            # default cutover: all host-class
    _force_batcher(node, max_batch=4, window_ms=10)
    node.query('{ q(func: uid(0x1, 0x2)) { name follows { uid } } }')
    g = node.dispatch_gate
    assert "host" in g._class_ewma, g._class_ewma
    assert "expand" not in g._class_ewma, g._class_ewma
    node.close()


# ---------------------------------------------------------------------------
# mid-batch per-task failure
# ---------------------------------------------------------------------------

def test_poisoned_task_fails_typed_rest_of_batch_succeeds(device_expand):
    """One member's host tail raises (bad uid_in literal); the other
    members' results are unaffected and identical to solo execution."""
    node = _graph_node()
    batcher = DeviceBatcher(node.dispatch_gate, node.metrics,
                            window_ms=2000, max_batch=2, idle_fire=False)
    snap = node.snapshot()
    schema = node.store.schema
    good = TaskQuery("follows", frontier=np.asarray([1, 2], dtype=np.int64))
    bad = TaskQuery("follows", frontier=np.asarray([3, 4], dtype=np.int64),
                    func=("uid_in", ["not-a-uid"]))
    with pytest.raises(ValueError):            # the error solo would raise
        taskmod.process_task(snap, bad, schema)
    want = taskmod.process_task(snap, good, schema)

    results, errors = {}, {}
    barrier = threading.Barrier(2)

    def run(name, q):
        barrier.wait(timeout=10)
        try:
            results[name] = batcher.dispatch(
                snap, schema, q,
                lambda tq, klass=None: taskmod.process_task(
                    snap, tq, schema))
        except BaseException as e:             # noqa: BLE001
            errors[name] = e

    ts = [threading.Thread(target=run, args=("good", good)),
          threading.Thread(target=run, args=("bad", bad))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert isinstance(errors.get("bad"), ValueError)
    got = results["good"]
    assert [m.tolist() for m in got.uid_matrix] == \
        [m.tolist() for m in want.uid_matrix]
    assert got.dest_uids.tolist() == want.dest_uids.tolist()
    occ = node.metrics.histogram("dgraph_batch_occupancy").snapshot()
    assert occ["max"] == 2, occ                # they DID share one batch
    node.close()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_batched_kernel_span_carries_batch_size(device_expand):
    node = _graph_node(span_sample=1.0)
    queries = [f'{{ q(func: uid(0x{2 * i + 1:x}, 0x{2 * i + 2:x})) '
               '{ follows { uid } } }' for i in range(3)]
    _force_batcher(node, max_batch=3)
    _outs, errs = _concurrent(node, queries)
    assert not any(errs), errs
    spans = [s for r in node.tracer.sink.index()
             for s in node.tracer.sink.get(r["trace_id"])["spans"]]
    kernels = [s for s in spans if s["name"] == "device_kernel"
               and s["attrs"].get("kernel") == "batch.expand"]
    assert kernels, "no batched device_kernel span"
    assert any(k["attrs"].get("batch", 0) >= 2 for k in kernels), \
        [k["attrs"] for k in kernels]
    # every member's own trace records it was batched, with the size
    joins = [e for s in spans
             for e in s.get("events", ())
             if e["name"] == "batched" and e["attrs"].get("size", 0) >= 2]
    assert joins, "no batched events on member traces"
    node.close()


def test_batch_metrics_on_prometheus_surface(device_expand):
    from dgraph_tpu.obs import prom

    node = _graph_node()
    _force_batcher(node, max_batch=2)
    _outs, errs = _concurrent(
        node, ['{ q(func: uid(0x1, 0x2)) { follows { uid } } }',
               '{ q(func: uid(0x3, 0x4)) { follows { uid } } }'])
    assert not any(errs), errs
    parsed = prom.parse(prom.render(node.metrics))
    for name in ("dgraph_batch_formed_total", "dgraph_batch_tasks_total",
                 "dgraph_batch_window_waits_total"):
        assert name in parsed, f"{name} missing from /metrics"
    node.close()


def test_classification_reasons_counted():
    node = _graph_node()      # default cutover: everything is host-class
    _force_batcher(node, max_batch=4, window_ms=10)
    node.query('{ q(func: uid(0x1, 0x2)) { name follows { uid } } }')
    reasons = node.metrics.keyed("dgraph_batch_incompatible").snapshot()
    assert reasons.get("host_path", 0) >= 1, reasons    # small expand
    assert reasons.get("value_pred", 0) >= 1, reasons   # name fetch
    assert node.metrics.counter("dgraph_batch_formed_total").value == 0
    node.close()


# ---------------------------------------------------------------------------
# gate: per-kernel-class EWMA
# ---------------------------------------------------------------------------

def test_gate_keeps_per_class_step_estimates():
    g = DispatchGate(2)
    g.run(lambda: time.sleep(0.05), klass="vector")
    g.run(lambda: None, klass="expand")
    assert g.expected_step("vector") >= 0.05
    assert g.expected_step("expand") < g.expected_step("vector")
    # unseen classes fall back to the global EWMA
    assert g.expected_step("mesh") == g.expected_step_s
    assert g.expected_step() == g.expected_step_s


def test_gate_shed_uses_class_estimate_not_global():
    """One global EWMA spans ~1ms expands and ~100ms vector steps: with
    the global poisoned high, a cheap-class acquire must NOT shed — the
    shed decision reads the caller's class estimate."""
    g = DispatchGate(1)
    g._step_ewma = 5.0                 # poisoned global: sheds everything
    g._class_ewma["vector"] = 5.0
    g._class_ewma["expand"] = 0.001
    ev = threading.Event()
    t = threading.Thread(target=lambda: g.run(lambda: ev.wait(2.0)))
    t.start()
    time.sleep(0.05)
    try:
        with dl.scope(0.2):
            with pytest.raises(ResourceExhausted):
                g.run(lambda: 1, klass="vector")     # 5s est > 0.2s budget
        with dl.scope(0.2):
            # expand's 1ms estimate fits the budget: it queues (and times
            # out as DeadlineExceeded since the slot stays held) instead
            # of being shed up front
            with pytest.raises(DeadlineExceeded):
                g.run(lambda: 1, klass="expand")
    finally:
        ev.set()
        t.join()


def test_kernel_klass_labels():
    assert kernel_klass(TaskQuery("follows",
                                  frontier=np.zeros(1, np.int64))) == \
        "expand"
    assert kernel_klass(TaskQuery("emb",
                                  func=("similar_to", ["[1]", 1]))) == \
        "vector"
    assert kernel_klass(TaskQuery("name", func=("eq", ["x"]))) == "root"


def test_classify_rejects_unbatchable_shapes(device_expand):
    node = _graph_node()
    snap = node.snapshot()
    schema = node.store.schema
    # value predicate
    key, reason, _ = classify(snap, schema,
                              TaskQuery("name",
                                        frontier=np.asarray([1, 2])))
    assert key is None and reason == "value_pred"
    # root function
    key, reason, _ = classify(snap, schema,
                              TaskQuery("name", func=("eq", ["p1"])))
    assert key is None and reason == "root_func"
    # device-class expand classifies, key pinned to the CSR object
    key, kind, work = classify(
        snap, schema, TaskQuery("follows",
                                frontier=np.asarray([1, 2], np.int64)))
    assert kind == "expand" and key[1] == id(work.csr)
    # a commit stamps a delta overlay on the tablet: overlay tablets serve
    # on the solo merge-on-read path until compaction folds a fresh base
    node.mutate(set_nquads="<0x1> <follows> <0x9> .", commit_now=True)
    snap2 = node.snapshot()
    key2, reason2, _ = classify(
        snap2, schema, TaskQuery("follows",
                                 frontier=np.asarray([1, 2], np.int64)))
    assert key2 is None and reason2 == "overlay"
    # compaction re-folds the base: batching resumes under a NEW key
    node._assembler.compact(node._lock, force=True)
    snap3 = node.snapshot()
    key3, kind3, work3 = classify(
        snap3, schema, TaskQuery("follows",
                                 frontier=np.asarray([1, 2], np.int64)))
    assert kind3 == "expand" and key3 != key and work3.csr is not work.csr
    node.close()
