"""Equality tests for the Pallas pull-BFS kernel (interpret mode on CPU).

The kernel (ops/pallas_bfs.py) is the TPU-native replacement for the
reference's bp128-unpack + per-uid posting iteration hot loop
(worker/task.go:476-602). These tests pin its semantics to a plain host
BFS across the shape edge cases the kernel's blocking scheme creates:
sparse<->dense frontier switch at FRONTIER_CAP, bitmap chunk boundaries
(num_nodes = 32768 +/- 1), edge streams not divisible by EDGE_BLOCK,
multi-chunk bitmaps, and empty frontiers.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from dgraph_tpu.models.rmat import rmat_csr
from dgraph_tpu.ops import pallas_bfs as pb


def host_k_hop(subjects, indptr, indices, seed_uids, num_nodes, hops):
    """Reference host BFS: visited mask + traversed out-edge count per hop."""
    adj = {int(s): indices[indptr[i]:indptr[i + 1]]
           for i, s in enumerate(subjects)}
    visited = np.zeros(num_nodes, dtype=bool)
    visited[seed_uids] = True
    frontier = np.unique(np.asarray(seed_uids, dtype=np.int64))
    traversed = 0
    for _ in range(hops):
        dests = [adj[int(u)] for u in frontier if int(u) in adj]
        total = sum(len(d) for d in dests)
        traversed += total
        if total == 0:
            frontier = np.zeros(0, dtype=np.int64)
            continue
        dest = np.unique(np.concatenate(dests))
        fresh = dest[~visited[dest]]
        visited[fresh] = True
        frontier = fresh
    return visited, traversed


def run_both(subjects, indptr, indices, seed_uids, num_nodes, hops):
    g = pb.prep_pull(subjects, indptr, indices, num_nodes)
    seeds_mask = jnp.zeros(num_nodes, dtype=bool)
    if len(seed_uids):
        seeds_mask = seeds_mask.at[jnp.asarray(np.asarray(seed_uids))].set(True)
    res = pb.k_hop_pull_pallas(g, seeds_mask, hops=hops)
    h_visited, h_traversed = host_k_hop(
        subjects, indptr, indices, seed_uids, num_nodes, hops)
    np.testing.assert_array_equal(np.asarray(res.visited), h_visited)
    assert int(res.traversed) == h_traversed
    # push fast path (explicit seed list) must agree with the mask-only run
    res_p = pb.k_hop_pull_pallas(
        g, seeds_mask, hops=hops,
        seed_uids=np.asarray(seed_uids, dtype=np.int64))
    np.testing.assert_array_equal(np.asarray(res_p.visited), h_visited)
    assert int(res_p.traversed) == h_traversed
    return res


def random_csr(rng, num_nodes, num_edges):
    src = rng.integers(0, num_nodes, size=num_edges)
    dst = rng.integers(0, num_nodes, size=num_edges)
    keep = np.unique(np.stack([src, dst], axis=1), axis=0)
    src, dst = keep[:, 0], keep[:, 1]
    order = np.lexsort((dst, src))
    src, dst = src[order], dst[order]
    subjects, counts = np.unique(src, return_counts=True)
    indptr = np.zeros(len(subjects) + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return subjects.astype(np.int64), indptr, dst.astype(np.int64)


def test_rmat_multi_hop_matches_host(rng):
    subjects, indptr, indices = rmat_csr(12, 8, seed=5)
    num_nodes = int(max(subjects.max(), indices.max())) + 2
    seeds = np.unique(rng.choice(subjects, size=16, replace=False))
    run_both(subjects, indptr, indices, seeds, num_nodes, hops=3)


def test_empty_frontier():
    subjects, indptr, indices = rmat_csr(8, 4, seed=1)
    num_nodes = int(max(subjects.max(), indices.max())) + 2
    res = run_both(subjects, indptr, indices, np.zeros(0, np.int64),
                   num_nodes, hops=2)
    assert int(res.traversed) == 0
    assert not np.asarray(res.visited).any()


def test_frontier_with_no_out_edges():
    # seed uid exists but has no row in the CSR
    subjects = np.array([1, 2], dtype=np.int64)
    indptr = np.array([0, 1, 2], dtype=np.int64)
    indices = np.array([5, 6], dtype=np.int64)
    run_both(subjects, indptr, indices, np.array([40]), 64, hops=2)


@pytest.mark.parametrize("delta", [-1, 0, 1])
def test_chunk_boundary_num_nodes(rng, delta):
    """num_nodes at 32768 +/- 1: the single/multi-chunk switch and the
    pad-node-outside-uid-space rule (prep_pull adds a chunk when the uid
    space exactly fills the bitmap)."""
    num_nodes = pb.NODES_PER_CHUNK + delta
    subjects, indptr, indices = random_csr(rng, num_nodes, 6000)
    # force edges touching the top of the uid space
    hi = num_nodes - 1
    subjects_l = list(subjects)
    if hi not in subjects_l:
        subjects = np.append(subjects, hi)
        indptr = np.append(indptr, indptr[-1] + 1)
        indices = np.append(indices, 0)
    seeds = np.array([int(subjects[0]), hi], dtype=np.int64)
    run_both(subjects, indptr, indices, seeds, num_nodes, hops=3)


def test_multi_chunk_bitmap(rng):
    """3+ bitmap chunks with edges crossing chunk boundaries. The chunk
    space is SOURCE-RANK-compressed, so >= 2*NODES_PER_CHUNK distinct
    sources are needed to exercise the multi-chunk path."""
    num_nodes = pb.NODES_PER_CHUNK * 2 + 123
    n_edges = pb.NODES_PER_CHUNK * 2 + 40000
    # every node appears as a source at least once -> Ns == num_nodes
    src = np.concatenate([np.arange(num_nodes),
                          rng.integers(0, num_nodes,
                                       size=n_edges - num_nodes)])
    # half the edges deliberately cross into a different chunk
    dst = (src + pb.NODES_PER_CHUNK + rng.integers(0, 100, size=n_edges)) % num_nodes
    order = np.lexsort((dst, src))
    src, dst = src[order], dst[order]
    subjects, counts = np.unique(src, return_counts=True)
    indptr = np.zeros(len(subjects) + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    seeds = np.unique(rng.choice(subjects, size=8))
    res = run_both(subjects, indptr, dst, seeds, num_nodes, hops=3)
    g = pb.prep_pull(subjects, indptr, dst, num_nodes)
    assert g.chunks >= 3
    assert int(res.traversed) > 0


@pytest.mark.parametrize("extra", [0, 1, 7])
def test_edge_count_not_block_aligned(rng, extra):
    """E % EDGE_BLOCK != 0 (and E < EDGE_BLOCK): padding edges must never
    count as active or mark nodes."""
    num_nodes = 2048
    num_edges = pb.EDGE_BLOCK + extra if extra else 300
    subjects, indptr, indices = random_csr(rng, num_nodes, num_edges)
    seeds = np.unique(rng.choice(subjects, size=4))
    run_both(subjects, indptr, indices, seeds, num_nodes, hops=2)


def _star_graph(n_spokes, num_nodes):
    """uid 0 -> spokes 1..n_spokes; each spoke -> uid num_nodes-1."""
    subjects = np.arange(0, n_spokes + 1, dtype=np.int64)
    counts = np.ones(n_spokes + 1, dtype=np.int64)
    counts[0] = n_spokes
    indptr = np.zeros(n_spokes + 2, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    indices = np.concatenate([
        np.arange(1, n_spokes + 1, dtype=np.int64),          # hub fan-out
        np.full(n_spokes, num_nodes - 1, dtype=np.int64),    # spokes converge
    ])
    return subjects, indptr, indices


@pytest.mark.parametrize("n_spokes", [pb.FRONTIER_CAP - 1,
                                      pb.FRONTIER_CAP,
                                      pb.FRONTIER_CAP + 1])
def test_sparse_dense_crossover(n_spokes):
    """Hop 2's frontier is exactly at/under/over FRONTIER_CAP, driving the
    sparse (2-level bucket search) vs dense (chunked bitmap) kernel choice.
    Both must agree with the host BFS."""
    num_nodes = pb.FRONTIER_CAP + 1000
    subjects, indptr, indices = _star_graph(n_spokes, num_nodes)
    res = run_both(subjects, indptr, indices, np.array([0]), num_nodes, hops=2)
    # hop1 traverses n_spokes hub edges; hop2 traverses n_spokes spoke edges
    assert int(res.traversed) == 2 * n_spokes


def test_dense_seed_frontier(rng):
    """Seed frontier itself above FRONTIER_CAP: first hop takes the dense
    path immediately."""
    num_nodes = 40000  # spans 2 chunks
    subjects, indptr, indices = random_csr(rng, num_nodes, 30000)
    seeds = np.unique(rng.choice(subjects, size=pb.FRONTIER_CAP + 500))
    run_both(subjects, indptr, indices, seeds, num_nodes, hops=2)


def test_prep_pull_rejects_out_of_range_uids():
    subjects = np.array([0], dtype=np.int64)
    indptr = np.array([0, 1], dtype=np.int64)
    indices = np.array([100], dtype=np.int64)
    with pytest.raises(ValueError, match="num_nodes"):
        pb.prep_pull(subjects, indptr, indices, num_nodes=50)
    with pytest.raises(ValueError, match="num_nodes"):
        pb.prep_pull(np.array([100], np.int64), indptr,
                     np.array([0], np.int64), num_nodes=50)


def test_matches_xla_pull_path(rng):
    """Cross-check against ops.traversal.k_hop_pull (the XLA formulation the
    kernel replaces) on a mid-size R-MAT graph."""
    from dgraph_tpu.ops import traversal

    subjects, indptr, indices = rmat_csr(11, 8, seed=9)
    num_nodes = int(max(subjects.max(), indices.max())) + 2
    seeds = np.unique(rng.choice(subjects, size=32, replace=False))

    g = pb.prep_pull(subjects, indptr, indices, num_nodes)
    seeds_mask = jnp.zeros(num_nodes, dtype=bool).at[jnp.asarray(seeds)].set(True)
    res = pb.k_hop_pull_pallas(g, seeds_mask, hops=3)

    in_sub, in_ptr, in_src = traversal.reverse_csr(subjects, indptr, indices)
    ref = traversal.k_hop_pull(
        jnp.asarray(subjects), jnp.asarray(indptr), jnp.asarray(in_sub),
        jnp.asarray(in_ptr), jnp.asarray(in_src), seeds_mask, hops=3,
        num_nodes=num_nodes)
    np.testing.assert_array_equal(np.asarray(res.visited),
                                  np.asarray(ref.visited))
    assert int(res.traversed) == int(ref.traversed)


def test_duplicate_seed_uids_not_overcounted(rng):
    """A repeated seed must not be expanded once per occurrence (review r4)."""
    subjects = np.array([0, 1])
    indptr = np.array([0, 1, 2])
    indices = np.array([1, 2])
    g = pb.prep_pull(subjects, indptr, indices, 4)
    mask = jnp.zeros(4, dtype=bool).at[0].set(True)
    res = pb.k_hop_pull_pallas(g, mask, hops=1, seed_uids=np.array([0, 0, 0]))
    assert int(res.traversed) == 1


def test_hops_zero_returns_seeds_as_frontier(rng):
    subjects = np.array([0])
    indptr = np.array([0, 1])
    indices = np.array([1])
    g = pb.prep_pull(subjects, indptr, indices, 4)
    mask = jnp.zeros(4, dtype=bool).at[0].set(True)
    res = pb.k_hop_pull_pallas(g, mask, hops=0)
    np.testing.assert_array_equal(np.asarray(res.frontier), np.asarray(mask))
    assert int(res.traversed) == 0
