"""The Pallas kernel IS the production @recurse path (VERDICT r4 #1).

Forcing KERNEL_MIN_EDGES=0 routes DQL @recurse through
ops/pallas_bfs.recurse_fused / recurse_step (interpret mode on the CPU test
mesh — the same program Mosaic compiles on TPU) and the full JSON output
must be identical to the host-mirror path for every query shape: fused
single-child, multi-child stepped, value children, filters, loops, reverse
edges, depth exhaustion, and the edge budget error.
"""

import json

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import recurse as recmod


def _graph_node(rng, n=48):
    node = Node()
    node.alter(schema_text="name: string .\nfollow: uid @reverse .\n"
                           "knows: uid .")
    quads = [f'<0x{u:x}> <name> "p{u}" .' for u in range(1, n + 1)]
    for _ in range(n * 3):
        a, b = int(rng.integers(1, n + 1)), int(rng.integers(1, n + 1))
        if a != b:
            quads.append(f"<0x{a:x}> <follow> <0x{b:x}> .")
    for _ in range(n * 2):
        a, b = int(rng.integers(1, n + 1)), int(rng.integers(1, n + 1))
        if a != b:
            quads.append(f"<0x{a:x}> <knows> <0x{b:x}> .")
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    return node


QUERIES = [
    # fused shape: single uid child, no filter
    "{ q(func: uid(0x1, 0x2)) @recurse(depth: 3) { follow } }",
    "{ q(func: uid(0x1)) @recurse(depth: 4, loop: true) { follow } }",
    # stepped: two uid children
    "{ q(func: uid(0x1, 0x3)) @recurse(depth: 3) { follow knows } }",
    # stepped: value child at every level
    "{ q(func: uid(0x2)) @recurse(depth: 3) { name follow } }",
    # filter on the uid child
    "{ q(func: uid(0x1)) @recurse(depth: 3) "
    "{ follow @filter(uid(0x2, 0x4, 0x6, 0x8, 0xa)) } }",
    # reverse edge
    "{ q(func: uid(0x5)) @recurse(depth: 2) { ~follow } }",
    # until exhaustion (stepped: depth cap 64 exceeds FUSED_MAX_DEPTH)
    "{ q(func: uid(0x1)) @recurse { follow } }",
]


def _canon(out) -> str:
    return json.dumps(out, sort_keys=True, default=str)


@pytest.mark.parametrize("qidx", range(len(QUERIES)))
def test_recurse_kernel_matches_host(rng, qidx):
    node = _graph_node(rng)
    q = QUERIES[qidx]
    host_out, _ = node.query(q)
    recmod.KERNEL_MIN_EDGES = 0
    try:
        kern_out, _ = node.query(q)
    finally:
        recmod.KERNEL_MIN_EDGES = None
    assert _canon(host_out) == _canon(kern_out)


def test_fused_path_taken(rng, monkeypatch):
    """The single-child no-filter shape must run ONE fused dispatch."""
    node = _graph_node(rng)
    from dgraph_tpu.ops import pallas_bfs as pb

    calls = {"fused": 0, "step": 0}
    real_fused, real_step = pb.recurse_fused, pb.recurse_step
    monkeypatch.setattr(pb, "recurse_fused", lambda *a, **k: (
        calls.__setitem__("fused", calls["fused"] + 1) or real_fused(*a, **k)))
    monkeypatch.setattr(pb, "recurse_step", lambda *a, **k: (
        calls.__setitem__("step", calls["step"] + 1) or real_step(*a, **k)))
    recmod.KERNEL_MIN_EDGES = 0
    try:
        node.query("{ q(func: uid(0x1, 0x2)) @recurse(depth: 3) { follow } }")
        assert calls == {"fused": 1, "step": 0}
        node.query("{ q(func: uid(0x1)) @recurse(depth: 3) { follow knows } }")
        assert calls["fused"] == 1 and calls["step"] > 0
    finally:
        recmod.KERNEL_MIN_EDGES = None


def test_kernel_edge_budget(rng):
    """The budget error must fire on the kernel path too (recurse.go:167)."""
    from dgraph_tpu.query import engine as eng

    node = _graph_node(rng)
    recmod.KERNEL_MIN_EDGES = 0
    old = eng.MAX_QUERY_EDGES
    eng.set_query_edge_limit(5)
    try:
        with pytest.raises(Exception, match="ErrTooBig|edge budget"):
            node.query("{ q(func: uid(0x1, 0x2)) @recurse(depth: 3) "
                       "{ follow } }")
    finally:
        eng.set_query_edge_limit(old)
        recmod.KERNEL_MIN_EDGES = None


def test_shortest_kernel_bfs_matches_host(rng, monkeypatch):
    """Large-CSR shortest runs the Pallas bfs_dist kernel; cost must equal
    the host Dijkstra and the path must be a real edge path."""
    from dgraph_tpu.query import shortest as sh

    node = _graph_node(rng, n=60)
    # this test probes WHICH execution path runs (host Dijkstra vs Pallas
    # kernel) by replaying identical queries after flipping module floors;
    # the whole-query result cache would legitimately serve the replay
    # without executing anything, so opt out of that tier here
    node.result_cache = None
    # pick reachable pairs from the host path first
    monkeypatch.setattr(sh, "DEVICE_SSSP_MIN_EDGES", 1 << 62)  # host Dijkstra
    pairs = []
    for dst in range(2, 40):
        out, _ = node.query(
            f"{{ p as shortest(from: 0x1, to: 0x{dst:x}) {{ follow }} "
            f"  r(func: uid(p)) {{ uid }} }}")
        if out.get("_path_"):
            pairs.append((dst, out["_path_"][0]["_weight_"]))
    assert pairs, "no reachable pairs in random graph"

    monkeypatch.setattr(sh, "SSSP_KERNEL_MIN", 0)
    monkeypatch.setattr(sh, "DEVICE_SSSP_MIN_EDGES", 0)
    from dgraph_tpu.ops import pallas_bfs as pb

    calls = []
    real = pb.shortest_bfs
    monkeypatch.setattr(pb, "shortest_bfs",
                        lambda *a, **k: calls.append(1) or real(*a, **k))
    for dst, want_cost in pairs[:6]:
        out, _ = node.query(
            f"{{ p as shortest(from: 0x1, to: 0x{dst:x}) {{ follow }} "
            f"  r(func: uid(p)) {{ uid }} }}")
        assert out["_path_"], f"kernel path missed dst 0x{dst:x}"
        assert out["_path_"][0]["_weight_"] == want_cost
        # validate the path is a real edge chain
        uids = []
        nodep = out["_path_"][0]
        while True:
            uids.append(int(nodep["uid"], 16))
            nxt = nodep.get("follow")
            if not nxt:
                break
            nodep = nxt[0]
        assert uids[0] == 0x1 and uids[-1] == dst
    assert calls, "kernel shortest_bfs was not used"


def test_shortest_kernel_unreachable(rng, monkeypatch):
    from dgraph_tpu.query import shortest as sh

    node = Node()
    node.alter(schema_text="follow: uid .")
    node.mutate(set_nquads="<0x1> <follow> <0x2> .\n<0x3> <follow> <0x4> .",
                commit_now=True)
    monkeypatch.setattr(sh, "SSSP_KERNEL_MIN", 0)
    monkeypatch.setattr(sh, "DEVICE_SSSP_MIN_EDGES", 0)
    out, _ = node.query("{ p as shortest(from: 0x1, to: 0x4) { follow } "
                        "  r(func: uid(p)) { uid } }")
    assert not out.get("_path_")


def test_set_query_edge_limit_bounds_shortest(rng):
    """Behavioral guard for the single-binding refactor: the setter must
    bound the shortest-path expansion too (a by-value re-import in
    shortest.py would silently escape it)."""
    from dgraph_tpu.query import engine as eng

    node = _graph_node(rng)
    old = eng.MAX_QUERY_EDGES
    eng.set_query_edge_limit(2)
    try:
        with pytest.raises(Exception, match="ErrTooBig|edge budget"):
            node.query("{ p as shortest(from: 0x1, to: 0x2f, numpaths: 2) "
                       "{ follow } r(func: uid(p)) { uid } }")
    finally:
        eng.set_query_edge_limit(old)
