"""Memory management: approx accounting, budget-driven rollup, cache drop
(reference: posting/lists.go:123-180 AllottedMemory / periodic commit)."""

import pytest

from dgraph_tpu.api.server import Node


@pytest.fixture
def node():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .\nv: int .")
    return n


def _churn(node, rounds=40):
    for i in range(rounds):
        node.mutate(set_nquads=f'<0x{i % 8 + 1:x}> <v> "{i}" .',
                    commit_now=True)


def test_rollup_under_budget_preserves_data(node):
    _churn(node)
    before = node.store.memory_stats()
    assert before["layers"] > 0
    report = node.enforce_memory(budget_bytes=1)   # force full compaction
    assert report["rolled_up"] > 0
    after = node.store.memory_stats()
    assert after["layers"] == 0                    # all folded into bases
    assert after["bytes"] < before["bytes"]
    # data identical after compaction
    out, _ = node.query('{ q(func: uid(0x1)) { v } }')
    assert out["q"][0]["v"] == 32                  # last write to 0x1


def test_rollup_respects_pending_txn(node):
    _churn(node, 10)
    txn = node.new_txn()       # open txn pins the watermark
    _churn(node, 10)
    node.enforce_memory(budget_bytes=1)
    # layers committed after the pending txn's start_ts must survive
    assert node.store.memory_stats()["layers"] > 0
    node.abort(txn.start_ts)
    node.enforce_memory(budget_bytes=1)
    assert node.store.memory_stats()["layers"] == 0


def test_budget_satisfied_is_noop(node):
    _churn(node, 5)
    before = node.store.memory_stats()
    report = node.enforce_memory(budget_bytes=1 << 30)
    assert report["rolled_up"] == 0
    assert node.store.memory_stats() == before


def test_memory_gauge_exported(node):
    _churn(node, 5)
    node.enforce_memory(budget_bytes=1 << 30)
    assert node.metrics.counter("dgraph_memory_bytes").value > 0
    assert "dgraph_memory_bytes" in node.metrics.to_dict()
