"""Vectorized multi-key/string-key @groupby (VERDICT r4 #9): dense-code
factorization + vectorized cartesian join must be output-identical to the
per-uid dict path, and 100k-subject grouping must run in single-digit ms
(cache-warm)."""

import json
import time

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import groupby as gbmod


@pytest.fixture()
def node(rng):
    n = Node()
    n.alter(schema_text="name: string .\ngenre: string @index(exact) .\n"
                        "age: int @index(int) .\ncity: string .\n"
                        "likes: [uid] .\ntags: [string] .")
    quads = []
    genres = ["a", "b", "c"]
    cities = ["x", "y"]
    for i in range(1, 61):
        quads.append(f'<0x{i:x}> <name> "p{i}" .')
        if i % 7:     # leave some uids without a genre
            quads.append(f'<0x{i:x}> <genre> "{genres[i % 3]}" .')
        quads.append(f'<0x{i:x}> <city> "{cities[i % 2]}" .')
        quads.append(f'<0x{i:x}> <age> "{20 + i % 5}"^^<xs:int> .')
        for _ in range(2):
            t = int(rng.integers(1, 61))
            quads.append(f"<0x{i:x}> <likes> <0x{t:x}> .")
        quads.append(f'<0x{i:x}> <tags> "t{i % 4}" .')
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    return n


QUERIES = [
    # single string key
    '{ q(func: has(name)) @groupby(genre) { count(uid) } }',
    # multi-key: string x string
    '{ q(func: has(name)) @groupby(genre, city) { count(uid) } }',
    # string x numeric
    '{ q(func: has(name)) @groupby(city, age) { count(uid) } }',
    # uid key (multi-valued) alone and crossed with a value key
    '{ q(func: has(name)) @groupby(likes) { count(uid) } }',
    '{ q(func: has(name)) @groupby(genre, likes) { count(uid) } }',
    # with aggregates
    '{ q(func: has(name)) @groupby(genre, city) { count(uid) '
    '  m: max(val(ag)) s: sum(val(ag)) } '
    '  var(func: has(name)) { ag as age } }',
    # aliased keys
    '{ q(func: has(name)) @groupby(g: genre) { count(uid) } }',
]


@pytest.mark.parametrize("qidx", range(len(QUERIES)))
def test_vectorized_matches_dict_path(node, qidx):
    q = QUERIES[qidx]
    vec_out, _ = node.query(q)
    gbmod.VECTORIZE = False
    try:
        ref_out, _ = node.query(q)
    finally:
        gbmod.VECTORIZE = True
    assert json.dumps(vec_out, sort_keys=True, default=str) == \
        json.dumps(ref_out, sort_keys=True, default=str)


def test_list_and_lang_keys_fall_back(node):
    """[string] list keys keep the dict path (first-value semantics)."""
    out, _ = node.query(
        '{ q(func: has(name)) @groupby(tags) { count(uid) } }')
    gbmod.VECTORIZE = False
    try:
        ref, _ = node.query(
            '{ q(func: has(name)) @groupby(tags) { count(uid) } }')
    finally:
        gbmod.VECTORIZE = True
    assert json.dumps(out, sort_keys=True, default=str) == \
        json.dumps(ref, sort_keys=True, default=str)


def test_100k_subject_groupby_ms():
    """100k subjects, string key x 4 values + city x 2: grouping itself
    must be single-digit ms once the per-snapshot factorization is warm."""
    from dgraph_tpu.query.engine import Executor, SubGraph
    from dgraph_tpu.query import dql
    from dgraph_tpu.storage.csr_build import GraphSnapshot, PredData
    from dgraph_tpu.utils.schema import SchemaState, parse_schema
    from dgraph_tpu.utils.types import TypeID, Val

    n = 100_000
    rng = np.random.default_rng(5)
    uids = np.arange(1, n + 1, dtype=np.int64)
    genres = np.asarray(["g%d" % i for i in range(4)])
    cities = np.asarray(["c%d" % i for i in range(2)])
    snap = GraphSnapshot(1)
    schema = SchemaState()
    for e in parse_schema("genre: string .\ncity: string ."):
        schema.set(e)

    for attr, choices in (("genre", genres), ("city", cities)):
        pd = PredData(attr, TypeID.STRING)
        pick = choices[rng.integers(0, len(choices), n)]
        pd.value_subjects_host = uids.copy()
        pd.host_values = {int(u): Val(TypeID.STRING, str(v))
                          for u, v in zip(uids, pick)}
        snap.preds[attr] = pd

    req = dql.parse(
        "{ q(func: uid(%s)) @groupby(genre, city) { count(uid) } }"
        % "0x1")   # placeholder; seed via sg.dest_uids directly below
    ex = Executor(snap, schema)
    sg = SubGraph(gq=req.queries[0], attr="q")
    sg.dest_uids = uids

    gbmod.process_groupby(ex, sg)      # warm the factorization cache
    dt = float("inf")
    for _ in range(5):                 # min-of-N: box load must not flake
        t0 = time.perf_counter()
        gbmod.process_groupby(ex, sg)
        dt = min(dt, (time.perf_counter() - t0) * 1e3)
    rows = sg.group_result
    assert len(rows) == 8
    assert sum(r["count"] for r in rows) == n
    # single-digit ms when the box is idle (measured ~3 ms); the full
    # suite runs jit compiles on all cores concurrently, so the CI gate
    # allows contention headroom while still catching a per-uid regression
    # (the dict path takes ~1.5 s here)
    assert dt < 30.0, f"groupby took {dt:.1f} ms"

    # golden-equal vs the dict path on a subset (full dict path is slow)
    sub = SubGraph(gq=req.queries[0], attr="q")
    sub.dest_uids = uids[:2000]
    gbmod.process_groupby(ex, sub)
    vec_rows = sub.group_result
    gbmod.VECTORIZE = False
    try:
        sub2 = SubGraph(gq=req.queries[0], attr="q")
        sub2.dest_uids = uids[:2000]
        gbmod.process_groupby(ex, sub2)
    finally:
        gbmod.VECTORIZE = True
    assert json.dumps(vec_rows, sort_keys=True) == \
        json.dumps(sub2.group_result, sort_keys=True)


def test_empty_groupby_keeps_dict_shape(node):
    out, _ = node.query('{ q(func: has(name)) @groupby() { count(uid) } }')
    gbmod.VECTORIZE = False
    try:
        ref, _ = node.query(
            '{ q(func: has(name)) @groupby() { count(uid) } }')
    finally:
        gbmod.VECTORIZE = True
    assert json.dumps(out, sort_keys=True, default=str) == \
        json.dumps(ref, sort_keys=True, default=str)


def test_device_aggregation_branch(node, monkeypatch):
    """The f32 device segmented-reduction branch (taken in production only
    above _HOST_AGG_MAX members) must stay golden-equal to the host one."""
    monkeypatch.setattr(gbmod, "_HOST_AGG_MAX", 0)   # force device branch
    q = ('{ q(func: has(name)) @groupby(genre) { count(uid) '
         '  s: sum(val(ag)) m: max(val(ag)) } '
         '  var(func: has(name)) { ag as age } }')
    dev_out, _ = node.query(q)
    monkeypatch.setattr(gbmod, "_HOST_AGG_MAX", 1 << 17)
    host_out, _ = node.query(q)
    assert json.dumps(dev_out, sort_keys=True, default=str) == \
        json.dumps(host_out, sort_keys=True, default=str)
