"""HBM working-set manager (ISSUE 11, storage/residency.py): tiered
device residency — budget admission, LRU-of-score eviction, pin floors,
hysteresis/thrash accounting, plan-driven prefetch, cold-tier host
serving, and the identity contracts (qcache tokens, DeviceBatcher
same-CSR-object compatibility, mesh placement caches) across an
evict → re-admit cycle of the same tablet."""

import gc

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import batch as batchmod
from dgraph_tpu.query import qcache
from dgraph_tpu.query import task as taskmod
from dgraph_tpu.query.task import TaskQuery
from dgraph_tpu.storage import residency as resmod
from dgraph_tpu.storage.csr_build import PredCSR
from dgraph_tpu.storage.residency import ResidencyManager
from dgraph_tpu.utils import faults
from dgraph_tpu.utils.metrics import Registry


# ---------------------------------------------------------------------------
# unit level: manager policy over stub owners
# ---------------------------------------------------------------------------

class _StubOwner:
    """Minimal residency owner: a named device-buffer group."""

    _res = None
    _res_attr = ""
    _res_kind = "csr"

    def __init__(self, mgr, attr, nbytes):
        self._res = mgr
        self._res_attr = attr
        self.nbytes = nbytes
        self._dev = None
        self.drops = 0

    def device_nbytes(self):
        return self.nbytes

    def device_resident(self):
        return self._dev is not None

    def drop_device(self):
        self._dev = None
        self.drops += 1

    def upload(self, prefetch=False):
        return resmod.ensure_device(self, "_dev", lambda: ("dev",),
                                    prefetch=prefetch)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture
def clock():
    return _Clock()


def _mgr(clock, budget=1000, **kw):
    kw.setdefault("min_resident_s", 0.0)
    return ResidencyManager(budget_bytes=budget, metrics=Registry(),
                            clock=clock, **kw)


def test_admission_under_budget_and_eviction(clock):
    mgr = _mgr(clock, budget=1000)
    a = _StubOwner(mgr, "a", 400)
    b = _StubOwner(mgr, "b", 400)
    a.upload()
    b.upload()
    assert mgr.usage()["hbm_bytes"] == 800
    # c needs 400 -> one of a/b must go; touch b so a is the cold victim
    mgr.touch("b")
    c = _StubOwner(mgr, "c", 400)
    c.upload()
    assert a.drops == 1 and b.drops == 0
    assert a._dev is None and b._dev is not None and c._dev is not None
    assert mgr.usage()["hbm_bytes"] == 800
    m = mgr.metrics
    assert m.counter("dgraph_residency_admissions_total").value == 3
    assert m.counter("dgraph_residency_evictions_total").value == 1


def test_eviction_order_is_lru_of_score(clock):
    mgr = _mgr(clock, budget=1200)
    owners = {n: _StubOwner(mgr, n, 400) for n in ("x", "y", "z")}
    for o in owners.values():
        o.upload()
    # x is hottest, z warm, y idle -> y is the lowest-score victim
    for _ in range(10):
        mgr.touch("x")
    mgr.touch("z")
    w = _StubOwner(mgr, "w", 400)
    w.upload()
    assert owners["y"].drops == 1
    assert owners["x"].drops == 0 and owners["z"].drops == 0


def test_pin_floor_never_evicts(clock):
    mgr = _mgr(clock, budget=800, pins=("keep",))
    kept = _StubOwner(mgr, "keep", 400)
    other = _StubOwner(mgr, "other", 400)
    kept.upload()
    other.upload()
    # hammer "other" so only the pin (not the score) can save "keep"
    for _ in range(20):
        mgr.touch("other")
    c = _StubOwner(mgr, "c", 400)
    c.upload()
    assert kept.drops == 0 and other.drops == 1


def test_hysteresis_skips_young_entries_when_possible(clock):
    mgr = _mgr(clock, budget=800, min_resident_s=5.0)
    old = _StubOwner(mgr, "old", 400)
    old.upload()
    clock.t += 10.0                   # old is past the hysteresis floor
    young = _StubOwner(mgr, "young", 400)
    young.upload()
    clock.t += 1.0                    # young is NOT
    c = _StubOwner(mgr, "c", 400)
    c.upload()
    assert old.drops == 1 and young.drops == 0


def test_thrash_counter_on_fast_readmit(clock):
    mgr = _mgr(clock, budget=400, thrash_window_s=10.0)
    a = _StubOwner(mgr, "a", 400)
    b = _StubOwner(mgr, "b", 400)
    a.upload()
    clock.t += 1.0
    b.upload()                        # evicts a
    clock.t += 1.0
    a.upload()                        # re-admit within the window
    assert mgr.metrics.counter(
        "dgraph_residency_thrash_total").value >= 1


def test_cold_tablet_never_admits(clock):
    mgr = _mgr(clock, budget=100)
    big = _StubOwner(mgr, "big", 400)
    assert not mgr.allows_device(big.device_nbytes())
    # prefer_host is a pure consult — a fused-shape check probing several
    # owners must not inflate cold_serves; serve sites count explicitly
    assert resmod.prefer_host(big)
    assert mgr.metrics.counter(
        "dgraph_residency_cold_serves_total").value == 0
    mgr.note_cold_serve()
    assert mgr.metrics.counter(
        "dgraph_residency_cold_serves_total").value == 1
    assert mgr.tier_of("big", 400) == resmod.TIER_COLD
    assert mgr.tier_of("big", 50) == resmod.TIER_WARM


def test_evict_to_and_weakref_unregister(clock):
    mgr = _mgr(clock, budget=1000)
    a = _StubOwner(mgr, "a", 300)
    b = _StubOwner(mgr, "b", 300)
    a.upload()
    b.upload()
    assert mgr.evict_to(300) == 1
    assert mgr.usage()["hbm_bytes"] == 300
    # dropping the last strong ref unregisters via the weakref callback
    del a, b
    gc.collect()
    assert mgr.usage()["hbm_bytes"] == 0


# ---------------------------------------------------------------------------
# node level: tiers through the real query path
# ---------------------------------------------------------------------------

N_PREDS = 16
N_SUBJ = 48
FANOUT = 8
PREDS = [f"p{i:02d}" for i in range(N_PREDS)]


def _build_node(**kw):
    """Node over N_PREDS uid tablets of ~equal size (so a budget between
    one tablet and the total forces real admission/eviction churn) plus
    an exact-indexed name predicate. Task/result caches off by default:
    these tests probe the dispatch seam, not the cache tiers."""
    kw.setdefault("task_cache_mb", 0)
    kw.setdefault("result_cache_mb", 0)
    # planner off: its estimated-frontier cutover would route these small
    # expands host-side regardless of the shrunken HOST_EXPAND_MAX
    kw.setdefault("planner", False)
    n = Node(**kw)
    schema = ["name: string @index(exact) ."]
    schema += [f"{p}: [uid] ." for p in PREDS]
    n.alter(schema_text="\n".join(schema))
    rng = np.random.default_rng(11)
    quads = []
    for i in range(1, N_SUBJ + 1):
        quads.append(f'<{i:#x}> <name> "s{i}" .')
    for p in PREDS:
        for i in range(1, N_SUBJ + 1):
            for t in rng.choice(N_SUBJ, FANOUT, replace=False) + 1:
                quads.append(f"<{i:#x}> <{p}> <{int(t):#x}> .")
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    return n


QUERIES = [f"{{ q(func: has({p})) {{ {p} {{ uid }} }} }}" for p in PREDS]


def _run_all(node, queries=QUERIES):
    return [node.query(q)[0] for q in queries]


def _graph_device_bytes(node) -> int:
    snap = node.snapshot()
    return sum(resmod.pred_host_nbytes(pd) for pd in snap.preds.values())


@pytest.fixture
def force_device(monkeypatch):
    """Shrink the host/device cutover so every multi-row expand takes the
    device path (the tier the manager governs)."""
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 8)


def test_tiered_serving_byte_identical_10x_budget(force_device):
    """The tiering gate at test scale: a budget ~10x smaller than the
    graph's device footprint serves the battery byte-identically, with
    real admission/eviction churn underneath."""
    resident = _build_node()
    want = _run_all(resident)
    tiered = _build_node(device_budget_mb=1)
    # refine the MB-granular flag to exactly graph/10 (bench.py residency
    # does the same): bigger than one tablet, 10x smaller than the graph
    total = _graph_device_bytes(tiered)
    tiered.residency.budget = total // 10
    tiered.residency.evict_to(tiered.residency.budget)
    got = _run_all(tiered)
    assert got == want
    m = tiered.residency.metrics
    assert m.counter("dgraph_residency_admissions_total").value > 0
    assert m.counter("dgraph_residency_evictions_total").value > 0
    assert tiered.residency.usage()["hbm_bytes"] <= \
        tiered.residency.budget
    resident.close()
    tiered.close()


def test_cold_tablet_serves_host_path(force_device):
    """A tablet bigger than the WHOLE budget never uploads: the expand
    takes the host gather at any frontier size, byte-identically."""
    want_node = _build_node()
    expect = _run_all(want_node)
    node = _build_node(device_budget_mb=1)
    node.residency.budget = 64          # smaller than any tablet here
    got = _run_all(node)
    assert got == expect
    snap = node.snapshot()
    assert snap.preds["p00"].csr._dev is None      # never uploaded
    assert node.residency.metrics.counter(
        "dgraph_residency_cold_serves_total").value > 0
    assert node.residency.usage()["hbm_bytes"] == 0
    node.close()
    want_node.close()


def test_evict_readmit_identity_rotation(force_device):
    """Satellite: qcache per-predicate tokens, DeviceBatcher
    same-CSR-object keys, and results must all survive an evict →
    re-admit cycle of the same tablet — and re-key only on a real
    commit."""
    node = _build_node(device_budget_mb=512)
    q0 = QUERIES[0]
    want = node.query(q0)[0]
    snap = node.snapshot()
    pd = snap.preds["p00"]
    csr = pd.csr
    assert csr._dev is not None          # device path ran
    tq = TaskQuery("p00", frontier=np.arange(1, 33, dtype=np.int64))
    tok0 = qcache.task_token(snap, tq)
    key0, kind0, _w = batchmod.classify(snap, node.store.schema, tq)
    assert kind0 == "expand" and key0 == ("expand", id(csr))

    # evict: device buffers drop, identity stays
    assert node.residency.evict_to(0) > 0
    assert csr._dev is None
    snap2 = node.snapshot()
    assert snap2.preds["p00"] is pd                # same PredData
    assert qcache.task_token(snap2, tq) == tok0    # token survives
    assert node.query(q0)[0] == want               # re-admits on demand
    assert csr._dev is not None                    # re-uploaded
    key1, kind1, _w = batchmod.classify(node.snapshot(),
                                        node.store.schema, tq)
    assert kind1 == "expand" and key1 == key0      # same batch bucket

    # a REAL commit must rotate the token (the invalidation half)
    node.mutate(set_nquads=f"<{1:#x}> <p00> <{47:#x}> .",
                commit_now=True)
    snap3 = node.snapshot()
    assert qcache.task_token(snap3, tq) != tok0
    node.close()


def test_mesh_placement_cache_survives_evict_cycle(force_device):
    """Mesh placement is identity-keyed on PredData: an evict/re-admit
    cycle must neither rotate the placement nor change results."""
    node = _build_node(device_budget_mb=512, mesh_devices=4,
                       mesh_min_edges=64)
    qs = QUERIES[:4]
    want = _run_all(node, qs)
    snap = node.snapshot()              # mesh-placed snapshot
    placed0 = snap.preds["p00"].csr
    node.residency.evict_to(0)
    snap2 = node.snapshot()
    assert snap2.preds["p00"].csr is placed0       # placement cache hit
    assert _run_all(node, qs) == want
    node.close()


def test_mesh_placement_defers_to_budget():
    """A tablet whose per-device row-shard would not fit the budget stays
    on the host path instead of sharding (placement defers)."""
    from dgraph_tpu.parallel.dist import DistPredCSR
    from dgraph_tpu.parallel.mesh_exec import MeshExecutor

    reg = Registry()
    mgr = ResidencyManager(budget_bytes=64, metrics=reg)
    mex = MeshExecutor(n_devices=4, metrics=reg, shard_min_edges=16,
                       residency=mgr)
    subjects = np.arange(1, 65, dtype=np.int32)
    indptr = np.arange(0, 65 * 8, 8, dtype=np.int32)
    indices = (np.arange(64 * 8, dtype=np.int32) % 64) + 1
    csr = PredCSR(subjects, indptr, indices)
    assert mex._place_csr(csr) is csr          # deferred: budget too small
    assert reg.counter("dgraph_mesh_residency_deferred_total").value == 1
    mgr.budget = 0                              # unbounded: shards again
    assert isinstance(mex._place_csr(csr), DistPredCSR)


def test_prefetch_hits_and_wasted(force_device):
    node = _build_node(device_budget_mb=512)
    snap = node.snapshot()
    assert node.residency.prefetch(["p00"], snap, sync=True) >= 1
    csr = snap.preds["p00"].csr
    assert csr._dev is not None                  # prefetched into HBM
    _ = node.query(QUERIES[0])                   # touches p00
    m = node.residency.metrics
    assert m.counter("dgraph_residency_prefetch_hits_total").value >= 1
    # prefetch another tablet, then evict it untouched -> wasted
    assert node.residency.prefetch(["p01"], snap, sync=True) >= 1
    node.residency.evict_to(0)
    assert m.counter("dgraph_residency_prefetch_wasted_total").value >= 1
    node.close()


def test_upload_fault_serves_host_byte_identical(force_device):
    """residency.h2d_upload chaos point: an injected upload failure must
    never fail or corrupt a read — the host gather serves it."""
    clean = _build_node()
    want = _run_all(clean, QUERIES[:4])
    node = _build_node(device_budget_mb=512)
    try:
        faults.GLOBAL.reseed(7)
        faults.GLOBAL.install("residency.h2d_upload", "error", p=1.0)
        got = _run_all(node, QUERIES[:4])
        assert got == want
        m = node.residency.metrics
        assert m.counter(
            "dgraph_residency_upload_failures_total").value > 0
        snap = node.snapshot()
        assert snap.preds["p00"].csr._dev is None
        # clearing the fault lets the next read promote again
        faults.GLOBAL.clear()
        assert _run_all(node, QUERIES[:4]) == want
        assert node.snapshot().preds["p00"].csr._dev is not None
    finally:
        faults.GLOBAL.clear()
        node.close()
        clean.close()


def test_vector_evict_readmit_rank_identical():
    """VectorIndex device matrices: identical ranking across an evict /
    re-admit cycle, and a cold vector tablet serves the exact host
    scan."""
    import dgraph_tpu.storage.vecindex as vx

    node = Node(device_budget_mb=512, task_cache_mb=0, result_cache_mb=0)
    node.alter(
        schema_text="emb: float32vector @index(vector(dim: 8)) .")
    rng = np.random.default_rng(5)
    quads = []
    for i in range(1, 200):
        v = ", ".join(f"{x:.4f}" for x in rng.normal(size=8))
        quads.append(f'<{i:#x}> <emb> "[{v}]" .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    qv = "[" + ", ".join(["0.1"] * 8) + "]"
    q = f'{{ q(func: similar_to(emb, "{qv}", 5)) {{ uid }} }}'
    # force the device path (tiny tablets host-scan by default)
    old = vx.HOST_SCAN_MAX
    vx.HOST_SCAN_MAX = 1
    try:
        want, _ = node.query(q)
        vi = node.snapshot().preds["emb"].vecindex
        assert vi._dev is not None
        node.residency.evict_to(0)
        assert vi._dev is None
        got, _ = node.query(q)
        assert got == want                     # re-admitted, same ranks
        # cold: budget below the matrix -> host float64 scan, same ranks
        node.residency.budget = 64
        node.residency.evict_to(64)
        cold, _ = node.query(q)
        assert cold == want
        assert vi._dev is None
    finally:
        vx.HOST_SCAN_MAX = old
        node.close()


def test_vector_heavy_snapshot_triggers_eviction():
    """Satellite regression (the undercount): vector embedding matrices
    were invisible to enforce_memory — a vector-heavy snapshot must now
    count toward the budget and trigger cache eviction."""
    node = Node()
    node.alter(
        schema_text="emb: float32vector @index(vector(dim: 64)) .")
    rng = np.random.default_rng(9)
    quads = []
    for i in range(1, 400):
        v = ", ".join(f"{x:.3f}" for x in rng.normal(size=64))
        quads.append(f'<{i:#x}> <emb> "[{v}]" .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    # fold the vector matrix (lazy snapshots fold on first READ)
    node.snapshot().pred("emb")
    vec_bytes = 399 * 64 * 4
    report = node.enforce_memory(
        budget_bytes=node.store.memory_stats()["bytes"] + vec_bytes // 4)
    # the fold accounting SEES the matrix ...
    assert report["fold_bytes"] >= vec_bytes
    # ... and the over-budget snapshot was dropped (the old code returned
    # dropped_caches == 0 here: store bytes alone were under budget)
    assert report["dropped_caches"] > 0
    node.close()


def test_residency_metrics_on_surfaces(force_device):
    """/metrics prom exposition + /debug/metrics residency section."""
    from dgraph_tpu.api.http import _serving_metrics
    from dgraph_tpu.obs import prom

    node = _build_node(device_budget_mb=512)
    _run_all(node, QUERIES[:4])
    node.residency.usage()
    text = prom.render(node.metrics)
    parsed = prom.parse(text)
    for name in ("dgraph_residency_admissions_total",
                 "dgraph_residency_evictions_total",
                 "dgraph_residency_prefetch_hits_total",
                 "dgraph_residency_prefetch_wasted_total",
                 "dgraph_residency_thrash_total",
                 "dgraph_residency_hbm_bytes",
                 "dgraph_residency_host_bytes"):
        assert name in parsed, name
    tiers = {lbl.get("tier") for lbl, _v in
             parsed.get("dgraph_residency_tier_bytes", [])}
    assert "hbm" in tiers
    section = _serving_metrics(node)["residency"]
    assert section["enabled"] is True
    assert section["admissions"] > 0
    assert set(section["tiers"]) == {"hbm", "warm", "cold"}
    assert isinstance(section["resident"], dict)
    node.close()


def test_unbounded_budget_is_accounting_only(force_device):
    """budget 0 (the default): no admission control, no eviction — the
    fully-resident fast path with accounting, so pre-existing deployments
    see zero behavior change."""
    node = _build_node()
    _run_all(node, QUERIES[:4])
    assert not node.residency.enabled
    m = node.residency.metrics
    assert m.counter("dgraph_residency_evictions_total").value == 0
    assert m.counter("dgraph_residency_cold_serves_total").value == 0
    snap = node.snapshot()
    assert snap.preds["p00"].csr._dev is not None
    node.close()


def test_tier_transition_span_events(force_device):
    """Admissions emit residency_tier span events — the span active at
    promotion time carries the warm->hbm transition it caused. Driven
    through process_task directly (not Node.query) so the async
    prefetcher can't win the upload race outside any span."""
    node = _build_node(device_budget_mb=512, span_sample=1.0)
    snap = node.snapshot()
    node.residency.evict_to(0)
    with node.tracer.root("probe", force=True):
        taskmod.process_task(
            snap, TaskQuery("p00", frontier=np.arange(1, 33,
                                                      dtype=np.int64)),
            node.store.schema)
    evs = []
    for rec in node.tracer.sink.index():
        full = node.tracer.sink.get(rec["trace_id"])
        for sp in full["spans"]:
            for ev in sp.get("events", []):
                if ev["name"] == "residency_tier":
                    evs.append(ev["attrs"])
    assert any(e.get("transition") == "warm->hbm" for e in evs)
    node.close()


def test_batcher_classifies_cold_tablet_out(force_device):
    """Review fix: the batched-dispatch classifier must consult the tier —
    a COLD tablet classifies out to the solo path (which serves the host
    gather) instead of being uploaded by a batched kernel."""
    node = _build_node(device_budget_mb=1)
    node.residency.budget = 64          # everything cold
    snap = node.snapshot()
    tq = TaskQuery("p00", frontier=np.arange(1, 33, dtype=np.int64))
    key, kind, work = batchmod.classify(snap, node.store.schema, tq)
    assert key is None and kind == "cold_tier"
    # warm again under an ample budget: classifies back to a batch bucket
    node.residency.budget = 512 << 20
    key, kind, _w = batchmod.classify(snap, node.store.schema, tq)
    assert kind == "expand" and key is not None
    node.close()


def test_batched_expand_upload_fault_host_fallback(force_device):
    """Review fix: a residency.h2d_upload fault inside a FORMED batch
    must not fail every member — the batched runner falls back to the
    per-slot host gather, byte-identical to solo execution."""
    from dgraph_tpu.query.batch import DeviceBatcher, _Entry

    node = _build_node(device_budget_mb=512)
    snap = node.snapshot()
    frontiers = [np.arange(1, 25, dtype=np.int64),
                 np.arange(9, 41, dtype=np.int64)]
    want = [taskmod.process_task(
        snap, TaskQuery("p01", frontier=f), node.store.schema)
        for f in frontiers]
    node.residency.evict_to(0)          # force a fresh upload attempt
    batcher = DeviceBatcher(metrics=Registry(), idle_fire=False)
    entries = []
    for f in frontiers:
        tq = TaskQuery("p01", frontier=f)
        _key, kind, work = batchmod.classify(snap, node.store.schema, tq)
        assert kind == "expand"
        entries.append(_Entry(work))
    try:
        faults.GLOBAL.reseed(1)
        faults.GLOBAL.install("residency.h2d_upload", "error", p=1.0)
        batcher._run_expand(entries)
        for e, w in zip(entries, want):
            assert e.error is None
            assert [m.tolist() for m in e.result.uid_matrix] == \
                [m.tolist() for m in w.uid_matrix]
            assert e.result.dest_uids.tolist() == w.dest_uids.tolist()
    finally:
        faults.GLOBAL.clear()
        node.close()
