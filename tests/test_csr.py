"""CSR expand vs a scipy-free numpy reference."""

import numpy as np
import jax.numpy as jnp

from dgraph_tpu.ops import csr, uidset as us


def build_csr(edges, n_rows):
    """edges: list of (src_row, dst). Returns (indptr, indices) numpy."""
    edges = sorted(set(edges))
    counts = np.zeros(n_rows, dtype=np.int32)
    for s, _ in edges:
        counts[s] += 1
    indptr = np.zeros(n_rows + 1, dtype=np.int32)
    indptr[1:] = np.cumsum(counts)
    indices = np.asarray([d for _, d in edges], dtype=np.int32)
    return indptr, indices


def test_expand_basic():
    #   0 -> {10, 11}, 1 -> {}, 2 -> {11, 12, 13}
    indptr, indices = build_csr([(0, 10), (0, 11), (2, 11), (2, 12), (2, 13)], 3)
    frontier = us.make_set([0, 2], capacity=4)
    res = csr.expand(jnp.asarray(indptr), jnp.asarray(indices), frontier, out_cap=8)
    assert int(res.total) == 5
    np.testing.assert_array_equal(np.asarray(res.targets)[:5], [10, 11, 11, 12, 13])
    np.testing.assert_array_equal(np.asarray(res.seg)[:5], [0, 0, 1, 1, 1])
    np.testing.assert_array_equal(np.asarray(res.counts)[:2], [2, 3])
    # padding
    assert np.asarray(res.seg)[5] == -1
    assert np.asarray(res.targets)[5] == us.SENTINEL32


def test_expand_dest_dedups():
    indptr, indices = build_csr([(0, 10), (0, 11), (2, 11), (2, 12)], 3)
    frontier = us.make_set([0, 2], capacity=4)
    dest, total = csr.expand_dest(jnp.asarray(indptr), jnp.asarray(indices), frontier, out_cap=8)
    assert int(total) == 4
    np.testing.assert_array_equal(us.to_numpy(dest), [10, 11, 12])


def test_expand_overflow_reports_total():
    indptr, indices = build_csr([(0, i) for i in range(10)], 1)
    frontier = us.make_set([0], capacity=2)
    res = csr.expand(jnp.asarray(indptr), jnp.asarray(indices), frontier, out_cap=4)
    assert int(res.total) == 10  # host sees overflow vs out_cap=4 and can retry bigger
    np.testing.assert_array_equal(np.asarray(res.targets), [0, 1, 2, 3])


def test_expand_empty_frontier():
    indptr, indices = build_csr([(0, 1)], 2)
    frontier = us.make_set([], capacity=4)
    res = csr.expand(jnp.asarray(indptr), jnp.asarray(indices), frontier, out_cap=4)
    assert int(res.total) == 0
    assert np.all(np.asarray(res.targets) == us.SENTINEL32)


def test_degrees():
    indptr, indices = build_csr([(0, 1), (0, 2), (1, 2)], 3)
    rows = us.make_set([0, 1, 2], capacity=5)
    d = csr.degrees(jnp.asarray(indptr), rows)
    np.testing.assert_array_equal(np.asarray(d)[:3], [2, 1, 0])


def test_expand_random(rng):
    n = 200
    edges = {(int(rng.integers(0, n)), int(rng.integers(0, 5000))) for _ in range(2000)}
    indptr, indices = build_csr(list(edges), n)
    rows_np = np.unique(rng.integers(0, n, size=40))
    frontier = us.make_set(rows_np, capacity=64)
    res = csr.expand(jnp.asarray(indptr), jnp.asarray(indices), frontier, out_cap=4096)
    want = []
    for r in rows_np:
        want.extend(sorted(d for s, d in edges if s == r))
    assert int(res.total) == len(want)
    np.testing.assert_array_equal(np.asarray(res.targets)[: len(want)], want)
    dest, _ = csr.expand_dest(jnp.asarray(indptr), jnp.asarray(indices), frontier, out_cap=4096)
    np.testing.assert_array_equal(us.to_numpy(dest), np.unique(want))
