"""Query cost ledger + fleet metrics (ISSUE 13, obs/costs.py):
per-request resource attribution threaded through every execution seam,
aggregatable fixed-bucket histograms with trace exemplars, the
Zero-federated fleet scrape, and the /debug/top sliding-window profiler
with EWMA regression baselines."""

import json
import random
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from dgraph_tpu.api.http import make_server
from dgraph_tpu.api.server import Node
from dgraph_tpu.obs import costs, prom
from dgraph_tpu.query import task as taskmod
from dgraph_tpu.utils import faults, metrics

SCHEMA = """
    name: string @index(exact) .
    age: int @index(int) .
    follows: [uid] @reverse .
"""


@pytest.fixture
def node():
    n = Node(span_sample=1.0, trace_rng=random.Random(11))
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads="""
        _:a <name> "ann" .
        _:b <name> "bob" .
        _:c <name> "cid" .
        _:a <age> "30" .
        _:a <follows> _:b .
        _:a <follows> _:c .
    """, commit_now=True)
    yield n
    n.close()


# ---------------------------------------------------------------------------
# ledger unit behavior
# ---------------------------------------------------------------------------

def test_ledger_accumulates_and_scopes():
    lg = costs.CostLedger(endpoint="query", shape="{ q }")
    assert costs.current() is None
    with costs.scope(lg):
        assert costs.current() is lg
        with lg.task("follows"):
            with costs.kernel("csr.expand") as ck:
                ck.set(h2d=100, d2h=200)
            lg.add_task("follows", 7)
        costs.note("task_cache_hit")
        costs.add_rows(5)
    assert costs.current() is None
    lg.finish()
    rec = lg.to_dict()
    t = rec["total"]
    assert t["edges"] == 7 and t["tasks"] == 1 and t["rows"] == 5
    assert t["h2d"] == 100 and t["d2h"] == 200
    assert t["out"] == {"task_cache_hit": 1}
    assert t["pred"]["follows"][1] == 7         # edges on the pred row
    assert t["pred"]["follows"][2] == 300       # bytes on the pred row
    assert "csr.expand" in t["kern"]


def test_ledger_wire_roundtrip_and_remote_merge():
    w = costs.CostLedger(endpoint="serve_task")
    with w.task("follows"):
        w.add_kernel("csr.expand", 2.5, h2d=10, d2h=20)
        w.add_task("follows", 3)
    w.finish()
    raw = w.to_wire()
    rec = costs.CostLedger.from_wire(raw)
    assert rec["edges"] == 3 and rec["pred"]["follows"][1] == 3

    root = costs.CostLedger(endpoint="query")
    root.add_task("follows", 3)     # the root attributed the RPC result
    root.merge_remote("w1:7080", rec)
    root.merge_remote("w1:7080", rec)   # second RPC to the same worker
    out = root.to_dict()
    # physical costs sum; logical counts dedupe against the root's view
    assert out["groups"]["w1:7080"]["device_ms"] == 5.0
    assert out["total"]["edges"] == 6       # 2 RPCs' worth, not 9
    assert out["total"]["device_ms"] == 5.0
    assert out["total"]["h2d"] == 20


def test_scope_none_suppresses_charging():
    lg = costs.CostLedger()
    with costs.scope(lg):
        with costs.scope(None):
            costs.note("x")
            with costs.kernel("k"):
                pass
        costs.note("y")
    assert lg.outcomes == {"y": 1}


# ---------------------------------------------------------------------------
# embedded node: assembled record, /debug/top, exemplars
# ---------------------------------------------------------------------------

def test_embedded_query_assembles_cost_record(node, monkeypatch):
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 0)  # force device
    out, _ = node.query(
        '{ q(func: eq(name, "ann")) { name follows { name } } }')
    assert len(out["q"][0]["follows"]) == 2
    rec = node.cost_book.last()
    t = rec["total"]
    assert t["tasks"] >= 2
    assert t["edges"] == 2
    assert t["device_ms"] > 0
    assert "follows" in t["pred"] and t["pred"]["follows"][1] == 2
    assert t["out"].get("task_cache_miss", 0) >= 1
    assert rec["trace_id"]
    # the trace the record names is servable
    assert node.tracer.sink.get(rec["trace_id"]) is not None
    assert node.metrics.counters["dgraph_cost_records_total"].value >= 1


def test_result_cache_hit_skips_book_but_notes_outcome(node):
    q = '{ q(func: eq(name, "bob")) { name } }'
    node.query(q)
    n0 = len(node.cost_book)
    c0 = node.metrics.counters["dgraph_cost_records_total"].value
    assert c0 >= 1
    node.query(q)                       # replay: whole-result cache hit
    assert len(node.cost_book) == n0    # zero-cost records stay out
    # the records counter means "admitted to the cost surfaces" — a
    # trivial cache-hit replay must not move it
    assert node.metrics.counters["dgraph_cost_records_total"].value == c0


def test_no_cost_ledger_measures_nothing():
    n = Node(cost_ledger=False)
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads='_:a <name> "ann" .', commit_now=True)
    n.query('{ q(func: eq(name, "ann")) { name } }')
    assert len(n.cost_book) == 0
    assert n.metrics.counters["dgraph_cost_records_total"].value == 0
    n.close()


def test_cost_histograms_carry_resolvable_exemplar(node):
    node.query('{ q(func: eq(name, "ann")) { name follows { name } } }')
    # exemplars are OpenMetrics-only syntax: the classic text-format
    # exposition (what an un-negotiated Prometheus scrape gets) must NOT
    # carry them — a 0.0.4 parser rejects the '# {...}' suffix and would
    # drop the whole scrape
    assert "# {trace_id=" not in prom.render(node.metrics)
    text = prom.render(node.metrics, exemplars=True)
    series = prom.parse(text)
    ex = [lbl["__exemplar__"]
          for lbl, _ in series.get("dgraph_query_cost_device_ms_bucket", [])
          if lbl.get("__exemplar__")]
    ex += [lbl["__exemplar__"]
           for lbl, _ in series.get("dgraph_query_latency_s_bucket", [])
           if lbl.get("__exemplar__")]
    assert ex, "no exemplar rendered on the cost/latency histograms"
    assert node.tracer.sink.get(ex[0]) is not None, \
        "exemplar trace id must resolve at /debug/traces/<id>"


def test_debug_top_ranks_shapes_and_preds(node, monkeypatch):
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 0)
    hot = '{ q(func: eq(name, "ann")) { name follows { name } } }'
    cold = '{ q(func: eq(name, "cid")) { name } }'
    for i in range(4):
        node.query(hot, variables={"$i": str(i)})
        node.query(cold, variables={"$i": str(i)})
    top = node.cost_book.top(window_s=60, by="device_ms", group="shape")
    assert top["records_in_window"] >= 2
    assert top["top"][0]["key"].startswith("{ q(func: eq(name,")
    assert top["top"][0]["device_ms"] >= top["top"][-1]["device_ms"]
    by_pred = node.cost_book.top(by="edges", group="pred")
    assert any(r["key"] == "follows" and r["edges"] > 0
               for r in by_pred["top"])
    by_ep = node.cost_book.top(group="endpoint")
    assert by_ep["top"] and by_ep["top"][0]["key"] == "query"


def test_regression_flagged_into_slowlog_below_threshold():
    """A shape whose device cost jumps k x over its EWMA baseline lands
    in the slow-query ring via a seeded device.dispatch delay fault —
    even though every run stays far under the 10s slow_query_ms."""
    n = Node(span_sample=0.0, slow_query_ms=10_000.0,
             cost_regression_factor=4.0)
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads='_:a <name> "ann" .', commit_now=True)
    q = '{ q(func: eq(name, "ann")) { name } }'
    # warm the baseline past MIN_SAMPLES (vary a variable so the
    # whole-result cache misses and the record is a real execution)
    for i in range(costs.CostBook.MIN_SAMPLES + 2):
        n.query(q, variables={"$i": str(i)})
    assert not any(e.get("root") == "cost_regression"
                   for e in n.slow_log.recent())
    faults.GLOBAL.configure("device.dispatch:delay:1:0.05")
    n.task_cache.clear()     # the regressed run must actually dispatch
    try:
        n.query(q, variables={"$i": "regressed"})
    finally:
        faults.GLOBAL.clear(None)
    entries = [e for e in n.slow_log.recent()
               if e.get("root") == "cost_regression"]
    assert entries, "regressed shape never reached the slowlog ring"
    e = entries[0]
    assert e["device_ms"] > 4 * max(e["baseline_ms"],
                                    costs.CostBook.BASELINE_FLOOR_MS)
    assert e["query"].startswith("{ q(func:")
    assert n.metrics.counters["dgraph_cost_regressions_total"].value == 1
    top = n.cost_book.top(by="device_ms", group="shape")
    assert top["flagged_total"] == 1
    n.close()


# ---------------------------------------------------------------------------
# fixed-bucket histograms: merge exactness + exposition
# ---------------------------------------------------------------------------

def test_histogram_fixed_buckets_merge_exactly():
    a = metrics.Histogram(buckets=metrics.BUCKETS_SECONDS)
    b = metrics.Histogram(buckets=metrics.BUCKETS_SECONDS)
    rng = random.Random(3)
    for _ in range(200):
        a.observe(rng.random())
        b.observe(rng.random() * 4)
    merged = metrics.merge_exports([
        {"histograms": {"h": a.export()}},
        {"histograms": {"h": b.export()}}])["histograms"]["h"]
    assert merged["count"] == a.count + b.count
    assert merged["sum"] == pytest.approx(a.total + b.total)
    assert merged["counts"] == [
        x + y for x, y in zip(a.export()["counts"], b.export()["counts"])]


def test_histogram_bucket_of_le_semantics():
    h = metrics.Histogram(buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 3.0, 100.0):
        h.observe(v)
    ex = h.export()
    # le buckets: 1.0 holds {0.5, 1.0}; 2.0 none; 4.0 holds 3.0; +Inf 100
    assert ex["counts"] == [2, 0, 1, 1]


def test_mismatched_bucket_schemes_never_merge():
    a = metrics.Histogram(buckets=(1.0, 2.0))
    b = metrics.Histogram(buckets=(1.0, 3.0))
    a.observe(0.5)
    b.observe(0.5)
    m = metrics.merge_exports([
        {"histograms": {"h": a.export()}},
        {"histograms": {"h": b.export()}}])["histograms"]["h"]
    assert m["count"] == 1          # the straggler dropped, not mis-merged


def test_meter_counts_overflow_drops():
    m = metrics.Meter(window=10.0, cap=4)
    for _ in range(4):
        m.mark()
    assert m.dropped == 0
    m.mark()                        # evicts a mark still in the window
    m.mark()
    assert m.dropped == 2
    snap = m.snapshot()
    assert snap["dropped"] == 2 and snap["qps"] > 0
    # expired marks evicted by cap are NOT lies: nothing in-window lost
    m2 = metrics.Meter(window=0.01, cap=4)
    for _ in range(4):
        m2.mark()
    time.sleep(0.02)
    m2.mark()
    assert m2.dropped == 0


# ---------------------------------------------------------------------------
# satellite: mechanical pre-registration audit
# ---------------------------------------------------------------------------

def test_every_incremented_metric_is_preregistered():
    """Every dgraph_* name constructed anywhere must appear on a FRESH
    node's /metrics at value 0. The source walk is the static analyzer's
    metric-registration collector (dgraph_tpu/analysis, ISSUE 14 — one
    implementation, two consumers: this runtime audit and the
    `python -m dgraph_tpu.analysis` tier-1 gate); f-string placeholders
    expand via analysis.checkers.METRIC_PLACEHOLDERS."""
    from dgraph_tpu.analysis.checkers import collect_metric_names

    pkg = Path(costs.__file__).resolve().parent.parent
    names = collect_metric_names(pkg)
    assert len(names) > 80, f"audit scan looks broken: {len(names)} names"
    n = Node()
    try:
        text = prom.render(n.metrics)
        series = prom.parse(text)
        missing = []
        for name in sorted(names):
            present = (name in series or f"{name}_count" in series
                       or f"# TYPE {name} " in text)
            if not present:
                missing.append(name)
        assert not missing, \
            f"metrics incremented somewhere but absent from a fresh " \
            f"node's /metrics: {missing} — pre-register them in " \
            f"utils/metrics.Registry"
    finally:
        n.close()


# ---------------------------------------------------------------------------
# satellite: concurrent debug surfaces under live load
# ---------------------------------------------------------------------------

def test_debug_surfaces_concurrent_with_mixed_workload(node):
    srv = make_server(node, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    node.query('{ q(func: eq(name, "ann")) { name follows { name } } }')
    tid = node.cost_book.last()["trace_id"]
    stop = threading.Event()
    errors: list = []

    def workload():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                node.query('{ q(func: eq(name, "ann")) '
                           '{ name follows { name } } }',
                           variables={"$i": str(i)})
                if i % 5 == 0:
                    node.mutate(
                        set_nquads=f'_:x <name> "w{i}" .', commit_now=True)
            except Exception as e:      # noqa: BLE001
                errors.append(("workload", e))

    def hammer(path, check_prom=False):
        while not stop.is_set():
            try:
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    body = r.read()
                    if r.status >= 500:
                        errors.append((path, r.status))
                    if check_prom:
                        prom.parse(body.decode())
                    elif path != "/metrics":
                        json.loads(body)
            except urllib.error.HTTPError as e:
                if e.code >= 500:
                    errors.append((path, e.code))
            except Exception as e:      # noqa: BLE001
                errors.append((path, e))

    threads = [threading.Thread(target=workload, daemon=True)
               for _ in range(2)]
    for spec in (("/metrics", True), ("/debug/metrics", False),
                 ("/debug/top", False), (f"/debug/traces/{tid}", False),
                 ("/metrics", True), ("/debug/metrics", False),
                 ("/debug/top?by=edges&group=pred", False),
                 ("/debug/vars", False),
                 ("/debug/compiles", False),
                 ("/debug/timeline", False)):
        threads.append(threading.Thread(target=hammer, args=spec,
                                        daemon=True))
    for t in threads:
        t.start()
    time.sleep(2.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    srv.shutdown()
    assert not errors, f"debug surfaces failed under load: {errors[:5]}"


# ---------------------------------------------------------------------------
# wire cluster: one assembled record + fleet merge exactness
# ---------------------------------------------------------------------------

grpc = pytest.importorskip("grpc")


@pytest.fixture
def wire_cluster():
    from dgraph_tpu.coord.zero import Zero
    from dgraph_tpu.coord.zero_service import ZeroClient, serve_zero
    from dgraph_tpu.parallel.client import ClusterClient
    from dgraph_tpu.parallel.remote import serve_worker
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.utils.schema import parse_schema

    def mk():
        s = Store()
        for e in parse_schema(SCHEMA):
            s.set_schema(e)
        return s

    zero = Zero(2)
    zero.move_tablet("name", 0)
    zero.move_tablet("follows", 1)
    zero.move_tablet("age", 1)
    zsrv, zport, zsvc = serve_zero(zero, "localhost:0")
    w0, p0 = serve_worker(mk(), "localhost:0")
    w1, p1 = serve_worker(mk(), "localhost:0")
    # register with Zero's membership so the fleet scrape finds them
    zc = ZeroClient(f"localhost:{zport}")
    zc.connect(f"localhost:{p0}", 0)
    zc.connect(f"localhost:{p1}", 1)
    zc.close()
    client = ClusterClient(
        f"localhost:{zport}",
        {0: [f"localhost:{p0}"], 1: [f"localhost:{p1}"]},
        span_sample=1.0, trace_rng=random.Random(7))
    client.mutate(set_nquads="""
        _:a <name> "ann" .
        _:b <name> "bob" .
        _:c <name> "cid" .
        _:a <age> "30" .
        _:a <follows> _:b .
        _:a <follows> _:c .
    """)
    yield client, zsvc, (f"localhost:{p0}", f"localhost:{p1}")
    client.close()
    w0.stop(0)
    w1.stop(0)
    zsrv.stop(0)


def test_cross_shard_query_one_merged_cost_record(wire_cluster,
                                                 monkeypatch):
    """ISSUE 13 acceptance: a cross-shard query yields ONE assembled
    record whose per-group device ms/bytes/edges match the spans."""
    client, _zsvc, addrs = wire_cluster
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 0)
    out = client.query(
        '{ q(func: eq(name, "ann")) { name age follows { name } } }')
    assert len(out["q"][0]["follows"]) == 2
    rec = client.cost_book.last()
    # both groups shipped their cost records back over trailing metadata
    assert set(rec["groups"]) == set(addrs), rec["groups"].keys()
    t = rec["total"]
    assert t["edges"] == 2                      # logical, not double-booked
    g_follows = rec["groups"][addrs[1]]
    assert g_follows["pred"]["follows"][1] == 2
    assert g_follows["device_ms"] > 0
    # per-group device charges reconcile against the shipped spans: every
    # group's device_kernel span total is <= that group's ledger device
    # ms (the ledger times the same fenced section), and a group with
    # kernel spans has nonzero ledger charges
    trace = client.tracer.sink.get(rec["trace_id"])
    assert trace is not None
    by_proc: dict = {}
    for s in trace["spans"]:
        if s["name"] == "device_kernel":
            by_proc.setdefault(s["proc"], 0.0)
            by_proc[s["proc"]] += s["dur"] * 1e3
    assert by_proc, "no device spans shipped"
    for proc, span_ms in by_proc.items():
        addr = proc.split(":", 1)[1] if ":" in proc else proc
        g = rec["groups"].get(addr)
        assert g is not None, (proc, rec["groups"].keys())
        assert g["device_ms"] >= span_ms * 0.5, \
            f"{addr}: ledger {g['device_ms']}ms vs spans {span_ms}ms"
    # the shipped per-group edge counts agree with the span annotations
    span_edges = sum(s["attrs"].get("edges", 0)
                     for s in trace["spans"]
                     if s["name"] == "device_kernel"
                     and s["attrs"].get("kernel") == "csr.expand")
    assert span_edges == g_follows["pred"]["follows"][1]


def test_fleet_scrape_merge_equals_per_node_sum(wire_cluster):
    """ISSUE 13 acceptance: /metrics/fleet histogram _sum/_count equal
    the sum of the per-node scrapes (merge exactness)."""
    from dgraph_tpu.coord.zero_service import fleet_scrape

    client, zsvc, addrs = wire_cluster
    for i in range(3):
        client.query('{ q(func: eq(name, "ann")) { name follows '
                     '{ name } } }', variables={"$i": str(i)})
    fl = fleet_scrape(zsvc)
    assert set(fl["nodes"]) == set(addrs), fl["unreachable"]
    merged = fl["merged"]
    per = list(fl["nodes"].values())
    for cname in ("dgraph_task_cache_misses_total",
                  "dgraph_posting_writes_total"):
        assert merged["counters"][cname] == \
            sum(p["counters"][cname] for p in per)
    for hname, h in merged["histograms"].items():
        assert h["count"] == sum(
            p["histograms"][hname]["count"] for p in per
            if hname in p["histograms"])
        assert h["sum"] == pytest.approx(sum(
            p["histograms"][hname]["sum"] for p in per
            if hname in p["histograms"]))
        cum = 0
        total = 0
        for c in h["counts"]:
            total += c
        assert total == h["count"]
    # and the merged exposition is valid prom text
    text = prom.render_export(merged)
    series = prom.parse(text)
    assert series
