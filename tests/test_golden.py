"""Golden acceptance suite: a film-style dataset + a fixed query battery.

Round-2 verdict item 10 (reference: contrib/scripts/goldendata-queries.sh +
the query/query_test.go golden pattern): load a deterministic film graph,
run ≥25 queries spanning every directive/function family, and diff the full
JSON against tests/golden/expected.json. Any engine change that shifts
results shows up as a golden diff; intentional changes regenerate with
  python -m pytest tests/test_golden.py --regen-golden  (via env GOLDEN_REGEN=1)
"""

import json
import os

import pytest

from dgraph_tpu.api.server import Node

GOLDEN_PATH = os.path.join(os.path.dirname(__file__), "golden",
                           "expected.json")

SCHEMA = """
name: string @index(exact, term, trigram) @lang .
release_date: dateTime @index(year) .
rating: float @index(float) .
runtime: int @index(int) .
genre: [uid] @reverse @count .
director: [uid] @reverse .
starring: [uid] @reverse @count .
lives_in: string @index(term) .
email: string @index(exact) @upsert .
loc: geo @index(geo) .
"""

D, F, A, G = 0x1000, 0x2000, 0x3000, 0x4000
GENRES = ["drama", "comedy", "action", "scifi", "noir"]


def _dataset() -> str:
    q = []
    for i, g in enumerate(GENRES):
        q.append(f'<0x{G + i:x}> <name> "{g}" .')
    for d in range(12):
        q.append(f'<0x{D + d:x}> <name> "director{d}" .')
        q.append(f'<0x{D + d:x}> <lives_in> "city{d % 4} land" .')
        q.append(f'<0x{D + d:x}> <email> "d{d}@films.io" .')
        q.append(f'<0x{D + d:x}> <loc> "{{\\"type\\":\\"Point\\",\\"coordinates\\":'
                 f'[{ -120 + d * 3}.5,{30 + d}.25]}}"^^<geo:geojson> .')
    for a in range(30):
        q.append(f'<0x{A + a:x}> <name> "actor{a}" .')
    for f in range(60):
        fu = F + f
        q.append(f'<0x{fu:x}> <name> "film {f} of genre {GENRES[f % 5]}" .')
        if f % 4 == 0:
            q.append(f'<0x{fu:x}> <name> "le film {f}"@fr .')
        q.append(f'<0x{fu:x}> <release_date> '
                 f'"{1960 + (f * 7) % 60}-0{f % 9 + 1}-15T00:00:00"^^<xs:dateTime> .')
        q.append(f'<0x{fu:x}> <rating> "{(f * 13) % 100 / 10}"^^<xs:float> .')
        q.append(f'<0x{fu:x}> <runtime> "{90 + (f * 11) % 80}"^^<xs:int> .')
        q.append(f'<0x{fu:x}> <genre> <0x{G + f % 5:x}> .')
        if f % 3 == 0:
            q.append(f'<0x{fu:x}> <genre> <0x{G + (f + 2) % 5:x}> .')
        q.append(f'<0x{fu:x}> <director> <0x{D + f % 12:x}> .')
        for k in range(3):
            q.append(f'<0x{fu:x}> <starring> <0x{A + (f * 3 + k) % 30:x}> '
                     f'(character="char{k}", billing={k + 1}) .')
    return "\n".join(q)


QUERIES: list[tuple[str, str]] = [
    ("eq_exact", '{ q(func: eq(name, "director3")) { name lives_in } }'),
    ("eq_multi", '{ q(func: eq(name, ["director1", "director2"])) { name } }'),
    ("term_any", '{ q(func: anyofterms(lives_in, "city1 city2"), orderasc: name) { name } }'),
    ("term_all", '{ q(func: allofterms(name, "film genre scifi"), first: 4, orderasc: name) { name } }'),
    ("ineq_int", '{ q(func: ge(runtime, 160), orderasc: runtime) { name runtime } }'),
    ("ineq_float_page", '{ q(func: lt(rating, 2.0), orderasc: rating, first: 5, offset: 2) { name rating } }'),
    ("year_index", '{ q(func: ge(release_date, "1981-01-01"), '
                   'orderasc: release_date, first: 4) { name release_date } }'),
    ("dt_eq", '{ q(func: eq(release_date, "1981-04-15T00:00:00")) { name } }'),
    ("regexp", '{ q(func: regexp(name, /film 1. of/), orderasc: name, first: 6) { name } }'),
    ("has_count", '{ q(func: has(genre), first: 5, orderasc: name) { name count(genre) } }'),
    ("count_index", '{ q(func: eq(count(genre), 2), first: 6, orderasc: name) { name } }'),
    ("uid_func", f'{{ q(func: uid(0x{F:x}, 0x{F + 1:x})) {{ name rating }} }}'),
    ("uid_in", f'{{ q(func: has(director)) @filter(uid_in(director, 0x{D + 2:x})) '
               '{ name } }'),
    ("filter_and_not", '{ q(func: has(rating), orderasc: name, first: 6) @filter(ge(rating, 8.0) '
                       'AND NOT eq(runtime, 113)) { name rating runtime } }'),
    ("filter_or", '{ q(func: eq(name, "director1")) { name ~director @filter('
                  'le(rating, 3.0) OR ge(rating, 9.0)) (orderasc: rating) { name rating } } }'),
    ("reverse_edge", f'{{ q(func: uid(0x{G:x})) {{ name ~genre(first: 4, orderasc: name) '
                     '{ name } } }'),
    ("facets_read", f'{{ q(func: uid(0x{F + 6:x})) {{ name starring @facets(character, billing) '
                    '(orderasc: name) { name } } }'),
    ("facet_filter", f'{{ q(func: uid(0x{F + 6:x})) {{ starring @facets(eq(billing, 1)) '
                     '{ name } } }'),
    ("lang_read", f'{{ q(func: uid(0x{F + 4:x})) {{ name name@fr }} }}'),
    ("sort_desc_after", '{ q(func: has(rating), orderdesc: rating, first: 4) { name rating } }'),
    ("pagination_neg", '{ q(func: eq(name, "director0")) { name '
                       '~director(first: -2, orderasc: name) { name } } }'),
    ("alias_cascade", '{ q(func: has(director), first: 3, orderasc: name) @cascade '
                      '{ film: name dirs: director { name } } }'),
    ("normalize", f'{{ q(func: uid(0x{F + 9:x})) @normalize {{ film: name director '
                  '{ dname: name } } }'),
    ("expand_all", f'{{ q(func: uid(0x{D + 5:x})) {{ expand(_all_) }} }}'),
    ("var_uid", '{ v as var(func: eq(name, "director4")) { ~director { f as genre } }\n'
                '  q(func: uid(f), orderasc: name) @filter(NOT uid(v)) { name } }'),
    ("var_val_math", '{ var(func: has(rating)) { r as rating rt as runtime '
                     'm as math(r * 10 + rt / 10) }\n'
                     '  q(func: has(rating), orderdesc: val(m), first: 5) '
                     '{ name val(m) } }'),
    ("agg_block", '{ var(func: has(rating)) { r as rating }\n'
                  '  stats() { mn: min(val(r)) mx: max(val(r)) av: avg(val(r)) '
                  'sm: sum(val(r)) } }'),
    ("groupby", '{ var(func: has(runtime)) { rt as runtime }\n'
                '  q(func: has(genre)) @groupby(genre) { count(uid) '
                'avg_rt: avg(val(rt)) } }'),
    ("recurse", f'{{ q(func: uid(0x{A + 3:x})) @recurse(depth: 3) '
                '{ name ~starring director } }'),
    ("shortest", f'{{ path as shortest(from: 0x{A:x}, to: 0x{D:x}) '
                 '{ ~starring director }\n  path(func: uid(path)) { name } }'),
    ("geo_near", f'{{ q(func: near(loc, [-117.5, 31.25], 100000)) {{ name }} }}'),
    ("trigram_regexp_child", '{ q(func: eq(name, "director2")) { name ~director '
                             '@filter(regexp(name, /genre noir/)) { name } } }'),
    ("multi_block", '{ a(func: eq(name, "director6")) { name }\n'
                    '  b(func: eq(name, "director7")) { name ~director(first: 2, '
                    'orderasc: name) { name rating } } }'),
    # round-3 feature coverage
    ("lang_chain", f'{{ q(func: uid(0x{F + 4:x}, 0x{F + 5:x}), orderasc: name) '
                   '{ name@de:fr:. } }'),
    ("uid_in_list", f'{{ q(func: has(genre)) @filter(uid_in(genre, '
                    f'[0x{G + 4:x}])) {{ name }} }}'),
    ("count_reverse_root", '{ q(func: ge(count(~genre), 15), orderasc: name) { name } }'),
    ("math_cond", '{ var(func: has(rating)) { r as rating '
                  'hi as math(cond(r >= 8.0, 1, 0)) }\n'
                  '  q(func: has(rating), orderdesc: val(r), first: 4) '
                  '{ name val(hi) } }'),
    ("facet_not", f'{{ q(func: uid(0x{F + 6:x})) {{ starring '
                  '@facets(NOT eq(billing, 1)) { name } } }'),
]


@pytest.fixture(scope="module")
def node():
    n = Node()
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads=_dataset(), commit_now=True)
    return n


def _run_all(node) -> dict:
    out = {}
    for qname, q in QUERIES:
        res, _ = node.query(q)
        out[qname] = res
    return out


def test_golden_battery(node):
    got = _run_all(node)
    if os.environ.get("GOLDEN_REGEN") == "1" or not os.path.exists(GOLDEN_PATH):
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            json.dump(got, f, indent=1, sort_keys=True, default=str)
        pytest.skip("golden file (re)generated — commit it")
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    got_j = json.loads(json.dumps(got, default=str))
    assert sorted(got_j.keys()) == sorted(want.keys())
    for qname in want:
        assert got_j[qname] == want[qname], f"golden diff in {qname!r}"


def test_golden_covers_every_query():
    names = [n for n, _ in QUERIES]
    assert len(names) == len(set(names))
    assert len(names) >= 25
