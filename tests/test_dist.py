"""Sharded traversal on the virtual 8-device CPU mesh vs single-device result."""

import numpy as np
import jax
import jax.numpy as jnp

from dgraph_tpu.ops import traversal, uidset as us
from dgraph_tpu.parallel import dist, mesh as meshmod


def build_host_csr(rng, n_nodes, n_edges):
    edges = sorted({(int(a), int(b))
                    for a, b in rng.integers(0, n_nodes, size=(n_edges, 2)) if a != b})
    subjects = sorted({a for a, _ in edges})
    sub_idx = {s: i for i, s in enumerate(subjects)}
    indptr = np.zeros(len(subjects) + 1, dtype=np.int32)
    for a, _ in edges:
        indptr[sub_idx[a] + 1] += 1
    np.cumsum(indptr, out=indptr)
    indices = np.asarray([b for _, b in edges], dtype=np.int32)
    return np.asarray(subjects, dtype=np.int32), indptr, indices


def test_dist_k_hop_matches_single_device(rng):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    subjects, indptr, indices, = build_host_csr(rng, 500, 4000)
    m = meshmod.make_mesh(8)
    sharded = dist.shard_csr(subjects, indptr, indices, m)
    seeds = us.make_set([0, 3, 7], capacity=8)

    single = traversal.k_hop(jnp.asarray(subjects), jnp.asarray(indptr),
                             jnp.asarray(indices), seeds,
                             hops=3, frontier_cap=2048, num_nodes=500)
    frontier, visited, traversed = dist.dist_k_hop(
        sharded, seeds, m, hops=3, frontier_cap=2048, num_nodes=500)

    np.testing.assert_array_equal(np.asarray(visited), np.asarray(single.visited))
    np.testing.assert_array_equal(us.to_numpy(frontier), us.to_numpy(single.frontier))
    assert int(traversed) == int(single.traversed)


def test_dist_mesh_sizes(rng):
    subjects, indptr, indices = build_host_csr(rng, 100, 400)
    for n in (2, 4):
        m = meshmod.make_mesh(n)
        sharded = dist.shard_csr(subjects, indptr, indices, m)
        assert sharded.subjects.shape[0] == n
        seeds = us.make_set([0], capacity=4)
        frontier, visited, traversed = dist.dist_k_hop(
            sharded, seeds, m, hops=2, frontier_cap=512, num_nodes=100)
        single = traversal.k_hop(jnp.asarray(subjects), jnp.asarray(indptr),
                                 jnp.asarray(indices), seeds,
                                 hops=2, frontier_cap=512, num_nodes=100)
        np.testing.assert_array_equal(np.asarray(visited), np.asarray(single.visited))
