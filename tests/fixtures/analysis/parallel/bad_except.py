"""Known-bad fixture: except-seam (silent swallow at a wire seam)."""


def send(peer, msg):
    try:
        peer.send(msg)
    except Exception:
        pass
