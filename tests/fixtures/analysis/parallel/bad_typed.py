"""Known-bad fixture: rpc-error-taxonomy (untyped raise at a seam)."""


def route(groups, g):
    if g not in groups:
        raise RuntimeError(f"no connection to group {g}")
    return groups[g]
