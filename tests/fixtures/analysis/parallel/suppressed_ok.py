"""Suppression fixture: every violation here carries an allow() and the
analyzer must report NOTHING for this file."""

import threading
import time


def work():
    pass


def kick(pool):
    # dgraph: allow(ctxvar-copy) detached fixture loop
    pool.submit(work)
    t = threading.Thread(target=work)   # dgraph: allow(ctxvar-copy) same
    t.start()


def serve(req):
    # dgraph: allow(deadline-wait) fixture: bounded by the test harness
    # watchdog, demonstrating multi-line rationale comments
    time.sleep(0.01)


def send(peer, msg):
    try:
        peer.send(msg)
    except Exception:  # dgraph: allow(except-seam) fixture best-effort
        pass
