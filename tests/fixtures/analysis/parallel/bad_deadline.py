"""Known-bad fixture for the blocking-wait rule (path contains
/parallel/ so the scoped rule applies). Four naked blocking waits."""

import time


class Server:
    def serve(self, req):
        time.sleep(0.2)                 # naked sleep on a request path
        self.cv.wait()                  # unbounded condition wait
        self.lk.acquire()               # blocking acquire, unclamped
        return self.queue.get()         # unbounded queue get
