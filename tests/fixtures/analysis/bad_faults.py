"""Known-bad fixture: fault-points (fired but never declared)."""

from dgraph_tpu.utils import faults


def ship(chunk):
    faults.fire("bogus.chunk_ship")
    return chunk
