"""Known-bad fixture: jax-purity — trace-time impurity inside device
functions AND a donated buffer read after the donating call."""

import random
import time

import jax
from jax import lax


@jax.jit
def step(x):
    return x * time.time()              # frozen at trace time


def run(frontier):
    def body(i, f):
        return f + random.random()      # one sample for every step

    return lax.fori_loop(0, 4, body, frontier)


def _expand(f, adj):
    return adj @ f


_prog = jax.jit(_expand, donate_argnums=(0,))


def caller(frontier, adj):
    out = _prog(frontier, adj)
    return out, frontier.sum()          # read after donation
