"""Known-bad fixture: metric-registration must flag both sites."""


class Thing:
    def __init__(self, metrics):
        # not in utils/metrics.Registry.__init__
        self.c = metrics.counter("dgraph_bogus_surprise_total")
        kind = "nope"
        # f-string placeholder missing from METRIC_PLACEHOLDERS
        self.h = metrics.histogram(f"dgraph_{kind}_latency_s")
