"""Known-bad fixture: ctxvar-copy must flag both thread seams."""

import threading


def work():
    pass


def kick(pool):
    pool.submit(work)                       # context lost across the pool
    threading.Thread(target=work).start()   # and across the thread
