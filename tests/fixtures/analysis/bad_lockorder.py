"""Known-bad fixture: lock-order (static A->B in one method, B->A in
another — a deadlock schedule)."""


class Store:
    def commit(self):
        with self._txn_lock:
            with self._wal_lock:
                return 1

    def replay(self):
        with self._wal_lock:
            with self._txn_lock:
                return 2
