"""End-to-end distributed tracing (obs/otrace.py): one trace id spans
client -> every group's serve_task -> Zero coordinator calls -> device
kernels, with parent/child links intact; traces export as Chrome
trace-event JSON (Perfetto-loadable, validated structurally); /metrics
serves a parseable Prometheus exposition; the slow-query log captures
plan + span tree for threshold-crossing queries."""

import json
import random
import urllib.request

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.api.http import make_server
from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import Zero
from dgraph_tpu.coord.zero_service import serve_zero
from dgraph_tpu.obs import otrace, prom
from dgraph_tpu.parallel.client import ClusterClient
from dgraph_tpu.parallel.remote import serve_worker
from dgraph_tpu.query import task as taskmod
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema

SCHEMA = """
    name: string @index(exact) .
    age: int @index(int) .
    follows: [uid] @reverse .
"""


def _mk_store():
    s = Store()
    for e in parse_schema(SCHEMA):
        s.set_schema(e)
    return s


@pytest.fixture
def wire_cluster():
    """2 worker groups + a zero, all over real loopback gRPC; name lives
    on group 0, follows/age on group 1, so a 2-hop query fans to both."""
    zero = Zero(2)
    zero.move_tablet("name", 0)
    zero.move_tablet("follows", 1)
    zero.move_tablet("age", 1)
    zsrv, zport, _zsvc = serve_zero(zero, "localhost:0")
    stores = [_mk_store(), _mk_store()]
    w0, p0 = serve_worker(stores[0], "localhost:0")
    w1, p1 = serve_worker(stores[1], "localhost:0")
    client = ClusterClient(f"localhost:{zport}",
                           {0: [f"localhost:{p0}"], 1: [f"localhost:{p1}"]},
                           span_sample=1.0, trace_rng=random.Random(7))
    client.mutate(set_nquads="""
        _:a <name> "ann" .
        _:b <name> "bob" .
        _:c <name> "cid" .
        _:a <age> "30" .
        _:b <age> "41" .
        _:a <follows> _:b .
        _:a <follows> _:c .
    """)
    yield client, (f"localhost:{p0}", f"localhost:{p1}"), (w0, w1)
    client.close()
    w0.stop(0)
    w1.stop(0)
    zsrv.stop(0)


def _links_intact(spans):
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if not s["parent_id"]]
    assert len(roots) == 1, f"expected one root, got {roots}"
    for s in spans:
        if s["parent_id"]:
            assert s["parent_id"] in ids, \
                f"dangling parent {s['parent_id']} for {s['name']}"
    return roots[0]


def test_single_trace_spans_client_workers_zero_device(wire_cluster,
                                                       monkeypatch):
    client, addrs, _srvs = wire_cluster
    # force the device expand path for tiny frontiers so the trace carries
    # a real device-kernel span with transfer bytes
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 0)
    out = client.query(
        '{ q(func: eq(name, "ann")) { name age follows { name } } }')
    assert out["q"][0]["name"] == "ann"
    assert len(out["q"][0]["follows"]) == 2

    idx = client.tracer.sink.index()
    rec = client.tracer.sink.get(
        next(r["trace_id"] for r in idx if r["root"] == "query"))
    spans = rec["spans"]
    # exactly one trace id across every span
    assert {s["trace_id"] for s in spans} == {rec["trace_id"]}
    root = _links_intact(spans)
    assert root["name"] == "query" and root["proc"] == "client"

    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # client-side fan-out spans hit BOTH workers
    rpc_addrs = {s["attrs"]["addr"] for s in by_name["rpc:ServeTask"]}
    assert set(addrs) <= rpc_addrs
    # each worker's server span arrived over trailing metadata, with its
    # proc naming the worker
    worker_procs = {s["proc"] for s in by_name["serve_task"]}
    assert len(worker_procs) == 2
    # Zero coordinator calls are part of the same trace
    assert any(n.startswith("zero:") for n in by_name), by_name.keys()
    assert any(s["proc"] == "zero" for s in spans)
    # at least one device-kernel span with transfer bytes, under a worker
    kernels = by_name.get("device_kernel", [])
    assert kernels, f"no device span; names={sorted(by_name)}"
    assert any(k["attrs"].get("transfer_d2h_bytes", 0) > 0 for k in kernels)
    assert all(k["proc"].startswith("worker:") for k in kernels)
    # no span buffers left behind anywhere
    assert client.tracer.active_traces() == 0


def test_failed_fanout_leaks_no_spans(wire_cluster):
    client, _addrs, (w0, w1) = wire_cluster
    client.query('{ q(func: eq(name, "ann")) { name follows { name } } }')
    w1.stop(0)            # group 1 (follows/age) dies mid-cluster
    client.task_cache.clear()   # don't let cached tasks mask the dead group
    with pytest.raises(Exception):
        client.query(
            '{ q(func: eq(name, "bob")) { name follows { name } } }')
    # the root span finished with the error and the trace assembled —
    # nothing lingers in the per-trace buffers
    assert client.tracer.active_traces() == 0
    failed = [r for r in client.tracer.sink.index() if r["error"]]
    assert failed, "failed query should still produce an assembled trace"


def test_deterministic_sampling_with_injected_rng():
    class FlipFlop:
        def __init__(self):
            self.i = 0

        def random(self):
            self.i += 1
            return 0.0 if self.i % 2 else 0.99

        def getrandbits(self, n):
            return random.getrandbits(n)

    tr = otrace.Tracer(fraction=0.5, rng=FlipFlop())
    kinds = [bool(tr.root("q")) for _ in range(6)]
    assert kinds == [True, False, True, False, True, False]
    # finish the sampled roots so nothing leaks
    # (roots 0/2/4 were real spans)


def test_join_take_roundtrip_and_remote_merge():
    a = otrace.Tracer(fraction=1.0, proc="caller", rng=random.Random(1))
    b = otrace.Tracer(proc="callee", rng=random.Random(2))
    with a.root("query") as root:
        wire = otrace.wire_context()
        assert wire and wire.startswith(root.trace_id)
        with b.join(wire, "serve_task") as srv:
            with b.start("device", parent=srv):
                pass
        shipped = b.take(root.trace_id)
        assert len(shipped) == 2 and b.active_traces() == 0
        a.add_remote(shipped)
    rec = a.sink.get(root.trace_id)
    assert rec["nspans"] == 3
    tree = otrace.span_tree(rec)
    q = tree["tree"][0]
    assert q["name"] == "query"
    assert q["children"][0]["name"] == "serve_task"
    assert q["children"][0]["children"][0]["name"] == "device"


# ---------------------------------------------------------------------------
# embedded node: HTTP surface + Chrome JSON + Prometheus + slow log
# ---------------------------------------------------------------------------

@pytest.fixture
def http_node():
    node = Node(span_sample=1.0, trace_rng=random.Random(3),
                slow_query_ms=0.0001)   # everything is "slow": log fills
    node.alter(schema_text=SCHEMA)
    node.mutate(set_nquads='_:a <name> "ann" .\n_:b <name> "bob" .\n'
                           '_:a <follows> _:b .', commit_now=True)
    srv = make_server(node, "127.0.0.1", 0)
    import threading

    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield node, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    node.close()


def _get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return r.status, r.read()


def _post(base, path, body):
    req = urllib.request.Request(base + path, data=body.encode(),
                                 method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_chrome_trace_export_loads_structurally(http_node):
    node, base = http_node
    _post(base, "/query", '{ q(func: eq(name, "ann")) { name follows '
                          '{ name } } }')
    st, body = _get(base, "/debug/traces")
    assert st == 200
    idx = json.loads(body)
    tid = next(r["trace_id"] for r in idx if r["root"] == "query")
    st, body = _get(base, f"/debug/traces/{tid}")
    assert st == 200
    ct = json.loads(body)
    # the Perfetto/chrome://tracing JSON object-format contract
    assert isinstance(ct["traceEvents"], list) and ct["traceEvents"]
    assert ct["otherData"]["trace_id"] == tid
    phases = {e["ph"] for e in ct["traceEvents"]}
    assert "X" in phases and "M" in phases
    for e in ct["traceEvents"]:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] > 0
    # thread names label the processes
    names = [e["args"]["name"] for e in ct["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert "node" in names
    # tree view renders too
    st, body = _get(base, f"/debug/traces/{tid}?view=tree")
    tree = json.loads(body)
    assert tree["tree"][0]["name"] == "query"
    # unknown id 404s
    with pytest.raises(urllib.error.HTTPError):
        _get(base, "/debug/traces/ffffffffffffffff")


def test_prometheus_exposition_parses(http_node):
    node, base = http_node
    _post(base, "/query", '{ q(func: has(name)) { name } }')
    st, body = _get(base, "/metrics")
    assert st == 200
    series = prom.parse(body.decode())      # raises on malformed output
    assert series["dgraph_num_queries_total"][0][1] >= 1
    # fixed-bucket histogram shape (ISSUE 13): cumulative le buckets +
    # _sum/_count — the OLD quantile-label summary rows are gone from
    # /metrics (they can't be aggregated across nodes; the ring
    # percentiles stay on /debug/metrics)
    buckets = series.get("dgraph_query_latency_s_bucket", [])
    assert buckets and any(lbl.get("le") == "+Inf" for lbl, _ in buckets)
    assert "dgraph_query_latency_s_count" in series
    assert not any("quantile" in lbl for samples in series.values()
                   for lbl, _ in samples)
    # bucket counts are cumulative and monotone
    vals = [v for lbl, v in buckets]
    assert vals == sorted(vals)
    # meters render as labeled endpoint gauges
    assert any(lbl.get("endpoint") == "query"
               for lbl, _ in series.get("dgraph_endpoint_qps", []))


def test_slow_query_log_captures_plan_and_tree(http_node):
    node, base = http_node
    _post(base, "/query", '{ q(func: eq(name, "ann")) { name follows '
                          '{ name } } }')
    st, body = _get(base, "/debug/slow")
    entries = json.loads(body)
    assert entries, "threshold 0.1us should log every query"
    e = next(x for x in entries if x["root"] == "query")
    assert e["trace_id"] and e["elapsed_ms"] > 0
    assert e["query"].startswith("{ q(func:")
    assert e["plan"] is not None and "root_swaps" in e["plan"]
    names = set()

    def walk(nodes):
        for n in nodes:
            names.add(n["name"])
            walk(n.get("children", ()))

    walk(e["tree"])
    assert "query" in names and any(n.startswith("task:") for n in names)


def test_slow_query_log_jsonl_file(tmp_path):
    path = tmp_path / "slow.jsonl"
    node = Node(span_sample=1.0, trace_rng=random.Random(5),
                slow_query_ms=0.0001, slow_query_log=str(path))
    node.alter(schema_text=SCHEMA)
    node.mutate(set_nquads='_:a <name> "ann" .', commit_now=True)
    node.query('{ q(func: eq(name, "ann")) { name } }')
    node.close()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert any(e["root"] == "query" for e in lines)


def test_debug_index_names_new_endpoints(http_node):
    _node, base = http_node
    st, body = _get(base, "/debug")
    eps = json.loads(body)["endpoints"]
    for p in ("/debug/traces", "/debug/slow", "/metrics"):
        assert p in eps


def test_unsampled_query_costs_no_trace():
    # no slow log armed (an armed slow log force-samples every root)
    node = Node(span_sample=0.0)
    node.alter(schema_text=SCHEMA)
    node.mutate(set_nquads='_:a <name> "ann" .', commit_now=True)
    before = len(node.tracer.sink)
    node.query('{ q(func: has(name)) { name } }')
    assert len(node.tracer.sink) == before
    assert node.tracer.active_traces() == 0
    node.close()


def test_slow_log_fires_even_when_span_sampling_is_off():
    """An armed slow-query log force-samples roots: the threshold must be
    honored even at the production 1% (here 0%) span_sample default."""
    node = Node(span_sample=0.0, slow_query_ms=0.0001)
    node.alter(schema_text=SCHEMA)
    node.mutate(set_nquads='_:a <name> "ann" .', commit_now=True)
    node.query('{ q(func: eq(name, "ann")) { name } }')
    assert any(e["root"] == "query" for e in node.slow_log.recent())
    node.close()


def test_prom_level_shaped_totals_render_as_gauges():
    """pending/active '_total' names are inc/dec levels — a counter TYPE
    would make Prometheus read every decrease as a reset."""
    from dgraph_tpu.utils import metrics as metrics_mod

    text = prom.render(metrics_mod.Registry())
    assert "# TYPE dgraph_pending_queries_total gauge" in text
    assert "# TYPE dgraph_active_mutations_total gauge" in text
    assert "# TYPE dgraph_num_queries_total counter" in text
