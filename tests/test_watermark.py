"""WaterMark: min-unfinished-index tracker (x/watermark.go:66-213) and its
wiring into the follower applied watermark + env-var config overrides."""

import threading

import pytest

from dgraph_tpu.utils.watermark import WaterMark


def test_in_order():
    w = WaterMark()
    for i in (1, 2, 3):
        w.begin(i)
    assert w.done_until() == 0
    w.done(1)
    assert w.done_until() == 1
    w.done(3)                 # 2 still pending: can't pass it
    assert w.done_until() == 1
    w.done(2)
    assert w.done_until() == 3


def test_multiple_begins_per_index():
    w = WaterMark()
    w.begin(5)
    w.begin(5)
    w.done(5)
    assert w.done_until() == 0     # one begin still open
    w.done(5)
    assert w.done_until() == 5
    with pytest.raises(ValueError):
        w.done(5)


def test_set_done_until_and_wait():
    w = WaterMark()
    w.set_done_until(10)
    assert w.done_until() == 10
    got = []
    t = threading.Thread(
        target=lambda: got.append(w.wait_for_mark(12, timeout=5)))
    t.start()
    w.begin(12)
    w.done(12)
    t.join(timeout=5)
    assert got == [True]
    assert not w.wait_for_mark(99, timeout=0.01)
    w.begin(13)
    with pytest.raises(ValueError):
        w.set_done_until(20)       # marks pending


def test_follower_applied_watermark(tmp_path):
    from dgraph_tpu.coord.replication import ReplicaGroup
    g = ReplicaGroup(str(tmp_path / "wm"), n=3, serve_reads=True)
    g.node.alter(schema_text="v: int .")
    g.node.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    rd = next(m.reader for m in g._followers() if m.reader is not None)
    n = rd.applied.done_until()
    assert n > 0                       # schema + mutation + commit records
    assert rd.applied.wait_for_mark(n, timeout=1)
    g.close()


def test_env_defaults_override(monkeypatch, capsys):
    import dgraph_tpu.__main__ as cli
    monkeypatch.setenv("DGRAPH_TPU_GEOPRED", "location")
    monkeypatch.setenv("DGRAPH_TPU_OUT", "/tmp/nope.rdf.gz")
    # parse-only check: defaults picked up from env (geo still required)
    import argparse
    with pytest.raises(SystemExit):
        cli.main(["convert"])          # --geo missing: still errors
    # with geo supplied, env defaults flow through
    import gzip, json, tempfile, os
    td = tempfile.mkdtemp()
    geo = os.path.join(td, "g.json")
    json.dump({"type": "Feature",
               "geometry": {"type": "Point", "coordinates": [0.0, 1.0]},
               "properties": {}}, open(geo, "w"))
    out = os.path.join(td, "o.rdf.gz")
    monkeypatch.setenv("DGRAPH_TPU_OUT", out)
    assert cli.main(["convert", "--geo", geo]) == 0
    assert "<location>" in gzip.open(out, "rt").read()
