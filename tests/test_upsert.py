"""Upsert blocks: query + @if conds + uid(v)/val(v) mutation quads in one
txn (reference: gql/upsert.go ParseMutation, edgraph doQueryInUpsert)."""

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import dql
from dgraph_tpu.query.mutation import MutationError
from dgraph_tpu.query.upsert import UpsertError, eval_cond


@pytest.fixture
def node():
    n = Node()
    n.alter(schema_text="""
        email: string @index(exact) @upsert .
        name: string @index(exact) .
        score: int @index(int) .
        total: int .
        follows: uid @reverse .
    """)
    return n


UPSERT_INSERT = '''upsert {
  query { v as var(func: eq(email, "a@x.io")) }
  mutation @if(eq(len(v), 0)) {
    set {
      _:u <email> "a@x.io" .
      _:u <name> "alice" .
    }
  }
}'''


def test_insert_if_absent_idempotent(node):
    out, uids, ctx = node.upsert(
        dql.parse(UPSERT_INSERT).upsert["query"],
        dql.parse(UPSERT_INSERT).upsert["mutations"], commit_now=True)
    assert uids  # created
    # second run: v is non-empty now, cond fails, nothing inserted
    _, uids2, _ = node.upsert(
        dql.parse(UPSERT_INSERT).upsert["query"],
        dql.parse(UPSERT_INSERT).upsert["mutations"], commit_now=True)
    assert uids2 == {}
    res, _ = node.query('{ q(func: eq(email, "a@x.io")) { name } }')
    assert res == {"q": [{"name": "alice"}]}


def test_uid_var_subject_update(node):
    node.mutate(set_nquads='_:a <email> "b@x.io" .\n_:a <name> "old" .',
                commit_now=True)
    q = '{ v as var(func: eq(email, "b@x.io")) }'
    node.upsert(q, [{"cond": "gt(len(v), 0)",
                     "set": 'uid(v) <name> "new" .', "delete": ""}],
                commit_now=True)
    res, _ = node.query('{ q(func: eq(email, "b@x.io")) { name } }')
    assert res == {"q": [{"name": "new"}]}


def test_val_var_copies_per_subject(node):
    node.mutate(set_nquads='''
        _:a <name> "a" .
        _:a <score> "10" .
        _:b <name> "b" .
        _:b <score> "20" .
    ''', commit_now=True)
    q = '{ v as var(func: has(score)) { s as score } }'
    node.upsert(q, [{"cond": "", "set": 'uid(v) <total> val(s) .',
                     "delete": ""}], commit_now=True)
    res, _ = node.query('{ q(func: has(total), orderasc: total) { name total } }')
    assert res == {"q": [{"name": "a", "total": 10},
                         {"name": "b", "total": 20}]}


def test_delete_via_uid_var(node):
    node.mutate(set_nquads='_:a <email> "gone@x.io" .\n_:a <name> "g" .',
                commit_now=True)
    q = '{ v as var(func: eq(email, "gone@x.io")) }'
    node.upsert(q, [{"cond": "", "set": "",
                     "delete": "uid(v) <email> * .\nuid(v) <name> * ."}],
                commit_now=True)
    res, _ = node.query('{ q(func: has(email)) { email } }')
    assert res == {}


def test_empty_var_drops_quads(node):
    q = '{ v as var(func: eq(email, "nobody@x.io")) }'
    # no cond: quads referencing the empty var just vanish; txn still commits
    _, uids, _ = node.upsert(q, [{"cond": "", "set": 'uid(v) <name> "x" .',
                                  "delete": ""}], commit_now=True)
    assert uids == {}


def test_uid_object_var_cross_product(node):
    node.mutate(set_nquads='''
        _:a <name> "fan" .
        _:x <email> "s1@x.io" .
        _:y <email> "s2@x.io" .
    ''', commit_now=True)
    q = '''{
      f as var(func: eq(name, "fan"))
      s as var(func: has(email))
    }'''
    node.upsert(q, [{"cond": "", "set": "uid(f) <follows> uid(s) .",
                     "delete": ""}], commit_now=True)
    res, _ = node.query('{ q(func: eq(name, "fan")) { follows { email } } }')
    emails = {x["email"] for x in res["q"][0]["follows"]}
    assert emails == {"s1@x.io", "s2@x.io"}


def test_upsert_through_query_surface(node):
    # the full text form through Node.query (HTTP /mutate parses the same way)
    out, ctx = node.query(UPSERT_INSERT)
    res, _ = node.query('{ q(func: eq(email, "a@x.io")) { name } }')
    assert res == {"q": [{"name": "alice"}]}


def test_multiple_conditional_mutations(node):
    node.mutate(set_nquads='_:a <email> "c@x.io" .', commit_now=True)
    q = '{ v as var(func: eq(email, "c@x.io")) }'
    node.upsert(q, [
        {"cond": "eq(len(v), 0)", "set": '_:n <name> "created" .', "delete": ""},
        {"cond": "gt(len(v), 0)", "set": 'uid(v) <name> "updated" .', "delete": ""},
    ], commit_now=True)
    res, _ = node.query('{ q(func: eq(email, "c@x.io")) { name } }')
    assert res == {"q": [{"name": "updated"}]}
    res, _ = node.query('{ q(func: eq(name, "created")) { name } }')
    assert res == {}


def test_vars_not_valid_outside_upsert(node):
    with pytest.raises(MutationError):
        node.mutate(set_nquads='uid(v) <name> "x" .', commit_now=True)


def test_cond_grammar():
    class VV:
        def __init__(self, uids):
            self.uids = uids
            self.vals = {}
    vm = {"v": VV([1, 2]), "w": VV([])}
    assert eval_cond("eq(len(v), 2)", vm)
    assert eval_cond("gt(len(v), 1) and eq(len(w), 0)", vm)
    assert eval_cond("eq(len(v), 9) or le(len(w), 0)", vm)
    assert eval_cond("not eq(len(v), 0)", vm)
    # AND binds tighter than OR
    assert eval_cond("eq(len(v), 9) or eq(len(v), 2) and eq(len(w), 0)", vm)
    assert not eval_cond("(eq(len(v), 9) or eq(len(v), 2)) and gt(len(w), 0)", vm)
    assert eval_cond("eq(len(missing), 0)", vm)   # unknown var == empty
    with pytest.raises(UpsertError):
        eval_cond("bogus(len(v), 1)", vm)
    with pytest.raises(UpsertError):
        eval_cond("eq(len(v), 1) eq(len(v), 2)", vm)


def test_parse_upsert_block_shape():
    req = dql.parse(UPSERT_INSERT)
    assert req.upsert is not None
    assert 'var(func: eq(email, "a@x.io"))' in req.upsert["query"]
    m = req.upsert["mutations"][0]
    assert m["cond"].strip() == "eq(len(v), 0)"
    assert '<email> "a@x.io"' in m["set"]


def test_upsert_unknown_start_ts_rejected(node):
    with pytest.raises(MutationError):
        node.upsert('{ v as var(func: has(name)) }',
                    [{"cond": "", "set": '_:x <name> "y" .', "delete": ""}],
                    start_ts=999999)


def test_upsert_error_aborts_implicit_txn(node):
    before = len(node._txns)
    with pytest.raises(MutationError):
        node.upsert("", [
            {"cond": "", "set": '_:ok <name> "fine" .', "delete": ""},
            {"cond": "", "set": '_:bad <score> "not-an-int" .', "delete": ""},
        ], commit_now=True)
    # implicit txn cleaned up, nothing committed, no leak
    assert len(node._txns) == before
    res, _ = node.query('{ q(func: eq(name, "fine")) { name } }')
    assert res == {}


def test_upsert_explicit_txn_not_autocommitted(node):
    node.mutate(set_nquads='_:a <email> "open@x.io" .', commit_now=True)
    ctx = node.new_txn()
    node.upsert('{ v as var(func: eq(email, "open@x.io")) }',
                [{"cond": "", "set": 'uid(v) <name> "buffered" .',
                  "delete": ""}], start_ts=ctx.start_ts)
    # not yet visible: the explicit txn is still open
    res, _ = node.query('{ q(func: eq(name, "buffered")) { name } }')
    assert res == {}
    node.commit(ctx.start_ts)
    res, _ = node.query('{ q(func: eq(name, "buffered")) { name } }')
    assert res == {"q": [{"name": "buffered"}]}


def test_idle_txn_reaping(node):
    node.MAX_IDLE_TXNS = 8
    first = node.new_txn()
    # age past the grace period (ADVICE r3: young pristine txns are exempt
    # so a slow-but-live client is never reaped — see test_advice_r3.py)
    first.last_active -= node.IDLE_TXN_GRACE_S + 1
    for _ in range(16):
        node.new_txn()
    # the earliest stale pristine txn was reaped; later commits fail cleanly
    with pytest.raises(MutationError):
        node.commit(first.start_ts)
    assert len(node._txns) <= 17


def test_idle_txn_burst_pressure_overrides_grace(node):
    node.MAX_IDLE_TXNS = 8
    txns = [node.new_txn() for _ in range(4 * 8 + 2)]
    # all young, but the hard bound (4x) kicked in: some were reaped
    assert len(node._txns) < len(txns)


def test_bodyless_named_block_still_errors(node):
    from dgraph_tpu.query.dql import ParseError
    with pytest.raises(ParseError):
        dql.parse('{ q(func: has(name)) }')
