"""Device-path traversal in the real engine (VERDICT r3 item #2).

- `shortest` with one unweighted predicate executes via ops/traversal.sssp
  (device Bellman-Ford) and must return the same cost/path as the host
  Dijkstra; facet costs and multi-predicate blocks keep the host path.
- `@recurse` uses the vectorized CSR edge-position dedup; a node reached
  again over a NEW edge must still re-appear at the deeper level (edge-level
  reach-set semantics, query/recurse.go:129-141) — the reason node-visited
  BFS cannot back this path.
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import shortest as shortestmod


@pytest.fixture()
def chain_node():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .\nnext: uid .\n"
                        "alt: uid .\nweight: int .")
    # unique shortest path 1 -> 2 -> 3 -> 4 plus a longer detour 1 -> 5 -> 6 -> 7 -> 4
    quads = []
    for a, b in [(1, 2), (2, 3), (3, 4), (1, 5), (5, 6), (6, 7), (7, 4)]:
        quads.append(f"<0x{a:x}> <next> <0x{b:x}> .")
    for u in range(1, 8):
        quads.append(f'<0x{u:x}> <name> "n{u}" .')
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    return n


def test_shortest_uses_device_sssp(chain_node, monkeypatch):
    calls = []
    from dgraph_tpu.ops import traversal
    from dgraph_tpu.query import shortest as sh
    monkeypatch.setattr(sh, "DEVICE_SSSP_MIN_EDGES", 0)  # tiny test graph
    real = traversal.sssp

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(traversal, "sssp", spy)
    out, _ = chain_node.query(
        "{ p as shortest(from: 0x1, to: 0x4) { next } "
        "  q(func: uid(p)) { name } }")
    assert calls, "device sssp path was not taken"
    assert [x["name"] for x in out["q"]] == ["n1", "n2", "n3", "n4"]
    path = out["_path_"][0]
    assert path["_weight_"] == 3.0
    assert path["uid"] == "0x1"


def test_shortest_device_matches_host(chain_node, monkeypatch):
    from dgraph_tpu.query import shortest as sh
    monkeypatch.setattr(sh, "DEVICE_SSSP_MIN_EDGES", 0)
    sgq = "{ p as shortest(from: 0x1, to: 0x4) { next } q(func: uid(p)) { name } }"
    dev_out, _ = chain_node.query(sgq)

    # force the host path by disabling eligibility
    orig = shortestmod._device_csr
    shortestmod._device_csr = lambda ex, sg: None
    try:
        host_out, _ = chain_node.query(sgq)
    finally:
        shortestmod._device_csr = orig
    assert dev_out == host_out


def test_shortest_unreachable_device(chain_node, monkeypatch):
    from dgraph_tpu.query import shortest as sh
    monkeypatch.setattr(sh, "DEVICE_SSSP_MIN_EDGES", 0)
    out, _ = chain_node.query(
        "{ p as shortest(from: 0x4, to: 0x1) { next } q(func: uid(p)) { name } }")
    assert out.get("q", []) == [] and "_path_" not in out


def test_shortest_facet_cost_falls_back_to_host(monkeypatch):
    n = Node()
    n.alter(schema_text="road: uid .")
    n.mutate(set_nquads="""
        <0x1> <road> <0x2> (w=1) .
        <0x2> <road> <0x3> (w=1) .
        <0x1> <road> <0x3> (w=9) .
    """, commit_now=True)
    from dgraph_tpu.ops import traversal

    def boom(*a, **kw):
        raise AssertionError("device path must not run for facet costs")

    monkeypatch.setattr(traversal, "sssp", boom)
    out, _ = n.query(
        "{ p as shortest(from: 0x1, to: 0x3) { road @facets(w) } "
        "  q(func: uid(p)) { uid } }")
    # weighted: the 2-hop w=1+1 path beats the direct w=9 edge
    assert out["_path_"][0]["_weight_"] == 2.0
    assert [x["uid"] for x in out["q"]] == ["0x1", "0x2", "0x3"]


def test_recurse_edge_dedup_reappearing_node():
    """Node 3 is reached at depth 1 (1->3) and AGAIN at depth 2 via the new
    edge 2->3; edge-level dedup must show it at both levels."""
    n = Node()
    n.alter(schema_text="name: string @index(exact) .\nfollows: [uid] .")
    n.mutate(set_nquads="""
        <0x1> <follows> <0x2> .
        <0x1> <follows> <0x3> .
        <0x2> <follows> <0x3> .
        <0x1> <name> "a" . <0x2> <name> "b" . <0x3> <name> "c" .
    """, commit_now=True)
    out, _ = n.query(
        '{ q(func: uid(0x1)) @recurse(depth: 5) { name follows } }')
    root = out["q"][0]
    by_name = {c["name"]: c for c in root["follows"]}
    assert set(by_name) == {"b", "c"}
    # node c re-appears UNDER b (new edge 0x2->0x3), even though it was
    # already reached directly from the root
    assert [g["name"] for g in by_name["b"].get("follows", [])] == ["c"]


def test_recurse_budget_still_enforced():
    from dgraph_tpu.query import engine as eng

    n = Node()
    n.alter(schema_text="follows: [uid] .")
    quads = [f"<0x{a:x}> <follows> <0x{b:x}> ."
             for a in range(1, 30) for b in range(1, 30) if a != b]
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    old = eng.MAX_QUERY_EDGES
    eng.set_query_edge_limit(10)
    try:
        with pytest.raises(Exception, match="ErrTooBig|edge budget"):
            n.query('{ q(func: uid(0x1)) @recurse(depth: 10) { follows } }')
    finally:
        eng.set_query_edge_limit(old)
