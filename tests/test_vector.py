"""Vector similarity index (ISSUE 8): schema/ingest round-trips, fold +
top-k exactness vs a host float64 scan, delta-overlay stamp/compaction
byte-equivalence, IVF recall, DQL surface, the fused hybrid ANN->graph
pipeline (span-tree verified), mesh-mode equality, and deadline/shed
behavior on large scans."""

import json

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.ops import vector as vops
from dgraph_tpu.storage import vecindex as vx
from dgraph_tpu.query.task import TaskError


def _vec_str(v) -> str:
    return "[" + ", ".join(repr(float(x)) for x in v) + "]"


def _mk_node(dim=8, n=60, metric="l2", seed=3, **kw):
    node = Node(**kw)
    node.alter(schema_text=f"""
        emb: float32vector @index(vector(dim: {dim}, metric: {metric})) .
        friend: [uid] @reverse .
        name: string @index(exact) .
    """)
    rng = np.random.default_rng(seed)
    quads = []
    for i in range(1, n + 1):
        quads.append(
            f'<0x{i:x}> <emb> "{_vec_str(rng.normal(size=dim))}"'
            f'^^<xs:float32vector> .')
        quads.append(f'<0x{i:x}> <name> "p{i}" .')
        for k in range(2):
            t = (i * 7 + k) % n + 1
            if t != i:
                quads.append(f'<0x{i:x}> <friend> <0x{t:x}> .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    return node, rng


# ---------------------------------------------------------------------------
# schema + literals
# ---------------------------------------------------------------------------

def test_schema_vector_roundtrip():
    from dgraph_tpu.utils.schema import parse_schema

    line = "emb: float32vector @index(vector(dim: 16, metric: cosine)) ."
    e = parse_schema(line)[0]
    assert e.vector is not None and e.vector.dim == 16
    assert e.vector.metric == "cosine"
    e2 = parse_schema(str(e))[0]       # WAL persistence round-trip
    assert e2.vector == e.vector and e2.type_id == e.type_id


@pytest.mark.parametrize("bad", [
    "emb: float32vector @index(vector(dim: 0)) .",
    "emb: float32vector @index(vector(metric: cosine)) .",
    "emb: float32vector @index(vector(dim: 4, metric: hamming)) .",
    "emb: int @index(vector(dim: 4)) .",
    "emb: [float32vector] @index(vector(dim: 4)) .",
    "emb: float32vector @index(term) .",
])
def test_schema_vector_rejects(bad):
    from dgraph_tpu.utils.schema import parse_schema

    with pytest.raises(ValueError):
        parse_schema(bad)


def test_vector_literal_parse_and_marshal():
    from dgraph_tpu.utils.types import (TypeID, Val, convert, marshal,
                                        parse_vector, unmarshal)

    v = convert(Val(TypeID.STRING, "[0.25, -1.5, 3]"), TypeID.VECTOR)
    assert v.value == (0.25, -1.5, 3.0)
    assert unmarshal(TypeID.VECTOR, marshal(v)) == v
    with pytest.raises(ValueError):
        parse_vector("[1.0, nan]")
    with pytest.raises(ValueError):
        parse_vector("[]")
    with pytest.raises(ValueError):
        parse_vector([1.0, float("inf")])
    with pytest.raises(ValueError):
        parse_vector("0.5")


def test_mutation_vector_typed_errors():
    from dgraph_tpu.query.mutation import MutationError

    node = Node()
    node.alter(schema_text="emb: float32vector @index(vector(dim: 4)) .")
    node.mutate(set_nquads='<0x1> <emb> "[1, 2, 3, 4]" .', commit_now=True)
    with pytest.raises(MutationError):
        node.mutate(set_nquads='<0x2> <emb> "[1, 2]" .', commit_now=True)
    with pytest.raises(MutationError):
        node.mutate(set_json={"uid": "0x3", "emb": [1.0, float("nan"),
                                                    2.0, 3.0]},
                    commit_now=True)
    # JSON array form lands as ONE vector, not per-element scalars
    node.mutate(set_json={"uid": "0x4", "emb": [4.0, 3.0, 2.0, 1.0]},
                commit_now=True)
    out, _ = node.query('{ q(func: uid(0x4)) { emb } }')
    assert out["q"][0]["emb"] == [4.0, 3.0, 2.0, 1.0]
    node.close()


def test_rdf_vector_roundtrip_and_export():
    import os
    import tempfile

    from dgraph_tpu.loader.export import export_rdf

    node = Node()
    node.alter(schema_text="emb: float32vector @index(vector(dim: 3)) .")
    node.mutate(set_nquads='<0x1> <emb> "[0.5, 1.5, -2]"'
                           '^^<xs:float32vector> .', commit_now=True)
    out, _ = node.query('{ q(func: has(emb)) { emb } }')
    assert out["q"][0]["emb"] == [0.5, 1.5, -2.0]
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "out.rdf")
        export_rdf(node.store, path)
        text = open(path).read()
        assert "xs:float32vector" in text
        # re-import the export: identical value
        node2 = Node()
        node2.alter(schema_text="emb: float32vector "
                                "@index(vector(dim: 3)) .")
        node2.mutate(set_nquads=text, commit_now=True)
        out2, _ = node2.query('{ q(func: has(emb)) { emb } }')
        assert out2 == out
        node2.close()
    node.close()


def test_bulk_load_vectors(tmp_path):
    from dgraph_tpu.loader.bulk import BulkError, bulk_load
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.storage.csr_build import build_snapshot

    rng = np.random.default_rng(11)
    rdf = tmp_path / "v.rdf"
    vecs = {i: rng.normal(size=4) for i in range(1, 21)}
    rdf.write_text("\n".join(
        f'<0x{i:x}> <emb> "{_vec_str(v)}"^^<xs:float32vector> .'
        for i, v in vecs.items()))
    schema = "emb: float32vector @index(vector(dim: 4, metric: l2)) .\n"
    bulk_load(str(rdf), schema, str(tmp_path / "out"))
    st = Store(str(tmp_path / "out"))
    snap = build_snapshot(st, st.max_seen_commit_ts)
    vi = snap.pred("emb").vecindex
    assert vi is not None and vi.n == 20
    q = rng.normal(size=4).astype(np.float32)   # index storage precision
    uids, _d = vx.search(vi, q, 5)
    d = vops.host_distances(
        np.asarray([vecs[i] for i in sorted(vecs)], np.float32)
        .astype(np.float64), q, "l2")
    subs = np.asarray(sorted(vecs), np.int64)
    want = subs[np.lexsort((subs, d))[:5]]
    assert np.array_equal(uids, want)
    st.close()

    bad = tmp_path / "bad.rdf"
    bad.write_text('<0x1> <emb> "[1, 2]"^^<xs:float32vector> .')
    with pytest.raises(BulkError):
        bulk_load(str(bad), schema, str(tmp_path / "out2"))


# ---------------------------------------------------------------------------
# fold + search exactness
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("metric", ["cosine", "l2", "dot"])
def test_topk_exact_vs_host_scan(metric, monkeypatch):
    node, rng = _mk_node(dim=8, n=80, metric=metric, seed=7)
    snap = node.snapshot()
    vi = snap.pred("emb").vecindex
    q = rng.normal(size=8).astype(np.float32)   # index storage precision
    d = vops.host_distances(vi.vecs64(), q.astype(np.float64), metric)
    want = vi.subjects[np.lexsort((vi.subjects, d))[:10]]
    # host-cutover path
    u_host, _ = vx.search(vi, q, 10)
    assert np.array_equal(u_host, want)
    # forced device path: float32 candidates + float64 re-rank must land
    # byte-identical to the host float64 scan
    monkeypatch.setattr(vx, "HOST_SCAN_MAX", 0)
    u_dev, d_dev = vx.search(vi, q, 10)
    assert np.array_equal(u_dev, want)
    assert np.allclose(d_dev, np.sort(d)[:10] if metric != "dot"
                       else d[np.lexsort((vi.subjects, d))[:10]])
    node.close()


def test_overlay_stamp_and_compaction_byte_equivalence():
    from dgraph_tpu.storage.csr_build import build_pred

    node, rng = _mk_node(dim=8, n=50, metric="cosine", seed=9)
    node.snapshot().pred("emb")   # warm the per-predicate fold cache (lazy
    #                               folds build on first read): the next
    #                               commit must STAMP that base, not re-fold
    stamps0 = node.metrics.counter("dgraph_overlay_stamps_total").value
    nv = rng.normal(size=8)
    node.mutate(set_nquads=f'<0x999> <emb> "{_vec_str(nv)}" .',
                commit_now=True)
    snap = node.snapshot()     # assembly stamps the cached base lazily
    vi = snap.pred("emb").vecindex
    assert vi.is_overlay
    assert node.metrics.counter("dgraph_overlay_stamps_total").value > \
        stamps0, "commit must stamp the overlay, not re-fold"
    # stamped view == from-scratch fold at the same read_ts, byte-for-byte
    fresh = build_pred(node.store, "emb", snap.read_ts).vecindex
    assert not fresh.is_overlay and fresh.n == vi.n == 51
    q = rng.normal(size=8)
    u1, d1 = vx.search(vi, q, 12)
    u2, d2 = vx.search(fresh, q, 12)
    assert np.array_equal(u1, u2) and np.array_equal(d1, d2)
    # the new embedding is visible through the overlay
    assert 0x999 in set(vx.search(vi, nv, 1)[0].tolist())
    # deletion via overlay
    node.mutate(del_nquads='<0x999> <emb> * .', commit_now=True)
    snap2 = node.snapshot()
    u3, d3 = vx.search(snap2.pred("emb").vecindex, q, 12)
    assert 0x999 not in set(u3.tolist())
    # compaction folds the overlay back: identical results
    node._assembler.compact(node._lock, force=True)
    snap3 = node.snapshot()
    assert not snap3.pred("emb").vecindex.is_overlay
    u4, d4 = vx.search(snap3.pred("emb").vecindex, q, 12)
    assert np.array_equal(u3, u4) and np.array_equal(d3, d4)
    node.close()


def _clustered_corpus(rng, n, dim, n_clusters=64, noise=0.15):
    """Mixture-of-Gaussians embeddings: the workload IVF exists for (real
    embedding spaces cluster; the coarse quantizer's lists align with the
    clusters, so nprobe lists cover a query's true neighbors)."""
    centers = rng.normal(size=(n_clusters, dim))
    assign = rng.integers(0, n_clusters, size=n)
    return (centers[assign] +
            noise * rng.normal(size=(n, dim))).astype(np.float32), centers


def test_ivf_recall_at_10():
    rng = np.random.default_rng(21)
    n, dim = 5000, 16
    vecs, centers = _clustered_corpus(rng, n, dim)
    subs = np.arange(1, n + 1, dtype=np.int64)
    from dgraph_tpu.utils.schema import VectorSpec

    spec = VectorSpec(dim=dim, metric="l2")
    ivf = vx._build_ivf(vecs, "l2")
    vi = vx.VectorIndex("emb", spec, subs, vecs, ivf)
    assert vi.ivf is not None and vi.ivf.n_lists >= 8
    hits = total = 0
    for i in range(20):
        q = centers[i] + 0.15 * rng.normal(size=dim)
        exact, _ = vx.search(vi, q, 10, exact=True)
        approx, _ = vx.search(vi, q, 10, exact=False)
        hits += len(set(exact.tolist()) & set(approx.tolist()))
        total += 10
    recall = hits / total
    assert recall >= 0.95, f"IVF recall@10 {recall:.3f} < 0.95"


# ---------------------------------------------------------------------------
# DQL surface
# ---------------------------------------------------------------------------

def test_dql_parse_forms():
    from dgraph_tpu.query import dql

    req = dql.parse('{ q(func: similar_to(emb, "[1, 2]", 3)) { uid } }')
    fn = req.queries[0].func
    assert fn.name == "similar_to" and fn.attr == "emb"
    assert fn.args == ["[1, 2]", 3]
    # list-literal and k-first forms
    req2 = dql.parse('{ q(func: similar_to(emb, 3, [1.0, 2.0])) { uid } }')
    assert req2.queries[0].func.args == [3, [1.0, 2.0]]
    # GraphQL variable
    req3 = dql.parse(
        'query q($v: string) { q(func: similar_to(emb, $v, 2)) { uid } }',
        {"$v": "[0.5, 0.5]"})
    assert req3.queries[0].func.args == ["[0.5, 0.5]", 2]
    # filter member
    req4 = dql.parse(
        '{ q(func: has(name)) @filter(similar_to(emb, "[1,2]", 3)) '
        '{ uid } }')
    assert req4.queries[0].filter.func.name == "similar_to"


def test_dql_golden_queries():
    node, rng = _mk_node(dim=4, n=30, metric="l2", seed=13)
    qv = _vec_str([0.5, -0.5, 1.0, 0.0])
    # scores ride val(vector_distance); orderasc sorts by it
    out, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 5), '
        f'orderasc: val(vector_distance)) '
        f'{{ uid d : val(vector_distance) name }} }}')
    assert len(out["q"]) == 5
    ds = [e["d"] for e in out["q"]]
    assert ds == sorted(ds) and all(e["name"] for e in out["q"])
    # composable with filters + pagination
    out2, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 10), first: 3) '
        f'@filter(has(name)) {{ uid }} }}')
    assert len(out2["q"]) == 3
    # filter-member form equals root form intersected with the frontier
    root, _ = node.query(f'{{ q(func: similar_to(emb, "{qv}", 5)) '
                         f'{{ uid }} }}')
    filt, _ = node.query(f'{{ q(func: has(emb)) '
                         f'@filter(similar_to(emb, "{qv}", 5)) '
                         f'{{ uid }} }}')
    assert sorted(e["uid"] for e in root["q"]) == \
        sorted(e["uid"] for e in filt["q"])
    # EXPLAIN costs it like any other root
    out3, _ = node.query(f'{{ q(func: similar_to(emb, "{qv}", 5)) '
                         f'{{ uid }} }}', explain=True)
    r = out3["explain"]["blocks"][0]["root"]
    assert r["source"] == "index probe" and r["est"] == 5
    assert out3["explain"]["stats"]["emb"]["vector"]["rows"] == 30
    node.close()


def test_planner_no_stats_fallback():
    """Regression: a vector predicate with no stats (no data at this
    snapshot) plans cleanly — parse-order execution, no planner crash."""
    node = Node()
    node.alter(schema_text="emb: float32vector @index(vector(dim: 4)) .\n"
                           "name: string @index(exact) .")
    node.mutate(set_nquads='<0x1> <name> "a" .', commit_now=True)
    out, _ = node.query(
        '{ q(func: similar_to(emb, "[1,2,3,4]", 5)) { uid } }',
        explain=True)
    assert out.get("q", []) == []
    assert out["explain"]["planner"] == "on"
    assert node.metrics.counter("dgraph_planner_fallbacks_total").value == 0
    node.close()


def test_stats_vector_entry_no_term_sketch():
    from dgraph_tpu.storage import stats as stmod

    node, _rng = _mk_node(dim=4, n=10, seed=1)
    pd = node.snapshot().pred("emb")
    st = stmod.pred_stats(pd)
    assert st.vector_rows == 10 and st.vector_dim == 4
    d = st.to_dict()
    assert d["vector"] == {"rows": 10, "dim": 4}
    # the vector index never enters the tokenizer-term sketch paths
    assert "vector" not in st.index_terms
    assert "vector" not in st.index_postings
    assert st.value_count == 10          # value-type entry present
    node.close()


# ---------------------------------------------------------------------------
# hybrid pipeline / mesh / deadlines
# ---------------------------------------------------------------------------

def test_fused_ann_pipeline_span_tree_and_equality(monkeypatch):
    monkeypatch.setattr(vx, "HOST_SCAN_MAX", 0)   # force the device class
    node, rng = _mk_node(dim=8, n=60, seed=17, span_sample=1.0)
    qv = _vec_str(rng.normal(size=8))
    q = (f'{{ q(func: similar_to(emb, "{qv}", 6)) '
         f'{{ uid friend {{ name }} }} }}')
    out, _ = node.query(q)
    assert node.metrics.counter(
        "dgraph_vector_fused_pipelines_total").value == 1
    # span tree: ONE device_kernel covers ANN + expansion — no host
    # round trip between the stages
    idx = node.tracer.sink.index()
    rec = node.tracer.sink.get(
        next(r["trace_id"] for r in idx if r["root"] == "query"))
    kernels = [s for s in rec["spans"] if s["name"] == "device_kernel"]
    assert any(s["attrs"].get("kernel") == "vector.ann_expand"
               for s in kernels), [s["attrs"] for s in kernels]
    # byte-identical to the classic stepped path (fusion disabled by a
    # root order arg, which only reorders — so compare uid sets per level)
    node.task_cache = node.result_cache = None
    fused_uids = sorted(e["uid"] for e in out["q"])
    fused_friends = {e["uid"]: sorted(f["name"] for f in e.get("friend", []))
                     for e in out["q"]}
    out2, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 6), '
        f'orderasc: val(vector_distance)) '
        f'{{ uid friend {{ name }} }} }}')
    assert sorted(e["uid"] for e in out2["q"]) == fused_uids
    for e in out2["q"]:
        assert sorted(f["name"] for f in e.get("friend", [])) == \
            fused_friends[e["uid"]]
    node.close()


def test_fused_declines_on_ivf_tablet(monkeypatch):
    """Regression: an IVF-equipped tablet must NOT fuse — the fused
    program is brute-force only, so fusing would make the same root
    return different candidates than the classic (IVF) path depending on
    incidental query shape."""
    monkeypatch.setattr(vx, "HOST_SCAN_MAX", 0)   # size isn't the decliner
    node, rng = _mk_node(dim=8, n=60, seed=17, vector_ivf_min_rows=16)
    assert node.snapshot().pred("emb").vecindex.ivf is not None
    qv = _vec_str(rng.normal(size=8))
    out, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 6)) '
        f'{{ uid friend {{ name }} }} }}')          # the fusable shape
    assert node.metrics.counter(
        "dgraph_vector_fused_pipelines_total").value == 0
    assert node.metrics.counter(
        "dgraph_vector_ivf_probes_total").value >= 1
    # same candidates as a shape that never fused
    node.task_cache = node.result_cache = None
    out2, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 6), '
        f'orderasc: val(vector_distance)) {{ uid }} }}')
    assert sorted(e["uid"] for e in out["q"]) == \
        sorted(e["uid"] for e in out2["q"])
    node.close()


def test_fused_declines_below_host_cutover():
    """Regression: the fused pipeline respects the size-adaptive
    host/device cutover — a tiny tablet answers by host scan + host
    expand, never a jitted device dispatch."""
    node, rng = _mk_node(dim=8, n=60, seed=17)    # 480 cells << cutover
    qv = _vec_str(rng.normal(size=8))
    out, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 6)) '
        f'{{ uid friend {{ name }} }} }}')
    assert out["q"] and node.metrics.counter(
        "dgraph_vector_fused_pipelines_total").value == 0
    node.close()


def test_cosine_ivf_recall_scale_invariant():
    """Regression: the cosine coarse probe must rank lists
    scale-invariantly — a 0.01x query has the same exact answer, so it
    must reach the same lists (the probe used to rank by raw L2)."""
    rng = np.random.default_rng(21)
    n, dim = 5000, 16
    vecs, centers = _clustered_corpus(rng, n, dim)
    # varying norms in the same directions: the failure used to hide on
    # corpora whose rows all have similar norms
    vecs = (vecs * rng.uniform(0.1, 10.0, size=(n, 1))).astype(np.float32)
    subs = np.arange(1, n + 1, dtype=np.int64)
    from dgraph_tpu.utils.schema import VectorSpec

    spec = VectorSpec(dim=dim, metric="cosine")
    vi = vx.VectorIndex("emb", spec, subs, vecs,
                        vx._build_ivf(vecs, "cosine"))
    hits = total = 0
    for i in range(20):
        q = 0.01 * (centers[i] + 0.15 * rng.normal(size=dim))
        exact, _ = vx.search(vi, q, 10, exact=True)
        approx, _ = vx.search(vi, q, 10, exact=False)
        hits += len(set(exact.tolist()) & set(approx.tolist()))
        total += 10
    recall = hits / total
    assert recall >= 0.95, f"cosine IVF recall@10 {recall:.3f} < 0.95"


def test_vector_knobs_scoped_per_node():
    """Regression: Node IVF knobs ride the node's Store into the fold —
    they must not leak to other Nodes in the process via module globals."""
    node_a, _ = _mk_node(dim=4, n=40, seed=41, vector_ivf_min_rows=16)
    assert node_a.snapshot().pred("emb").vecindex.ivf is not None
    assert vx.IVF_MIN_ROWS == 4096          # module default untouched
    node_b, _ = _mk_node(dim=4, n=40, seed=41)
    assert node_b.snapshot().pred("emb").vecindex.ivf is None
    node_a.close()
    node_b.close()


def test_fold_rejects_out_of_range_uid():
    """Regression: a subject past the int32 device uid space must raise
    at fold time (the CSR/value-table contract) instead of silently
    wrapping in the device subject map."""
    from dgraph_tpu.utils.schema import VectorSpec
    from dgraph_tpu.utils.types import TypeID, Val

    spec = VectorSpec(dim=2, metric="l2")
    vals = {1: Val(TypeID.VECTOR, (0.5, 0.5)),
            2**31: Val(TypeID.VECTOR, (1.0, 0.0))}
    with pytest.raises(ValueError, match="device uid space"):
        vx.build_vecindex("emb", spec, vals)


def test_hybrid_ann_filter_recurse():
    node, rng = _mk_node(dim=8, n=40, seed=23)
    qv = _vec_str(rng.normal(size=8))
    out, _ = node.query(
        f'{{ q(func: similar_to(emb, "{qv}", 4)) '
        f'@filter(has(friend)) @recurse(depth: 2) {{ name friend }} }}')
    assert out["q"], out
    for e in out["q"]:
        assert "name" in e
    node.close()


def test_filter_form_exposes_vector_distance():
    """Regression: val(vector_distance) must resolve when similar_to is a
    @filter member (the dependency walk only saw root-form bindings)."""
    node, rng = _mk_node(dim=4, n=20, metric="l2", seed=31)
    qv = _vec_str(rng.normal(size=4))
    out, _ = node.query(
        f'{{ q(func: has(name)) @filter(similar_to(emb, "{qv}", 3)) '
        f'{{ uid d : val(vector_distance) }} }}')
    assert len(out["q"]) == 3 and all("d" in e for e in out["q"]), out
    # second-block filter form resolves too
    out2, _ = node.query(
        f'{{ a(func: uid(0x1)) {{ name }} '
        f'  r(func: has(name)) @filter(similar_to(emb, "{qv}", 2)) '
        f'{{ uid d : val(vector_distance) }} }}')
    assert len(out2["r"]) == 2 and all("d" in e for e in out2["r"]), out2
    node.close()


def test_mesh_nonpow2_devices_and_ivf_precedence(monkeypatch):
    """Regressions: (1) a non-pow2 mesh device count must tile the pow2
    row capacity (ceil-division shards); (2) a mesh-sharded tablet big
    enough to have built IVF must still scan SHARDED — the IVF fine stage
    would upload the full matrix to one device."""
    monkeypatch.setattr(vx, "HOST_SCAN_MAX", 0)
    monkeypatch.setattr(vx, "IVF_MIN_ROWS", 16)    # fold builds IVF
    monkeypatch.setattr(vx, "VECTOR_NPROBE", 64)   # ref IVF scans ALL lists
    q = ('{ q(func: similar_to(emb, "[0.3, -1.0, 0.2, 0.5, 0.0, 1.1, '
         '-0.4, 0.9]", 7), orderasc: val(vector_distance)) '
         '{ uid d : val(vector_distance) } }')
    ref_node, _ = _mk_node(dim=8, n=90, seed=5)
    assert ref_node.snapshot().pred("emb").vecindex.ivf is not None
    ref, _ = ref_node.query(q)
    for nd in (3, 6):
        node, _ = _mk_node(dim=8, n=90, seed=5, mesh_devices=nd,
                           mesh_min_edges=1)
        node.mesh_exec.SHARD_MIN_EDGES = 1
        out, _ = node.query(q)
        assert json.dumps(out, sort_keys=True) == \
            json.dumps(ref, sort_keys=True), nd
        assert node.metrics.counter(
            "dgraph_vector_mesh_dispatches_total").value >= 1, nd
        assert node.metrics.counter(
            "dgraph_vector_ivf_probes_total").value == 0, nd
        node.close()
    ref_node.close()


def test_mesh_mode_equality(monkeypatch):
    monkeypatch.setattr(vx, "HOST_SCAN_MAX", 0)   # force device stage
    q = ('{ q(func: similar_to(emb, "[0.3, -1.0, 0.2, 0.5, 0.0, 1.1, '
         '-0.4, 0.9]", 7), orderasc: val(vector_distance)) '
         '{ uid d : val(vector_distance) } }')
    node1, _ = _mk_node(dim=8, n=120, seed=5)
    out1, _ = node1.query(q)
    node2, _ = _mk_node(dim=8, n=120, seed=5, mesh_devices=8,
                        mesh_min_edges=1)
    node2.mesh_exec.SHARD_MIN_EDGES = 1
    out2, _ = node2.query(q)
    assert json.dumps(out1, sort_keys=True) == \
        json.dumps(out2, sort_keys=True)
    assert node2.metrics.counter(
        "dgraph_vector_mesh_dispatches_total").value >= 1
    node1.close()
    node2.close()


def test_deadline_and_shed_on_large_scan(monkeypatch):
    from dgraph_tpu.utils.deadline import DeadlineExceeded, ResourceExhausted

    monkeypatch.setattr(vx, "HOST_SCAN_MAX", 0)   # force the device scan
    node, rng = _mk_node(dim=8, n=200, seed=29)
    qv = _vec_str(rng.normal(size=8))
    q = f'{{ q(func: similar_to(emb, "{qv}", 10)) {{ uid }} }}'
    node.query(q)                                  # warm (compile) once
    node.task_cache = node.result_cache = None
    with pytest.raises((DeadlineExceeded, ResourceExhausted)):
        node.query(q, timeout_ms=0.000001)
    assert node.metrics.counter("dgraph_deadline_exceeded_total").value \
        + node.metrics.counter("dgraph_shed_total").value >= 1
    node.close()
