"""Cross-process query execution over the internal wire protocol
(reference: worker/task.go:137 ProcessTaskOverNetwork + protos/internal.proto
ServeTask; worker/groups.go:292 BelongsTo routing)."""

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.coord.zero import Zero
from dgraph_tpu.parallel.remote import (NetworkDispatcher, RemoteWorker,
                                        decode_result, decode_task,
                                        encode_result, encode_task,
                                        serve_worker)
from dgraph_tpu.query import dql
from dgraph_tpu.query import mutation as mut
from dgraph_tpu.query import rdf
from dgraph_tpu.query.engine import Executor
from dgraph_tpu.query.task import TaskQuery, TaskResult
from dgraph_tpu.storage.csr_build import build_snapshot
from dgraph_tpu.storage.postings import Op
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val


def _mk_store(schema_text, nquads, ts=1):
    from dgraph_tpu.coord.zero import UidLease
    s = Store()
    for e in parse_schema(schema_text):
        s.set_schema(e)
    edges = mut.to_edges(rdf.parse(nquads),
                         mut.assign_uids(rdf.parse(nquads),
                                         UidLease()), Op.SET)
    touched, _, _ = mut.apply_mutations(s, edges, ts)
    s.commit(ts, ts + 1, touched)
    return s


def test_task_codec_roundtrip():
    q = TaskQuery("friend", frontier=np.array([1, 5, 9], np.int64),
                  func=("eq", ["x", 2]), lang="fr", facet_keys=["w"],
                  first=3)
    q2, ts = decode_task(encode_task(q, 42))
    assert ts == 42 and q2.attr == "friend" and q2.func == ("eq", ["x", 2])
    np.testing.assert_array_equal(q2.frontier, [1, 5, 9])
    res = TaskResult(
        uid_matrix=[np.array([2, 3], np.int64), np.zeros(0, np.int64)],
        value_matrix=[[Val(TypeID.INT, 7)], []],
        facet_matrix=[[(("w", Val(TypeID.FLOAT, 0.5)),)], [()]],
        counts=[2, 0], dest_uids=np.array([2, 3], np.int64),
        traversed_edges=2)
    r2 = decode_result(encode_result(res))
    np.testing.assert_array_equal(r2.uid_matrix[0], [2, 3])
    assert r2.value_matrix[0][0].value == 7
    assert r2.facet_matrix[0][0][0][1].value == 0.5
    assert r2.counts == [2, 0] and r2.traversed_edges == 2


@pytest.fixture(scope="module")
def network():
    """Two groups: names local, ages+follows on a remote worker."""
    # group 1 (remote): age + follows tablets
    g1 = _mk_store("age: int @index(int) .\nfollows: [uid] @reverse .",
                   "\n".join(f'<0x{i:x}> <age> "{20 + i}"^^<xs:int> .'
                             for i in range(1, 6))
                   + '\n<0x1> <follows> <0x2> .\n<0x1> <follows> <0x3> .')
    server, port = serve_worker(g1, "localhost:0")
    # group 0 (local): name tablet
    g0 = _mk_store("name: string @index(exact, term) .",
                   "\n".join(f'<0x{i:x}> <name> "p{i}" .'
                             for i in range(1, 6)))
    zero = Zero(2)
    zero.move_tablet("name", 0)
    zero.move_tablet("age", 1)
    zero.move_tablet("follows", 1)
    remote = RemoteWorker(f"localhost:{port}")
    snap = build_snapshot(g0, read_ts=10)

    # merged schema view for the coordinator
    sch = g0.schema
    for attr in g1.schema.predicates():
        sch.set(g1.schema.get(attr))
    disp = NetworkDispatcher(zero, 0, lambda ts=10: snap,
                             {1: remote}, sch)
    yield disp, sch
    remote.close()
    server.stop(0)


def _run(network, q):
    disp, sch = network
    ex = Executor(disp.local_snap_fn(), sch,
                  dispatch=lambda tq: disp.process_task(tq, 10))
    return ex.execute(dql.parse(q))


def test_remote_root_function(network):
    out = _run(network, '{ q(func: ge(age, 23), orderasc: name) { name } }')
    assert [x["name"] for x in out["q"]] == ["p3", "p4", "p5"]


def test_cross_group_two_hop(network):
    # root resolves locally (name), expansion + value fetch go over the wire
    out = _run(network, '{ q(func: eq(name, "p1")) '
                        '{ name follows { name age } } }')
    assert out["q"][0]["name"] == "p1"
    got = {(f["name"], f["age"]) for f in out["q"][0]["follows"]}
    assert got == {("p2", 22), ("p3", 23)}


def test_remote_reverse_edge(network):
    out = _run(network, '{ q(func: eq(name, "p2")) { ~follows { name } } }')
    assert [x["name"] for x in out["q"][0]["~follows"]] == ["p1"]


def test_remote_filter(network):
    out = _run(network, '{ q(func: has(name), orderasc: name) '
                        '@filter(le(age, 22)) { name age } }')
    assert [(x["name"], x["age"]) for x in out["q"]] == [("p1", 21),
                                                         ("p2", 22)]


def test_matches_single_process(network):
    """The network-routed answer must equal an all-local merged store."""
    disp, sch = network
    merged = _mk_store(
        "name: string @index(exact, term) .\nage: int @index(int) .\n"
        "follows: [uid] @reverse .",
        "\n".join(f'<0x{i:x}> <name> "p{i}" .\n'
                  f'<0x{i:x}> <age> "{20 + i}"^^<xs:int> .'
                  for i in range(1, 6))
        + '\n<0x1> <follows> <0x2> .\n<0x1> <follows> <0x3> .')
    local = Executor(build_snapshot(merged, read_ts=10), merged.schema)
    q = ('{ q(func: ge(age, 22), orderasc: name) '
         '{ name age follows { name } } }')
    assert _run(network, q) == local.execute(dql.parse(q))


def test_remote_sort_key(network):
    # orderasc on a REMOTE tablet (age lives on group 1)
    out = _run(network, '{ q(func: has(name), orderdesc: age, first: 3) '
                        '{ name age } }')
    assert [(x["name"], x["age"]) for x in out["q"]] == [
        ("p5", 25), ("p4", 24), ("p3", 23)]


def test_remote_groupby_value_key(network):
    out = _run(network, '{ q(func: has(name)) @groupby(age) { count(uid) } }')
    groups = {g["age"]: g["count"] for g in out["q"][0]["@groupby"]}
    assert groups == {21: 1, 22: 1, 23: 1, 24: 1, 25: 1}


def test_unreachable_group_errors(network):
    disp, sch = network
    disp.zero.move_tablet("orphan", 1)
    saved = dict(disp.remotes)
    disp.remotes.clear()
    try:
        with pytest.raises(RuntimeError):
            disp.process_task(TaskQuery("orphan", func=("has", [])), 10)
    finally:
        disp.remotes.update(saved)


def test_unknown_predicate_answers_empty(network):
    out = _run(network, '{ q(func: has(never_seen)) { uid } }')
    assert out == {}


# -- write fan-out over the wire (MutateOverNetwork / CommitOverNetwork) ----

@pytest.fixture
def wnet():
    """Fresh 2-group topology with a writable dispatcher."""
    from dgraph_tpu.coord.zero import UidLease
    g0 = _mk_store("name: string @index(exact) .",
                   '<0x1> <name> "p1" .\n<0x2> <name> "p2" .')
    g1 = _mk_store("age: int @index(int) .",
                   '<0x1> <age> "21"^^<xs:int> .')
    server, port = serve_worker(g1, "localhost:0")
    zero = Zero(2)
    zero.oracle.timestamps(8)   # move past seed commit ts
    zero.move_tablet("name", 0)
    zero.move_tablet("age", 1)
    remote = RemoteWorker(f"localhost:{port}")
    sch = g0.schema
    for attr in g1.schema.predicates():
        sch.set(g1.schema.get(attr))

    def snap_fn(ts=None):
        return build_snapshot(g0, read_ts=zero.oracle.read_ts())

    disp = NetworkDispatcher(zero, 0, snap_fn, {1: remote}, sch)
    yield disp, g0, zero
    remote.close()
    server.stop(0)


def _dist_query(disp, zero, q):
    ts = zero.oracle.read_ts()
    ex = Executor(disp.local_snap_fn(), disp.schema,
                  dispatch=lambda tq: disp.process_task(tq, ts))
    return ex.execute(dql.parse(q))


def _dist_mutate(disp, g0, zero, nquads, commit=True):
    st = zero.oracle.new_txn()
    edges = mut.to_edges(rdf.parse(nquads), {}, Op.SET)
    keys_by_group, conflicts, preds = disp.mutate_over_network(
        edges, st.start_ts, g0)
    zero.oracle.track(st.start_ts, conflicts, sorted(preds))
    if commit:
        commit_ts = zero.oracle.commit(st.start_ts)
        disp.decide_over_network(st.start_ts, commit_ts, keys_by_group, g0)
    else:
        zero.oracle.abort(st.start_ts)
        disp.decide_over_network(st.start_ts, 0, keys_by_group, g0)
    return st.start_ts


def test_cross_group_write_commit(wnet):
    disp, g0, zero = wnet
    _dist_mutate(disp, g0, zero,
                 '<0x2> <age> "44"^^<xs:int> .\n<0x3> <name> "p3" .')
    out = _dist_query(disp, zero, '{ q(func: has(name), orderasc: name) '
                                  '{ name age } }')
    assert out["q"] == [{"name": "p1", "age": 21},
                       {"name": "p2", "age": 44}, {"name": "p3"}]


def test_cross_group_write_abort_invisible(wnet):
    disp, g0, zero = wnet
    _dist_mutate(disp, g0, zero, '<0x9> <age> "99"^^<xs:int> .',
                 commit=False)
    out = _dist_query(disp, zero, '{ q(func: ge(age, 90)) { uid } }')
    assert out == {}


def test_remote_conflict_detected(wnet):
    from dgraph_tpu.coord.zero import TxnConflict
    disp, g0, zero = wnet
    st1, st2 = zero.oracle.new_txn(), zero.oracle.new_txn()
    e = mut.to_edges(rdf.parse('<0x1> <age> "30"^^<xs:int> .'), {}, Op.SET)
    k1, c1, p1 = disp.mutate_over_network(e, st1.start_ts, g0)
    k2, c2, p2 = disp.mutate_over_network(
        mut.to_edges(rdf.parse('<0x1> <age> "31"^^<xs:int> .'), {}, Op.SET),
        st2.start_ts, g0)
    zero.oracle.track(st1.start_ts, c1, sorted(p1))
    zero.oracle.track(st2.start_ts, c2, sorted(p2))
    cts = zero.oracle.commit(st1.start_ts)
    disp.decide_over_network(st1.start_ts, cts, k1, g0)
    with pytest.raises(TxnConflict):
        zero.oracle.commit(st2.start_ts)
    disp.decide_over_network(st2.start_ts, 0, k2, g0)
    out = _dist_query(disp, zero, '{ q(func: uid(0x1)) { age } }')
    assert out["q"][0]["age"] == 30


def test_partial_failure_aborts_buffered_slices(wnet):
    disp, g0, zero = wnet
    disp.zero.move_tablet("phantom", 1)
    saved = dict(disp.remotes)
    disp.remotes.clear()     # group 1 unreachable
    st = zero.oracle.new_txn()
    try:
        with pytest.raises(RuntimeError):
            # name slice (local) buffers first, then phantom's group fails
            disp.mutate_over_network(
                mut.to_edges(rdf.parse(
                    '<0x5> <name> "ghost" .\n<0x5> <phantom> "x" .'),
                    {}, Op.SET), st.start_ts, g0)
    finally:
        disp.remotes.update(saved)
    zero.oracle.abort(st.start_ts)
    # the locally-buffered name layer was aborted: nothing leaks into reads
    # and no uncommitted layer remains anywhere in the local store
    out = _dist_query(disp, zero, '{ q(func: eq(name, "ghost")) { uid } }')
    assert out == {}
    assert not any(pl.has_uncommitted() for pl in g0.lists.values())


def test_move_fence_blocks_networked_writes(wnet):
    disp, g0, zero = wnet
    zero.block_writes("age")
    st = zero.oracle.new_txn()
    try:
        with pytest.raises(RuntimeError):
            disp.mutate_over_network(
                mut.to_edges(rdf.parse('<0x1> <age> "50"^^<xs:int> .'),
                             {}, Op.SET), st.start_ts, g0)
    finally:
        zero.unblock_writes("age")
        zero.oracle.abort(st.start_ts)


# -- replication protocol (Append/Promote/Status; worker/draft.go analog) ----

def _mk_replica_trio():
    """Leader + 2 follower WorkerServices with live gRPC servers."""
    from concurrent import futures as _f

    from dgraph_tpu.parallel.remote import WorkerService

    svcs, servers, addrs = [], [], []
    for _ in range(3):
        store = Store()
        for e in parse_schema("v: int ."):
            store.set_schema(e)
        svc = WorkerService(store)
        server = grpc.server(_f.ThreadPoolExecutor(max_workers=4))
        server.add_generic_rpc_handlers((svc.handler(),))
        port = server.add_insecure_port("localhost:0")
        server.start()
        svcs.append(svc)
        servers.append(server)
        addrs.append(f"localhost:{port}")
    return svcs, servers, addrs


def _write_edge(leader_addr, uid, val, ts):
    rw = RemoteWorker(leader_addr)
    from dgraph_tpu.storage.postings import DirectedEdge

    resp = rw.mutate(ts, [DirectedEdge(uid, "v", value=Val(TypeID.INT, val))])
    rw.decide(ts, ts + 1, list(resp.keys))
    rw.close()


def test_lagging_peer_catches_up_from_buffer():
    """A transiently-failing follower is re-fed missed records from the
    leader's buffer on the next ship (per-peer nextIndex semantics)."""
    svcs, servers, addrs = _mk_replica_trio()
    leader, fa, fb = svcs
    rw = RemoteWorker(addrs[0])
    assert rw.promote(1, [addrs[1], addrs[2]]).ok

    # make peer B's transport fail for the next ship only
    pb = leader.peers[1]
    real_append = pb.append
    fails = {"n": 2}     # one txn = mutation record + commit record ships

    def flaky(*a, **kw):
        if fails["n"]:
            fails["n"] -= 1
            raise RuntimeError("transient transport fault")
        return real_append(*a, **kw)

    pb.append = flaky
    _write_edge(addrs[0], 1, 10, ts=10)   # B misses these records
    assert fa.store.max_seen_commit_ts == 11
    assert fb.store.max_seen_commit_ts == 0

    _write_edge(addrs[0], 2, 20, ts=20)   # next ship re-feeds B everything
    assert fb.store.max_seen_commit_ts == 21
    assert fb._last_seq == leader._session_seq
    rw.close()
    for s in servers:
        s.stop(0)


def test_leader_steps_down_without_quorum():
    """NoQuorum steps the leader down: it must not keep minting sequence
    numbers its group never accepted (log-fork guard)."""
    import pytest as _pytest

    from dgraph_tpu.parallel.remote import NoQuorum

    svcs, servers, addrs = _mk_replica_trio()
    leader = svcs[0]
    rw = RemoteWorker(addrs[0])
    assert rw.promote(1, [addrs[1], addrs[2]]).ok
    servers[1].stop(0)
    servers[2].stop(0)

    from dgraph_tpu.storage.postings import DirectedEdge
    from dgraph_tpu.query import mutation as mut

    with _pytest.raises(NoQuorum):
        mut.apply_mutations(leader.store,
                            [DirectedEdge(1, "v", value=Val(TypeID.INT, 1))],
                            5)
    assert not leader.is_leader
    assert leader.store.wal_sink is None
    rw.close()
    servers[0].stop(0)


def test_stale_leader_fenced_by_term():
    """A deposed leader's ship is rejected once a peer saw a higher term."""
    from dgraph_tpu.parallel.remote import StaleLeader

    svcs, servers, addrs = _mk_replica_trio()
    l1, l2, f = svcs
    rw1, rw2 = RemoteWorker(addrs[0]), RemoteWorker(addrs[1])
    assert rw1.promote(1, [addrs[1], addrs[2]]).ok
    _write_edge(addrs[0], 1, 1, ts=2)
    # replica 1 takes over at term 2 (shares follower addrs[2])
    assert rw2.promote(2, [addrs[2]]).ok
    _write_edge(addrs[1], 2, 2, ts=6)

    from dgraph_tpu.storage.postings import DirectedEdge
    from dgraph_tpu.query import mutation as mut
    import pytest as _pytest

    with _pytest.raises(StaleLeader):
        mut.apply_mutations(l1.store,
                            [DirectedEdge(3, "v", value=Val(TypeID.INT, 3))],
                            9)
    assert not l1.is_leader
    rw1.close()
    rw2.close()
    for s in servers:
        s.stop(0)


def test_follower_state_sync_beyond_buffer():
    """A follower that missed more records than the leader's ship buffer
    holds pulls the leader's full state via FetchState and resumes appends
    (retrieveSnapshot analog, worker/draft.go:452)."""
    import tempfile
    import time as _t
    from concurrent import futures as _f

    from dgraph_tpu.parallel.remote import (GRPC_OPTIONS, RemoteWorker,
                                            WorkerService)

    tmp = tempfile.mkdtemp()
    svcs, servers, addrs = [], [], []
    for i in range(3):
        store = Store(f"{tmp}/r{i}")
        for e in parse_schema("v: int ."):
            store.set_schema(e)
        svc = WorkerService(store)
        svc.SHIP_BUFFER = 8            # tiny window to force the sync
        svc._buffer = __import__("collections").deque(maxlen=8)
        server = grpc.server(_f.ThreadPoolExecutor(max_workers=4),
                             options=GRPC_OPTIONS)
        server.add_generic_rpc_handlers((svc.handler(),))
        port = server.add_insecure_port("localhost:0")
        svc.advertise_addr = f"localhost:{port}"
        server.start()
        svcs.append(svc)
        servers.append(server)
        addrs.append(f"localhost:{port}")
    leader, fa, fb = svcs
    rw = RemoteWorker(addrs[0])
    assert rw.promote(1, [addrs[1], addrs[2]]).ok

    # B's transport goes dark for a while
    pb = leader.peers[1]
    real_append = pb.append
    pb.append = lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("down"))
    for i in range(30):                # >> buffer of 8 (2 records per txn)
        _write_edge(addrs[0], i + 1, i, ts=10 + 2 * i)
    assert fa.store.max_seen_commit_ts == 10 + 2 * 29 + 1
    assert fb.store.max_seen_commit_ts == 0

    # B comes back: next ship finds the gap beyond the buffer; B pulls the
    # leader's full state and subsequent appends land normally
    pb.append = real_append
    _write_edge(addrs[0], 99, 99, ts=200)   # gap detected: sync kicks off
    # drive more writes until the post-sync resume lands on B
    deadline = _t.time() + 20
    ts = 400
    while _t.time() < deadline and fb.store.max_seen_commit_ts < 401:
        _write_edge(addrs[0], 101, 101, ts=ts)
        ts += 2
        _t.sleep(0.2)
    assert fb.store.max_seen_commit_ts >= 401, fb.store.max_seen_commit_ts
    assert fb._last_seq == leader._session_seq
    rw.close()
    for s in servers:
        s.stop(0)


def test_in_memory_leader_buffer_never_evicts():
    """An in-memory leader has no files for FetchState, so its promote()
    must install an unbounded ship buffer — the buffer IS the history a
    lagging follower catches up from (review r4)."""
    svcs, servers, addrs = _mk_replica_trio()   # in-memory stores
    leader = svcs[0]
    rw = RemoteWorker(addrs[0])
    assert rw.promote(1, [addrs[1], addrs[2]]).ok
    assert leader._buffer.maxlen is None

    # follower B misses far more than SHIP_BUFFER would hold, then recovers
    leader.SHIP_BUFFER = 8   # would have evicted if maxlen were set
    pb = leader.peers[1]
    real_append = pb.append
    pb.append = lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("down"))
    for i in range(30):
        _write_edge(addrs[0], i + 1, i, ts=10 + 2 * i)
    pb.append = real_append
    # the failed peer backed off; keep writing until its due tick re-feeds
    # the ENTIRE history from the unbounded buffer
    fb = svcs[2]
    ts = 200
    for _ in range(80):
        _write_edge(addrs[0], 99, 99, ts=ts)
        ts += 2
        if fb._last_seq == leader._session_seq:
            break
    assert fb._last_seq == leader._session_seq
    assert fb.store.max_seen_commit_ts == ts - 1
    rw.close()
    for s in servers:
        s.stop(0)


def test_chunked_predicate_data_stream():
    """Predicate moves stream in <=max_bytes chunks with a resumable cursor
    (reference predicate_move.go:187 <=32MB batches) and the destination
    returns applied counts (the :171-176 count handshake)."""
    src = _mk_store("name: string @index(exact, term) .",
                    "\n".join(f'<0x{i:x}> <name> "person{i}" .'
                              for i in range(1, 60)))
    server, port = serve_worker(src, "localhost:0")
    rw = RemoteWorker(f"localhost:{port}")
    try:
        full = rw.predicate_data("name", read_ts=10, start_ts=100)
        assert full.done and not full.next
        assert len(full.records) > 60        # data + index rows + schema

        records, keys, chunks = [], [], 0
        cursor = b""
        while True:
            resp = rw.predicate_data("name", 10, 100, after=cursor,
                                     max_bytes=256)
            records.extend(bytes(r) for r in resp.records)
            keys.extend(bytes(k) for k in resp.keys)
            chunks += 1
            if resp.done:
                assert not resp.next
                break
            assert resp.next
            cursor = bytes(resp.next)
        assert chunks > 3, "chunking did not engage"
        assert records == [bytes(r) for r in full.records]
        assert keys == [bytes(k) for k in full.keys]

        # count handshake: destination reports exactly what it applied
        dst = _mk_store("name: string @index(exact, term) .",
                        '<0x1> <name> "seed" .')
        server2, port2 = serve_worker(dst, "localhost:0")
        rw2 = RemoteWorker(f"localhost:{port2}")
        try:
            ingested = 0
            for lo in range(0, len(records), 7):
                ingested += rw2.ingest_records(records[lo: lo + 7])
            assert ingested == len(records)
        finally:
            rw2.close()
            server2.stop(0)
    finally:
        rw.close()
        server.stop(0)
