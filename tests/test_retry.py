"""Request-lifeline primitives (ISSUE 7): deadlines, the unified
RetryPolicy, circuit breakers, and the seeded fault-injection registry.

Reference analog: context deadlines + x/x.go retry loops + conn/pool
health state; the chaos-side registry is our stand-in for the reference's
systest process kills."""

import random
import threading
import time

import pytest

from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import faults
from dgraph_tpu.utils.deadline import (Deadline, DeadlineExceeded,
                                       ResourceExhausted)
from dgraph_tpu.utils.retry import (CircuitBreaker, CommitAmbiguous,
                                    RetryPolicy, backoff_s)


# -- deadlines ---------------------------------------------------------------

def test_deadline_remaining_and_check():
    d = Deadline(0.2)
    assert 0 < d.remaining() <= 0.2
    d.check()                     # not expired: no raise
    d.expires = time.monotonic() - 0.01
    assert d.expired()
    with pytest.raises(DeadlineExceeded):
        d.check("unit")


def test_deadline_clamp():
    d = Deadline(1.0)
    assert d.clamp(0.1) == pytest.approx(0.1, abs=0.01)
    assert d.clamp(None) == pytest.approx(1.0, abs=0.05)
    d.expires = time.monotonic() - 1
    assert d.clamp(5.0) == 0.0


def test_scope_installs_and_restores():
    assert dl.current() is None
    with dl.scope(0.5):
        assert dl.current() is not None
        assert dl.remaining() > 0
    assert dl.current() is None
    assert dl.remaining() is None
    # None budget = no-op scope
    with dl.scope(None):
        assert dl.current() is None


def test_nested_scope_never_extends():
    """A callee's default budget cannot outlive its caller's deadline."""
    with dl.scope(0.05):
        outer = dl.current()
        with dl.scope(10.0):
            assert dl.current() is outer    # tighter bound wins
        with dl.scope(0.01):
            assert dl.current() is not outer


def test_metadata_round_trip():
    with dl.scope(0.5):
        md = dl.to_metadata()
        assert md[0] == dl.WIRE_KEY
        got = dl.from_metadata([md])
        assert got is not None
        assert 0 < got.remaining() <= 0.5
    assert dl.to_metadata() is None
    assert dl.from_metadata([("other", "1")]) is None
    assert dl.from_metadata([(dl.WIRE_KEY, "junk")]) is None


def test_module_clamp_and_check():
    assert dl.clamp(3.0) == 3.0          # unbudgeted: identity
    dl.check()                           # unbudgeted: no-op
    with dl.scope(0.2):
        assert dl.clamp(3.0) <= 0.2
        assert dl.clamp(0.01) <= 0.01


# -- retry policy ------------------------------------------------------------

def test_backoff_full_jitter_bounds():
    rng = random.Random(3)
    for attempt in range(6):
        for _ in range(50):
            s = backoff_s(attempt, base_s=0.05, cap_s=0.4, rng=rng)
            assert 0 <= s <= min(0.4, 0.05 * 2 ** attempt)


def test_retry_retries_transport_then_succeeds():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("flaky")
        return "ok"

    p = RetryPolicy(max_attempts=5, base_s=0.001, cap_s=0.002,
                    rng=random.Random(1))
    assert p.run(fn) == "ok"
    assert len(calls) == 3


def test_retry_exhausts_and_raises_last():
    p = RetryPolicy(max_attempts=3, base_s=0.001, cap_s=0.002,
                    rng=random.Random(1))
    calls = []

    def fn():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        p.run(fn)
    assert len(calls) == 3


def test_retry_programming_error_not_retried():
    """Only transport shapes retry — a bug surfaces on the first throw."""
    calls = []

    def fn():
        calls.append(1)
        raise KeyError("bug")

    p = RetryPolicy(max_attempts=5, base_s=0.001)
    with pytest.raises(KeyError):
        p.run(fn)
    assert len(calls) == 1


def test_retry_abort_on_and_ambiguous_never_retried():
    for exc in (CommitAmbiguous("?"), DeadlineExceeded("late")):
        calls = []

        def fn():
            calls.append(1)
            raise exc

        p = RetryPolicy(max_attempts=5, base_s=0.001)
        with pytest.raises(type(exc)):
            p.run(fn)
        assert len(calls) == 1, type(exc).__name__


def test_retry_respects_deadline():
    """A retry whose backoff sleep would blow the deadline surfaces the
    cause instead of sleeping past it."""
    calls = []

    def fn():
        calls.append(1)
        raise ConnectionError("down")

    p = RetryPolicy(max_attempts=50, base_s=0.05, cap_s=0.05,
                    rng=random.Random(2))
    t0 = time.monotonic()
    with dl.scope(0.08):
        with pytest.raises(ConnectionError):
            p.run(fn)
    assert time.monotonic() - t0 < 0.5
    assert len(calls) < 50


def test_retry_on_retry_hook():
    seen = []

    def fn():
        if len(seen) < 1:
            raise ConnectionError("x")
        return 1

    p = RetryPolicy(max_attempts=3, base_s=0.001)
    assert p.run(fn, on_retry=lambda e: seen.append(type(e).__name__)) == 1
    assert seen == ["ConnectionError"]


# -- circuit breaker ---------------------------------------------------------

def _clocked_breaker(**kw):
    clk = [0.0]
    br = CircuitBreaker(clock=lambda: clk[0], **kw)
    return br, clk


def test_breaker_trips_after_consecutive_failures():
    br, _ = _clocked_breaker(fail_threshold=3, open_s=5.0)
    assert br.state == CircuitBreaker.CLOSED
    br.record(False)
    br.record(False)
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    br.record(False)
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()


def test_breaker_success_resets_streak():
    br, _ = _clocked_breaker(fail_threshold=2, open_s=5.0)
    br.record(False)
    br.record(True)
    br.record(False)
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_half_open_probe_then_close_or_reopen():
    br, clk = _clocked_breaker(fail_threshold=1, open_s=2.0)
    br.record(False)
    assert br.state == CircuitBreaker.OPEN
    clk[0] = 2.5
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow()           # the single probe
    assert not br.allow()       # second request still rejected
    br.record(False)            # probe failed: re-open
    assert br.state == CircuitBreaker.OPEN
    clk[0] = 5.0
    assert br.allow()
    br.record(True)             # probe succeeded: close
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow() and br.allow()


def test_breaker_stale_probe_token_expires():
    """A granted half-open probe whose request never reports back must
    not wedge the breaker: the token expires after open_s."""
    br, clk = _clocked_breaker(fail_threshold=1, open_s=2.0)
    br.record(False)
    clk[0] = 2.5
    assert br.allow()            # probe granted, caller then vanishes
    assert not br.allow()
    clk[0] = 5.0                 # token expired: a fresh probe is admitted
    assert br.allow()


def test_breaker_latency_counts_as_soft_failure():
    br, _ = _clocked_breaker(fail_threshold=2, open_s=5.0,
                             latency_threshold_s=0.1)
    br.record(True, latency_s=0.5)
    br.record(True, latency_s=0.5)
    assert br.state == CircuitBreaker.OPEN


# -- fault registry ----------------------------------------------------------

def test_fault_registry_error_mode_is_transport_shaped():
    r = faults.FaultRegistry(seed=1)
    r.install("p", "error")
    with pytest.raises(ConnectionError):
        r.fire("p")


def test_fault_registry_deterministic_schedule():
    """The same seed replays the same fire/skip sequence."""

    def schedule(seed):
        r = faults.FaultRegistry(seed=seed)
        r.install("p", "error", p=0.5)
        out = []
        for _ in range(64):
            try:
                r.fire("p")
                out.append(0)
            except faults.FaultError:
                out.append(1)
        return out

    a, b = schedule(42), schedule(42)
    assert a == b
    assert 0 < sum(a) < 64            # actually probabilistic
    assert schedule(43) != a          # and seed-dependent


def test_fault_registry_count_budget_and_clear():
    r = faults.FaultRegistry()
    r.install("p", "error", count=2)
    for _ in range(2):
        with pytest.raises(faults.FaultError):
            r.fire("p")
    r.fire("p")                       # budget exhausted: no-op
    assert r.snapshot()["points"]["p"]["fired"] == 2
    r.clear("p")
    r.fire("p")
    r.install("a", "error")
    r.install("b", "error")
    r.clear()
    r.fire("a")
    r.fire("b")


def test_fault_registry_delay_and_drop():
    r = faults.FaultRegistry()
    r.install("slow", "delay", delay_s=0.05)
    t0 = time.monotonic()
    r.fire("slow")                    # sleeps, returns
    assert time.monotonic() - t0 >= 0.05
    r.install("hole", "drop", delay_s=0.02)
    t0 = time.monotonic()
    with pytest.raises(faults.FaultError):
        r.fire("hole")
    assert time.monotonic() - t0 >= 0.02


def test_fault_registry_spec_parse():
    r = faults.FaultRegistry(seed=9)
    r.configure("a:error:0.25, b:delay:1.0:0.2:3 ,c:drop")
    snap = r.snapshot()["points"]
    assert snap["a"] == {"mode": "error", "p": 0.25, "delay_s": 0.0,
                         "remaining": None, "fired": 0}
    assert snap["b"]["mode"] == "delay" and snap["b"]["delay_s"] == 0.2 \
        and snap["b"]["remaining"] == 3
    assert snap["c"]["mode"] == "drop"
    with pytest.raises(ValueError):
        r.configure("justaname")
    with pytest.raises(ValueError):
        r.install("x", "explode")


def test_fault_registry_unknown_point_never_fires():
    r = faults.FaultRegistry()
    r.install("somewhere.else", "error")
    r.fire("worker.serve_task")       # installed name differs: no-op


def test_fault_fire_counts_metric():
    from dgraph_tpu.utils.metrics import Registry

    m = Registry()
    r = faults.FaultRegistry()
    r.install("p", "error")
    with pytest.raises(faults.FaultError):
        r.fire("p", m=m)
    assert m.counter("dgraph_fault_injected_total").value == 1


# -- gate shedding (deadline-aware bounded queue) ---------------------------

def test_gate_unbudgeted_behavior_unchanged():
    from dgraph_tpu.query.qcache import DispatchGate

    g = DispatchGate(2)
    assert g.run(lambda: 7) == 7
    assert g.expected_step_s > 0      # EWMA primed


def test_gate_budget_exhausted_raises_typed():
    from dgraph_tpu.query.qcache import DispatchGate

    g = DispatchGate(1)
    ev = threading.Event()
    t = threading.Thread(target=lambda: g.run(lambda: ev.wait(2.0)))
    t.start()
    time.sleep(0.05)
    try:
        t0 = time.monotonic()
        with dl.scope(0.1):
            with pytest.raises(DeadlineExceeded):
                g.run(lambda: 1)
        assert time.monotonic() - t0 < 1.0   # bounded, not the full wait
        # overrun ACCOUNTING is owned by the request entry points (Node/
        # ClusterClient) — the gate itself only raises, never counts
        assert g.metrics.counter("dgraph_deadline_exceeded_total").value == 0
    finally:
        ev.set()
        t.join()


def test_gate_sheds_when_budget_below_expected_step():
    from dgraph_tpu.query.qcache import DispatchGate

    g = DispatchGate(1)
    g._step_ewma = 5.0                # expected device step >> budget
    ev = threading.Event()
    t = threading.Thread(target=lambda: g.run(lambda: ev.wait(2.0)))
    t.start()
    time.sleep(0.05)
    try:
        with dl.scope(0.2):
            with pytest.raises(ResourceExhausted):
                g.run(lambda: 1)
        assert g.metrics.counter("dgraph_shed_total").value == 1
    finally:
        ev.set()
        t.join()


def test_gate_queue_bound_sheds():
    from dgraph_tpu.query.qcache import DispatchGate

    g = DispatchGate(1, max_queue=0)
    ev = threading.Event()
    t = threading.Thread(target=lambda: g.run(lambda: ev.wait(2.0)))
    t.start()
    time.sleep(0.05)
    try:
        with dl.scope(5.0):
            with pytest.raises(ResourceExhausted):
                g.run(lambda: 1)
    finally:
        ev.set()
        t.join()
