"""Zero coordinator durability (reference assign.go:65-125 lease blocks +
Raft-persisted tablet map): a restarted Zero must never re-issue a
timestamp or uid it could already have handed out, and keeps its tablet
assignments; a crash skips at most one lease block."""

import pytest

from dgraph_tpu.coord.zero import LEASE_BLOCK, Zero


def test_restart_never_reissues_leases(tmp_path):
    d = str(tmp_path / "z")
    z = Zero(n_groups=2, dirpath=d)
    issued_ts = [z.oracle.new_txn().start_ts for _ in range(5)]
    issued_ts.append(z.oracle.timestamps(3))
    s, e = z.uids.assign(1000)
    assert z.should_serve("name") in (0, 1)
    z.move_tablet("age", 1)
    g_name = z.tablets()["name"]

    z2 = Zero(n_groups=2, dirpath=d)
    # monotonic past everything possibly issued (may burn <= one block)
    nt = z2.oracle.new_txn().start_ts
    assert nt > max(issued_ts)
    assert nt <= max(issued_ts) + 2 * LEASE_BLOCK
    s2, _ = z2.uids.assign(10)
    assert s2 > e
    # tablet map survived
    assert z2.tablets() == {"name": g_name, "age": 1}


def test_restart_after_many_blocks(tmp_path):
    d = str(tmp_path / "z")
    z = Zero(dirpath=d)
    # cross several persist blocks
    last = 0
    for _ in range(5):
        last = z.oracle.timestamps(LEASE_BLOCK // 2 + 7)
    hw = z.oracle.max_assigned
    z2 = Zero(dirpath=d)
    assert z2.oracle.new_txn().start_ts > hw


def test_memory_only_zero_unchanged():
    z = Zero()
    a = z.oracle.new_txn().start_ts
    b = z.oracle.new_txn().start_ts
    assert b == a + 1


def test_commit_ts_covered_by_ceiling(tmp_path):
    """Commit timestamps also cross the persisted ceiling (review r4: the
    commit mutator must be covered, not just new_txn/timestamps)."""
    import json
    import os

    d = str(tmp_path / "z")
    z = Zero(dirpath=d)
    # drive max_assigned right up to the ceiling using commits only
    sts = [z.oracle.new_txn() for _ in range(8)]
    for st in sts:
        z.oracle.track(st.start_ts, [b"k%d" % st.start_ts])
    commit_ts = [z.oracle.commit(st.start_ts) for st in sts]
    with open(os.path.join(d, "zero_state.json")) as f:
        ceiling = json.load(f)["ts_ceiling"]
    assert ceiling > max(commit_ts)
    z2 = Zero(dirpath=d)
    assert z2.oracle.new_txn().start_ts > max(commit_ts)


def test_double_restart_keeps_ceilings(tmp_path):
    """A restart that issues NOTHING before the next crash must still
    protect everything the previous incarnation issued (review r4: the
    restored ceilings were written back as 0)."""
    d = str(tmp_path / "z")
    z = Zero(dirpath=d)
    issued = [z.oracle.new_txn().start_ts for _ in range(3)]
    z.uids.assign(50)
    z2 = Zero(dirpath=d)      # restart 1: serves nothing
    z3 = Zero(dirpath=d)      # restart 2
    assert z3.oracle.new_txn().start_ts > max(issued)
    s, _ = z3.uids.assign(1)
    assert s > 50
