"""Chaos harness (ISSUE 7 tentpole gate): the mixed query battery under
seeded randomized fault schedules on a 2-group wire cluster.

The contract under test — the request-lifeline layer's whole point:
every request either returns BYTE-IDENTICAL results (json, sort_keys) or
a TYPED error (DeadlineExceeded / ResourceExhausted / CommitAmbiguous /
grpc status / transport error) *within its deadline* — zero hangs (global
watchdog on every worker thread), zero wrong results.

Schedules: flaky/slow transport (seeded fault points at the serve/send
seams), worker crash mid-fan-out (real server stop + restart recovery),
Zero leader kill mid-commit (degraded reads + typed write failures).
Determinism: the fault registry's PRNG is seeded per schedule, so a
failing run replays."""

import json
import threading
import time

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.coord.zero import Zero
from dgraph_tpu.coord.zero_service import serve_zero
from dgraph_tpu.parallel.client import ClusterClient
from dgraph_tpu.parallel.remote import serve_worker
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils import deadline as dl_mod
from dgraph_tpu.utils import faults
from dgraph_tpu.utils.deadline import DeadlineExceeded, ResourceExhausted
from dgraph_tpu.utils.retry import CommitAmbiguous
from dgraph_tpu.utils.schema import parse_schema

SCHEMA = """
    name: string @index(exact) .
    age: int @index(int) .
    follows: [uid] @reverse .
"""

# the typed-error contract: anything else raised by a request is a bug
TYPED_ERRORS = (DeadlineExceeded, ResourceExhausted, CommitAmbiguous,
                grpc.RpcError, ConnectionError, OSError, RuntimeError)

# mixed battery: eq root, hop, reverse hop, int-index filter, has+first
BATTERY = [
    '{ q(func: eq(name, "p1")) { name age } }',
    '{ q(func: eq(name, "p1")) { name follows { name age } } }',
    '{ q(func: eq(name, "p3")) { name ~follows { name } } }',
    '{ q(func: ge(age, 25)) { name } }',
    '{ q(func: has(name), first: 4) { name follows { name } } }',
]

WATCHDOG_SLACK_S = 3.0      # wire + scheduling slack on top of a deadline


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.GLOBAL.clear()
    yield
    faults.GLOBAL.clear()


@pytest.fixture(autouse=True)
def _lockdep_armed():
    """The whole chaos suite runs with lockdep ARMED (ISSUE 14): every
    lock the cluster constructs is instrumented, and any order-inversion
    cycle observed during the schedules raises at the acquisition that
    closed it — plus a belt-and-braces teardown assert that the run
    recorded zero violations."""
    from dgraph_tpu.utils import locks

    locks.reset()
    locks.arm(raise_on_cycle=True)
    yield
    vs = locks.violations()
    locks.disarm()
    locks.reset()
    assert vs == [], f"lock-order violations under chaos: {vs}"


@pytest.fixture
def cluster():
    """2 worker groups + zero over real loopback gRPC; name/age on group
    0, follows on group 1, so hop queries fan to both."""
    zero = Zero(2)
    zero.move_tablet("name", 0)
    zero.move_tablet("age", 0)
    zero.move_tablet("follows", 1)
    zsrv, zport, _ = serve_zero(zero, "localhost:0")
    stores, workers = [], []
    for _g in range(2):
        s = Store()
        for e in parse_schema(SCHEMA):
            s.set_schema(e)
        stores.append(s)
        workers.append(serve_worker(s, "localhost:0"))
    client = ClusterClient(
        f"localhost:{zport}",
        {g: [f"localhost:{workers[g][1]}"] for g in range(2)},
        default_timeout_ms=4000)
    nq = []
    for i in range(8):
        nq.append(f'_:p{i} <name> "p{i}" .')
        nq.append(f'_:p{i} <age> "{20 + i}"^^<xs:int> .')
    for i in range(7):
        nq.append(f"_:p{i} <follows> _:p{i + 1} .")
    client.mutate(set_nquads="\n".join(nq))
    yield client, zsrv, workers, stores
    client.close()
    for w, _p in workers:
        try:
            w.stop(0)
        except Exception:
            pass
    try:
        zsrv.stop(0)
    except Exception:
        pass


def _expected(client) -> list[str]:
    """Fault-free golden outputs, canonicalized."""
    out = []
    for q in BATTERY:
        client.task_cache.clear()
        out.append(json.dumps(client.query(q), sort_keys=True))
    return out


def _run_one(client, q, golden, deadline_ms, outcomes):
    t0 = time.monotonic()
    try:
        client.task_cache.clear()      # force the wire every time
        got = json.dumps(client.query(q, timeout_ms=deadline_ms),
                         sort_keys=True)
        dt = time.monotonic() - t0
        outcomes.append({"q": q, "status": "ok", "dt": dt,
                         "identical": got == golden})
    except TYPED_ERRORS as e:
        outcomes.append({"q": q, "status": type(e).__name__,
                         "dt": time.monotonic() - t0, "identical": None})
    except BaseException as e:                      # untyped = bug
        outcomes.append({"q": q, "status": f"UNTYPED:{type(e).__name__}",
                         "dt": time.monotonic() - t0, "identical": None})


def _battery_round(client, golden, deadline_ms, threads_per_q=1):
    """One concurrent battery pass under the global watchdog. Returns the
    outcome records; asserts the lifeline contract on every one."""
    outcomes: list[dict] = []
    threads = []
    for qi, q in enumerate(BATTERY):
        for _ in range(threads_per_q):
            threads.append(threading.Thread(
                target=_run_one,
                args=(client, q, golden[qi], deadline_ms, outcomes)))
    for t in threads:
        t.start()
    budget = deadline_ms / 1000.0 + WATCHDOG_SLACK_S
    stop_by = time.monotonic() + budget
    for t in threads:
        t.join(timeout=max(stop_by - time.monotonic(), 0.1))
    # global watchdog: a hung request fails here, not by wedging CI
    hung = [t for t in threads if t.is_alive()]
    assert not hung, f"{len(hung)} requests hung past deadline+slack"
    assert len(outcomes) == len(threads)
    for o in outcomes:
        assert o["dt"] <= budget, f"overran watchdog budget: {o}"
        if o["status"] == "ok":
            assert o["identical"], f"WRONG RESULT under faults: {o}"
        else:
            assert not o["status"].startswith("UNTYPED"), \
                f"untyped error escaped: {o}"
    return outcomes


def test_flaky_transport_schedule(cluster):
    """Seeded random errors+delays at the serve/send seams: every request
    completes byte-identical or typed within its deadline."""
    client, _zsrv, _workers, _stores = cluster
    golden = _expected(client)
    faults.GLOBAL.reseed(1234)
    faults.GLOBAL.install("worker.serve_task", "error", p=0.2)
    faults.GLOBAL.install("rpc.send", "delay", p=0.2, delay_s=0.05)
    all_out = []
    for _round in range(3):
        all_out += _battery_round(client, golden, deadline_ms=3000,
                                  threads_per_q=2)
    oks = sum(1 for o in all_out if o["status"] == "ok")
    # the schedule must not be all-fail (the seed fixes the fault
    # SEQUENCE; which request draws each value shifts with thread
    # interleaving, so the ok-count itself has variance — keep the floor
    # conservative, the real gate is the zero-hang/zero-wrong contract)
    assert oks >= len(all_out) // 4, all_out
    assert faults.GLOBAL.snapshot()["points"]["worker.serve_task"]["fired"] > 0


def test_slow_transport_is_deadline_bounded(cluster):
    """A blackholed-slow worker costs exactly the budget: every request
    resolves typed within deadline+slack, and full service returns the
    moment the fault lifts."""
    client, _zsrv, _workers, _stores = cluster
    golden = _expected(client)
    faults.GLOBAL.install("worker.serve_task", "delay", p=1.0, delay_s=1.0)
    out = _battery_round(client, golden, deadline_ms=300)
    # nothing can finish under a 1s injected delay with a 300ms budget
    assert all(o["status"] != "ok" for o in out), out
    assert all(o["dt"] < 300 / 1000 + WATCHDOG_SLACK_S for o in out)
    faults.GLOBAL.clear()
    out = _battery_round(client, golden, deadline_ms=4000)
    assert all(o["status"] == "ok" and o["identical"] for o in out), out


def test_worker_crash_mid_fanout_and_recovery(cluster):
    """Kill group 1's worker mid-battery: requests settle byte-identical
    or typed; after a restart on the same port the battery is fully
    byte-identical again (channel reconnect + echo re-poll)."""
    client, _zsrv, workers, stores = cluster
    golden = _expected(client)
    crash_at = threading.Event()

    def crasher():
        crash_at.wait(0.05)
        workers[1][0].stop(0)          # group 1 (follows) dies mid-fan-out

    t = threading.Thread(target=crasher)
    t.start()
    crash_at.set()
    for _round in range(2):
        _battery_round(client, golden, deadline_ms=2500)
    t.join()
    # group 0 tablets (name/age, no hop) must still serve byte-identical
    out = _battery_round(client, golden, deadline_ms=2500)
    by_q = {o["q"]: o for o in out}
    assert by_q[BATTERY[0]]["status"] == "ok"       # eq(name) — group 0
    assert by_q[BATTERY[3]]["status"] == "ok"       # ge(age) — group 0
    # restart the worker on the SAME port: the stubs reconnect
    port1 = workers[1][1]
    for attempt in range(20):
        try:
            workers[1] = serve_worker(stores[1], f"localhost:{port1}")
            break
        except RuntimeError:
            time.sleep(0.1)
    else:
        pytest.skip("could not rebind worker port after stop")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        out = _battery_round(client, golden, deadline_ms=3000)
        if all(o["status"] == "ok" and o["identical"] for o in out):
            break
        time.sleep(0.2)
    else:
        pytest.fail(f"battery never fully recovered: {out}")


def test_zero_leader_kill_mid_commit(cluster):
    """Kill Zero while a write stream runs: writes fail TYPED (ambiguous
    commits included), reads degrade to byte-identical stale serving —
    never a hang, never a wrong result."""
    client, zsrv, _workers, _stores = cluster
    golden = _expected(client)
    write_outcomes: list[str] = []
    stop = threading.Event()

    def writer():
        i = 100
        while not stop.is_set():
            try:
                client.mutate(set_nquads=f'_:w{i} <name> "w{i}" .',
                              retries=2, timeout_ms=1500)
                write_outcomes.append("ok")
            except TYPED_ERRORS as e:
                write_outcomes.append(type(e).__name__)
            except BaseException as e:
                write_outcomes.append(f"UNTYPED:{type(e).__name__}")
            i += 1

    wt = threading.Thread(target=writer)
    wt.start()
    time.sleep(0.15)
    zsrv.stop(0)                      # the oracle dies mid-stream
    time.sleep(0.3)
    stop.set()
    wt.join(timeout=15.0)
    assert not wt.is_alive(), "write stream hung after Zero death"
    assert write_outcomes, "writer never ran"
    assert not any(o.startswith("UNTYPED") for o in write_outcomes), \
        write_outcomes
    # golden outputs include only pre-kill commits the battery never saw
    # mid-flight; a degraded read of that data stays byte-identical —
    # except writes that landed during the stream changed has(name)
    # results, so compare only the stable shapes
    stable = [0, 1, 2, 3]
    client.task_cache.clear()
    for qi in stable:
        got = json.dumps(client.query(BATTERY[qi], timeout_ms=3000),
                         sort_keys=True)
        if write_outcomes.count("ok") == 0:
            assert got == golden[qi]
    assert client.last_degraded is None or client.last_degraded["degraded"]


def test_deterministic_fault_schedule_replays():
    """Same seed, same sequential request stream => same outcome sequence
    (the debuggability contract of the seeded registry)."""

    def one_run(seed):
        zero = Zero(1)
        zsrv, zport, _ = serve_zero(zero, "localhost:0")
        s = Store()
        for e in parse_schema(SCHEMA):
            s.set_schema(e)
        wsrv, wport = serve_worker(s, "localhost:0")
        client = ClusterClient(f"localhost:{zport}",
                               {0: [f"localhost:{wport}"]})
        client.mutate(set_nquads='_:a <name> "ann" .')
        faults.GLOBAL.clear()
        faults.GLOBAL.reseed(seed)
        faults.GLOBAL.install("worker.serve_task", "error", p=0.5)
        outcomes = []
        for _i in range(12):
            client.task_cache.clear()
            try:
                client.query('{ q(func: eq(name, "ann")) { name } }',
                             timeout_ms=2000)
                outcomes.append("ok")
            except TYPED_ERRORS as e:
                outcomes.append(type(e).__name__)
        faults.GLOBAL.clear()
        client.close()
        wsrv.stop(0)
        zsrv.stop(0)
        return outcomes

    a = one_run(7)
    b = one_run(7)
    assert a == b
    assert "ok" in a        # the schedule is not all-fail
    assert len(set(a)) > 1  # ... and not all-ok


def test_move_killed_mid_chunk_aborts_clean_then_retries():
    """Placement satellite (ISSUE 10): a tablet move killed mid-chunk
    (fault point move.chunk_ship) must resume-or-abort — here abort: the
    partial copy is reaped, the map never flips, every read before /
    during / after is byte-identical — and a retry with the fault lifted
    completes the move with reads still byte-identical."""
    from dgraph_tpu.coord.zero_service import ZeroOps

    zero = Zero(2)
    zero.move_tablet("name", 0)
    zero.move_tablet("age", 0)
    zsrv, zport, svc = serve_zero(zero, "localhost:0")
    stores, workers = [], []
    for g in range(2):
        s = Store()
        for e in parse_schema(SCHEMA):
            s.set_schema(e)
        stores.append(s)
        workers.append(serve_worker(s, "localhost:0"))
        svc._members[g] = [f"localhost:{workers[g][1]}"]
    client = ClusterClient(
        f"localhost:{zport}",
        {g: [f"localhost:{workers[g][1]}"] for g in range(2)})
    try:
        client.mutate(set_nquads="\n".join(
            f'_:p{i} <name> "p{i}" .\n_:p{i} <age> "{20 + i}"^^<xs:int> .'
            for i in range(24)))
        q = '{ q(func: eq(name, "p7")) { name age } }'

        def read():
            client.task_cache.clear()
            return json.dumps(client.query(q), sort_keys=True)

        golden = read()
        ops = ZeroOps(svc)
        ops.chunk_bytes = 256          # force MANY chunks through the wire
        # seeded schedule: some chunks ship, then the stream dies
        faults.GLOBAL.reseed(77)
        faults.GLOBAL.install("move.chunk_ship", "error", p=0.5)
        moved = None
        with pytest.raises(ConnectionError):
            for _ in range(64):        # p=0.5: dies within a few chunks
                moved = ops.move_tablet("name", 1)
                faults.GLOBAL.clear("move.chunk_ship")  # pragma: no cover
                break
        assert moved is None
        assert faults.GLOBAL.snapshot()["points"][
            "move.chunk_ship"]["fired"] >= 1
        # aborted clean: map never flipped, source authoritative, reads
        # byte-identical, and the partial copy's buffered txn was reaped
        # on the destination (no uncommitted layer survives the abort)
        assert zero.tablets()["name"] == 0
        assert read() == golden
        assert not any(pl.has_uncommitted()
                       for pl in stores[1].lists.values())
        faults.GLOBAL.clear()
        # retry completes (chunked stream restarts from the cursor start)
        out = ops.move_tablet("name", 1)
        assert out["tablet"] == "name" and out["moved_records"] > 0
        assert zero.tablets()["name"] == 1
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                if read() == golden:
                    break
            except TYPED_ERRORS:
                pass               # stale-map fence: retry with fresh state
            time.sleep(0.1)
        assert read() == golden
    finally:
        faults.GLOBAL.clear()
        client.close()
        for w, _p in workers:
            w.stop(0)
        zsrv.stop(0)


def test_lifeline_metrics_on_http_metrics():
    """The new lifeline metrics render on /metrics and prom-parse clean
    (satellite: prom-parse-checked exposition)."""
    import urllib.request

    from dgraph_tpu.api.http import make_server
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.obs import prom

    node = Node(default_timeout_ms=0)
    node.alter(schema_text="name: string @index(exact) .")
    node.mutate(set_nquads='_:a <name> "x" .', commit_now=True)
    srv = make_server(node, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        # drive one shed so the counters are live, not just registered
        from dgraph_tpu.query.qcache import DispatchGate

        gate = DispatchGate(1, node.metrics)
        gate._step_ewma = 30.0
        ev = threading.Event()
        t = threading.Thread(target=lambda: gate.run(lambda: ev.wait(2.0)))
        t.start()
        time.sleep(0.05)
        with pytest.raises(ResourceExhausted):
            with dl_mod.scope(0.2):
                gate.run(lambda: 1)
        ev.set()
        t.join()
        # HTTP surface: ?timeoutMs= maps typed errors to typed statuses
        req = urllib.request.Request(
            base + "/query?timeoutMs=2000",
            data=b'{ q(func: eq(name, "x")) { name } }', method="POST")
        assert json.loads(urllib.request.urlopen(req, timeout=10).read())[
            "data"]["q"] == [{"name": "x"}]
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        series = prom.parse(text)
        for name in ("dgraph_retry_total", "dgraph_shed_total",
                     "dgraph_deadline_exceeded_total",
                     "dgraph_hedge_fired_total",
                     "dgraph_breaker_open_total",
                     "dgraph_degraded_reads_total",
                     "dgraph_fault_injected_total"):
            assert name in series, name
        assert series["dgraph_shed_total"][0][1] >= 1
        assert "# TYPE dgraph_breaker_state gauge" in text
        # /debug/faults round-trip: install over HTTP, observe, clear
        req = urllib.request.Request(
            base + "/debug/faults",
            data=json.dumps({"seed": 5, "install": {
                "name": "device.dispatch", "mode": "error",
                "count": 1}}).encode(), method="POST")
        snap = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert snap["points"]["device.dispatch"]["mode"] == "error"
        # cached replays bypass the dispatch gate — force a real dispatch
        node.task_cache.clear()
        node.result_cache.clear()
        req = urllib.request.Request(
            base + "/query", data=b'{ q(func: eq(name, "x")) { name } }',
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400     # FaultError -> invalid-request
        req = urllib.request.Request(
            base + "/debug/faults", data=json.dumps({"clear": True}).encode(),
            method="POST")
        assert json.loads(urllib.request.urlopen(
            req, timeout=5).read())["points"] == {}
    finally:
        faults.GLOBAL.clear()
        srv.shutdown()
        node.close()


def test_eviction_storm_under_write_mix():
    """ISSUE 11 chaos schedule: an embedded node with a device budget
    ~10x smaller than the graph, a seeded residency.h2d_upload fault
    (10%), and a 10% write mix hammering OTHER predicates — an eviction
    storm with failing promotions underneath. Contract: every read of the
    static predicates is byte-identical or typed, no hangs."""
    import threading as _th

    import numpy as np

    from dgraph_tpu.api.server import Node
    from dgraph_tpu.query import task as taskmod
    from dgraph_tpu.storage import residency as resmod

    preds = [f"e{i:02d}" for i in range(8)]
    write_pred = "wp"

    def build(budget: bool):
        n = Node(task_cache_mb=0, result_cache_mb=0, planner=False)
        n.alter(schema_text="\n".join(
            [f"{p}: [uid] ." for p in preds] + [f"{write_pred}: [uid] ."]))
        rng = np.random.default_rng(31)
        nq = []
        for p in preds:
            for i in range(1, 33):
                for t in rng.choice(32, 6, replace=False) + 1:
                    nq.append(f"<{i:#x}> <{p}> <{int(t):#x}> .")
        n.mutate(set_nquads="\n".join(nq), commit_now=True)
        if budget:
            total = sum(resmod.pred_host_nbytes(pd)
                        for pd in n.snapshot().preds.values())
            # ~10x the budget, floored above one ~1KB tablet so
            # promotion/eviction churn (not pure cold serving) happens
            n.residency.budget = max(total // 10, 2048)
        return n

    old_cut = taskmod.HOST_EXPAND_MAX
    taskmod.HOST_EXPAND_MAX = 8          # force the device tier
    clean = build(budget=False)
    node = build(budget=True)
    queries = [f"{{ q(func: has({p})) {{ {p} {{ uid }} }} }}"
               for p in preds]
    try:
        golden = [json.dumps(clean.query(q)[0], sort_keys=True)
                  for q in queries]
        faults.GLOBAL.reseed(4242)
        faults.GLOBAL.install("residency.h2d_upload", "error", p=0.1)
        outcomes: list[dict] = []
        stop = _th.Event()

        def reader(qi):
            rng = np.random.default_rng(qi)
            while not stop.is_set():
                i = int(rng.integers(len(queries)))
                t0 = time.monotonic()
                try:
                    got = json.dumps(
                        node.query(queries[i], timeout_ms=4000)[0],
                        sort_keys=True)
                    outcomes.append({"status": "ok",
                                     "identical": got == golden[i],
                                     "dt": time.monotonic() - t0})
                except TYPED_ERRORS as e:
                    outcomes.append({"status": type(e).__name__,
                                     "identical": None,
                                     "dt": time.monotonic() - t0})
                except BaseException as e:
                    outcomes.append(
                        {"status": f"UNTYPED:{type(e).__name__}",
                         "identical": None,
                         "dt": time.monotonic() - t0})

        def writer():
            # ~10% write mix against a predicate the readers never touch
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    node.mutate(
                        set_nquads=f"<{i % 32 + 1:#x}> <{write_pred}> "
                                   f"<{i % 31 + 1:#x}> .",
                        commit_now=True)
                except TYPED_ERRORS:
                    pass
                time.sleep(0.01)

        threads = [_th.Thread(target=reader, args=(qi,))
                   for qi in range(4)] + [_th.Thread(target=writer)]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        deadline = time.monotonic() + 8.0
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        hung = [t for t in threads if t.is_alive()]
        assert not hung, f"{len(hung)} request threads hung"
        assert outcomes, "no requests completed"
        for o in outcomes:
            if o["status"] == "ok":
                assert o["identical"], f"WRONG READ under storm: {o}"
            else:
                assert not o["status"].startswith("UNTYPED"), \
                    f"untyped error escaped: {o}"
            assert o["dt"] <= 4.0 + WATCHDOG_SLACK_S, o
        m = node.residency.metrics
        # the storm actually stormed: promotions + failures both happened
        assert m.counter("dgraph_residency_admissions_total").value > 0
        assert faults.GLOBAL.snapshot()[
            "points"]["residency.h2d_upload"]["fired"] > 0
    finally:
        taskmod.HOST_EXPAND_MAX = old_cut
        faults.GLOBAL.clear()
        clean.close()
        node.close()


def test_group_commit_chaos_wal_fault_and_kill_mid_window(tmp_path):
    """ISSUE 16 chaos schedule: concurrent committers through FORCED
    commit windows under a seeded disk.wal_write fault, then a hard kill
    (the journal as it sits on disk, no clean close) and replay, then a
    torn group-record tail. Contract: every acked commit is durably
    visible after replay; every failed commit is typed (TxnConflict /
    CommitAmbiguous / fault transport error); a multi-key txn is NEVER
    torn — both its predicates replay or neither — and a torn gc tail
    drops whole. Lockdep is armed for the run (autouse fixture)."""
    import shutil

    from dgraph_tpu.api.server import Node
    from dgraph_tpu.storage.writebatch import WriteBatcher
    from dgraph_tpu.utils.faults import FaultError

    d = tmp_path / "primary"
    d.mkdir()
    node = Node(dirpath=str(d))
    node.alter(schema_text="name: string @index(exact) .\n"
                           "age: int @index(int) .")
    # never idle-fire: every commit joins a real multi-member window
    node.write_batcher = WriteBatcher(
        node.zero.oracle, node.store, node.metrics,
        window_ms=50.0, max_batch=8, idle_fire=False)

    faults.GLOBAL.reseed(1616)
    faults.GLOBAL.install("disk.wal_write", "error", p=0.3)
    acked: dict[int, int] = {}        # subject uid -> commit_ts
    failures: list[BaseException] = []
    lock = threading.Lock()

    def commit_one(uid):
        # one txn, TWO predicates: the torn-write probe — after replay
        # the subject has BOTH name and age or NEITHER
        try:
            r = node.mutate(set_nquads=(
                f'<0x{uid:x}> <name> "p{uid}" .\n'
                f'<0x{uid:x}> <age> "{uid}"^^<xs:int> .'))
            ts = node.commit(r.context.start_ts)
            with lock:
                acked[uid] = ts
        except (CommitAmbiguous, FaultError, ConnectionError, OSError) as e:
            with lock:
                failures.append(e)
        except TYPED_ERRORS as e:
            with lock:
                failures.append(e)

    uid = 0
    try:
        for _round in range(6):
            threads = []
            for _ in range(8):
                uid += 1
                threads.append(threading.Thread(target=commit_one,
                                                args=(uid,)))
            for t in threads:
                t.start()
            stop_by = time.monotonic() + 30.0
            for t in threads:
                t.join(timeout=max(stop_by - time.monotonic(), 0.1))
            hung = [t for t in threads if t.is_alive()]
            assert not hung, f"{len(hung)} committers hung mid-window"
    finally:
        faults.GLOBAL.clear()

    assert acked, "fault schedule starved every window (p=0.3 seed drift)"
    assert failures, "fault schedule never fired on the group append"

    # HARD KILL mid-stream: copy the journal as it sits on disk right now
    # (acked windows are fsynced; nothing about the kill is clean) and
    # replay it into a fresh store — the node object is simply abandoned.
    killed = tmp_path / "killed"
    shutil.copytree(d, killed)
    n2 = Node(dirpath=str(killed))
    out, _ = n2.query('{ q(func: has(name)) { uid name age } }')
    rows = {int(x["uid"], 16): x for x in out.get("q", [])}
    for u, _ts in acked.items():
        assert u in rows, f"acked commit 0x{u:x} lost by replay"
        assert rows[u]["name"] == f"p{u}" and rows[u]["age"] == u
    # never torn: any replayed subject (acked or ambiguous-but-landed)
    # carries BOTH predicates of its single commit record
    out_age, _ = n2.query('{ q(func: has(age)) { uid } }')
    assert {int(x["uid"], 16) for x in out_age.get("q", [])} == \
        set(rows), "torn commit: name and age diverged after replay"
    n2.close()

    # TORN TAIL: truncate the copied journal mid-way through its LAST
    # record — replay must drop the whole gc record (no member partially
    # applied), keeping every earlier record intact.
    torn = tmp_path / "torn"
    shutil.copytree(killed, torn)
    wal = torn / "wal.log"
    raw = wal.read_bytes()
    import struct as _struct
    off, frames = 0, []
    while off + 4 <= len(raw):
        (ln,) = _struct.unpack_from("<I", raw, off)
        frames.append((off, 4 + ln))
        off += 4 + ln
    last_off, last_len = frames[-1]
    wal.write_bytes(raw[: last_off + 4 + max(last_len - 4 - 2, 1)])
    n3 = Node(dirpath=str(torn))
    out3, _ = n3.query('{ q(func: has(name)) { uid name age } }')
    rows3 = {int(x["uid"], 16) for x in out3.get("q", [])}
    out3a, _ = n3.query('{ q(func: has(age)) { uid } }')
    assert {int(x["uid"], 16) for x in out3a.get("q", [])} == rows3, \
        "torn tail partially applied a window member"
    assert rows3 <= set(rows)          # only whole records survived
    n3.close()
    node.close()


# -- live queries under chaos (ISSUE 18) -------------------------------------
# The subscription contract under faults: a client receives a TYPED
# resync event and converges to the correct result — never a silent gap,
# never a stale feed. Lockdep stays armed via the module fixture: the
# notifier's lock (live.LiveManager._lock) must stay acyclic against the
# store/gate/batcher locks it composes with.

def test_live_eval_fault_typed_resync_then_convergence():
    """Seeded kill of the re-evaluation seam mid-subscription (the
    embedded analog of a worker crash during the fan-out): the notifier
    must retry with backoff and, once the seam heals, deliver a typed
    resync whose result is byte-identical at its watermark."""
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.live.diff import canon

    n = Node()
    try:
        for e in parse_schema(SCHEMA):
            n.store.set_schema(e)
        n.mutate(set_nquads='<0x1> <name> "p1" .', commit_now=True)
        q = "{ q(func: has(name)) { uid name } }"
        sub = n.subscribe(q)
        assert sub.next(5)["type"] == "init"
        faults.GLOBAL.reseed(31)
        faults.GLOBAL.install("device.dispatch", "error", p=1.0)
        n.mutate(set_nquads='<0x2> <name> "p2" .', commit_now=True)
        # the wake is pending but every re-eval dies at the dispatch gate
        assert sub.next(0.9) is None
        assert n.live.stats()["pending"] == 1
        faults.GLOBAL.clear()
        ev = sub.next(10)
        assert ev is not None and ev["type"] == "resync", ev
        assert ev["reason"] == "error"
        assert {e2["name"] for e2 in ev["result"]["q"]} == {"p1", "p2"}
        rerun = n.query(q, start_ts=ev["at"], read_only=True)[0]
        assert canon(ev["result"]) == canon(rerun)
        assert n.live.stats()["pending"] == 0
        sub.cancel()
    finally:
        faults.GLOBAL.clear()
        n.close()


def _p99(samples_s: list[float]) -> float:
    xs = sorted(samples_s)
    return xs[int(0.99 * (len(xs) - 1))]


def test_noisy_neighbor_tenant_schedule():
    """ISSUE 20 chaos schedule: one abusive tenant offering >=100x the
    device time its quota grants, under a seeded device.step delay (every
    dispatch holds its gate slot for the injected step — the device is
    genuinely scarce). Contract: the well-behaved tenants' p99 degrades
    < 10% vs their solo baseline under the SAME fault schedule, and every
    response — the hog's included — is byte-identical or typed. The QoS
    edge (cost-metered admission off the ledger the injected step charges
    into) is what makes that hold: once the hog's burst is burned its
    requests shed typed ResourceExhausted before touching the device."""
    from dgraph_tpu import tenancy as tnc
    from dgraph_tpu.api.server import Node

    GOOD = ("good1", "good2")
    node = Node(task_cache_mb=0, result_cache_mb=0,   # force the gate
                tenants={"tenants": {
                    "good1": {"weight": 1.0},
                    "good2": {"weight": 1.0},
                    # ~30ms of burst against ~40ms/request of injected
                    # device time: the first dispatch lands the hog in
                    # debt it refills out of in ~30s — locked out, typed
                    "hog": {"weight": 1.0, "device_ms_per_s": 1.0,
                            "burst_s": 30.0},
                }})
    for t in GOOD + ("hog",):
        with tnc.scope(t):
            node.alter(schema_text="name: string @index(exact) .")
            node.mutate(set_nquads="\n".join(
                f'<0x{i:x}> <name> "{t}-{i}" .' for i in range(1, 9)),
                commit_now=True)
    tq = "{ q(func: has(name), first: 8) { name } }"

    def run_query(tenant: str) -> str:
        with tnc.scope(tenant):
            return json.dumps(node.query(tq)[0], sort_keys=True)

    golden = {t: run_query(t) for t in GOOD + ("hog",)}
    N = 30
    bad: list[str] = []

    def battery(tenant, lat):
        for _ in range(N):
            t0 = time.perf_counter()
            try:
                got = run_query(tenant)
                lat.append(time.perf_counter() - t0)
                if got != golden[tenant]:
                    bad.append(f"{tenant}: WRONG RESULT")
            except TYPED_ERRORS:
                lat.append(time.perf_counter() - t0)
            except BaseException as e:
                bad.append(f"{tenant}: UNTYPED:{type(e).__name__}")

    def run_phase(lat):
        ths = [threading.Thread(target=battery, args=(t, lat))
               for t in GOOD]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=60.0)
        assert not any(th.is_alive() for th in ths), "battery hung"

    stop = threading.Event()
    hog_stats = {"attempts": 0, "granted": 0}
    hlock = threading.Lock()

    def hog():
        while not stop.is_set():
            try:
                got = run_query("hog")
                with hlock:
                    hog_stats["attempts"] += 1
                    hog_stats["granted"] += 1
                if got != golden["hog"]:
                    bad.append("hog: WRONG RESULT")
            except TYPED_ERRORS:
                with hlock:
                    hog_stats["attempts"] += 1
            except BaseException as e:
                bad.append(f"hog: UNTYPED:{type(e).__name__}")
            time.sleep(0.0015)     # offered load, not a GIL-spin DoS

    try:
        # every dispatch holds its slot for the injected device step; the
        # seed pins the schedule (p=1.0 makes it deterministic anyway)
        faults.GLOBAL.reseed(2020)
        faults.GLOBAL.install("device.step", "delay", p=1.0, delay_s=0.02)

        # SOLO baseline: the good tenants alone, same fault schedule
        base_lat: list[float] = []
        run_phase(base_lat)

        # unleash the hog, burn its burst BEFORE the measured window so
        # its one granted dispatch's slot time never overlaps it
        hogs = [threading.Thread(target=hog) for _ in range(2)]
        for th in hogs:
            th.start()
        time.sleep(0.5)

        chaos_lat: list[float] = []
        run_phase(chaos_lat)
        step_fired = faults.GLOBAL.snapshot()[
            "points"]["device.step"]["fired"]
    finally:
        stop.set()
        for th in hogs:
            th.join(timeout=10.0)
        faults.GLOBAL.clear()
        node.close()

    assert not bad, bad
    assert len(base_lat) == len(chaos_lat) == N * len(GOOD)
    # the abusive tenant really offered >=100x what the meter granted ...
    granted = hog_stats["granted"]
    assert hog_stats["attempts"] >= 100 * max(granted, 1), hog_stats
    assert hog_stats["attempts"] > granted, "hog was never shed"
    # ... every refusal typed AND booked against the tenant ...
    shed = node.metrics.keyed("dgraph_tenant_shed_total").get("hog")
    assert shed >= hog_stats["attempts"] - granted > 0
    assert step_fired > 0
    # ... and the bystanders barely felt it: p99 degraded < 10%
    p99b, p99c = _p99(base_lat), _p99(chaos_lat)
    assert p99c <= p99b * 1.10, \
        f"noisy neighbor leaked through QoS: p99 {p99b:.4f}s -> {p99c:.4f}s"


def test_live_journal_overflow_mid_subscription_wire_cluster():
    """Journal overflow mid-subscription on the 2-group embedded wire
    topology: the overflowed predicate's subscribers get a typed
    `overflow` resync and converge; an untouched-predicate subscriber
    sees nothing. Lockdep armed throughout (manager lock vs the cluster
    commit path)."""
    from dgraph_tpu.coord.cluster import Cluster
    from dgraph_tpu.live.diff import canon

    cl = Cluster(n_groups=2)
    try:
        for st in cl.stores:
            st.MAX_DELTA_KEYS = 4          # force overflow cheaply
            for e in parse_schema(SCHEMA):
                st.set_schema(e)
        cl.mutate(set_nquads='<0x1> <name> "p1" .')
        q = "{ q(func: has(name)) { uid name } }"
        sub = cl.subscribe(q)
        assert sub.next(5)["type"] == "init"
        q_age = "{ a(func: has(age)) { uid age } }"
        bystander = cl.subscribe(q_age)
        assert bystander.next(5)["type"] == "init"
        # one commit touching >4 distinct `name` keys overflows group 0's
        # journal inside the commit critical section
        quads = "\n".join(f'<0x{i + 16:x}> <name> "o{i}" .'
                          for i in range(8))
        cl.mutate(set_nquads=quads)
        ev = sub.next(10)
        assert ev is not None and ev["type"] == "resync", ev
        assert ev["reason"] == "overflow"
        assert len(ev["result"]["q"]) == 1 + 8
        rerun = cl.query(q, read_ts=ev["at"])
        assert canon(ev["result"]) == canon(rerun)
        # the untouched predicate's subscription saw no event at all
        assert bystander.next(0.8) is None
        sub.cancel()
        bystander.cancel()
    finally:
        cl.close()
