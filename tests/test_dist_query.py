"""Distributed execution of REAL queries: the full DQL path over the mesh.

Round-2 verdict item 3: process_task runs single-device and nothing consults
the tablet map at query time. Here every uid-predicate expand runs SPMD over
a virtual 2/4/8-device mesh (parallel/worker.distribute_snapshot +
dist.DistPredCSR), routed by the Zero tablet map, and the JSON output is
diffed against the single-device Executor. Reference: worker/task.go:137
ProcessTaskOverNetwork + worker/groups.go:292 BelongsTo.
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.parallel.mesh import make_mesh
from dgraph_tpu.parallel.worker import distribute_snapshot, group_submesh
from dgraph_tpu.query import dql
from dgraph_tpu.query.engine import Executor


@pytest.fixture(scope="module")
def node():
    n = Node()
    n.alter(schema_text="""
        name: string @index(exact, term) .
        age: int @index(int) .
        follows: [uid] @reverse @count .
        likes: [uid] .
    """)
    rng = np.random.default_rng(7)
    people = [f'_:p{i} <name> "person{i}" .\n'
              f'_:p{i} <age> "{20 + i % 40}"^^<xs:int> .'
              for i in range(60)]
    edges = []
    for i in range(60):
        for j in sorted(rng.choice(60, size=4, replace=False)):
            if i != j:
                edges.append(f"_:p{i} <follows> _:p{j} .")
        if i % 3 == 0:
            edges.append(f"_:p{i} <likes> _:p{(i * 7 + 1) % 60} .")
    n.mutate(set_nquads="\n".join(people + edges), commit_now=True)
    return n


QUERIES = [
    # 2-hop expansion with a filter — the verdict's named target
    '{ q(func: eq(name, "person3")) { name follows @filter(ge(age, 25)) '
    '{ name follows { name age } } } }',
    # root index function + has-filter + count
    '{ q(func: ge(age, 55)) @filter(has(likes)) { name count(follows) } }',
    # reverse edges
    '{ q(func: eq(name, "person5")) { name ~follows { name } } }',
    # sort + pagination over an indexed predicate
    '{ q(func: has(follows), orderasc: age, first: 7, offset: 3) { name age } }',
    # recurse directive
    '{ q(func: eq(name, "person1")) @recurse(depth: 3) { name follows } }',
    # var propagation across blocks
    '{ a as var(func: eq(name, "person2")) { f as follows }\n'
    '  q(func: uid(f)) @filter(NOT uid(a)) { name } }',
]


@pytest.mark.parametrize("n_devices", [2, 4, 8])
@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_dist_query_matches_single_device(node, n_devices, qi):
    q = QUERIES[qi]
    single, _ = node.query(q)
    mesh = make_mesh(n_devices)
    dsnap = distribute_snapshot(node.snapshot(), mesh, node.zero)
    dist_out = Executor(dsnap, node.store.schema).execute(dql.parse(q))
    assert dist_out == single


def test_tablet_routing_to_group_submeshes(node):
    """With n_groups=2 on an 8-device mesh, predicates land on disjoint
    4-device submeshes per the Zero tablet map, and results still match."""
    mesh = make_mesh(8)
    zero2 = type(node.zero)(n_groups=2)
    dsnap = distribute_snapshot(node.snapshot(), mesh, zero2)
    tablets = zero2.tablets()
    assert set(tablets.values()) == {0, 1}, tablets
    meshes = {attr: dsnap.preds[attr].csr.mesh
              for attr in tablets if dsnap.preds[attr].csr is not None}
    seen_devsets = {frozenset(d.id for d in m.devices.ravel())
                    for m in meshes.values()}
    assert len(seen_devsets) == 2
    assert all(len(s) == 4 for s in seen_devsets)
    q = QUERIES[0]
    single, _ = node.query(q)
    dist_out = Executor(dsnap, node.store.schema).execute(dql.parse(q))
    assert dist_out == single


def test_group_submesh_layout():
    mesh = make_mesh(8)
    subs = [group_submesh(mesh, 2, g) for g in range(2)]
    ids = [sorted(d.id for d in m.devices.ravel()) for m in subs]
    assert ids[0] + ids[1] == sorted(d.id for d in mesh.devices.ravel())
    # degenerate: too few devices per group -> whole-mesh passthrough identity
    m2 = make_mesh(2)
    assert group_submesh(m2, 2, 0) is m2
