"""Mesh deployment mode on the virtual 8-device CPU mesh (conftest forces
`XLA_FLAGS=--xla_force_host_platform_device_count=8`; this module is a
no-op anywhere that fixture is absent).

Covers the ISSUE-6 acceptance gates: shard_csr padding/sentinel rows,
dist_k_hop program reuse, the fused multi-hop chain executing as ONE
device dispatch (vs one per hop on the per-task path), and mesh-mode
results byte-identical to the single-device executor on the golden query
corpus (tests/golden/expected.json — the same battery the wire cluster is
diffed against in contrib/scripts/smoke_mesh.sh)."""

import json
import os

import numpy as np
import pytest
import jax

from dgraph_tpu.api.server import Node
from dgraph_tpu.parallel import dist
from dgraph_tpu.parallel.mesh import make_mesh
from dgraph_tpu.query.engine import set_query_edge_limit

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest-provided 8-virtual-device CPU mesh")


# ---------------------------------------------------------------------------
# shard_csr: padding / sentinel invariants
# ---------------------------------------------------------------------------

def _toy_csr():
    # 5 subject rows over 8 shards: shards 5-7 are pure padding
    subjects = np.asarray([2, 5, 7, 11, 13], dtype=np.int32)
    indptr = np.asarray([0, 2, 3, 6, 6, 8], dtype=np.int32)
    indices = np.asarray([5, 7, 2, 1, 5, 9, 2, 7], dtype=np.int32)
    return subjects, indptr, indices


def test_shard_csr_padding_and_sentinel_rows():
    subjects, indptr, indices = _toy_csr()
    mesh = make_mesh(8)
    sh = dist.shard_csr(subjects, indptr, indices, mesh)
    assert sh.n_shards == 8
    sub = np.asarray(sh.subjects)
    ptr = np.asarray(sh.indptr)
    idx = np.asarray(sh.indices)
    snt = int(dist.SNT)
    # every shard is padded to the same row/edge capacity
    assert sub.shape == (8, 1) and ptr.shape == (8, 2)
    assert idx.shape[0] == 8
    for s in range(8):
        if s < 5:
            assert sub[s, 0] == subjects[s]
            deg = int(indptr[s + 1] - indptr[s])
            assert ptr[s, 1] - ptr[s, 0] == deg
            got = idx[s, : deg]
            np.testing.assert_array_equal(got, indices[indptr[s]: indptr[s + 1]])
            # padding beyond the shard's real edges is sentinel
            assert (idx[s, deg:] == snt).all()
        else:
            # pure padding shard: sentinel subject, zero degree, sentinel edges
            assert sub[s, 0] == snt
            assert (ptr[s] == 0).all()
            assert (idx[s] == snt).all()
    # row 3 has zero degree (indptr[3] == indptr[4]): its shard's ptr is flat
    assert ptr[3, 0] == ptr[3, 1] == 0


def test_expand_matrix_matches_host_and_stages_frontier():
    subjects, indptr, indices = _toy_csr()
    mesh = make_mesh(8)
    csr = dist.DistPredCSR(subjects, indptr, indices, mesh)
    uids = np.asarray([2, 7, 11, 99], dtype=np.int64)   # 11: empty row, 99: missing
    matrix, total = csr.expand_matrix(uids)
    assert total == 5
    np.testing.assert_array_equal(matrix[0], [5, 7])
    np.testing.assert_array_equal(matrix[1], [1, 5, 9])
    assert len(matrix[2]) == 0 and len(matrix[3]) == 0
    # the merged dest set is staged on device: replaying it skips the upload
    staged_uids, staged_dev = csr._staged
    np.testing.assert_array_equal(staged_uids, [1, 5, 7, 9])
    m2, _ = csr.expand_matrix(staged_uids)
    # rows must match the host mirrors exactly
    host = {int(s): indices[indptr[i]: indptr[i + 1]].tolist()
            for i, s in enumerate(subjects)}
    for u, row in zip(staged_uids, m2):
        np.testing.assert_array_equal(row, host.get(int(u), []))


def test_expand_program_cached_across_calls():
    subjects, indptr, indices = _toy_csr()
    mesh = make_mesh(8)
    csr = dist.DistPredCSR(subjects, indptr, indices, mesh)
    csr.expand_matrix(np.asarray([2, 5], dtype=np.int64))
    before = dist._expand_program.cache_info()
    for _ in range(3):
        csr.expand_matrix(np.asarray([2, 5], dtype=np.int64))
    after = dist._expand_program.cache_info()
    assert after.misses == before.misses       # no rebuild per call
    assert after.hits > before.hits


def test_dist_k_hop_program_cached():
    rng = np.random.default_rng(5)
    from tests.test_dist import build_host_csr
    from dgraph_tpu.ops import uidset as us

    subjects, indptr, indices = build_host_csr(rng, 200, 1500)
    mesh = make_mesh(8)
    sh = dist.shard_csr(subjects, indptr, indices, mesh)
    seeds = us.make_set([0, 3], capacity=8)
    r1 = dist.dist_k_hop(sh, seeds, mesh, hops=2, frontier_cap=512,
                         num_nodes=200)
    before = dist._k_hop_program.cache_info()
    r2 = dist.dist_k_hop(sh, seeds, mesh, hops=2, frontier_cap=512,
                         num_nodes=200)
    after = dist._k_hop_program.cache_info()
    assert after.misses == before.misses
    np.testing.assert_array_equal(np.asarray(r1[1]), np.asarray(r2[1]))


# ---------------------------------------------------------------------------
# mesh-mode Node vs single-device executor
# ---------------------------------------------------------------------------

from tests.test_golden import QUERIES as GOLDEN_QUERIES  # noqa: E402
from tests.test_golden import SCHEMA as GOLDEN_SCHEMA  # noqa: E402
from tests.test_golden import GOLDEN_PATH, _dataset  # noqa: E402


@pytest.fixture(scope="module")
def mesh_node():
    n = Node(mesh_devices=8, mesh_min_edges=1)
    n.alter(schema_text=GOLDEN_SCHEMA)
    n.mutate(set_nquads=_dataset(), commit_now=True)
    return n


def test_mesh_golden_corpus_byte_identical(mesh_node):
    """Every golden-corpus query answers byte-identically in mesh mode."""
    if not os.path.exists(GOLDEN_PATH):
        pytest.skip("golden file not generated yet")
    with open(GOLDEN_PATH) as f:
        want = json.load(f)
    for qname, q in GOLDEN_QUERIES:
        res, _ = mesh_node.query(q)
        got = json.loads(json.dumps(res, default=str))
        assert got == want[qname], f"mesh golden diff in {qname!r}"
    assert mesh_node.metrics.counter(
        "dgraph_mesh_sharded_tablets").value > 0


CHAIN_SCHEMA = """
name: string @index(exact) .
p0: [uid] .
p1: [uid] .
p2: [uid] @reverse .
follows: [uid] .
"""


@pytest.fixture(scope="module")
def chain_pair():
    """(plain node, mesh node) over an identical 3-predicate chain graph +
    a self-referencing follows graph — caches disabled so every query
    reaches the dispatch seam (dispatch counting must not be short-
    circuited by the result tiers)."""
    rng = np.random.default_rng(11)
    quads = [f'_:n{i} <name> "node{i}" .' for i in range(80)]
    for i in range(80):
        for attr, mul, off in (("p0", 3, 1), ("p1", 5, 2), ("p2", 7, 3)):
            for k in range(3):
                quads.append(f"_:n{i} <{attr}> _:n{(i * mul + off + k) % 80} .")
        for j in sorted(rng.choice(80, size=3, replace=False)):
            if j != i:
                quads.append(f"_:n{i} <follows> _:n{j} .")
    nodes = []
    for mesh in (0, 8):
        n = Node(mesh_devices=mesh, mesh_min_edges=1)
        n.alter(schema_text=CHAIN_SCHEMA)
        n.mutate(set_nquads="\n".join(quads), commit_now=True)
        n.plan_cache = n.task_cache = n.result_cache = None
        nodes.append(n)
    return nodes


CHAIN_BATTERY = [
    # the acceptance shape: a 3-hop traversal crossing 3 predicate shards
    '{ q(func: eq(name, "node3")) { p0 { p1 { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 { p1 { p2 { name } } } } }',
    '{ q(func: uid(0x1, 0x2)) { p0 { p0 { p0 } } } }',
    '{ q(func: eq(name, "node5")) { p2 { ~p2 } } }',
    '{ q(func: eq(name, "node1")) @recurse(depth: 3) { follows } }',
    '{ q(func: eq(name, "node1")) @recurse(depth: 4, loop: true) { p0 } }',
    '{ p as shortest(from: 0x1, to: 0x30) { follows } r(func: uid(p)) { uid } }',
    '{ p as shortest(from: 0x1, to: 0x30, numpaths: 2) { follows } '
    'r(func: uid(p)) { uid } }',
]


def test_mesh_battery_byte_identical(chain_pair):
    plain, mesh = chain_pair
    for q in CHAIN_BATTERY:
        a, _ = plain.query(q)
        b, _ = mesh.query(q)
        assert json.dumps(a, sort_keys=True, default=str) == \
            json.dumps(b, sort_keys=True, default=str), q


def test_chain_is_one_dispatch_vs_hops_on_per_task_path(chain_pair):
    """The headline gate: a 3-hop traversal crossing 3 predicate shards is
    ONE device dispatch in mesh mode; the same query forced through the
    per-task seam (the shape gRPC/ProcessTaskOverNetwork pays per hop)
    costs one dispatch per hop."""
    _plain, mesh = chain_pair
    q = '{ q(func: eq(name, "node3")) { p0 { p1 { p2 } } } }'
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    before = c.value
    out, _ = mesh.query(q)
    assert c.value - before == 1, "fused chain must be one dispatch"
    # same placed snapshot, fusion off -> one dispatch per hop (the N×hops
    # shape the gRPC fan-out pays per group, minus the wire). Force the
    # device regime: this test graph is far below the real cutover.
    from dgraph_tpu.query import dql, task as task_mod
    from dgraph_tpu.query.engine import Executor

    snap = mesh.snapshot()
    before = c.value
    old = task_mod.HOST_EXPAND_MAX
    task_mod.HOST_EXPAND_MAX = 0
    try:
        out2 = Executor(snap, mesh.store.schema,
                        mesh=None).execute(dql.parse(q))
    finally:
        task_mod.HOST_EXPAND_MAX = old
    assert c.value - before == 3, "per-task path pays one dispatch per hop"
    assert json.dumps(out, sort_keys=True) == json.dumps(out2, sort_keys=True)


def test_per_task_mesh_expand_is_size_adaptive(chain_pair):
    """Below the host/device cutover a per-task expand over a sharded
    tablet serves from the host mirrors — no mesh dispatch (the planner's
    cutover machinery applies to mesh tablets unchanged)."""
    _plain, mesh = chain_pair
    from dgraph_tpu.query import dql
    from dgraph_tpu.query.engine import Executor

    snap = mesh.snapshot()
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    before = c.value
    Executor(snap, mesh.store.schema, mesh=None).execute(
        dql.parse('{ q(func: uid(0x1)) { p0 { uid } } }'))
    assert c.value == before, "tiny frontier must take the host mirror"


def test_mesh_recurse_one_dispatch(chain_pair):
    _plain, mesh = chain_pair
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    before = c.value
    mesh.query('{ q(func: eq(name, "node1")) @recurse(depth: 3) { follows } }')
    assert c.value - before == 1


def test_mesh_recurse_edge_budget(chain_pair):
    _plain, mesh = chain_pair
    set_query_edge_limit(3)     # conftest restores the module default
    with pytest.raises(Exception, match="ErrTooBig"):
        mesh.query(
            '{ q(func: eq(name, "node1")) @recurse(depth: 3) { follows } }')


def test_mesh_fallback_shapes_still_classic(chain_pair):
    """Shapes the fused program does not cover (filters between hops,
    pagination) stay byte-identical via the per-task fallback."""
    plain, mesh = chain_pair
    for q in [
        '{ q(func: eq(name, "node3")) { p0 @filter(uid(0x1, 0x2, 0x3)) '
        '{ p1 } } }',
        '{ q(func: eq(name, "node3")) { p0 (first: 2) { p1 } } }',
    ]:
        a, _ = plain.query(q)
        b, _ = mesh.query(q)
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


def test_mesh_write_then_read_fresh(chain_pair):
    """A commit lands as a delta overlay (host fallback) and is visible
    immediately; the tablet re-shards after compaction."""
    _plain, mesh = chain_pair
    mesh.mutate(set_nquads='<0x1> <p0> <0x4f> .', commit_now=True)
    out, _ = mesh.query('{ q(func: uid(0x1)) { p0 { uid } } }')
    uids = {x["uid"] for x in out["q"][0]["p0"]}
    assert "0x4f" in uids
