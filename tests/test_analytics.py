"""Whole-graph analytics (ISSUE 17): PageRank / connected components /
triangle counting as device-resident while_loop programs on the mesh,
checked against NetworkX oracles; host fallbacks byte-identical where the
math is exact (CC labels, triangle counts); Node.analytics + /analytics
surfaces with metrics and the LDBC SF10 scale gate.

Needs the conftest-provided 8-virtual-device CPU mesh."""

import json
import time

import numpy as np
import pytest
import jax

nx = pytest.importorskip("networkx")

from dgraph_tpu.api.server import Node
from dgraph_tpu.parallel.mesh_exec import MeshExecutor
from dgraph_tpu.query import analytics as an

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest-provided 8-virtual-device CPU mesh")


@pytest.fixture(scope="module")
def mesh():
    return MeshExecutor()


def _random_digraph(n, m, seed):
    rng = np.random.default_rng(seed)
    e = rng.integers(0, n, size=(m, 2))
    e = np.unique(e[e[:, 0] != e[:, 1]], axis=0)
    return e[:, 0].astype(np.int32), e[:, 1].astype(np.int32)


# ---------------------------------------------------------------------------
# device kernels vs NetworkX oracles
# ---------------------------------------------------------------------------

def test_pagerank_device_matches_networkx(mesh):
    n = 500
    esrc, edst = _random_digraph(n, 3000, 7)
    r, it = mesh.run_pagerank(esrc, edst, n, tol=1e-9, max_iters=200)
    assert 0 < it < 200
    g = nx.DiGraph()
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(esrc.tolist(), edst.tolist()))
    oracle = nx.pagerank(g, alpha=0.85, tol=1e-12, max_iter=500)
    want = np.asarray([oracle[i] for i in range(n)])
    assert np.abs(np.asarray(r, np.float64) - want).max() < 1e-6
    assert abs(float(np.sum(r)) - 1.0) < 1e-4


def test_pagerank_dangling_mass_conserved(mesh):
    # a sink chain: dangling mass must redistribute, not vanish
    esrc = np.asarray([0, 1, 2], np.int32)
    edst = np.asarray([1, 2, 3], np.int32)
    r, _ = mesh.run_pagerank(esrc, edst, 4, tol=1e-12, max_iters=300)
    g = nx.DiGraph()
    g.add_nodes_from(range(4))
    g.add_edges_from(zip(esrc.tolist(), edst.tolist()))
    oracle = nx.pagerank(g, alpha=0.85, tol=1e-14, max_iter=1000)
    want = np.asarray([oracle[i] for i in range(4)])
    assert np.abs(np.asarray(r, np.float64) - want).max() < 1e-6


def test_cc_device_exact_vs_networkx(mesh):
    n = 400
    esrc, edst = _random_digraph(n, 260, 11)   # sparse → many components
    lab, it = mesh.run_cc(esrc, edst, n)
    assert it >= 1
    g = nx.Graph()
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(esrc.tolist(), edst.tolist()))
    want = np.arange(n, dtype=np.int64)
    for comp in nx.connected_components(g):
        mn = min(comp)
        for v in comp:
            want[v] = mn
    assert np.array_equal(np.asarray(lab, np.int64), want)


def test_triangles_device_exact_vs_networkx(mesh):
    n = 300
    esrc, edst = _random_digraph(n, 4000, 13)
    tri = mesh.run_triangles(esrc, edst, n)
    g = nx.Graph()
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(esrc.tolist(), edst.tolist()))
    want = sum(nx.triangles(g).values()) // 3
    assert tri == want


def test_host_fallbacks_match_device(mesh):
    n = 350
    esrc, edst = _random_digraph(n, 2200, 17)
    lab_d, _ = mesh.run_cc(esrc, edst, n)
    lab_h = an.cc_host(esrc, edst, n)
    assert np.array_equal(np.asarray(lab_d, np.int64),
                          np.asarray(lab_h, np.int64))
    assert mesh.run_triangles(esrc, edst, n) == \
        an.triangles_host(esrc, edst, n)
    r_d, _ = mesh.run_pagerank(esrc, edst, n, tol=1e-9, max_iters=200)
    r_h, _ = an.pagerank_host(esrc, edst, n, tol=1e-9, max_iters=200)
    assert np.abs(np.asarray(r_d, np.float64) - r_h).max() < 1e-6


def test_empty_and_single_node_graphs(mesh):
    r, it = mesh.run_pagerank(np.zeros(0, np.int32), np.zeros(0, np.int32),
                              1, tol=1e-9, max_iters=50)
    assert len(r) == 1 and abs(float(r[0]) - 1.0) < 1e-6
    lab, _ = mesh.run_cc(np.zeros(0, np.int32), np.zeros(0, np.int32), 3)
    assert np.array_equal(np.asarray(lab), [0, 1, 2])
    assert an.pagerank_host(np.zeros(0, np.int32),
                            np.zeros(0, np.int32), 0)[0].shape == (0,)
    assert an.triangles_host(np.zeros(0, np.int32),
                             np.zeros(0, np.int32), 0) == 0


# ---------------------------------------------------------------------------
# Node.analytics + HTTP surface
# ---------------------------------------------------------------------------

SCHEMA = """
name: string @index(exact) .
follows: [uid] @reverse .
"""


def _social_quads(n=60, seed=3):
    rng = np.random.default_rng(seed)
    quads = [f'<0x{i:x}> <name> "u{i}" .' for i in range(1, n + 1)]
    for i in range(1, n + 1):
        for j in sorted(set(int(x) for x in rng.integers(1, n + 1, 4))):
            if j != i:
                quads.append(f"<0x{i:x}> <follows> <0x{j:x}> .")
    return "\n".join(quads)


@pytest.fixture(scope="module")
def social_pair():
    nodes = []
    for dev in (0, 8):
        node = Node(mesh_devices=dev, mesh_min_edges=1)
        node.alter(schema_text=SCHEMA)
        node.mutate(set_nquads=_social_quads(), commit_now=True)
        nodes.append(node)
    return nodes


def test_node_analytics_device_and_host_agree(social_pair):
    host, dev = social_pair
    for kind in ("cc", "triangles"):
        a = host.analytics(kind, "follows")
        b = dev.analytics(kind, "follows")
        assert a["device"] is False and b["device"] is True
        a.pop("device"), b.pop("device")
        a.pop("iterations", None), b.pop("iterations", None)
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    a = host.analytics("pagerank", "follows", tol=1e-10, max_iters=300)
    b = dev.analytics("pagerank", "follows", tol=1e-10, max_iters=300)
    assert [r["uid"] for r in a["top"][:5]] == \
        [r["uid"] for r in b["top"][:5]]
    for ra, rb in zip(a["top"], b["top"]):
        assert abs(ra["score"] - rb["score"]) < 1e-6


def test_node_analytics_reverse_pred_and_oracle(social_pair):
    _host, dev = social_pair
    out = dev.analytics("pagerank", "~follows", tol=1e-10, max_iters=300)
    assert out["pred"] == "~follows" and out["device"] is True
    # oracle over the reversed edge set
    g = nx.DiGraph()
    uids, _, _ = dev._read_view(None)[1].pred("follows").csr.host_arrays()
    q, _ = dev.query('{ q(func: has(name)) { uid follows { uid } } }')
    for row in q["q"]:
        for t in row.get("follows", []):
            g.add_edge(int(t["uid"], 16), int(row["uid"], 16))
    oracle = nx.pagerank(g, alpha=0.85, tol=1e-13, max_iter=1000)
    best = max(oracle, key=oracle.get)
    assert int(out["top"][0]["uid"], 16) == best


def test_node_analytics_metrics_and_errors(social_pair):
    host, dev = social_pair
    c_runs = dev.metrics.counter("dgraph_analytics_runs_total")
    c_host = host.metrics.counter("dgraph_analytics_host_fallbacks_total")
    r0, h0 = c_runs.value, c_host.value
    dev.analytics("cc", "follows")
    host.analytics("cc", "follows")
    assert c_runs.value > r0
    assert c_host.value > h0
    with pytest.raises(ValueError):
        dev.analytics("betweenness", "follows")
    with pytest.raises(ValueError):
        dev.analytics("pagerank", "name")    # value pred: no uid edges


def test_overlay_tablet_falls_back_to_host(social_pair):
    _host, dev = social_pair
    dev.mutate(set_nquads="<0x1> <follows> <0x2> .", commit_now=True)
    try:
        out = dev.analytics("cc", "follows")
        assert out["device"] is False       # delta overlay → host oracle
    finally:
        pass


def test_http_analytics_endpoint(social_pair):
    import urllib.error
    import urllib.request

    from dgraph_tpu.api.http import serve_forever

    _host, dev = social_pair
    srv = serve_forever(dev, port=0)
    try:
        port = srv.server_address[1]
        body = json.dumps({"kind": "pagerank", "pred": "follows",
                           "maxIters": 200, "top": 3}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/analytics", data=body)
        with urllib.request.urlopen(req) as r:
            env = json.loads(r.read())
        out = env["data"]["analytics"]
        assert out["kind"] == "pagerank" and out["pred"] == "follows"
        assert len(out["top"]) == 3
        assert "server_latency" in env["extensions"]
        # bad request maps to 400 like every other endpoint
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/analytics",
            data=json.dumps({"kind": "pagerank"}).encode())
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad)
        assert ei.value.code == 400
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# scale gate: LDBC SF10 person_knows_person PageRank in seconds
# ---------------------------------------------------------------------------

def test_pagerank_ldbc_sf10_scale(tmp_path, mesh):
    """The acceptance claim: PageRank over the LDBC SF10 knows graph
    (~70k persons, ~1.5M edges) converges on the mesh in seconds and
    matches the NetworkX oracle."""
    from dgraph_tpu.models.ldbc import generate_ldbc

    d = tmp_path / "ldbc"
    st = generate_ldbc(str(d), sf=10)
    assert st.persons > 50_000 and st.knows > 1_000_000
    raw = np.loadtxt(d / "person_knows_person_0_0.csv", delimiter="|",
                     skiprows=1, usecols=(0, 1), dtype=np.int64)
    ids = np.unique(raw)
    esrc = np.searchsorted(ids, raw[:, 0]).astype(np.int32)
    edst = np.searchsorted(ids, raw[:, 1]).astype(np.int32)
    n = len(ids)
    t0 = time.perf_counter()
    r, it = mesh.run_pagerank(esrc, edst, n, tol=1e-8, max_iters=200)
    dt = time.perf_counter() - t0
    assert 0 < it < 200
    assert dt < 120.0, f"SF10 PageRank took {dt:.1f}s"
    g = nx.DiGraph()
    g.add_nodes_from(range(n))
    g.add_edges_from(zip(esrc.tolist(), edst.tolist()))
    oracle = nx.pagerank(g, alpha=0.85, tol=1e-11, max_iter=500)
    want = np.asarray([oracle[i] for i in range(n)])
    got = np.asarray(r, np.float64)
    assert np.abs(got - want).max() < 1e-5
    # the top of the ranking is stable across device/oracle
    assert set(np.argsort(-got)[:10].tolist()) == \
        set(np.argsort(-want)[:10].tolist())
