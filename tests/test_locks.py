"""Runtime lockdep verifier (utils/locks.py, ISSUE 14).

The contract under test: armed runs record acquisition orderings into
one global order graph and the FIRST acquisition that closes a cycle
raises LockOrderError with both witness sites — no actual deadlock has
to be lost to detect the schedule. Disarmed, the factories hand back raw
threading primitives (byte-identical production behavior, zero
overhead by construction).
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

import pytest

from dgraph_tpu.utils import locks


@pytest.fixture
def lockdep():
    locks.reset()
    locks.arm(raise_on_cycle=True)
    yield
    locks.disarm()
    locks.reset()


def test_disarmed_factories_return_raw_primitives():
    locks.disarm()
    assert type(locks.Lock("x")) is type(threading.Lock())
    assert type(locks.RLock("x")) is type(threading.RLock())


def test_seeded_inversion_detected(lockdep):
    a, b = locks.Lock("t.A"), locks.Lock("t.B")
    with a:
        with b:                       # A -> B recorded
            pass
    with pytest.raises(locks.LockOrderError, match="t.A"):
        with b:
            with a:                   # B -> A closes the cycle
                pass
    v = locks.violations()
    assert len(v) == 1 and v[0]["kind"] == "inversion"
    assert set(v[0]["cycle"]) == {"t.A", "t.B"}
    # both locks were released on the unwind — nothing stays wedged
    assert a.acquire(blocking=False) and b.acquire(blocking=False)
    a.release(), b.release()


def test_transitive_cycle_detected(lockdep):
    a, b, c = (locks.Lock(f"t.{n}") for n in "ABC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(locks.LockOrderError):
        with c:
            with a:                   # A -> B -> C -> A
                pass
    assert locks.violations()[0]["cycle"][0] == \
        locks.violations()[0]["cycle"][-1] or \
        len(locks.violations()[0]["cycle"]) >= 3


def test_cross_thread_inversion_detected_without_deadlocking(lockdep):
    """Thread 1 runs A->B to completion, thread 2 then runs B->A: no run
    ever deadlocks, lockdep still proves the schedule."""
    a, b = locks.Lock("x.A"), locks.Lock("x.B")
    err: list = []

    def t1():
        with a:
            with b:
                pass

    def t2():
        try:
            with b:
                with a:
                    pass
        except locks.LockOrderError as e:
            err.append(e)

    th = threading.Thread(target=t1)
    th.start(); th.join()
    th = threading.Thread(target=t2)
    th.start(); th.join()
    assert err and locks.violations()[0]["kind"] == "inversion"


def test_reentrant_rlock_not_flagged(lockdep):
    r = locks.RLock("t.R")
    with r:
        with r:                       # reentrant: no ordering, no edge
            with r:
                pass
    assert locks.violations() == []
    assert "t.R" not in locks.edges()


def test_same_class_two_instances_flagged(lockdep):
    s1, s2 = locks.Lock("stripe"), locks.Lock("stripe")
    with pytest.raises(locks.LockOrderError, match="same-class"):
        with s1:
            with s2:                  # hash-ordered stripes nesting
                pass
    assert locks.violations()[0]["kind"] == "same-class-nesting"


def test_record_only_mode_collects_without_raising(lockdep):
    locks.arm(raise_on_cycle=False)
    a, b = locks.Lock("r.A"), locks.Lock("r.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass                      # recorded, not raised
    assert [v["kind"] for v in locks.violations()] == ["inversion"]


def test_reset_epoch_isolates_surviving_holders(lockdep):
    """A background thread still holding an instrumented lock across
    reset() (a daemon loop outliving one test into the next) must not
    leak its pre-reset ordering as edges into the fresh graph."""
    a, b = locks.Lock("ep.A"), locks.Lock("ep.B")
    entered, release = threading.Event(), threading.Event()

    def holder():
        with a:                       # held across the reset boundary
            entered.set()
            release.wait(10)
            with b:                   # post-reset acquisition
                pass

    th = threading.Thread(target=holder)
    th.start()
    entered.wait(10)
    locks.reset()                     # new test's fresh graph
    locks.arm(raise_on_cycle=True)
    release.set()
    th.join(10)
    assert not th.is_alive()
    # the stale-held A is invisible post-reset: no A->B edge recorded,
    # so a fresh B->A ordering elsewhere cannot flakily close a cycle
    assert "ep.A" not in locks.edges()
    with b:
        with a:
            pass
    assert locks.violations() == []


def test_ordered_nesting_is_clean(lockdep):
    a, b = locks.Lock("ok.A"), locks.Lock("ok.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert locks.violations() == []
    assert locks.edges() == {"ok.A": ["ok.B"]}


# ---------------------------------------------------------------------------
# striped residency locks under the prefetch pool (the ISSUE's named case)
# ---------------------------------------------------------------------------

class _FakeOwner:
    """Minimal owner-protocol object driving the manager's real locking
    (upload stripe -> manager lock) exactly like PredCSR uploads do."""

    def __init__(self, mgr, attr, nbytes=1024):
        self._res = mgr
        self._res_attr = attr
        self._res_kind = "csr"
        self._nbytes = int(nbytes)
        self._resident = False
        self.mgr = mgr

    def device_nbytes(self):
        return self._nbytes

    def device_resident(self):
        return self._resident

    def drop_device(self):
        self._resident = False

    def device_arrays(self, prefetch=False):
        with self.mgr.upload_lock_for(self):
            if self._resident:
                return
            self.mgr.before_upload(self)
            self._resident = True
            self.mgr.after_upload(self, prefetch=prefetch)


def test_residency_striped_locks_under_prefetch_pool(lockdep):
    """Concurrent pool prefetches + foreground uploads + evictions drive
    every stripe against the manager lock; lockdep must see a clean
    (acyclic) order graph — and the graph must actually contain the
    stripe->manager edges (the test is not vacuous)."""
    from dgraph_tpu.storage.residency import ResidencyManager

    mgr = ResidencyManager(budget_bytes=8 * 1024, prefetch_workers=4)
    owners = [_FakeOwner(mgr, f"p{i}") for i in range(24)]
    snap = SimpleNamespace(preds={
        o._res_attr: SimpleNamespace(csr=o, rev_csr=None, vecindex=None)
        for o in owners})

    stop = threading.Event()
    errs: list = []

    def foreground(ixs):
        try:
            while not stop.is_set():
                for i in ixs:
                    owners[i].device_arrays()
                    mgr.touch(owners[i]._res_attr)
        except BaseException as e:   # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=foreground,
                                args=(range(i, 24, 3),)) for i in range(3)]
    for t in threads:
        t.start()
    for _ in range(20):
        mgr.prefetch([o._res_attr for o in owners], snap)
        mgr.evict_to(2 * 1024)
        time.sleep(0.001)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    if mgr._pool is not None:
        mgr._pool.shutdown(wait=True)
    assert not errs, errs
    assert locks.violations() == []
    # the order graph saw stripe-family -> manager-lock edges (the 16
    # stripes share ONE lockdep class, so nesting two stripes would have
    # raised same-class-nesting — none did)
    e = locks.edges()
    assert "residency.ResidencyManager._lock" in \
        e.get("residency.upload", []), e
