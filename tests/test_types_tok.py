"""Value types, conversion matrix, tokenizers, geo (reference: types/, tok/)."""

from datetime import datetime, timezone

import pytest

from dgraph_tpu.utils import geo, tok
from dgraph_tpu.utils.types import (TypeID, Val, compare_vals, convert,
                                    hash_password, marshal, unmarshal,
                                    verify_password)


def test_conversion_matrix():
    assert convert(Val(TypeID.STRING, "42"), TypeID.INT).value == 42
    assert convert(Val(TypeID.STRING, "3.5"), TypeID.FLOAT).value == 3.5
    assert convert(Val(TypeID.STRING, "true"), TypeID.BOOL).value is True
    assert convert(Val(TypeID.INT, 5), TypeID.FLOAT).value == 5.0
    assert convert(Val(TypeID.FLOAT, 2.9), TypeID.INT).value == 2
    dt = convert(Val(TypeID.STRING, "2006-01-02T15:04:05"), TypeID.DATETIME).value
    assert dt == datetime(2006, 1, 2, 15, 4, 5, tzinfo=timezone.utc)
    assert convert(Val(TypeID.DATETIME, dt), TypeID.STRING).value.startswith("2006-01-02")
    assert convert(Val(TypeID.INT, 7), TypeID.STRING).value == "7"
    with pytest.raises(ValueError):
        convert(Val(TypeID.STRING, "xyz"), TypeID.INT)
    with pytest.raises(ValueError):
        convert(Val(TypeID.BOOL, True), TypeID.DATETIME)


def test_marshal_roundtrip():
    for v in [Val(TypeID.INT, -7), Val(TypeID.FLOAT, 1.25), Val(TypeID.BOOL, True),
              Val(TypeID.STRING, "héllo"), Val(TypeID.BINARY, b"\x00\x01"),
              Val(TypeID.DATETIME, datetime(2020, 5, 17, tzinfo=timezone.utc)),
              Val(TypeID.UID, 12345)]:
        assert unmarshal(v.tid, marshal(v)) == v


def test_compare_vals():
    assert compare_vals("lt", Val(TypeID.INT, 3), Val(TypeID.INT, 5))
    assert compare_vals("ge", Val(TypeID.FLOAT, 5.0), Val(TypeID.INT, 5))
    assert not compare_vals("eq", Val(TypeID.STRING, "a"), Val(TypeID.STRING, "b"))


def test_password():
    h = hash_password("secret1")
    assert verify_password("secret1", h)
    assert not verify_password("secret2", h)
    with pytest.raises(ValueError):
        hash_password("abc")  # too short


def test_term_and_fulltext_tokens():
    t = tok.get("term")
    toks = t.tokens(Val(TypeID.STRING, "The Quick  brown-Fox"))
    words = {x[1:].decode() for x in toks}
    assert words == {"the", "quick", "brown", "fox"}
    ft = tok.get("fulltext")
    toks = ft.tokens(Val(TypeID.STRING, "running dogs and the cats"))
    stems = {x[1:].decode() for x in toks}
    assert "runn" in stems or "run" in stems  # stemmed
    assert "the" not in stems and "and" not in stems  # stopwords dropped


def test_int_tokens_order_preserving():
    enc = lambda i: tok.get("int").tokens(Val(TypeID.INT, i))[0]
    vals = [-(2**40), -5, 0, 3, 2**40]
    encoded = [enc(v) for v in vals]
    assert encoded == sorted(encoded)
    fenc = lambda f: tok.get("float").tokens(Val(TypeID.FLOAT, f))[0]
    fvals = [-1e30, -2.5, -0.0, 0.0, 1.5, 1e30]
    fencoded = [fenc(v) for v in fvals]
    assert fencoded == sorted(fencoded)


def test_trigram_tokens():
    toks = tok.get("trigram").tokens(Val(TypeID.STRING, "hello"))
    grams = {x[1:].decode() for x in toks}
    assert grams == {"hel", "ell", "llo"}
    assert tok.get("trigram").tokens(Val(TypeID.STRING, "ab")) == []


def test_datetime_bucket_tokens():
    v = Val(TypeID.DATETIME, datetime(2019, 7, 4, 13, tzinfo=timezone.utc))
    y = tok.get("year").tokens(v)[0]
    m = tok.get("month").tokens(v)[0]
    d = tok.get("day").tokens(v)[0]
    h = tok.get("hour").tokens(v)[0]
    assert len(y) < len(m) < len(d) < len(h)
    v2 = Val(TypeID.DATETIME, datetime(2020, 1, 1, tzinfo=timezone.utc))
    assert tok.get("year").tokens(v2)[0] > y  # sortable across years


def test_custom_tokenizer_registry():
    tok.register_custom("cidr_test", lambda v: [str(v.value).split(".")[0].encode()])
    t = tok.get("cidr_test")
    assert t.tokens(Val(TypeID.STRING, "10.1.2.3"))[0][1:] == b"10"


def test_geohash_and_predicates():
    sf = (-122.4194, 37.7749)
    nyc = (-74.0060, 40.7128)
    h_sf = geo.geohash(*sf, 6)
    h_near_sf = geo.geohash(-122.4195, 37.7750, 6)
    assert h_sf[:4] == h_near_sf[:4]
    assert geo.haversine_m(sf, nyc) == pytest.approx(4_130_000, rel=0.02)

    g = geo.parse_geojson('{"type":"Point","coordinates":[-122.4194,37.7749]}')
    toks = geo.index_tokens(g)
    assert any(t == h_sf[: len(t)] for t in toks)

    square = geo.Geom("Polygon", ((( -1.0, -1.0), (1.0, -1.0), (1.0, 1.0),
                                   (-1.0, 1.0), (-1.0, -1.0)),))
    assert geo.contains(square, geo.Geom("Point", (0.0, 0.0)))
    assert not geo.contains(square, geo.Geom("Point", (2.0, 0.0)))
    assert geo.within(geo.Geom("Point", (0.5, 0.5)), square)
    assert geo.near(geo.Geom("Point", sf), (-122.41, 37.77), 5000)
    assert not geo.near(geo.Geom("Point", sf), (-74.0, 40.7), 5000)
    roundtrip = geo.parse_geojson(geo.to_geojson(square))
    assert roundtrip == square


# -- per-language full-text (reference tok/fts.go Bleve analyzers) -----------

def test_fulltext_lang_stemming_roundtrip():
    """Index-side and query-side tokens agree per language, folding common
    inflections onto one token."""
    from dgraph_tpu.utils.tok import fulltext_tokens

    # Russian plural/case forms meet at one stem
    assert fulltext_tokens("собаки", "ru") == fulltext_tokens("собака", "ru")
    # German plural
    assert fulltext_tokens("Hunden", "de") == fulltext_tokens("Hunde", "de")
    # Spanish verb forms
    assert set(fulltext_tokens("corriendo", "es")) & \
        set(fulltext_tokens("correr", "es"))
    # stopwords per language
    assert fulltext_tokens("и в не", "ru") == []
    assert fulltext_tokens("der die das", "de") == []
    # unknown language: no stemming, no stopwords (consistent both sides)
    assert fulltext_tokens("running the dogs", "xx") == sorted(
        {b"running", b"the", b"dogs"})
    # English keeps Porter
    assert fulltext_tokens("running dogs", "en") == fulltext_tokens(
        "run dog", "en")


def test_alloftext_lang_end_to_end():
    """alloftext on @ru values matches inflected forms because index and
    query use the same Russian analyzer."""
    from dgraph_tpu.api.server import Node

    n = Node()
    n.alter(schema_text="bio: string @index(fulltext) @lang .")
    n.mutate(set_nquads='_:a <bio> "большие собаки"@ru .\n'
                        '_:a <bio> "big dogs"@en .\n'
                        '_:b <bio> "кошка спит"@ru .', commit_now=True)
    out, _ = n.query('{ q(func: alloftext(bio@ru, "собака")) { uid } }')
    assert len(out["q"]) == 1
    out, _ = n.query('{ q(func: alloftext(bio@en, "dog")) { uid } }')
    assert len(out["q"]) == 1
    out, _ = n.query('{ q(func: alloftext(bio@ru, "собака кошка")) { uid } }')
    assert out.get("q", []) == []


def test_fulltext_accented_stopwords_and_suffixes():
    """Tables are stored in normalized form: accented stopwords are
    dropped and accented suffixes stem (review r4: _normalize strips
    combining marks before the checks)."""
    from dgraph_tpu.utils.tok import fulltext_tokens

    assert fulltext_tokens("était le chien", "fr") == [b"chien"]
    assert fulltext_tokens("für den Hund", "de") == [b"hund"]
    # French past participle singular/plural meet at one token
    assert fulltext_tokens("donné", "fr") == fulltext_tokens("données", "fr")
