"""Cost-based query planner (query/planner.py): estimation math, ordering
decisions, and — the load-bearing contract — plan ≡ parse-order result
equivalence on the golden corpus and fuzz seeds. Plans only ever change
ORDER; any output difference planner-on vs planner-off is a bug."""

import json
import random

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import dql, planner
from dgraph_tpu.storage import stats as stmod

N_PEOPLE = 2000
FOLLOWS = 6


@pytest.fixture(scope="module")
def node():
    from dgraph_tpu.models.film import film_node

    n = film_node(n_people=N_PEOPLE, follows=FOLLOWS)
    yield n
    n.close()


def _est(node, snap, fname, attr, *args, **kw):
    fn = dql.Function(name=fname, attr=attr, args=list(args), **kw)
    return planner._est_func(fn, snap, node.store.schema, None, 10**9)


# ---------------------------------------------------------------------------
# estimation math
# ---------------------------------------------------------------------------

def test_eq_estimate_is_exact_term_frequency(node):
    snap = node.snapshot()
    est, src, dep = _est(node, snap, "eq", "name", "p7")
    assert (est, src, dep) == (1, "index probe", False)
    est, src, _ = _est(node, snap, "eq", "genre", "noir")
    assert src == "index probe"
    assert est == N_PEOPLE // 4          # i % 4 == 2 -> "noir"
    # multi-value eq sums the term frequencies
    est2, _, _ = _est(node, snap, "eq", "genre", "noir", "drama")
    assert est2 == 2 * (N_PEOPLE // 4)


def test_inequality_estimate_counts_index_range(node):
    snap = node.snapshot()
    est, src, dep = _est(node, snap, "ge", "age", 50)
    assert src == "index probe" and not dep
    # exact: ages are 18 + i % 60 -> [50, 77] hits 28 of every 60
    actual, _ = node.query('{ q(func: ge(age, 50)) { count(uid) } }')
    assert est == actual["q"][0]["count"]


def test_has_estimate_and_frontier_dependence(node):
    snap = node.snapshot()
    est, src, dep = _est(node, snap, "has", "age")
    assert est == N_PEOPLE and src == "tablet scan"
    assert dep           # value predicate: evaluated over the frontier
    est, src, dep = _est(node, snap, "has", "follows")
    assert src == "tablet scan" and not dep   # uid predicate
    assert est > 0


def test_absent_predicate_estimates_zero(node):
    snap = node.snapshot()
    assert _est(node, snap, "eq", "nosuchpred", "x")[0] == 0


# ---------------------------------------------------------------------------
# ordering decisions
# ---------------------------------------------------------------------------

CHAIN = ('{ q(func: has(age)) @filter(ge(count(follows), 1) AND '
         'eq(genre, "noir") AND eq(name, "p6")) { uid name } }')


def test_and_order_most_selective_first(node):
    req = dql.parse(CHAIN)
    snap = node.snapshot()
    plan = planner.build_plan(req, snap, node.store.schema)
    gq = req.queries[0]
    # root swap: eq(name, "p6") (est 1) beats the has(age) tablet scan
    sw = plan.root_swap.get(id(gq))
    assert sw is not None and sw.new_func.attr == "name"
    ft = gq.filter
    order = plan.and_order[id(ft)]
    ordered_attrs = []
    for i in order:
        leaf = ft.children[i]
        fn = sw.orig_func if id(leaf) == sw.leaf_id else leaf.func
        ordered_attrs.append((fn.attr, fn.is_count))
    # the absolute eq(genre) index probe first; the frontier-scaled
    # leaves after, ascending by estimate — the count probe (est
    # has/8) before the demoted has(age) full scan. Ordering must key
    # on what the leaf EXECUTES (the demoted root), not the promoted
    # probe that used to sit there.
    assert ordered_attrs == [("genre", False), ("follows", True),
                             ("age", False)]


def test_no_swap_when_uids_join_the_root(node):
    # explicit uids union with the root function: swapping would change
    # the result set, so the planner must not touch it
    q = '{ q(func: has(age)) @filter(eq(name, "p6")) { uid } }'
    req = dql.parse(q)
    req.queries[0].uids = [1, 2]
    plan = planner.build_plan(req, node.snapshot(), node.store.schema)
    assert id(req.queries[0]) not in plan.root_swap


def test_sibling_order_skipped_when_vars_bind(node):
    q = ('{ q(func: eq(age, 30)) { x as age follows { uid } } }')
    req = dql.parse(q)
    plan = planner.build_plan(req, node.snapshot(), node.store.schema)
    assert id(req.queries[0]) not in plan.child_order
    assert not planner._orderable_children(req.queries[0])


def test_cutover_override_for_moderate_expansions(node):
    # fake stats: a predicate whose estimated expansion lands between the
    # static 64k threshold and the device minimum gets a host-preferring
    # cutover override
    snap = node.snapshot()
    pd = snap.pred("follows")
    real = stmod.pred_stats(pd)
    fake = stmod.PredStats(
        attr="follows", type_name="UID",
        fwd=stmod.CSRStats(n_subjects=real.fwd.n_subjects,
                           n_edges=200_000),
        rev=stmod.CSRStats())
    pd.__dict__[stmod._STATS_ATTR] = fake
    try:
        req = dql.parse('{ q(func: has(age)) { follows { uid } } }')
        plan = planner.build_plan(req, snap, node.store.schema)
        cgq = req.queries[0].children[0]
        cut = plan.cutover.get(id(cgq))
        assert cut is not None and cut > (1 << 16)
        assert cut <= planner.DEVICE_MIN_EDGES
    finally:
        pd.__dict__[stmod._STATS_ATTR] = real


# ---------------------------------------------------------------------------
# plan ≡ parse-order equivalence
# ---------------------------------------------------------------------------

def _on_off(node, q):
    """Run q planner-off then planner-on with the task/result caches
    disabled — a cache hit would serve the first run's output and make
    the comparison vacuous."""
    stash = (node.task_cache, node.result_cache)
    node.task_cache = node.result_cache = None
    try:
        node.planner_enabled = False
        off, _ = node.query(q)
        node.planner_enabled = True
        on, _ = node.query(q)
    finally:
        node.task_cache, node.result_cache = stash
    return json.dumps(off, sort_keys=True, default=str), \
        json.dumps(on, sort_keys=True, default=str)


def test_golden_corpus_equivalence():
    """Every golden-battery query yields byte-identical JSON planner-on
    vs planner-off (the golden dataset spans every directive/function
    family, so this is the broadest semantics gate)."""
    from tests.test_golden import QUERIES, SCHEMA, _dataset

    n = Node()
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads=_dataset(), commit_now=True)
    try:
        for qname, q in QUERIES:
            off, on = _on_off(n, q)
            assert off == on, f"planner changed output of {qname!r}"
        assert n.metrics.counter("dgraph_planner_plans_total").value > 0
    finally:
        n.close()


def test_fuzz_seed_equivalence(node):
    """Seeded random filter chains over the film graph: planned output ==
    parse-order output for every seed."""
    rng = random.Random(20260803)
    leaves = ['eq(genre, "noir")', 'eq(genre, "drama")', 'eq(name, "p6")',
              'ge(age, 40)', 'le(age, 30)', 'has(genre)', 'has(follows)',
              'ge(count(follows), 1)', 'eq(count(follows), 2)',
              'eq(name, "p100")', 'lt(age, 77)']
    roots = ['has(age)', 'has(name)', 'eq(genre, "scifi")', 'ge(age, 70)',
             'has(follows)']

    def tree(depth):
        if depth == 0 or rng.random() < 0.5:
            return rng.choice(leaves)
        op = rng.choice([" AND ", " OR "])
        parts = [tree(depth - 1) for _ in range(rng.randint(2, 3))]
        t = "(" + op.join(parts) + ")"
        if rng.random() < 0.2:
            t = f"(NOT {t})"
        return t

    for _ in range(40):
        body = rng.choice(["uid", "uid name", "uid follows { uid }",
                           "name count(follows)"])
        q = (f'{{ q(func: {rng.choice(roots)}) @filter({tree(2)}) '
             f'{{ {body} }} }}')
        off, on = _on_off(node, q)
        assert off == on, q


def test_child_filter_reorder_equivalence(node):
    q = ('{ q(func: eq(age, 30), first: 10) { name follows '
         '@filter(ge(count(follows), 1) AND eq(genre, "noir") AND '
         'eq(name, "p6")) { uid } } }')
    off, on = _on_off(node, q)
    assert off == on


# ---------------------------------------------------------------------------
# EXPLAIN surface + plan cache + flags
# ---------------------------------------------------------------------------

def test_explain_returns_est_vs_actual(node):
    node.planner_enabled = True
    out, _ = node.query(CHAIN, explain=True)
    ex = out["explain"]
    assert ex["planner"] == "on"
    assert ex["decisions"]["root_swaps"] >= 1
    # stats header: the read set's live stats with the top-K sketch
    assert ex["stats"]["name"]["subjects"] == 0      # value predicate
    assert ex["stats"]["name"]["values"] == N_PEOPLE
    assert len(ex["stats"]["genre"]["top_terms"]["exact"]) == 4
    blk = ex["blocks"][0]
    assert blk["root"]["swapped"] is True
    assert blk["root"]["est"] >= 0 and blk["root"]["actual"] is not None
    # the promoted probe ran as root; its actual equals the root's
    assert any(f["actual"] is not None for f in blk["filters"])
    # plain queries must NOT carry the explain key
    out2, _ = node.query(CHAIN)
    assert "explain" not in out2


def test_explain_planner_off():
    n = Node(planner=False)
    n.alter(schema_text="name: string @index(exact) .")
    n.mutate(set_nquads='<0x1> <name> "a" .', commit_now=True)
    try:
        out, _ = n.query('{ q(func: has(name)) { uid } }', explain=True)
        assert out["explain"] == {"planner": "off"}
        assert n.metrics.counter("dgraph_planner_plans_total").value == 0
    finally:
        n.close()


def test_plan_cache_hits_and_invalidates(node):
    node.planner_enabled = True
    # result cache off: a whole-query hit would return before planning
    stash, node.result_cache = node.result_cache, None
    q = '{ q(func: has(age)) @filter(eq(name, "p9")) { uid } }'
    c = lambda name: node.metrics.counter(name).value
    node.query(q)
    h0 = c("dgraph_planner_cache_hits_total")
    node.query(q)
    node.result_cache = stash
    assert c("dgraph_planner_cache_hits_total") == h0 + 1
    # a commit to a predicate the plan reads rotates its stats token:
    # the cached plan must be rebuilt against fresh stats
    m0 = c("dgraph_planner_cache_misses_total")
    node.mutate(set_nquads=f'<0x{N_PEOPLE + 50:x}> <name> "fresh" .',
                commit_now=True)
    node.query(q)
    assert c("dgraph_planner_cache_misses_total") == m0 + 1


def test_estimation_error_histogram_feeds(node):
    node.planner_enabled = True
    node.query(CHAIN)
    snap = node.metrics.histogram(
        "dgraph_planner_est_error_log2").snapshot()
    assert snap["count"] > 0


def test_http_explain_surface(node):
    import urllib.request

    from dgraph_tpu.api.http import serve_forever

    node.planner_enabled = True
    srv = serve_forever(node, port=0)
    try:
        port = srv.server_address[1]
        body = CHAIN.encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/query?explain=true", data=body)
        with urllib.request.urlopen(req) as r:
            env = json.loads(r.read())
        assert "explain" in env["extensions"]
        assert env["extensions"]["explain"]["planner"] == "on"
        assert "explain" not in env["data"]
        # /debug/metrics exposes the planner section
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/metrics") as r:
            m = json.loads(r.read())
        assert m["planner"]["plans_built"] > 0
        assert "est_error_log2" in m["planner"]
    finally:
        srv.shutdown()
