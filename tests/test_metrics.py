"""Observability: dgraph_* counters, latency histograms, request traces,
and the /debug HTTP surface (reference: x/metrics.go, net/trace sampling in
edgraph/server.go:289,388)."""

import json
import threading
import urllib.request

import pytest

from dgraph_tpu.api.http import make_server
from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import TxnConflict
from dgraph_tpu.utils import metrics


def test_counters_and_latency():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    n.mutate(set_nquads='_:a <name> "m" .', commit_now=True)
    n.query('{ q(func: eq(name, "m")) { name } }')
    c = n.metrics.counters
    assert c["dgraph_num_queries_total"].value == 1
    assert c["dgraph_num_mutations_total"].value == 1
    assert c["dgraph_num_commits_total"].value == 1
    assert c["dgraph_num_alters_total"].value == 1
    assert c["dgraph_posting_writes_total"].value > 0
    assert c["dgraph_posting_reads_total"].value > 0
    assert c["dgraph_pending_queries_total"].value == 0   # dec in finally
    h = n.metrics.histograms["dgraph_query_latency_s"].snapshot()
    assert h["count"] == 1 and h["p50"] > 0


def test_abort_counter():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    t1, t2 = n.new_txn(), n.new_txn()
    n.mutate(set_nquads='<0x9> <name> "x" .', start_ts=t1.start_ts)
    n.mutate(set_nquads='<0x9> <name> "y" .', start_ts=t2.start_ts)
    n.commit(t1.start_ts)
    with pytest.raises(TxnConflict):
        n.commit(t2.start_ts)
    assert n.metrics.counters["dgraph_num_aborts_total"].value == 1


def test_traces_record_breadcrumbs_and_errors():
    n = Node(trace_fraction=1.0)
    n.alter(schema_text="name: string @index(exact) .")
    n.query('{ q(func: has(name)) { name } }')
    recent = n.traces.recent()
    assert recent and recent[0]["kind"] == "query"
    msgs = [e["msg"] for e in recent[0]["events"]]
    assert any("parsed" in m for m in msgs)
    assert any("executed" in m for m in msgs)
    with pytest.raises(Exception):
        n.query("{ bad dql !!!")
    assert n.traces.recent()[0]["error"]


def test_trace_sampling_off():
    n = Node(trace_fraction=0.0)
    n.alter(schema_text="name: string .")
    n.query("{ q(func: has(name)) { name } }")
    assert n.traces.recent() == []


def test_debug_http_endpoints():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    srv = make_server(n, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        body = json.dumps({"query": '{ q(func: has(name)) { name } }'}).encode()
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/query", body,
            {"Content-Type": "application/json"}), timeout=5).read()
        v = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/vars", timeout=5).read())
        assert v["dgraph_num_queries_total"] >= 1
        assert "dgraph_query_latency_s" in v
        tr = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/requests", timeout=5).read())
        assert tr and tr[0]["kind"] == "query"
    finally:
        srv.shutdown()


def test_histogram_percentiles():
    h = metrics.Histogram(cap=100)
    for i in range(1, 101):
        h.observe(float(i))
    s = h.snapshot()
    assert s["count"] == 100 and s["p50"] == 51.0 and s["max"] == 100.0


def test_meter_rate_prunes_expired_marks():
    m = metrics.Meter(window=10.0, cap=8192)
    import time as _time

    now = _time.monotonic()
    with m._lock:
        # 500 expired marks + 3 live ones, planted directly in the ring
        for dt in range(500):
            m._ring.append(now - 20.0 - dt * 0.01)
        for _ in range(3):
            m._ring.append(now)
    assert m.rate() == pytest.approx(3 / 10.0)
    # expired timestamps were dropped from the ring, not rescanned forever
    assert len(m._ring) == 3
    # a NARROWER window must not evict marks the default window still needs
    with m._lock:
        m._ring.appendleft(now - 5.0)       # inside 10s, outside 1s
    assert m.rate(window=1.0) == pytest.approx(3 / 1.0)
    assert len(m._ring) == 4
    assert m.rate() == pytest.approx(4 / 10.0)


def test_keyed_gauge_get_is_locked_and_consistent():
    g = metrics.KeyedGauge()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            g.set(f"k{i % 50}", i % 7)      # zero values delete keys
            i += 1

    def reader():
        try:
            while not stop.is_set():
                g.get("k3")
                g.snapshot()
        except Exception as e:              # torn dict state surfaces here
            errors.append(e)

    ts = [threading.Thread(target=writer) for _ in range(2)] + \
         [threading.Thread(target=reader) for _ in range(2)]
    for t in ts:
        t.start()
    stop.wait(0.3)
    stop.set()
    for t in ts:
        t.join()
    assert not errors
    g.set("x", 5)
    assert g.get("x") == 5 and g.get("missing") == 0


def test_trace_store_injectable_rng():
    class Seq:
        def __init__(self, vals):
            self.vals = list(vals)

        def random(self):
            return self.vals.pop(0)

    ts = metrics.TraceStore(fraction=0.5, rng=Seq([0.1, 0.9, 0.4, 0.6]))
    picks = [ts.start("query", "t") is not metrics.NULL_TRACE
             for _ in range(4)]
    assert picks == [True, False, True, False]
    # fraction 1.0 never consults the rng (hot path stays coin-flip free)
    ts_all = metrics.TraceStore(fraction=1.0, rng=Seq([]))
    assert ts_all.start("query", "t") is not metrics.NULL_TRACE


def test_traces_finish_on_every_error_path():
    """query/mutate/alter breadcrumb traces must finish (with the error)
    on every failure shape — parse errors, unknown txns, bad schema."""
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    with pytest.raises(Exception):
        n.query("{ q(func: bogus~~ }")                    # parse error
    with pytest.raises(Exception):
        n.mutate(set_nquads='<0x1> <name> "x" .', start_ts=999999)
    with pytest.raises(Exception):
        n.alter(schema_text="name: notatype .")
    kinds = [(t["kind"], t["error"] != "") for t in n.traces.recent()]
    assert ("query", True) in kinds
    assert ("mutate", True) in kinds
    assert ("alter", True) in kinds
    # the span-trace buffers drained too (no active-trace leaks)
    assert n.tracer.active_traces() == 0


def test_meter_rate_wider_window_clamps_to_retention():
    """Pruning keeps only self.window of history, so a wider request
    clamps instead of silently undercounting over the longer divisor."""
    m = metrics.Meter(window=10.0)
    import time as _time

    now = _time.monotonic()
    with m._lock:
        for _ in range(5):
            m._ring.append(now - 1.0)
    assert m.rate(window=60.0) == pytest.approx(5 / 10.0)
