"""Observability: dgraph_* counters, latency histograms, request traces,
and the /debug HTTP surface (reference: x/metrics.go, net/trace sampling in
edgraph/server.go:289,388)."""

import json
import threading
import urllib.request

import pytest

from dgraph_tpu.api.http import make_server
from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import TxnConflict
from dgraph_tpu.utils import metrics


def test_counters_and_latency():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    n.mutate(set_nquads='_:a <name> "m" .', commit_now=True)
    n.query('{ q(func: eq(name, "m")) { name } }')
    c = n.metrics.counters
    assert c["dgraph_num_queries_total"].value == 1
    assert c["dgraph_num_mutations_total"].value == 1
    assert c["dgraph_num_commits_total"].value == 1
    assert c["dgraph_num_alters_total"].value == 1
    assert c["dgraph_posting_writes_total"].value > 0
    assert c["dgraph_posting_reads_total"].value > 0
    assert c["dgraph_pending_queries_total"].value == 0   # dec in finally
    h = n.metrics.histograms["dgraph_query_latency_s"].snapshot()
    assert h["count"] == 1 and h["p50"] > 0


def test_abort_counter():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    t1, t2 = n.new_txn(), n.new_txn()
    n.mutate(set_nquads='<0x9> <name> "x" .', start_ts=t1.start_ts)
    n.mutate(set_nquads='<0x9> <name> "y" .', start_ts=t2.start_ts)
    n.commit(t1.start_ts)
    with pytest.raises(TxnConflict):
        n.commit(t2.start_ts)
    assert n.metrics.counters["dgraph_num_aborts_total"].value == 1


def test_traces_record_breadcrumbs_and_errors():
    n = Node(trace_fraction=1.0)
    n.alter(schema_text="name: string @index(exact) .")
    n.query('{ q(func: has(name)) { name } }')
    recent = n.traces.recent()
    assert recent and recent[0]["kind"] == "query"
    msgs = [e["msg"] for e in recent[0]["events"]]
    assert any("parsed" in m for m in msgs)
    assert any("executed" in m for m in msgs)
    with pytest.raises(Exception):
        n.query("{ bad dql !!!")
    assert n.traces.recent()[0]["error"]


def test_trace_sampling_off():
    n = Node(trace_fraction=0.0)
    n.alter(schema_text="name: string .")
    n.query("{ q(func: has(name)) { name } }")
    assert n.traces.recent() == []


def test_debug_http_endpoints():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    srv = make_server(n, "127.0.0.1", 0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        body = json.dumps({"query": '{ q(func: has(name)) { name } }'}).encode()
        urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/query", body,
            {"Content-Type": "application/json"}), timeout=5).read()
        v = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/vars", timeout=5).read())
        assert v["dgraph_num_queries_total"] >= 1
        assert "dgraph_query_latency_s" in v
        tr = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/requests", timeout=5).read())
        assert tr and tr[0]["kind"] == "query"
    finally:
        srv.shutdown()


def test_histogram_percentiles():
    h = metrics.Histogram(cap=100)
    for i in range(1, 101):
        h.observe(float(i))
    s = h.snapshot()
    assert s["count"] == 100 and s["p50"] == 51.0 and s["max"] == 100.0
