"""Device aggregation terminal stage (ISSUE 17): @groupby blocks whose
children are count(uid) / numeric __agg_* compile as TERMINAL
segmented-reduce ops of the whole-plan mesh program — byte-identical to
classic, ONE dispatch for the whole chain including the aggregation,
labeled fallback reasons for every non-terminal groupby shape, and
EXPLAIN est-vs-actual rows for the aggregation step.

Needs the conftest-provided 8-virtual-device CPU mesh."""

import json

import numpy as np
import pytest
import jax

from dgraph_tpu.api.server import Node

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest-provided 8-virtual-device CPU mesh")


SCHEMA = """
name: string @index(exact) .
rating: float @index(float) .
score: int @index(int) .
p0: [uid] .
p1: [uid] .
p2: [uid] @reverse .
follows: [uid] .
"""


def _quads():
    rng = np.random.default_rng(17)
    quads = [f'_:n{i} <name> "node{i}" .' for i in range(80)]
    quads += [f'_:n{i} <rating> "{(i * 13) % 100 / 10}"^^<xs:float> .'
              for i in range(80)]
    # integer values on a subset only: some groupby members carry no
    # value (the NaN-for-missing path) and some groups end up empty
    quads += [f'_:n{i} <score> "{(i * 7) % 50}"^^<xs:int> .'
              for i in range(80) if i % 5]
    for i in range(80):
        for attr, mul, off in (("p0", 3, 1), ("p1", 5, 2), ("p2", 7, 3)):
            for k in range(3):
                t = (i * mul + off + k) % 80
                quads.append(f"_:n{i} <{attr}> _:n{t} .")
        for j in sorted(rng.choice(80, size=3, replace=False)):
            if j != i:
                quads.append(f"_:n{i} <follows> _:n{j} .")
    return "\n".join(quads)


@pytest.fixture(scope="module")
def pair():
    nodes = []
    for mesh in (0, 8):
        n = Node(mesh_devices=mesh, mesh_min_edges=1)
        n.alter(schema_text=SCHEMA)
        n.mutate(set_nquads=_quads(), commit_now=True)
        n.task_cache = n.result_cache = None
        nodes.append(n)
    return nodes


def _same(plain, mesh, q):
    a, _ = plain.query(q)
    b, _ = mesh.query(q)
    assert json.dumps(a, sort_keys=True, default=str) == \
        json.dumps(b, sort_keys=True, default=str), q
    return a


def _reasons(mesh):
    return mesh.metrics.keyed("dgraph_mesh_fallbacks_total",
                              labels=("reason",)).snapshot()


# ---------------------------------------------------------------------------
# terminal shapes: byte identity + ONE dispatch for chain + aggregation
# ---------------------------------------------------------------------------

TERMINAL_BATTERY = [
    # count-only terminals at depth 1 and 2
    '{ q(func: eq(name, "node3")) { p0 @groupby(p2) { count(uid) } } }',
    '{ q(func: eq(name, "node3")) { p0 { p1 @groupby(p2) '
    '{ count(uid) } } } }',
    # filters upstream of the terminal
    '{ q(func: eq(name, "node3")) { p0 @filter(ge(rating, 2.0)) '
    '{ p1 @groupby(p2) { count(uid) } } } }',
    # float aggregates over a val var (separate defining block)
    '{ var(func: has(name)) { r as rating } '
    '  q(func: eq(name, "node3")) { p0 { p1 @groupby(p2) '
    '{ count(uid) s: sum(val(r)) m: min(val(r)) x: max(val(r)) '
    '  a: avg(val(r)) } } } }',
    # int aggregates with missing members (score absent on i % 5 == 0)
    '{ var(func: has(name)) { sc as score } '
    '  q(func: eq(name, "node3")) { p0 { p1 @groupby(p2) '
    '{ count(uid) t: sum(val(sc)) mn: min(val(sc)) } } } }',
    # aggregate-only terminal, no count child
    '{ var(func: has(name)) { r as rating } '
    '  q(func: eq(name, "node3")) { p0 @groupby(p2) '
    '{ x: max(val(r)) } } }',
]


def test_terminal_battery_byte_identical_one_dispatch(pair):
    plain, mesh = pair
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    t = mesh.metrics.counter("dgraph_agg_terminal_ops_total")
    for q in TERMINAL_BATTERY:
        a, _ = plain.query(q)
        d0, t0 = c.value, t.value
        b, _ = mesh.query(q)
        assert c.value - d0 == 1, f"not one dispatch: {q}"
        assert t.value - t0 == 1, f"no terminal op: {q}"
        assert json.dumps(a, sort_keys=True, default=str) == \
            json.dumps(b, sort_keys=True, default=str), q


def test_terminal_cross_check_runs_and_groups_nonempty(pair, monkeypatch):
    """Guard against vacuous identity: the device terminal's key table /
    counts really reach the host cross-check, over a non-trivial group
    set (nested @groupby rows don't render in JSON — the byte-identity
    invariant for terminals IS the exact count/agg cross-check)."""
    from dgraph_tpu.query import groupby as gbmod

    _plain, mesh = pair
    seen = []
    orig = gbmod._fused_check_counts

    def spy(fused, row_seeds, members_per):
        seen.append((len(fused["table"]), len(row_seeds)))
        return orig(fused, row_seeds, members_per)

    monkeypatch.setattr(gbmod, "_fused_check_counts", spy)
    mesh.query(TERMINAL_BATTERY[3])
    assert seen and seen[0][0] >= 2 and seen[0][1] >= 2


def test_terminal_cross_check_has_teeth(pair, monkeypatch):
    """A corrupted device count vector must be a hard error, not a
    silent wrong answer."""
    from dgraph_tpu.query import groupby as gbmod
    from dgraph_tpu.query.engine import QueryError

    _plain, mesh = pair
    orig = gbmod._fused_check_counts

    def corrupt(fused, row_seeds, members_per):
        fused = dict(fused, counts=np.asarray(fused["counts"]) + 1)
        return orig(fused, row_seeds, members_per)

    monkeypatch.setattr(gbmod, "_fused_check_counts", corrupt)
    with pytest.raises(QueryError):
        mesh.query(TERMINAL_BATTERY[0])


def test_terminal_fuzz_roots(pair):
    """Terminal stage across root selectivities and both key tablets."""
    plain, mesh = pair
    for root in ('eq(name, "node1")', 'eq(name, "node42")', 'uid(0x1)',
                 'uid(0x1, 0x9, 0x20)'):
        for key in ("p2", "p1"):
            q = ('{ var(func: has(name)) { r as rating } '
                 '  q(func: %s) { p0 { follows @groupby(%s) '
                 '{ count(uid) s: sum(val(r)) } } } }' % (root, key))
            _same(plain, mesh, q)


# ---------------------------------------------------------------------------
# labeled fallbacks: reason=groupby / reason=agg
# ---------------------------------------------------------------------------

def test_value_key_groupby_falls_back_labeled(pair):
    plain, mesh = pair
    q = ('{ q(func: eq(name, "node3")) { p0 { p1 @groupby(name) '
         '{ count(uid) } } } }')
    before = _reasons(mesh).get("groupby", 0)
    _same(plain, mesh, q)
    assert _reasons(mesh).get("groupby", 0) > before


def test_multi_key_groupby_falls_back_labeled(pair):
    plain, mesh = pair
    q = ('{ q(func: eq(name, "node3")) { p0 { p1 @groupby(p2, follows) '
         '{ count(uid) } } } }')
    before = _reasons(mesh).get("groupby", 0)
    _same(plain, mesh, q)
    assert _reasons(mesh).get("groupby", 0) > before


def test_non_agg_child_falls_back_labeled(pair):
    plain, mesh = pair
    # a plain pred child inside the groupby block is outside the
    # terminal ops vocabulary (classic skips it; both paths identical)
    q = ('{ q(func: eq(name, "node3")) { p0 { p1 @groupby(p2) '
         '{ count(uid) name } } } }')
    before = _reasons(mesh).get("agg", 0)
    _same(plain, mesh, q)
    assert _reasons(mesh).get("agg", 0) > before


def test_non_numeric_val_var_stays_host_side(pair):
    """A string-valued var under __agg_min is structurally terminal but
    execution drops the device candidate — host answers, byte-identical."""
    plain, mesh = pair
    q = ('{ var(func: has(name)) { nm as name } '
         '  q(func: eq(name, "node3")) { p0 @groupby(p2) '
         '{ count(uid) w: min(val(nm)) } } }')
    _same(plain, mesh, q)


# ---------------------------------------------------------------------------
# EXPLAIN: the aggregation terminal renders est vs actual
# ---------------------------------------------------------------------------

def test_explain_groupby_rows(pair):
    plain, _mesh = pair
    out, _ = plain.query(
        '{ q(func: has(name)) @groupby(p2) { count(uid) } }',
        explain=True)
    blk = out["explain"]["blocks"][0]
    gb = blk["groupby"]
    assert gb["desc"] == "p2"
    assert gb["est"] >= 1
    assert gb["actual"] == len(out["q"][0]["@groupby"])
    assert gb["aggs"] == 1


def test_explain_groupby_child_level(pair):
    plain, _mesh = pair
    out, _ = plain.query(
        '{ var(func: has(name)) { r as rating } '
        '  q(func: eq(name, "node3")) { p0 @groupby(p2) '
        '{ count(uid) s: sum(val(r)) } } }', explain=True)
    q_blk = [b for b in out["explain"]["blocks"] if b["block"] == "q"][0]
    child = q_blk["children"][0]
    assert child["groupby"]["desc"] == "p2"
    assert child["groupby"]["aggs"] == 2
    assert child["groupby"]["actual"] is not None
