"""ops/segments + uidset host dispatchers + vectorized value-compare path.

Round-2 verdict item 6: no O(frontier) Python loop in the hot query path;
groupby aggregation as real segment reductions.
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.ops import segments as segs
from dgraph_tpu.ops import uidset as us


def test_segment_reduce_ops():
    vals = np.array([1, 2, 3, 10, np.nan, 5], dtype=np.float32)
    seg = np.array([0, 0, 1, 1, 2, 2], dtype=np.int32)
    assert segs.group_reduce("sum", seg, vals, 4).tolist()[:3] == [3, 13, 5]
    assert segs.group_reduce("min", seg, vals, 4).tolist()[:3] == [1, 3, 5]
    assert segs.group_reduce("max", seg, vals, 4).tolist()[:3] == [2, 10, 5]
    assert segs.group_reduce("avg", seg, vals, 4).tolist()[:3] == [1.5, 6.5, 5]
    assert segs.group_reduce("count", seg, vals, 4).tolist() == [2, 2, 1, 0]
    # group 3 has no members: NaN for value ops
    assert np.isnan(segs.group_reduce("sum", seg, vals, 4)[3])


def test_segment_reduce_empty():
    assert len(segs.group_reduce("sum", np.zeros(0, np.int32),
                                 np.zeros(0, np.float32), 0)) == 0
    out = segs.group_reduce("count", np.zeros(0, np.int32),
                            np.zeros(0, np.float32), 3)
    assert out.tolist() == [0, 0, 0]


def test_segment_reduce_rejects_bad_op():
    with pytest.raises(ValueError):
        segs.group_reduce("median", np.zeros(1, np.int32),
                          np.zeros(1, np.float32), 1)


@pytest.mark.parametrize("n", [10, 9000])
def test_host_dispatchers_match_numpy(rng, n):
    """Both the numpy and device branches agree with numpy set semantics
    (n=9000 crosses HOST_CUTOVER into the device path)."""
    a = np.unique(rng.integers(0, n * 4, size=n).astype(np.int64))
    b = np.unique(rng.integers(0, n * 4, size=n).astype(np.int64))
    np.testing.assert_array_equal(us.intersect_host(a, b), np.intersect1d(a, b))
    np.testing.assert_array_equal(us.union_host(a, b), np.union1d(a, b))
    np.testing.assert_array_equal(us.difference_host(a, b), np.setdiff1d(a, b))


def _value_node():
    node = Node()
    node.alter(schema_text="age: int @index(int) .\n"
               "score: float .\nborn: dateTime .\nname: string .")
    quads = []
    for i in range(1, 41):
        quads.append(f'<0x{i:x}> <name> "n{i}" .')
        if i % 3:
            quads.append(f'<0x{i:x}> <age> "{i}"^^<xs:int> .')
        if i % 2:
            quads.append(f'<0x{i:x}> <score> "{i}.5"^^<xs:float> .')
        quads.append(f'<0x{i:x}> <born> "20{i % 30 + 10}-01-02T03:04:05"^^<xs:dateTime> .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    return node


def test_vectorized_value_filters_match_semantics():
    node = _value_node()
    # numeric ineq filter over a frontier (vectorized num_values_host path)
    out, _ = node.query('{ q(func: has(name)) @filter(ge(age, 30)) { age } }')
    ages = sorted(r["age"] for r in out["q"])
    assert ages == [i for i in range(30, 41) if i % 3]
    # float compare
    out, _ = node.query('{ q(func: has(name)) @filter(eq(score, 7.5)) { score } }')
    assert [r["score"] for r in out["q"]] == [7.5]
    # datetime compare must be exact (f32 would round to ~128s)
    out, _ = node.query(
        '{ q(func: has(name)) @filter(eq(born, "2015-01-02T03:04:05")) { uid } }')
    assert len(out["q"]) == 2  # i=5 and i=35 -> i%30+10 == 15
    # has() via vectorized presence
    out, _ = node.query('{ q(func: has(name)) @filter(has(age)) { uid } }')
    assert len(out["q"]) == len([i for i in range(1, 41) if i % 3])


def test_groupby_segment_aggregation():
    node = _value_node()
    out, _ = node.query('''
    { var(func: has(name)) { a as age }
      q(func: has(age)) @groupby(g: born) {
        count(uid)
        s: sum(val(a))
        m: max(val(a))
        v: avg(val(a))
      } }''')
    rows = out["q"][0]["@groupby"]
    # every group's sum/max/avg must be consistent with its count
    total = sum(r["count"] for r in rows)
    assert total == len([i for i in range(1, 41) if i % 3])
    for r in rows:
        assert r["m"] <= 40 and r["s"] >= r["m"]
        assert abs(r["v"] - r["s"] / r["count"]) < 1e-4
