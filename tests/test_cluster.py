"""Multi-group cluster: tablet routing, cross-group txns, federated queries,
and the predicate-move protocol (reference worker/groups.go BelongsTo,
worker/mutation.go populateMutationMap, worker/predicate_move.go:86-177)."""

import numpy as np
import pytest

from dgraph_tpu.coord.cluster import Cluster, MoveInProgress
from dgraph_tpu.storage import keys as K


@pytest.fixture
def cluster():
    c = Cluster(n_groups=2)
    c.alter("""
        name: string @index(exact) .
        follows: [uid] @reverse @count .
        age: int @index(int) .
    """)
    return c


def _seed(c):
    c.mutate(set_nquads="""
        _:a <name> "ann" .
        _:a <age> "30" .
        _:b <name> "bob" .
        _:b <age> "41" .
        _:a <follows> _:b .
    """)


def test_tablets_split_across_groups(cluster):
    _seed(cluster)
    groups = {cluster.group_of(a) for a in ("name", "age", "follows")}
    assert len(groups) == 2          # load-balanced claim spread the tablets


def test_cross_group_txn_and_federated_query(cluster):
    _seed(cluster)
    out = cluster.query('{ q(func: eq(name, "ann")) { name age follows { name } } }')
    assert out == {"q": [{"name": "ann", "age": 30,
                          "follows": [{"name": "bob"}]}]}


def test_move_predicate_full_protocol(cluster):
    _seed(cluster)
    attr = "name"
    src = cluster.group_of(attr)
    dst = 1 - src
    before = cluster.query('{ q(func: eq(name, "bob"), orderasc: name) { name age } }')
    report = cluster.move_predicate(attr, dst)
    assert report["moved_keys"] > 0
    # ownership flipped; data gone at the source, fully served at the target
    assert cluster.group_of(attr) == dst
    assert not cluster.stores[src].keys_of(K.KeyKind.DATA, attr)
    assert cluster.stores[dst].keys_of(K.KeyKind.DATA, attr)
    after = cluster.query('{ q(func: eq(name, "bob"), orderasc: name) { name age } }')
    assert after == before
    # index keys moved too: eq() above used the exact index on the new group
    assert cluster.stores[dst].keys_of(K.KeyKind.INDEX, attr)


def test_move_blocks_writes_and_aborts_open_txns(cluster):
    _seed(cluster)
    attr = "age"
    dst = 1 - cluster.group_of(attr)
    # an open txn touching the predicate gets aborted by the move
    from dgraph_tpu.query import rdf
    from dgraph_tpu.query import mutation as mut
    from dgraph_tpu.storage.postings import Op
    st = cluster.zero.oracle.new_txn()
    edges = mut.to_edges(rdf.parse('<0x1> <age> "99" .'), {}, Op.SET)
    touched, conflict, preds = mut.apply_mutations(
        cluster.store_of(attr), edges, st.start_ts)
    cluster.zero.oracle.track(st.start_ts, conflict, sorted(preds))
    cluster._txn_keys[st.start_ts] = {cluster.group_of(attr): touched}
    report = cluster.move_predicate(attr, dst)
    assert report["aborted_txns"] == 1
    with pytest.raises(Exception):
        cluster.commit(st.start_ts)
    # the aborted write is invisible
    out = cluster.query('{ q(func: eq(name, "ann")) { age } }')
    assert out["q"][0]["age"] == 30


def test_writes_rejected_mid_move(cluster):
    _seed(cluster)
    cluster.zero.block_writes("age")
    with pytest.raises(MoveInProgress):
        cluster.mutate(set_nquads='<0x1> <age> "50" .')
    cluster.zero.unblock_writes("age")
    cluster.mutate(set_nquads='<0x1> <age> "50" .')
    out = cluster.query('{ q(func: eq(name, "ann")) { age } }')
    assert out["q"][0]["age"] == 50


def test_reverse_and_count_follow_the_move(cluster):
    _seed(cluster)
    attr = "follows"
    dst = 1 - cluster.group_of(attr)
    cluster.move_predicate(attr, dst)
    out = cluster.query('{ q(func: eq(name, "bob")) { ~follows { name } } }')
    assert out == {"q": [{"~follows": [{"name": "ann"}]}]}
    out = cluster.query('{ q(func: eq(count(follows), 1)) { name } }')
    assert out == {"q": [{"name": "ann"}]}


def test_move_to_same_group_noop(cluster):
    _seed(cluster)
    g = cluster.group_of("name")
    assert cluster.move_predicate("name", g) == {"moved_keys": 0,
                                                 "aborted_txns": 0}


def test_conflict_detection_spans_groups(cluster):
    _seed(cluster)
    from dgraph_tpu.coord.zero import TxnConflict
    from dgraph_tpu.query import rdf
    from dgraph_tpu.query import mutation as mut
    from dgraph_tpu.storage.postings import Op

    def open_write(val):
        st = cluster.zero.oracle.new_txn()
        edges = mut.to_edges(rdf.parse(f'<0x1> <age> "{val}" .'), {}, Op.SET)
        touched, conflict, preds = mut.apply_mutations(
            cluster.store_of("age"), edges, st.start_ts)
        cluster.zero.oracle.track(st.start_ts, conflict, sorted(preds))
        cluster._txn_keys[st.start_ts] = {cluster.group_of("age"): touched}
        return st.start_ts

    t1, t2 = open_write(71), open_write(72)
    cluster.commit(t1)
    with pytest.raises(TxnConflict):
        cluster.commit(t2)


def test_star_delete_spans_groups(cluster):
    _seed(cluster)
    # <0x1>=ann has name (one group) and age (the other); S * * must clear both
    out = cluster.query('{ q(func: eq(name, "ann")) { uid } }')
    uid = out["q"][0]["uid"]
    cluster.mutate(del_nquads=f"<{uid}> * * .")
    out = cluster.query(f'{{ q(func: uid({uid})) {{ name age }} }}')
    assert out == {}


def test_failed_mutation_aborts_oracle_txn(cluster):
    _seed(cluster)
    before = cluster.zero.oracle.pending_count()
    with pytest.raises(Exception):
        cluster.mutate(set_nquads='<0x1> <age> "not-an-int" .')
    assert cluster.zero.oracle.pending_count() == before
    # and a MoveInProgress rejection leaks nothing either (raises pre-txn)
    cluster.zero.block_writes("age")
    with pytest.raises(MoveInProgress):
        cluster.mutate(set_nquads='<0x1> <age> "77" .')
    cluster.zero.unblock_writes("age")
    assert cluster.zero.oracle.pending_count() == before


# -- auto-rebalance (dgraph/cmd/zero/tablet.go:60-74) ------------------------

def test_rebalance_moves_tablet_from_skewed_group(tmp_path):
    from dgraph_tpu.coord.cluster import Cluster

    c = Cluster(n_groups=2)
    c.alter("name: string @index(exact) .\nbig: string .\nsmall: int .")
    # force a skew: both heavy tablets on group 0
    c.zero.move_tablet("name", 0)
    c.zero.move_tablet("big", 0)
    c.zero.move_tablet("small", 1)
    c.mutate(set_nquads="\n".join(
        f'_:n{i} <name> "person{i}" .\n_:n{i} <big> "{"x" * 200}" .'
        for i in range(40)) + '\n_:n0 <small> "1"^^<xs:int> .')

    sizes = {g: sum(c.stores[g].tablet_sizes().values()) for g in (0, 1)}
    assert sizes[0] > sizes[1] / 0.85

    moved = c.rebalance_once()
    assert moved is not None and moved["src"] == 0 and moved["dst"] == 1
    # the map flipped and queries stay correct THROUGH the move
    assert c.zero.tablets()[moved["tablet"]] == 1
    out = c.query('{ q(func: eq(name, "person3")) { name big } }')
    assert out["q"][0]["name"] == "person3"
    assert len(out["q"][0]["big"]) == 200

    # balanced enough now: a second tick is a no-op or improves further
    again = c.rebalance_once()
    if again is not None:
        assert again["tablet"] != moved["tablet"]
    c.close()


def test_rebalancer_background_loop(tmp_path):
    import time as _t

    from dgraph_tpu.coord.cluster import Cluster

    c = Cluster(n_groups=2)
    c.alter("name: string @index(exact) .\nbig: string .")
    c.zero.move_tablet("name", 0)
    c.zero.move_tablet("big", 0)
    c.mutate(set_nquads="\n".join(
        f'_:n{i} <name> "p{i}" .\n_:n{i} <big> "{"y" * 150}" .'
        for i in range(30)))
    c.start_rebalancer(interval_s=0.1)
    deadline = _t.time() + 10
    while _t.time() < deadline:
        if len(set(c.zero.tablets().values())) == 2:
            break
        _t.sleep(0.05)
    assert len(set(c.zero.tablets().values())) == 2, c.zero.tablets()
    out = c.query('{ q(func: eq(name, "p7")) { name big } }')
    assert out["q"][0]["name"] == "p7"
    c.close()


def test_cluster_query_reuses_device_arrays():
    """Federated queries reuse per-predicate device arrays across calls;
    a commit touching one predicate re-folds only that predicate
    (VERDICT r3 weak#9)."""
    from dgraph_tpu.coord.cluster import Cluster

    c = Cluster(n_groups=2)
    c.alter("name: string @index(exact) .\nage: int .")
    c.zero.move_tablet("name", 0)
    c.zero.move_tablet("age", 1)
    c.mutate(set_nquads='_:a <name> "x" .\n_:a <age> "3"^^<xs:int> .')
    c.query('{ q(func: eq(name, "x")) { name age } }')
    snap1 = {attr: a._pred_cache.get(attr)
             for a, attr in ((c._assemblers[0], "name"),
                             (c._assemblers[1], "age"))}
    c.mutate(set_nquads='_:b <age> "9"^^<xs:int> .')   # touches age only
    out = c.query('{ q(func: eq(name, "x")) { name age } }')
    assert out["q"][0]["age"] == 3
    assert c._assemblers[0]._pred_cache["name"][1] is snap1["name"][1]
    assert c._assemblers[1]._pred_cache["age"][1] is not snap1["age"][1]
    # schema change invalidates; move keeps queries correct
    c.alter("nick: string @index(term) .")
    c.move_predicate("name", 1)
    out = c.query('{ q(func: eq(name, "x")) { name age } }')
    assert out["q"][0]["name"] == "x"
    c.close()


def test_choose_rebalance_move_decision_table():
    """The shared decision function (tablet.go:60-74 + chooseTablet :156)
    drives BOTH the in-process and zero-process rebalancers."""
    from dgraph_tpu.coord.zero import choose_rebalance_move as pick

    # balanced within the 85% ratio: no move
    assert pick({0: {"a": 100}, 1: {"b": 90}}) is None
    # single group: no move
    assert pick({0: {"a": 100}}) is None
    # imbalanced: the largest tablet fitting half the gap moves
    got = pick({0: {"a": 60, "b": 50}, 1: {"c": 10}})
    assert got == ("b", 0, 1, 50)     # gap=(110-10)/2=50; b fits, a doesn't
    # nothing fits half the gap (one huge tablet): no move (anti-thrash)
    assert pick({0: {"a": 200}, 1: {"b": 10}}) is None
    # blocked tablets are skipped (gap=38: a fits and sorts first, so only
    # the blocked check can force b)
    assert pick({0: {"a": 38, "b": 38}, 1: {}})[0] == "a"
    got = pick({0: {"a": 38, "b": 38}, 1: {}}, blocked={"a"})
    assert got[0] == "b"
    # empty smallest group with several comparable tablets
    got = pick({0: {"x": 30, "y": 29, "z": 28}, 1: {}})
    assert got[0] == "x" and got[2] == 1


def test_cluster_conflict_aborts_across_groups():
    """SSI conflict on a cross-group txn aborts every group's slice."""
    from dgraph_tpu.coord.cluster import Cluster
    from dgraph_tpu.coord.zero import TxnConflict

    c = Cluster(n_groups=2)
    c.alter("name: string @index(exact) @upsert .\nage: int .")
    c.zero.move_tablet("name", 0)
    c.zero.move_tablet("age", 1)
    c.mutate(set_nquads='<0x1> <name> "a" .\n<0x1> <age> "1"^^<xs:int> .')

    # two txns race on the same subject+predicate
    st1 = c.zero.oracle.new_txn()
    st2 = c.zero.oracle.new_txn()
    from dgraph_tpu.query import mutation as mut
    from dgraph_tpu.query import rdf
    from dgraph_tpu.storage.postings import Op

    def buffer(st, val):
        nq = rdf.parse(f'<0x1> <name> "{val}" .\n'
                       f'<0x1> <age> "9"^^<xs:int> .')
        edges = mut.to_edges(nq, {}, Op.SET)
        by_group = mut.split_edges_by_group(edges, 2, c.group_of)
        keys = {}
        conflicts = []
        for g, ge in by_group.items():
            touched, confl, preds = mut.apply_mutations(
                c.stores[g], ge, st.start_ts)
            keys[g] = touched
            conflicts += confl
        c.zero.oracle.track(st.start_ts, conflicts)
        return keys

    k1 = buffer(st1, "x")
    k2 = buffer(st2, "y")
    ts1 = c.zero.oracle.commit(st1.start_ts)
    for g, kb in k1.items():
        c.stores[g].commit(st1.start_ts, ts1, kb)
    with pytest.raises(TxnConflict):
        c.zero.oracle.commit(st2.start_ts)
    for g, kb in k2.items():
        c.stores[g].abort(st2.start_ts, kb)
    out = c.query('{ q(func: eq(name, "x")) { name age } }')
    assert out["q"] == [{"name": "x", "age": 9}]
    c.close()
