"""qcache.plan_attrs as the live-query touch test (ISSUE 18, satellite).

plan_attrs was born as a cache-invalidation key; live queries make it
load-bearing for CORRECTNESS: a commit to a predicate the plan reads but
plan_attrs omits would leave a standing subscription silently stale.
These tests pin the contract across the non-chain roots — @recurse,
shortest, similar_to, @groupby terminals, reverse edges, order/filter
trees — with a differential oracle on top: for every shape whose attr
set claims to be exact (not None), mutating any predicate that CHANGES
the query's result must be a predicate in the set. Under-approximation
is a test failure here, not a stale feed in production."""

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.live.diff import canon
from dgraph_tpu.query import dql, qcache

SCHEMA = """
name: string @index(term) .
age: int @index(int) .
score: float .
friend: [uid] @reverse .
emb: float32vector @index(vector(dim: 2, metric: l2)) .
"""


def attrs_of(q: str):
    return qcache.subscription_attrs(dql.parse(q))


# -- static shape coverage ---------------------------------------------------

def test_recurse_covers_recursed_predicates():
    a = attrs_of('{ q(func: eq(name, "a")) @recurse(depth: 3) '
                 "{ name friend } }")
    assert a is not None and {"name", "friend"} <= a


def test_recurse_with_loop_and_filter():
    a = attrs_of('{ q(func: has(name)) @recurse(depth: 2, loop: true) '
                 "{ name friend @filter(ge(age, 10)) } }")
    assert a is not None and {"name", "friend", "age"} <= a


def test_shortest_is_wildcard_not_underapproximated():
    # shortest reads path predicates dynamically; the only safe static
    # answer is None (wake on every commit) — a concrete set that missed
    # the traversed edge would be silently stale
    a = attrs_of("{ path as shortest(from: 0x1, to: 0x4) { friend } }")
    assert a is None


def test_similar_to_covers_vector_predicate():
    a = attrs_of('{ q(func: similar_to(emb, "[0.1, 0.2]", 4)) '
                 "{ uid name } }")
    assert a is not None and {"emb", "name"} <= a


def test_groupby_covers_grouped_attr():
    a = attrs_of("{ q(func: has(name)) @groupby(age) { count(uid) } }")
    assert a is not None and {"name", "age"} <= a


def test_groupby_with_val_aggregate():
    a = attrs_of("{ var(func: has(name)) { s as score } "
                 "q(func: has(name)) @groupby(age) "
                 "{ count(uid) m : max(val(s)) } }")
    assert a is not None and {"name", "age", "score"} <= a


def test_reverse_edge_strips_to_forward_attr():
    a = attrs_of("{ q(func: has(name)) { uid ~friend { name } } }")
    assert a is not None and "friend" in a and "~friend" not in a


def test_order_and_nested_filter_tree():
    a = attrs_of('{ q(func: has(name), orderasc: age) '
                 "@filter(ge(score, 0.5) OR (has(friend) AND "
                 'anyofterms(name, "x"))) { uid } }')
    assert a is not None and {"name", "age", "score", "friend"} <= a


def test_uids_and_expand_are_wildcards():
    assert attrs_of("{ q(func: uid(0x1)) { name } }") is None
    assert attrs_of("{ q(func: has(name)) { expand(_all_) } }") is None


# -- differential oracle -----------------------------------------------------

SHAPES = [
    '{ q(func: eq(name, "root")) @recurse(depth: 3) { name friend } }',
    "{ q(func: has(name)) @groupby(age) { count(uid) } }",
    '{ q(func: similar_to(emb, "[0.5, 0.5]", 3)) { uid name } }',
    "{ q(func: has(age)) { uid ~friend { name } } }",
    "{ q(func: has(name), orderasc: age) @filter(ge(score, 0.0)) "
    "{ uid name score } }",
]

# every predicate any differential probe below mutates
PROBE_PREDS = ("name", "age", "score", "friend", "emb")

PROBES = {
    "name": '<0x51> <name> "probe" .',
    "age": '<0x52> <age> "77" .',
    "score": '<0x53> <score> "0.25" .',
    "friend": "<0x54> <friend> <0x1> .",
    "emb": '<0x55> <emb> "[0.9, 0.1]"^^<xs:float32vector> .',
}


@pytest.fixture(scope="module")
def seeded_node():
    n = Node()
    n.alter(SCHEMA)
    n.mutate(set_nquads="\n".join([
        '<0x1> <name> "root" .', '<0x1> <age> "30" .',
        '<0x1> <score> "1.5" .', '<0x2> <name> "leaf" .',
        '<0x2> <age> "20" .', '<0x2> <score> "0.5" .',
        "<0x1> <friend> <0x2> .", '<0x1> <emb> "[0.5, 0.5]"^^<xs:float32vector> .',
        '<0x2> <emb> "[0.4, 0.6]"^^<xs:float32vector> .',
    ]), commit_now=True)
    yield n
    n.close()


@pytest.mark.parametrize("q", SHAPES)
def test_no_underapproximation_differential(seeded_node, q):
    """If mutating predicate P changes the query's result, P MUST be in
    the subscription attr set (or the set must be the None wildcard).
    This is exactly the property notification correctness rests on."""
    n = seeded_node
    attrs = attrs_of(q)
    if attrs is None:
        return                  # wildcard wakes on everything: safe
    for pred in PROBE_PREDS:
        before = canon(n.query(q)[0])
        n.mutate(set_nquads=PROBES[pred], commit_now=True)
        after = canon(n.query(q)[0])
        n.mutate(del_nquads=_del_form(PROBES[pred]), commit_now=True)
        if before != after:
            assert pred in attrs, (
                f"mutating {pred!r} changed the result of {q!r} but "
                f"plan_attrs={sorted(attrs)} omits it — a live "
                f"subscription would go silently stale")


def _del_form(set_quad: str) -> str:
    subj, pred, _rest = set_quad.split(None, 2)
    return f"{subj} {pred} * ."


def test_differential_catches_a_lying_attr_set(seeded_node):
    """Sanity on the oracle itself: a deliberately under-approximated set
    trips the same assertion the real shapes are held to."""
    n = seeded_node
    q = "{ q(func: has(name)) { uid name } }"
    lying = frozenset({"age"})          # pretends `name` is not read
    before = canon(n.query(q)[0])
    n.mutate(set_nquads=PROBES["name"], commit_now=True)
    after = canon(n.query(q)[0])
    n.mutate(del_nquads=_del_form(PROBES["name"]), commit_now=True)
    assert before != after
    assert "name" not in lying          # the under-approximation is real
    real = attrs_of(q)
    assert real is not None and "name" in real
