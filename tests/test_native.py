"""Native C++ codec (native/codec.cc via storage/native.py) must be
bit-identical to the numpy codec — same wire format, every width class."""

import numpy as np
import pytest

from dgraph_tpu.storage import native, packed

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def _eq(a: packed.PackedUidList, b: packed.PackedUidList):
    assert a.count == b.count
    np.testing.assert_array_equal(a.block_first, b.block_first)
    np.testing.assert_array_equal(a.block_last, b.block_last)
    np.testing.assert_array_equal(a.block_count, b.block_count)
    np.testing.assert_array_equal(a.block_width, b.block_width)
    np.testing.assert_array_equal(a.block_off, b.block_off)
    np.testing.assert_array_equal(a.words, b.words)


CASES = [
    np.zeros(0, np.uint64),
    np.array([7], np.uint64),
    np.arange(1, 129, dtype=np.uint64),                    # exactly one block
    np.arange(1, 130, dtype=np.uint64),                    # block boundary +1
    np.cumsum(np.ones(1000, np.uint64)),                   # width 1
    np.cumsum(np.full(5000, 1 << 20, np.uint64)),          # width 21
    np.array([1, 2, 3, 1 << 40, (1 << 40) + 5], np.uint64),  # raw64 escape
]


@pytest.mark.parametrize("uids", CASES, ids=range(len(CASES)))
def test_pack_bit_identical(uids):
    _eq(native.pack(uids), packed.pack(uids))


def test_random_roundtrip(rng):
    for _ in range(20):
        n = int(rng.integers(1, 3000))
        gaps = rng.integers(1, 1 << int(rng.integers(1, 34)), size=n)
        uids = np.cumsum(gaps.astype(np.uint64))
        npl, ppl = native.pack(uids), packed.pack(uids)
        _eq(npl, ppl)
        np.testing.assert_array_equal(native.unpack(ppl), uids)
        np.testing.assert_array_equal(packed.unpack(npl), uids)


def test_pack_many_matches(rng):
    rows = []
    for _ in range(200):
        n = int(rng.integers(0, 400))
        rows.append(np.cumsum(rng.integers(1, 1000, size=n).astype(np.uint64)))
    rows.append(np.array([3, 1 << 45], np.uint64))          # raw row
    nat = native.pack_many(rows)
    ref = packed.pack_many(rows)
    for a, b in zip(nat, ref):
        _eq(a, b)
    for a, r in zip(nat, rows):
        np.testing.assert_array_equal(packed.unpack(a), r)


def test_seek_contract_native(rng):
    uids = np.cumsum(rng.integers(1, 50, size=4000).astype(np.uint64))
    pl = native.pack(uids)
    for probe in [0, int(uids[17]), int(uids[-1]), int(uids[-1]) + 10]:
        b = packed.seek_block(pl, probe)
        if b < pl.nblocks:
            assert pl.block_last[b] > probe
        if b > 0:
            assert pl.block_last[b - 1] <= probe


def test_unpack_many_matches(rng):
    rows = []
    for _ in range(300):
        n = int(rng.integers(0, 500))
        rows.append(np.cumsum(rng.integers(1, 1 << 22, size=n).astype(np.uint64)))
    rows.append(np.array([9, 1 << 40], np.uint64))
    pls = packed.pack_many(rows)
    nat = native.unpack_many(pls)
    ref = packed.unpack_many(pls)
    assert len(nat) == len(ref)
    for a, b, r in zip(nat, ref, rows):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, r)
