"""Delta-overlay posting maintenance: O(Δ) commit-to-visible.

Covers the storage/delta.py overlay tier end to end: stamping visibility,
byte-identity against from-scratch folds, device base-array identity,
background compaction, per-predicate cache invalidation, the journal
fallbacks, and the SnapshotAssembler replay-race staleness branch
(pred_replay_seq) that previously had no direct test.
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.storage.csr_build import SnapshotAssembler, build_pred
from dgraph_tpu.storage.delta import OverlayCSR


SCHEMA = ("name: string @index(exact, term) .\n"
          "age: int @index(int) .\n"
          "follows: [uid] @reverse .\n")


def small_node(n=200, follows=3) -> Node:
    node = Node()
    node.alter(schema_text=SCHEMA)
    quads = []
    for i in range(1, n + 1):
        quads.append(f'<0x{i:x}> <name> "p{i}" .')
        quads.append(f'<0x{i:x}> <age> "{18 + i % 40}"^^<xs:int> .')
        for j in range(follows):
            quads.append(f'<0x{i:x}> <follows> <0x{(i + j) % n + 1:x}> .')
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    return node


def assert_pred_equal(a, b):
    """Byte-identity between two PredData views of the same data."""
    def csr_arrays(csr):
        if csr is None:
            return (np.zeros(0, np.int64),) * 3
        s, ip, ix = csr.host_arrays()
        return (np.asarray(s, np.int64), np.asarray(ip, np.int64),
                np.asarray(ix, np.int64))

    for ca, cb in ((a.csr, b.csr), (a.rev_csr, b.rev_csr)):
        for x, y in zip(csr_arrays(ca), csr_arrays(cb)):
            assert np.array_equal(x, y), (x, y)
    for fa, fb in ((a.value_subjects_host, b.value_subjects_host),
                   (a.num_values_host, b.num_values_host)):
        if fa is None or fb is None:
            assert (fa is None or not len(fa)) and (fb is None or not len(fb))
        else:
            assert np.array_equal(fa, fb, equal_nan=True)
    assert a.host_values == b.host_values
    assert a.list_values == b.list_values
    assert a.lang_values == b.lang_values
    assert a.facets == b.facets
    assert sorted(a.indexes) == sorted(b.indexes)
    for name in a.indexes:
        ta, tb = a.indexes[name], b.indexes[name]
        assert ta.terms == tb.terms, name
        ia, ua = ta.host_arrays()
        ib, ub = tb.host_arrays()
        assert np.array_equal(np.asarray(ia), np.asarray(ib)), name
        assert np.array_equal(np.asarray(ua), np.asarray(ub)), name


def test_single_quad_commit_stamps_overlay_and_keeps_base_identity():
    node = small_node()
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    base_csr = node.snapshot().preds["follows"].csr
    base_subjects = base_csr.subjects

    node.mutate(set_nquads='<0x1> <follows> <0x64> .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    got = {x["uid"] for x in out["q"][0]["follows"]}
    assert "0x64" in got                      # commit is visible

    csr = node.snapshot().preds["follows"].csr
    assert isinstance(csr, OverlayCSR)        # stamped, not re-folded
    assert csr.base.subjects is base_subjects  # device identity preserved
    assert csr.base.indices is base_csr.indices
    assert node.metrics.counter("dgraph_overlay_stamps_total").value >= 1
    node.close()


def test_overlay_reads_byte_identical_to_full_fold():
    node = small_node()
    # prime the pred cache for every stamped predicate: lazy folds
    # (ISSUE 15) build a base only on first read, and only a READ
    # predicate has a base for the overlay stamp to land on
    node.query('{ q(func: has(name)) { name age follows { uid } } }')
    node.mutate(set_nquads='\n'.join([
        '<0x1> <follows> <0x80> .',
        '<0x2> <name> "renamed" .',
        '<0x3> <age> "99"^^<xs:int> .',
    ]), commit_now=True)
    node.mutate(del_nquads='<0x4> <follows> * .', commit_now=True)
    node.mutate(del_nquads='<0x5> <name> * .', commit_now=True)
    ts = node.store.max_seen_commit_ts
    snap = node.snapshot(ts)
    assert isinstance(snap.preds["follows"].csr, OverlayCSR)
    for attr in ("name", "age", "follows"):
        assert_pred_equal(snap.preds[attr], build_pred(node.store, attr, ts))
    node.close()


def test_value_overlay_serves_eq_has_sort_and_index():
    node = small_node()
    node.query('{ q(func: has(age)) { age } }')
    node.mutate(set_nquads='<0x1> <age> "99"^^<xs:int> .\n'
                           '<0x2> <name> "zzz" .', commit_now=True)
    out, _ = node.query('{ q(func: eq(age, 99)) { uid age } }')
    assert out["q"] == [{"uid": "0x1", "age": 99}]
    out, _ = node.query('{ q(func: eq(name, "zzz")) { uid } }')
    assert out["q"] == [{"uid": "0x2"}]
    out, _ = node.query('{ q(func: ge(age, 99)) { uid } }')
    assert out["q"] == [{"uid": "0x1"}]
    out, _ = node.query(
        '{ q(func: has(age), orderdesc: age, first: 1) { uid age } }')
    assert out["q"] == [{"uid": "0x1", "age": 99}]
    node.close()


def test_reverse_count_and_has_on_overlaid_predicate():
    node = small_node()
    node.query('{ q(func: has(follows)) { uid } }')
    node.mutate(set_nquads='<0x1> <follows> <0x64> .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x64)) { ~follows { uid } } }')
    assert "0x1" in {x["uid"] for x in out["q"][0]["~follows"]}
    out, _ = node.query('{ q(func: eq(count(follows), 4)) { uid } }')
    assert [x["uid"] for x in out["q"]] == ["0x1"]
    out, _ = node.query('{ q(func: has(follows)) { uid } }')
    assert "0x1" in {x["uid"] for x in out["q"]}
    node.close()


def test_compaction_empties_overlay_and_results_unchanged():
    node = small_node()
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    node.mutate(set_nquads='<0x1> <follows> <0x64> .', commit_now=True)
    before, _ = node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    assert node._assembler.overlay_stats()      # an overlay is live

    done = node._assembler.compact(node._lock, force=True)
    assert done >= 1
    assert node._assembler.overlay_stats() == {}    # overlay is empty
    assert node.store.delta_since(
        "follows", node.store.pred_commit_ts["follows"]) == {}
    after, _ = node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    assert after == before                      # results unchanged
    csr = node.snapshot().preds["follows"].csr
    assert not isinstance(csr, OverlayCSR)      # folded base again
    assert node.metrics.counter("dgraph_compactions_total").value >= 1
    node.close()


def test_deep_overlay_compacts_inline_via_fold():
    node = small_node()
    node._assembler.OVERLAY_MAX_KEYS = 2
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    quads = "\n".join(f'<0x{i:x}> <follows> <0x90> .' for i in range(1, 9))
    node.mutate(set_nquads=quads, commit_now=True)   # 8 keys > ceiling
    out, _ = node.query('{ q(func: uid(0x3)) { follows { uid } } }')
    assert "0x90" in {x["uid"] for x in out["q"][0]["follows"]}
    csr = node.snapshot().preds["follows"].csr
    assert not isinstance(csr, OverlayCSR)      # folded, not stamped
    node.close()


def test_overlay_disabled_still_correct():
    node = small_node()
    node._assembler.overlay_enabled = False
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    node.mutate(set_nquads='<0x1> <follows> <0x64> .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    assert "0x64" in {x["uid"] for x in out["q"][0]["follows"]}
    assert not isinstance(node.snapshot().preds["follows"].csr, OverlayCSR)
    node.close()


def test_journal_overflow_falls_back_to_fold():
    node = small_node()
    node.store.MAX_DELTA_KEYS = 4
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    quads = "\n".join(f'<0x{i:x}> <follows> <0x90> .' for i in range(1, 9))
    node.mutate(set_nquads=quads, commit_now=True)   # overflows the journal
    assert node.store.delta_since(
        "follows", node.store.pred_commit_ts["follows"] - 1) is None
    out, _ = node.query('{ q(func: uid(0x5)) { follows { uid } } }')
    assert "0x90" in {x["uid"] for x in out["q"][0]["follows"]}
    # the fold re-based stamping: the NEXT small commit overlays again
    node.mutate(set_nquads='<0x1> <follows> <0x91> .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    assert "0x91" in {x["uid"] for x in out["q"][0]["follows"]}
    assert isinstance(node.snapshot().preds["follows"].csr, OverlayCSR)
    node.close()


def test_uid_only_commit_keeps_value_table_identity():
    node = small_node()
    node.query('{ q(func: has(age)) { age } }')
    pd1 = node.snapshot().preds["age"]
    node.mutate(set_nquads='<0x1> <follows> <0x64> .', commit_now=True)
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    pd2 = node.snapshot().preds["age"]
    assert pd2 is pd1     # untouched predicate: same object, same arrays
    node.close()


def test_per_predicate_invalidation_preserves_cache_heat():
    """A commit to predicate A must not evict task/result cache entries of
    queries that only read predicate B (the overlay tier's cache contract:
    per-PredData tokens instead of one global snapshot token)."""
    node = small_node()
    qb = '{ q(func: eq(name, "p7")) { name } }'
    node.query(qb)
    out1, _ = node.query(qb)                 # fills + hits result cache
    hits0 = node.metrics.counter("dgraph_result_cache_hits_total").value
    task_hits0 = node.metrics.counter("dgraph_task_cache_hits_total").value
    assert hits0 >= 1

    node.mutate(set_nquads='<0x1> <age> "77"^^<xs:int> .', commit_now=True)
    out2, _ = node.query(qb)                 # age commit: name heat survives
    assert out2 == out1
    assert node.metrics.counter(
        "dgraph_result_cache_hits_total").value > hits0
    assert node.metrics.counter(
        "dgraph_cache_invalidations_avoided_total").value > 0

    # and the changed predicate itself must NOT be served stale
    out, _ = node.query('{ q(func: eq(age, 77)) { uid } }')
    assert out["q"] == [{"uid": "0x1"}]
    node.close()


def test_replay_race_rebuilds_cached_view():
    """The pred_replay_seq branch of SnapshotAssembler._stale: a commit
    REPLAYED below the predicate's watermark after assembly (out-of-order
    WAL/replication apply) must rebuild the cached view — the max-only
    watermark alone cannot see it."""
    from dgraph_tpu.query import mutation as mut
    from dgraph_tpu.storage.postings import DirectedEdge
    from dgraph_tpu.storage.store import Store, encode_record, decode_record
    from dgraph_tpu.utils.schema import parse_schema
    from dgraph_tpu.utils.types import TypeID, Val

    s = Store()
    for e in parse_schema("a: int ."):
        s.set_schema(e)
    touched, _, _ = mut.apply_mutations(
        s, [DirectedEdge(1, "a", value=Val(TypeID.INT, 1))], 1)
    s.commit(1, 2, touched)
    touched, _, _ = mut.apply_mutations(
        s, [DirectedEdge(2, "a", value=Val(TypeID.INT, 2))], 9)
    s.commit(9, 10, touched)

    asm = SnapshotAssembler(s)
    snap1 = asm.snapshot(10)
    assert snap1.preds["a"].host_values == {1: Val(TypeID.INT, 1),
                                           2: Val(TypeID.INT, 2)}

    # an out-of-order WAL record pair lands BELOW the watermark (ts 4 < 10)
    # through the replication/replay apply path — exactly what a follower
    # sees when a lagging leader re-ships history
    from dgraph_tpu.storage import keys as K
    from dgraph_tpu.storage.postings import Op, Posting
    kb3 = K.data_key("a", 3).encode()
    for rec in ({"t": "m", "s": 3, "k": kb3,
                 "p": Posting(0, Op.SET, Val(TypeID.INT, 33))},
                {"t": "c", "s": 3, "ts": 4, "k": [kb3]}):
        s.apply_record(decode_record(encode_record(rec)))
    assert s.pred_replay_seq.get("a", 0) == 1
    assert s.pred_commit_ts["a"] == 10          # watermark did NOT move

    snap2 = asm.snapshot(10)
    assert snap2 is not snap1                   # cached view was rebuilt
    assert snap2.preds["a"].host_values[3] == Val(TypeID.INT, 33)


def test_parallel_fold_matches_serial():
    node = small_node(n=50)
    ts = node.store.max_seen_commit_ts
    from dgraph_tpu.storage.csr_build import build_snapshot

    ser = build_snapshot(node.store, ts, fold_workers=1)
    par = build_snapshot(node.store, ts, fold_workers=4)
    assert sorted(ser.preds) == sorted(par.preds)
    for attr in ser.preds:
        assert_pred_equal(ser.preds[attr], par.preds[attr])
    node.close()


def test_background_rollup_loop_compacts_aged_overlay():
    node = small_node(n=50)
    node._assembler.OVERLAY_MAX_AGE_S = 0.05
    node.ROLLUP_TICK_S = 0.05
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    node.mutate(set_nquads='<0x1> <follows> <0x20> .', commit_now=True)
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    assert node._assembler.overlay_stats()
    import time

    deadline = time.time() + 5
    while time.time() < deadline and node._assembler.overlay_stats():
        time.sleep(0.05)
    assert node._assembler.overlay_stats() == {}
    out, _ = node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    assert "0x20" in {x["uid"] for x in out["q"][0]["follows"]}
    node.close()


def test_overlay_on_edgeless_base_tablet():
    """An overlay stamped onto a predicate whose folded base has NO edges
    (all deleted, then compacted) has base csr None — the merge-on-read
    plan must serve the delta-born rows instead of indexing an empty
    indptr (regression: IndexError in OverlayCSR.frontier_plan)."""
    node = Node()
    node.alter(schema_text="friend: [uid] .")
    node.mutate(set_nquads='<0x1> <friend> <0x2> .', commit_now=True)
    node.query('{ q(func: uid(0x1)) { friend { uid } } }')
    node.mutate(del_nquads='<0x1> <friend> <0x2> .', commit_now=True)
    node.query('{ q(func: uid(0x1)) { friend { uid } } }')
    node._assembler.compact(node._lock, force=True)   # base: csr=None
    node.mutate(set_nquads='<0x1> <friend> <0x3> .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x1)) { friend { uid } } }')
    assert [x["uid"] for x in out["q"][0]["friend"]] == ["0x3"]
    ts = node.store.max_seen_commit_ts
    assert_pred_equal(node.snapshot(ts).preds["friend"],
                      build_pred(node.store, "friend", ts))
    node.close()


def test_expand_masked_matches_expand_with_patch():
    """ops/csr.expand_masked: the base half of the overlay merge leaves
    patched slots empty for the host splice."""
    import jax.numpy as jnp

    from dgraph_tpu.ops import csr as csrops
    from dgraph_tpu.ops.uidset import SENTINEL32

    indptr = jnp.asarray(np.asarray([0, 2, 5, 6], np.int32))
    indices = jnp.asarray(np.asarray([1, 2, 3, 4, 5, 9], np.int32))
    rows = jnp.asarray(np.asarray([0, 1, 2], np.int32))
    patched = np.asarray([False, True, False])
    res = csrops.expand_masked(indptr, indices, rows, patched, out_cap=8)
    counts = np.asarray(res.counts)
    assert counts.tolist() == [2, 0, 1]
    targets = np.asarray(res.targets)[: int(res.total)]
    assert targets.tolist() == [1, 2, 9]
