"""Transaction oracle: SSI conflict detection + the bank-invariant hammer.

Reference: dgraph/cmd/zero/oracle.go:71-83 (hasConflict), :276-320 (commit),
assign.go (leases); contrib/integration/bank/ (balance-invariant ACID test).
"""

import threading

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import Oracle, TxnConflict, UidLease


def test_oracle_conflict_detection():
    o = Oracle()
    t1 = o.new_txn()
    t2 = o.new_txn()
    o.track(t1.start_ts, [b"key-a"])
    o.track(t2.start_ts, [b"key-a"])
    c1 = o.commit(t1.start_ts)
    assert c1 > t2.start_ts
    with pytest.raises(TxnConflict):
        o.commit(t2.start_ts)           # first committer wins
    # disjoint keys don't conflict
    t3, t4 = o.new_txn(), o.new_txn()
    o.track(t3.start_ts, [b"key-b"])
    o.track(t4.start_ts, [b"key-c"])
    assert o.commit(t3.start_ts) < o.commit(t4.start_ts)


def test_oracle_no_conflict_after_start():
    o = Oracle()
    t1 = o.new_txn()
    o.track(t1.start_ts, [b"k"])
    o.commit(t1.start_ts)
    t2 = o.new_txn()                    # starts AFTER t1 committed
    o.track(t2.start_ts, [b"k"])
    o.commit(t2.start_ts)               # sees t1's write: no conflict


def test_uid_lease_blocks():
    lease = UidLease()
    s1, e1 = lease.assign(10)
    s2, _ = lease.assign(5)
    assert s1 == 1 and e1 == 10 and s2 == 11


def test_node_level_conflict():
    n = Node()
    n.alter(schema_text="balance: int .")
    n.mutate(set_nquads='<0x1> <balance> "100"^^<xs:int> .', commit_now=True)
    r1 = n.mutate(set_nquads='<0x1> <balance> "150"^^<xs:int> .')
    r2 = n.mutate(set_nquads='<0x1> <balance> "90"^^<xs:int> .')
    n.commit(r1.context.start_ts)
    with pytest.raises(TxnConflict):
        n.commit(r2.context.start_ts)
    out, _ = n.query('{ q(func: uid(0x1)) { balance } }')
    assert out["q"][0]["balance"] == 150


def test_bank_hammer():
    """N threads transfer between accounts with conflicting txns; the total
    balance is invariant and every conflicting commit aborts cleanly."""
    n = Node()
    n.alter(schema_text="balance: int .")
    ACCTS = 5
    START = 100
    for i in range(1, ACCTS + 1):
        n.mutate(set_nquads=f'<{hex(i)}> <balance> "{START}"^^<xs:int> .',
                 commit_now=True)

    aborts = [0]
    commits = [0]
    lock = threading.Lock()

    def worker(seed: int):
        import random

        rng = random.Random(seed)
        for _ in range(20):
            a, b = rng.sample(range(1, ACCTS + 1), 2)
            amt = rng.randint(1, 10)
            ctx = n.new_txn()           # read AND write inside one txn
            try:
                out, _ = n.query(
                    f'{{ A(func: uid({a})) {{ balance }} '
                    f'B(func: uid({b})) {{ balance }} }}',
                    start_ts=ctx.start_ts)
                bal_a = out["A"][0]["balance"]
                bal_b = out["B"][0]["balance"]
                n.mutate(set_nquads=(
                    f'<{hex(a)}> <balance> "{bal_a - amt}"^^<xs:int> .\n'
                    f'<{hex(b)}> <balance> "{bal_b + amt}"^^<xs:int> .'),
                    start_ts=ctx.start_ts)
                n.commit(ctx.start_ts)
                with lock:
                    commits[0] += 1
            except TxnConflict:
                with lock:
                    aborts[0] += 1

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    out, _ = n.query('{ q(func: has(balance)) { balance } }')
    total = sum(x["balance"] for x in out["q"])
    assert total == ACCTS * START, (total, commits[0], aborts[0])
    assert commits[0] > 0
    # with 8 threads hammering 5 accounts, conflicts must occur — if none
    # did, the SSI check silently stopped firing
    assert aborts[0] > 0, "expected at least one SSI abort"


def test_read_snapshot_isolation_during_txn():
    n = Node()
    n.alter(schema_text="v: int .")
    n.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    snap_ts = n.zero.oracle.read_ts()
    n.mutate(set_nquads='<0x1> <v> "2"^^<xs:int> .', commit_now=True)
    out, _ = n.query('{ q(func: uid(0x1)) { v } }', start_ts=snap_ts)
    assert out["q"][0]["v"] == 1
    out, _ = n.query('{ q(func: uid(0x1)) { v } }')
    assert out["q"][0]["v"] == 2
