"""Bulk/live loader, xidmap, and RDF export round-trip tests.

Reference: dgraph/cmd/bulk (map/shuffle/reduce to packed lists),
dgraph/cmd/live (batched txns), xidmap/xidmap.go, worker/export.go, and
systest/bulk_live_cases_test.go's bulk-vs-live equivalence pattern.
"""

import gzip
import os

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import UidLease
from dgraph_tpu.loader import XidMap, bulk_load, export_rdf, live_load
from dgraph_tpu.loader.bulk import BulkError
from dgraph_tpu.storage.store import Store

SCHEMA = """
name: string @index(exact, term) .
age: int @index(int) .
follows: [uid] @reverse @count .
bio: string @lang .
weight: float .
"""

RDF = """\
_:alice <name> "Alice" .
_:alice <age> "30"^^<xs:int> .
_:alice <bio> "hello"@en .
_:alice <bio> "bonjour"@fr .
_:alice <weight> "62.5"^^<xs:float> .
_:bob <name> "Bob" .
_:bob <age> "25"^^<xs:int> .
_:alice <follows> _:bob (since=2006) .
_:bob <follows> _:carol .
_:carol <name> "Carol rhymes with \\"parol\\"" .
_:carol <follows> _:alice .
_:carol <follows> _:bob .
"""


def _write(tmp_path, text, name="data.rdf", gz=False):
    p = os.path.join(tmp_path, name)
    if gz:
        p += ".gz"
        with gzip.open(p, "wt") as f:
            f.write(text)
    else:
        with open(p, "w") as f:
            f.write(text)
    return p


@pytest.mark.parametrize("gz", [False, True])
def test_bulk_load_then_query(tmp_path, gz):
    rdf_path = _write(str(tmp_path), RDF, gz=gz)
    out = os.path.join(str(tmp_path), "p")
    stats = bulk_load(rdf_path, SCHEMA, out, workers=1)
    assert stats.uid_edges == 4 and stats.values == 8
    assert stats.nodes == 3 and stats.xids == 3

    node = Node(out)
    q, _ = node.query('{ q(func: eq(name, "Alice")) '
                      '{ name age weight bio@fr follows { name } '
                      '  fc: count(follows) } }')
    row = q["q"][0]
    assert row["name"] == "Alice" and row["age"] == 30
    assert row["weight"] == 62.5 and row["bio@fr"] == "bonjour"
    assert row["fc"] == 1 and row["follows"][0]["name"] == "Bob"
    # reverse + facet survive
    q2, _ = node.query('{ q(func: eq(name, "Bob")) '
                       '{ ~follows @facets(since) { name } } }')
    assert sorted(x["name"] for x in q2["q"][0]["~follows"]) \
        == ["Alice", "Carol rhymes with \"parol\""]
    # term index built by the bulk path
    q3, _ = node.query('{ q(func: anyofterms(name, "Carol")) { name } }')
    assert len(q3["q"]) == 1
    # mutations keep working on a bulk-loaded store (lease recovered)
    res = node.mutate(set_nquads='_:dan <name> "Dan" .\n'
                      '_:dan <follows> <0x1> .', commit_now=True)
    assert res.uids["_:dan"] > 3
    q4, _ = node.query('{ q(func: eq(name, "Dan")) { name } }')
    assert q4["q"] == [{"name": "Dan"}]
    node.close()


def test_bulk_refuses_nonempty_dir(tmp_path):
    rdf_path = _write(str(tmp_path), RDF)
    out = os.path.join(str(tmp_path), "p")
    bulk_load(rdf_path, SCHEMA, out, workers=1)
    with pytest.raises(BulkError, match="already contains"):
        bulk_load(rdf_path, SCHEMA, out, workers=1)


def test_bulk_rejects_deletes(tmp_path):
    rdf_path = _write(str(tmp_path), '<0x1> <name> * .\n')
    with pytest.raises(BulkError, match="delete"):
        bulk_load(rdf_path, SCHEMA, os.path.join(str(tmp_path), "p"),
                  workers=1)


def test_export_roundtrip(tmp_path):
    rdf_path = _write(str(tmp_path), RDF)
    out1 = os.path.join(str(tmp_path), "p1")
    bulk_load(rdf_path, SCHEMA, out1, workers=1)

    exp1 = os.path.join(str(tmp_path), "export1.rdf.gz")
    sch1 = os.path.join(str(tmp_path), "export1.schema")
    store = Store(out1)
    st = export_rdf(store, exp1, schema_path=sch1)
    store.close()
    assert st.quads == 12

    # re-load the export, re-export, and compare quad sets
    out2 = os.path.join(str(tmp_path), "p2")
    with open(sch1) as f:
        schema2 = f.read()
    bulk_load(exp1, schema2, out2, workers=1)
    exp2 = os.path.join(str(tmp_path), "export2.rdf")
    store2 = Store(out2)
    export_rdf(store2, exp2)
    store2.close()

    with gzip.open(exp1, "rt") as f:
        quads1 = sorted(f.read().splitlines())
    with open(exp2) as f:
        quads2 = sorted(f.read().splitlines())
    assert quads1 == quads2

    # and the two stores answer identically
    n1, n2 = Node(out1), Node(out2)
    q = '{ q(func: has(name), orderasc: name) { name age bio@en follows { name } } }'
    r1, _ = n1.query(q)
    r2, _ = n2.query(q)
    assert r1 == r2
    n1.close()
    n2.close()


def test_live_load_matches_bulk(tmp_path):
    rdf_path = _write(str(tmp_path), RDF)
    out_b = os.path.join(str(tmp_path), "pb")
    bulk_load(rdf_path, SCHEMA, out_b, workers=1)
    nb = Node(out_b)

    nl = Node()
    nl.alter(schema_text=SCHEMA)
    stats = live_load(nl, rdf_path, batch=5)
    assert stats.quads == 12 and stats.txns >= 3

    q = '{ q(func: has(name), orderasc: name) { name age follows { name } } }'
    rb, _ = nb.query(q)
    rl, _ = nl.query(q)
    assert rb == rl
    nb.close()


def test_xidmap_identity_and_persistence(tmp_path):
    lease = UidLease()
    xm = XidMap(lease, block=4)
    a = xm.uid("alice")
    assert xm.uid("alice") == a
    assert xm.uid("0x2a") == 0x2a          # explicit passthrough
    b = xm.uid("bob")
    assert b != a and b != 0x2a
    # explicit uid INSIDE the current leased block must never be re-issued
    inside = b + 1
    assert xm.uid(f"0x{inside:x}") == inside
    c = xm.uid("carol")
    assert c not in (a, b, inside, 0x2a)
    # future blocks start past the largest explicit uid
    assert lease.max_leased >= 0x2a
    path = os.path.join(str(tmp_path), "x.json")
    xm.save(path)
    lease2 = UidLease()
    xm2 = XidMap.load(path, lease2)
    assert xm2.uid("alice") == a
    assert xm2.uid("new") > max(a, b, c)


def test_bulk_scale_parallel(tmp_path):
    """~120k-edge load through the multiprocess map stage; spot-check with
    queries + count index."""
    rng = np.random.default_rng(11)
    n_people = 5000
    lines = [f'_:p{i} <name> "p{i}" .' for i in range(n_people)]
    for i in range(n_people):
        for j in rng.choice(n_people, size=20, replace=False):
            lines.append(f"_:p{i} <follows> _:p{j} .")
    rdf_path = _write(str(tmp_path), "\n".join(lines) + "\n", gz=True)
    out = os.path.join(str(tmp_path), "p")
    stats = bulk_load(rdf_path, "name: string @index(exact) .\n"
                      "follows: [uid] @count .", out, workers=2)
    assert stats.uid_edges >= 99000 and stats.nodes == n_people
    node = Node(out)
    q, _ = node.query('{ q(func: eq(name, "p17")) { c: count(follows) } }')
    assert q["q"][0]["c"] in (19, 20)
    q2, _ = node.query('{ q(func: eq(count(follows), 20), first: 5) { name } }')
    assert len(q2["q"]) == 5
    node.close()


def test_export_roundtrip_hostile_facets(tmp_path):
    """Facet strings with quotes, commas, and parens must survive
    export -> re-import (r3 code-review finding)."""
    node = Node()
    node.alter(schema_text="follows: [uid] .\nname: string .")
    node.mutate(set_nquads='_:a <name> "A" .\n_:b <name> "B" .',
                commit_now=True)
    node.mutate(set_json=[{"uid": "0x1",
                           "follows": {"uid": "0x2"},
                           "follows|note": 'say "hi", ok (really)'}],
                commit_now=True)
    exp = os.path.join(str(tmp_path), "e.rdf")
    export_rdf(node.store, exp)
    out = os.path.join(str(tmp_path), "p")
    bulk_load(exp, "follows: [uid] .\nname: string .", out, workers=1)
    n2 = Node(out)
    q, _ = n2.query('{ q(func: uid(0x1)) { follows @facets(note) { name } } }')
    got = q["q"][0]["follows"][0]
    assert got["follows|note"] == 'say "hi", ok (really)', got
    n2.close()


def test_bulk_mixed_uid_and_value_predicate_clear_error(tmp_path):
    p = _write(str(tmp_path), '_:a <p> _:b .\n_:a <p> "hello" .\n')
    with pytest.raises(BulkError, match="both uid edges and literal"):
        bulk_load(p, "", os.path.join(str(tmp_path), "o"), workers=1)


def test_geojson_convert_roundtrip(tmp_path):
    """convert: GeoJSON features -> RDF, loadable and geo-queryable
    (reference dgraph/cmd/dgraph-converter/main.go)."""
    import json as _json

    from dgraph_tpu.api.server import Node
    from dgraph_tpu.loader.convert import convert_geojson
    from dgraph_tpu.loader.live import live_load

    geo = tmp_path / "cities.json"
    geo.write_text(_json.dumps({"type": "FeatureCollection", "features": [
        {"type": "Feature",
         "geometry": {"type": "Point", "coordinates": [-122.42, 37.77]},
         "properties": {"name": "SF", "pop": 880000, "coastal": True}},
        {"type": "Feature",
         "geometry": {"type": "Point", "coordinates": [2.35, 48.85]},
         "properties": {"name": "Paris", "pop": 2140000}},
        {"type": "Feature", "geometry": None, "properties": {"name": "skip"}},
    ]}))
    out = tmp_path / "cities.rdf.gz"
    stats = convert_geojson(str(geo), str(out))
    assert stats.features == 2 and stats.triples == 7

    node = Node(str(tmp_path / "p"))
    node.alter(schema_text="loc: geo @index(geo) .\nname: string .\npop: int .")
    live_load(node, [str(out)])
    res, _ = node.query('{ q(func: near(loc, [-122.42, 37.77], 1000)) '
                        '{ name pop coastal } }')
    assert res == {"q": [{"name": "SF", "pop": 880000, "coastal": True}]}
    node.close()


def test_ldbc_convert_roundtrip(tmp_path):
    """convert --ldbc: LDBC-SNB interactive CSVs (persons/knows/posts
    subset) -> N-Quads + schema, loadable and traversable (ROADMAP item 5
    groundwork; the SF10 ingest itself rides the bulk pipeline)."""
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.loader.convert import convert_ldbc
    from dgraph_tpu.loader.live import live_load

    fixture = os.path.join(os.path.dirname(__file__), "fixtures", "ldbc")
    out = tmp_path / "snb.rdf.gz"
    stats = convert_ldbc(fixture, str(out))
    assert stats.persons == 3 and stats.knows == 2 and stats.posts == 2
    assert stats.comments == 3 and stats.reply_of == 3
    # persons: id + 5 value cols = 18; knows: 2; posts: 343 has id +
    # imageFile + creationDate + length(0 -> "0" kept? length "0" is
    # falsy-string "0"? no: "0" is truthy) = 4... count explicitly below
    assert stats.triples == sum(1 for ln in gzip.open(out, "rt"))

    node = Node(str(tmp_path / "p"))
    with open(str(out) + ".schema") as f:
        node.alter(schema_text=f.read())
    live_load(node, [str(out)])
    # knows edges traverse; reverse hasCreator finds a person's posts
    res, _ = node.query('{ q(func: eq(firstName, "Mahinda")) '
                        '{ lastName knows { firstName } '
                        '  ~hasCreator { length } } }')
    q = res["q"][0]
    assert q["lastName"] == "Perera"
    assert sorted(k["firstName"] for k in q["knows"]) == \
        ["Carmen", "Hồ Chí"]
    # post 343 (length 0) + comment 1013 (length 13) both credit Mahinda
    assert sorted(x["length"] for x in q["~hasCreator"]) == [0, 13]
    # unicode content survives the round trip
    res, _ = node.query('{ q(func: eq(post.id, 618)) { content language '
                        '  hasCreator { firstName } } }')
    assert res["q"][0]["language"] == "uz"
    assert "Hồ Chí Minh" in res["q"][0]["content"]
    assert res["q"][0]["hasCreator"] == [{"firstName": "Carmen"}]
    # comment entities (ISSUE 15): a depth-3 replyOf chain resolves
    # comment -> comment -> comment -> post, and hasCreator hangs off
    # every hop (the fan-out shape the 3-hop battery exercises)
    res, _ = node.query('{ q(func: eq(comment.id, 1014)) { '
                        '  replyOf { comment.id replyOf { comment.id '
                        '    replyOf { post.id hasCreator '
                        '      { firstName } } } } } }')
    hop1 = res["q"][0]["replyOf"][0]
    assert hop1["comment.id"] == 1013
    hop2 = hop1["replyOf"][0]
    assert hop2["comment.id"] == 1012
    hop3 = hop2["replyOf"][0]
    assert hop3["post.id"] == 618
    assert hop3["hasCreator"] == [{"firstName": "Carmen"}]
    # unicode comment content + reverse replyOf (who replied to 1012?)
    res, _ = node.query('{ q(func: eq(comment.id, 1013)) { content '
                        '  ~replyOf { comment.id } } }')
    assert "không hẳn vậy" in res["q"][0]["content"]
    assert res["q"][0]["~replyOf"] == [{"comment.id": 1014}]
    node.close()


def test_export_roundtrip_list_values_and_value_facets(tmp_path):
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.loader.export import export_rdf
    from dgraph_tpu.loader.live import live_load

    n = Node(str(tmp_path / "a"))
    n.alter(schema_text="nick: [string] @index(term) .\n"
                        "name: string @index(exact) .")
    n.mutate(set_nquads='_:a <name> "Jay" (src="x") .\n'
                        '_:a <nick> "jj" .\n_:a <nick> "jbird" .',
             commit_now=True)
    out = str(tmp_path / "dump.rdf.gz")
    export_rdf(n.store, out, schema_path=str(tmp_path / "s.txt"))
    n2 = Node(str(tmp_path / "b"))
    n2.alter(schema_text=(tmp_path / "s.txt").read_text())
    live_load(n2, [out])
    q, _ = n2.query('{ q(func: eq(name, "Jay")) { name @facets nick } }')
    assert sorted(q["q"][0]["nick"]) == ["jbird", "jj"]
    assert q["q"][0]["name|src"] == "x"
    n.close()
    n2.close()


def test_xidmap_crash_resumable(tmp_path):
    """Append-log xidmap (xidmap/xidmap.go's persisted-map role): a
    re-opened map replays assignments (incl. past a torn tail) and a
    resumed live load reuses identities instead of minting duplicates."""
    from dgraph_tpu.coord.zero import UidLease
    from dgraph_tpu.loader.xidmap import XidMap

    wal = str(tmp_path / "xidmap.log")
    lease = UidLease()
    xm = XidMap.open(wal, lease)
    u_a, u_b = xm.uid("_:a"), xm.uid("_:b")
    xm.sync()
    xm.close()

    # torn trailing record (crash mid-write)
    with open(wal, "ab") as f:
        f.write(b"_:c\t12")          # no newline, no full record

    lease2 = UidLease()
    xm2 = XidMap.open(wal, lease2)
    assert len(xm2) == 2             # the torn record was NOT replayed
    assert xm2.uid("_:a") == u_a and xm2.uid("_:b") == u_b
    u_c = xm2.uid("_:c")             # torn record dropped: re-assigned
    assert u_c not in (u_a, u_b) and u_c != 12
    # the replayed lease can never re-mint a logged uid
    first, _ = lease2.assign(1)
    assert first > max(u_a, u_b)
    xm2.close()


def test_live_load_resume_keeps_identities(tmp_path):
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.loader.live import live_load

    rdf1 = tmp_path / "a.rdf"
    rdf1.write_text('_:x <name> "one" .\n')
    rdf2 = tmp_path / "b.rdf"
    rdf2.write_text('_:x <age> "5"^^<xs:int> .\n')
    wal = str(tmp_path / "xidmap.log")

    node = Node(dirpath=str(tmp_path / "p"))
    node.alter(schema_text="name: string @index(exact) .\nage: int .")
    live_load(node, str(rdf1), xidmap_path=wal)
    # "resumed" second run (fresh XidMap from the log): _:x keeps its uid
    live_load(node, str(rdf2), xidmap_path=wal)
    out, _ = node.query('{ q(func: eq(name, "one")) { name age } }')
    assert out["q"][0]["age"] == 5   # both triples landed on ONE node
    node.close()
