"""Server-side TLS on both API ports (reference x/tls_helper.go surface):
self-signed cert generated at test time, HTTPS + secure-channel gRPC."""

import json
import ssl
import subprocess
import threading
import urllib.request

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.api.grpc_client import DgraphClient
from dgraph_tpu.api.grpc_server import serve_grpc
from dgraph_tpu.api.http import make_server
from dgraph_tpu.api.server import Node


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1", "-subj",
         "/CN=localhost", "-addext", "subjectAltName=DNS:localhost"],
        check=True, capture_output=True)
    return cert, key


def test_https_round_trip(certs):
    cert, key = certs
    node = Node()
    node.alter(schema_text="name: string @index(exact) .")
    node.mutate(set_nquads='_:a <name> "tls" .', commit_now=True)
    srv = make_server(node, "localhost", 0, tls_cert=cert, tls_key=key)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        ctx = ssl.create_default_context(cafile=cert)
        body = json.dumps({"query": '{ q(func: eq(name, "tls")) { name } }'})
        r = urllib.request.urlopen(urllib.request.Request(
            f"https://localhost:{port}/query", body.encode(),
            {"Content-Type": "application/json"}), timeout=5, context=ctx)
        out = json.loads(r.read())
        assert out["data"] == {"q": [{"name": "tls"}]}
        # plaintext against the TLS port fails
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://localhost:{port}/health",
                                   timeout=2)
    finally:
        srv.shutdown()


def test_grpc_tls_round_trip(certs):
    cert, key = certs
    node = Node()
    node.alter(schema_text="name: string @index(exact) .")
    server, port = serve_grpc(node, "localhost:0", tls_cert=cert,
                              tls_key=key)
    try:
        creds = grpc.ssl_channel_credentials(
            root_certificates=open(cert, "rb").read())
        chan = grpc.secure_channel(f"localhost:{port}", creds)
        c = DgraphClient(channel=chan)
        assert c.check_version() == "dgraph-tpu"
        c.txn().mutate(set_nquads='_:a <name> "grpc-tls" .', commit_now=True)
        out = c.txn(read_only=True).query(
            '{ q(func: eq(name, "grpc-tls")) { name } }')
        assert out == {"q": [{"name": "grpc-tls"}]}
        c.close()
    finally:
        server.stop(0)
