"""process_task function taxonomy vs hand-computed ground truth.

Mirrors the reference's worker/worker_test.go (processTask cases over an
embedded store, SURVEY.md §4).
"""

import numpy as np
import pytest

from dgraph_tpu.storage import index as idx
from dgraph_tpu.storage.csr_build import build_snapshot
from dgraph_tpu.storage.postings import DirectedEdge
from dgraph_tpu.storage.store import Store
from dgraph_tpu.query.task import TaskError, TaskQuery, process_task
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val, hash_password


@pytest.fixture(scope="module")
def snap_env():
    s = Store()
    schema_text = """
        friend: uid @reverse @count .
        name: string @index(term, exact, trigram) .
        age: int @index(int) .
        bio: string @index(fulltext) .
        loc: geo @index(geo) .
        pass: password .
    """
    for e in parse_schema(schema_text):
        s.set_schema(e)
    people = {
        1: ("alice jones", 25, "loves fast cars and racing"),
        2: ("bob smith", 32, "enjoys cooking italian food"),
        3: ("carol jones", 25, "cars are my passion"),
        4: ("dave stone", 40, "hiking in the mountains"),
        5: ("eve adams", 19, "food blogger and chef"),
    }
    for uid, (nm, age, bio) in people.items():
        idx.add_mutation_with_index(s, DirectedEdge(uid, "name", value=Val(TypeID.STRING, nm)), 1)
        idx.add_mutation_with_index(s, DirectedEdge(uid, "age", value=Val(TypeID.INT, age)), 1)
        idx.add_mutation_with_index(s, DirectedEdge(uid, "bio", value=Val(TypeID.STRING, bio)), 1)
    for sub, obj in [(1, 2), (1, 3), (2, 3), (3, 4), (4, 5), (5, 1), (1, 5)]:
        idx.add_mutation_with_index(s, DirectedEdge(sub, "friend", object_uid=obj), 1)
    idx.add_mutation_with_index(
        s, DirectedEdge(1, "loc",
                        value=Val(TypeID.GEO, __import__("dgraph_tpu.utils.geo", fromlist=["geo"]).parse_geojson(
                            '{"type":"Point","coordinates":[-122.42,37.77]}'))), 1)
    idx.add_mutation_with_index(
        s, DirectedEdge(2, "loc",
                        value=Val(TypeID.GEO, __import__("dgraph_tpu.utils.geo", fromlist=["geo"]).parse_geojson(
                            '{"type":"Point","coordinates":[-74.0,40.71]}'))), 1)
    idx.add_mutation_with_index(
        s, DirectedEdge(1, "pass", value=Val(TypeID.PASSWORD, hash_password("hunter22"))), 1)
    s.commit(1, 2, list(s.lists.keys()))
    return s, build_snapshot(s, read_ts=3)


def run(snap_env, q):
    s, snap = snap_env
    return process_task(snap, q, s.schema)


def test_has(snap_env):
    res = run(snap_env, TaskQuery("friend", func=("has", [])))
    np.testing.assert_array_equal(res.dest_uids, [1, 2, 3, 4, 5])
    res = run(snap_env, TaskQuery("loc", func=("has", [])))
    np.testing.assert_array_equal(res.dest_uids, [1, 2])


def test_eq_exact_and_int(snap_env):
    res = run(snap_env, TaskQuery("name", func=("eq", ["alice jones"])))
    np.testing.assert_array_equal(res.dest_uids, [1])
    res = run(snap_env, TaskQuery("age", func=("eq", [25])))
    np.testing.assert_array_equal(res.dest_uids, [1, 3])
    # multi-arg eq = union
    res = run(snap_env, TaskQuery("age", func=("eq", [25, 40])))
    np.testing.assert_array_equal(res.dest_uids, [1, 3, 4])


def test_inequalities(snap_env):
    res = run(snap_env, TaskQuery("age", func=("lt", [25])))
    np.testing.assert_array_equal(res.dest_uids, [5])
    res = run(snap_env, TaskQuery("age", func=("le", [25])))
    np.testing.assert_array_equal(res.dest_uids, [1, 3, 5])
    res = run(snap_env, TaskQuery("age", func=("gt", [32])))
    np.testing.assert_array_equal(res.dest_uids, [4])
    res = run(snap_env, TaskQuery("age", func=("ge", [32])))
    np.testing.assert_array_equal(res.dest_uids, [2, 4])


def test_terms_and_fulltext(snap_env):
    res = run(snap_env, TaskQuery("name", func=("anyofterms", ["jones bob"])))
    np.testing.assert_array_equal(res.dest_uids, [1, 2, 3])
    res = run(snap_env, TaskQuery("name", func=("allofterms", ["carol jones"])))
    np.testing.assert_array_equal(res.dest_uids, [3])
    res = run(snap_env, TaskQuery("bio", func=("anyoftext", ["car"])))
    np.testing.assert_array_equal(res.dest_uids, [1, 3])  # cars stems to car
    res = run(snap_env, TaskQuery("bio", func=("alloftext", ["food cooking"])))
    np.testing.assert_array_equal(res.dest_uids, [2])


def test_regexp(snap_env):
    res = run(snap_env, TaskQuery("name", func=("regexp", ["jon", ""])))
    np.testing.assert_array_equal(res.dest_uids, [1, 3])
    res = run(snap_env, TaskQuery("name", func=("regexp", ["^bob.*th$", ""])))
    np.testing.assert_array_equal(res.dest_uids, [2])
    res = run(snap_env, TaskQuery("name", func=("regexp", ["ALICE", "i"])))
    np.testing.assert_array_equal(res.dest_uids, [1])


def test_geo_near(snap_env):
    res = run(snap_env, TaskQuery(
        "loc", func=("near", ['{"type":"Point","coordinates":[-122.4,37.78]}', 10000])))
    np.testing.assert_array_equal(res.dest_uids, [1])
    res = run(snap_env, TaskQuery(
        "loc", func=("near", ['{"type":"Point","coordinates":[0.0,0.0]}', 1000])))
    assert len(res.dest_uids) == 0


def test_count_scalar(snap_env):
    # friend out-degrees: 1->3, 2->1, 3->1, 4->1, 5->1
    res = run(snap_env, TaskQuery("friend", func=("eq", ["__count__", 3])))
    np.testing.assert_array_equal(res.dest_uids, [1])
    res = run(snap_env, TaskQuery("friend", func=("ge", ["__count__", 1])))
    np.testing.assert_array_equal(res.dest_uids, [1, 2, 3, 4, 5])


def test_expand_and_reverse(snap_env):
    res = run(snap_env, TaskQuery("friend", frontier=np.asarray([1, 3])))
    np.testing.assert_array_equal(res.uid_matrix[0], [2, 3, 5])
    np.testing.assert_array_equal(res.uid_matrix[1], [4])
    np.testing.assert_array_equal(res.dest_uids, [2, 3, 4, 5])
    assert res.counts == [3, 1]
    assert res.traversed_edges == 4
    # reverse: who points at 3?
    res = run(snap_env, TaskQuery("~friend", frontier=np.asarray([3])))
    np.testing.assert_array_equal(res.uid_matrix[0], [1, 2])


def test_value_fetch_and_filters(snap_env):
    res = run(snap_env, TaskQuery("age", frontier=np.asarray([1, 2, 4])))
    assert [v[0].value for v in res.value_matrix] == [25, 32, 40]
    res = run(snap_env, TaskQuery("age", frontier=np.asarray([1, 2, 4]), func=("ge", [30])))
    np.testing.assert_array_equal(res.dest_uids, [2, 4])


def test_uid_in(snap_env):
    res = run(snap_env, TaskQuery("friend", frontier=np.asarray([1, 2, 4]),
                                  func=("uid_in", [3])))
    np.testing.assert_array_equal(res.dest_uids, [1, 2])


def test_checkpwd(snap_env):
    res = run(snap_env, TaskQuery("pass", frontier=np.asarray([1]),
                                  func=("checkpwd", ["hunter22"])))
    np.testing.assert_array_equal(res.dest_uids, [1])
    res = run(snap_env, TaskQuery("pass", frontier=np.asarray([1]),
                                  func=("checkpwd", ["wrong"])))
    assert len(res.dest_uids) == 0


def test_first_truncation(snap_env):
    res = run(snap_env, TaskQuery("friend", frontier=np.asarray([1]), first=2))
    np.testing.assert_array_equal(res.uid_matrix[0], [2, 3])


def test_missing_index_errors(snap_env):
    with pytest.raises(TaskError, match="needs @index"):
        run(snap_env, TaskQuery("bio", func=("eq", ["x"])))


def test_case_insensitive_regexp_uses_trigram_pruning():
    """/pat/i prunes candidates via case-variant trigram probes instead of a
    full index scan (codesearch case-folded query expansion)."""
    from dgraph_tpu.query.task import _case_variants, _trigram_plan
    assert set(_case_variants("ab1")) == {"ab1", "Ab1", "aB1", "AB1"}
    assert _trigram_plan("RiCk") == [["RiC", "iCk"]]


def test_trigram_plan_per_branch_or_of_and():
    """Alternations plan one AND-list per branch (worker/trigram.go:36 +
    codesearch index/regexp), ORed at probe time; branches with no literal
    >= 3 chars poison the whole plan (full scan, never dropped matches)."""
    from dgraph_tpu.query.task import _trigram_plan
    assert _trigram_plan("GRIMES|rhee") == [
        ["GRI", "IME", "MES", "RIM"], ["hee", "rhe"]]
    assert _trigram_plan("(abc)?def") == [["def"]]     # optional group
    assert _trigram_plan("ab{0,3}cde") == [["cde"]]    # counted repeat
    assert _trigram_plan("film 1. of") == [[" of", "fil", "ilm", "lm ", "m 1"]]
    assert _trigram_plan("rick") == [["ick", "ric"]]
    assert _trigram_plan("a|b") is None                # short branch
    assert _trigram_plan("x[0-9]+y") is None           # class-only
    assert _trigram_plan("(abc)+") == [["abc"]]        # min>=1 repeat
    # group/repeat boundaries never concatenate: "ab+c" must not claim "abc"
    assert _trigram_plan("ab+c") is None


def test_expand_allocation_is_frontier_proportional(monkeypatch):
    """VERDICT r3 weak#1: out_cap must scale with the frontier's degree sum,
    not the predicate's total edge count (two-pass count-then-gather)."""
    import jax.numpy as jnp

    from dgraph_tpu.query import task as taskmod
    from dgraph_tpu.storage.csr_build import PredCSR

    n, deg = 4096, 64                       # 262144-edge predicate
    subjects = jnp.arange(1, n + 1, dtype=jnp.int32)
    indptr = jnp.arange(0, (n + 1) * deg, deg, dtype=jnp.int32)
    indices = jnp.arange(n * deg, dtype=jnp.int32) % n + 1
    csr = PredCSR(subjects, indptr, indices)

    caps = []
    real_expand = taskmod.csrops.expand

    def spy(indptr_, indices_, rows_, out_cap):
        caps.append(out_cap)
        return real_expand(indptr_, indices_, rows_, out_cap)

    monkeypatch.setattr(taskmod.csrops, "expand", spy)
    # force the device path (small expands normally take the host mirror)
    monkeypatch.setattr(taskmod, "HOST_EXPAND_MAX", 0)
    matrix, total = taskmod._expand_csr(csr, np.asarray([7], dtype=np.int64))
    assert total == deg and len(matrix[0]) == deg
    # 1-uid frontier: capacity is the pow2 class of its degree (64), nowhere
    # near the 262144-edge predicate
    assert caps == [128]

    caps.clear()
    matrix, total = taskmod._expand_csr(
        csr, np.asarray([1, 2, 3, 999999], dtype=np.int64))
    assert total == 3 * deg
    assert caps == [256]                    # 3 live rows * 64 → pow2 256
    assert len(matrix[3]) == 0              # missing subject stays empty


def test_regexp_alternation_end_to_end():
    """regexp(name, /^(GRIMES|rhee)/) prunes via per-branch trigrams AND
    returns both branches' matches (VERDICT r3 weak#8)."""
    from dgraph_tpu.api.server import Node

    n = Node()
    n.alter(schema_text="name: string @index(trigram) .")
    n.mutate(set_nquads='_:a <name> "GRIMES the artist" .\n'
                        '_:b <name> "rhee of dgraph" .\n'
                        '_:c <name> "unrelated" .', commit_now=True)
    out, _ = n.query('{ q(func: regexp(name, /^(GRIMES|rhee)/)) { name } }')
    assert sorted(x["name"] for x in out["q"]) == [
        "GRIMES the artist", "rhee of dgraph"]
    out, _ = n.query('{ q(func: regexp(name, /(grimes|RHEE)/i)) { name } }')
    assert sorted(x["name"] for x in out["q"]) == [
        "GRIMES the artist", "rhee of dgraph"]


def test_regexp_inline_ignorecase_flag():
    """(?i) inside the pattern must case-expand the trigram probe exactly
    like /re/i (review r4: the planner sees exact-case literals)."""
    from dgraph_tpu.api.server import Node

    n = Node()
    n.alter(schema_text="name: string @index(trigram) .")
    n.mutate(set_nquads='_:a <name> "RICK GRIMES" .', commit_now=True)
    out, _ = n.query('{ q(func: regexp(name, /(?i)rick/)) { name } }')
    assert [x["name"] for x in out["q"]] == ["RICK GRIMES"]
