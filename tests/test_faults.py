"""Failure detection and fault injection (SURVEY §5): transport faults on
the replication ship path mark members dead, quorum math reacts, recovery
works through the normal rejoin path. Reference analog: conn/pool.go
Echo-based health checks + Raft CheckQuorum."""

import pytest

from dgraph_tpu.coord.replication import NoQuorum, ReplicaGroup


def _mk(tmp_path, n=3):
    g = ReplicaGroup(str(tmp_path / "fg"), n=n)
    g.node.alter(schema_text="v: int .")
    g.node.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    return g


def test_transport_fault_marks_member_dead(tmp_path):
    g = _mk(tmp_path)
    victim = next(m for m in g._followers())

    def flaky(m, data):
        if m.id == victim.id:
            raise IOError("injected transport fault")

    g.fault_hook = flaky
    # write still succeeds: 2/3 quorum without the faulty member
    g.node.mutate(set_nquads='<0x1> <v> "2"^^<xs:int> .', commit_now=True)
    assert not victim.alive
    g.fault_hook = None
    g.close()


def test_all_followers_faulty_blocks_commit(tmp_path):
    g = _mk(tmp_path)
    g.fault_hook = lambda m, data: (_ for _ in ()).throw(IOError("down"))
    with pytest.raises(NoQuorum):
        g.node.mutate(set_nquads='<0x1> <v> "3"^^<xs:int> .', commit_now=True)
    g.fault_hook = None
    g.close()


def test_faulted_member_recovers_via_rejoin(tmp_path):
    g = _mk(tmp_path)
    victim = next(m for m in g._followers())
    g.fault_hook = lambda m, data: (_ for _ in ()).throw(
        IOError("x")) if m.id == victim.id else None
    g.node.mutate(set_nquads='<0x1> <v> "4"^^<xs:int> .', commit_now=True)
    assert not victim.alive
    g.fault_hook = None
    g.node.mutate(set_nquads='<0x1> <v> "5"^^<xs:int> .', commit_now=True)
    g.rejoin(victim.id)
    # rejoined member can now be promoted with full state
    g.kill(g.leader_id)
    out, _ = g.node.query('{ q(func: uid(0x1)) { v } }')
    assert out["q"][0]["v"] == 5
    g.close()


def test_no_partial_append_on_rejected_ship(tmp_path):
    """A NoQuorum rejection must leave no follower holding a record the
    leader never wrote (atomicity of the ship)."""
    g = _mk(tmp_path)
    lens_before = {m.id: m.wal_len() for m in g._followers()}
    # both followers fault on the NEXT ship
    g.fault_hook = lambda m, data: (_ for _ in ()).throw(IOError("gone"))
    with pytest.raises(NoQuorum):
        g.node.mutate(set_nquads='<0x1> <v> "9"^^<xs:int> .', commit_now=True)
    g.fault_hook = None
    for m in g._followers():
        assert m.wal_len() == lens_before[m.id]
    g.close()
