"""Failure detection and fault injection (SURVEY §5): transport faults on
the replication ship path mark members dead, quorum math reacts, recovery
works through the normal rejoin path. Reference analog: conn/pool.go
Echo-based health checks + Raft CheckQuorum.

Round-12 additions (ISSUE 7): overload-shedding and degraded-mode paths of
the request-lifeline layer — a saturated dispatch gate sheds typed
ResourceExhausted, a node that loses its Zero serves read-only snapshot
queries with a staleness annotation, and the named fault points at the
store/serve seams inject through the live paths."""

import threading
import time

import pytest

from dgraph_tpu.coord.replication import NoQuorum, ReplicaGroup
from dgraph_tpu.utils import deadline as dl
from dgraph_tpu.utils import faults
from dgraph_tpu.utils.deadline import DeadlineExceeded, ResourceExhausted


def _mk(tmp_path, n=3):
    g = ReplicaGroup(str(tmp_path / "fg"), n=n)
    g.node.alter(schema_text="v: int .")
    g.node.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    return g


def test_transport_fault_marks_member_dead(tmp_path):
    g = _mk(tmp_path)
    victim = next(m for m in g._followers())

    def flaky(m, data):
        if m.id == victim.id:
            raise IOError("injected transport fault")

    g.fault_hook = flaky
    # write still succeeds: 2/3 quorum without the faulty member
    g.node.mutate(set_nquads='<0x1> <v> "2"^^<xs:int> .', commit_now=True)
    assert not victim.alive
    g.fault_hook = None
    g.close()


def test_all_followers_faulty_blocks_commit(tmp_path):
    g = _mk(tmp_path)
    g.fault_hook = lambda m, data: (_ for _ in ()).throw(IOError("down"))
    with pytest.raises(NoQuorum):
        g.node.mutate(set_nquads='<0x1> <v> "3"^^<xs:int> .', commit_now=True)
    g.fault_hook = None
    g.close()


def test_faulted_member_recovers_via_rejoin(tmp_path):
    g = _mk(tmp_path)
    victim = next(m for m in g._followers())
    g.fault_hook = lambda m, data: (_ for _ in ()).throw(
        IOError("x")) if m.id == victim.id else None
    g.node.mutate(set_nquads='<0x1> <v> "4"^^<xs:int> .', commit_now=True)
    assert not victim.alive
    g.fault_hook = None
    g.node.mutate(set_nquads='<0x1> <v> "5"^^<xs:int> .', commit_now=True)
    g.rejoin(victim.id)
    # rejoined member can now be promoted with full state
    g.kill(g.leader_id)
    out, _ = g.node.query('{ q(func: uid(0x1)) { v } }')
    assert out["q"][0]["v"] == 5
    g.close()


def test_no_partial_append_on_rejected_ship(tmp_path):
    """A NoQuorum rejection must leave no follower holding a record the
    leader never wrote (atomicity of the ship)."""
    g = _mk(tmp_path)
    lens_before = {m.id: m.wal_len() for m in g._followers()}
    # both followers fault on the NEXT ship
    g.fault_hook = lambda m, data: (_ for _ in ()).throw(IOError("gone"))
    with pytest.raises(NoQuorum):
        g.node.mutate(set_nquads='<0x1> <v> "9"^^<xs:int> .', commit_now=True)
    g.fault_hook = None
    for m in g._followers():
        assert m.wal_len() == lens_before[m.id]
    g.close()


# -- named fault points through the live store/query paths -------------------

@pytest.fixture(autouse=True)
def _clean_global_faults():
    faults.GLOBAL.clear()
    yield
    faults.GLOBAL.clear()


def test_wal_write_fault_point_fails_the_mutation(tmp_path):
    """disk.wal_write fires BEFORE the in-memory apply (a real fsync
    failure's ordering): the mutation errors and nothing becomes
    visible."""
    from dgraph_tpu.api.server import Node

    node = Node(str(tmp_path / "w"))
    node.alter(schema_text="v: int .")
    node.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    faults.GLOBAL.install("disk.wal_write", "error", count=1)
    with pytest.raises(faults.FaultError):
        node.mutate(set_nquads='<0x1> <v> "2"^^<xs:int> .', commit_now=True)
    faults.GLOBAL.clear()
    out, _ = node.query("{ q(func: uid(0x1)) { v } }")
    assert out["q"][0]["v"] == 1
    assert node.metrics.counter("dgraph_fault_injected_total").value >= 1
    node.close()


def test_device_dispatch_fault_point_is_typed(tmp_path):
    from dgraph_tpu.api.server import Node

    node = Node()
    node.alter(schema_text="name: string @index(exact) .")
    node.mutate(set_nquads='_:a <name> "x" .', commit_now=True)
    faults.GLOBAL.install("device.dispatch", "error")
    with pytest.raises(faults.FaultError):
        node.query('{ q(func: eq(name, "x")) { name } }')
    faults.GLOBAL.clear()
    out, _ = node.query('{ q(func: eq(name, "x")) { name } }')
    assert out == {"q": [{"name": "x"}]}
    node.close()


# -- overload shedding + degraded mode (wire cluster) ------------------------

grpc = pytest.importorskip("grpc")


def _wire_cluster(n_groups=2, **client_kw):
    from dgraph_tpu.coord.zero import Zero
    from dgraph_tpu.coord.zero_service import serve_zero
    from dgraph_tpu.parallel.client import ClusterClient
    from dgraph_tpu.parallel.remote import serve_worker
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.utils.schema import parse_schema

    schema = ("name: string @index(exact) .\n"
              "follows: [uid] @reverse .")
    zero = Zero(n_groups)
    zero.move_tablet("name", 0)
    zero.move_tablet("follows", n_groups - 1)
    zsrv, zport, _ = serve_zero(zero, "localhost:0")
    stores, workers = [], []
    for _g in range(n_groups):
        s = Store()
        for e in parse_schema(schema):
            s.set_schema(e)
        stores.append(s)
        workers.append(serve_worker(s, "localhost:0"))
    client = ClusterClient(
        f"localhost:{zport}",
        {g: [f"localhost:{workers[g][1]}"] for g in range(n_groups)},
        **client_kw)
    client.mutate(set_nquads='_:a <name> "ann" .\n_:b <name> "bob" .\n'
                             '_:a <follows> _:b .')
    return client, zsrv, workers, stores


def test_degraded_mode_serves_stale_reads_when_zero_dies():
    """Losing the Zero quorum degrades to read-only snapshot serving with
    a staleness annotation — byte-identical output for unchanged data —
    instead of erroring outright; writes still fail typed."""
    client, zsrv, workers, _stores = _wire_cluster(default_timeout_ms=5000)
    try:
        q = '{ q(func: eq(name, "ann")) { name follows { name } } }'
        live = client.query(q)
        assert client.last_degraded is None
        zsrv.stop(0)
        time.sleep(0.1)
        client.task_cache.clear()
        degraded = client.query(q)
        assert degraded == live                     # byte-identical
        assert client.last_degraded["degraded"] is True
        assert client.last_degraded["staleness_s"] >= 0
        assert client.metrics.counter(
            "dgraph_degraded_reads_total").value == 1
        # writes cannot be served from a dead coordinator: typed error,
        # bounded time, no hang
        t0 = time.monotonic()
        with pytest.raises(Exception) as ei:
            client.mutate(set_nquads='_:c <name> "cid" .', retries=2,
                          timeout_ms=2000)
        assert isinstance(ei.value, (grpc.RpcError, ConnectionError,
                                     OSError, DeadlineExceeded))
        assert time.monotonic() - t0 < 4.0
    finally:
        client.close()
        for w, _p in workers:
            w.stop(0)


def test_degraded_mode_off_surfaces_the_error():
    client, zsrv, workers, _stores = _wire_cluster(degraded_reads=False)
    try:
        q = '{ q(func: eq(name, "ann")) { name } }'
        client.query(q)
        zsrv.stop(0)
        time.sleep(0.1)
        client.task_cache.clear()
        with pytest.raises((grpc.RpcError, ConnectionError, OSError)):
            client.query(q)
    finally:
        client.close()
        for w, _p in workers:
            w.stop(0)


def test_inflight_commit_timeout_is_commit_ambiguous():
    """An in-flight CommitOrAbort timeout (typed DeadlineExceeded with
    the wire RpcError as __cause__) must surface as CommitAmbiguous with
    NO retry — re-running the txn could apply it twice."""
    from dgraph_tpu.utils.retry import CommitAmbiguous

    client, zsrv, workers, _stores = _wire_cluster()
    calls = []

    class _WireTimeout(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.DEADLINE_EXCEEDED

    def bad_commit(start_ts, conflict_keys, preds):
        calls.append(start_ts)
        err = DeadlineExceeded("zero:CommitOrAbort deadline exceeded")
        err.__cause__ = _WireTimeout()
        raise err

    try:
        client.zero._zero.commit = bad_commit
        with pytest.raises(CommitAmbiguous):
            client.mutate(set_nquads='_:x <name> "x" .', retries=5)
        assert len(calls) == 1, "ambiguous commit was retried"
    finally:
        client.close()
        for w, _p in workers:
            w.stop(0)
        zsrv.stop(0)


def test_gate_saturation_sheds_instead_of_hanging():
    """A saturated client dispatch gate with an armed deadline sheds or
    deadline-errors the overflow — every request resolves within its
    budget, none hang (the chaos gate's local version)."""
    client, zsrv, workers, _stores = _wire_cluster()
    from dgraph_tpu.query.qcache import DispatchGate

    client.dispatch_gate = DispatchGate(1, client.metrics, max_queue=0)
    faults.GLOBAL.install("worker.serve_task", "delay", delay_s=0.4)
    results = []

    def one(i):
        t0 = time.monotonic()
        try:
            client.task_cache.clear()    # force the wire each time
            client.query('{ q(func: eq(name, "ann")) { name } }',
                         timeout_ms=600)
            results.append(("ok", time.monotonic() - t0))
        except (DeadlineExceeded, ResourceExhausted) as e:
            results.append((type(e).__name__, time.monotonic() - t0))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=5.0)
        assert not any(t.is_alive() for t in threads), "a request hung"
        assert len(results) == 6
        # every outcome typed; at least one request was rejected up front
        kinds = {k for k, _ in results}
        assert kinds <= {"ok", "DeadlineExceeded", "ResourceExhausted"}
        assert kinds & {"DeadlineExceeded", "ResourceExhausted"}, results
        assert all(dt < 2.0 for _, dt in results), results
    finally:
        faults.GLOBAL.clear()
        client.close()
        for w, _p in workers:
            w.stop(0)
        zsrv.stop(0)
