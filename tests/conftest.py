"""Test harness: run all tests on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is validated
on XLA's host platform with 8 virtual devices (the same XLA partitioner runs on
TPU). Mirrors the reference's embedded single-process cluster test pattern
(query/query_test.go TestMain runs zero+worker in-process, SURVEY.md §4).
"""

import os

# Must be set before jax is imported anywhere in the test process. Forced (not
# setdefault): the host environment pins JAX_PLATFORMS to the TPU plugin, and
# tests must run on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent compilation cache: makes repeated test runs cheap.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The TPU-plugin sitecustomize imports jax at interpreter startup, freezing
# jax_platforms before this file runs — override through the config API too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) >= 8, (
    "tests need the 8-virtual-device CPU mesh; got "
    f"{jax.devices()} — check XLA_FLAGS/JAX_PLATFORMS handling in conftest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')")


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _restore_query_edge_limit():
    """The edge budget default is a module global (engine.MAX_QUERY_EDGES);
    tests that shrink it via set_query_edge_limit must not leak the budget
    into later tests — restore it unconditionally around every test."""
    from dgraph_tpu.query import engine

    old = engine.MAX_QUERY_EDGES
    yield
    engine.MAX_QUERY_EDGES = old
