"""Wire leader election (VERDICT r4 #3, group half): a replica set heals
its own leadership with a RequestVote-style ballot — no control plane.

Reference: conn/node.go:47-105 etcd-raft ballot + CheckQuorum;
worker/draft.go:485-624. Here the ballot rides the session-sequence
replication: heartbeats carry membership, silence triggers a campaign,
grants follow Raft's up-to-date rule on (max_commit_ts, log_len), and the
winner self-promotes through the same _become_leader path Promote uses.
"""

import time

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.parallel.remote import RemoteWorker, WorkerService
from dgraph_tpu.storage.store import Store
from dgraph_tpu.storage.postings import Op, Posting
from dgraph_tpu.storage import keys as K
from dgraph_tpu.utils.schema import parse_schema


def _mk_trio(tmp_path, fast=True):
    import concurrent.futures as _f

    svcs, servers, addrs = [], [], []
    for i in range(3):
        store = Store(str(tmp_path / f"r{i}"))
        for e in parse_schema("v: int ."):
            store.set_schema(e)
        svc = WorkerService(store)
        if fast:
            svc.HEARTBEAT_S = 0.1
            svc.ELECTION_TIMEOUT_S = (0.4, 0.8)
        server = grpc.server(_f.ThreadPoolExecutor(max_workers=6))
        server.add_generic_rpc_handlers((svc.handler(),))
        port = server.add_insecure_port("localhost:0")
        server.start()
        svc.advertise_addr = f"localhost:{port}"
        svcs.append(svc)
        servers.append(server)
        addrs.append(f"localhost:{port}")
    return svcs, servers, addrs


def _write(addr, uid, ts):
    rw = RemoteWorker(addr)
    try:
        kb = K.data_key("v", uid)
        store_rec = rw  # noqa: F841
        # go through the Mutate RPC so the write rides the leader WAL path
        from dgraph_tpu.storage.postings import DirectedEdge

        resp = rw.mutate(ts, [DirectedEdge(
            subject=uid, attr="v", object_uid=0,
            value=__import__("dgraph_tpu.utils.types",
                             fromlist=["Val"]).Val(
                __import__("dgraph_tpu.utils.types",
                           fromlist=["TypeID"]).TypeID.INT, 1),
            op=Op.SET)])
        rw.decide(ts, ts + 1, list(resp.keys))
    finally:
        rw.close()


def _leader_idx(svcs):
    return [i for i, s in enumerate(svcs) if s.is_leader]


def test_election_after_leader_death(tmp_path):
    svcs, servers, addrs = _mk_trio(tmp_path)
    rw = RemoteWorker(addrs[0])
    assert rw.promote(1, [addrs[1], addrs[2]]).ok
    rw.close()
    for svc in svcs:
        svc.enable_elections()
    # heartbeats propagate membership to followers
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        if all(len(s.group_members) == 3 for s in svcs[1:]):
            break
        time.sleep(0.05)
    assert all(len(s.group_members) == 3 for s in svcs[1:])

    _write(addrs[0], 1, ts=10)          # replicate something

    servers[0].stop(0)                   # SIGKILL-equivalent: leader gone
    svcs[0].stop_elections()
    svcs[0]._step_down()

    deadline = time.monotonic() + 6
    new_leader = None
    while time.monotonic() < deadline:
        up = [i for i in (1, 2) if svcs[i].is_leader]
        if up:
            new_leader = up[0]
            break
        time.sleep(0.05)
    assert new_leader is not None, "no replica won the ballot"
    assert svcs[new_leader].term > 1

    # the new leader serves writes through the quorum path
    _write(addrs[new_leader], 2, ts=20)
    follower = 3 - new_leader            # the other live replica
    deadline = time.monotonic() + 3
    while time.monotonic() < deadline:
        if svcs[follower].store.max_seen_commit_ts >= 21:
            break
        time.sleep(0.05)
    assert svcs[follower].store.max_seen_commit_ts >= 21

    for s in servers[1:]:
        s.stop(0)


def test_stale_candidate_loses(tmp_path):
    """A replica behind on applied state must not win the ballot."""
    svcs, servers, addrs = _mk_trio(tmp_path, fast=False)
    rw = RemoteWorker(addrs[0])
    assert rw.promote(1, [addrs[1], addrs[2]]).ok
    rw.close()
    for s in svcs:
        s.group_members = list(addrs)
    _write(addrs[0], 1, ts=10)
    # make replica 2 artificially ahead so 1's candidacy is rejected
    svcs[2].store.max_seen_commit_ts = 99

    r = RemoteWorker(addrs[2])
    try:
        got = r.vote(5, svcs[1].store.max_seen_commit_ts,
                     svcs[1].store.wal_record_count, addrs[1])
        assert not got.granted            # candidate behind receiver
        got = r.vote(6, 100, 10_000, addrs[1])
        assert got.granted                # up-to-date candidate wins
    finally:
        r.close()
    for s in servers:
        s.stop(0)


def test_no_campaign_without_membership(tmp_path):
    """A lone replica that never learned members must not loop ballots."""
    svcs, servers, addrs = _mk_trio(tmp_path)
    svcs[0].enable_elections()
    time.sleep(1.2)
    assert svcs[0].term == 0 and not svcs[0].is_leader
    for s in servers:
        s.stop(0)
