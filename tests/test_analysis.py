"""dgraph-analyze static-analysis suite (ISSUE 14).

Covers: every checker catches its checked-in known-bad fixture, the
suppression syntax silences annotated violations, the whole package
comes up CLEAN (the tier-1 gate that keeps the invariants machine-
checked as the tree grows), and the CLI contract (--rule, --format=json,
exit codes, the <10s budget).
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from dgraph_tpu.analysis import RULES, analyze_paths
from dgraph_tpu.analysis.checkers import (collect_metric_names,
                                          registered_metric_names)

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"
PKG = Path(__file__).parent.parent / "dgraph_tpu"


def _findings(rule: str):
    return [f for f in analyze_paths([FIXTURES], [rule]) if f.rule == rule]


# ---------------------------------------------------------------------------
# each checker catches its known-bad fixture
# ---------------------------------------------------------------------------

def test_metric_registration_fixture():
    fs = _findings("metric-registration")
    assert any(f.path == "bad_metric.py" and
               "dgraph_bogus_surprise_total" in f.message for f in fs)
    # unknown f-string placeholder is its own finding (the audit must
    # stay mechanical, not silently skip what it cannot expand)
    assert any("placeholder" in f.message for f in fs)


def test_ctxvar_fixture():
    fs = _findings("ctxvar-copy")
    assert {f.line for f in fs if f.path == "bad_ctxvar.py"} == {11, 12}


def test_deadline_wait_fixture():
    fs = [f for f in _findings("deadline-wait")
          if f.path == "parallel/bad_deadline.py"]
    # sleep, cv.wait, lock.acquire, queue.get
    assert len(fs) == 4, fs


def test_except_seam_fixture():
    fs = _findings("except-seam")
    assert [f.path for f in fs] == ["parallel/bad_except.py"]


def test_typed_error_fixture():
    fs = _findings("rpc-error-taxonomy")
    assert [f.path for f in fs] == ["parallel/bad_typed.py"]


def test_jax_purity_fixture():
    fs = [f for f in _findings("jax-purity") if f.path == "bad_jax.py"]
    msgs = "\n".join(f.message for f in fs)
    assert "time.time" in msgs          # jit-decorated body
    assert "random.random" in msgs      # fori_loop body fn
    assert "donated" in msgs            # read-after-donation


def test_fault_points_fixture():
    fs = _findings("fault-points")
    assert any("bogus.chunk_ship" in f.message for f in fs)


def test_lock_order_fixture():
    fs = _findings("lock-order")
    assert any(f.path == "bad_lockorder.py" and "cycle" in f.message
               for f in fs)


# ---------------------------------------------------------------------------
# suppression + scoping semantics
# ---------------------------------------------------------------------------

def test_suppressions_silence_annotated_violations():
    for f in analyze_paths([FIXTURES]):
        assert f.path != "parallel/suppressed_ok.py", f


def test_single_file_run_keeps_scope_segments():
    # `python -m dgraph_tpu.analysis path/to/seam_file.py` roots at the
    # file's parent; scoping must still see the absolute path's segments
    # or the run reports a vacuous clean for exactly the rules that apply
    fs = analyze_paths([FIXTURES / "parallel" / "bad_typed.py"],
                       ["rpc-error-taxonomy"])
    assert len(fs) == 1, fs


def test_scoped_rules_ignore_out_of_scope_files(tmp_path):
    # the same naked sleep OUTSIDE query/parallel/api/coord is not a
    # deadline-wait finding (background tooling, loaders, benches)
    (tmp_path / "tool.py").write_text(
        "import time\n\ndef run():\n    time.sleep(1.0)\n")
    assert analyze_paths([tmp_path], ["deadline-wait"]) == []


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        analyze_paths([FIXTURES], ["no-such-rule"])


# ---------------------------------------------------------------------------
# the tier-1 gate: the package itself is clean, fast
# ---------------------------------------------------------------------------

def test_package_is_clean_and_fast():
    t0 = time.perf_counter()
    findings = analyze_paths([PKG])
    dt = time.perf_counter() - t0
    assert findings == [], "analyzer findings in dgraph_tpu/:\n" + \
        "\n".join(f.format() for f in findings)
    assert dt < 10.0, f"analyzer took {dt:.1f}s over the package"


def test_rule_registry_shape():
    # the ~8 checkers the issue names, by stable rule id
    assert set(RULES) == {
        "metric-registration", "ctxvar-copy", "deadline-wait",
        "except-seam", "rpc-error-taxonomy", "jax-purity",
        "fault-points", "lock-order"}
    for name, cls in RULES.items():
        assert cls().doc, name


# ---------------------------------------------------------------------------
# shared metric collector (one implementation, two consumers)
# ---------------------------------------------------------------------------

def test_metric_collector_sees_the_tree():
    names = collect_metric_names(PKG)
    assert len(names) > 80, names
    assert "dgraph_task_cache_hits_total" in names    # {prefix} expansion
    assert "dgraph_http_query_latency_s" in names     # {ep} expansion
    reg = registered_metric_names()
    assert names <= reg, sorted(names - reg)


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.analysis", *args],
        capture_output=True, text=True, timeout=120,
        cwd=Path(__file__).parent.parent)


def test_cli_findings_exit_nonzero_and_json():
    p = _cli(str(FIXTURES), "--format=json")
    assert p.returncode == 1, p.stderr
    out = json.loads(p.stdout)
    assert out["findings"], out
    rules = {f["rule"] for f in out["findings"]}
    assert "lock-order" in rules and "metric-registration" in rules


def test_cli_rule_filter_and_clean_exit():
    p = _cli(str(FIXTURES / "bad_ctxvar.py"), "--rule", "except-seam")
    assert p.returncode == 0, (p.stdout, p.stderr)
    p = _cli(str(FIXTURES), "--rule", "bogus")
    assert p.returncode == 2
    p = _cli("--list-rules")
    assert p.returncode == 0 and "deadline-wait" in p.stdout


@pytest.mark.slow
def test_cli_package_clean():
    p = _cli("dgraph_tpu")
    assert p.returncode == 0, p.stdout
