"""Conflict-keyed mutation scheduler (reference worker/scheduler.go:34-95):
disjoint footprints overlap, shared footprints serialize in arrival order,
and the Node-level apply path stays correct under concurrent writers."""

import threading
import time

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import TxnConflict
from dgraph_tpu.parallel.scheduler import Scheduler


def test_disjoint_keys_run_concurrently():
    s = Scheduler()
    gate = threading.Barrier(3, timeout=5)

    def task():
        gate.wait()   # all three must be inside fn simultaneously

    ts = [threading.Thread(target=s.run, args=([k], task)) for k in (1, 2, 3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=5)
    assert s.max_concurrent == 3


def test_shared_key_serializes_in_order():
    s = Scheduler()
    order = []
    started = threading.Event()

    def slow():
        order.append("first")
        started.set()
        time.sleep(0.05)

    def fast(tag):
        order.append(tag)

    t1 = threading.Thread(target=s.run, args=([7], slow))
    t1.start()
    started.wait(5)
    t2 = threading.Thread(target=s.run, args=([7, 8], lambda: fast("second")))
    t2.start()
    for _ in range(500):        # t3 must enqueue after t2 holds key 8's queue
        with s._cv:
            if 8 in s._queues:
                break
        time.sleep(0.005)
    t3 = threading.Thread(target=s.run, args=([8], lambda: fast("third")))
    t3.start()
    for t in (t1, t2, t3):
        t.join(timeout=5)
    assert order == ["first", "second", "third"]
    assert s.max_concurrent == 1


def test_overlapping_sets_no_deadlock():
    s = Scheduler()
    done = []

    def mk(keys):
        def f():
            time.sleep(0.001)
            done.append(keys)
        return f

    ts = [threading.Thread(target=s.run, args=(k, mk(tuple(k))))
          for _ in range(5)
          for k in ([1, 2], [2, 3], [3, 1], [1, 2, 3])]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert len(done) == 20


def test_concurrent_disjoint_mutations_correct():
    node = Node()
    node.alter(schema_text="name: string @index(exact) .\nscore: int .")
    errs = []

    def writer(i):
        try:
            for j in range(10):
                node.mutate(
                    set_nquads=f'<0x{i * 100 + j + 1:x}> <score> "{j}" .',
                    commit_now=True)
        except Exception as e:   # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs
    out, _ = node.query('{ q(func: has(score)) { uid } }')
    assert len(out["q"]) == 80
    # spot-check: subject i*100+j+1 carries score j
    for i, j in [(1, 0), (3, 7), (7, 9)]:
        out, _ = node.query(
            f'{{ q(func: uid(0x{i * 100 + j + 1:x})) {{ score }} }}')
        assert out["q"][0]["score"] == j


def test_commit_waits_for_inflight_apply():
    """A commit issued mid-apply must not orphan the txn's layers."""
    node = Node()
    node.alter(schema_text="v: int .")
    ctx = node.new_txn()
    release = threading.Event()
    entered = threading.Event()

    real_run = node._sched.run

    def slow_run(keys, fn, **kw):
        def wrapped():
            entered.set()
            release.wait(5)
            return fn()
        return real_run(keys, wrapped, **kw)

    node._sched.run = slow_run
    t = threading.Thread(target=node.mutate, kwargs=dict(
        set_nquads='<0x1> <v> "1" .', start_ts=ctx.start_ts))
    t.start()
    entered.wait(5)
    committed = []
    c = threading.Thread(
        target=lambda: committed.append(node.commit(ctx.start_ts)))
    c.start()
    time.sleep(0.05)
    assert not committed          # commit is parked on inflight
    release.set()
    t.join(timeout=5)
    c.join(timeout=5)
    assert committed              # and completes with the mutation included
    out, _ = node.query('{ q(func: uid(0x1)) { v } }')
    assert out["q"][0]["v"] == 1


def test_exclusive_blocks_everything():
    s = Scheduler()
    order = []
    started = threading.Event()

    def first():
        order.append("normal-1")
        started.set()
        time.sleep(0.05)

    t1 = threading.Thread(target=s.run, args=([1], first))
    t1.start()
    started.wait(5)
    tx = threading.Thread(target=s.run,
                          args=([], lambda: order.append("exclusive")),
                          kwargs=dict(exclusive=True))
    tx.start()
    for _ in range(500):
        with s._cv:
            if s._excl:
                break
        time.sleep(0.005)
    t2 = threading.Thread(target=s.run, args=([9], lambda: order.append("after")))
    t2.start()
    for t in (t1, tx, t2):
        t.join(timeout=5)
    assert order == ["normal-1", "exclusive", "after"]


def test_star_delete_takes_exclusive_and_works():
    node = Node()
    node.alter(schema_text="name: string @index(exact) .\nv: int .")
    node.mutate(set_nquads='<0x5> <name> "gone" .\n<0x5> <v> "3" .',
                commit_now=True)
    node.mutate(del_nquads="<0x5> * * .", commit_now=True)
    out, _ = node.query('{ q(func: uid(0x5)) { name v } }')
    assert out == {}
    assert node._sched.started >= 2


def test_mutation_after_commit_started_rejected():
    node = Node()
    node.alter(schema_text="v: int .")
    ctx = node.new_txn()
    node.mutate(set_nquads='<0x1> <v> "1" .', start_ts=ctx.start_ts)
    node.commit(ctx.start_ts)
    with pytest.raises(Exception):
        node.mutate(set_nquads='<0x2> <v> "2" .', start_ts=ctx.start_ts)
