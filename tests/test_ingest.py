"""Out-of-core ingest tier: spill/merge primitives, byte-identical bulk
output across spill on/off and worker counts, the disk-backed sharded-LRU
xidmap (crash-kill-resume under a cache cap), and the streaming checkpoint
(peak transient independent of key count).

Reference: dgraph/cmd/bulk mapper.go:121-175 (spill runs) + merge_shards.go
+ reduce.go (k-way merge reduce), xidmap/xidmap.go:30-80 (badger-backed
sharded LRU)."""

import hashlib
import os

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import UidLease
from dgraph_tpu.ingest import spill
from dgraph_tpu.loader.bulk import bulk_load
from dgraph_tpu.loader.live import live_load
from dgraph_tpu.loader.xidmap import XidMap
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.store import Store

SCHEMA = """
name: string @index(exact, term) .
age: int @index(int) .
follows: [uid] @reverse @count .
bio: string @lang .
nick: [string] @index(term) .
"""


def _rich_rdf(n=300, edges=6, seed=11):
    """Values, langs, facets, list values, uid edges with dups — every
    reduce branch."""
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        lines.append(f'_:p{i} <name> "person {i}" .')
        lines.append(f'_:p{i} <age> "{20 + i % 60}"^^<xs:int> .')
        if i % 3 == 0:
            lines.append(f'_:p{i} <bio> "hello {i}"@en .')
            lines.append(f'_:p{i} <bio> "bonjour {i}"@fr .')
        if i % 5 == 0:
            lines.append(f'_:p{i} <nick> "nick{i}" .')
            lines.append(f'_:p{i} <nick> "alias{i % 7}" .')
        for j in rng.choice(n, size=edges, replace=False):
            if j % 11 == 3:
                lines.append(f'_:p{i} <follows> _:p{j} '
                             f'(since={1990 + int(j) % 30}) .')
            else:
                lines.append(f'_:p{i} <follows> _:p{j} .')
    lines += lines[:40]               # duplicate quads on purpose
    return "\n".join(lines) + "\n"


def _sha(path):
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


# -- spill/merge primitives ---------------------------------------------------

def test_pair_spill_merge_roundtrip(tmp_path):
    """Merged groups == global sort+dedupe of every added pair, across many
    tiny runs (budget forces constant flushing)."""
    st = spill.SpillStats()
    pool = spill.SpillSet(str(tmp_path / "spl"), 2048, st)
    ps = spill.UidPairSpiller(pool)
    rng = np.random.default_rng(3)
    pairs = [(int(rng.integers(1, 200)), int(rng.integers(1, 5000)))
             for _ in range(8000)]
    for a, b in pairs:
        ps.add("ch", a, b)
    pool.flush()
    assert st.spill_runs > 4            # the budget actually forced runs
    ref: dict[int, list[int]] = {}
    for a, b in sorted(set(pairs)):
        ref.setdefault(a, []).append(b)
    got = {a: row.tolist()
           for a, row in spill.merge_pairs(ps.runs("ch"), st)}
    assert got == ref
    assert st.merge_fanin == min(len(ps.runs("ch")), spill.MERGE_FANIN_MAX)


def test_merge_cascade_bounds_fanin(tmp_path):
    """More runs than max_fanin cascade through intermediate runs (fd
    bound); results identical to a flat merge, temps cleaned up."""
    pool = spill.SpillSet(str(tmp_path / "spl"), 1024)
    ps = spill.UidPairSpiller(pool)
    fs = spill.FramedSpiller(pool)
    rng = np.random.default_rng(9)
    pref: dict[int, list[int]] = {}
    fref: dict[bytes, list[bytes]] = {}
    for i in range(4000):
        a, b = int(rng.integers(1, 60)), int(rng.integers(1, 999))
        ps.add("p", a, b)
        key = (a % 13).to_bytes(8, "big")
        fs.add("f", key, f"x{i}".encode())
        fref.setdefault(key, []).append(f"x{i}".encode())
    pool.flush()
    assert len(ps.runs("p")) > 6 and len(fs.runs("f")) > 6
    flat_p = {a: r.tolist()
              for a, r in spill.merge_pairs(ps.runs("p"), max_fanin=10**6)}
    casc_p = {a: r.tolist()
              for a, r in spill.merge_pairs(ps.runs("p"), max_fanin=3)}
    assert casc_p == flat_p
    flat_f = dict(spill.group_framed(
        spill.merge_framed(fs.runs("f"), max_fanin=10**6)))
    casc_f = dict(spill.group_framed(
        spill.merge_framed(fs.runs("f"), max_fanin=3)))
    assert casc_f == flat_f == fref
    # cascade temps were deleted
    leftovers = [p for p in os.listdir(str(tmp_path / "spl"))
                 if ".c" in p]
    assert not leftovers, leftovers


def test_pair_merge_group_spans_chunks(tmp_path):
    """A single subject whose pairs span multiple on-disk chunks (a hub
    node) must still come out as ONE complete group."""
    pool = spill.SpillSet(str(tmp_path / "spl"), 1 << 30)
    ps = spill.UidPairSpiller(pool)
    hub_edges = spill.PAIR_CHUNK * 2 + 123
    for b in range(hub_edges):
        ps.add("ch", 7, b + 1)
    ps.add("ch", 9, 1)
    pool.flush()
    groups = list(spill.merge_pairs(ps.runs("ch")))
    assert [g[0] for g in groups] == [7, 9]
    assert len(groups[0][1]) == hub_edges


def test_framed_spill_preserves_input_order(tmp_path):
    """Per-key payload order after merge == input order (the determinism
    contract value rows rely on), even across runs."""
    pool = spill.SpillSet(str(tmp_path / "spl"), 512)   # tiny: many runs
    fs = spill.FramedSpiller(pool)
    rng = np.random.default_rng(5)
    ref: dict[bytes, list[bytes]] = {}
    for i in range(2000):
        key = int(rng.integers(1, 50)).to_bytes(8, "big")
        payload = f"p{i}".encode()
        fs.add("ch", key, payload)
        ref.setdefault(key, []).append(payload)
    pool.flush()
    got = dict(spill.group_framed(spill.merge_framed(fs.runs("ch"))))
    assert got == ref


def test_pair_codec_nonmonotonic_column(tmp_path):
    """The b-column is only sorted per group — deltas wrap mod 2**64 and
    must still round-trip exactly through the packed run codec."""
    pool = spill.SpillSet(str(tmp_path / "spl"), 1 << 30)
    ps = spill.UidPairSpiller(pool)
    rows = {1: [2**40, 2**41], 2: [5], 3: [1, 2**63, 2**63 + 1]}
    for a, bs in rows.items():
        for b in bs:
            ps.add("ch", a, b)
    pool.flush()
    got = {a: row.tolist() for a, row in spill.merge_pairs(ps.runs("ch"))}
    assert got == rows


# -- bulk determinism ---------------------------------------------------------

def test_bulk_byte_identical_across_spill_and_workers(tmp_path):
    """The acceptance gate in miniature: snapshot bytes identical across
    --workers counts AND across spill on/off (with the spill budget small
    enough to force dozens of runs), including the bounded-xidmap case."""
    rdf = tmp_path / "d.rdf"
    rdf.write_text(_rich_rdf())
    stats = {}
    outs = {}
    for label, kw in [
            ("inram_w1", dict(workers=1)),
            ("inram_w2", dict(workers=2)),
            ("spill_w1", dict(workers=1, spill_mb=0.02)),
            ("spill_w2_capped", dict(workers=2, spill_mb=0.02,
                                     xidmap_cache=64))]:
        out = str(tmp_path / label)
        stats[label] = bulk_load(str(rdf), SCHEMA, out, **kw)
        outs[label] = _sha(os.path.join(out, "snapshot.bin"))
    assert len(set(outs.values())) == 1, outs
    s0 = stats["inram_w1"]
    for s in stats.values():
        assert (s.edges, s.uid_edges, s.values, s.nodes, s.predicates,
                s.xids) == (s0.edges, s0.uid_edges, s0.values, s0.nodes,
                            s0.predicates, s0.xids)
    sp = stats["spill_w1"]
    assert sp.spill_runs > 10 and sp.merge_fanin > 1   # out-of-core engaged
    assert stats["spill_w2_capped"].xidmap_hit_rate < 1.0  # LRU paged

    # the spill output actually serves: reverse, count, term index, facet
    node = Node(str(tmp_path / "spill_w1"))
    q, _ = node.query('{ q(func: eq(name, "person 3")) '
                      '{ name bio@fr fc: count(follows) '
                      '  follows @facets(since) { name } } }')
    assert q["q"][0]["name"] == "person 3" and q["q"][0]["fc"] >= 1
    q2, _ = node.query('{ q(func: anyofterms(nick, "alias3")) '
                       '{ count(uid) } }')
    assert q2["q"][0]["count"] > 0
    q3, _ = node.query('{ q(func: eq(name, "person 1")) '
                       '{ ~follows { count(uid) } } }')
    node.close()


def test_bulk_spill_requires_out_dir(tmp_path):
    from dgraph_tpu.loader.bulk import BulkError

    rdf = tmp_path / "d.rdf"
    rdf.write_text('_:a <name> "x" .\n')
    with pytest.raises(BulkError, match="out_dir"):
        bulk_load(str(rdf), "", "", spill_mb=1)


def test_bulk_spill_mixed_predicate_error_cleans_up(tmp_path):
    """A failed spill load must not leak the WAL fd or leave graph-sized
    run files / a half-written snapshot behind (review finding)."""
    from dgraph_tpu.loader.bulk import BulkError

    rdf = tmp_path / "d.rdf"
    rdf.write_text('_:a <p> _:b .\n_:a <p> "hello" .\n')
    out = tmp_path / "o"
    with pytest.raises(BulkError, match="both uid edges and literal"):
        bulk_load(str(rdf), "", str(out), spill_mb=1)
    assert not (out / ".spill").exists()
    assert not (out / "snapshot.bin.tmp").exists()
    # the dir is re-usable: the store fd was released, a clean retry works
    rdf2 = tmp_path / "ok.rdf"
    rdf2.write_text('_:a <p> _:b .\n')
    stats = bulk_load(str(rdf2), "", str(out), spill_mb=1)
    assert stats.uid_edges == 1


# -- sharded xidmap -----------------------------------------------------------

def test_xidmap_lru_pages_to_disk(tmp_path):
    """Cardinality 8x the cache cap: evictions happen, every mapping stays
    stable through reloads."""
    lease = UidLease()
    d = str(tmp_path / "xm")
    xm = XidMap(lease, dirpath=d, cache_entries=100)
    first = {f"node{i}": xm.uid(f"node{i}") for i in range(800)}
    assert xm.stats.evictions > 0
    # re-reading every xid pages shards back in and returns the SAME uids
    for x, u in first.items():
        assert xm.uid(x) == u
    assert xm.stats.hit_rate < 1.0          # loads happened
    assert len(xm) == 800
    xm.flush()
    # fresh attach from disk only (no log): identical mappings
    lease2 = UidLease()
    xm2 = XidMap(lease2, dirpath=d, cache_entries=100)
    for x, u in first.items():
        assert xm2.uid(x) == u
    # new names never collide with persisted ones (meta max_uid bumped)
    assert xm2.uid("fresh") > max(first.values())


def test_xidmap_crashed_dir_recovers_lease_ceiling(tmp_path):
    """Crash window (review finding): shard files on disk, flush() never
    ran. Attaching must recover the lease ceiling — new xids must NEVER
    mint an already-assigned uid (silent entity merging). Covers both the
    eager unclean-meta path and the legacy meta-less dir (meta deleted)."""
    import json as _json

    d = str(tmp_path / "xm")
    lease = UidLease()
    xm = XidMap(lease, dirpath=d, cache_entries=50, shards=48)
    first = {f"n{i}": xm.uid(f"n{i}") for i in range(400)}
    assert xm.stats.evictions > 0          # shard files exist on disk
    # crash: no flush(), no close() — meta exists (eager write at
    # creation/eviction) but is marked unclean
    meta = _json.load(open(os.path.join(d, "meta.json")))
    assert meta["clean"] is False and meta["shards"] == 48

    lease2 = UidLease()
    xm2 = XidMap(lease2, dirpath=d, cache_entries=50)
    assert xm2._nshards == 48              # non-default modulus preserved
    kept = {u for x, u in first.items() if xm2.uid(x) == u}
    fresh = xm2.uid("brand-new-xid")
    assert fresh not in first.values(), \
        "lease re-minted a uid from an orphaned shard"
    assert kept                  # some mappings did come back from disk

    # legacy dir shape: meta.json gone entirely — the shard scan must
    # still widen the modulus past every file and recover the ceiling
    os.unlink(os.path.join(d, "meta.json"))
    lease3 = UidLease()
    xm3 = XidMap(lease3, dirpath=d, cache_entries=50)
    assert xm3._nshards >= 48
    fresh3 = xm3.uid("another-new-xid")
    assert fresh3 not in first.values()


def test_xidmap_taken_set_stays_bounded():
    """All-literal-uid input (the R-MAT battery shape) must not grow an
    O(distinct uids) reservation set the cache bound can't see — only
    current-block collisions are remembered (review finding)."""
    lease = UidLease()
    xm = XidMap(lease, block=64)
    for i in range(1, 20001):
        assert xm.uid(f"0x{i:x}") == i
    assert len(xm._taken) <= 64, len(xm._taken)
    # reservation semantics survive the pruning: an explicit uid inside
    # the CURRENT leased block is still never handed out
    named = xm.uid("named-a")
    inside = named + 1
    assert xm.uid(f"0x{inside:x}") == inside
    assert xm.uid("named-b") != inside


def test_xidmap_crash_kill_resume_under_cache_cap(tmp_path):
    """Kill (no close/flush) after sync: the append log replays through the
    bounded LRU and preserves every identity — the cap being far below the
    live cardinality must not lose or duplicate assignments."""
    wal = str(tmp_path / "x.log")
    lease = UidLease()
    xm = XidMap.open(wal, lease, cache_entries=50)
    first = {f"n{i}": xm.uid(f"n{i}") for i in range(400)}   # 8x the cap
    xm.sync()
    # crash: NO close(), NO flush() — some shards only exist in the log
    del xm

    # torn trailing record on top (crash mid-write)
    with open(wal, "ab") as f:
        f.write(b"n9999\t12")
    lease2 = UidLease()
    xm2 = XidMap.open(wal, lease2, cache_entries=50)
    for x, u in first.items():
        assert xm2.uid(x) == u, x
    u_new = xm2.uid("n9999")                # torn record re-assigned
    assert u_new not in first.values() and u_new != 12
    nxt, _ = lease2.assign(1)
    assert nxt > max(first.values())
    xm2.close()


def test_xidmap_old_json_loads_and_migrates(tmp_path):
    """Deprecated whole-map JSON files still load, and migrate() converts
    them one-shot into the sharded dir format."""
    import json as _json

    old = tmp_path / "xidmap.json"
    mapping = {f"p{i}": i + 1 for i in range(50)}
    old.write_text(_json.dumps(mapping))

    xm = XidMap.load(str(old), UidLease())
    assert xm.uid("p7") == 8 and len(xm) == 50
    assert xm.uid("new") > 50               # lease bumped past the map

    lease = UidLease()
    xm2 = XidMap.migrate(str(old), str(tmp_path / "sharded"), lease)
    assert xm2.uid("p7") == 8 and len(xm2) == 50
    # the sharded dir now attaches standalone
    xm3 = XidMap(UidLease(), dirpath=str(tmp_path / "sharded"))
    assert xm3.uid("p7") == 8


def test_xidmap_save_is_deprecated_but_works(tmp_path):
    lease = UidLease()
    xm = XidMap(lease)
    a = xm.uid("alice")
    with pytest.warns(DeprecationWarning):
        xm.save(str(tmp_path / "m.json"))
    xm2 = XidMap.load(str(tmp_path / "m.json"), UidLease())
    assert xm2.uid("alice") == a


def test_live_load_with_lru_cap_below_cardinality(tmp_path):
    """Satellite acceptance: live-load with xid cardinality >= 4x the LRU
    cap succeeds, and a resumed load keeps every identity."""
    n = 400
    rdf1 = tmp_path / "a.rdf"
    rdf1.write_text("".join(f'_:x{i} <name> "v{i}" .\n' for i in range(n)))
    rdf2 = tmp_path / "b.rdf"
    rdf2.write_text("".join(f'_:x{i} <age> "{i % 90}"^^<xs:int> .\n'
                            for i in range(n)))
    wal = str(tmp_path / "xm.log")

    node = Node(dirpath=str(tmp_path / "p"))
    node.alter(schema_text="name: string @index(exact) .\nage: int .")
    live_load(node, str(rdf1), xidmap_path=wal, xidmap_cache=n // 4)
    # resumed run, same cap: identities must line up on the same nodes
    live_load(node, str(rdf2), xidmap_path=wal, xidmap_cache=n // 4)
    out, _ = node.query('{ q(func: eq(name, "v17")) { name age } }')
    assert out["q"] == [{"name": "v17", "age": 17 % 90}]
    assert node.metrics.counter("dgraph_xidmap_evictions_total").value > 0
    node.close()


# -- streaming checkpoint -----------------------------------------------------

def test_checkpoint_peak_transient_independent_of_keys(tmp_path):
    """8x the keys must NOT mean 8x the checkpoint transient: the streaming
    writer's spool ceiling dominates (shrunk here so the bound binds)."""
    from dgraph_tpu.storage.postings import Posting

    peaks = {}
    for label, n in [("small", 500), ("big", 4000)]:
        d = str(tmp_path / label)
        s = Store(d)
        s.SNAP_SPOOL_MAX = 1 << 12
        kbs = []
        for i in range(1, n + 1):
            k = K.data_key("p", i)
            s.add_mutation(1, k, Posting(i + 1))
            kbs.append(k.encode())
        s.commit(1, 2, kbs)
        s.checkpoint(2)
        peaks[label] = s.last_checkpoint_stats["peak_transient_bytes"]
        assert s.last_checkpoint_stats["rows"] == n
        s.close()
    assert peaks["big"] < peaks["small"] * 3, peaks


def test_paged_pristine_checkpoint_is_byte_identical_copy(tmp_path):
    """A paged store with zero writes re-checkpoints by streaming its mmap
    segments file-to-file — the output snapshot is byte-identical to the
    input (nothing was ever decoded)."""
    rdf = tmp_path / "d.rdf"
    rdf.write_text(_rich_rdf(n=120, edges=4))
    out = str(tmp_path / "p")
    bulk_load(str(rdf), SCHEMA, out, workers=1)
    snap = os.path.join(out, "snapshot.bin")
    before = _sha(snap)

    s = Store(out, memory_budget=1 << 20)
    assert s._segments
    s.checkpoint(s.snapshot_ts)
    # zero rows went through a spool: pure run copy
    assert s.last_checkpoint_stats["peak_transient_bytes"] == 0
    s.close()
    assert _sha(snap) == before


def test_paged_dirty_checkpoint_merges_residents_over_segments(tmp_path):
    """Writes on top of segment-backed keys + brand-new keys: the streamed
    checkpoint must fold them over the pristine rows, and a reopen (eager
    AND paged) sees the merged state."""
    from dgraph_tpu.storage.postings import Op, Posting

    rdf = tmp_path / "d.rdf"
    rdf.write_text(_rich_rdf(n=80, edges=3))
    out = str(tmp_path / "p")
    bulk_load(str(rdf), SCHEMA, out, workers=1)

    node = Node(out, memory_mb=32)
    node.mutate(set_nquads="<0x3> <follows> <0x4f> .", commit_now=True)
    node.mutate(set_nquads='_:new <name> "fresh" .', commit_now=True)
    want, _ = node.query('{ q(func: uid(0x3)) { follows { uid } } }')
    node.store.checkpoint(node.store.max_seen_commit_ts)
    node.close()

    for kw in ({}, {"memory_mb": 32}):
        n2 = Node(out, **kw)
        got, _ = n2.query('{ q(func: uid(0x3)) { follows { uid } } }')
        assert got == want
        got2, _ = n2.query('{ q(func: eq(name, "fresh")) { name } }')
        assert got2["q"] == [{"name": "fresh"}]
        n2.close()


def test_checkpoint_metrics_gauge(tmp_path):
    """The peak-transient gauge lands in the node registry (satellite:
    ingest counters on /metrics)."""
    node = Node(dirpath=str(tmp_path / "p"))
    node.alter(schema_text="name: string .")
    node.mutate(set_nquads='_:a <name> "x" .', commit_now=True)
    node.store.checkpoint(node.store.max_seen_commit_ts)
    assert node.metrics.counter(
        "dgraph_checkpoint_peak_transient_bytes").value > 0
    from dgraph_tpu.obs import prom
    series = prom.parse(prom.render(node.metrics))
    assert "dgraph_checkpoint_peak_transient_bytes" in series
    assert "dgraph_ingest_spill_bytes_total" in series
    node.close()


def test_bulk_metrics_populate_registry(tmp_path):
    """A registry-wired bulk load actually FEEDS the dgraph_ingest_* and
    dgraph_xidmap_* counters (review finding: registered-but-always-zero
    series are worse than absent ones)."""
    from dgraph_tpu.utils import metrics as m

    rdf = tmp_path / "d.rdf"
    rdf.write_text(_rich_rdf(n=120, edges=3))
    reg = m.Registry()
    bulk_load(str(rdf), SCHEMA, str(tmp_path / "p"), workers=1,
              spill_mb=0.02, xidmap_cache=64, metrics=reg)
    assert reg.counter("dgraph_ingest_spill_bytes_total").value > 0
    assert reg.counter("dgraph_ingest_spill_runs_total").value > 0
    assert reg.counter("dgraph_ingest_merge_fanin").value > 0
    assert reg.counter("dgraph_xidmap_lookups_total").value > 0
    assert reg.counter("dgraph_xidmap_evictions_total").value > 0
