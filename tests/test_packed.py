"""Packed uid codec: host roundtrip, seek, and device decode parity.

Mirrors the reference's bp128 roundtrip tests on real posting distributions
(bp128/bp128_test.go with fixtures in bp128/data/).
"""

import numpy as np
import pytest

from dgraph_tpu.storage import packed
from dgraph_tpu.ops import packed_decode, uidset as us


def gen_uids(rng, n, max_delta=1000):
    deltas = rng.integers(1, max_delta, size=n).astype(np.uint64)
    return np.cumsum(deltas)


@pytest.mark.parametrize("n", [0, 1, 5, 127, 128, 129, 1000, 10_000])
def test_roundtrip_sizes(rng, n):
    uids = gen_uids(rng, n) if n else np.zeros(0, dtype=np.uint64)
    pl = packed.pack(uids)
    assert pl.count == n
    np.testing.assert_array_equal(packed.unpack(pl), uids)


def test_roundtrip_dense_and_sparse(rng):
    dense = np.arange(5000, dtype=np.uint64) + 7  # delta=1 → 1-bit blocks
    pl = packed.pack(dense)
    assert pl.block_width.max() <= 1
    np.testing.assert_array_equal(packed.unpack(pl), dense)

    sparse = np.cumsum(rng.integers(1, 2**40, size=500).astype(np.uint64))
    pl = packed.pack(sparse)
    assert (pl.block_width == 64).any()  # raw64 escape exercised
    np.testing.assert_array_equal(packed.unpack(pl), sparse)


def test_compression_ratio(rng):
    uids = gen_uids(rng, 100_000, max_delta=100)  # typical posting gaps
    pl = packed.pack(uids)
    raw_bytes = uids.nbytes
    assert pl.nbytes < raw_bytes / 4  # ≥4x over raw uint64
    np.testing.assert_array_equal(packed.unpack(pl), uids)


def test_seek_block(rng):
    uids = gen_uids(rng, 1000, max_delta=10)
    pl = packed.pack(uids)
    for after in [0, int(uids[0]), int(uids[500]), int(uids[-1])]:
        b = packed.seek_block(pl, after)
        if after >= int(uids[-1]):
            assert b == pl.nblocks or int(pl.block_last[b]) >= after
        else:
            # every uid > after lives in block >= b
            first_greater = int(np.searchsorted(uids, after, side="right"))
            assert first_greater // packed.BLOCK >= b or b == 0


@pytest.mark.parametrize("n,max_delta", [(1, 2), (300, 3), (4096, 1000), (10_000, 30)])
def test_device_decode_parity(rng, n, max_delta):
    uids = gen_uids(rng, n, max_delta=max_delta)
    assert int(uids[-1]) < 2**31, "keep test uids in int32 range"
    pl = packed.pack(uids)
    dev = packed_decode.to_device(pl)
    out = packed_decode.unpack_device(dev)
    np.testing.assert_array_equal(us.to_numpy(out), uids.astype(np.int64))


def test_device_rejects_wide_uids(rng):
    pl = packed.pack(np.array([1, 2**33], dtype=np.uint64))
    with pytest.raises(ValueError):
        packed_decode.to_device(pl)


def test_pack_many_matches_pack(rng):
    from dgraph_tpu.storage import packed

    rows = [
        np.zeros(0, dtype=np.uint64),
        np.array([5], dtype=np.uint64),
        np.unique(rng.integers(0, 10**6, size=20).astype(np.uint64)),
        np.unique(rng.integers(0, 10**9, size=300).astype(np.uint64)),
        np.arange(128, dtype=np.uint64) * 7 + 3,          # exactly one block
        np.arange(129, dtype=np.uint64),                  # block boundary + 1
        np.array([1, 2**33, 2**40], dtype=np.uint64),     # raw64 escape
        np.unique(rng.integers(0, 50, size=10).astype(np.uint64)),
    ]
    many = packed.pack_many(rows)
    assert len(many) == len(rows)
    for row, pm in zip(rows, many):
        one = packed.pack(row)
        np.testing.assert_array_equal(packed.unpack(pm), row)
        np.testing.assert_array_equal(packed.unpack(pm), packed.unpack(one))
        assert pm.count == one.count
        np.testing.assert_array_equal(pm.block_first, one.block_first)
        np.testing.assert_array_equal(pm.block_last, one.block_last)
        np.testing.assert_array_equal(pm.block_count, one.block_count)
        np.testing.assert_array_equal(pm.block_width, one.block_width)
