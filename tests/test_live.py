"""Live queries (ISSUE 18, dgraph_tpu/live/): lifecycle, O(Δ) wake
filtering, per-window coalescing, flow control, journal retention, and
the byte-identity correctness gate — every notification's result must be
byte-identical (live.diff.canon) to re-running the query read-only at
the commit watermark it carries."""

import json
import threading
import time

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.cluster import Cluster
from dgraph_tpu.live.diff import canon, result_diff

SCHEMA = """
name: string @index(term) .
age: int @index(int) .
follows: [uid] @reverse .
"""

Q_NAME = "{ q(func: has(name)) { uid name } }"


@pytest.fixture
def node():
    n = Node()
    n.alter(SCHEMA)
    n.mutate(set_nquads='<0x1> <name> "alice" .\n<0x2> <name> "bob" .\n'
                        '<0x1> <age> "30" .',
             commit_now=True)
    yield n
    n.close()


def _assert_byte_identical(node, q, ev):
    """THE correctness gate: the notification's result re-derives exactly
    at its carried watermark."""
    rerun = node.query(q, start_ts=ev["at"], read_only=True)[0]
    assert canon(ev["result"]) == canon(rerun), (ev, rerun)


# -- diff engine -------------------------------------------------------------

def test_result_diff_uid_keyed():
    old = {"q": [{"uid": "0x1", "name": "a"}, {"uid": "0x2", "name": "b"}]}
    new = {"q": [{"uid": "0x1", "name": "a2"}, {"uid": "0x3", "name": "c"}]}
    d = result_diff(old, new)
    assert d["q"]["changed"] == [{"uid": "0x1", "name": "a2"}]
    assert d["q"]["added"] == [{"uid": "0x3", "name": "c"}]
    assert d["q"]["removed"] == [{"uid": "0x2", "name": "b"}]


def test_result_diff_uidless_multiset_and_no_change():
    old = {"q": [{"count": 2}]}
    assert result_diff(old, {"q": [{"count": 3}]})["q"]["added"] == [
        {"count": 3}]
    assert result_diff(old, {"q": [{"count": 2}]}) is None
    assert result_diff(None, {"q": []}) is None


def test_canon_is_order_insensitive_and_compact():
    assert canon({"b": 1, "a": [1, 2]}) == '{"a":[1,2],"b":1}'
    assert canon({"a": 1, "b": 2}) == canon({"b": 2, "a": 1})


# -- lifecycle ---------------------------------------------------------------

def test_subscribe_init_diff_cancel(node):
    sub = node.subscribe(Q_NAME)
    ev = sub.next(5)
    assert ev["type"] == "init" and ev["sub"] == sub.id
    assert {e["name"] for e in ev["result"]["q"]} == {"alice", "bob"}
    _assert_byte_identical(node, Q_NAME, ev)

    node.mutate(set_nquads='<0x3> <name> "carol" .', commit_now=True)
    ev = sub.next(5)
    assert ev["type"] == "diff"
    assert ev["diff"]["q"]["added"] == [{"uid": "0x3", "name": "carol"}]
    assert ev["diff"]["q"]["removed"] == []
    _assert_byte_identical(node, Q_NAME, ev)

    # delete reports as removed
    node.mutate(del_nquads='<0x3> <name> * .', commit_now=True)
    ev = sub.next(5)
    assert ev["type"] == "diff"
    assert ev["diff"]["q"]["removed"] == [{"uid": "0x3", "name": "carol"}]
    _assert_byte_identical(node, Q_NAME, ev)

    assert sub.cancel() is True
    assert sub.cancel() is False
    with pytest.raises(StopIteration):
        sub.next(1)
    assert node.live.stats()["active"] == 0


def test_subscription_is_an_iterator(node):
    sub = node.subscribe(Q_NAME)
    it = iter(sub)
    assert next(it)["type"] == "init"
    node.mutate(set_nquads='<0x4> <name> "dave" .', commit_now=True)
    assert next(it)["type"] == "diff"
    sub.cancel()


def test_mutations_not_subscribable(node):
    with pytest.raises(Exception):
        node.subscribe('{ set { <0x1> <name> "x" . } }')
    with pytest.raises(Exception):
        node.subscribe("schema {}")
    assert node.live.stats()["active"] == 0


def test_watermark_monotone_and_carried(node):
    sub = node.subscribe(Q_NAME)
    last = sub.next(5)["at"]
    for i in range(3):
        node.mutate(set_nquads=f'<0x{i + 5:x}> <name> "u{i}" .',
                    commit_now=True)
        ev = sub.next(5)
        assert ev["at"] > last
        last = ev["at"]
        _assert_byte_identical(node, Q_NAME, ev)
    sub.cancel()


# -- O(Δ) wake filtering -----------------------------------------------------

def test_unrelated_predicate_does_not_wake(node):
    sub = node.subscribe(Q_NAME)
    sub.next(5)
    evals0 = node.metrics.counter("dgraph_subs_evals_total").value
    node.mutate(set_nquads='<0x1> <age> "31" .', commit_now=True)
    assert sub.next(0.8) is None   # commit touched only `age`
    # ... and the notifier never re-evaluated anything for it
    assert node.metrics.counter("dgraph_subs_evals_total").value == evals0
    node.mutate(set_nquads='<0x9> <name> "eve" .', commit_now=True)
    ev = sub.next(5)
    assert ev["type"] == "diff"
    _assert_byte_identical(node, Q_NAME, ev)
    sub.cancel()


def test_touch_test_covers_filters_and_children(node):
    q = ('{ q(func: has(name)) @filter(ge(age, 0)) '
         '{ uid name follows { uid name } } }')
    sub = node.subscribe(q)
    sub.next(5)
    # a commit touching only a FILTER predicate must wake it
    node.mutate(set_nquads='<0x2> <age> "44" .', commit_now=True)
    ev = sub.next(5)
    assert ev is not None and ev["type"] == "diff", ev
    _assert_byte_identical(node, q, ev)
    # ... and a child predicate too
    node.mutate(set_nquads="<0x1> <follows> <0x2> .", commit_now=True)
    ev = sub.next(5)
    assert ev is not None and ev["type"] == "diff", ev
    _assert_byte_identical(node, q, ev)
    sub.cancel()


def test_wildcard_plan_wakes_on_every_commit(node):
    # explicit uids => plan_attrs None => wake on every window
    q = "{ q(func: uid(0x1)) { uid name age } }"
    sub = node.subscribe(q)
    sub.next(5)
    assert node.live.stats()["wildcard"] == 1
    node.mutate(set_nquads='<0x1> <age> "32" .', commit_now=True)
    ev = sub.next(5)
    assert ev is not None and ev["type"] == "diff"
    _assert_byte_identical(node, q, ev)
    sub.cancel()


def test_false_positive_wake_advances_cursor_silently(node):
    # touches `name` (the subscribed attr) on a uid the query result
    # doesn't change for: must wake + re-eval but deliver NOTHING
    q = '{ q(func: eq(name, "alice")) { uid name } }'
    sub = node.subscribe(q)
    w0 = sub.next(5)["at"]
    node.mutate(set_nquads='<0x2> <name> "bobby" .', commit_now=True)
    assert sub.next(0.8) is None
    assert sub.cursor > w0     # cursor advanced without a notification
    sub.cancel()


# -- coalescing --------------------------------------------------------------

def test_identical_subscriptions_coalesce_to_one_eval(node):
    subs = [node.subscribe(Q_NAME) for _ in range(8)]
    for s in subs:
        s.next(5)
    evals0 = node.metrics.counter("dgraph_subs_evals_total").value
    wakes0 = node.metrics.counter("dgraph_subs_wakeups_total").value
    node.mutate(set_nquads='<0xa> <name> "zed" .', commit_now=True)
    evs = [s.next(5) for s in subs]
    assert all(e["type"] == "diff" for e in evs)
    # all 8 notifications came from the same watermark + payload
    assert len({e["at"] for e in evs}) == 1
    assert len({canon(e["result"]) for e in evs}) == 1
    d_evals = node.metrics.counter("dgraph_subs_evals_total").value - evals0
    d_wakes = node.metrics.counter("dgraph_subs_wakeups_total").value - wakes0
    assert d_wakes == 8 and d_evals == 1, (d_wakes, d_evals)
    for s in subs:
        s.cancel()


def test_commit_burst_coalesces_into_windows(node):
    sub = node.subscribe(Q_NAME)
    sub.next(5)
    # burst of commits while the notifier evaluates: deliveries may
    # coalesce into fewer windows, but the LAST delivery must reflect
    # everything, byte-identically at its watermark
    for i in range(6):
        node.mutate(set_nquads=f'<0x{i + 16:x}> <name> "b{i}" .',
                    commit_now=True)
    final = None
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        ev = sub.next(0.6)
        if ev is not None:
            final = ev
        n = len(final["result"]["q"]) if final else 0
        if n == 2 + 6:
            break
    assert final is not None
    assert len(final["result"]["q"]) == 8
    _assert_byte_identical(node, Q_NAME, final)
    sub.cancel()


# -- reconnect cursors -------------------------------------------------------

def test_cursor_ack_when_journal_proves_unchanged(node):
    sub = node.subscribe(Q_NAME)
    w = sub.next(5)["at"]
    sub.cancel()
    sub2 = node.subscribe(Q_NAME, cursor=w)
    ev = sub2.next(5)
    assert ev["type"] == "ack" and "result" not in ev
    assert ev["at"] >= w
    sub2.cancel()


def test_stale_cursor_resyncs(node):
    sub = node.subscribe(Q_NAME)
    w = sub.next(5)["at"]
    sub.cancel()
    node.mutate(set_nquads='<0xb> <name> "newguy" .', commit_now=True)
    sub2 = node.subscribe(Q_NAME, cursor=w)
    ev = sub2.next(5)
    assert ev["type"] == "resync" and ev["reason"] == "cursor"
    _assert_byte_identical(node, Q_NAME, ev)
    sub2.cancel()


def test_wildcard_cursor_can_never_ack(node):
    q = "{ q(func: uid(0x1)) { uid name } }"
    sub = node.subscribe(q)
    w = sub.next(5)["at"]
    sub.cancel()
    # nothing changed, but a wildcard read set is unprovable => resync
    sub2 = node.subscribe(q, cursor=w)
    assert sub2.next(5)["type"] == "resync"
    sub2.cancel()


# -- flow control ------------------------------------------------------------

def test_slow_consumer_sheds_to_typed_resync(node):
    sub = node.subscribe(Q_NAME, queue_max=1)
    sub.next(5)
    for i in range(4):     # consumer never drains between windows
        node.mutate(set_nquads=f'<0x{i + 32:x}> <name> "s{i}" .',
                    commit_now=True)
        time.sleep(0.05)
    deadline = time.monotonic() + 10
    ev = None
    while time.monotonic() < deadline:
        nxt = sub.next(0.5)
        if nxt is None and ev is not None and \
                len(ev["result"]["q"]) == 2 + 4:
            break
        if nxt is not None:
            ev = nxt
    # the queue was replaced, never grown: a resync was delivered at some
    # point and the final state converged byte-identically
    assert node.metrics.counter("dgraph_subs_sheds_total").value >= 1
    assert ev is not None and len(ev["result"]["q"]) == 6
    _assert_byte_identical(node, Q_NAME, ev)
    assert len(sub.queue) <= 1
    sub.cancel()


def test_blocked_subscription_expires():
    n = Node(live_idle_timeout_s=0.2)
    try:
        n.alter(SCHEMA)
        n.mutate(set_nquads='<0x1> <name> "alice" .', commit_now=True)
        sub = n.subscribe(Q_NAME, queue_max=1)
        # never consume: init sits in the queue, the next delivery sheds
        # (marking the queue blocked), and the expiry sweep reaps it
        n.mutate(set_nquads='<0x2> <name> "bob" .', commit_now=True)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not sub.closed:
            time.sleep(0.1)
        assert sub.closed
        assert n.metrics.counter("dgraph_subs_expired_total").value == 1
        # the final queued event is the typed expire marker
        evs = []
        try:
            while True:
                evs.append(sub.next(0.1))
        except StopIteration:
            pass
        assert evs and evs[-1]["type"] == "expire"
        assert n.live.stats()["active"] == 0
    finally:
        n.close()


# -- journal retention -------------------------------------------------------

def test_journal_pinned_by_oldest_cursor(node):
    assert node.store.delta_log_stats()["pinned_floor"] is None
    sub = node.subscribe(Q_NAME)
    w = sub.next(5)["at"]
    st = node.store.delta_log_stats()
    assert st["pinned_floor"] is not None and st["pinned_floor"] <= w
    # prune above the pin is clamped: entries stay provable
    node.mutate(set_nquads='<0xc> <name> "pinned" .', commit_now=True)
    ev = sub.next(5)
    node.store.prune_delta("name", ev["at"] + 100)
    assert node.store.delta_since("name", sub.cursor) is not None
    sub.cancel()
    assert node.store.delta_log_stats()["pinned_floor"] is None


def test_journal_knob_and_overflow_resync():
    n = Node(delta_journal_max_keys=4)
    try:
        n.alter(SCHEMA)
        n.mutate(set_nquads='<0x1> <name> "alice" .', commit_now=True)
        assert n.store.delta_log_stats()["max_keys"] == 4
        sub = n.subscribe(Q_NAME)
        sub.next(5)
        # one commit touching >4 distinct keys of `name` overflows the
        # journal => the subscription must receive a typed resync, not a
        # silent gap
        quads = "\n".join(f'<0x{i + 64:x}> <name> "o{i}" .'
                          for i in range(8))
        n.mutate(set_nquads=quads, commit_now=True)
        ev = sub.next(10)
        assert ev is not None and ev["type"] == "resync", ev
        assert ev["reason"] in ("overflow", "shed")
        assert ev["reason"] == "overflow"
        assert len(ev["result"]["q"]) == 1 + 8
        rerun = n.query(Q_NAME, start_ts=ev["at"], read_only=True)[0]
        assert canon(ev["result"]) == canon(rerun)
        assert n.store.delta_log_stats()["overflows"] >= 1
        assert n.metrics.counter(
            "dgraph_delta_journal_overflows").value >= 1
        sub.cancel()
    finally:
        n.close()


# -- cost attribution --------------------------------------------------------

def test_live_evals_rank_under_live_endpoint(node):
    sub = node.subscribe(Q_NAME)
    sub.next(5)
    node.mutate(set_nquads='<0xd> <name> "costed" .', commit_now=True)
    sub.next(5)
    top = node.cost_book.top(window_s=300, group="shape", endpoint="live")
    assert top["endpoint"] == "live"
    assert any(Q_NAME.startswith(r["key"][:20]) for r in top["top"]), top
    # the foreground view excludes standing load
    fg = node.cost_book.top(window_s=300, group="endpoint")
    assert "live" in {r["key"] for r in fg["top"]}
    sub.cancel()


# -- serving-metrics sections ------------------------------------------------

def test_debug_metrics_journal_and_subscriptions_sections(node):
    from dgraph_tpu.api.http import _serving_metrics

    sub = node.subscribe(Q_NAME)
    sub.next(5)
    sm = _serving_metrics(node)
    j = sm["journal"]
    assert {"attrs", "keys", "max_keys", "overflows",
            "pinned_floor"} <= set(j)
    s = sm["subscriptions"]
    assert s["active"] == 1 and s["registered"] == 1
    assert {"notifications", "wakeups", "evals", "sheds", "resyncs",
            "expired", "reaped", "heartbeats",
            "notify_latency_s"} <= set(s)
    sub.cancel()


# -- wire mode (multi-group cluster) ----------------------------------------

def test_cluster_subscribe_federated_and_byte_identical():
    cl = Cluster(n_groups=2)
    try:
        cl.alter(SCHEMA)
        cl.mutate(set_nquads='<0x1> <name> "alice" .')
        sub = cl.subscribe(Q_NAME)
        ev = sub.next(5)
        assert ev["type"] == "init"
        cl.mutate(set_nquads='<0x2> <name> "bob" .\n<0x2> <age> "9" .')
        ev = sub.next(5)
        assert ev["type"] == "diff"
        assert ev["diff"]["q"]["added"] == [{"uid": "0x2", "name": "bob"}]
        rerun = cl.query(Q_NAME, read_ts=ev["at"])
        assert canon(ev["result"]) == canon(rerun)
        # unrelated predicate on the other group: no wake
        cl.mutate(set_nquads='<0x1> <age> "40" .')
        assert sub.next(0.8) is None
        sub.cancel()
    finally:
        cl.close()


# -- HTTP SSE surface --------------------------------------------------------

@pytest.fixture
def http_node(node):
    from dgraph_tpu.api.http import make_server

    srv = make_server(node, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield node, srv.server_address[1]
    srv.shutdown()


def _read_frame(fp):
    """One SSE frame (blank-line terminated) as its list of lines."""
    lines = []
    while True:
        ln = fp.readline().decode("utf-8").rstrip("\n")
        if ln == "":
            if lines:
                return lines
            continue
        lines.append(ln)


def _sse_connect(port, body):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/subscribe", json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return conn, resp


def test_http_subscribe_sse_stream(http_node):
    node, port = http_node
    conn, resp = _sse_connect(port, {"query": Q_NAME})
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "text/event-stream"
    fr = _read_frame(resp.fp)
    assert fr[0] == "event: init"
    ev = json.loads(fr[1][len("data: "):])
    assert {e["name"] for e in ev["result"]["q"]} == {"alice", "bob"}
    node.mutate(set_nquads='<0x21> <name> "pushed" .', commit_now=True)
    while True:
        fr = _read_frame(resp.fp)
        if not fr[0].startswith(":"):
            break
    assert fr[0] == "event: diff"
    ev = json.loads(fr[1][len("data: "):])
    assert ev["diff"]["q"]["added"] == [{"uid": "0x21", "name": "pushed"}]
    # the wire payload is the canonical encoding — byte-identity holds on
    # exactly what the client received
    rerun = node.query(Q_NAME, start_ts=ev["at"], read_only=True)[0]
    assert canon(ev["result"]) == canon(rerun)
    conn.close()


def test_http_subscribe_heartbeats_and_reap(http_node):
    node, port = http_node
    conn, resp = _sse_connect(port, {"query": Q_NAME, "heartbeat_s": 0.2})
    _read_frame(resp.fp)                     # init
    fr = _read_frame(resp.fp)
    assert fr[0].startswith(": hb"), fr      # comment frame, not an event
    deadline = time.monotonic() + 5          # counter incs after the write
    while time.monotonic() < deadline and not \
            node.metrics.counter("dgraph_subs_heartbeats_total").value:
        time.sleep(0.05)
    assert node.metrics.counter("dgraph_subs_heartbeats_total").value >= 1
    # vanish without cancel (close the response's fd too — it holds a
    # dup of the socket): the next failed write must REAP the
    # subscription so it cannot pin the journal floor forever
    resp.close()
    conn.close()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and node.live.stats()["active"]:
        time.sleep(0.1)
    assert node.live.stats()["active"] == 0
    assert node.metrics.counter("dgraph_subs_reaped_total").value == 1
    assert node.store.delta_log_stats()["pinned_floor"] is None


def test_http_subscribe_cursor_roundtrip(http_node):
    node, port = http_node
    conn, resp = _sse_connect(port, {"query": Q_NAME})
    ev = json.loads(_read_frame(resp.fp)[1][len("data: "):])
    conn.close()
    # reconnect at the delivered watermark: ack, no result payload
    conn2, resp2 = _sse_connect(port, {"query": Q_NAME, "cursor": ev["at"]})
    fr = _read_frame(resp2.fp)
    assert fr[0] == "event: ack"
    assert "result" not in json.loads(fr[1][len("data: "):])
    conn2.close()
    # reconnect at a pre-change cursor: typed resync with the full result
    node.mutate(set_nquads='<0x22> <name> "moved" .', commit_now=True)
    conn3, resp3 = _sse_connect(port, {"query": Q_NAME, "cursor": ev["at"]})
    fr = _read_frame(resp3.fp)
    assert fr[0] == "event: resync"
    ev3 = json.loads(fr[1][len("data: "):])
    assert ev3["reason"] == "cursor" and "result" in ev3
    conn3.close()


def test_http_subscribe_invalid_is_enveloped_error(http_node):
    _node, port = http_node
    conn, resp = _sse_connect(port, {"query": "{ q(func: nosuchfn()) }"})
    assert resp.status == 400
    err = json.loads(resp.read())
    assert err["errors"], err
    conn.close()


# -- concurrency hammer ------------------------------------------------------

def test_many_subscribers_concurrent_writes_all_converge(node):
    n_subs = 16
    subs = [node.subscribe(Q_NAME) for _ in range(n_subs)]
    finals = [s.next(5) for s in subs]

    stop = threading.Event()
    errs = []

    def drain(i, s):
        try:
            while not stop.is_set():
                try:
                    ev = s.next(0.2)
                except StopIteration:
                    return
                if ev is not None:
                    finals[i] = ev
        except Exception as e:  # surfaced by the main thread's assert
            errs.append(e)

    threads = [threading.Thread(target=drain, args=(i, s), daemon=True)
               for i, s in enumerate(subs)]
    for t in threads:
        t.start()
    for i in range(10):
        node.mutate(set_nquads=f'<0x{i + 128:x}> <name> "w{i}" .',
                    commit_now=True)
    # wait until every subscriber reflects the final state
    want = 2 + 10
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if all(len(f["result"]["q"]) == want for f in finals):
            break
        time.sleep(0.1)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errs, errs
    for f in finals:
        assert len(f["result"]["q"]) == want
        _assert_byte_identical(node, Q_NAME, f)
    for s in subs:
        s.cancel()
