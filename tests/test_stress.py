"""Multithreaded invariant stress (the `go test -race` analog, SURVEY §5).

Default iteration counts keep CI fast; DGRAPH_TPU_STRESS=1 scales them up
for soak runs. Each test hammers a concurrency seam and checks a global
invariant at the end (money conserved, all tasks ran, no leaked txns)."""

import os
import threading

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.parallel.scheduler import Scheduler
from dgraph_tpu.utils.sync import SafeLock

SCALE = 10 if os.environ.get("DGRAPH_TPU_STRESS") == "1" else 1


def test_safelock_assertions():
    lk = SafeLock()
    with pytest.raises(AssertionError):
        lk.assert_held()
    with lk:
        lk.assert_held()
        with lk:                      # reentrant
            lk.assert_held()
        lk.assert_held()
    with pytest.raises(AssertionError):
        lk.assert_held()


def test_scheduler_random_keyset_hammer():
    s = Scheduler()
    ran = []
    lock = threading.Lock()

    def task(i):
        def fn():
            with lock:
                ran.append(i)
        rng = np.random.default_rng(1000 + i)   # Generator isn't thread-safe
        keys = rng.integers(0, 12, size=rng.integers(1, 5)).tolist()
        s.run(keys, fn, exclusive=bool(rng.random() < 0.05))

    n = 120 * SCALE
    ts = [threading.Thread(target=task, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert sorted(ran) == list(range(n))
    assert not s._queues and not s._outstanding and not s._excl


def test_bank_invariant_under_contention():
    """Concurrent read-modify-write transfers on few accounts: heavy SSI
    conflicts, yet money is conserved and no txn leaks."""
    node = Node()
    node.alter(schema_text="bal: int .")
    N, START = 4, 100
    node.mutate(set_nquads="\n".join(
        f'<0x{i:x}> <bal> "{START}"^^<xs:int> .' for i in range(1, N + 1)),
        commit_now=True)
    rng_master = np.random.default_rng(7)
    seeds = rng_master.integers(0, 1 << 31, size=8)

    def worker(seed):
        rng = np.random.default_rng(seed)
        for _ in range(15 * SCALE):
            a, b = rng.choice(np.arange(1, N + 1), 2, replace=False)
            ctx = node.new_txn()
            try:
                out, _ = node.query('{ q(func: has(bal)) { uid bal } }',
                                    start_ts=ctx.start_ts)
                bals = {int(r["uid"], 16): r["bal"] for r in out["q"]}
                amt = int(rng.integers(1, 10))
                node.mutate(
                    set_nquads=(
                        f'<0x{a:x}> <bal> "{bals[int(a)] - amt}"^^<xs:int> .\n'
                        f'<0x{b:x}> <bal> "{bals[int(b)] + amt}"^^<xs:int> .'),
                    start_ts=ctx.start_ts)
                node.commit(ctx.start_ts)
            except Exception:        # TxnConflict and friends: abort + retry
                try:
                    node.abort(ctx.start_ts)
                except Exception:
                    pass

    ts = [threading.Thread(target=worker, args=(s,)) for s in seeds]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    out, _ = node.query('{ q(func: has(bal)) { bal } }')
    assert sum(r["bal"] for r in out["q"]) == N * START
    assert not node._txns                       # nothing leaked
    assert node.zero.oracle.pending_count() == 0
