"""gRPC api.Dgraph round-trip tests (reference: edgraph/server.go public API
through a real grpc channel — server and client in one process over
localhost)."""

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.api.grpc_client import DgraphClient, TxnAborted
from dgraph_tpu.api.grpc_server import serve_grpc
from dgraph_tpu.api.server import Node


@pytest.fixture(scope="module")
def client():
    node = Node()
    server, port = serve_grpc(node, "localhost:0")
    c = DgraphClient(f"localhost:{port}")
    yield c
    c.close()
    server.stop(0)


def test_check_version(client):
    assert client.check_version() == "dgraph-tpu"


def test_alter_mutate_query(client):
    client.alter(schema="name: string @index(exact) .\nage: int @index(int) .")
    txn = client.txn()
    uids = txn.mutate(set_nquads='_:a <name> "alice" .\n_:a <age> "30" .',
                      commit_now=True)
    assert "a" in uids
    out = client.txn(read_only=True).query(
        '{ q(func: eq(name, "alice")) { name age } }')
    assert out == {"q": [{"name": "alice", "age": 30}]}


def test_txn_commit_visibility(client):
    txn = client.txn()
    txn.mutate(set_nquads='_:b <name> "bob" .')
    # not yet visible to other readers
    out = client.txn(read_only=True).query('{ q(func: eq(name, "bob")) { name } }')
    assert out == {}
    # visible to the txn itself
    own = txn.query('{ q(func: eq(name, "bob")) { name } }')
    assert own == {"q": [{"name": "bob"}]}
    txn.commit()
    out = client.txn(read_only=True).query('{ q(func: eq(name, "bob")) { name } }')
    assert out == {"q": [{"name": "bob"}]}


def test_txn_discard(client):
    txn = client.txn()
    txn.mutate(set_nquads='_:c <name> "carol" .')
    txn.discard()
    out = client.txn(read_only=True).query('{ q(func: eq(name, "carol")) { name } }')
    assert out == {}


def test_conflict_aborts(client):
    t1 = client.txn()
    t2 = client.txn()
    t1.mutate(set_nquads='<0x777> <name> "one" .')
    t2.mutate(set_nquads='<0x777> <name> "two" .')
    t1.commit()
    with pytest.raises(TxnAborted):
        t2.commit()


def test_bad_query_is_invalid_argument(client):
    with pytest.raises(grpc.RpcError) as ei:
        client.txn(read_only=True).query("{ not valid dql !!!")
    assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT


def test_json_mutation(client):
    txn = client.txn()
    uids = txn.mutate(set_json={"name": "dave", "age": 41}, commit_now=True)
    assert uids
    out = client.txn(read_only=True).query(
        '{ q(func: eq(name, "dave")) { name age } }')
    assert out == {"q": [{"name": "dave", "age": 41}]}


def test_drop_attr(client):
    client.alter(schema="tmp: string @index(exact) .")
    client.txn().mutate(set_nquads='_:t <tmp> "gone" .', commit_now=True)
    client.alter(drop_attr="tmp")
    out = client.txn(read_only=True).query('{ q(func: has(tmp)) { tmp } }')
    assert out == {}


def test_query_then_mutate_same_txn(client):
    # lazy txn open: first op is a query, mutate must join the same txn
    txn = client.txn()
    out = txn.query('{ q(func: eq(name, "nobody-here")) { name } }')
    assert out == {}
    txn.mutate(set_nquads='_:e <name> "erin" .')
    txn.commit()
    out = client.txn(read_only=True).query('{ q(func: eq(name, "erin")) { name } }')
    assert out == {"q": [{"name": "erin"}]}


def test_grpc_upsert_insert_then_update(client):
    client.alter(schema="email: string @index(exact) @upsert .")
    q = '{ v as var(func: eq(email, "up@x.io")) }'
    # insert when absent
    _, uids = client.txn().upsert(
        q, set_nquads='_:u <email> "up@x.io" .\n_:u <name> "first" .')
    assert "u" in uids
    # second run: cond-free update via uid(v)
    txn = client.txn()
    out, uids2 = txn.upsert(q, set_nquads='uid(v) <name> "second" .')
    assert uids2 == {}
    res = client.txn(read_only=True).query(
        '{ q(func: eq(email, "up@x.io")) { name } }')
    assert res == {"q": [{"name": "second"}]}


def test_grpc_conditional_upsert_cond_blocks(client):
    q = '{ v as var(func: eq(email, "up@x.io")) }'
    from dgraph_tpu.protos import api_pb2 as pb
    req = pb.Request(query=q, commit_now=True, mutations=[
        pb.Mutation(set_nquads=b'_:dup <email> "up@x.io" .',
                    cond="@if(eq(len(v), 0))")])
    resp = client._query(req)
    assert dict(resp.uids) == {}   # cond failed, no insert
    res = client.txn(read_only=True).query(
        '{ q(func: eq(email, "up@x.io")) { uid } }')
    assert len(res["q"]) == 1      # still exactly one


def test_multi_mutation_uids_all_returned(client):
    from dgraph_tpu.protos import api_pb2 as pb
    req = pb.Request(commit_now=True, mutations=[
        pb.Mutation(set_nquads=b'_:m1 <name> "m-one" .'),
        pb.Mutation(set_nquads=b'_:m2 <name> "m-two" .')])
    resp = client._query(req)
    assert set(resp.uids) == {"m1", "m2"}
