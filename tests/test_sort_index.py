"""sortWithIndex: index-bucket-ordered sort racing the value sort.

Round-2 verdict item 7 (reference worker/sort.go:144-259 sortWithIndex +
:480 intersectBucket): order-by on an indexed sortable predicate walks
token buckets in key order, intersecting each bucket with the candidates,
stopping once offset+first is satisfied; results must equal the value sort.
"""

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import dql
from dgraph_tpu.query.engine import Executor


@pytest.fixture(scope="module")
def node():
    n = Node()
    n.alter(schema_text="""
        name: string @index(exact) .
        age: int @index(int) .
        height: float @index(float) .
        nick: string .
    """)
    rng = np.random.default_rng(5)
    quads = []
    for i in range(1, 101):
        quads.append(f'<0x{i:x}> <name> "name{rng.integers(0, 30):03d}" .')
        if i % 5:  # some uids have no age -> missing tail
            quads.append(f'<0x{i:x}> <age> "{int(rng.integers(0, 40))}"^^<xs:int> .')
        quads.append(f'<0x{i:x}> <height> "{int(rng.integers(100, 220))}.5"^^<xs:float> .')
        quads.append(f'<0x{i:x}> <nick> "nick{i}" .')
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    return n


def _run(node, q):
    ex = Executor(node.snapshot(), node.store.schema)
    out = ex.execute(dql.parse(q))
    return out, ex


@pytest.mark.parametrize("desc", [False, True])
def test_index_sort_matches_value_sort(node, desc):
    d = "orderdesc" if desc else "orderasc"
    q = f'{{ q(func: has(nick), {d}: age, first: 100) {{ uid age }} }}'
    out, ex = _run(node, q)
    assert ex.sort_index_buckets > 0, "index path must be taken"
    # equality vs the value-sort fallback, forced by disabling the index path
    ex2 = Executor(node.snapshot(), node.store.schema)
    ex2._sort_with_index = lambda *a, **k: None
    out2 = ex2.execute(dql.parse(q))
    assert ex2.sort_index_buckets == -1
    assert out == out2


def test_index_sort_early_stop_touches_few_buckets(node):
    q = '{ q(func: has(age), orderasc: age, first: 5) { age } }'
    out, ex = _run(node, q)
    assert len(out["q"]) == 5
    ages = [r["age"] for r in out["q"]]
    assert ages == sorted(ages)
    # ~40 distinct ages exist; first:5 must not walk them all
    assert 0 < ex.sort_index_buckets <= 6, ex.sort_index_buckets
    # pagination correctness vs the full sort
    full, _ = _run(node, '{ q(func: has(age), orderasc: age) { age } }')
    assert out["q"] == full["q"][:5]


def test_index_sort_offset_window(node):
    out, ex = _run(node,
                   '{ q(func: has(age), orderasc: age, offset: 7, first: 4) { uid age } }')
    full, _ = _run(node, '{ q(func: has(age), orderasc: age) { uid age } }')
    assert out["q"] == full["q"][7:11]
    assert ex.sort_index_buckets > 0


def test_missing_values_sink_to_end(node):
    out, ex = _run(node, '{ q(func: has(nick), orderasc: age, first: 100) { uid age } }')
    assert ex.sort_index_buckets > 0
    rows = out["q"]
    seen_missing = False
    for r in rows:
        if "age" not in r:
            seen_missing = True
        else:
            assert not seen_missing, "valued uid after missing tail began"
    assert seen_missing  # i%5==0 uids have no age


def test_lossy_float_index_sort_matches(node):
    q = '{ q(func: has(height), orderasc: height, first: 20) { height } }'
    out, ex = _run(node, q)
    hs = [r["height"] for r in out["q"]]
    assert hs == sorted(hs) and len(hs) == 20
    assert ex.sort_index_buckets > 0


def test_string_exact_index_sort(node):
    q = '{ q(func: has(name), orderdesc: name, first: 10) { name } }'
    out, ex = _run(node, q)
    names = [r["name"] for r in out["q"]]
    assert names == sorted(names, reverse=True)
    assert ex.sort_index_buckets > 0


def test_multi_key_and_val_sort_fall_back(node):
    out, ex = _run(node,
                   '{ q(func: has(age), orderasc: age, orderdesc: name) { uid } }')
    assert ex.sort_index_buckets == -1
    out, ex = _run(node,
                   '{ var(func: has(age)) { a as age }\n'
                   '  q(func: uid(a), orderasc: val(a)) { uid } }')
    assert ex.sort_index_buckets == -1


def test_unbounded_sort_uses_value_path(node):
    """No first: the index walk loses to one value-sort pass; must fall
    back (the reference races both, worker/sort.go:379)."""
    out, ex = _run(node, '{ q(func: has(age), orderasc: age) { age } }')
    assert ex.sort_index_buckets == -1
    ages = [r["age"] for r in out["q"]]
    assert ages == sorted(ages)
