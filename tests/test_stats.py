"""Cardinality-statistics subsystem (storage/stats.py): exactness at fold
time, O(Δ) maintenance through the delta-overlay stamp, and exact
reconciliation after compaction."""

import numpy as np
import pytest

from dgraph_tpu.storage import stats as stmod
from dgraph_tpu.storage.csr_build import build_pred
from dgraph_tpu.storage.delta import OverlayCSR

N_PEOPLE = 800
FOLLOWS = 4


@pytest.fixture()
def node():
    from dgraph_tpu.models.film import film_node

    n = film_node(n_people=N_PEOPLE, follows=FOLLOWS)
    yield n
    n.close()


def _fresh_stats(node, attr):
    """Stats of a from-scratch fold at the current watermark — the
    reconciliation oracle."""
    pd = build_pred(node.store, attr, node.store.max_seen_commit_ts)
    return stmod.pred_stats(pd)


def _same(a: stmod.PredStats, b: stmod.PredStats) -> None:
    assert a.fwd.n_subjects == b.fwd.n_subjects
    assert a.fwd.n_edges == b.fwd.n_edges
    assert np.array_equal(a.fwd.hist, b.fwd.hist)
    assert a.value_count == b.value_count
    assert a.numeric_values == b.numeric_values
    assert a.index_terms == b.index_terms
    assert a.index_postings == b.index_postings


def test_fold_time_stats_exact(node):
    snap = node.snapshot()
    st = stmod.pred_stats(snap.pred("follows"))
    sub, ip, _ = snap.pred("follows").csr.host_arrays()
    deg = np.asarray(ip)[1:] - np.asarray(ip)[:-1]
    assert st.fwd.n_subjects == len(sub)
    assert st.fwd.n_edges == int(deg.sum())
    assert int(st.fwd.hist.sum()) == len(sub)
    assert not st.fwd.via_delta
    ages = stmod.pred_stats(snap.pred("age"))
    assert ages.value_count == N_PEOPLE
    assert ages.numeric_values == N_PEOPLE     # int values: all numeric
    names = stmod.pred_stats(snap.pred("name"))
    assert names.value_count == N_PEOPLE
    assert names.index_terms["exact"] == N_PEOPLE
    assert names.index_postings["exact"] == N_PEOPLE


def test_overlay_commit_updates_stats_o_delta(node):
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')  # warm fold
    snap0 = node.snapshot()
    stmod.pred_stats(snap0.pred("follows"), node.metrics)    # cache base
    builds0 = node.metrics.counter("dgraph_stats_builds_total").value
    # single-quad commit -> overlay stamp, NOT a re-fold
    node.mutate(set_nquads=f'<0x1> <follows> <0x{N_PEOPLE + 7:x}> .',
                commit_now=True)
    snap1 = node.snapshot()
    pd = snap1.pred("follows")
    assert isinstance(pd.csr, OverlayCSR)      # the stamp actually ran
    d0 = node.metrics.counter("dgraph_stats_delta_updates_total").value
    st = stmod.pred_stats(pd, node.metrics)
    assert st.fwd.via_delta                    # adjusted, not recounted
    assert node.metrics.counter(
        "dgraph_stats_delta_updates_total").value == d0 + 1
    # the delta path must not have re-counted any tablet
    assert node.metrics.counter(
        "dgraph_stats_builds_total").value == builds0
    _same(st, _fresh_stats(node, "follows"))   # ...and must be EXACT


def test_overlay_delete_and_readd_stats_exact(node):
    node.query('{ q(func: uid(0x2)) { follows { uid } } }')
    snap0 = node.snapshot()
    stmod.pred_stats(snap0.pred("follows"))
    # delete every follows edge of 0x2 (row leaves the CSR), touch another
    node.mutate(del_nquads='<0x2> <follows> * .', commit_now=True)
    node.mutate(set_nquads=f'<0x3> <follows> <0x{N_PEOPLE + 9:x}> .',
                commit_now=True)
    snap1 = node.snapshot()
    pd = snap1.pred("follows")
    assert isinstance(pd.csr, OverlayCSR)
    _same(stmod.pred_stats(pd), _fresh_stats(node, "follows"))


def test_compaction_reconciles_exactly(node):
    node.query('{ q(func: uid(0x1)) { follows { uid } } }')
    stmod.pred_stats(node.snapshot().pred("follows"))
    for i in range(5):
        node.mutate(
            set_nquads=f'<0x{i + 1:x}> <follows> <0x{N_PEOPLE + 20 + i:x}> .',
            commit_now=True)
    overlaid = stmod.pred_stats(node.snapshot().pred("follows"))
    assert overlaid.fwd.via_delta
    assert node._assembler.compact(node._lock, force=True) >= 1
    pd = node.snapshot().pred("follows")
    assert not isinstance(pd.csr, OverlayCSR)  # folded base again
    st = stmod.pred_stats(pd)
    assert not st.fwd.via_delta
    _same(st, overlaid)                        # delta math was exact
    _same(st, _fresh_stats(node, "follows"))


def test_index_patch_keeps_term_probes_exact(node):
    node.query('{ q(func: eq(name, "p1")) { uid } }')
    stmod.pred_stats(node.snapshot().pred("name"))
    node.mutate(set_nquads=f'<0x{N_PEOPLE + 40:x}> <name> "p1" .',
                commit_now=True)
    pd = node.snapshot().pred("name")
    ti = pd.indexes["exact"]
    # planner point probe: exact row length after the index patch
    from dgraph_tpu.utils import tok as tokmod
    from dgraph_tpu.utils.types import TypeID, Val

    t = tokmod.get("exact").tokens(Val(TypeID.STRING, "p1"))[0][1:]
    assert stmod.term_freq(ti, t) == 2
    st = stmod.pred_stats(pd)
    assert st.index_postings["exact"] == N_PEOPLE + 1


def test_range_count_matches_walked_rows(node):
    snap = node.snapshot()
    ti = snap.pred("age").indexes["int"]
    from dgraph_tpu.query.task import _ineq_rows
    from dgraph_tpu.utils import tok as tokmod
    from dgraph_tpu.utils.types import TypeID, Val, convert

    indptr = np.asarray(ti.host_arrays()[0], dtype=np.int64)
    for op, val in (("ge", 50), ("lt", 30), ("le", 18), ("gt", 76),
                    ("eq", 40)):
        tok = tokmod.get("int").tokens(
            convert(Val(TypeID.INT, val), TypeID.INT))[0][1:]
        rows = _ineq_rows(ti, op, tok)
        walked = int(sum(indptr[r + 1] - indptr[r] for r in rows))
        assert stmod.range_count(ti, op, tok) == walked, (op, val)


def test_topk_terms_sketch(node):
    snap = node.snapshot()
    top = stmod.topk_terms(snap.pred("genre").indexes["exact"], 4)
    assert len(top) == 4
    assert sorted(t for t, _ in top) == ["comedy", "drama", "noir", "scifi"]
    assert all(n == N_PEOPLE // 4 for _, n in top)
    # snapshot_stats carries the sketch for the ops readout
    allstats = stmod.snapshot_stats(snap, top_k=2)
    assert "top_terms" in allstats["genre"]


def test_stats_never_describe_dead_data(node):
    """A structural change (drop) rebuilds PredData; stats cached on the
    old object are unreachable from the new snapshot."""
    snap0 = node.snapshot()
    st0 = stmod.pred_stats(snap0.pred("follows"))
    node.alter(drop_attr="follows")
    snap1 = node.snapshot()
    assert snap1.pred("follows") is None or \
        stmod.pred_stats(snap1.pred("follows")) is not st0


def test_stats_on_baseless_overlay(node):
    """A tablet born entirely from deltas (edgeless base) stamps an
    OverlayCSR with base=None — its stats come purely from the delta."""
    node.query('{ q(func: uid(0x1)) { name } }')         # warm fold caches
    node.alter(schema_text="knows: [uid] .")
    node.query('{ q(func: uid(0x1)) { knows { uid } } }')
    node.mutate(set_nquads='<0x1> <knows> <0x2> .\n<0x1> <knows> <0x3> .',
                commit_now=True)
    pd = node.snapshot().pred("knows")
    st = stmod.pred_stats(pd)
    if isinstance(pd.csr, OverlayCSR):       # stamped, not re-folded
        assert pd.csr.base is None
        assert st.fwd.via_delta
    assert st.fwd.n_subjects == 1 and st.fwd.n_edges == 2
    _same(st, _fresh_stats(node, "knows"))
