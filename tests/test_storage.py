"""Storage stack: keys, MVCC posting lists, WAL/snapshot durability, indexes.

Mirrors the reference's posting/*_test.go (mutation layering, commit/abort,
value reads) and x/keys_test.go.
"""

import numpy as np
import pytest

from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage import index as idx
from dgraph_tpu.storage.csr_build import build_snapshot
from dgraph_tpu.storage.postings import DirectedEdge, Op, Posting, PostingList
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val


def test_key_roundtrip():
    for key in [
        K.data_key("friend", 123),
        K.reverse_key("friend", 9),
        K.index_key("name", b"\x01alice"),
        K.count_key("friend", 42),
        K.count_key("friend", 42, reverse=True),
        K.schema_key("name"),
    ]:
        assert K.parse_key(key.encode()) == key


def test_data_keys_sort_by_uid():
    ks = [K.data_key("p", u).encode() for u in (1, 255, 256, 70000, 2**40)]
    assert ks == sorted(ks)


def test_posting_list_mvcc():
    pl = PostingList()
    pl.add_mutation(start_ts=5, p=Posting(10))
    pl.add_mutation(start_ts=5, p=Posting(20))
    # invisible before commit (other readers)
    assert pl.length(read_ts=100) == 0
    # visible to own txn
    np.testing.assert_array_equal(pl.uids(100, own_start_ts=5), [10, 20])
    assert pl.commit(start_ts=5, commit_ts=7)
    np.testing.assert_array_equal(pl.uids(7), [10, 20])
    assert pl.length(read_ts=6) == 0  # snapshot below commit_ts

    # delete one uid in a later txn
    pl.add_mutation(start_ts=8, p=Posting(10, Op.DEL))
    pl.commit(8, 9)
    np.testing.assert_array_equal(pl.uids(9), [20])
    np.testing.assert_array_equal(pl.uids(7), [10, 20])  # old snapshot intact

    # wildcard delete
    pl.add_mutation(start_ts=10, p=Posting(0, Op.DEL_ALL))
    pl.commit(10, 11)
    assert pl.length(11) == 0
    np.testing.assert_array_equal(pl.uids(9), [20])

    # rollup folds layers; later snapshots unchanged
    pl.rollup(9)
    np.testing.assert_array_equal(pl.uids(9), [20])
    assert pl.length(11) == 0


def test_posting_list_values_and_lang():
    pl = PostingList()
    pl.add_mutation(1, Posting(0, value=Val(TypeID.STRING, "hello")))
    pl.commit(1, 2)
    assert pl.value(2).value == "hello"
    from dgraph_tpu.storage.postings import lang_uid

    pl.add_mutation(3, Posting(lang_uid("fr"), value=Val(TypeID.STRING, "bonjour"), lang="fr"))
    pl.commit(3, 4)
    assert pl.value(4, lang="fr").value == "bonjour"
    assert pl.value(4).value == "hello"
    # abort leaves state untouched
    pl.add_mutation(5, Posting(0, value=Val(TypeID.STRING, "bye")))
    pl.abort(5)
    assert pl.value(10).value == "hello"


def test_store_wal_replay(tmp_path):
    d = str(tmp_path / "st")
    s = Store(d)
    for e in parse_schema("friend: uid @reverse @count .\nname: string @index(exact) ."):
        s.set_schema(e)
    ts = 1
    for sub, obj in [(1, 2), (1, 3), (2, 3)]:
        idx.add_mutation_with_index(s, DirectedEdge(sub, "friend", object_uid=obj), ts)
    idx.add_mutation_with_index(
        s, DirectedEdge(1, "name", value=Val(TypeID.STRING, "alice")), ts)
    s.commit(ts, 2, list(s.lists.keys()))
    s.close()

    # reopen: WAL replay restores everything
    s2 = Store(d)
    pl = s2.get(K.data_key("friend", 1))
    np.testing.assert_array_equal(pl.uids(5), [2, 3])
    rev = s2.get(K.reverse_key("friend", 3))
    np.testing.assert_array_equal(rev.uids(5), [1, 2])
    assert s2.schema.get("friend").reverse
    assert s2.get(K.data_key("name", 1)).value(5).value == "alice"
    s2.close()


def test_store_checkpoint_and_tail(tmp_path):
    d = str(tmp_path / "st")
    s = Store(d)
    s.add_mutation(1, K.data_key("p", 1), Posting(100))
    s.commit(1, 2, [K.data_key("p", 1).encode()])
    s.checkpoint(upto_ts=2)
    # post-checkpoint commits land in the fresh WAL
    s.add_mutation(3, K.data_key("p", 1), Posting(200))
    s.commit(3, 4, [K.data_key("p", 1).encode()])
    # uncommitted txn survives checkpoint+reopen via WAL
    s.add_mutation(5, K.data_key("p", 1), Posting(300))
    s.close()

    s2 = Store(d)
    pl = s2.get(K.data_key("p", 1))
    np.testing.assert_array_equal(pl.uids(4), [100, 200])
    np.testing.assert_array_equal(pl.uids(2), [100])
    np.testing.assert_array_equal(pl.uids(10, own_start_ts=5), [100, 200, 300])
    s2.commit(5, 6, [K.data_key("p", 1).encode()])
    np.testing.assert_array_equal(s2.get(K.data_key("p", 1)).uids(6), [100, 200, 300])
    s2.close()


def test_count_index_maintenance():
    s = Store()
    for e in parse_schema("friend: uid @count ."):
        s.set_schema(e)
    idx.add_mutation_with_index(s, DirectedEdge(1, "friend", object_uid=2), 1)
    idx.add_mutation_with_index(s, DirectedEdge(1, "friend", object_uid=3), 1)
    s.commit(1, 2, list(s.lists.keys()))
    ck = s.get(K.count_key("friend", 2))
    np.testing.assert_array_equal(ck.uids(3), [1])
    # degree 1 bucket must be empty for subject 1
    assert 1 not in s.get(K.count_key("friend", 1)).uids(3).tolist()


def test_index_value_replacement():
    s = Store()
    for e in parse_schema("name: string @index(exact) ."):
        s.set_schema(e)
    idx.add_mutation_with_index(s, DirectedEdge(7, "name", value=Val(TypeID.STRING, "bob")), 1)
    s.commit(1, 2, list(s.lists.keys()))
    idx.add_mutation_with_index(s, DirectedEdge(7, "name", value=Val(TypeID.STRING, "carol")), 3)
    s.commit(3, 4, list(s.lists.keys()))
    from dgraph_tpu.utils import tok

    old_term = tok.get("exact").tokens(Val(TypeID.STRING, "bob"))[0]
    new_term = tok.get("exact").tokens(Val(TypeID.STRING, "carol"))[0]
    assert s.get(K.index_key("name", old_term)).length(5) == 0
    np.testing.assert_array_equal(s.get(K.index_key("name", new_term)).uids(5), [7])


def test_snapshot_build():
    s = Store()
    for e in parse_schema("friend: uid @reverse .\nage: int @index(int) .\nname: string ."):
        s.set_schema(e)
    for sub, obj in [(1, 2), (1, 3), (4, 1)]:
        idx.add_mutation_with_index(s, DirectedEdge(sub, "friend", object_uid=obj), 1)
    idx.add_mutation_with_index(s, DirectedEdge(1, "age", value=Val(TypeID.INT, 30)), 1)
    idx.add_mutation_with_index(s, DirectedEdge(2, "age", value=Val(TypeID.INT, 25)), 1)
    idx.add_mutation_with_index(s, DirectedEdge(1, "name", value=Val(TypeID.STRING, "x")), 1)
    s.commit(1, 2, list(s.lists.keys()))

    snap = build_snapshot(s, read_ts=3)
    f = snap.pred("friend")
    np.testing.assert_array_equal(np.asarray(f.csr.subjects), [1, 4])
    np.testing.assert_array_equal(np.asarray(f.csr.indptr), [0, 2, 3])
    np.testing.assert_array_equal(np.asarray(f.csr.indices), [2, 3, 1])
    np.testing.assert_array_equal(np.asarray(f.rev_csr.subjects), [1, 2, 3])
    age = snap.pred("age")
    np.testing.assert_array_equal(np.asarray(age.value_subjects), [1, 2])
    np.testing.assert_array_equal(np.asarray(age.num_values), [30.0, 25.0])
    assert age.host_values[1].value == 30
    ti = age.indexes["int"]
    assert len(ti.terms) == 2  # two distinct int tokens
    assert ti.term_row(ti.terms[0]) == 0
    # snapshot at ts before commit sees nothing
    empty = build_snapshot(s, read_ts=1)
    assert empty.pred("friend").csr is None


def test_schema_parse_and_validation():
    es = parse_schema("""
        # comment
        name: string @index(term, exact) @lang .
        friend: [uid] @reverse @count .
        age: int @index(int) .
        loc: geo @index(geo) .
    """)
    m = {e.predicate: e for e in es}
    assert m["name"].tokenizers == ["term", "exact"] and m["name"].lang
    assert m["friend"].is_list and m["friend"].reverse and m["friend"].count
    with pytest.raises(ValueError):
        parse_schema("name: string @index(int) .")  # tokenizer/type mismatch
    with pytest.raises(ValueError):
        parse_schema("x: string @reverse .")  # reverse needs uid
    with pytest.raises(ValueError):
        parse_schema("x: int @upsert .")  # upsert needs index


def test_lang_index_isolation():
    # regression: setting a lang-tagged value must not delete the untagged
    # value's index terms (found by review)
    s = Store()
    for e in parse_schema("name: string @index(exact) @lang ."):
        s.set_schema(e)
    idx.add_mutation_with_index(s, DirectedEdge(7, "name", value=Val(TypeID.STRING, "bob")), 1)
    s.commit(1, 2, list(s.lists.keys()))
    idx.add_mutation_with_index(
        s, DirectedEdge(7, "name", value=Val(TypeID.STRING, "robert"), lang="fr"), 3)
    s.commit(3, 4, list(s.lists.keys()))
    from dgraph_tpu.utils import tok

    bob = tok.get("exact").tokens(Val(TypeID.STRING, "bob"))[0]
    np.testing.assert_array_equal(s.get(K.index_key("name", bob)).uids(5), [7])
    assert s.get(K.data_key("name", 7)).value(5).value == "bob"
    assert s.get(K.data_key("name", 7)).value(5, lang="fr").value == "robert"


def test_list_valued_scalar():
    # regression: [string] predicates accumulate values (found by review)
    s = Store()
    for e in parse_schema("hobby: [string] @index(exact) ."):
        s.set_schema(e)
    idx.add_mutation_with_index(s, DirectedEdge(1, "hobby", value=Val(TypeID.STRING, "chess")), 1)
    idx.add_mutation_with_index(s, DirectedEdge(1, "hobby", value=Val(TypeID.STRING, "go")), 1)
    s.commit(1, 2, list(s.lists.keys()))
    vals = {v.value for v in s.get(K.data_key("hobby", 1)).all_values(3)}
    assert vals == {"chess", "go"}
    # delete one specific value
    idx.add_mutation_with_index(
        s, DirectedEdge(1, "hobby", value=Val(TypeID.STRING, "chess"), op=Op.DEL), 3)
    s.commit(3, 4, list(s.lists.keys()))
    vals = {v.value for v in s.get(K.data_key("hobby", 1)).all_values(5)}
    assert vals == {"go"}
    from dgraph_tpu.utils import tok

    chess = tok.get("exact").tokens(Val(TypeID.STRING, "chess"))[0]
    assert s.get(K.index_key("hobby", chess)).length(5) == 0


def test_checkpoint_crash_window(tmp_path):
    # regression: crash between snapshot replace and WAL truncation must not
    # double-apply old commits (found by review)
    import os
    import shutil

    d = str(tmp_path / "st")
    s = Store(d)
    k = K.data_key("p", 1)
    s.add_mutation(1, k, Posting(0, Op.DEL_ALL))
    s.commit(1, 5, [k.encode()])
    s.add_mutation(2, k, Posting(77))
    s.commit(2, 7, [k.encode()])
    wal_copy = str(tmp_path / "wal.copy")
    shutil.copy(os.path.join(d, "wal.log"), wal_copy)
    s.checkpoint(10)
    s.close()
    # simulate: snapshot.bin is new, wal.log is the OLD pre-checkpoint WAL
    shutil.copy(wal_copy, os.path.join(d, "wal.log"))
    s2 = Store(d)
    np.testing.assert_array_equal(s2.get(k).uids(10), [77])
    s2.close()


def test_rollup_watermark_guard():
    pl = PostingList()
    pl.add_mutation(1, Posting(5))
    pl.commit(1, 2)
    pl.rollup(2)
    with pytest.raises(ValueError, match="watermark"):
        pl.uids(1)


def test_rebuild_survives_replay(tmp_path):
    # regression: index rebuild drops must be WAL-logged (found by review)
    d = str(tmp_path / "st")
    s = Store(d)
    for e in parse_schema("friend: uid @count ."):
        s.set_schema(e)
    idx.add_mutation_with_index(s, DirectedEdge(1, "friend", object_uid=2), 1)
    s.commit(1, 2, list(s.lists.keys()))
    idx.add_mutation_with_index(s, DirectedEdge(1, "friend", object_uid=3), 3)
    s.commit(3, 4, list(s.lists.keys()))
    idx.rebuild_count(s, "friend", read_ts=5, commit_ts=6)
    s.close()
    s2 = Store(d)  # replay without checkpoint
    assert 1 not in s2.get(K.count_key("friend", 1)).uids(7).tolist()
    np.testing.assert_array_equal(s2.get(K.count_key("friend", 2)).uids(7), [1])
    s2.close()


def test_v1_snapshot_still_loads(tmp_path):
    """Snapshots written by the pre-columnar DGTS1 row format must keep
    loading (frozen format; the writer moved to DGTS2)."""
    import json as _json
    import struct as _struct

    import numpy as _np

    from dgraph_tpu.storage import keys as _K
    from dgraph_tpu.storage import packed as _packed
    from dgraph_tpu.storage.store import Store as _Store
    _u32 = _struct.Struct("<I")

    uids = _np.array([3, 7, 9], dtype=_np.uint64)
    bp = _packed.pack(uids)
    kb = _K.data_key("name", 1).encode()
    d = tmp_path / "v1store"
    d.mkdir()
    with open(d / "snapshot.bin", "wb") as f:
        f.write(b"DGTS1")
        f.write(_struct.pack("<Q", 5))
        meta = _json.dumps({"schema": "name: uid .", "max_commit_ts": 5}).encode()
        f.write(_u32.pack(len(meta)) + meta)
        f.write(_u32.pack(len(kb)) + kb)
        f.write(_struct.pack("<QI", 5, bp.count))
        for arr in (bp.block_first, bp.block_last, bp.block_count,
                    bp.block_width, bp.block_off, bp.words):
            b = arr.tobytes()
            f.write(_u32.pack(len(b)) + b)
        f.write(_u32.pack(2) + b"[]")
    s = _Store(str(d))
    _np.testing.assert_array_equal(s.lists[kb].uids(5), [3, 7, 9])
    # and the next checkpoint upgrades it to the current format (DGTS3)
    s.checkpoint(5)
    s.close()
    with open(d / "snapshot.bin", "rb") as f:
        assert f.read(5) == b"DGTS3"
    s2 = _Store(str(d))
    _np.testing.assert_array_equal(s2.lists[kb].uids(5), [3, 7, 9])
    s2.close()


def test_v2_snapshot_still_loads(tmp_path):
    """Snapshots written by the file-global-column DGTS2 format (the writer
    before the streaming tablet-sectioned DGTS3) must keep loading, eager
    AND paged — the fixture is handwritten so the frozen layout can never
    drift with the code."""
    import json as _json
    import struct as _struct

    import numpy as _np

    from dgraph_tpu.storage import keys as _K
    from dgraph_tpu.storage import packed as _packed
    from dgraph_tpu.storage.store import Store as _Store
    _u32 = _struct.Struct("<I")

    rows = [(_K.data_key("name", 1).encode(), _np.array([3, 7], _np.uint64)),
            (_K.data_key("name", 2).encode(), _np.array([9], _np.uint64))]
    bps = [_packed.pack(u) for _, u in rows]
    keys = [kb for kb, _ in rows]
    N = len(rows)

    def cat(dt, arrs):
        arrs = [_np.asarray(a, dt) for a in arrs if len(a)]
        return _np.concatenate(arrs) if arrs else _np.zeros(0, dt)

    d = tmp_path / "v2store"
    d.mkdir()
    with open(d / "snapshot.bin", "wb") as f:
        f.write(b"DGTS2")
        f.write(_struct.pack("<Q", 5))
        meta = _json.dumps({"schema": "name: uid .",
                            "max_commit_ts": 5}).encode()
        f.write(_u32.pack(len(meta)) + meta)
        f.write(_u32.pack(N))
        cols = [
            _np.fromiter((len(k) for k in keys), _np.uint32, count=N),
            _np.frombuffer(b"".join(keys), _np.uint8),
            _np.full(N, 5, _np.uint64),
            _np.fromiter((bp.count for bp in bps), _np.uint32, count=N),
            _np.fromiter((bp.nblocks for bp in bps), _np.uint32, count=N),
            cat(_np.uint64, [bp.block_first for bp in bps]),
            cat(_np.uint64, [bp.block_last for bp in bps]),
            cat(_np.int32, [bp.block_count for bp in bps]),
            cat(_np.int32, [bp.block_width for bp in bps]),
            cat(_np.int64, [bp.block_off for bp in bps]),
            _np.fromiter((len(bp.words) for bp in bps), _np.uint64, count=N),
            cat(_np.uint32, [bp.words for bp in bps]),
            _np.zeros(N, _np.uint32),
            _np.zeros(0, _np.uint8),
        ]
        for arr in cols:
            b = arr.tobytes()
            f.write(_struct.pack("<Q", len(b)))
            f.write(b)
    s = _Store(str(d))
    _np.testing.assert_array_equal(s.lists[keys[0]].uids(5), [3, 7])
    _np.testing.assert_array_equal(s.lists[keys[1]].uids(5), [9])
    s.close()
    sp = _Store(str(d), memory_budget=1 << 20)     # paged mmap path
    _np.testing.assert_array_equal(sp.lists[keys[0]].uids(5), [3, 7])
    sp.close()


# -- binary WAL record codec (round 4) ---------------------------------------

def test_wal_record_codec_roundtrip():
    from dgraph_tpu.storage import keys as K
    from dgraph_tpu.storage.postings import Op, Posting
    from dgraph_tpu.storage.store import decode_record, encode_record
    from dgraph_tpu.utils.types import TypeID, Val

    kb = K.data_key("name", 7).encode()
    p = Posting(0, Op.SET, Val(TypeID.STRING, "héllo"), "fr",
                (("w", Val(TypeID.FLOAT, 0.5)),))
    rec = decode_record(encode_record({"t": "m", "s": -42, "k": kb, "p": p}))
    assert rec["t"] == "m" and rec["s"] == -42 and rec["k"] == kb
    assert rec["p"].value.value == "héllo" and rec["p"].lang == "fr"
    assert rec["p"].facets[0][0] == "w"

    rec = decode_record(encode_record(
        {"t": "c", "s": 5, "ts": 6, "k": [kb, kb + b"x"]}))
    assert rec["ts"] == 6 and rec["k"][1] == kb + b"x"
    rec = decode_record(encode_record({"t": "a", "s": 5, "k": [kb]}))
    assert rec["t"] == "a" and rec["k"] == [kb]
    # rare types stay JSON (starts with '{')
    data = encode_record({"t": "s", "line": "name: string ."})
    assert data[0:1] == b"{"
    assert decode_record(data)["line"] == "name: string ."


def test_old_json_wal_replays(tmp_path):
    """A WAL written in the pre-r4 JSON format must replay unchanged."""
    import base64
    import json
    import struct

    from dgraph_tpu.storage import keys as K
    from dgraph_tpu.storage.store import Store

    kb = K.data_key("v", 1).encode()
    records = [
        {"t": "s", "line": "v: int ."},
        {"t": "m", "s": 3, "k": base64.b64encode(kb).decode(),
         "p": {"u": 0, "o": int(__import__("dgraph_tpu.storage.postings", fromlist=["Op"]).Op.SET),
               "v": {"t": 2, "b": base64.b64encode(
                   (9).to_bytes(8, "little", signed=True)).decode()}}},
        {"t": "c", "s": 3, "ts": 4,
         "k": [base64.b64encode(kb).decode()]},
    ]
    d = tmp_path / "old"
    d.mkdir()
    with open(d / "wal.log", "wb") as f:
        for rec in records:
            data = json.dumps(rec).encode()
            f.write(struct.pack("<I", len(data)) + data)
    s = Store(str(d))
    assert s.max_seen_commit_ts == 4
    pl = s.lists[kb]
    assert pl.value(4).value == 9
    s.close()


def test_abort_record_applies(tmp_path):
    """Replaying/shipping a 't':'a' record must reap the buffered layer
    (review r4: the refactor had left the lookup unbound)."""
    from dgraph_tpu.storage import keys as K
    from dgraph_tpu.storage.postings import Op, Posting
    from dgraph_tpu.storage.store import Store, decode_record, encode_record
    from dgraph_tpu.utils.types import TypeID, Val

    s = Store()
    k = K.data_key("v", 1)
    s.add_mutation(5, k, Posting(0, Op.SET, Val(TypeID.INT, 1)))
    kb = k.encode()
    s.apply_record(decode_record(encode_record({"t": "a", "s": 5, "k": [kb]})))
    assert 5 not in s.lists[kb].uncommitted
    # unknown key must be a no-op, not a crash
    s.apply_record(decode_record(encode_record(
        {"t": "a", "s": 9, "k": [K.data_key("v", 99).encode()]})))


def test_wal_codec_wide_fields():
    """Lang tags / facet names / facet counts beyond 255 must round-trip
    (review r4: the first binary cut used 1-byte length fields)."""
    from dgraph_tpu.storage import keys as K
    from dgraph_tpu.storage.postings import Op, Posting
    from dgraph_tpu.storage.store import decode_record, encode_record
    from dgraph_tpu.utils.types import TypeID, Val

    kb = K.data_key("p", 1).encode()
    facets = tuple((f"key{i:04d}" + "x" * 300, Val(TypeID.INT, i))
                   for i in range(300))
    p = Posting(0, Op.SET, Val(TypeID.STRING, "v"), "x-" + "l" * 300, facets)
    rec = decode_record(encode_record({"t": "m", "s": 1, "k": kb, "p": p}))
    assert rec["p"].lang == p.lang
    assert len(rec["p"].facets) == 300
    assert rec["p"].facets[299][0] == p.facets[299][0]
