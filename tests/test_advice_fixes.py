"""Regression tests for the round-1 advisor findings (ADVICE.md).

1. Untagged value reads must NOT fall back to lang-tagged values; only the
   explicit "." tag does (reference posting/list.go postingForLangs).
2. ops.csr.expand with an empty adjacency returns an all-sentinel result.
3. Nested count(uid) inside a child block emits {"count": n} per parent.
4. Frontier-level eq(pred, v1, v2, ...) matches any listed value.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dgraph_tpu.ops import csr as csrops
from dgraph_tpu.query import dql
from dgraph_tpu.query.engine import Executor
from dgraph_tpu.storage import index as idx
from dgraph_tpu.storage.csr_build import build_snapshot
from dgraph_tpu.storage.postings import DirectedEdge, PostingList, Posting, lang_uid
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val


@pytest.fixture(scope="module")
def env():
    s = Store()
    for e in parse_schema("""
        name: string @index(exact) @lang .
        age: int .
        friend: uid .
    """):
        s.set_schema(e)
    # uid 1: only a French name. uid 2: untagged + French. uid 3: untagged only.
    idx.add_mutation_with_index(
        s, DirectedEdge(1, "name", value=Val(TypeID.STRING, "Michel"), lang="fr"), 1)
    idx.add_mutation_with_index(
        s, DirectedEdge(2, "name", value=Val(TypeID.STRING, "Rick")), 1)
    idx.add_mutation_with_index(
        s, DirectedEdge(2, "name", value=Val(TypeID.STRING, "Rique"), lang="fr"), 1)
    idx.add_mutation_with_index(
        s, DirectedEdge(3, "name", value=Val(TypeID.STRING, "Glenn")), 1)
    for u, a in [(1, 10), (2, 15), (3, 20)]:
        idx.add_mutation_with_index(s, DirectedEdge(u, "age", value=Val(TypeID.INT, a)), 1)
    for b in (1, 2, 3):
        idx.add_mutation_with_index(s, DirectedEdge(4, "friend", object_uid=b), 1)
    s.commit(1, 2, list(s.lists.keys()))
    return s, build_snapshot(s, read_ts=3)


def run(env, q):
    s, snap = env
    return Executor(snap, s.schema).execute(dql.parse(q))


# -- 1. lang fallback ---------------------------------------------------------

def test_untagged_read_ignores_lang_only_values():
    pl = PostingList()
    pl.add_mutation(1, Posting(lang_uid("fr"), value=Val(TypeID.STRING, "chat"),
                               lang="fr"))
    pl.commit(1, 2)
    assert pl.value(3) is None                 # untagged read: nothing
    assert pl.value(3, "fr").value == "chat"   # exact tag
    assert pl.value(3, ".").value == "chat"    # any-language tag


def test_query_untagged_name_on_lang_only_node(env):
    # uid 1 holds only name@fr: plain `name` must NOT surface the French value
    out = run(env, '{ q(func: uid(1)) { name } }')
    assert "name" not in out.get("q", [{}])[0] if out.get("q") else True
    out = run(env, '{ q(func: uid(1)) { name@fr } }')
    assert out["q"][0]["name@fr"] == "Michel"
    out = run(env, '{ q(func: uid(1)) { name@. } }')
    assert out["q"][0]["name@."] == "Michel"


def test_any_lang_prefers_untagged(env):
    out = run(env, '{ q(func: uid(2)) { name@. } }')
    assert out["q"][0]["name@."] == "Rick"


def test_has_matches_lang_only_nodes(env):
    out = run(env, '{ q(func: has(name)) { uid } }')
    uids = {x["uid"] for x in out["q"]}
    assert uids == {"0x1", "0x2", "0x3"}
    # frontier-level has() too
    out = run(env, '{ q(func: uid(4)) { friend @filter(has(name)) { uid } } }')
    uids = {x["uid"] for x in out["q"][0]["friend"]}
    assert uids == {"0x1", "0x2", "0x3"}


# -- 2. empty expand ----------------------------------------------------------

def test_expand_empty_indices():
    indptr = jnp.zeros(3, dtype=jnp.int32)
    indices = jnp.zeros(0, dtype=jnp.int32)
    rows = jnp.asarray([0, 1], dtype=jnp.int32)
    res = csrops.expand(indptr, indices, rows, out_cap=8)
    assert int(res.total) == 0
    assert np.all(np.asarray(res.seg) == -1)
    res2 = csrops.expand(indptr, indices, jnp.zeros(0, jnp.int32), out_cap=4)
    assert int(res2.total) == 0


# -- 3. nested count(uid) -----------------------------------------------------

def test_nested_count_uid(env):
    out = run(env, '{ q(func: uid(4)) { friend { count(uid) } } }')
    assert out["q"][0]["friend"] == [{"count": 3}]
    # respects filters
    out = run(env, '{ q(func: uid(4)) { friend @filter(ge(age, 15)) { count(uid) } } }')
    assert out["q"][0]["friend"] == [{"count": 2}]
    # mixed with sibling attributes: count is one more list entry (ref query.go:472)
    out = run(env, '{ q(func: uid(4)) { friend { count(uid) name } } }')
    objs = out["q"][0]["friend"]
    assert {"count": 3} in objs and {"name": "Glenn"} in objs


# -- 4. multi-value eq on frontier --------------------------------------------

def test_multivalue_eq_filter(env):
    out = run(env, '{ q(func: uid(4)) { friend @filter(eq(age, 10, 20)) { uid } } }')
    uids = {x["uid"] for x in out["q"][0]["friend"]}
    assert uids == {"0x1", "0x3"}
