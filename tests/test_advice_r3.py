"""Regression tests for the round-3 advisor findings (ADVICE.md r3).

1. (high) Numeric frontier-compare fast path must not fire for [type] list
   predicates: num_values_host holds one representative element per subject,
   so eq/lt/... must check every list element (reference matches any).
2. (high) Root eq on a lossy-indexed [string] list predicate: the lossy
   post-filter must re-check against pd.list_values, not just the single
   representative host value.
3. (medium) FollowerReader builds its read snapshot at ts =
   max_seen_commit_ts (not ts+1): a commit landing at exactly ts+1 mid-build
   must not become partially visible.
4. (low) Idle-txn reaper exempts young txns: a slow client that opened a
   txn lazily and mutates later must not get "unknown txn".
"""

import pytest

from dgraph_tpu.api.server import Node


@pytest.fixture()
def node():
    n = Node()
    n.alter(schema_text="""
        name: string @index(exact) .
        score: [int] @index(int) .
        nick: [string] @index(term) .
    """)
    n.mutate(set_nquads="""
        _:a <name> "ann" .
        _:a <score> "9"^^<xs:int> .
        _:a <score> "10"^^<xs:int> .
        _:a <nick> "bob" .
        _:a <nick> "zed" .
        _:b <name> "bea" .
        _:b <score> "11"^^<xs:int> .
        _:b <nick> "carol" .
    """, commit_now=True)
    return n


def _names(out, block="q"):
    return sorted(x["name"] for x in out.get(block, []))


def test_list_int_frontier_eq_matches_any_element(node):
    # score = {9, 10}: sorted-by-string representative is 10, so the old
    # vector fast path compared only 10 and dropped the eq(score, 9) match
    out, _ = node.query(
        '{ q(func: has(name)) @filter(eq(score, 9)) { name } }')
    assert _names(out) == ["ann"]


def test_list_int_frontier_lt_matches_any_element(node):
    # lt(score, 10) must match via element 9 even though representative is 10
    out, _ = node.query(
        '{ q(func: has(name)) @filter(lt(score, 10)) { name } }')
    assert _names(out) == ["ann"]


def test_list_int_frontier_no_false_positive(node):
    out, _ = node.query(
        '{ q(func: has(name)) @filter(eq(score, 12)) { name } }')
    assert _names(out) == []


def test_root_eq_lossy_list_predicate(node):
    # term index is lossy → post-filter; representative host value is "bob",
    # so eq(nick, "zed") used to return empty
    out, _ = node.query('{ q(func: eq(nick, "zed")) { name } }')
    assert _names(out) == ["ann"]
    out, _ = node.query('{ q(func: eq(nick, "bob")) { name } }')
    assert _names(out) == ["ann"]
    out, _ = node.query('{ q(func: eq(nick, "nope")) { name } }')
    assert _names(out) == []


def test_follower_snapshot_covers_max_seen_commit_ts(tmp_path):
    # functional guard for the read_ts fix: everything shipped (including the
    # newest commit, which lands at exactly max_seen_commit_ts) must be
    # visible at the follower's build ts
    from dgraph_tpu.coord.replication import ReplicaGroup

    g = ReplicaGroup(str(tmp_path / "grp"), n=3, serve_reads=True)
    try:
        g.node.alter(schema_text="balance: int .")
        g.node.mutate(set_nquads='_:x <balance> "42"^^<xs:int> .',
                      commit_now=True)
        follower = next(m.reader for m in g.members if m.reader is not None)
        got = follower.query("{ q(func: has(balance)) { balance } }")
        assert got["q"] == [{"balance": 42}]
    finally:
        g.close()


def test_idle_txn_reaper_spares_young_txns():
    n = Node()
    n.alter(schema_text="v: int .")
    n.MAX_IDLE_TXNS = 8  # keep the test fast
    slow = n.new_txn()   # lazily-opened, pristine, young
    for _ in range(20):
        n.new_txn()
    # the slow client finally mutates + commits — must still be known
    n.mutate(set_nquads='_:x <v> "1"^^<xs:int> .', start_ts=slow.start_ts)
    assert n.commit(slow.start_ts) > slow.start_ts


def test_idle_txn_reaper_still_reaps_stale_txns():
    n = Node()
    n.MAX_IDLE_TXNS = 8
    stale = [n.new_txn() for _ in range(12)]
    for ctx in stale:
        ctx.last_active -= n.IDLE_TXN_GRACE_S + 1
    n.new_txn()  # triggers the reap
    assert sum(1 for c in stale if c.start_ts not in n._txns) > 0


def test_regexp_matches_any_list_element(node):
    node.alter(schema_text="nick: [string] @index(trigram) .")
    node.mutate(set_nquads='_:c <name> "cyd" .\n_:c <nick> "aaa" .\n'
                           '_:c <nick> "zedding" .', commit_now=True)
    out, _ = node.query('{ q(func: regexp(nick, /zedd/)) { name } }')
    assert _names(out) == ["cyd"]


# -- incremental snapshots on workers + followers (VERDICT r3 #6) ------------

def test_worker_snapshot_rebuilds_one_predicate(tmp_path):
    """A commit touching one predicate re-folds that predicate only — every
    other PredData keeps array identity on the worker wire service."""
    pytest.importorskip("grpc")
    from dgraph_tpu.parallel.remote import WorkerService
    from dgraph_tpu.query import mutation as mut
    from dgraph_tpu.query import rdf
    from dgraph_tpu.storage.postings import DirectedEdge, Op
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.utils.schema import parse_schema
    from dgraph_tpu.utils.types import TypeID, Val

    s = Store()
    for e in parse_schema("a: int .\nb: int ."):
        s.set_schema(e)
    for ts, (attr, val) in ((1, ("a", 1)), (3, ("b", 2))):
        touched, _, _ = mut.apply_mutations(
            s, [DirectedEdge(1, attr, value=Val(TypeID.INT, val))], ts)
        s.commit(ts, ts + 1, touched)
    svc = WorkerService(s)
    snap1 = svc._snapshot(10)
    pd_a1, pd_b1 = snap1.preds["a"], snap1.preds["b"]

    # commit touching ONLY b
    touched, _, _ = mut.apply_mutations(
        s, [DirectedEdge(2, "b", value=Val(TypeID.INT, 9))], 20)
    s.commit(20, 21, touched)
    snap2 = svc._snapshot(30)
    assert snap2.preds["a"] is pd_a1          # untouched: same arrays
    assert snap2.preds["b"] is not pd_b1      # re-folded past the commit
    assert 2 in snap2.preds["b"].host_values


def test_follower_snapshot_rebuilds_one_predicate(tmp_path):
    from dgraph_tpu.coord.replication import ReplicaGroup

    g = ReplicaGroup(str(tmp_path / "grp"), n=3, serve_reads=True)
    try:
        g.node.alter(schema_text="a: int .\nb: int .")
        g.node.mutate(set_nquads='<0x1> <a> "1"^^<xs:int> .\n'
                                 '<0x1> <b> "2"^^<xs:int> .', commit_now=True)
        f = next(m.reader for m in g.members if m.reader is not None)
        assert f.query("{ q(func: has(a)) { a b } }")["q"] == [
            {"a": 1, "b": 2}]
        snap1 = f._assembler.snapshot(f.store.max_seen_commit_ts)
        pd_a1, pd_b1 = snap1.preds["a"], snap1.preds["b"]

        g.node.mutate(set_nquads='<0x2> <b> "9"^^<xs:int> .', commit_now=True)
        out = f.query("{ q(func: has(b)) { b } }")
        assert sorted(x["b"] for x in out["q"]) == [2, 9]
        snap2 = f._assembler.snapshot(f.store.max_seen_commit_ts)
        assert snap2.preds["a"] is pd_a1
        assert snap2.preds["b"] is not pd_b1
    finally:
        g.close()


def test_old_ts_snapshot_stays_cached_after_newer_commit():
    """A newer commit must NOT invalidate cached snapshots at older read
    timestamps — they are immutable views (review r4 on _stale)."""
    from dgraph_tpu.parallel.remote import WorkerService
    from dgraph_tpu.query import mutation as mut
    from dgraph_tpu.storage.postings import DirectedEdge
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.utils.schema import parse_schema
    from dgraph_tpu.utils.types import TypeID, Val

    s = Store()
    for e in parse_schema("a: int ."):
        s.set_schema(e)
    touched, _, _ = mut.apply_mutations(
        s, [DirectedEdge(1, "a", value=Val(TypeID.INT, 1))], 1)
    s.commit(1, 2, touched)
    svc = WorkerService(s)
    old = svc._snapshot(2)
    touched, _, _ = mut.apply_mutations(
        s, [DirectedEdge(2, "a", value=Val(TypeID.INT, 5))], 10)
    s.commit(10, 11, touched)
    assert svc._snapshot(2) is old          # immutable old view: cache hit
    new = svc._snapshot(11)
    assert new is not old
    assert 2 in new.preds["a"].host_values
    assert 2 not in old.preds["a"].host_values
