"""Flat uid-tablet fold (VERDICT r4 #5): the vectorized key parse + single
batched native decode must produce the same CSR as the per-key reference
fold, including interleaved pure-base and live-layer lists, deletions,
facets, and empty lists."""

import numpy as np
import pytest

from dgraph_tpu.storage import csr_build as cb
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage import native
from dgraph_tpu.storage.packed import pack
from dgraph_tpu.storage.postings import Op, Posting, PostingList
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val


def _mk_store(rng, n_keys=40):
    """Interleaved pure-base and live-layer lists under one uid predicate."""
    s = Store()
    for e in parse_schema("friend: [uid] @reverse ."):
        s.set_schema(e)
    expect: dict[int, set[int]] = {}
    for i in range(1, n_keys + 1):
        kb = K.data_key("friend", i).encode()
        pl = PostingList()
        base = np.unique(rng.integers(1, 500, rng.integers(0, 9))).astype(
            np.uint64)
        pl.base_packed = pack(base)
        s.lists[kb] = pl
        s.by_pred.setdefault((int(K.KeyKind.DATA), "friend"),
                             set()).add(kb)
        expect[i] = set(int(x) for x in base)
        if i % 3 == 0:     # live layer: one add (with facet), one delete
            add = int(rng.integers(500, 600))
            pl.add_mutation(5, Posting(add, op=Op.SET,
                                       facets=(("w", Val(TypeID.INT, i)),)))
            if expect[i]:
                rm = next(iter(expect[i]))
                pl.add_mutation(5, Posting(rm, op=Op.DEL))
                expect[i].discard(rm)
            pl.commit(5, 6)
            expect[i].add(add)
    return s, expect


def test_flat_fold_matches_reference(rng):
    s, expect = _mk_store(rng)
    pd = cb.build_pred(s, "friend", read_ts=10)
    got: dict[int, set[int]] = {}
    if pd.csr is not None:
        subs, indptr, indices = pd.csr.host_arrays()
        for r, u in enumerate(subs.tolist()):
            got[int(u)] = set(
                int(x) for x in indices[indptr[r]: indptr[r + 1]])
    want = {u: v for u, v in expect.items() if v}
    assert got == want
    # facets captured from live-layer postings only
    for (subj, obj), facets in pd.facets.items():
        assert subj % 3 == 0
        assert dict(facets)["w"].value == subj


def test_flat_fold_empty_and_all_complex(rng):
    s, expect = _mk_store(rng, n_keys=6)
    # read below the commit: layers invisible -> pure bases only
    pd = cb.build_pred(s, "friend", read_ts=4)
    if pd.csr is not None:
        subs, indptr, indices = pd.csr.host_arrays()
        for r, u in enumerate(subs.tolist()):
            base = s.lists[K.data_key("friend", int(u)).encode()]
            ref = set(int(x) for x in native.unpack(base.base_packed))
            assert set(
                int(x) for x in indices[indptr[r]: indptr[r + 1]]) == ref


def test_uids_of_keys_vectorized():
    kbs = [K.data_key("p", u).encode() for u in (1, 7, 2**33, 2**40 + 5)]
    np.testing.assert_array_equal(
        cb._uids_of_keys(kbs), [1, 7, 2**33, 2**40 + 5])
    assert len(cb._uids_of_keys([])) == 0


def test_unpack_many_flat_matches_sliced(rng):
    rows = [np.unique(rng.integers(0, 10_000, rng.integers(0, 400)))
            .astype(np.uint64) for _ in range(50)]
    pls = [pack(r) for r in rows]
    flat, counts = native.unpack_many_flat(pls)
    assert counts.tolist() == [len(r) for r in rows]
    offs = np.concatenate([[0], np.cumsum(counts)])
    for i, r in enumerate(rows):
        np.testing.assert_array_equal(flat[offs[i]: offs[i + 1]], r)


def test_read_below_rollup_watermark_raises(rng):
    """Snapshot isolation: a uid-tablet read below a rollup watermark must
    raise, on both the flat path and the TabletPacked cold-open path
    (PostingList._base_only semantics)."""
    import tempfile

    from dgraph_tpu.storage.store import Store as S2

    d = tempfile.mkdtemp(prefix="foldts-")
    s = S2(d)
    for e in parse_schema("friend: [uid] ."):
        s.set_schema(e)
    kb = K.data_key("friend", 1)
    s.add_mutation(10, kb, Posting(42, op=Op.SET))
    s.commit(10, 11, [kb.encode()])
    s.checkpoint(11)          # rollup watermark = 11
    with pytest.raises(ValueError, match="below rollup watermark"):
        cb.build_pred(s, "friend", read_ts=5)
    s.close()
    s2 = S2(d)                # cold open: TabletPacked path
    assert s2.packed_tablet(int(K.KeyKind.DATA), "friend") is not None
    with pytest.raises(ValueError, match="below rollup watermark"):
        cb.build_pred(s2, "friend", read_ts=5)
    pd = cb.build_pred(s2, "friend", read_ts=11)
    assert pd.csr is not None
    s2.close()
