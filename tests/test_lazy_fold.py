"""Lazy on-demand snapshot folds (ISSUE 15, storage/csr_build.py).

Covers the tentpole's contracts: lazy assembly is byte-identical to eager
over a value/lang/facet/reverse/index-rich corpus AND at the query-output
level; racing first readers share ONE fold (no double fold, no torn
PredData identity) with lockdep armed; per-predicate cache tokens survive
lazy resolution exactly like eager reuse; the residency prefetch leg
resolves pending folds; overlay-forced folds count as `inline`; txn
read views share pending thunks; the LDBC generator is seed-deterministic
through convert --ldbc (same seed ⇒ same N-Quads sha256); and the
host/mesh/tiered serving paths return identical 3-hop result UID sets on
a generated LDBC-shaped graph.
"""

import gzip
import hashlib
import json
import os
import threading

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.storage import csr_build
from dgraph_tpu.storage.csr_build import (LazyPreds, SnapshotAssembler,
                                          build_snapshot)

SCHEMA = """
name: string @index(exact, term) @lang .
age: int @index(int) .
follows: [uid] @reverse @count .
nick: [string] @index(term) .
"""

QUADS = [
    '<0x1> <name> "alice" .',
    '<0x1> <name> "alicia"@es .',
    '<0x2> <name> "bob" .',
    '<0x3> <name> "carol smith" .',
    '<0x1> <age> "30"^^<xs:int> .',
    '<0x2> <age> "41"^^<xs:int> .',
    '<0x1> <follows> <0x2> (weight=0.5) .',
    '<0x1> <follows> <0x3> .',
    '<0x2> <follows> <0x3> .',
    '<0x3> <follows> <0x1> .',
    '<0x1> <nick> "al" .',
    '<0x1> <nick> "ally" .',
]

BATTERY = [
    '{ q(func: eq(name, "alice")) { name name@es age nick '
    '  follows @facets { name } } }',
    '{ q(func: has(follows)) { count(follows) } }',
    '{ q(func: ge(age, 31)) { name ~follows { name } } }',
    '{ q(func: anyofterms(name, "carol")) { name follows { age } } }',
    '{ q(func: uid(0x1)) { follows { follows { name } } } }',
]


def _mk_node(**kw) -> Node:
    n = Node(**kw)
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads="\n".join(QUADS), commit_now=True)
    return n


def _pd_equal(a, b) -> None:
    """Structural byte-equality of two folded PredData."""
    for fld in ("csr", "rev_csr"):
        ca, cb = getattr(a, fld), getattr(b, fld)
        assert (ca is None) == (cb is None), fld
        if ca is not None:
            for xa, xb in zip(ca.host_arrays(), cb.host_arrays()):
                np.testing.assert_array_equal(xa, xb)
    for fld in ("value_subjects_host", "num_values_host"):
        va, vb = getattr(a, fld), getattr(b, fld)
        assert (va is None) == (vb is None), fld
        if va is not None:
            np.testing.assert_array_equal(va, vb)
    assert a.host_values == b.host_values
    assert a.list_values == b.list_values
    assert a.lang_values == b.lang_values
    assert a.facets == b.facets
    assert sorted(a.indexes) == sorted(b.indexes)
    for name, ta in a.indexes.items():
        tb = b.indexes[name]
        assert ta.terms == tb.terms
        np.testing.assert_array_equal(ta.host_arrays()[0],
                                      tb.host_arrays()[0])
        np.testing.assert_array_equal(ta.host_arrays()[1],
                                      tb.host_arrays()[1])


def test_lazy_snapshot_byte_identical_to_eager():
    """build_snapshot(lazy=True) resolves to the exact arrays the eager
    fold produces — per predicate, across CSR / reverse / value tables /
    lang / facets / token indexes."""
    n = _mk_node()
    ts = n.store.max_seen_commit_ts
    eager = build_snapshot(n.store, ts)
    lazy = build_snapshot(n.store, ts, lazy=True)
    assert isinstance(lazy.preds, LazyPreds)
    assert sorted(lazy.preds.keys()) == sorted(eager.preds.keys())
    assert lazy.preds.pending_attrs()
    for attr in eager.preds:
        _pd_equal(lazy.preds[attr], eager.preds[attr])
    assert not lazy.preds.pending_attrs()
    n.close()


def test_query_outputs_identical_lazy_vs_eager():
    """The mixed battery returns byte-identical JSON on a lazy node and
    an eager (--no_lazy_folds) node."""
    nl = _mk_node()
    ne = _mk_node(lazy_folds=False)
    for q in BATTERY:
        ol, _ = nl.query(q)
        oe, _ = ne.query(q)
        assert json.dumps(ol, sort_keys=True) == \
            json.dumps(oe, sort_keys=True), q
    nl.close()
    ne.close()


def test_racing_first_readers_share_one_fold():
    """8 threads racing the first read of one pending tablet produce ONE
    build_pred call and one PredData identity — lockdep armed, zero
    lock-order violations."""
    from dgraph_tpu.utils import locks

    locks.reset()
    locks.arm(raise_on_cycle=True)
    try:
        n = _mk_node()
        asm = SnapshotAssembler(n.store, lazy_folds=True)
        snap = asm.snapshot(n.store.max_seen_commit_ts)
        assert "follows" in snap.preds.pending_attrs()

        calls = []
        orig = csr_build.build_pred

        def counted(store, attr, read_ts, own_start_ts=None):
            if attr == "follows":
                calls.append(attr)
            return orig(store, attr, read_ts, own_start_ts)

        csr_build.build_pred = counted
        try:
            got = [None] * 8
            barrier = threading.Barrier(8)

            def read(i):
                barrier.wait()
                got[i] = snap.preds.get("follows")

            ts = [threading.Thread(target=read, args=(i,))
                  for i in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            csr_build.build_pred = orig
        assert calls == ["follows"]            # exactly one fold
        assert all(g is got[0] and g is not None for g in got)
        n.close()
    finally:
        vs = locks.violations()
        locks.disarm()
        locks.reset()
        assert vs == [], vs


def test_tokens_and_identity_survive_lazy_resolution():
    """qcache per-predicate tokens key on PredData identity: a lazily
    folded tablet keeps ONE identity across successive snapshots (the
    same both-views-complete reuse rule as the eager cache), so task
    keys never rotate without a commit."""
    from dgraph_tpu.query import qcache
    from dgraph_tpu.query.task import TaskQuery

    n = _mk_node()
    s1 = n.snapshot()
    tq = TaskQuery(attr="follows")
    pd1 = s1.preds.get("follows")
    tok1 = qcache.task_token(s1, tq)
    s2 = n.snapshot(n.zero.oracle.read_ts())   # fresh ts, no commits
    assert s2.preds.get("follows") is pd1
    assert qcache.task_token(s2, tq) == tok1
    # a commit to a DIFFERENT predicate keeps follows' token
    n.mutate(set_nquads='<0x9> <name> "dave" .', commit_now=True)
    s3 = n.snapshot()
    assert qcache.task_token(s3, tq) == tok1
    # a commit to follows rotates it
    n.mutate(set_nquads='<0x9> <follows> <0x1> .', commit_now=True)
    s4 = n.snapshot()
    assert s4.preds.get("follows") is not None
    assert qcache.task_token(s4, tq) != tok1
    n.close()


def test_prefetch_leg_resolves_pending_folds():
    """residency.prefetch's fold leg resolves pending thunks (counted as
    trigger=prefetch) even with no device budget configured."""
    n = _mk_node()
    asm = SnapshotAssembler(n.store, metrics=n.metrics, lazy_folds=True)
    snap = asm.snapshot(n.store.max_seen_commit_ts)
    assert "follows" in snap.preds.pending_attrs()
    before = n.metrics.counter("dgraph_fold_prefetch_total").value
    n.residency.prefetch(["follows"], snap, sync=True)
    assert "follows" not in snap.preds.pending_attrs()
    assert n.metrics.counter(
        "dgraph_fold_prefetch_total").value == before + 1
    n.close()


def test_overlay_forced_fold_counts_inline():
    """With the stamp ceiling at 0 every post-read commit forces the fold
    path for a cached predicate — counted as trigger=inline."""
    n = _mk_node(overlay_max_keys=0, background_rollup=False)
    n.query('{ q(func: has(follows)) { follows { uid } } }')   # prime base
    n.mutate(set_nquads='<0x7> <follows> <0x1> .', commit_now=True)
    out, _ = n.query('{ q(func: uid(0x7)) { follows { uid } } }')
    assert out["q"][0]["follows"] == [{"uid": "0x1"}]
    assert n.metrics.counter("dgraph_fold_inline_total").value >= 1
    n.close()


def test_txn_read_view_shares_pending_thunks():
    """An open txn's read view lazy-copies the base snapshot: its own
    uncommitted writes overlay, untouched predicates still resolve
    through the SHARED pending thunks."""
    n = _mk_node()
    r = n.mutate(set_nquads='<0x1> <name> "renamed" .')   # open txn
    ts = r.context.start_ts
    out, _ = n.query('{ q(func: uid(0x1)) { name age follows { name } } }',
                     start_ts=ts)
    q = out["q"][0]
    assert q["name"] == "renamed"          # own write visible
    assert q["age"] == 30                  # untouched pred resolves
    assert sorted(x["name"] for x in q["follows"]) == \
        ["bob", "carol smith"]
    n.abort(ts)
    n.close()


def test_fold_metrics_and_debug_section():
    """Pre-registration + the /debug/metrics folds section + prom
    exposition for every new fold metric name."""
    from dgraph_tpu.api.http import _serving_metrics
    from dgraph_tpu.obs import prom

    n = _mk_node()
    n.query('{ q(func: eq(name, "alice")) { name } }')
    d = _serving_metrics(n)["folds"]
    assert d["lazy_enabled"] is True
    assert d["lazy"] + d["prefetch"] >= 1
    assert d["cold_open_ms"] >= 0 and d["first_query_ms"] > 0
    text = prom.render(n.metrics)
    prom.parse(text)
    for name in ("dgraph_fold_lazy_total", "dgraph_fold_eager_total",
                 "dgraph_fold_prefetch_total", "dgraph_fold_inline_total",
                 "dgraph_fold_ms", "dgraph_fold_pending_tablets",
                 "dgraph_cold_open_ms", "dgraph_first_query_ms"):
        assert any(ln.startswith(name) or f" {name}" in ln
                   or ln.startswith(f"# TYPE {name}")
                   for ln in text.splitlines()), name
    n.close()


# ---------------------------------------------------------------------------
# LDBC generator + battery equality
# ---------------------------------------------------------------------------

def _gen_sha(tmp_path, name, seed):
    from dgraph_tpu.loader.convert import convert_ldbc
    from dgraph_tpu.models.ldbc import generate_ldbc

    d = str(tmp_path / name)
    generate_ldbc(d, sf=0.004, seed=seed)
    convert_ldbc(d, os.path.join(d, "out.rdf.gz"))
    with gzip.open(os.path.join(d, "out.rdf.gz"), "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def test_generator_determinism_same_seed_same_sha256(tmp_path):
    a = _gen_sha(tmp_path, "a", 7)
    b = _gen_sha(tmp_path, "b", 7)
    c = _gen_sha(tmp_path, "c", 8)
    assert a == b
    assert a != c


@pytest.fixture(scope="module")
def ldbc_dir(tmp_path_factory):
    """One tiny generated LDBC-shaped graph, bulk-loaded once."""
    from dgraph_tpu.loader.bulk import bulk_load
    from dgraph_tpu.loader.convert import convert_ldbc
    from dgraph_tpu.models.ldbc import generate_ldbc

    tmp = tmp_path_factory.mktemp("ldbc")
    generate_ldbc(str(tmp / "csv"), sf=0.004)
    convert_ldbc(str(tmp / "csv"), str(tmp / "snb.rdf.gz"))
    with open(str(tmp / "snb.rdf.gz.schema")) as f:
        schema = f.read()
    bulk_load(str(tmp / "snb.rdf.gz"), schema, str(tmp / "out"))
    return str(tmp / "out")


def test_battery_uid_sets_identical_host_mesh_tiered(ldbc_dir):
    """The paper's acceptance shape on the generated graph: 3-hop
    friends-of-friends result UID sets identical across the host, mesh,
    and tiered-residency serving paths."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest 8-virtual-device CPU mesh")
    fof = ('{ q(func: eq(person.id, %d)) '
           '{ knows { knows { knows { uid } } } } }')
    nodes = {
        "host": Node(dirpath=ldbc_dir),
        "mesh": Node(dirpath=ldbc_dir, mesh_devices=8, mesh_min_edges=1),
        "tiered": Node(dirpath=ldbc_dir, device_budget_mb=1),
    }

    def uids(out):
        got = set()

        def walk(rows, d):
            for row in rows:
                if d == 0:
                    got.add(row.get("uid"))
                else:
                    walk(row.get("knows", []), d - 1)

        walk(out.get("q", []), 3)
        return got

    for pid in (933, 933 + 7 * 10, 933 + 7 * 39):
        outs = {p: n.query(fof % pid)[0] for p, n in nodes.items()}
        ref = uids(outs["host"])
        for p, o in outs.items():
            assert uids(o) == ref, (pid, p)
    for n in nodes.values():
        n.close()
