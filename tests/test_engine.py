"""End-to-end query engine tests: DQL in → JSON out.

Mirrors the reference's query/query_test.go pattern (embedded single-process
cluster, golden JSON assertions; SURVEY.md §4).
"""

import numpy as np
import pytest

from dgraph_tpu.query import dql
from dgraph_tpu.query.engine import Executor, QueryError
from dgraph_tpu.storage import index as idx
from dgraph_tpu.storage.csr_build import build_snapshot
from dgraph_tpu.storage.postings import DirectedEdge, Op
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema
from dgraph_tpu.utils.types import TypeID, Val


@pytest.fixture(scope="module")
def env():
    s = Store()
    for e in parse_schema("""
        name: string @index(term, exact) @lang .
        age: int @index(int) .
        friend: uid @reverse @count .
        follows: uid .
    """):
        s.set_schema(e)
    people = {1: ("Michonne", 38), 2: ("Rick Grimes", 15), 3: ("Glenn Rhee", 15),
              4: ("Daryl Dixon", 17), 5: ("Andrea", 19), 6: ("Carl", 10)}
    for uid, (nm, age) in people.items():
        idx.add_mutation_with_index(s, DirectedEdge(uid, "name", value=Val(TypeID.STRING, nm)), 1)
        idx.add_mutation_with_index(s, DirectedEdge(uid, "age", value=Val(TypeID.INT, age)), 1)
    friends = [(1, 2), (1, 3), (1, 4), (1, 5), (2, 1), (3, 1), (4, 5), (5, 6)]
    for a, b in friends:
        fac = (("weight", Val(TypeID.FLOAT, 0.5 if (a, b) == (1, 2) else 1.0)),
               ("close", Val(TypeID.BOOL, (a, b) in [(1, 2), (1, 3)])))
        idx.add_mutation_with_index(s, DirectedEdge(a, "friend", object_uid=b, facets=fac), 1)
    idx.add_mutation_with_index(s, DirectedEdge(1, "name", value=Val(TypeID.STRING, "Michonne-fr"), lang="fr"), 1)
    s.commit(1, 2, list(s.lists.keys()))
    return s, build_snapshot(s, read_ts=3)


def run(env, q, variables=None):
    s, snap = env
    return Executor(snap, s.schema).execute(dql.parse(q, variables))


def test_basic_query(env):
    out = run(env, '{ me(func: eq(name, "Michonne")) { uid name age } }')
    assert out == {"me": [{"uid": "0x1", "name": "Michonne", "age": 38}]}


def test_children_and_nesting(env):
    out = run(env, '{ me(func: uid(1)) { name friend { name age } } }')
    me = out["me"][0]
    assert me["name"] == "Michonne"
    names = {f["name"] for f in me["friend"]}
    assert names == {"Rick Grimes", "Glenn Rhee", "Daryl Dixon", "Andrea"}


def test_filters_and_or_not(env):
    out = run(env, '''{
      me(func: uid(1)) {
        friend @filter(eq(age, 15) or eq(name, "Andrea")) { name }
      }
    }''')
    names = {f["name"] for f in out["me"][0]["friend"]}
    assert names == {"Rick Grimes", "Glenn Rhee", "Andrea"}
    out = run(env, '{ me(func: uid(1)) { friend @filter(not eq(age, 15)) { name } } }')
    names = {f["name"] for f in out["me"][0]["friend"]}
    assert names == {"Daryl Dixon", "Andrea"}


def test_root_filter(env):
    out = run(env, '{ q(func: has(friend)) @filter(ge(age, 17)) { name } }')
    names = {f["name"] for f in out["q"]}
    assert names == {"Michonne", "Daryl Dixon", "Andrea"}


def test_pagination_and_order(env):
    out = run(env, '{ q(func: has(name), orderasc: age, first: 3) { name age } }')
    assert [x["age"] for x in out["q"]] == [10, 15, 15]
    out = run(env, '{ q(func: has(name), orderdesc: age, offset: 1, first: 2) { age } }')
    assert [x["age"] for x in out["q"]] == [19, 17]


def test_count_children(env):
    out = run(env, '{ me(func: uid(1, 2)) { name fc: count(friend) } }')
    by_name = {x["name"]: x.get("fc") for x in out["me"]}
    assert by_name == {"Michonne": 4, "Rick Grimes": 1}
    out = run(env, '{ q(func: has(friend)) { count(uid) } }')
    assert out["q"] == [{"count": 5}]


def test_count_at_root(env):
    out = run(env, '{ q(func: eq(count(friend), 4)) { name } }')
    assert out["q"] == [{"name": "Michonne"}]


def test_reverse_edge(env):
    out = run(env, '{ q(func: uid(5)) { ~friend { name } } }')
    names = {x["name"] for x in out["q"][0]["~friend"]}
    assert names == {"Michonne", "Daryl Dixon"}


def test_uid_vars(env):
    out = run(env, '''{
      A as var(func: uid(1)) { friend { friend } }
      q(func: uid(A)) { name }
    }''')
    assert {x["name"] for x in out["q"]} == {"Michonne"}  # only 1 in A... wait
    # A = uids of var block root = [1]; check friend-of-friend var instead
    out = run(env, '''{
      var(func: uid(1)) { friend { B as friend } }
      q(func: uid(B), orderasc: name) { name }
    }''')
    assert [x["name"] for x in out["q"]] == ["Andrea", "Carl", "Michonne"]


def test_value_vars_and_math(env):
    out = run(env, '''{
      var(func: uid(1)) { friend { a as age } }
      q(func: uid(2, 3), orderasc: name) {
        name
        doubled: math(a * 2)
      }
    }''')
    by = {x["name"]: x["doubled"] for x in out["q"]}
    assert by == {"Glenn Rhee": 30, "Rick Grimes": 30}


def test_aggregates(env):
    out = run(env, '''{
      var(func: has(name)) { a as age }
      q() {
        mn: min(val(a)) mx: max(val(a)) total: sum(val(a)) mean: avg(val(a))
      }
    }''')
    vals = {}
    for obj in out["q"]:
        vals.update(obj)
    assert vals["mn"] == 10 and vals["mx"] == 38
    assert vals["total"] == 38 + 15 + 15 + 17 + 19 + 10
    assert vals["mean"] == pytest.approx(19.0)


def test_eq_valvar_at_root(env):
    out = run(env, '''{
      var(func: has(name)) { a as age }
      q(func: eq(val(a), 15), orderasc: name) { name }
    }''')
    assert [x["name"] for x in out["q"]] == ["Glenn Rhee", "Rick Grimes"]


def test_cascade(env):
    # Carl(6) has no friend edges: cascade drops him
    out = run(env, '{ q(func: has(name)) @cascade { name friend { name } } }')
    names = {x["name"] for x in out["q"]}
    assert names == {"Michonne", "Rick Grimes", "Glenn Rhee", "Daryl Dixon", "Andrea"}


def test_normalize(env):
    out = run(env, '''{
      q(func: uid(1)) @normalize {
        n: name
        friend { fn: name }
      }
    }''')
    rows = out["q"]
    assert all(r.get("n") == "Michonne" for r in rows)
    assert {r["fn"] for r in rows} == {"Rick Grimes", "Glenn Rhee", "Daryl Dixon", "Andrea"}


def test_groupby(env):
    out = run(env, '''{
      q(func: has(name)) @groupby(age) { count(uid) }
    }''')
    groups = {g["age"]: g["count"] for g in out["q"][0]["@groupby"]}
    assert groups == {38: 1, 15: 2, 17: 1, 19: 1, 10: 1}


def test_recurse(env):
    out = run(env, '''{
      q(func: uid(1)) @recurse(depth: 2) { name friend }
    }''')
    me = out["q"][0]
    assert me["name"] == "Michonne"
    level1 = {f["name"] for f in me["friend"]}
    assert level1 == {"Rick Grimes", "Glenn Rhee", "Daryl Dixon", "Andrea"}
    # depth 2: Rick's friend = Michonne (edge 1->2 seen, 2->1 new)
    rick = [f for f in me["friend"] if f["name"] == "Rick Grimes"][0]
    assert {f["name"] for f in rick.get("friend", [])} == {"Michonne"}


def test_shortest_path(env):
    out = run(env, '''{
      path as shortest(from: 0x1, to: 0x6) { friend }
      path(func: uid(path), orderasc: name) { name }
    }''')
    p = out["_path_"][0]
    assert p["uid"] == "0x1"
    assert p["friend"][0]["uid"] == "0x5"
    assert p["friend"][0]["friend"][0]["uid"] == "0x6"
    assert {x["name"] for x in out["path"]} == {"Michonne", "Andrea", "Carl"}


def test_shortest_path_weighted(env):
    out = run(env, '''{
      sp as shortest(from: 0x2, to: 0x5, numpaths: 2) { friend @facets(weight) }
      q(func: uid(sp)) { name }
    }''')
    paths = out["_path_"]
    assert len(paths) == 2
    assert paths[0]["_weight_"] <= paths[1]["_weight_"]


def test_facets_output(env):
    out = run(env, '{ q(func: uid(1)) { friend @facets(close) { name } } }')
    friends = out["q"][0]["friend"]
    close = {f["name"]: f.get("friend|close") for f in friends}
    assert close["Rick Grimes"] is True and close["Andrea"] is False


def test_facet_filter(env):
    out = run(env, '{ q(func: uid(1)) { friend @facets(eq(close, true)) { name } } }')
    names = {f["name"] for f in out["q"][0]["friend"]}
    assert names == {"Rick Grimes", "Glenn Rhee"}


def test_lang(env):
    out = run(env, '{ q(func: uid(1)) { name@fr } }')
    assert out["q"] == [{"name@fr": "Michonne-fr"}]


def test_graphql_vars(env):
    out = run(env, 'query t($n: string) { q(func: eq(name, $n)) { age } }',
              variables={"$n": "Andrea"})
    assert out["q"] == [{"age": 19}]


def test_edge_budget(env):
    s, snap = env
    import dgraph_tpu.query.engine as eng

    old = eng.MAX_QUERY_EDGES
    eng.MAX_QUERY_EDGES = 2
    try:
        with pytest.raises(QueryError, match="edge budget"):
            Executor(snap, s.schema).execute(
                dql.parse("{ q(func: has(name)) { friend { friend } } }"))
    finally:
        eng.MAX_QUERY_EDGES = old


def test_missing_var_errors(env):
    with pytest.raises(QueryError, match="missing variable"):
        run(env, "{ q(func: uid(NOPE)) { name } }")


def test_leaf_child_filter(env):
    # regression: @filter on a leaf child (no sub-block) must prune results
    out = run(env, '{ q(func: uid(1)) { friend @filter(eq(age, 15)) } }')
    uids = {f["uid"] for f in out["q"][0]["friend"]}
    assert uids == {"0x2", "0x3"}


def test_child_pagination_with_filter(env):
    out = run(env, '{ q(func: uid(1)) { friend @filter(not eq(age, 10)) (first: 2) { name } } }')
    assert len(out["q"][0]["friend"]) == 2


def test_math_division_twice(env):
    # regression: two '/' in one query must not lex as a regex literal
    out = run(env, '''{
      var(func: uid(1)) { a as age }
      q(func: uid(1)) { half: math(a / 2 / 1) }
    }''')
    assert out["q"][0]["half"] == 19.0


def test_uid_in_hex(env):
    out = run(env, '{ q(func: has(friend)) @filter(uid_in(friend, 0x6)) { name } }')
    assert {x["name"] for x in out["q"]} == {"Andrea"}


def test_uid_var_in_filter(env):
    # regression: uid(x) in @filter must register the var dependency even when
    # the defining block comes later in the query text
    out = run(env, '''{
      q(func: has(name)) @filter(uid(a)) { name }
      a as var(func: eq(age, 15)) { uid }
    }''')
    assert {x["name"] for x in out["q"]} == {"Rick Grimes", "Glenn Rhee"}


def test_negative_first(env):
    out = run(env, '{ q(func: has(name), orderasc: age, first: -2) { age } }')
    assert [x["age"] for x in out["q"]] == [19, 38]


def test_orderdesc_string_prefix(env):
    # regression: descending string order with prefix pairs
    s, snap = env
    out = run(env, '{ q(func: eq(age, 15), orderdesc: name) { name } }')
    assert [x["name"] for x in out["q"]] == ["Rick Grimes", "Glenn Rhee"]


def test_eq_list_form_valvar(env):
    # regression: eq(val(x), [v1, v2]) must flatten at parse time so the
    # value-var compare path matches ANY listed value
    out = run(env, '''{
      v as var(func: has(name)) { a as age }
      q(func: eq(val(a), [15, 17]), orderasc: val(a)) @filter(uid(v)) { name }
    }''')
    assert [x["name"] for x in out["q"]] == [
        "Rick Grimes", "Glenn Rhee", "Daryl Dixon"]


def test_eq_list_form_root(env):
    out = run(env, '{ q(func: eq(name, ["Andrea", "Carl"]), orderasc: name) { name } }')
    assert [x["name"] for x in out["q"]] == ["Andrea", "Carl"]


def test_eq_empty_list(env):
    # degenerate eq(pred, []) matches nothing instead of crashing
    out = run(env, '{ q(func: eq(name, [])) { name } }')
    assert out == {}


def test_two_math_var_defs_one_block(env):
    # regression: two `x as math(...)` defs in one block must not collide on
    # the "math" output key
    out = run(env, '''{
      q(func: uid(1)) { a as math(1 + 1) b as math(2 + 2) name }
    }''')
    row = out["q"][0]
    assert row["a"] == 2 and row["b"] == 4 and row["name"] == "Michonne"


def test_eq_count_list_form(env):
    # eq(count(pred), [n1, n2]) matches ANY listed degree — root and filter
    out = run(env, '{ q(func: eq(count(friend), [1, 4]), orderasc: name) { name } }')
    assert [x["name"] for x in out["q"]] == [
        "Andrea", "Daryl Dixon", "Glenn Rhee", "Michonne", "Rick Grimes"]
    out = run(env, '''{
      q(func: has(name), orderasc: name) @filter(eq(count(friend), [1, 4])) { name }
    }''')
    assert [x["name"] for x in out["q"]] == [
        "Andrea", "Daryl Dixon", "Glenn Rhee", "Michonne", "Rick Grimes"]


def test_facet_eq_list_form(env):
    # @facets(eq(key, [v1, v2])) matches ANY listed facet value
    out = run(env, '''{
      q(func: uid(1)) { friend @facets(eq(close, [true, false])) { name } }
    }''')
    names = {x["name"] for x in out["q"][0]["friend"]}
    assert names == {"Andrea", "Daryl Dixon", "Glenn Rhee", "Rick Grimes"}
    out = run(env, '''{
      q(func: uid(1)) { friend @facets(eq(close, [false])) { name } }
    }''')
    names = {x["name"] for x in out["q"][0]["friend"]}
    assert names == {"Andrea", "Daryl Dixon"}


def test_ineq_missing_rhs_errors(env):
    with pytest.raises(Exception):
        run(env, '{ q(func: lt(age)) { name } }')


def test_regexp_case_insensitive():
    # values store raw-case trigrams; /rick/i must still find "Rick Grimes"
    # through the case-variant trigram probe (not a full scan)
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="name: string @index(trigram) .")
    n.mutate(set_nquads="""
        _:a <name> "Rick Grimes" .
        _:b <name> "GLENN RHEE" .
        _:c <name> "daryl dixon" .
    """, commit_now=True)
    out, _ = n.query('{ q(func: regexp(name, /rick/i)) { name } }')
    assert [x["name"] for x in out["q"]] == ["Rick Grimes"]
    out, _ = n.query('{ q(func: regexp(name, /GRIMES|rhee/i)) { name } }')
    assert {x["name"] for x in out["q"]} == {"Rick Grimes", "GLENN RHEE"}
    out, _ = n.query('{ q(func: regexp(name, /dixon$/i)) { name } }')
    assert [x["name"] for x in out["q"]] == ["daryl dixon"]


def test_lang_fallback_chain():
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="name: string @index(exact) @lang .")
    n.mutate(set_nquads='_:a <name> "Alice" .\n_:a <name> "Alicia"@es .\n'
                        '_:b <name> "Bobby"@en .', commit_now=True)
    out, _ = n.query('{ q(func: eq(name, "Alice")) { name@fr:es:. } }')
    assert out == {"q": [{"name@fr:es:.": "Alicia"}]}
    out, _ = n.query('{ q(func: has(name)) { name@fr:. } }')
    assert {r["name@fr:."] for r in out["q"]} == {"Alice", "Bobby"}
    out, _ = n.query('{ q(func: has(name)) { name@fr:de } }')
    assert out == {}                      # chain without "." can miss


def test_count_reverse_at_root(env):
    # eq(count(~friend), n): degree compare over the REVERSE index
    out = run(env, '{ q(func: eq(count(~friend), 2), orderasc: name) { name } }')
    assert [x["name"] for x in out["q"]] == ["Andrea", "Michonne"]


def test_uid_in_list_form(env):
    out = run(env, '{ q(func: has(friend)) @filter(uid_in(friend, [0x2, 0x6])) '
                   '{ name } }')
    assert {x["name"] for x in out["q"]} == {"Michonne", "Andrea"}


def test_has_reverse_at_root(env):
    # has(~friend): nodes with INCOMING friend edges (Carl has none outgoing
    # but one incoming; uid2/3 have incoming from Michonne, etc.)
    out = run(env, '{ q(func: has(~friend), orderasc: name) { name } }')
    assert [x["name"] for x in out["q"]] == [
        "Andrea", "Carl", "Daryl Dixon", "Glenn Rhee", "Michonne",
        "Rick Grimes"]


def test_bad_lang_chain_rejected():
    from dgraph_tpu.query.dql import ParseError, parse
    with pytest.raises(ParseError):
        parse('{ q(func: has(name)) { name@en:2 } }')
    with pytest.raises(ParseError):
        parse('{ q(func: has(name)) { name@en: } }')


def test_checkpwd_child():
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="name: string @index(exact) .\npwd: password .")
    n.mutate(set_nquads='_:a <name> "A" .\n'
                        '_:a <pwd> "secret123"^^<xs:password> .',
             commit_now=True)
    out, _ = n.query('{ q(func: eq(name, "A")) { checkpwd(pwd, "secret123") } }')
    assert out == {"q": [{"checkpwd(pwd)": True}]}
    out, _ = n.query('{ q(func: eq(name, "A")) { checkpwd(pwd, "wrong1") } }')
    assert out == {"q": [{"checkpwd(pwd)": False}]}


def test_fulltext_stemming_inflections():
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="bio: string @index(fulltext) .\n"
                        "name: string @index(exact) .")
    n.mutate(set_nquads='_:a <name> "A" .\n'
                        '_:a <bio> "loves hiking in the mountains" .\n'
                        '_:b <name> "B" .\n_:b <bio> "agreed to run fast" .',
             commit_now=True)
    out, _ = n.query('{ q(func: alloftext(bio, "mountain hike")) { name } }')
    assert out == {"q": [{"name": "A"}]}
    out, _ = n.query('{ q(func: anyoftext(bio, "agree running")) { name } }')
    assert out == {"q": [{"name": "B"}]}


def test_math_comparisons_and_cond():
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="name: string @index(exact) .\nscore: float .")
    n.mutate(set_nquads='_:a <name> "hi" .\n_:a <score> "7.5"^^<xs:float> .\n'
                        '_:b <name> "lo" .\n_:b <score> "3.0"^^<xs:float> .',
             commit_now=True)
    out, _ = n.query('''{
      var(func: has(score)) { s as score
        c as math(cond(s > 5.0, 1, 0))
        d as math(cond(s <= 3.0, 1, 0)) }
      q(func: has(score), orderasc: name) { name val(c) val(d) }
    }''')
    assert out["q"] == [{"name": "hi", "val(c)": 1, "val(d)": 0},
                       {"name": "lo", "val(c)": 0, "val(d)": 1}]


def test_facet_filter_not_and_parens(env):
    out = run(env, '''{
      q(func: uid(1)) { friend @facets(NOT eq(close, true)) { name } }
    }''')
    names = {x["name"] for x in out["q"][0]["friend"]}
    assert names == {"Daryl Dixon", "Andrea"}
    out = run(env, '''{
      q(func: uid(1)) { friend @facets((eq(close, true))) { name } }
    }''')
    names = {x["name"] for x in out["q"][0]["friend"]}
    assert names == {"Rick Grimes", "Glenn Rhee"}


def test_list_value_predicates():
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="nick: [string] @index(term) .\n"
                        "name: string @index(exact) .")
    n.mutate(set_json={"name": "Jay", "nick": ["jj", "jbird"]},
             commit_now=True)
    out, _ = n.query('{ q(func: eq(name, "Jay")) { nick } }')
    assert out == {"q": [{"nick": ["jbird", "jj"]}]}
    out, _ = n.query('{ q(func: anyofterms(nick, "jbird")) { name } }')
    assert out == {"q": [{"name": "Jay"}]}
    ju = n.query('{ q(func: eq(name, "Jay")) { uid } }')[0]["q"][0]["uid"]
    n.mutate(del_nquads=f'<{ju}> <nick> "jj" .', commit_now=True)
    out, _ = n.query('{ q(func: eq(name, "Jay")) { nick } }')
    assert out == {"q": [{"nick": "jbird"}]}
    n.mutate(del_nquads=f'<{ju}> <nick> * .', commit_now=True)
    out, _ = n.query('{ q(func: has(nick)) { uid } }')
    assert out == {}


def test_value_edge_facets():
    from dgraph_tpu.api.server import Node
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    n.mutate(set_nquads='_:a <name> "Fay" (since=2021-01-01T00:00:00, '
                        'by="import") .', commit_now=True)
    out, _ = n.query('{ q(func: eq(name, "Fay")) { name @facets } }')
    row = out["q"][0]
    assert row["name"] == "Fay" and row["name|by"] == "import"
    assert row["name|since"].startswith("2021-01-01")
    out, _ = n.query('{ q(func: eq(name, "Fay")) { name @facets(src: by) } }')
    assert out["q"][0] == {"name": "Fay", "name|src": "import"}


def test_groupby_numeric_fast_path_matches_generic():
    """Single-numeric-key groupby takes the vectorized path and must equal
    the generic per-uid path exactly (keys, order, members, aggregates)."""
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.query import groupby as gbmod

    n = Node()
    n.alter(schema_text="name: string .\nage: int .\nscore: float .")
    quads = []
    for i in range(1, 40):
        quads.append(f'<0x{i:x}> <name> "p{i}" .')
        quads.append(f'<0x{i:x}> <age> "{20 + i % 5}"^^<xs:int> .')
        quads.append(f'<0x{i:x}> <score> "{i}.25"^^<xs:float> .')
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    q = ('{ q(func: has(name)) @groupby(age) { count(uid) m : max(val(s)) } '
         '  var(func: has(name)) { s as score } }')
    spy = {"n": 0}
    real = gbmod._numeric_single_key_groups

    def counting(*a, **kw):
        out = real(*a, **kw)
        if out is not None:
            spy["n"] += 1
        return out

    gbmod._numeric_single_key_groups = counting
    try:
        fast, _ = n.query(q)
    finally:
        gbmod._numeric_single_key_groups = real
    assert spy["n"] == 1, "fast path was not taken"
    gbmod._numeric_single_key_groups = lambda *a, **kw: None
    try:
        generic, _ = n.query(q)
    finally:
        gbmod._numeric_single_key_groups = real
    assert fast == generic
    counts = {g["age"]: g["count"] for g in fast["q"][0]["@groupby"]}
    assert sum(counts.values()) == 39 and len(counts) == 5


def test_groupby_fast_path_exactness_guards():
    """Cases where the float64 mirror is lossy/ambiguous must take the
    generic path and keep exact semantics (review r4)."""
    from dgraph_tpu.api.server import Node

    n = Node()
    n.alter(schema_text="big: int .\nx: float .\nwhen: datetime .")
    n.mutate(set_nquads=f'''
        <0x1> <big> "{2**53}"^^<xs:int> .
        <0x2> <big> "{2**53 + 1}"^^<xs:int> .
        <0x3> <x> "NaN"^^<xs:float> .
        <0x4> <x> "1.5"^^<xs:float> .
        <0x5> <when> "2021-01-01T00:00:00+00:00" .
        <0x6> <when> "2021-01-01T01:00:00+01:00" .
    ''', commit_now=True)
    # distinct int64 keys above 2^53 stay distinct
    out, _ = n.query('{ q(func: has(big)) @groupby(big) { count(uid) } }')
    assert len(out["q"][0]["@groupby"]) == 2
    # stored float NaN keeps its group
    out, _ = n.query('{ q(func: has(x)) @groupby(x) { count(uid) } }')
    assert len(out["q"][0]["@groupby"]) == 2
    # same instant, different tz offsets: distinct display keys
    out, _ = n.query('{ q(func: has(when)) @groupby(when) { count(uid) } }')
    assert len(out["q"][0]["@groupby"]) == 2
