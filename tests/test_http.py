"""HTTP API round-trip: alter → mutate → query → txn commit/abort over real
sockets against a temp-dir store.

Reference: dgraph/cmd/server/run.go:246-261 endpoint registration + the
{"data": ...}/{"errors": ...} envelope of http.go.
"""

import json
import urllib.request

import pytest

from dgraph_tpu.api.http import serve_forever
from dgraph_tpu.api.server import Node


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    node = Node(dirpath=str(tmp_path_factory.mktemp("pdir")))
    srv = serve_forever(node, port=0)           # ephemeral port
    port = srv.server_address[1]
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    node.close()


def _post(base, path, body, ctype="application/rdf", headers=None):
    req = urllib.request.Request(
        base + path, data=body.encode(), method="POST",
        headers={"Content-Type": ctype, **(headers or {})})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    with urllib.request.urlopen(base + path) as r:
        return r.status, json.loads(r.read())


def test_full_round_trip(server):
    st, out = _post(server, "/alter",
                    "name: string @index(exact) .\nfriend: uid @reverse .")
    assert st == 200 and out["data"]["code"] == "Success"

    st, out = _post(server, "/mutate?commitNow=true", '''
    {
      set {
        _:a <name> "Ada" .
        _:b <name> "Byron" .
        _:a <friend> _:b .
      }
    }''')
    assert st == 200
    uids = out["data"]["uids"]
    assert set(uids) == {"a", "b"}
    assert out["extensions"]["txn"]["commit_ts"] > 0

    st, out = _post(server, "/query",
                    '{ q(func: eq(name, "Ada")) { name friend { name } } }')
    assert st == 200
    assert out["data"]["q"][0]["friend"][0]["name"] == "Byron"

    # JSON query body with variables
    st, out = _post(server, "/query", json.dumps({
        "query": 'query me($n: string) { q(func: eq(name, $n)) { name } }',
        "variables": {"$n": "Byron"}}), ctype="application/json")
    assert st == 200 and out["data"]["q"][0]["name"] == "Byron"


def test_txn_commit_and_abort(server):
    # open txn, mutate, commit via /commit
    st, out = _post(server, "/mutate", '{ set { <0x50> <name> "T1" . } }')
    assert st == 200
    start_ts = out["extensions"]["txn"]["start_ts"]
    st, out = _post(server, f"/commit/?startTs={start_ts}", "")
    assert st == 200 and out["extensions"]["txn"]["commit_ts"] > start_ts

    st, out = _post(server, "/query", '{ q(func: uid(0x50)) { name } }')
    assert out["data"]["q"][0]["name"] == "T1"

    # abort path: buffered write never becomes visible
    st, out = _post(server, "/mutate", '{ set { <0x51> <name> "T2" . } }')
    start_ts = out["extensions"]["txn"]["start_ts"]
    st, out = _post(server, f"/abort/?startTs={start_ts}", "")
    assert st == 200
    st, out = _post(server, "/query", '{ q(func: uid(0x51)) { name } }')
    assert out["data"].get("q", []) == []


def test_json_mutation_over_http(server):
    st, out = _post(server, "/mutate?commitNow=true",
                    json.dumps({"set": [{"name": "Judy", "score": 7}]}),
                    ctype="application/json")
    assert st == 200
    st, out = _post(server, "/query", '{ q(func: eq(name, "Judy")) { score } }')
    assert out["data"]["q"][0]["score"] == 7


def test_conflict_maps_to_409(server):
    _post(server, "/alter", "bal: int .")
    _post(server, "/mutate?commitNow=true",
          '{ set { <0x60> <bal> "1"^^<xs:int> . } }')
    st, o1 = _post(server, "/mutate", '{ set { <0x60> <bal> "2"^^<xs:int> . } }')
    st, o2 = _post(server, "/mutate", '{ set { <0x60> <bal> "3"^^<xs:int> . } }')
    ts1 = o1["extensions"]["txn"]["start_ts"]
    ts2 = o2["extensions"]["txn"]["start_ts"]
    st, _ = _post(server, f"/commit/?startTs={ts1}", "")
    assert st == 200
    st, out = _post(server, f"/commit/?startTs={ts2}", "")
    assert st == 409 and out["errors"][0]["code"] == "ErrorAborted"


def test_health_and_state(server):
    st, h = _get(server, "/health")
    assert st == 200 and h["status"] == "healthy"
    st, s = _get(server, "/state")
    assert st == 200 and "groups" in s


def test_error_envelope(server):
    st, out = _post(server, "/query", "{ bad query ")
    assert st == 400 and out["errors"][0]["code"] == "ErrorInvalidRequest"


def test_admin_export_and_memory(tmp_path):
    import urllib.request

    from dgraph_tpu.api.http import make_server
    from dgraph_tpu.api.server import Node

    node = Node(str(tmp_path / "p"))
    node.alter(schema_text="name: string @index(exact) .")
    node.mutate(set_nquads='_:a <name> "x" .', commit_now=True)
    srv = make_server(node, "127.0.0.1", 0)
    import threading

    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/admin/export", data=b"", method="POST")
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert out["code"] == "Success" and out["quads"] >= 1
        import os

        assert os.path.exists(out["file"])

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/admin/config/memory_mb", data=b"512",
            method="POST")
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert out["code"] == "Success" and "bytes" in out
    finally:
        srv.shutdown()
        node.close()


def test_admin_shutdown(tmp_path):
    import urllib.request

    from dgraph_tpu.api.http import make_server
    from dgraph_tpu.api.server import Node

    node = Node()
    srv = make_server(node, "127.0.0.1", 0)
    import threading

    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    port = srv.server_address[1]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/admin/shutdown", data=b"", method="POST")
    out = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert out["code"] == "Success"
    t.join(timeout=10)
    assert not t.is_alive()
    node.close()
