"""Multi-zero replication + election (VERDICT r4 #3, zero half).

Three ZeroService instances with ZeroReplica roles: the leader quorum-ships
its durable state on every persist, standbys reject coordination RPCs,
clients rotate transparently, and when the leader dies a standby wins the
ballot, recovers Zero from the replicated state, and serves — lease
ceilings guarantee no ts/uid reuse across the failover (assign.go
semantics: at most one lease block burns)."""

import time

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.coord.zero import Zero
from dgraph_tpu.protos import internal_pb2 as ipb
from dgraph_tpu.coord.zero_service import (ZeroClient, ZeroReplica,
                                           ZeroService, serve_zero)


def _mk_zeros(tmp_path, n=3, fast=True):
    # two-phase: bind ports first so every replica knows the full member set
    import socket

    socks, addrs = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        addrs.append(f"127.0.0.1:{s.getsockname()[1]}")
        socks.append(s)
    for s in socks:
        s.close()

    svcs, servers, reps = [], [], []
    for i in range(n):
        d = str(tmp_path / f"z{i}")
        import os

        os.makedirs(d, exist_ok=True)
        zero = Zero(n_groups=1, dirpath=d)
        svc = ZeroService(zero)
        rep = ZeroReplica(svc, d, addrs[i], addrs, bootstrap_leader=i == 0)
        if fast:
            rep.PING_S = 0.1
            rep.ELECTION_TIMEOUT_S = (0.4, 0.8)
        server, _port, svc = serve_zero(zero, addrs[i], svc=svc)
        rep.start()
        svcs.append(svc)
        servers.append(server)
        reps.append(rep)
    return svcs, servers, reps, addrs


def test_standby_rejects_and_client_rotates(tmp_path):
    svcs, servers, reps, addrs = _mk_zeros(tmp_path)
    try:
        # direct call to a standby fails with FAILED_PRECONDITION
        standby = ZeroClient(addrs[1])
        with pytest.raises(grpc.RpcError) as ei:
            standby.new_txn()
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        standby.close()
        # a rotating client pointed at a standby first still succeeds
        c = ZeroClient(",".join([addrs[1], addrs[0]]))
        ts = c.new_txn()
        assert ts > 0
        c.close()
    finally:
        for s in servers:
            s.stop(0)
        for r in reps:
            r.stop()


def test_zero_failover_preserves_lease_ceilings(tmp_path):
    svcs, servers, reps, addrs = _mk_zeros(tmp_path)
    try:
        c = ZeroClient(",".join(addrs))
        ts1 = c.timestamps(5)
        uid1 = c.assign_uids(7)
        assert ts1 > 0 and uid1 > 0
        # ships reached the standbys
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if all(r.seq > 0 for r in reps[1:]):
                break
            time.sleep(0.05)
        assert all(r.seq > 0 for r in reps[1:])

        servers[0].stop(0)         # kill the zero leader
        reps[0].stop()
        reps[0].is_leader = False

        deadline = time.monotonic() + 6
        new = None
        while time.monotonic() < deadline:
            up = [i for i in (1, 2) if reps[i].is_leader]
            if up:
                new = up[0]
                break
            time.sleep(0.05)
        assert new is not None, "no standby won the zero ballot"

        # the rotating client keeps working; leases never go backwards
        ts2 = c.timestamps(1)
        uid2 = c.assign_uids(1)
        assert ts2 > ts1
        assert uid2 > uid1
        c.close()
    finally:
        for s in servers:
            s.stop(0)
        for r in reps:
            r.stop()


def test_single_zero_mode_unaffected(tmp_path):
    """No replica attached: handlers serve as before (no leader gate)."""
    zero = Zero(n_groups=1)
    server, port, _svc = serve_zero(zero, "127.0.0.1:0")
    try:
        c = ZeroClient(f"127.0.0.1:{port}")
        assert c.new_txn() > 0
        c.close()
    finally:
        server.stop(0)


def test_standby_adopts_newer_term_ship_with_lower_seq(tmp_path):
    """Satellite regression (PR 3): a standby that alone received a
    quorum-failed ship (inflated seq) must accept a strictly-newer term's
    full-state replace and ADOPT the leader's lower seq — the old
    `msg.seq < self.seq` check rejected every subsequent ship and let the
    standby later resurrect the unacked state by winning an election."""
    import os

    d = str(tmp_path / "zs")
    os.makedirs(d, exist_ok=True)
    svc = ZeroService(Zero(n_groups=1))
    rep = ZeroReplica(svc, d, "127.0.0.1:1", ["127.0.0.1:1"],
                      bootstrap_leader=False)
    # term-1 leader ships seq 5 — then dies before quorum-acking it
    r = rep.zero_ship(ipb.ZeroShipRequest(term=1, seq=5,
                                          state_json="{\"a\":1}"), None)
    assert r.ok and rep.seq == 5
    # same-term stale re-ship still rejected
    r = rep.zero_ship(ipb.ZeroShipRequest(term=1, seq=3,
                                          state_json="{}"), None)
    assert not r.ok
    # the NEW term-2 leader (elected without the unacked seq-5 state)
    # ships its full state at seq 1: must be accepted, seq adopted
    r = rep.zero_ship(ipb.ZeroShipRequest(term=2, seq=1,
                                          state_json="{\"b\":2}"), None)
    assert r.ok and rep.term == 2 and rep.seq == 1
    with open(os.path.join(d, "zero_state.json")) as f:
        assert f.read() == "{\"b\":2}"
    # a vote request keyed on the adopted seq no longer out-ranks peers
    v = rep.zero_vote(ipb.ZeroVoteRequest(term=3, seq=1,
                                          candidate="x"), None)
    assert v.granted
