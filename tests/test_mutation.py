"""Mutations end-to-end: DQL/RDF/JSON → store → fresh snapshot → query.

Reference: query/mutation.go (AssignUids/ToInternal/ApplyMutations),
edgraph/nquads_from_json.go, edgraph/server.go Mutate.
"""

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import mutation as mut
from dgraph_tpu.query import rdf
from dgraph_tpu.storage.postings import Op


@pytest.fixture
def node():
    n = Node()
    n.alter(schema_text="""
        name: string @index(exact) .
        age: int @index(int) .
        friend: uid @reverse .
    """)
    return n


def test_set_mutation_via_dql_entry(node):
    out, mres = node.run_request('''
    {
      set {
        _:alice <name> "Alice" .
        _:alice <age> "25"^^<xs:int> .
        _:bob <name> "Bob" .
        _:alice <friend> _:bob .
      }
    }''')
    assert mres is not None and mres.context.commit_ts > 0
    alice = mres.uids["_:alice"]
    out, _ = node.query('{ q(func: eq(name, "Alice")) { uid name age friend { name } } }')
    assert out["q"][0]["name"] == "Alice"
    assert out["q"][0]["uid"] == hex(alice)
    assert out["q"][0]["friend"][0]["name"] == "Bob"
    # reverse edge maintained
    out, _ = node.query('{ q(func: eq(name, "Bob")) { ~friend { name } } }')
    assert out["q"][0]["~friend"][0]["name"] == "Alice"


def test_read_ts_visibility(node):
    # a pre-commit read_ts must not see the mutation; a post-commit one must
    pre_ts = node.zero.oracle.read_ts()
    res = node.mutate(set_nquads='_:x <name> "Carol" .', commit_now=False)
    out, _ = node.query('{ q(func: eq(name, "Carol")) { name } }')
    assert "q" not in out or out["q"] == []      # uncommitted: invisible
    node.commit(res.context.start_ts)
    out, _ = node.query('{ q(func: eq(name, "Carol")) { name } }',
                        start_ts=pre_ts)
    assert "q" not in out or out["q"] == []      # old snapshot: still invisible
    out, _ = node.query('{ q(func: eq(name, "Carol")) { name } }')
    assert out["q"][0]["name"] == "Carol"        # fresh snapshot: visible


def test_delete_and_star(node):
    node.mutate(set_nquads='''
        <0x100> <name> "Dave" .
        <0x100> <age> "40"^^<xs:int> .
        <0x100> <friend> <0x101> .
        <0x101> <name> "Erin" .
    ''', commit_now=True)
    # S P * : drop all values of one predicate
    node.mutate(del_nquads='<0x100> <name> * .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x100)) { name age } }')
    assert "name" not in out["q"][0] and out["q"][0]["age"] == 40
    # S * * : drop the whole node
    node.mutate(del_nquads='<0x100> * * .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x100)) { name age friend { name } } }')
    assert out.get("q", [{}])[0].get("age") is None


def test_json_mutation(node):
    res = node.mutate(set_json={
        "name": "Frank",
        "age": 33,
        "friend": [{"name": "Grace", "age": 31}],
        "friend|weight": 0.9,
    }, commit_now=True)
    assert len(res.uids) == 2
    out, _ = node.query('{ q(func: eq(name, "Frank")) { name age friend @facets { name } } }')
    q = out["q"][0]
    assert q["age"] == 33
    assert q["friend"][0]["name"] == "Grace"
    assert q["friend"][0]["friend|weight"] == 0.9


def test_json_delete(node):
    node.mutate(set_json={"uid": "0x200", "name": "Heidi", "age": 50},
                commit_now=True)
    node.mutate(delete_json={"uid": "0x200", "age": None}, commit_now=True)
    out, _ = node.query('{ q(func: uid(0x200)) { name age } }')
    assert out["q"][0] == {"name": "Heidi"}
    node.mutate(delete_json={"uid": "0x200"}, commit_now=True)
    out, _ = node.query('{ q(func: uid(0x200)) { name } }')
    assert out.get("q", [{}])[0].get("name") is None


def test_blank_node_assignment():
    nq = rdf.parse('_:a <friend> _:b .\n_:b <friend> _:a .')

    class FakeLease:
        def assign(self, n):
            return 100, 100 + n - 1

    m = mut.assign_uids(nq, FakeLease())
    assert m == {"_:a": 100, "_:b": 101}
    edges = mut.to_edges(nq, m)
    assert edges[0].subject == 100 and edges[0].object_uid == 101


def test_alter_reindex(node):
    node.mutate(set_nquads='<0x1> <title> "hello world" .', commit_now=True)
    with pytest.raises(Exception):
        node.query('{ q(func: anyofterms(title, "hello")) { title } }')
    node.alter(schema_text="title: string @index(term) .")
    out, _ = node.query('{ q(func: anyofterms(title, "hello")) { title } }')
    assert out["q"][0]["title"] == "hello world"


def test_drop_attr_and_all(node):
    node.mutate(set_nquads='<0x1> <name> "X" .\n<0x1> <age> "9"^^<xs:int> .',
                commit_now=True)
    node.alter(drop_attr="age")
    out, _ = node.query('{ q(func: has(name)) { name age } }')
    assert out["q"][0] == {"name": "X"}
    node.alter(drop_all=True)
    out, _ = node.query('{ q(func: has(name)) { name } }')
    assert "q" not in out or out["q"] == []


def test_uid_lease_recovery(tmp_path):
    d = str(tmp_path / "p")
    n1 = Node(dirpath=d)
    res = n1.mutate(set_nquads='_:x <name> "A" .', commit_now=True)
    first_uid = res.uids["_:x"]
    n1.close()
    n2 = Node(dirpath=d)
    res2 = n2.mutate(set_nquads='_:y <name> "B" .', commit_now=True)
    assert res2.uids["_:y"] > first_uid     # no uid reuse after restart
    out, _ = n2.query('{ q(func: has(name)) { name } }')
    assert {x["name"] for x in out["q"]} == {"A", "B"}
    n2.close()
