"""Regression tests for the round-2 advisor findings.

1. Queries at an open txn's start_ts see the txn's own uncommitted writes
   (reference posting/list.go:528 — StartTs == readTs visibility).
2. Oracle conflict/abort state is purged below the min-pending watermark
   (reference dgraph/cmd/zero/oracle.go:112-160 purgeBelow).
3. Oracle.track refuses to resurrect decided txns.
4. Incremental snapshots: a commit touching one predicate rebuilds only
   that predicate (device-array identity for untouched predicates).
"""

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import Oracle, TxnNotFound


def test_open_txn_sees_own_writes():
    node = Node()
    node.alter(schema_text='name: string @index(exact) .\nage: int .')
    node.mutate(set_nquads='<0x1> <name> "alice" .', commit_now=True)

    ctx = node.new_txn()
    node.mutate(set_nquads='<0x1> <age> "30"^^<xs:int> .\n<0x2> <name> "bob" .',
                start_ts=ctx.start_ts)

    # same txn reads: must see both uncommitted writes
    out, _ = node.query(
        '{ q(func: eq(name, "alice")) { name age } }', start_ts=ctx.start_ts)
    assert out["q"] == [{"name": "alice", "age": 30}]
    out, _ = node.query(
        '{ q(func: eq(name, "bob")) { name } }', start_ts=ctx.start_ts)
    assert out["q"] == [{"name": "bob"}]

    # an independent reader must NOT see them
    out, _ = node.query('{ q(func: has(age)) { age } }')
    assert out.get("q", []) == []

    # after commit everyone sees them
    node.commit(ctx.start_ts)
    out, _ = node.query('{ q(func: eq(name, "bob")) { name } }')
    assert out["q"] == [{"name": "bob"}]


def test_upsert_query_then_mutate_flow():
    """The documented /query?startTs upsert pattern: read inside the txn,
    decide, write, commit."""
    node = Node()
    node.alter(schema_text='email: string @index(exact) .')
    ctx = node.new_txn()
    node.mutate(set_nquads='_:u <email> "a@x.com" .', start_ts=ctx.start_ts)
    out, _ = node.query('{ q(func: eq(email, "a@x.com")) { uid } }',
                        start_ts=ctx.start_ts)
    assert len(out["q"]) == 1  # sees its own write -> no duplicate insert
    node.commit(ctx.start_ts)
    out, _ = node.query('{ q(func: eq(email, "a@x.com")) { uid } }')
    assert len(out["q"]) == 1


def test_oracle_purges_below_watermark():
    o = Oracle()
    o.PURGE_EVERY = 8
    for _ in range(32):
        t = o.new_txn()
        o.track(t.start_ts, [f"k{t.start_ts}".encode()])
        o.commit(t.start_ts)
    # no pending txns: everything decidable has been purged
    assert len(o._key_commit) < 8
    t_old = o.new_txn()           # pending: pins the watermark
    for _ in range(32):
        t = o.new_txn()
        o.track(t.start_ts, [f"k{t.start_ts}".encode()])
        o.commit(t.start_ts)
    # keys committed after t_old's start_ts must survive (conflict-relevant)
    assert len(o._key_commit) >= 32
    o.abort(t_old.start_ts)
    for _ in range(o.PURGE_EVERY):
        t = o.new_txn()
        o.commit(t.start_ts)
    assert len(o._key_commit) < 8
    assert len(o._aborted) < 8


def test_track_rejects_decided_ts():
    o = Oracle()
    t = o.new_txn()
    o.track(t.start_ts, [b"k"])
    o.commit(t.start_ts)
    with pytest.raises(TxnNotFound):
        o.track(t.start_ts, [b"k2"])   # committed: not recreatable
    t2 = o.new_txn()
    o.abort(t2.start_ts)
    with pytest.raises(TxnNotFound):
        o.track(t2.start_ts, [b"k3"])  # aborted


def test_incremental_snapshot_rebuilds_only_dirty_pred():
    node = Node()
    node.alter(schema_text='name: string @index(exact) .\nfollows: [uid] .')
    node.mutate(set_nquads='''
        <0x1> <name> "a" .
        <0x1> <follows> <0x2> .
        <0x2> <name> "b" .
    ''', commit_now=True)
    s1 = node.snapshot()
    # commit touching only `name`
    node.mutate(set_nquads='<0x3> <name> "c" .', commit_now=True)
    s2 = node.snapshot()
    assert s2.preds["follows"] is s1.preds["follows"], \
        "untouched predicate must reuse its device arrays"
    assert s2.preds["name"] is not s1.preds["name"]
    # and the new data is visible
    out, _ = node.query('{ q(func: eq(name, "c")) { name } }')
    assert out["q"] == [{"name": "c"}]


def test_snapshot_cache_respects_historical_reads():
    node = Node()
    node.alter(schema_text='v: int .')
    node.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    ts1 = node.zero.oracle.read_ts()
    node.mutate(set_nquads='<0x1> <v> "2"^^<xs:int> .', commit_now=True)
    out_new, _ = node.query('{ q(func: has(v)) { v } }')
    assert out_new["q"] == [{"v": 2}]
    out_old, _ = node.query('{ q(func: has(v)) { v } }', start_ts=ts1)
    assert out_old["q"] == [{"v": 1}]


def test_blank_node_uid_never_collides_with_explicit():
    """A leased blank-node uid must not collide with client-chosen uids in
    the same or earlier mutations (found by a round-3 verification drive:
    _:c was assigned uid 1, silently overwriting <0x1>'s data)."""
    node = Node()
    node.alter(schema_text='name: string @index(exact) .')
    node.mutate(set_nquads='<0x1> <name> "alice" .', commit_now=True)
    res = node.mutate(set_nquads='_:c <name> "carol" .', commit_now=True)
    assert res.uids["_:c"] != 1
    out, _ = node.query('{ q(func: eq(name, "alice")) { name } }')
    assert out["q"] == [{"name": "alice"}]
    # explicit uid AFTER a blank lease: lease must already be past it
    res2 = node.mutate(set_nquads='<0x500> <name> "zed" .\n_:d <name> "dora" .',
                       commit_now=True)
    assert res2.uids["_:d"] > 0x500
