"""Kubernetes manifests stay structurally valid and consistent with the
CLI surface (reference contrib/config/kubernetes)."""

import yaml


def _docs():
    with open("contrib/config/kubernetes/dgraph-tpu.yaml") as f:
        return list(yaml.safe_load_all(f))


def test_manifest_topology():
    docs = _docs()
    kinds = {(d["kind"], d["metadata"]["name"]) for d in docs}
    assert ("Service", "dgraph-tpu-zero") in kinds
    assert ("StatefulSet", "dgraph-tpu-zero") in kinds
    assert ("StatefulSet", "dgraph-tpu-g0") in kinds
    assert ("StatefulSet", "dgraph-tpu-g1") in kinds
    groups = [d for d in docs if d["kind"] == "StatefulSet"
              and d["metadata"]["name"].startswith("dgraph-tpu-g")]
    assert all(d["spec"]["replicas"] == 3 for d in groups)


def test_selectors_match_template_labels():
    for d in _docs():
        if d["kind"] != "StatefulSet":
            continue
        sel = d["spec"]["selector"]["matchLabels"]
        tmpl = d["spec"]["template"]["metadata"]["labels"]
        assert all(tmpl.get(k) == v for k, v in sel.items())


def test_args_are_real_cli_flags():
    """Every --flag in the manifests must exist in the argparse surface."""
    import argparse

    from dgraph_tpu.__main__ import build_parser

    parser = build_parser()
    subs = next(a for a in parser._actions
                if isinstance(a, argparse._SubParsersAction))
    known = {}
    for name, sp in subs.choices.items():
        known[name] = {opt for a in sp._actions for opt in a.option_strings}
    for d in _docs():
        if d["kind"] != "StatefulSet":
            continue
        for c in d["spec"]["template"]["spec"]["containers"]:
            cmd = c["args"][0]
            flags = [a for a in c["args"] if a.startswith("--")]
            for fl in flags:
                assert fl in known[cmd], f"{cmd} has no flag {fl}"
