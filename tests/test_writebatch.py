"""Group-commit write path (ISSUE 16): commit-window batching, the one-
fsync group WAL record, and per-member demux.

Covers the tentpole contracts — a window's group record replays
byte-identically to its members' solo records (including a handwritten
FROZEN pre-16 per-commit WAL fixture, so the old log format can never
drift), Oracle.commit_batch decides exactly like sequential commit()
calls, conflicting members get their typed TxnConflict while the rest of
the window commits, and `write_batch=False` restores the exact
per-commit path.
"""

import struct
import threading

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.coord.zero import Oracle, TxnConflict, TxnNotFound
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.store import Store, decode_record, encode_record
from dgraph_tpu.storage.writebatch import WriteBatcher
from dgraph_tpu.utils.retry import CommitAmbiguous


def _forced_window(node, max_batch=64, window_ms=200.0):
    """Swap in a batcher that NEVER idle-fires: every commit joins a real
    window, so tests observe deterministic multi-member groups."""
    wb = WriteBatcher(node.zero.oracle, node.store, node.metrics,
                      window_ms=window_ms, max_batch=max_batch,
                      idle_fire=False)
    node.write_batcher = wb
    return wb


def _commit_n(node, n, pred="name"):
    """n concurrent committers writing disjoint keys; returns (oks, errs)."""
    txns = []
    for i in range(n):
        r = node.mutate(set_nquads=f'<0x{i + 1:x}> <{pred}> "p{i + 1}" .')
        txns.append(r.context.start_ts)
    oks, errs = [], []
    lock = threading.Lock()

    def commit_one(st):
        try:
            ts = node.commit(st)
            with lock:
                oks.append(ts)
        except BaseException as e:          # noqa: BLE001 — demuxed below
            with lock:
                errs.append(e)

    threads = [threading.Thread(target=commit_one, args=(st,))
               for st in txns]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return oks, errs


# -- codec: the group-commit record ------------------------------------------

def test_gc_record_codec_roundtrip():
    rec = {"t": "gc", "txns": [
        {"s": 11, "ts": 12, "k": [K.data_key("name", 1).encode()]},
        {"s": 10, "ts": 13, "k": [K.data_key("name", 2).encode(),
                                  K.index_key("name", b"p2").encode()]},
    ]}
    out = decode_record(encode_record(rec))
    assert out["t"] == "gc" and len(out["txns"]) == 2
    # members decode as plain "c" records — replay and replication apply
    # them through the exact single-commit branch
    assert out["txns"][0] == {"t": "c", "s": 11, "ts": 12,
                              "k": [K.data_key("name", 1).encode()]}
    assert out["txns"][1]["k"][1] == K.index_key("name", b"p2").encode()


def test_group_record_replays_identically_to_singles(tmp_path):
    """The same three commits journaled as ONE gc record and as three
    per-commit c records must replay to identical stores."""
    from dgraph_tpu.storage.postings import Op, Posting

    d_gc, d_solo = tmp_path / "gc", tmp_path / "solo"
    members = []
    for i in range(3):
        kb = K.data_key("follows", i + 1)
        members.append((10 + i, 20 + i, kb))

    for d in (d_gc, d_solo):
        d.mkdir()
        s = Store(str(d))
        for st, _ts, kb in members:
            s.add_mutation(st, kb, Posting(100 + st, Op.SET))
        if d is d_gc:
            s.commit_group([(st, ts, [kb.encode()])
                            for st, ts, kb in members])
        else:
            for st, ts, kb in members:
                s.commit(st, ts, [kb.encode()])
        s.close()

    r_gc, r_solo = Store(str(d_gc)), Store(str(d_solo))
    for st, _ts, kb in members:
        np.testing.assert_array_equal(r_gc.get(kb).uids(25), [100 + st])
        np.testing.assert_array_equal(r_solo.get(kb).uids(25), [100 + st])
    # visibility watermark advanced identically
    assert r_gc.pred_commit_ts["follows"] == \
        r_solo.pred_commit_ts["follows"] == 22
    assert r_gc.max_seen_commit_ts == r_solo.max_seen_commit_ts == 22
    r_gc.close()
    r_solo.close()


def test_pre16_per_commit_wal_still_loads(tmp_path):
    """A WAL written by the pre-group-commit path (per-commit binary c
    records) must keep replaying. The fixture bytes are HANDWRITTEN to the
    frozen layout — tag 0x01 m-record (<q I> start_ts,klen + key + <Q B B>
    uid,op,flags) and tag 0x02 c-record (<q q I> start_ts,commit_ts,nkeys
    + <I>-prefixed keys), each framed by a little-endian u32 length — so
    the frozen format can never drift with encode_record."""
    u32 = struct.Struct("<I")
    kb = K.data_key("follows", 1).encode()

    def frame(payload: bytes) -> bytes:
        return u32.pack(len(payload)) + payload

    m_rec = (bytes([0x01]) + struct.pack("<q I", 10, len(kb)) + kb
             + struct.pack("<Q B B", 7, 0, 0))     # uid 7, SET, no flags
    c_rec = (bytes([0x02]) + struct.pack("<q q I", 10, 11, 1)
             + struct.pack("<I", len(kb)) + kb)

    d = tmp_path / "pre16"
    d.mkdir()
    with open(d / "wal.log", "wb") as f:
        f.write(frame(m_rec) + frame(c_rec))
    s = Store(str(d))
    np.testing.assert_array_equal(s.lists[kb].uids(11), [7])
    assert s.pred_commit_ts["follows"] == 11
    s.close()


def test_mixed_wal_gc_after_pre16_records(tmp_path):
    """Old per-commit records and new group records interleave in one log
    (the upgrade case: a store whose WAL predates the window keeps
    appending gc records to the same file)."""
    from dgraph_tpu.storage.postings import Op, Posting

    d = tmp_path / "mixed"
    d.mkdir()
    s = Store(str(d))
    k1, k2 = K.data_key("follows", 1), K.data_key("follows", 2)
    s.add_mutation(10, k1, Posting(7, Op.SET))
    s.commit(10, 11, [k1.encode()])                       # pre-16 shape
    s.add_mutation(12, k2, Posting(8, Op.SET))
    s.commit_group([(12, 13, [k2.encode()])])             # window shape
    s.close()
    r = Store(str(d))
    np.testing.assert_array_equal(r.lists[k1.encode()].uids(14), [7])
    np.testing.assert_array_equal(r.lists[k2.encode()].uids(14), [8])
    r.close()


# -- oracle: batched conflict pass -------------------------------------------

def test_commit_batch_matches_sequential_commits():
    """One commit_batch call must decide exactly what sequential commit()
    calls decide: same commit_ts assignment order, same conflict losers,
    same typed errors."""
    def build():
        o = Oracle()
        ts = [o.new_txn().start_ts for _ in range(5)]
        o.track(ts[0], [b"a"])
        o.track(ts[1], [b"a"])            # loses to ts[0]
        o.track(ts[2], [b"b"])
        o.track(ts[3], [b"c"])
        o.track(ts[4], [b"b"])            # loses to ts[2]
        return o, ts

    o1, ts1 = build()
    batched = o1.commit_batch(ts1 + [999_999])
    o2, ts2 = build()
    seq = []
    for st in ts2 + [999_999]:
        try:
            seq.append(o2.commit(st))
        except BaseException as e:        # noqa: BLE001 — compared below
            seq.append(e)
    assert len(batched) == len(seq) == 6
    for b, s in zip(batched, seq):
        if isinstance(s, BaseException):
            assert type(b) is type(s)
        else:
            assert b == s
    assert isinstance(batched[1], TxnConflict)
    assert isinstance(batched[4], TxnConflict)
    assert isinstance(batched[5], TxnNotFound)
    # purge cadence kept the maps bounded the same way
    assert o1._key_commit == o2._key_commit


def test_commit_batch_intra_window_first_wins():
    o = Oracle()
    t1, t2 = o.new_txn().start_ts, o.new_txn().start_ts
    o.track(t1, [b"k"])
    o.track(t2, [b"k"])
    r = o.commit_batch([t1, t2])
    assert isinstance(r[0], int) and isinstance(r[1], TxnConflict)


# -- the window ---------------------------------------------------------------

def test_window_forms_one_group_one_fsync():
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    wb = _forced_window(n, max_batch=8)
    oks, errs = _commit_n(n, 8)
    assert errs == [] and len(oks) == 8 and len(set(oks)) == 8
    m = n.metrics
    assert m.counter("dgraph_write_batch_formed_total").value == 1
    assert m.counter("dgraph_write_batch_fsyncs_total").value == 1
    assert m.counter("dgraph_write_batch_commits_total").value == 8
    assert m.histogram("dgraph_write_batch_occupancy").snapshot()["max"] == 8
    assert wb._open is None
    # every member is visible — acks demuxed only after the stamp landed
    out, _ = n.query('{ q(func: has(name)) { name } }')
    assert len(out["q"]) == 8
    n.close()


def test_window_demuxes_conflict_while_rest_commit():
    n = Node()
    n.alter(schema_text="v: int .")
    n.mutate(set_nquads='<0x1> <v> "1"^^<xs:int> .', commit_now=True)
    _forced_window(n, max_batch=4)
    # two txns race on 0x1 (one must lose), two touch disjoint subjects
    r1 = n.mutate(set_nquads='<0x1> <v> "2"^^<xs:int> .')
    r2 = n.mutate(set_nquads='<0x1> <v> "3"^^<xs:int> .')
    r3 = n.mutate(set_nquads='<0x2> <v> "4"^^<xs:int> .')
    r4 = n.mutate(set_nquads='<0x3> <v> "5"^^<xs:int> .')
    oks, errs = [], []
    lock = threading.Lock()

    def commit_one(st):
        try:
            ts = n.commit(st)
            with lock:
                oks.append(ts)
        except TxnConflict as e:
            with lock:
                errs.append(e)

    threads = [threading.Thread(target=commit_one,
                                args=(r.context.start_ts,))
               for r in (r1, r2, r3, r4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(oks) == 3 and len(errs) == 1
    assert isinstance(errs[0], TxnConflict)
    m = n.metrics
    assert m.counter("dgraph_write_batch_conflict_aborts_total").value == 1
    assert m.counter("dgraph_num_aborts_total").value == 1
    out, _ = n.query('{ q(func: uid(0x1)) { v } }')
    assert out["q"][0]["v"] in (2, 3)      # exactly one racer won
    out, _ = n.query('{ q(func: uid(0x2, 0x3)) { v } }')
    assert sorted(x["v"] for x in out["q"]) == [4, 5]
    n.close()


def test_batch_of_one_runs_exact_solo_path(tmp_path):
    """An unaccompanied commit through the window must journal the same
    per-commit c record the pre-16 path wrote (byte-compatible logs for
    unbatched traffic)."""
    d = tmp_path / "one"
    d.mkdir()
    n = Node(dirpath=str(d))
    n.alter(schema_text="name: string .")
    n.mutate(set_nquads='<0x1> <name> "solo" .', commit_now=True)
    n.close()
    tags = []
    u32 = struct.Struct("<I")
    with open(d / "wal.log", "rb") as f:
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                break
            (ln,) = u32.unpack(hdr)
            tags.append(f.read(ln)[0])
    assert 0x02 in tags and 0x04 not in tags   # c record, never gc


def test_no_write_batch_restores_per_commit_path():
    n = Node(write_batch=False)
    assert n.write_batcher is None
    n.alter(schema_text="name: string @index(exact) .")
    oks, errs = _commit_n(n, 6)
    assert errs == [] and len(oks) == 6
    assert n.metrics.counter("dgraph_write_batch_formed_total").value == 0
    out, _ = n.query('{ q(func: has(name)) { name } }')
    assert len(out["q"]) == 6
    n.close()


def test_reads_identical_window_on_vs_off():
    """The acceptance gate's read-equivalence check in unit form: the same
    write program through the window and through the solo path must leave
    byte-identical query results."""
    import json

    outs = []
    for write_batch in (True, False):
        n = Node(write_batch=write_batch)
        n.alter(schema_text="name: string @index(exact) .\n"
                            "follows: [uid] @reverse .")
        _commit_n(n, 12)
        n.mutate(set_nquads="<0x1> <follows> <0x2> .\n"
                            "<0x2> <follows> <0x3> .", commit_now=True)
        out, _ = n.query('{ q(func: has(name), orderasc: name) '
                         '{ name follows { name } } }')
        outs.append(json.dumps(out, sort_keys=True))
        n.close()
    assert outs[0] == outs[1]


def test_wal_append_fault_types_whole_window_ambiguous(tmp_path):
    """disk.wal_write mid-window: the oracle already decided, the single
    group append covers every member — so every member gets the typed
    CommitAmbiguous (never a hang, never a silent partial commit) and
    nothing becomes visible (all-or-nothing record)."""
    from dgraph_tpu.utils import faults

    d = tmp_path / "faulted"
    d.mkdir()
    n = Node(dirpath=str(d))     # a real journal, so the fault point fires
    n.alter(schema_text="name: string @index(exact) .")
    _forced_window(n, max_batch=4)
    # stage all mutations BEFORE arming the fault: their own m-record
    # appends must succeed — the fault is for the window's group append
    txns = [n.mutate(set_nquads=f'<0x{i + 1:x}> <name> "p{i + 1}" .')
            .context.start_ts for i in range(4)]
    faults.GLOBAL.clear()
    faults.GLOBAL.reseed(16)
    oks, errs = [], []
    lock = threading.Lock()

    def commit_one(st):
        try:
            ts = n.commit(st)
            with lock:
                oks.append(ts)
        except BaseException as e:       # noqa: BLE001 — typed below
            with lock:
                errs.append(e)

    try:
        faults.GLOBAL.install("disk.wal_write", "error", p=1.0, count=1)
        threads = [threading.Thread(target=commit_one, args=(st,))
                   for st in txns]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert oks == [] and len(errs) == 4
        for e in errs:
            assert isinstance(e, CommitAmbiguous)
            assert e.__cause__ is not None
        out, _ = n.query('{ q(func: has(name)) { name } }')
        assert out.get("q", []) == []
    finally:
        faults.GLOBAL.clear()
    # the window machinery survives: the next commits go through clean
    oks2, errs2 = _commit_n(n, 2, pred="name")
    assert errs2 == [] and len(oks2) == 2
    n.close()


def test_deadline_bypass_commits_solo():
    from dgraph_tpu.utils import deadline as dl

    n = Node()
    n.alter(schema_text="name: string .")
    _forced_window(n, window_ms=500.0)   # window far wider than the budget
    r = n.mutate(set_nquads='<0x1> <name> "p" .')
    with dl.scope(0.2):
        ts = n.commit(r.context.start_ts)
    assert ts > 0
    m = n.metrics
    assert m.counter("dgraph_write_batch_deadline_bypass_total").value == 1
    assert m.counter("dgraph_write_batch_formed_total").value == 0
    n.close()


def test_live_load_routes_through_window_and_retries(tmp_path):
    """Satellite 1: the live loader's batches commit through the window
    and TxnConflict retries ride utils/retry's policy (visible on
    dgraph_retry_total when a conflict occurs)."""
    from dgraph_tpu.loader.live import live_load

    rdf = tmp_path / "live.rdf"
    rdf.write_text("".join(
        f'_:p{i} <name> "p{i}" .\n' for i in range(40)))
    n = Node()
    n.alter(schema_text="name: string @index(exact) .")
    stats = live_load(n, str(rdf), batch=10)
    assert stats.quads == 40 and stats.txns == 4 and stats.aborts == 0
    out, _ = n.query('{ q(func: has(name)) { count(uid) } }')
    assert out["q"][0]["count"] == 40
    # windows formed (batch-of-one counts: live loader is sequential here)
    assert n.metrics.counter(
        "dgraph_write_batch_commits_total").value == stats.txns
    n.close()


def test_node_wal_replay_after_windowed_commits(tmp_path):
    """End-to-end durability: a node that group-committed everything is
    reopened from its journal and serves identical reads."""
    import json

    d = tmp_path / "store"
    d.mkdir()
    n = Node(dirpath=str(d))
    n.alter(schema_text="name: string @index(exact) .")
    _forced_window(n, max_batch=8)
    oks, errs = _commit_n(n, 8)
    assert errs == [] and len(oks) == 8
    out1, _ = n.query('{ q(func: has(name), orderasc: name) { name } }')
    n.close()
    n2 = Node(dirpath=str(d))
    out2, _ = n2.query('{ q(func: has(name), orderasc: name) { name } }')
    assert json.dumps(out1, sort_keys=True) == \
        json.dumps(out2, sort_keys=True)
    n2.close()
