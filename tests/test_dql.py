"""DQL parser + RDF parser (reference: gql/parser_test.go, rdf/parse_test.go)."""

import pytest

from dgraph_tpu.query import dql, rdf
from dgraph_tpu.utils.types import TypeID


def test_basic_query():
    req = dql.parse('{ me(func: eq(name, "alice")) { name age friend { name } } }')
    q = req.queries[0]
    assert q.alias == "me" and q.func.name == "eq" and q.func.attr == "name"
    assert q.func.args == ["alice"]
    kids = [c.attr for c in q.children]
    assert kids == ["name", "age", "friend"]
    assert q.children[2].children[0].attr == "name"


def test_uid_root_and_pagination():
    req = dql.parse("{ q(func: uid(0x1, 2, 0xff), first: 5, offset: 2) { uid } }")
    q = req.queries[0]
    assert q.uids == [1, 2, 255]
    assert q.args == {"first": 5, "offset": 2}
    assert q.children[0].is_uid_node


def test_filters():
    req = dql.parse('''{
      q(func: has(friend)) @filter(eq(age, 25) and (anyofterms(name, "a b") or not uid(0x5))) {
        name
      }
    }''')
    f = req.queries[0].filter
    assert f.op == "and"
    assert f.children[0].func.name == "eq"
    assert f.children[1].op == "or"
    assert f.children[1].children[1].op == "not"


def test_count_and_alias():
    req = dql.parse("{ q(func: has(friend)) { total: count(friend) count(uid) n: name } }")
    c0, c1, c2 = req.queries[0].children
    assert c0.is_count and c0.attr == "friend" and c0.alias == "total"
    assert c1.is_count and c1.is_uid_node
    assert c2.alias == "n" and c2.attr == "name"


def test_vars_and_valvars():
    req = dql.parse("""{
      A as var(func: has(friend)) { x as age }
      q(func: uid(A), orderasc: val(x)) { uid age: val(x) }
    }""")
    v, q = req.queries
    assert v.var_name == "A" and v.attr == "var"
    assert v.children[0].var_name == "x"
    assert q.needs_vars == ["A"]
    assert q.order[0].is_val and q.order[0].attr == "x"
    assert q.children[1].val_ref == "x"


def test_count_func_at_root():
    req = dql.parse("{ q(func: eq(count(friend), 2)) { uid } }")
    fn = req.queries[0].func
    assert fn.is_count and fn.attr == "friend" and fn.args == [2]


def test_recurse_groupby_directives():
    req = dql.parse("""{
      q(func: uid(0x1)) @recurse(depth: 3, loop: true) { friend name }
      g(func: has(friend)) @groupby(age) { count(uid) }
    }""")
    r, g = req.queries
    assert r.recurse.depth == 3 and r.recurse.allow_loop
    assert g.groupby.attrs == [("", "age", "")]


def test_shortest_block():
    req = dql.parse("""{
      path as shortest(from: 0x1, to: 0x4, numpaths: 2) { friend @facets(weight) }
      path(func: uid(path)) { name }
    }""")
    sp = req.queries[0]
    assert sp.shortest.from_ == 1 and sp.shortest.to == 4 and sp.shortest.numpaths == 2
    assert sp.children[0].facets.keys == [("weight", "weight")]
    assert req.queries[1].needs_vars == ["path"]


def test_facets_variants():
    req = dql.parse("""{
      q(func: uid(1)) {
        friend @facets { name }
        knows @facets(w: weight, since) { name }
        likes @facets(eq(close, true)) { name }
        rated @facets(orderasc: rating) { name }
        f2 @facets(w as weight) { name }
      }
    }""")
    ch = req.queries[0].children
    assert ch[0].facets is not None and ch[0].facets.keys == []
    assert ch[1].facets.keys == [("w", "weight"), ("since", "since")]
    assert ch[2].facets.filter.func.name == "eq"
    assert ch[3].facets.order == [("rating", False)]
    assert ch[4].facets.var_map == {"weight": "w"}


def test_lang_tags():
    req = dql.parse("{ q(func: uid(1)) { name@en name@en:fr friend { name } } }")
    c0, c1, _ = req.queries[0].children
    assert c0.lang == "en" and c1.lang == "en:fr"


def test_math_and_aggs():
    req = dql.parse("""{
      var(func: has(friend)) { a as age b as count(friend) }
      q(func: uid(1)) {
        total: math(a + b * 2)
        mn: min(val(a)) mx: max(val(a)) s: sum(val(b)) av: avg(val(a))
      }
    }""")
    q = req.queries[1]
    m = q.children[0].math
    assert m.op == "+" and m.children[1].op == "*"
    assert set(q.children[0].needs_vars) == {"a", "b"}
    assert [c.attr for c in q.children[1:]] == ["__agg_min", "__agg_max", "__agg_sum", "__agg_avg"]


def test_graphql_variables():
    req = dql.parse(
        'query test($name: string, $age: int = 30) { q(func: eq(name, $name)) '
        '@filter(le(age, $age)) { uid } }',
        gql_vars={"$name": "bob"})
    q = req.queries[0]
    assert q.func.args == ["bob"]
    assert q.filter.func.args == [30]
    with pytest.raises(dql.ParseError, match="not supplied"):
        dql.parse("query t($x: int) { q(func: uid($x)) { uid } }")


def test_fragments():
    req = dql.parse("""
      query {
        q(func: uid(1)) { ...common friend { ...common } }
      }
      fragment common { name age }
    """)
    q = req.queries[0]
    assert [c.attr for c in q.children] == ["name", "age", "friend"]
    assert [c.attr for c in q.children[2].children] == ["name", "age"]


def test_expand_all():
    req = dql.parse("{ q(func: uid(1)) { expand(_all_) { name } } }")
    assert req.queries[0].children[0].expand == "_all_"


def test_regex_function():
    req = dql.parse('{ q(func: regexp(name, /^ali.*e$/i)) { uid } }')
    fn = req.queries[0].func
    assert fn.name == "regexp" and fn.args == ["^ali.*e$", "i"]


def test_mutation_block():
    req = dql.parse('''{
      set {
        _:a <name> "Alice" .
        _:a <friend> <0x2> .
      }
    }''')
    assert req.mutations[0]["op"] == "set"
    nquads = rdf.parse(req.mutations[0]["rdf"])
    assert nquads[0].subject == "_:a" and nquads[0].object_value.value == "Alice"
    assert nquads[1].object_id == "0x2"


def test_rdf_typed_literals_and_facets():
    nq = rdf.parse_line('<0x1> <age> "25"^^<xs:int> .')
    assert nq.object_value.tid == TypeID.INT and nq.object_value.value == 25
    nq = rdf.parse_line('<0x1> <name> "chat"@fr .')
    assert nq.lang == "fr"
    nq = rdf.parse_line('<0x1> <friend> <0x2> (weight=0.5, rel="close") .')
    assert dict((k, v.value) for k, v in nq.facets) == {"weight": 0.5, "rel": "close"}
    nq = rdf.parse_line('<0x1> <friend> * .')
    assert nq.star
    nq = rdf.parse_line('<0x1> * * .')
    assert nq.predicate == "*" and nq.star
    with pytest.raises(rdf.RDFError):
        rdf.parse_line("<0x1> <p> .")
    assert rdf.parse_line("# comment") is None


def test_schema_block():
    req = dql.parse("{ schema(pred: [name, age]) { type index } }")
    assert req.schema_request == ["name", "age"]
    req = dql.parse("{ schema { } }")  # all predicates
    assert req.schema_request == []


def test_top_level_schema_query():
    """dgraph clients send `schema {}` WITHOUT enclosing braces."""
    req = dql.parse("schema {}")
    assert req.schema_request == []
    req = dql.parse('schema(pred: [name, age]) {}')
    assert req.schema_request == ["name", "age"]
