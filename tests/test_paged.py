"""Paged (spill-to-disk) posting store (VERDICT r4 #4 — badger's LSM role):
the snapshot is mmap'd, posting lists materialize lazily per key, clean
lists evict under the memory budget, and every query path stays correct —
including writes on top of segment-backed keys, checkpoint round-trips,
and uid-lease recovery without materialization."""

import json

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.storage import keys as K
from dgraph_tpu.storage.store import Store


def _build_dataset(tmp_path, n=400):
    """An eager Node writes + checkpoints a dataset, then closes."""
    d = str(tmp_path / "p")
    node = Node(dirpath=d)
    node.alter(schema_text="name: string @index(exact) .\n"
                           "age: int @index(int) .\nfriend: [uid] .")
    rng = np.random.default_rng(11)
    quads = []
    for i in range(1, n + 1):
        quads.append(f'<0x{i:x}> <name> "p{i}" .')
        quads.append(f'<0x{i:x}> <age> "{20 + i % 50}"^^<xs:int> .')
        for _ in range(3):
            t = int(rng.integers(1, n + 1))
            quads.append(f"<0x{i:x}> <friend> <0x{t:x}> .")
    node.mutate(set_nquads="\n".join(quads), commit_now=True)
    node.store.checkpoint(node.store.max_seen_commit_ts)
    node.close()
    return d


QUERIES = [
    '{ q(func: eq(name, "p7")) { name age friend { name } } }',
    '{ q(func: ge(age, 60), orderasc: name, first: 5) { name age } }',
    '{ q(func: uid(0x1)) @recurse(depth: 2) { friend } }',
    '{ q(func: has(friend)) { count(uid) } }',
]


def test_paged_node_matches_eager(tmp_path):
    d = _build_dataset(tmp_path)
    eager = Node(dirpath=d)
    outs_e = [eager.query(q)[0] for q in QUERIES]
    eager.close()

    paged = Node(dirpath=d, memory_mb=64)
    assert paged.store.paged and paged.store._segments
    outs_p = [paged.query(q)[0] for q in QUERIES]
    for a, b in zip(outs_e, outs_p):
        assert json.dumps(a, sort_keys=True, default=str) == \
            json.dumps(b, sort_keys=True, default=str)
    paged.close()


def test_paged_lazy_and_eviction(tmp_path):
    d = _build_dataset(tmp_path)
    s = Store(d, memory_budget=1)      # 1 byte: evict everything clean
    assert s.paged
    seg_keys = sum(seg.n for seg in s._segments.values())
    assert seg_keys > 400
    assert len(dict.keys(s.lists)) == 0        # nothing materialized yet

    kb = K.data_key("friend", 3).encode()
    pl = s.lists.get(kb)
    assert pl is not None and pl.base_packed.count >= 1
    # repeated materializations trigger eviction back under budget
    for u in range(1, 300):
        s.lists.get(K.data_key("friend", u).encode())
    s._evict_clean()
    assert len(dict.keys(s.lists)) < 300
    # re-access after eviction reproduces the same content
    pl2 = s.lists.get(kb)
    np.testing.assert_array_equal(pl2.uids(10), pl.uids(10))
    s.close()


def test_paged_write_then_read_and_checkpoint(tmp_path):
    d = _build_dataset(tmp_path)
    node = Node(dirpath=d, memory_mb=64)
    # a write on top of a segment-backed key merges with its base
    node.mutate(set_nquads="<0x3> <friend> <0x190> .", commit_now=True)
    out, _ = node.query('{ q(func: uid(0x3)) { friend { uid } } }')
    uids = {x["uid"] for x in out["q"][0]["friend"]}
    assert "0x190" in uids and len(uids) >= 2   # old base edges survive

    # new blank-node writes: uid lease recovered WITHOUT materialization
    node.mutate(set_nquads='_:n <name> "fresh" .', commit_now=True)
    out, _ = node.query('{ q(func: eq(name, "fresh")) { uid name } }')
    new_uid = int(out["q"][0]["uid"], 16)
    assert new_uid > 400       # never collides with segment-backed uids

    # checkpoint under paging: transient materialization, then reopen
    node.store.checkpoint(node.store.max_seen_commit_ts)
    node.close()
    node2 = Node(dirpath=d, memory_mb=64)
    out, _ = node2.query('{ q(func: uid(0x3)) { friend { uid } } }')
    assert "0x190" in {x["uid"] for x in out["q"][0]["friend"]}
    out, _ = node2.query('{ q(func: eq(name, "fresh")) { name } }')
    assert out["q"][0]["name"] == "fresh"
    node2.close()


def test_paged_delete_predicate_drops_segment(tmp_path):
    d = _build_dataset(tmp_path)
    s = Store(d, memory_budget=1 << 20)
    assert (int(K.KeyKind.DATA), "friend") in s._segments
    s.delete_predicate("friend")
    assert (int(K.KeyKind.DATA), "friend") not in s._segments
    assert s.lists.get(K.data_key("friend", 3).encode()) is None
    assert "friend" not in s.predicates()
    s.close()


def test_paged_memory_stays_bounded(tmp_path):
    """The done-gate shape in miniature: query battery under a cap far
    below the dataset's eager resident size."""
    d = _build_dataset(tmp_path, n=800)
    eager = Store(d)
    full_bytes = eager.memory_stats()["bytes"]
    eager.close()

    cap = full_bytes // 2
    node = Node(dirpath=d, memory_mb=max(1, cap // (1 << 20)))
    node.store.memory_budget = cap     # byte-precise for the assertion
    for q in QUERIES:
        node.query(q)
    node.store._evict_clean()
    stats = node.store.memory_stats()
    assert stats["paged"]
    assert stats["bytes"] <= cap, (stats, cap)
    node.close()


def test_paged_write_to_existing_value_key_visible(tmp_path):
    """Review regression: a committed UPDATE to an existing segment-backed
    VALUE key must appear in fold-built query results (the pristine bulk
    fold must step aside once the tablet is touched)."""
    d = _build_dataset(tmp_path)
    node = Node(dirpath=d, memory_mb=64)
    out, _ = node.query('{ q(func: uid(0x5)) { age } }')
    old_age = out["q"][0]["age"]
    node.mutate(set_nquads='<0x5> <age> "99"^^<xs:int> .', commit_now=True)
    out, _ = node.query('{ q(func: uid(0x5)) { age } }')
    assert out["q"][0]["age"] == 99 != old_age
    # index fold sees it too
    out, _ = node.query('{ q(func: eq(age, 99)) { uid } }')
    assert {x["uid"] for x in out["q"]} == {"0x5"}
    node.close()


def test_paged_replay_after_checkpoint_not_stale(tmp_path):
    """Satellite regression (PR 3): _apply_record_locked's 'm' branch must
    call _drop_packed UNCONDITIONALLY. The old `if self._packed_tablets:`
    fast path skipped the _touched side effect once checkpoint() cleared
    the packed cache, so tablet_lists() kept serving pristine segment rows
    that omit the applied mutation (stale reads on WAL replay / follower
    ship-apply / predicate-move ingest)."""
    from dgraph_tpu.storage.postings import Op, Posting

    d = _build_dataset(tmp_path)
    store = Store(d, memory_budget=64 << 20)
    assert store.paged and store._segments
    store.checkpoint(store.max_seen_commit_ts)   # clears _packed_tablets
    assert not store._packed_tablets
    ts = store.max_seen_commit_ts
    kb = K.data_key("friend", 1).encode()
    # follower ship-apply path: records land via apply_record
    store.apply_record({"t": "m", "s": ts + 1, "k": kb,
                        "p": Posting(uid=399, op=Op.SET)})
    store.apply_record({"t": "c", "s": ts + 1, "ts": ts + 2, "k": [kb]})
    kbs = store.keys_of(K.KeyKind.DATA, "friend")
    pls = store.tablet_lists(int(K.KeyKind.DATA), "friend", kbs)
    got = pls[kbs.index(kb)].uids(ts + 2)
    assert 399 in got.tolist(), "tablet scan served a pristine segment row"
    store.close()


def test_materialize_returns_resident_list(tmp_path):
    """Satellite regression (PR 3): _materialize must re-check the map
    under the lock immediately before inserting — a racing reader's
    pristine copy must never replace a writer's dirty list (which would
    make a committed write invisible until WAL replay)."""
    from dgraph_tpu.storage.postings import Op, Posting

    d = _build_dataset(tmp_path)
    store = Store(d, memory_budget=64 << 20)
    key = K.data_key("friend", 2)
    kb = key.encode()
    pl = store.get(key)                  # writer materializes + holds it
    pl.add_mutation(999, Posting(uid=777, op=Op.SET))
    # racing reader re-materializes the same key from the segment: it
    # must return the resident (dirty) object, not clobber it
    got = store._materialize(kb)
    assert got is pl
    assert dict.get(store.lists, kb) is pl
    store.close()
