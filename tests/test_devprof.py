"""Device-runtime observatory (ISSUE 19, obs/devprof.py): XLA
compile/retrace tracking attributed to program families, HBM telemetry
with budget-headroom pressure flags, and the dispatch-timeline
utilization profiler fed from DispatchGate — plus the --no_devprof
disarm contract and the /debug/compiles + /debug/timeline surfaces."""

import json
import random
import threading
import urllib.request

import pytest

from dgraph_tpu.api.http import make_server
from dgraph_tpu.api.server import Node
from dgraph_tpu.obs import costs
from dgraph_tpu.obs import devprof as devprof_mod
from dgraph_tpu.obs.devprof import DevProfiler
from dgraph_tpu.utils import metrics as metrics_mod

SCHEMA = """
    name: string @index(exact) .
    age: int @index(int) .
    follows: [uid] @reverse .
"""


@pytest.fixture
def node():
    n = Node(span_sample=1.0, trace_rng=random.Random(11))
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads="""
        _:a <name> "ann" .
        _:b <name> "bob" .
        _:c <name> "cid" .
        _:a <age> "30" .
        _:a <follows> _:b .
        _:a <follows> _:c .
    """, commit_now=True)
    yield n
    n.close()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# profiler unit behavior (no Node)
# ---------------------------------------------------------------------------

def _mk_prof(slow_log=None, budget_bytes=0, residency=None):
    return DevProfiler(metrics_mod.Registry(), slow_log=slow_log,
                       budget_bytes=budget_bytes, residency=residency)


class _RecordingLog:
    def __init__(self):
        self.entries = []

    def record(self, entry):
        self.entries.append(entry)


def test_retrace_storm_detector_flags_shape_churn():
    """The seeded storm fixture: one family rebuilt under >= 3 distinct
    shape signatures within the window must flag exactly once (rate
    limited to one flag per window per family)."""
    log = _RecordingLog()
    prof = _mk_prof(slow_log=log)
    # warmup below the floor: 2 distinct shapes is a normal cache warm
    prof.on_build("mesh.plan", ("plan", 64))
    prof.on_build("mesh.plan", ("plan", 128))
    assert prof._m.counter("dgraph_xla_retrace_storms_total").value == 0
    # churn past both floors
    for cap in (256, 512, 1024):
        prof.on_build("mesh.plan", ("plan", cap))
    assert prof._m.counter("dgraph_xla_retrace_storms_total").value == 1
    assert len(log.entries) == 1
    e = log.entries[0]
    assert e["root"] == "retrace_storm"
    assert e["family"] == "mesh.plan"
    assert e["distinct_shapes"] >= 3
    # rate limit: more churn inside the same window does NOT re-flag
    for cap in (2048, 4096, 8192):
        prof.on_build("mesh.plan", ("plan", cap))
    assert prof._m.counter("dgraph_xla_retrace_storms_total").value == 1
    assert len(log.entries) == 1
    # a different family has its own window
    for cap in (1, 2, 3, 4):
        prof.on_build("mesh.bfs", ("bfs", cap))
    assert prof._m.counter("dgraph_xla_retrace_storms_total").value == 2
    snap = prof.compiles_snapshot()
    assert snap["families"]["mesh.plan"]["storms"] == 1
    assert snap["families"]["mesh.plan"]["builds"] == 8
    assert snap["retrace_storms"] == 2


def test_compile_listener_attributes_family_and_books_ledger():
    """The jax.monitoring callback: compile ms lands on the TLS family's
    row, on every armed profiler, and on the current cost ledger's
    compile_ms (kept SEPARATE from device_ms so first-touch compiles
    don't poison regression baselines)."""
    prof = _mk_prof()
    devprof_mod.register(prof)
    try:
        lg = costs.CostLedger(endpoint="query", shape="{ q }")
        with costs.scope(lg):
            devprof_mod.push_family("pb.k_hop")
            try:
                devprof_mod._on_duration_event(
                    "/jax/core/compile/backend_compile_duration", 0.025)
            finally:
                devprof_mod.pop_family()
        f = prof.compiles_snapshot()["families"]["pb.k_hop"]
        assert f["compiles"] == 1
        assert f["compile_ms"] == pytest.approx(25.0)
        assert lg.compile_ms == pytest.approx(25.0)
        assert lg.device_ms == 0.0          # separation contract
        # other event names are ignored
        devprof_mod._on_duration_event("/jax/core/trace_duration", 1.0)
        assert prof._m.counter("dgraph_xla_compiles_total").value == 1
        # no family pushed -> attributed to the catch-all row
        devprof_mod._on_duration_event(
            "/jax/core/compile/backend_compile_duration", 0.001)
        assert "unattributed" in prof.compiles_snapshot()["families"]
    finally:
        devprof_mod.unregister(prof)


def test_listener_is_noop_when_disarmed(monkeypatch):
    # force the module fan-out empty regardless of other tests' live
    # nodes sharing the process
    monkeypatch.setattr(devprof_mod, "_PROFILERS", ())
    # must not raise, must not book anywhere
    lg = costs.CostLedger(endpoint="query")
    with costs.scope(lg):
        devprof_mod._on_duration_event(
            "/jax/core/compile/backend_compile_duration", 0.5)
    assert lg.compile_ms == 0.0


def test_hbm_pressure_latches_against_budget():
    class _Residency:
        bytes_live = 0

        def usage(self):
            return self.bytes_live

        def host_bytes(self):
            return 0

    res = _Residency()
    prof = _mk_prof(budget_bytes=1000, residency=res)
    t = 0.0
    # below headroom: no pressure
    res.bytes_live = 500
    prof.record_dispatch("mesh", t, t, t + 0.001)
    assert prof._m.counter("dgraph_devprof_hbm_pressure_total").value == 0
    # crossing 0.9 * budget: one pressure event, then latched
    res.bytes_live = 950
    prof.record_dispatch("mesh", t, t, t + 0.001)
    prof.record_dispatch("mesh", t, t, t + 0.001)
    assert prof._m.counter("dgraph_devprof_hbm_pressure_total").value == 1
    assert prof.hbm_snapshot()["high_water"]["hbm"] == 950
    # back off below 0.8 * budget re-arms the latch
    res.bytes_live = 100
    prof.record_dispatch("mesh", t, t, t + 0.001)
    res.bytes_live = 980
    prof.record_dispatch("mesh", t, t, t + 0.001)
    assert prof._m.counter("dgraph_devprof_hbm_pressure_total").value == 2
    # high-water never regresses
    assert prof.hbm_snapshot()["high_water"]["hbm"] == 980


def test_timeline_ring_and_chrome_trace_shape():
    prof = _mk_prof()
    prof.record_dispatch("host", 1.0, 1.002, 1.010, bytes_moved=64)
    devprof_mod.register(prof)
    try:
        with costs.scope(costs.CostLedger(endpoint="query")):
            with costs.kernel("vector.topk"):
                prof.record_dispatch("mesh", 2.0, 2.001, 2.005)
    finally:
        devprof_mod.unregister(prof)
    recs = prof.timeline_snapshot()
    assert [r["seq"] for r in recs] == [1, 2]
    assert recs[0]["family"] == "host" and recs[0]["bytes"] == 64
    assert recs[0]["queue_ms"] == pytest.approx(2.0)
    assert recs[0]["run_ms"] == pytest.approx(8.0)
    # the kernel-timer TLS family wins over the coarse gate class
    assert recs[1]["family"] == "vector.topk"
    ct = prof.timeline_chrome()
    assert ct["displayTimeUnit"] == "ms"
    names = [e["name"] for e in ct["traceEvents"]]
    assert "host" in names and "vector.topk" in names
    assert "host (queued)" in names
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert all(e["dur"] > 0 for e in xs)
    assert ct["otherData"]["records"] == 2
    assert ct["otherData"]["dispatches"] == 2


# ---------------------------------------------------------------------------
# node integration: every dispatch exactly once, families on records
# ---------------------------------------------------------------------------

def test_every_gated_dispatch_lands_exactly_once(node):
    for i in range(4):
        node.query('{ q(func: eq(name, "ann")) { name follows { name } } }')
    disp = node.metrics.counter("dgraph_devprof_dispatches_total").value
    assert disp > 0
    recs = node.devprof.timeline_snapshot(n=4096)
    # ring small enough here to hold everything: counter == ring length
    assert len(recs) == disp
    assert [r["seq"] for r in recs] == list(range(1, disp + 1))
    assert all(r["family"] for r in recs)
    assert all(r["run_ms"] >= 0.0 and r["queue_ms"] >= 0.0 for r in recs)


def test_shed_and_failed_dispatches_do_not_record(node):
    """Raises out of the gated fn still fence exactly once; admission
    rejections (before the gate's run window opens) record nothing."""
    before = node.metrics.counter("dgraph_devprof_dispatches_total").value

    def boom():
        raise RuntimeError("kernel exploded")

    with pytest.raises(RuntimeError):
        node.dispatch_gate.run(boom, klass="host")
    after = node.metrics.counter("dgraph_devprof_dispatches_total").value
    assert after == before + 1          # the dispatch DID run and fence
    assert len(node.devprof.timeline_snapshot(n=4096)) == after


# ---------------------------------------------------------------------------
# disarm contract
# ---------------------------------------------------------------------------

def test_no_devprof_disarms_every_seam():
    n = Node(devprof=False)
    try:
        n.alter(schema_text=SCHEMA)
        n.mutate(set_nquads='_:a <name> "ann" .', commit_now=True)
        assert n.devprof is None
        assert n.dispatch_gate.profiler is None
        assert n.mesh_exec is None or n.mesh_exec._prof is None
        r, _ = n.query('{ q(func: eq(name, "ann")) { name } }')
        assert r["q"] == [{"name": "ann"}]
        assert n.metrics.counter(
            "dgraph_devprof_dispatches_total").value == 0
        # runtime toggle arms and disarms the same seams
        n.set_devprof(True)
        prof = n.devprof
        assert prof is not None
        assert n.dispatch_gate.profiler is prof
        assert prof in devprof_mod._PROFILERS
        # a distinct query — the identical one would be served from the
        # task cache without ever reaching the dispatch gate
        n.query('{ q(func: has(name)) { name } }')
        assert n.metrics.counter(
            "dgraph_devprof_dispatches_total").value > 0
        n.set_devprof(False)
        assert n.devprof is None and n.dispatch_gate.profiler is None
        assert prof not in devprof_mod._PROFILERS
    finally:
        n.close()


def test_close_unregisters_from_module_fanout(node):
    prof = node.devprof
    assert prof in devprof_mod._PROFILERS
    node.close()
    assert prof not in devprof_mod._PROFILERS


# ---------------------------------------------------------------------------
# /debug surfaces
# ---------------------------------------------------------------------------

def test_debug_compiles_and_timeline_endpoints(node):
    srv = make_server(node, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        node.query('{ q(func: eq(name, "ann")) { name follows { name } } }')
        node.devprof.on_build("mesh.plan", ("plan", 64))
        c = _get(base, "/debug/compiles")
        assert c["enabled"] is True
        assert c["families"]["mesh.plan"]["builds"] == 1
        assert "(" in c["families"]["mesh.plan"]["last_shape"]
        assert isinstance(c["cache_sizes"], dict)
        t = _get(base, "/debug/timeline")
        assert t["displayTimeUnit"] == "ms"
        assert t["otherData"]["records"] > 0
        assert any(e["ph"] == "X" for e in t["traceEvents"])
        raw = _get(base, "/debug/timeline?view=raw&n=8")
        assert isinstance(raw, list) and len(raw) <= 8
        assert all("family" in r for r in raw)
        # the index names both
        idx = _get(base, "/debug")["endpoints"]
        assert "/debug/compiles" in idx and "/debug/timeline" in idx
        # /debug/metrics carries the summary section
        dm = _get(base, "/debug/metrics")
        assert dm["devprof"]["enabled"] is True
        assert dm["devprof"]["dispatches"] > 0
        assert "analytics" in dm["endpoints"]
    finally:
        srv.shutdown()


def test_debug_surfaces_honest_when_disarmed():
    n = Node(devprof=False)
    srv = make_server(n, "127.0.0.1", 0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        assert _get(base, "/debug/compiles") == {"enabled": False}
        assert _get(base, "/debug/timeline") == {"enabled": False}
        assert _get(base, "/debug/metrics")["devprof"] == {
            "enabled": False}
    finally:
        srv.shutdown()
        n.close()


# ---------------------------------------------------------------------------
# satellites: per-subscription + analytics cost attribution
# ---------------------------------------------------------------------------

def test_subscription_costs_group_by_sub(node):
    sub = node.subscribe('{ q(func: has(name)) { name } }')
    try:
        ev = sub.next(5)
        assert ev["type"] == "init"
        # the initial eval ran through the cost ledger tagged with the
        # subscription id; /debug/top?group=sub apportions it
        top = node.cost_book.top(group="sub", endpoint="live")
        keys = [row["key"] for row in top["top"]]
        assert sub.id in keys, top
        row = top["top"][keys.index(sub.id)]
        assert row["records"] >= 1
        assert row["wall_ms"] > 0
        # re-evals after a delta keep attributing
        node.mutate(set_nquads='_:z <name> "zed" .', commit_now=True)
        assert sub.next(5)["type"] == "diff"
        top2 = node.cost_book.top(group="sub", endpoint="live")
        row2 = [r for r in top2["top"] if r["key"] == sub.id][0]
        assert row2["records"] >= row["records"]
    finally:
        sub.cancel()


def test_analytics_rides_the_cost_ledger(node):
    node.analytics("pagerank", "follows")
    top = node.cost_book.top(group="endpoint")
    keys = [row["key"] for row in top["top"]]
    assert "analytics" in keys, top
