"""Multi-PROCESS cluster system tests (reference systest/cluster_test.go:36).

Topology per test: a real `zero` coordinator process plus worker processes
spawned via the CLI (`python -m dgraph_tpu zero|worker`), coordinated ONLY
over the internal gRPC protocol — no in-process ReplicaGroup, no shared
memory. Replication ships WAL records through the Append RPC with quorum
acks; the leader is killed with SIGKILL mid-hammer and the control plane
promotes the live replica with the longest log (Raft's up-to-date rule,
worker/draft.go:485-624 / conn/node.go:47-105 contract).
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.parallel.client import ClusterClient
from dgraph_tpu.parallel.remote import RemoteWorker

SCHEMA = """
name: string @index(exact) .
balance: int .
follows: [uid] .
owner: uid .
"""


def _spawn(tmp_path, args, tag):
    """Start a CLI process; return (proc, bound_port) parsed from stdout."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PYTHONUNBUFFERED", "1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dgraph_tpu"] + args,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo")
    port = None
    deadline = time.time() + 120   # jax import under load
    lines = []
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(f"{tag} died: {''.join(lines)}")
            continue
        lines.append(line)
        m = re.search(r"serving .* on [\w.]+:(\d+)", line)
        if m:
            port = int(m.group(1))
            break
    if port is None:
        proc.kill()
        raise RuntimeError(f"{tag} never reported a port: {''.join(lines)}")
    return proc, port


@pytest.fixture()
def procs():
    running = []

    def add(p):
        running.append(p)
        return p

    yield add
    for p in running:
        if p.poll() is None:
            p.kill()
    for p in running:
        try:
            p.wait(timeout=10)
        except Exception:
            pass


def _write_schema(tmp_path):
    sf = tmp_path / "schema.txt"
    sf.write_text(SCHEMA)
    return str(sf)


def _start_cluster(tmp_path, procs, n_replicas=3, n_groups=1):
    zp, zport = _spawn(tmp_path, ["zero", "--port", "0",
                                  "--groups", str(n_groups)], "zero")
    procs(zp)
    sf = _write_schema(tmp_path)
    workers = []   # (proc, addr) per replica of group 0 … n_groups-1
    groups = {}
    for g in range(n_groups):
        addrs = []
        for r in range(n_replicas if g == 0 else 1):
            wp, wport = _spawn(tmp_path, [
                "worker", "--port", "0",
                "-p", str(tmp_path / f"g{g}r{r}"),
                "--schema", sf, "--zero", f"127.0.0.1:{zport}",
                "--group", str(g)], f"worker g{g}r{r}")
            procs(wp)
            workers.append((wp, f"127.0.0.1:{wport}", g, r))
            addrs.append(f"127.0.0.1:{wport}")
        groups[g] = addrs
    return zport, workers, groups


def _balances(client):
    out = client.query("{ q(func: has(balance)) { name balance } }")
    return {x["name"]: x["balance"] for x in out.get("q", [])}


def test_replicated_group_kill9_failover(tmp_path, procs):
    """3-replica group: quorum-shipped writes survive a SIGKILL of the
    leader; the longest-log live replica takes over and the bank invariant
    holds across the failover."""
    zport, workers, groups = _start_cluster(tmp_path, procs, n_replicas=3)
    addrs = groups[0]
    replicas = [RemoteWorker(a) for a in addrs]
    # control plane promotes — unless the wire ballot (always on in CLI
    # workers) already elected; either way exactly one leader emerges
    t0 = max(rw.status().term for rw in replicas)
    r = replicas[0].promote(t0 + 1, [addrs[1], addrs[2]])
    if not r.ok:     # lost the race to a self-election: adopt its leader
        deadline = time.time() + 20
        while time.time() < deadline and not any(
                rw.status().leader for rw in replicas):
            time.sleep(0.2)
    assert any(rw.status().leader for rw in replicas)
    client = ClusterClient(f"127.0.0.1:{zport}", groups)

    n_accounts, start = 6, 100
    client.mutate(set_nquads="\n".join(
        f'_:a{i} <name> "acct{i}" .\n_:a{i} <balance> "{start}"^^<xs:int> .'
        for i in range(n_accounts)))
    assert sum(_balances(client).values()) == n_accounts * start

    def hammer(rounds):
        import random
        rng = random.Random(7)
        moved = 0
        for _ in range(rounds):
            bal = _balances(client)
            names = sorted(bal)
            a, b = rng.sample(names, 2)
            amt = rng.randint(1, 25)
            # read-modify-write both balances in ONE txn
            uid_out = client.query(
                '{ q(func: has(balance)) { uid name } }')
            uids = {x["name"]: x["uid"] for x in uid_out["q"]}
            client.mutate(set_nquads=(
                f'<{uids[a]}> <balance> "{bal[a] - amt}"^^<xs:int> .\n'
                f'<{uids[b]}> <balance> "{bal[b] + amt}"^^<xs:int> .'))
            moved += amt
        return moved

    hammer(5)
    assert sum(_balances(client).values()) == n_accounts * start

    # SIGKILL the CURRENT leader (promoted or self-elected) mid-life
    old_leader = next(i for i, rw in enumerate(replicas)
                      if rw.status().leader)
    old_term = replicas[old_leader].status().term
    leader_proc = workers[old_leader][0]
    os.kill(leader_proc.pid, signal.SIGKILL)
    leader_proc.wait(timeout=10)

    # control plane: promote the most up-to-date live replica (highest
    # applied commit, then longest durable log — Raft's rule); the wire
    # ballot may win the race, which is equally valid
    live = [i for i in range(3) if i != old_leader]
    stats = sorted(((replicas[i].status().max_commit_ts,
                     replicas[i].status().log_len, -i, i) for i in live),
                   reverse=True)
    new_leader = stats[0][3]
    peer = [addrs[j] for j in live if j != new_leader]
    if not replicas[new_leader].promote(old_term + 1, peer).ok:
        deadline = time.time() + 20
        while time.time() < deadline:
            up = [i for i in live if replicas[i].status().leader]
            if up:
                new_leader = up[0]
                break
            time.sleep(0.2)

    # the hammer continues against the new leader (client re-discovers it)
    hammer(5)
    got = _balances(client)
    assert sum(got.values()) == n_accounts * start
    assert len(got) == n_accounts

    # stale leader fencing: the new leader's term supersedes the old one
    st = replicas[new_leader].status()
    assert st.leader and st.term > old_term


def test_cross_group_processes(tmp_path, procs):
    """Two single-replica groups behind a zero process: mutations split by
    tablet owner, 2-hop queries fan out over ServeTask, Sort and Schema ride
    their own RPCs (worker/sort.go:50, worker/schema.go:160)."""
    zport, workers, groups = _start_cluster(tmp_path, procs,
                                            n_replicas=1, n_groups=2)
    client = ClusterClient(f"127.0.0.1:{zport}", groups)
    client.mutate(set_nquads="\n".join(
        f'_:p{i} <name> "p{i}" .\n_:p{i} <balance> "{10 * i}"^^<xs:int> .'
        for i in range(1, 5)) + """
        _:p1 <follows> _:p2 .
        _:p2 <follows> _:p3 .
        _:p1 <owner> _:p4 .
    """)
    # tablets actually split across the two groups
    tablets = client.zero.tablets()
    assert len(set(tablets.values())) == 2, tablets

    out = client.query('{ q(func: eq(name, "p1")) '
                       '{ name follows { name follows { name } } owner { name } } }')
    q = out["q"][0]
    assert q["follows"][0]["name"] == "p2"
    assert q["follows"][0]["follows"][0]["name"] == "p3"
    assert q["owner"][0]["name"] == "p4"

    # order-by on a (possibly remote) predicate matches value order
    out = client.query('{ q(func: has(balance), orderdesc: balance) '
                       '{ name balance } }')
    got = [x["balance"] for x in out["q"]]
    assert got == sorted(got, reverse=True)

    # Sort RPC direct: owner group orders candidates by its tablet's values
    g = tablets["balance"]
    rw = client.leader_of(g)
    uid_out = client.query("{ q(func: has(balance)) { uid balance } }")
    import numpy as np

    uids = np.asarray([int(x["uid"], 16) for x in uid_out["q"]], np.int64)
    ordered = rw.sort("balance", np.sort(uids), desc=False, lang="",
                      read_ts=int(client.zero.state()["maxTxnTs"]))
    by_uid = {int(x["uid"], 16): x["balance"] for x in uid_out["q"]}
    vals = [by_uid[int(u)] for u in ordered]
    assert vals == sorted(vals)

    # Schema RPC: merged cluster schema covers both groups' entries
    schema = client.schema()
    assert schema.get("balance") is not None
    assert schema.get("follows") is not None
    client.close()


def test_process_move_tablet_and_rebalance(tmp_path, procs):
    """Tablet move OVER THE WIRE driven from the zero process: HTTP
    /moveTablet streams the predicate to the destination leader, flips the
    map, deletes at the source; /state reflects it; queries stay correct.
    A skewed cluster then auto-rebalances (tablet.go:60-74)."""
    import json as _json
    import urllib.request

    # zero with ops HTTP + fast rebalance tick; spawn manually to capture
    # BOTH ports (http + grpc)
    env_extra = ["zero", "--port", "0", "--groups", "2",
                 "--rebalance_interval", "1"]
    import os as _os, re as _re, subprocess as _sp, sys as _sys, time as _time
    env = dict(_os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    # force MANY small chunks through the wire move (predicate_move.go:187)
    env["DGRAPH_TPU_MOVE_CHUNK"] = "256"
    p = _sp.Popen([_sys.executable, "-m", "dgraph_tpu"] + env_extra,
                  stdout=_sp.PIPE, stderr=_sp.STDOUT, text=True, env=env,
                  cwd="/root/repo")
    procs(p)
    http_port = grpc_port = None
    deadline = _time.time() + 60
    while _time.time() < deadline and (http_port is None or grpc_port is None):
        line = p.stdout.readline()
        m = _re.search(r"ops HTTP on [\w.]+:(\d+)", line or "")
        if m:
            http_port = int(m.group(1))
        m = _re.search(r"zero serving .* on [\w.]+:(\d+)", line or "")
        if m:
            grpc_port = int(m.group(1))
    assert http_port and grpc_port

    sf = _write_schema(tmp_path)
    groups = {}
    for g in range(2):
        wp, wport = _spawn(tmp_path, [
            "worker", "--port", "0", "-p", str(tmp_path / f"mg{g}"),
            "--schema", sf, "--zero", f"127.0.0.1:{grpc_port}",
            "--group", str(g)], f"worker g{g}")
        procs(wp)
        groups[g] = [f"127.0.0.1:{wport}"]

    client = ClusterClient(f"127.0.0.1:{grpc_port}", groups)
    client.mutate(set_nquads="\n".join(
        f'_:n{i} <name> "q{i}" .' for i in range(20)))
    tablets = client.zero.tablets()
    src = tablets["name"]
    dst = 1 - src

    def http_get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}{path}", timeout=30) as r:
            return _json.loads(r.read())

    out = http_get(f"/moveTablet?tablet=name&group={dst}")
    assert out.get("tablet") == "name" and out.get("dst") == dst, out
    assert http_get("/state")["tabletMap"]["name"] == dst
    res = client.query('{ q(func: eq(name, "q7")) { name } }')
    assert [x["name"] for x in res["q"]] == ["q7"]

    # deterministic skew: several comparable tablets all on group 0, none
    # on group 1 — choose_rebalance_move MUST find a tablet fitting half
    # the gap, so the background rebalancer has to move one within its tick
    client.mutate(set_nquads="\n".join(
        f'_:b{i} <balance> "{i}"^^<xs:int> .\n'
        f'_:b{i} <follows> _:b{(i + 1) % 300} .' for i in range(300)))
    for t, g in http_get("/state")["tabletMap"].items():
        if g != 0:
            http_get(f"/moveTablet?tablet={t}&group=0")
    before = http_get("/state")["tabletMap"]
    assert set(before.values()) == {0}
    deadline = _time.time() + 30
    moved = False
    while _time.time() < deadline:
        now = http_get("/state")["tabletMap"]
        if any(g != 0 for g in now.values()):
            moved = True
            break
        _time.sleep(0.5)
    assert moved, f"auto-rebalancer never moved a tablet: {now}"
    # queries stay correct through the automatic move; allow the client's
    # 1s tablet-map TTL to lapse (the reference's membership stream has the
    # same propagation window, worker/groups.go:454)
    deadline = _time.time() + 10
    while _time.time() < deadline:
        client._invalidate()
        res = client.query("{ q(func: has(balance)) { balance } }")
        res2 = client.query('{ q(func: eq(name, "q3")) { name } }')
        if len(res.get("q", [])) == 300 and \
                [x["name"] for x in res2.get("q", [])] == ["q3"]:
            break
        _time.sleep(0.5)
    assert len(res["q"]) == 300
    assert [x["name"] for x in res2["q"]] == ["q3"]
    client.close()


def test_zero_process_restart_with_wal(tmp_path, procs):
    """kill -9 the zero coordinator and restart it from its state dir: the
    tablet map and lease ceilings survive, so the cluster keeps answering
    and new uids/timestamps never collide with pre-crash ones."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    zport = s.getsockname()[1]
    s.close()
    zw = str(tmp_path / "zw")
    zp, _ = _spawn(tmp_path, ["zero", "--port", str(zport), "-w", zw], "zero")
    procs(zp)
    sf = _write_schema(tmp_path)
    wp, wport = _spawn(tmp_path, [
        "worker", "--port", "0", "-p", str(tmp_path / "w0"),
        "--schema", sf, "--zero", f"127.0.0.1:{zport}",
        "--group", "0", "--membership_interval", "1"], "worker")
    procs(wp)
    groups = {0: [f"127.0.0.1:{wport}"]}
    client = ClusterClient(f"127.0.0.1:{zport}", groups)
    uids1 = client.mutate(set_nquads='_:a <name> "before" .')
    out = client.query('{ q(func: eq(name, "before")) { uid name } }')
    assert [x["name"] for x in out["q"]] == ["before"]
    old_uid = int(out["q"][0]["uid"], 16)

    os.kill(zp.pid, signal.SIGKILL)
    zp.wait(timeout=10)
    zp2, _ = _spawn(tmp_path, ["zero", "--port", str(zport), "-w", zw],
                    "zero-restarted")
    procs(zp2)

    client._invalidate()
    deadline = time.time() + 30
    uids2 = None
    while time.time() < deadline:
        try:
            uids2 = client.mutate(set_nquads='_:b <name> "after" .')
            break
        except Exception:
            time.sleep(0.5)
    assert uids2 is not None, "cluster never recovered after zero restart"
    new_uid = uids2["_:b"]
    assert new_uid > old_uid        # lease ceiling prevented uid reuse
    out = client.query('{ q(func: has(name), orderasc: name) { name } }')
    assert [x["name"] for x in out["q"]] == ["after", "before"]
    client.close()


def _free_ports(n):
    import socket

    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def test_self_healing_cluster_no_control_plane(tmp_path, procs):
    """VERDICT r4 #3 'done' gate: SIGKILL the zero leader AND the group
    leader with NO control-plane actor; the zero standbys and worker
    replicas elect over the wire and the cluster keeps serving reads and
    writes."""
    zports = _free_ports(3)
    zaddrs = [f"127.0.0.1:{p}" for p in zports]
    peers = ",".join(zaddrs)
    zprocs = []
    for i, p in enumerate(zports):
        zp, _ = _spawn(tmp_path, [
            "zero", "--port", str(p), "--groups", "1",
            "--peers", peers, "--idx", str(i),
            "-w", str(tmp_path / f"z{i}")], f"zero{i}")
        procs(zp)
        zprocs.append(zp)

    sf = _write_schema(tmp_path)
    wprocs, waddrs = [], []
    for r in range(3):
        wp, wport = _spawn(tmp_path, [
            "worker", "--port", "0", "-p", str(tmp_path / f"w{r}"),
            "--schema", sf, "--zero", peers, "--group", "0",
            "--membership_interval", "1"], f"worker{r}")
        procs(wp)
        wprocs.append(wp)
        waddrs.append(f"127.0.0.1:{wport}")

    # the group SELF-elects (no Promote from any control plane): wait for
    # one replica to report leadership via Status
    def leader_idx(deadline=25.0):
        end = time.time() + deadline
        while time.time() < end:
            for i, a in enumerate(waddrs):
                rw = RemoteWorker(a)
                try:
                    if rw.status(timeout=1.0).leader:
                        return i
                except Exception:
                    pass
                finally:
                    rw.close()
            time.sleep(0.3)
        return None

    first = leader_idx()
    assert first is not None, "group never self-elected a leader"

    client = ClusterClient(peers, {0: waddrs})
    client.mutate(set_nquads='_:a <name> "before" .')
    out = client.query('{ q(func: eq(name, "before")) { name } }')
    assert out["q"][0]["name"] == "before"

    # SIGKILL the zero leader (idx 0 bootstraps) AND the group leader
    zprocs[0].send_signal(signal.SIGKILL)
    wprocs[first].send_signal(signal.SIGKILL)

    second = None
    end = time.time() + 30
    while time.time() < end:
        for i, a in enumerate(waddrs):
            if i == first:
                continue
            rw = RemoteWorker(a)
            try:
                st = rw.status(timeout=1.0)
                if st.leader and st.term > 1:
                    second = i
                    break
            except Exception:
                pass
            finally:
                rw.close()
        if second is not None:
            break
        time.sleep(0.3)
    assert second is not None, "no surviving replica won the wire ballot"

    # reads AND writes keep working with both leaders dead
    client2 = ClusterClient(peers, {0: [a for i, a in enumerate(waddrs)
                                        if i != first]})
    deadline = time.time() + 30
    ok = False
    while time.time() < deadline:
        try:
            client2.mutate(set_nquads='_:b <name> "after" .')
            out = client2.query('{ q(func: eq(name, "after")) { name } }')
            if out.get("q") and out["q"][0]["name"] == "after":
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok, "cluster did not converge to serve reads+writes"
    client.close()
    client2.close()
