"""Seeded grammar fuzz: parsers must either succeed or raise their own
error type — never crash with anything else (the reference's lexer/parser
fuzz posture; `go test -fuzz` analog, bounded for CI)."""

import random

import pytest

from dgraph_tpu.query import dql, rdf

N = 3000


def test_dql_parser_never_crashes():
    rng = random.Random(7)
    frags = ['{', '}', '(', ')', 'q', 'func:', 'eq', 'name', '"x"', 'uid',
             '0x1', '@filter', '@facets', 'orderasc:', 'val', 'as', 'v',
             'math', '+', '<p>', '~', 'count', 'first:', '3', ',', ':', '@',
             '.', 'le', '[', ']', 'upsert', 'mutation', 'set', '@if', 'len',
             'shortest', 'from:', 'to:', 'expand', '_all_', '*', '/re/',
             '$var', 'schema', 'pred:']
    for _ in range(N):
        s = " ".join(rng.choice(frags)
                     for _ in range(rng.randint(1, 24)))
        try:
            dql.parse(s)
        except (dql.ParseError, RecursionError):
            pass


def test_rdf_parser_never_crashes():
    rng = random.Random(11)
    frags = ['<0x1>', '_:a', '<name>', '"val"', '"v"@fr', '"3"^^<xs:int>',
             '*', '.', '(', ')', 'k=1', 'k="s"', ',', '<', '>', '"', '\\',
             '@', '^^', '<geo:geojson>', '# comment', 'uid(v)', 'val(x)',
             '_:', '0x']
    for _ in range(N):
        s = " ".join(rng.choice(frags)
                     for _ in range(rng.randint(1, 14)))
        try:
            rdf.parse(s)
        except rdf.RDFError:
            pass


def test_schema_parser_never_crashes():
    from dgraph_tpu.utils import schema as sch
    rng = random.Random(13)
    frags = ['name', ':', 'string', 'int', 'uid', '[', ']', '@index', '(',
             ')', 'term', 'exact', ',', '@reverse', '@count', '@lang',
             '@upsert', '.', '<p>', 'geo', 'password', 'bogus']
    for _ in range(N):
        s = " ".join(rng.choice(frags)
                     for _ in range(rng.randint(1, 12)))
        try:
            sch.parse_schema(s)
        except ValueError:      # schema errors are ValueError subclasses
            pass
