"""Seeded grammar fuzz: parsers must either succeed or raise their own
error type — never crash with anything else (the reference's lexer/parser
fuzz posture; `go test -fuzz` analog, bounded for CI)."""

import random

import pytest

from dgraph_tpu.query import dql, rdf

N = 3000


def test_dql_parser_never_crashes():
    rng = random.Random(7)
    frags = ['{', '}', '(', ')', 'q', 'func:', 'eq', 'name', '"x"', 'uid',
             '0x1', '@filter', '@facets', 'orderasc:', 'val', 'as', 'v',
             'math', '+', '<p>', '~', 'count', 'first:', '3', ',', ':', '@',
             '.', 'le', '[', ']', 'upsert', 'mutation', 'set', '@if', 'len',
             'shortest', 'from:', 'to:', 'expand', '_all_', '*', '/re/',
             '$var', 'schema', 'pred:', 'similar_to', 'emb', '"[0.1, 0.2]"',
             '0.5', '-1.5', 'vector_distance', 'orderasc:']
    for _ in range(N):
        s = " ".join(rng.choice(frags)
                     for _ in range(rng.randint(1, 24)))
        try:
            dql.parse(s)
        except (dql.ParseError, RecursionError):
            pass


def test_rdf_parser_never_crashes():
    rng = random.Random(11)
    frags = ['<0x1>', '_:a', '<name>', '"val"', '"v"@fr', '"3"^^<xs:int>',
             '*', '.', '(', ')', 'k=1', 'k="s"', ',', '<', '>', '"', '\\',
             '@', '^^', '<geo:geojson>', '# comment', 'uid(v)', 'val(x)',
             '_:', '0x']
    for _ in range(N):
        s = " ".join(rng.choice(frags)
                     for _ in range(rng.randint(1, 14)))
        try:
            rdf.parse(s)
        except rdf.RDFError:
            pass


def test_schema_parser_never_crashes():
    from dgraph_tpu.utils import schema as sch
    rng = random.Random(13)
    frags = ['name', ':', 'string', 'int', 'uid', '[', ']', '@index', '(',
             ')', 'term', 'exact', ',', '@reverse', '@count', '@lang',
             '@upsert', '.', '<p>', 'geo', 'password', 'bogus',
             'float32vector', 'vector', 'dim:', 'metric:', 'cosine', 'l2',
             'dot', '8', '-3']
    for _ in range(N):
        s = " ".join(rng.choice(frags)
                     for _ in range(rng.randint(1, 12)))
        try:
            sch.parse_schema(s)
        except ValueError:      # schema errors are ValueError subclasses
            pass


def test_trigram_plan_soundness_fuzz():
    """Planner invariant: every string MATCHING the pattern must contain
    every trigram of at least one plan alternative — otherwise the index
    probe would drop real matches (worker/trigram.go contract)."""
    import random
    import re as remod

    from dgraph_tpu.query.task import _trigram_plan

    rng = random.Random(20260730)
    atoms = ["abc", "defg", "hi", "xyz", "lmnop", "q", "[0-9]", ".", "w+",
             "(abc|wxyz)", "(?:def)?", "tuv{0,2}", "st*", "\\d", "rick",
             "(GRIMES|rhee)", "a(bc)d", "ef|gh"]
    corpus_bits = ["abc", "defg", "hi", "xyz", "lmnop", "q", "7", "z", "ww",
                   "def", "tu", "tuvv", "s", "sttt", "rick", "GRIMES",
                   "rhee", "abcd", "ef", "gh", " ", "Q"]
    checked = 0
    for _ in range(300):
        pat = "".join(rng.choice(atoms) for _ in range(rng.randint(1, 4)))
        try:
            rx = remod.compile(pat)
        except remod.error:
            continue
        plan = _trigram_plan(pat)
        if plan is None:
            continue                      # full scan: trivially sound
        for _ in range(40):
            s = "".join(rng.choice(corpus_bits)
                        for _ in range(rng.randint(1, 8)))
            if rx.search(s) is None:
                continue
            ok = any(all(t in s for t in alt) for alt in plan)
            assert ok, (pat, plan, s)
            checked += 1
    assert checked > 50   # the fuzz actually exercised matching cases


def test_wal_codec_roundtrip_fuzz():
    """Random postings/keys round-trip the binary WAL codec bit-exactly."""
    import random

    from dgraph_tpu.storage import keys as K
    from dgraph_tpu.storage.postings import Op, Posting
    from dgraph_tpu.storage.store import decode_record, encode_record
    from dgraph_tpu.utils.types import TypeID, Val

    rng = random.Random(42)

    def rand_val():
        tid = rng.choice([TypeID.INT, TypeID.FLOAT, TypeID.BOOL,
                          TypeID.STRING])
        v = {TypeID.INT: lambda: rng.randint(-2**40, 2**40),
             TypeID.FLOAT: lambda: rng.random() * 1e6,
             TypeID.BOOL: lambda: rng.random() < 0.5,
             TypeID.STRING: lambda: "".join(
                 rng.choice("aé日🎉 b\\\"\n") for _ in range(rng.randint(0, 40)))
             }[tid]()
        return Val(tid, v)

    for _ in range(200):
        kind = rng.choice([lambda: K.data_key("p" * rng.randint(1, 30),
                                              rng.randint(1, 2**40)),
                           lambda: K.index_key("attr", bytes(
                               rng.randrange(256) for _ in range(
                                   rng.randint(0, 300))))])
        kb = kind().encode()
        p = Posting(
            uid=rng.randint(0, 2**50), op=Op(rng.randint(0, 2)),
            value=rand_val() if rng.random() < 0.7 else None,
            lang=rng.choice(["", "en", "zh-Hant", "x" * 300]),
            facets=tuple((f"k{i}", rand_val())
                         for i in range(rng.randint(0, 5))))
        rec = {"t": "m", "s": rng.randint(-2**40, 2**40), "k": kb, "p": p}
        got = decode_record(encode_record(rec))
        assert got["s"] == rec["s"] and got["k"] == kb
        gp = got["p"]
        assert (gp.uid, gp.op, gp.lang) == (p.uid, p.op, p.lang)
        assert (gp.value is None) == (p.value is None)
        if p.value is not None:
            assert gp.value.tid == p.value.tid
            if p.value.tid == TypeID.FLOAT:
                assert abs(gp.value.value - p.value.value) < 1e-9
            else:
                assert gp.value.value == p.value.value
        assert len(gp.facets) == len(p.facets)

        keys = [kind().encode() for _ in range(rng.randint(0, 20))]
        crec = decode_record(encode_record(
            {"t": "c", "s": 5, "ts": rng.randint(1, 2**40), "k": keys}))
        assert crec["k"] == keys


def test_similar_to_execution_fuzz():
    """Random similar_to forms against a live vector index (ISSUE 8):
    root and @filter member, string/list/variable vectors, both arg
    orders, malformed literals, wrong dims, k edge cases, and composition
    with the existing directive surface — every case must answer or raise
    a TYPED error, never an internal crash."""
    import random

    from dgraph_tpu.api.server import Node
    from dgraph_tpu.query.dql import ParseError
    from dgraph_tpu.query.engine import QueryError
    from dgraph_tpu.query.task import TaskError

    n = Node()
    n.alter(schema_text="""
        emb: float32vector @index(vector(dim: 4, metric: l2)) .
        name: string @index(exact) .
        friend: [uid] @reverse .
    """)
    rng = random.Random(8)
    quads = []
    for i in range(1, 25):
        vec = ", ".join(f"{rng.uniform(-2, 2):.3f}" for _ in range(4))
        quads += [f'<0x{i:x}> <emb> "[{vec}]"^^<xs:float32vector> .',
                  f'<0x{i:x}> <name> "p{i}" .',
                  f'<0x{i:x}> <friend> <0x{i % 24 + 1:x}> .']
    n.mutate(set_nquads="\n".join(quads), commit_now=True)

    # weighted draws: mostly well-formed (the floor below proves the valid
    # surface actually runs), with a malicious tail for the crash hunt
    good_vecs = ['"[1, 0, -1, 0.5]"', '"[0.1,0.2,0.3,0.4]"',
                 '[1.0, 0, 2, 3]', '$v', '"[1e9, -1e9, 0, 0]"']
    bad_vecs = ['"[1, 2]"', '"[]"', '"[1, nan, 2, 3]"', '"x"', '""']
    good_ks = ['3', '1', '25']
    bad_ks = ['0', '-2', '"3"', 'k']
    attrs = ['emb'] * 3 + ['name', 'friend', 'missing']
    tails = ['{ uid }', '{ uid d : val(vector_distance) }',
             '{ name friend { name } }',
             '{ uid friend { name } }']
    posts = ['', ', first: 2', ', orderasc: val(vector_distance)',
             ', orderdesc: name']
    filts = ['', '@filter(has(name))',
             '@filter(similar_to(emb, "[0, 1, 0, 1]", 4))']
    ran = 0
    for _ in range(200):
        a = rng.choice(attrs)
        v = rng.choice(good_vecs if rng.random() < 0.7 else bad_vecs)
        k = rng.choice(good_ks if rng.random() < 0.7 else bad_ks)
        args = f'{a}, {v}, {k}' if rng.random() < 0.5 else f'{a}, {k}, {v}'
        if rng.random() < 0.75:
            q = (f'{{ q(func: similar_to({args}){rng.choice(posts)}) '
                 f'{rng.choice(filts)} {rng.choice(tails)} }}')
        else:
            q = (f'{{ q(func: has(name)) '
                 f'@filter(similar_to({args})) {rng.choice(tails)} }}')
        vars_ = {"$v": "[0.5, 0.5, 0.5, 0.5]"} if "$v" in q else None
        try:
            out, _ = n.query(q, variables=vars_)
            assert isinstance(out, dict)
            ran += 1
        except (ParseError, TaskError, QueryError):
            pass     # typed rejection is fine; internal crashes are not
    assert ran > 40, ran
    n.close()


def test_engine_execution_fuzz():
    """Random structurally-valid queries against a seeded graph: execution
    must either answer or raise a TYPED error (ParseError/TaskError/
    QueryError) — never crash with an internal exception. Covers engine
    paths the goldens don't reach (odd filter/directive/pagination
    combos)."""
    import random

    from dgraph_tpu.api.server import Node
    from dgraph_tpu.query.dql import ParseError
    from dgraph_tpu.query.engine import QueryError
    from dgraph_tpu.query.task import TaskError

    n = Node()
    n.alter(schema_text="""
        name: string @index(exact, term, trigram) @lang .
        age: int @index(int) .
        score: [float] .
        friend: [uid] @reverse @count .
        bio: string @index(fulltext) .
    """)
    quads = []
    for i in range(1, 30):
        quads += [f'<0x{i:x}> <name> "p{i}" .',
                  f'<0x{i:x}> <age> "{18 + i}"^^<xs:int> .',
                  f'<0x{i:x}> <score> "{i}.5"^^<xs:float> .',
                  f'<0x{i:x}> <bio> "likes running and dogs {i}" .',
                  f'<0x{i:x}> <friend> <0x{(i * 3) % 29 + 1:x}> .']
    n.mutate(set_nquads="\n".join(quads), commit_now=True)

    rng = random.Random(4)
    roots = ['has(name)', 'eq(name, "p3")', 'ge(age, 25)',
             'anyofterms(name, "p1 p2")', 'alloftext(bio, "dog run")',
             'regexp(name, /p[0-9]+/)', 'uid(0x1, 0x5)', 'has(friend)',
             'eq(count(friend), 1)', 'le(age, 30)']
    filters = ['', '@filter(ge(age, 20))', '@filter(has(friend))',
               '@filter(NOT eq(name, "p1") AND le(age, 40))',
               '@filter(uid_in(friend, 0x2) OR eq(name, "p9"))']
    directives = ['', '@cascade', '@normalize',
                  '@recurse(depth: 2)', '@groupby(age) { count(uid) }']
    pageargs = ['', ', first: 3', ', offset: 2', ', first: -2',
                ', first: 2, offset: 1', ', orderasc: age',
                ', orderdesc: name, first: 4', ', after: 0x3']
    bodies = ['{ name }', '{ name age }', '{ uid friend { name } }',
              '{ count(uid) }', '{ name ~friend { name } }',
              '{ friend (first: 1) { age } }', '{ expand(_all_) }',
              '{ a : name n : count(friend) }']
    ran = 0
    for _ in range(250):
        d = rng.choice(directives)
        body = '' if d.startswith('@groupby') else rng.choice(bodies)
        if d == '@recurse(depth: 2)':
            body = '{ name friend }'
        q = (f'{{ q(func: {rng.choice(roots)}{rng.choice(pageargs)}) '
             f'{rng.choice(filters)} {d} {body} }}')
        try:
            out, _ = n.query(q)
            assert isinstance(out, dict)
            ran += 1
        except (ParseError, TaskError, QueryError):
            pass     # typed rejection is fine; internal crashes are not
    assert ran > 150, ran
