"""Round-6 serving layer: plan/task/result caches, singleflight coalescing,
dispatch gate, per-request edge budgets, and the /debug/metrics surface.

The correctness contract under test: a mutate / alter / drop-attr must NEVER
let a cached entry be served stale (snapshot-token rotation), and K
concurrent identical queries must share ONE underlying process_task
execution per distinct task while every caller gets identical results.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query import engine as eng
from dgraph_tpu.query import qcache
from dgraph_tpu.query.engine import Executor, QueryError
from dgraph_tpu.query.task import TaskQuery, TaskResult
from dgraph_tpu.utils.metrics import Registry


def _node():
    node = Node()
    node.alter(schema_text="name: string @index(exact) .\n"
                           "age: int @index(int) .\n"
                           "friend: [uid] .")
    node.mutate(set_nquads="\n".join(
        [f'<0x{i:x}> <name> "p{i}" .' for i in range(1, 9)] +
        [f'<0x{i:x}> <age> "{20 + i}"^^<xs:int> .' for i in range(1, 9)] +
        ['<0x1> <friend> <0x2> .', '<0x1> <friend> <0x3> .',
         '<0x2> <friend> <0x4> .']), commit_now=True)
    return node


Q = '{ q(func: ge(age, 21)) { name friend { name } } }'


def _uncached(node, q):
    caches = (node.plan_cache, node.task_cache, node.result_cache)
    node.plan_cache = node.task_cache = node.result_cache = None
    try:
        out, _ = node.query(q)
    finally:
        (node.plan_cache, node.task_cache, node.result_cache) = caches
    return out


# ---------------------------------------------------------------------------
# invalidation: never serve stale
# ---------------------------------------------------------------------------

def test_mutate_invalidates_cached_results():
    node = _node()
    warm1, _ = node.query(Q)
    warm2, _ = node.query(Q)          # served from cache
    assert warm1 == warm2
    assert node.metrics.counter("dgraph_result_cache_hits_total").value > 0
    node.mutate(set_nquads='<0x9> <age> "30"^^<xs:int> .\n'
                           '<0x9> <name> "p9" .', commit_now=True)
    got, _ = node.query(Q)
    assert got != warm1               # the new person must appear
    assert json.dumps(got, sort_keys=True) == \
        json.dumps(_uncached(node, Q), sort_keys=True)
    node.close()


def test_alter_and_drop_attr_invalidate():
    node = _node()
    node.query(Q)
    node.query(Q)
    node.alter(drop_attr="friend")
    got, _ = node.query(Q)
    assert json.dumps(got, sort_keys=True) == \
        json.dumps(_uncached(node, Q), sort_keys=True)
    assert "friend" not in json.dumps(got)
    node.close()


def test_txn_overlay_version_bump_invalidates():
    """A buffered (uncommitted) write inside a txn must be visible to the
    txn's next read — the per-mutate version bump rotates the overlay
    snapshot token."""
    node = _node()
    res = node.mutate(set_nquads='<0x1> <name> "renamed" .',
                      commit_now=False)
    ts = res.context.start_ts
    q = '{ q(func: uid(0x1)) { name } }'
    got1, _ = node.query(q, start_ts=ts)
    assert got1["q"][0]["name"] == "renamed"
    node.mutate(set_nquads='<0x1> <name> "again" .', start_ts=ts)
    got2, _ = node.query(q, start_ts=ts)
    assert got2["q"][0]["name"] == "again"
    node.abort(ts)
    got3, _ = node.query(q)
    assert got3["q"][0]["name"] == "p1"
    node.close()


def test_cached_vs_uncached_byte_identical():
    node = _node()
    for q in (Q, '{ q(func: uid(0x1)) @recurse(depth: 2) { name friend } }',
              '{ q(func: has(age)) { c : count(uid) } }'):
        node.query(q)                  # prime
        cached, _ = node.query(q)
        assert json.dumps(cached, sort_keys=True) == \
            json.dumps(_uncached(node, q), sort_keys=True)
    node.close()


# ---------------------------------------------------------------------------
# singleflight coalescing
# ---------------------------------------------------------------------------

def test_singleflight_one_execution_per_task(monkeypatch):
    node = _node()
    node.result_cache = None          # exercise the task tier, not tier 3
    node.plan_cache = None
    calls: dict = {}
    lock = threading.Lock()
    real = eng.process_task

    def counting(snap, q, schema):
        key = qcache.task_key(q)
        with lock:
            calls[key] = calls.get(key, 0) + 1
        import time
        time.sleep(0.01)              # widen the overlap window
        return real(snap, q, schema)

    monkeypatch.setattr(eng, "process_task", counting)
    results = [None] * 6
    errs = []

    def run(i):
        try:
            results[i] = node.query(Q)[0]
        except Exception as e:        # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    # one underlying execution per distinct task, however many callers
    assert all(n == 1 for n in calls.values()), calls
    assert len(calls) > 0
    assert all(json.dumps(r, sort_keys=True) ==
               json.dumps(results[0], sort_keys=True) for r in results)
    node.close()


def test_singleflight_waiters_share_leader_error():
    cache = qcache.TaskResultCache(1 << 20, Registry())
    barrier = threading.Barrier(3)
    boom = RuntimeError("boom")
    n_calls = [0]

    def compute(q):
        barrier.wait(timeout=5)
        n_calls[0] += 1
        import time
        time.sleep(0.02)
        raise boom

    q = TaskQuery("a", frontier=np.asarray([1, 2], dtype=np.int64))
    errs = []

    def run(first):
        try:
            if not first:
                barrier.wait(timeout=5)
            cache.dispatch(1, q, compute)
        except RuntimeError as e:
            errs.append(e)

    ts = [threading.Thread(target=run, args=(i == 0,)) for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(errs) == 3 and all(e is boom for e in errs)
    assert n_calls[0] == 1            # followers joined the failed flight


# ---------------------------------------------------------------------------
# task/result cache mechanics
# ---------------------------------------------------------------------------

def _mk_result(n) -> TaskResult:
    return TaskResult(uid_matrix=[np.arange(n, dtype=np.int64)],
                      counts=[n],
                      dest_uids=np.arange(n, dtype=np.int64))


def test_task_cache_byte_eviction():
    reg = Registry()
    one = qcache.result_nbytes(_mk_result(100))
    cache = qcache.TaskResultCache(int(one * 2.5), reg)
    for i in range(4):
        q = TaskQuery(f"p{i}")
        cache.dispatch(1, q, lambda _q: _mk_result(100))
    assert len(cache) == 2            # LRU kept the newest two
    assert reg.counter("dgraph_task_cache_evicted_total").value == 2
    assert cache.bytes <= int(one * 2.5)
    # oldest evicted, newest still hits
    hits0 = reg.counter("dgraph_task_cache_hits_total").value
    cache.dispatch(1, TaskQuery("p3"), lambda _q: _mk_result(100))
    assert reg.counter("dgraph_task_cache_hits_total").value == hits0 + 1


def test_task_cache_copy_isolation():
    cache = qcache.TaskResultCache(1 << 20, Registry())
    q = TaskQuery("p")
    a = cache.dispatch(1, q, lambda _q: _mk_result(4))
    a.uid_matrix[0] = np.zeros(0, np.int64)   # caller prunes its copy
    a.counts[0] = 0
    b = cache.dispatch(1, q, lambda _q: _mk_result(4))
    assert len(b.uid_matrix[0]) == 4 and b.counts[0] == 4


def test_result_cache_eviction_and_roundtrip():
    reg = Registry()
    cache = qcache.ResultCache(600, reg)
    out = {"q": [{"uid": "0x1", "vals": list(range(20))}]}
    cache.put(("k1",), out)
    got = cache.get(("k1",))
    assert got == out and got is not out
    got["q"].append("mutated")        # hits hand out independent copies
    assert cache.get(("k1",)) == out
    for i in range(8):
        cache.put((f"k{i}",), out)
    assert cache.bytes <= 600
    assert reg.counter("dgraph_result_cache_evicted_total").value > 0


def test_enforce_memory_evicts_caches():
    node = _node()
    node.query(Q)
    assert node.result_cache.bytes > 0
    stats = node.enforce_memory(1)    # 1-byte budget: everything must go
    assert stats["task_cache_evicted"] > 0
    assert node.result_cache.bytes == 0 and node.task_cache.bytes == 0
    got, _ = node.query(Q)            # rebuilt read-through
    assert got["q"]
    node.close()


# ---------------------------------------------------------------------------
# dispatch gate
# ---------------------------------------------------------------------------

def test_dispatch_gate_bounds_concurrency():
    reg = Registry()
    gate = qcache.DispatchGate(2, reg)
    active, peak = [0], [0]
    lock = threading.Lock()

    def work():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        import time
        time.sleep(0.02)
        with lock:
            active[0] -= 1

    ts = [threading.Thread(target=lambda: gate.run(work)) for _ in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert peak[0] <= 2
    assert reg.counter("dgraph_dispatch_waits_total").value > 0
    assert reg.counter("dgraph_dispatch_inflight").value == 0


# ---------------------------------------------------------------------------
# per-request edge budget
# ---------------------------------------------------------------------------

def test_per_executor_edge_limit_overrides_global():
    node = _node()
    node.task_cache = node.result_cache = None
    q = '{ q(func: uid(0x1)) { friend { friend { name } } } }'
    with pytest.raises(QueryError):
        node.query(q, edge_limit=1)
    out, _ = node.query(q)            # module default untouched
    assert out["q"]
    assert eng.MAX_QUERY_EDGES == 1_000_000
    node.close()


def test_executor_edge_budget_reads_global_dynamically():
    snap = type("S", (), {"preds": {}, "read_ts": 1,
                          "pred": lambda self, a: None})()
    from dgraph_tpu.utils.schema import SchemaState

    ex = Executor.__new__(Executor)
    ex.edge_limit = None
    old = eng.MAX_QUERY_EDGES
    try:
        eng.set_query_edge_limit(7)
        assert ex.edge_budget() == 7
        ex.edge_limit = 3
        assert ex.edge_budget() == 3
    finally:
        eng.set_query_edge_limit(old)


# ---------------------------------------------------------------------------
# plan cache
# ---------------------------------------------------------------------------

def test_plan_cache_variables_signature():
    reg = Registry()
    pc = qcache.PlanCache(8, reg)
    q = 'query q($a: int) { q(func: eq(age, $a)) { name } }'
    r1 = pc.parse(q, {"$a": 21})
    r2 = pc.parse(q, {"$a": 21})
    r3 = pc.parse(q, {"$a": 22})
    assert r1 is r2 and r1 is not r3    # same text+vars hits, new vars miss
    assert reg.counter("dgraph_plan_cache_hits_total").value == 1
    assert reg.counter("dgraph_plan_cache_misses_total").value == 2


# ---------------------------------------------------------------------------
# /debug/metrics HTTP surface
# ---------------------------------------------------------------------------

def test_debug_metrics_http_surface():
    from dgraph_tpu.api.http import serve_forever

    node = _node()
    srv = serve_forever(node, port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        body = Q.encode()
        for _ in range(3):
            req = urllib.request.Request(
                base + "/query", data=body, method="POST",
                headers={"Content-Type": "application/graphql+-"})
            with urllib.request.urlopen(req) as r:
                assert r.status == 200
        with urllib.request.urlopen(base + "/debug/metrics") as r:
            m = json.loads(r.read())
        assert m["caches"]["plan"]["hits"] > 0
        assert m["caches"]["result"]["hits"] > 0
        assert m["caches"]["task"]["hit_rate"] >= 0
        assert m["endpoints"]["query"]["qps"] > 0
        assert m["endpoints"]["query"]["latency"]["count"] == 3
        assert m["dispatch"]["width"] >= 1
        assert "dgraph_task_cache_hits_total" in m["vars"]
    finally:
        srv.shutdown()
        node.close()


# ---------------------------------------------------------------------------
# namespace isolation (ISSUE 20): colliding DQL across tenants
# ---------------------------------------------------------------------------

def test_colliding_dql_across_tenants_never_cross_hits():
    """Two tenants issue the byte-identical query against predicates with
    the same bare names but different data: every cache tier (plan, task,
    result) must keep them apart, and repeats must still HIT within each
    tenant."""
    from dgraph_tpu import tenancy as tnc

    node = Node()
    q = '{ q(func: has(name)) { name } }'
    for tenant, tag in (("acme", "a"), ("beta", "b")):
        with tnc.scope(tenant):
            node.alter(schema_text="name: string @index(exact) .")
            node.mutate(set_nquads="\n".join(
                f'<0x{i:x}> <name> "{tag}{i}" .' for i in (1, 2)),
                commit_now=True)
    try:
        with tnc.scope("acme"):
            a1, _ = node.query(q)
        with tnc.scope("beta"):
            b1, _ = node.query(q)          # same DQL, other namespace
        assert {r["name"] for r in a1["q"]} == {"a1", "a2"}
        assert {r["name"] for r in b1["q"]} == {"b1", "b2"}
        hits0 = node.metrics.counter("dgraph_result_cache_hits_total").value
        with tnc.scope("acme"):
            a2, _ = node.query(q)          # replay: must hit acme's entry
        with tnc.scope("beta"):
            b2, _ = node.query(q)
        assert a2 == a1 and b2 == b1
        assert node.metrics.counter(
            "dgraph_result_cache_hits_total").value >= hits0 + 2
    finally:
        node.close()


def test_plan_cache_keys_include_namespace():
    reg = Registry()
    pc = qcache.PlanCache(8, reg)
    q = "{ q(func: has(name)) { name } }"
    r0 = pc.parse(q, None)
    ra = pc.parse(q, None, ns="acme")
    rb = pc.parse(q, None, ns="beta")
    assert r0 is not ra and ra is not rb   # namespaces never share ASTs
    assert pc.parse(q, None, ns="acme") is ra   # ...but replays hit
