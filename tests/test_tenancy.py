"""Multi-tenant QoS (ISSUE 20): namespaces, cost-metered quotas, and
weighted-fair device scheduling.

The correctness contract under test: every request resolves predicates
inside its caller's namespace (tenant attrs are DISTINCT storage attrs),
cross-namespace access is a typed NamespaceError, over-quota tenants shed
typed ResourceExhausted at the API edge, and the default namespace with
QoS disarmed behaves byte-identically to the pre-tenancy server.
"""

import json
import time
import urllib.request

import pytest

from dgraph_tpu import tenancy as tnc
from dgraph_tpu.api.server import Node
from dgraph_tpu.tenancy.namespace import owns
from dgraph_tpu.tenancy.quota import TenantRegistry
from dgraph_tpu.tenancy.sched import FairScheduler
from dgraph_tpu.utils.deadline import ResourceExhausted
from dgraph_tpu.utils.metrics import Registry


# ---------------------------------------------------------------------------
# name translation primitives
# ---------------------------------------------------------------------------

def test_prefix_strip_roundtrip():
    assert tnc.prefix("t1", "name") == "t1/name"
    assert tnc.strip("t1", "t1/name") == "name"
    assert tnc.prefix("", "name") == "name"          # default: no wrapper
    assert tnc.strip("", "name") == "name"
    # the reverse marker stays OUTSIDE the namespace prefix
    assert tnc.prefix("t1", "~friend") == "~t1/friend"
    assert tnc.strip("t1", "~t1/friend") == "~friend"
    # '*' (wildcard / expand-all token) passes through untranslated
    assert tnc.prefix("t1", "*") == "*"


def test_split_and_owns():
    assert tnc.split("t1/name") == ("t1", "name")
    assert tnc.split("name") == ("", "name")
    assert tnc.split("~t1/friend") == ("t1", "~friend")
    assert owns("t1", "t1/name")
    assert not owns("t1", "t2/name")
    assert owns("", "name") and not owns("", "t1/name")


def test_cross_namespace_reference_is_typed():
    with pytest.raises(tnc.NamespaceError):
        tnc.prefix("t1", "t2/name")


def test_tenant_name_validation():
    assert tnc.validate("") == ""
    assert tnc.validate("acme-1.prod") == "acme-1.prod"
    for bad in ("a/b", "~x", " lead", "-lead", "x" * 65):
        with pytest.raises(tnc.NamespaceError):
            tnc.validate(bad)


def test_scope_contextvar():
    assert tnc.current() == ""
    with tnc.scope("t1"):
        assert tnc.current() == "t1"
        with tnc.scope(""):
            assert tnc.current() == ""
        assert tnc.current() == "t1"
    assert tnc.current() == ""


# ---------------------------------------------------------------------------
# namespace isolation end to end
# ---------------------------------------------------------------------------

def _node(**kw):
    return Node(**kw)


def _seed(node, tenant, tag, n=3):
    with tnc.scope(tenant):
        node.alter(schema_text="name: string @index(exact) .\n"
                               "friend: [uid] .")
        node.mutate(set_nquads="\n".join(
            [f'<0x{i:x}> <name> "{tag}{i}" .' for i in range(1, n + 1)] +
            [f'<0x1> <friend> <0x{i:x}> .' for i in range(2, n + 1)]),
            commit_now=True)


Q = '{ q(func: has(name)) { name friend { name } } }'


def test_tenants_see_only_their_data():
    node = _node()
    _seed(node, "", "root")
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        out0, _ = node.query(Q)
        with tnc.scope("acme"):
            outa, _ = node.query(Q)
        with tnc.scope("beta"):
            outb, _ = node.query(Q)
        names = lambda o: {r["name"] for r in o["q"]}
        assert names(outa) == {"a1", "a2", "a3"}
        assert names(outb) == {"b1", "b2", "b3"}
        assert names(out0) == {"root1", "root2", "root3"}
    finally:
        node.close()


def test_tenant_storage_attrs_are_prefixed():
    node = _node()
    _seed(node, "acme", "a")
    try:
        preds = node.store.predicates()
        assert "acme/name" in preds and "acme/friend" in preds
        assert "name" not in preds          # nothing leaked to default
    finally:
        node.close()


def test_cross_namespace_mutate_and_alter_are_typed():
    node = _node()
    try:
        with tnc.scope("acme"):
            with pytest.raises(tnc.NamespaceError):
                node.mutate(set_nquads='_:a <beta/name> "steal" .',
                            commit_now=True)
            with pytest.raises(tnc.NamespaceError):
                node.alter(schema_text="beta/name: string .")
    finally:
        node.close()


def test_wildcard_delete_rejected_in_tenant_namespace():
    node = _node()
    _seed(node, "acme", "a")
    try:
        with tnc.scope("acme"), pytest.raises(tnc.NamespaceError):
            node.mutate(del_nquads="<0x1> * * .", commit_now=True)
        # the default (admin) namespace keeps full wildcard power
        node.mutate(del_nquads="<0x1> * * .", commit_now=True)
    finally:
        node.close()


def test_schema_view_strips_prefix():
    node = _node()
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        with tnc.scope("acme"):
            out, _ = node.query("schema {}")
        preds = {e["predicate"] for e in out["schema"]}
        assert preds == {"name", "friend"}
        # default namespace (admin) sees every storage attr
        out0, _ = node.query("schema {}")
        preds0 = {e["predicate"] for e in out0["schema"]}
        assert {"acme/name", "beta/name"} <= preds0
    finally:
        node.close()


def test_expand_all_stays_in_namespace():
    node = _node()
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        with tnc.scope("acme"):
            out, _ = node.query(
                '{ q(func: has(name)) { expand(_all_) } }')
        blob = json.dumps(out)
        assert "beta" not in blob and "/" not in blob.replace("\\/", "")
    finally:
        node.close()


def test_tenant_drop_all_scoped_to_namespace():
    node = _node()
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        with tnc.scope("acme"):
            node.alter(drop_all=True)
        preds = node.store.predicates()
        assert not any(a.startswith("acme/") for a in preds)
        assert "beta/name" in preds         # the neighbor survived
    finally:
        node.close()


def test_tenant_drop_attr_scoped():
    node = _node()
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        with tnc.scope("acme"):
            node.alter(drop_attr="name")
        preds = node.store.predicates()
        assert "acme/name" not in preds and "beta/name" in preds
    finally:
        node.close()


def test_default_namespace_unwrapped():
    """The single-tenant fast path: no scope installed means raw
    snapshot/schema objects — no view wrappers anywhere."""
    node = _node(qos=False)
    _seed(node, "", "root")
    try:
        snap = node._read_view(None)[1] if False else None
        out, _ = node.query(Q)
        assert {r["name"] for r in out["q"]} == {"root1", "root2", "root3"}
        assert node.dispatch_gate.fair is None
        assert node.write_batcher is None or \
            node.write_batcher.tenant_fn is None
        assert node.live.registry is None
    finally:
        node.close()


# ---------------------------------------------------------------------------
# quotas: token buckets in cost-ledger units
# ---------------------------------------------------------------------------

def test_quota_debt_sheds_typed_then_refills():
    reg = TenantRegistry(Registry())
    reg.configure({"tenants": {"t": {"device_ms_per_s": 50.0,
                                     "burst_s": 0.2}}})
    reg.admit("t")                       # fresh bucket: admitted
    reg.debit("t", device_ms=1e6)        # way over: deep debt (floored)
    with pytest.raises(ResourceExhausted):
        reg.admit("t")
    # debt is floored at one burst window (10 units here, refilling at
    # 50/s): out of debt in ~200ms, never an unbounded lockout
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            reg.admit("t")
            break
        except ResourceExhausted:
            time.sleep(0.005)
    else:
        pytest.fail("bucket never refilled out of debt")


def test_quota_unlimited_units_never_shed():
    reg = TenantRegistry(Registry())
    reg.configure({"tenants": {"t": {"weight": 2.0}}})   # no rates
    reg.debit("t", device_ms=1e9, edges=1e9, bytes_=1e9)
    reg.admit("t")                       # unlimited: always admitted


def test_default_spec_key_applies_to_unknown_tenants():
    reg = TenantRegistry(Registry())
    reg.configure({"tenants": {"*": {"edges_per_s": 1.0,
                                     "burst_s": 60.0}}})
    reg.debit("anyone", edges=1e6)
    with pytest.raises(ResourceExhausted):
        reg.admit("anyone")


def test_shed_books_metrics():
    m = Registry()
    reg = TenantRegistry(m)
    reg.configure({"tenants": {"t": {"device_ms_per_s": 1.0,
                                     "burst_s": 60.0}}})
    reg.debit("t", device_ms=1e6)
    with pytest.raises(ResourceExhausted):
        reg.admit("t")
    assert m.counter("dgraph_shed_total").value == 1
    assert m.keyed("dgraph_tenant_shed_total",
                   labels=("tenant",)).get("t") == 1
    assert reg.table()["t"]["sheds"] == 1


def test_hot_reload_merges_and_resets_only_reconfigured_buckets():
    reg = TenantRegistry(Registry())
    reg.configure({"tenants": {"a": {"device_ms_per_s": 1.0,
                                     "burst_s": 60.0},
                               "b": {"device_ms_per_s": 1.0,
                                     "burst_s": 60.0}}})
    reg.debit("a", device_ms=1e6)
    reg.debit("b", device_ms=1e6)
    # reconfigure only b: a's debt must survive the reload
    reg.configure({"tenants": {"b": {"device_ms_per_s": 1e9}}})
    with pytest.raises(ResourceExhausted):
        reg.admit("a")
    reg.admit("b")                       # fresh generous bucket
    # replace=True swaps the whole table
    reg.configure({"tenants": {"c": {}}}, replace=True)
    assert set(k for k in reg.table() if reg.table()[k]["spec"]) == {"c"}
    reg.admit("a")                       # a has no spec anymore


def test_window_share_is_weight_proportional():
    reg = TenantRegistry(Registry())
    reg.configure({"tenants": {"heavy": {"weight": 3.0},
                               "light": {"weight": 1.0}}})
    assert reg.window_share("heavy", 64) == 48
    assert reg.window_share("light", 64) == 16
    assert reg.window_share("unknown", 64) >= 1   # floor of one slot


def test_unknown_quota_key_rejected():
    reg = TenantRegistry(Registry())
    with pytest.raises(ValueError):
        reg.configure({"tenants": {"t": {"qps": 10}}})


# ---------------------------------------------------------------------------
# weighted-fair scheduling
# ---------------------------------------------------------------------------

def test_fair_scheduler_vtime_orders_by_charged_share():
    fs = FairScheduler(weight_fn={"a": 1.0, "b": 4.0}.get)
    # equal measured work each round; a first-time tenant enters at the
    # current floor, then advances by wall-ms / weight
    fs.charge("a", 100.0)               # a = 100/1 = 100
    fs.charge("b", 100.0)               # b = floor(100) + 100/4 = 125
    fs.charge("a", 100.0)               # a = 200
    fs.charge("b", 100.0)               # b = 150
    snap = fs.snapshot()
    assert snap["vtime_ms"]["a"] == 200.0
    assert snap["vtime_ms"]["b"] == 150.0
    # under sustained equal load the heavier-weighted tenant's clock
    # falls behind: it goes first when both wait
    fs._waiting = {"a": 1, "b": 1}
    assert fs._turn_locked() == "b"


def test_fair_scheduler_idle_reentry_at_floor():
    fs = FairScheduler()
    fs.charge("busy", 1000.0)
    # a brand-new tenant enters at the floor (0 here is below busy's
    # clock) and is admitted immediately — no banked burst, no penalty
    t0 = time.monotonic()
    fs.admit("newcomer")
    assert time.monotonic() - t0 < 0.5
    assert fs.snapshot()["vtime_ms"].get("newcomer", 0.0) <= 1000.0


def test_fair_scheduler_ewma():
    fs = FairScheduler()
    fs.charge("t", 10.0)
    assert fs.ewma_ms("t") == 10.0
    fs.charge("t", 20.0)
    assert 10.0 < fs.ewma_ms("t") < 20.0


def test_gate_armed_only_with_config_and_qos():
    node = _node(qos=True)
    try:
        assert node.dispatch_gate.fair is None       # unconfigured
        node.configure_tenants({"tenants": {"a": {"weight": 2.0}}})
        assert node.dispatch_gate.fair is not None
        if node.write_batcher is not None:
            assert node.write_batcher.tenant_fn is not None
        assert node.live.registry is node.tenancy
    finally:
        node.close()

    node = _node(qos=False, tenants={"tenants": {"a": {"weight": 2.0}}})
    try:
        # --no_qos: namespaces stay active, scheduling stays disarmed
        assert node.dispatch_gate.fair is None
        assert node.tenancy.configured
    finally:
        node.close()


# ---------------------------------------------------------------------------
# quota admission at the Node edge
# ---------------------------------------------------------------------------

def test_node_sheds_over_quota_tenant_typed():
    node = _node(tenants={"tenants": {"acme": {"device_ms_per_s": 1.0,
                                               "burst_s": 60.0}}})
    _seed(node, "acme", "a")
    try:
        node.tenancy.debit("acme", device_ms=1e6)     # force debt
        with tnc.scope("acme"), pytest.raises(ResourceExhausted):
            node.query(Q)
        # an unconstrained neighbor keeps serving
        _seed(node, "beta", "b")
        with tnc.scope("beta"):
            out, _ = node.query(Q)
        assert out["q"]
    finally:
        node.close()


def test_cost_attribution_reaches_ledger_and_top():
    node = _node(tenants={"tenants": {"acme": {"weight": 2.0}}})
    _seed(node, "acme", "a")
    try:
        with tnc.scope("acme"):
            node.query(Q)
        top = node.cost_book.top(group="tenant")
        keys = {row["key"] for row in top["top"]}
        assert "acme" in keys
        assert "acme" in node.tenancy.table()
    finally:
        node.close()


# ---------------------------------------------------------------------------
# live queries: per-tenant caps + namespace-scoped notification
# ---------------------------------------------------------------------------

def test_live_subscription_tenant_cap_and_isolation():
    node = _node(tenants={"tenants": {"acme": {"max_subs": 1}}})
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        with tnc.scope("acme"):
            sub = node.subscribe('{ q(func: has(name)) { name } }')
            ev = sub.next(5.0)
            assert ev is not None and ev["type"] == "init"
            with pytest.raises(ResourceExhausted):
                node.subscribe('{ q(func: has(name)) { uid } }')
        # a commit in ANOTHER namespace must not touch acme's sub
        _seed(node, "beta", "b2")
        # a commit in acme's namespace must notify with acme's data
        with tnc.scope("acme"):
            node.mutate(set_nquads='<0x9> <name> "a-new" .',
                        commit_now=True)
        deadline = time.monotonic() + 10.0
        diff = None
        while time.monotonic() < deadline:
            ev = sub.next(0.25)
            if ev is not None and ev["type"] == "diff":
                diff = ev
                break
        assert diff is not None, "no diff arrived for the tenant commit"
        blob = json.dumps(diff)
        assert "a-new" in blob and "b2" not in blob
        sub.cancel()
        stats = node.live.stats()
        assert stats.get("tenants", {}).get("acme", 0) in (0, 1)
    finally:
        node.close()


def test_live_same_dql_different_tenants_not_coalesced():
    node = _node()
    _seed(node, "acme", "a")
    _seed(node, "beta", "b")
    try:
        q = '{ q(func: has(name)) { name } }'
        with tnc.scope("acme"):
            s1 = node.subscribe(q)
        with tnc.scope("beta"):
            s2 = node.subscribe(q)
        e1, e2 = s1.next(5.0), s2.next(5.0)
        assert "a1" in json.dumps(e1) and "a1" not in json.dumps(e2)
        assert "b1" in json.dumps(e2)
        s1.cancel()
        s2.cancel()
    finally:
        node.close()


# ---------------------------------------------------------------------------
# write-window tenant slot caps
# ---------------------------------------------------------------------------

def test_write_batcher_tenant_cap_forces_solo():
    from dgraph_tpu.storage import writebatch as wb_mod

    class _Oracle:
        pass

    wb = wb_mod.WriteBatcher(_Oracle(), None, window_ms=50.0,
                             max_batch=4, idle_fire=False)
    wb.tenant_fn = lambda: "hog"
    wb.tenant_cap_fn = lambda t: 1       # one slot per window for anyone
    import threading

    solos = []
    results = []

    def submit(i):
        results.append(wb.submit(
            100 + i, [b"k%d" % i], lambda i=i: solos.append(i) or i))

    # first submit leads a window (allowed); the second would JOIN the
    # open window over its 1-slot cap -> exact solo path
    t1 = threading.Thread(target=submit, args=(0,))
    t1.start()
    time.sleep(0.01)                     # let the leader open the window
    submit(1)
    t1.join(5.0)
    assert 1 in solos                    # capped joiner committed solo
    assert wb.metrics.counter(
        "dgraph_write_batch_tenant_solo_total").value == 1


# ---------------------------------------------------------------------------
# HTTP edge: header scoping, typed 403, hot reload, metrics surfaces
# ---------------------------------------------------------------------------

def _http(base, path, data=None, hdrs=None):
    req = urllib.request.Request(base + path, data=data,
                                 headers=hdrs or {})
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_http_tenant_header_scopes_and_403s():
    from dgraph_tpu.api.http import serve_forever

    node = _node()
    srv = serve_forever(node, port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        st, _ = _http(base, "/admin/tenant", json.dumps(
            {"tenants": {"acme": {"weight": 4.0},
                         "beta": {"weight": 1.0}}}).encode())
        assert st == 200
        st, _ = _http(base, "/mutate?commitNow=true",
                      b'{ set { _:a <name> "acme-http" . } }',
                      {"X-Dgraph-Tenant": "acme"})
        assert st == 200
        st, body = _http(base, "/query", b'{ q(func: has(name)) { name } }',
                         {"X-Dgraph-Tenant": "acme"})
        assert st == 200 and "acme-http" in body
        st, body = _http(base, "/query", b'{ q(func: has(name)) { name } }',
                         {"X-Dgraph-Tenant": "beta"})
        assert st == 200 and "acme-http" not in body
        # invalid tenant name and cross-namespace predicate: typed 403
        st, body = _http(base, "/query", b"{ q(func: has(name)) { uid } }",
                         {"X-Dgraph-Tenant": "no/slash"})
        assert st == 403 and "ErrorNamespace" in body
        st, body = _http(base, "/mutate?commitNow=true",
                         b'{ set { _:a <beta/name> "x" . } }',
                         {"X-Dgraph-Tenant": "acme"})
        assert st == 403 and "ErrorNamespace" in body
        # the serving readout carries the tenancy section
        st, body = _http(base, "/debug/metrics")
        m = json.loads(body)
        assert m["tenancy"]["configured"]
        assert "acme" in m["tenancy"]["tenants"]
        assert "acme" in m["tenancy"]["storage"]
        # /debug/top?group=tenant ranks by tenant
        st, body = _http(base, "/debug/top?group=tenant")
        assert st == 200 and json.loads(body)["group"] == "tenant"
        # empty-body /admin/tenant reads the table back
        st, body = _http(base, "/admin/tenant", b"")
        assert st == 200 and "acme" in body
    finally:
        srv.shutdown()
        node.close()


def test_http_shed_is_429_and_labeled():
    from dgraph_tpu.api.http import serve_forever

    node = _node(tenants={"tenants": {"acme": {"edges_per_s": 1.0,
                                               "burst_s": 60.0}}})
    _seed(node, "acme", "a")
    node.tenancy.debit("acme", edges=1e6)
    srv = serve_forever(node, port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        st, body = _http(base, "/query", b"{ q(func: has(name)) { uid } }",
                         {"X-Dgraph-Tenant": "acme"})
        assert st == 429 and "ErrorResourceExhausted" in body
        st, body = _http(base, "/metrics")
        assert 'dgraph_tenant_shed_total{tenant="acme"} 1' in body
    finally:
        srv.shutdown()
        node.close()


def test_zero_state_carries_tenant_table():
    node = _node(tenants={"tenants": {"acme": {"weight": 2.0}}})
    try:
        st = node.state()
        assert "tenants" in st and "acme" in st["tenants"]
    finally:
        node.close()
