"""Whole-plan fused compilation (ISSUE 12): fused-vs-classic byte
identity across filter/pagination/facet shapes, one-dispatch gates for
every traversal family, the labeled fallback-reason taxonomy, and the
golden-corpus fused-coverage ratio the acceptance criteria pin at ≥ 0.9.

Needs the conftest-provided 8-virtual-device CPU mesh (no-op elsewhere,
same rule as tests/test_mesh_exec.py)."""

import json

import numpy as np
import pytest
import jax

from dgraph_tpu.api.server import Node

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs the conftest-provided 8-virtual-device CPU mesh")


SCHEMA = """
name: string @index(exact) .
rating: float @index(float) .
p0: [uid] .
p1: [uid] .
p2: [uid] @reverse .
follows: [uid] .
"""


def _quads():
    rng = np.random.default_rng(11)
    quads = [f'_:n{i} <name> "node{i}" .' for i in range(80)]
    quads += [f'_:n{i} <rating> "{(i * 13) % 100 / 10}"^^<xs:float> .'
              for i in range(80)]
    for i in range(80):
        for attr, mul, off in (("p0", 3, 1), ("p1", 5, 2), ("p2", 7, 3)):
            for k in range(3):
                t = (i * mul + off + k) % 80
                facet = ' (w=%d)' % (k + 1) if attr == "p0" else ""
                quads.append(f"_:n{i} <{attr}> _:n{t}{facet} .")
        for j in sorted(rng.choice(80, size=3, replace=False)):
            if j != i:
                quads.append(f"_:n{i} <follows> _:n{j} .")
    return "\n".join(quads)


@pytest.fixture(scope="module")
def pair():
    """(plain node, mesh node) over an identical graph — task/result
    caches disabled so every query reaches the dispatch seam."""
    nodes = []
    for mesh in (0, 8):
        n = Node(mesh_devices=mesh, mesh_min_edges=1)
        n.alter(schema_text=SCHEMA)
        n.mutate(set_nquads=_quads(), commit_now=True)
        n.task_cache = n.result_cache = None
        nodes.append(n)
    return nodes


def _same(plain, mesh, q):
    a, _ = plain.query(q)
    b, _ = mesh.query(q)
    assert json.dumps(a, sort_keys=True, default=str) == \
        json.dumps(b, sort_keys=True, default=str), q


# ---------------------------------------------------------------------------
# fused shapes: byte identity + ONE dispatch
# ---------------------------------------------------------------------------

FUSED_BATTERY = [
    # filters mid-chain — the PR-6 bail-out shapes, now fused
    '{ q(func: eq(name, "node3")) { p0 @filter(ge(rating, 3.0)) '
    '{ p1 @filter(lt(rating, 8.0)) { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 @filter(uid(0x1,0x2,0x3,0x10)) '
    '{ p1 { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 @filter(NOT eq(name, "node10")) '
    '{ p1 @filter(has(rating) AND ge(rating, 1.0)) { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 @filter(ge(count(p1), 3)) '
    '{ p1 { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 @filter(le(count(p1), 0)) '
    '{ p1 { p2 } } } }',
    # pagination mid-chain (incl. negative first)
    '{ q(func: eq(name, "node3")) { p0 (first: 2) '
    '{ p1 (first: 1, offset: 1) { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 (first: -2) { p1 { p2 } } } }',
    '{ q(func: eq(name, "node3")) { p0 @filter(ge(rating, 2.0)) '
    '(first: 2, offset: 1) { p1 { p2 } } } }',
    # facet READS ride the fused chain (host attach)
    '{ q(func: eq(name, "node3")) { p0 @facets(w) { p1 { p2 } } } }',
    # value / count co-children at every level
    '{ q(func: eq(name, "node3")) { name p0 { name rating '
    'p1 { p2 { name } } } } }',
    '{ q(func: eq(name, "node3")) { p0 { count(p1) p1 { p2 } } } }',
    # var capture on a chain node, consumed by a later block
    '{ q(func: eq(name, "node3")) { p0 { v as p1 { p2 } } } '
    ' r(func: uid(v), first: 3) { name } }',
    # reverse edges + order args (child order is presentation-only)
    '{ q(func: eq(name, "node5")) { p2 @filter(ge(rating, 1.0)) '
    '{ ~p2 } } }',
    '{ q(func: eq(name, "node3")) { p0 (orderasc: rating) '
    '{ p1 { p2 } } } }',
]


def test_fused_battery_byte_identical_one_dispatch(pair):
    plain, mesh = pair
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    for q in FUSED_BATTERY:
        a, _ = plain.query(q)
        d0 = c.value
        b, _ = mesh.query(q)
        assert c.value - d0 == 1, f"not one dispatch: {q}"
        assert json.dumps(a, sort_keys=True, default=str) == \
            json.dumps(b, sort_keys=True, default=str), q


def test_fuzz_grid_filter_pagination_facets(pair):
    """Cartesian fuzz: filter × pagination × facet-read combos on a
    2-hop chain, every combination byte-identical fused vs classic."""
    plain, mesh = pair
    filters = ["", "@filter(ge(rating, 2.0))",
               "@filter(uid(0x2, 0x5, 0x9, 0x11))",
               "@filter(NOT le(rating, 4.0))",
               "@filter(eq(count(p2), 3) OR ge(rating, 8.0))"]
    pags = ["", "(first: 2)", "(first: 2, offset: 1)", "(first: -1)"]
    facets = ["", "@facets(w)"]
    for f in filters:
        for p in pags:
            for fc in facets:
                q = ('{ q(func: eq(name, "node7")) { p0 %s %s %s '
                     '{ p1 { uid } } } }' % (fc, f, p))
                _same(plain, mesh, q)


def test_recurse_filter_and_val_children(pair):
    plain, mesh = pair
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    for q in [
        '{ q(func: eq(name, "node1")) @recurse(depth: 3) '
        '{ name follows @filter(ge(rating, 1.0)) } }',
        '{ q(func: eq(name, "node1")) @recurse(depth: 4) '
        '{ rating follows } }',
        '{ q(func: eq(name, "node1")) @recurse(depth: 3, loop: true) '
        '{ follows } }',
    ]:
        a, _ = plain.query(q)
        d0 = c.value
        b, _ = mesh.query(q)
        assert c.value - d0 == 1, f"not one dispatch: {q}"
        assert json.dumps(a, sort_keys=True, default=str) == \
            json.dumps(b, sort_keys=True, default=str), q


def test_shortest_one_dispatch_all_variants(pair):
    """Shortest path — single, multi-predicate, k-shortest — runs the
    whole expandOut loop as ONE while_loop dispatch (12 before)."""
    plain, mesh = pair
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    for q in [
        '{ p as shortest(from: 0x1, to: 0x30) { follows } '
        ' r(func: uid(p)) { uid } }',
        '{ p as shortest(from: 0x1, to: 0x30) { follows p0 } '
        ' r(func: uid(p)) { uid } }',
        '{ p as shortest(from: 0x1, to: 0x30, numpaths: 2) { follows } '
        ' r(func: uid(p)) { uid } }',
        '{ p as shortest(from: 0x1, to: 0x999) { follows } '
        ' r(func: uid(p)) { uid } }',     # unreachable endpoint
    ]:
        a, _ = plain.query(q)
        d0 = c.value
        b, _ = mesh.query(q)
        assert c.value - d0 == 1, f"not one dispatch: {q}"
        assert json.dumps(a, sort_keys=True, default=str) == \
            json.dumps(b, sort_keys=True, default=str), q


# ---------------------------------------------------------------------------
# fallback reasons: enumerable coverage gaps
# ---------------------------------------------------------------------------

def _reasons(mesh):
    return mesh.metrics.keyed("dgraph_mesh_fallbacks_total",
                              labels=("reason",)).snapshot()


def test_facet_filter_falls_back_labeled(pair):
    plain, mesh = pair
    q = ('{ q(func: eq(name, "node3")) { p0 @facets(eq(w, 1)) '
         '{ p1 { p2 } } } }')
    before = _reasons(mesh).get("facet", 0)
    _same(plain, mesh, q)
    assert _reasons(mesh).get("facet", 0) > before


def test_var_define_read_same_block_falls_back(pair):
    plain, mesh = pair
    # x binds at the p1 level and a deeper filter reads it — classic's
    # depth-first binding order is load-bearing, so the block stays
    # classic (reason=var) and stays byte-identical
    q = ('{ q(func: eq(name, "node3")) { p0 { x as p1 '
         '{ p2 @filter(uid(x)) } } } }')
    before = _reasons(mesh).get("var", 0)
    _same(plain, mesh, q)
    assert _reasons(mesh).get("var", 0) > before


def test_multi_pred_recurse_falls_back_labeled(pair):
    plain, mesh = pair
    q = ('{ q(func: eq(name, "node1")) @recurse(depth: 2) '
         '{ follows p0 } }')
    before = _reasons(mesh).get("multi_pred", 0)
    _same(plain, mesh, q)
    assert _reasons(mesh).get("multi_pred", 0) > before


def test_overlay_falls_back_labeled_and_fresh():
    """A commit lands as a delta overlay: the chain bails (reason=
    overlay) but the write is visible immediately and byte-identical."""
    n = Node(mesh_devices=8, mesh_min_edges=1)
    n.alter(schema_text=SCHEMA)
    n.mutate(set_nquads=_quads(), commit_now=True)
    n.task_cache = n.result_cache = None
    q = '{ q(func: uid(0x1)) { p0 { uid p1 { uid } } } }'
    n.query(q)
    n.mutate(set_nquads="<0x1> <p0> <0x4f> .", commit_now=True)
    out, _ = n.query(q)
    uids = {x["uid"] for x in out["q"][0]["p0"]}
    assert "0x4f" in uids
    assert _reasons(n).get("overlay", 0) >= 1


def test_coverage_ratio_on_golden_corpus():
    """The acceptance gate: ≥ 90% of golden-corpus queries that touch
    mesh-owned tablets run their traversals fully fused."""
    from tests.test_golden import QUERIES, SCHEMA as GSCHEMA, _dataset

    n = Node(mesh_devices=8, mesh_min_edges=1)
    n.alter(schema_text=GSCHEMA)
    n.mutate(set_nquads=_dataset(), commit_now=True)
    for _name, q in QUERIES:
        n.query(q)
    fused = n.metrics.counter("dgraph_mesh_fused_queries_total").value
    unfused = n.metrics.counter(
        "dgraph_mesh_unfused_queries_total").value
    assert fused + unfused > 0, "corpus never touched a mesh tablet"
    ratio = fused / (fused + unfused)
    assert ratio >= 0.9, (
        f"fused coverage {ratio:.2f} < 0.9 "
        f"(reasons: {_reasons(n)})")


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------

def test_debug_metrics_mesh_section(pair):
    from dgraph_tpu.api.http import _serving_metrics

    _plain, mesh = pair
    mesh.query('{ q(func: eq(name, "node3")) { p0 { p1 { p2 } } } }')
    m = _serving_metrics(mesh)["mesh"]
    assert m["enabled"] and m["devices"] == 8
    assert m["dispatches"] >= 1 and m["fused_queries"] >= 1
    assert 0.0 <= m["fused_coverage_ratio"] <= 1.0
    assert isinstance(m["fallbacks"], dict)


def test_prom_reason_labels_parse(pair):
    from dgraph_tpu.obs import prom

    plain, mesh = pair
    # force at least one labeled fallback then round-trip /metrics
    _same(plain, mesh,
          '{ q(func: eq(name, "node3")) { p0 @facets(eq(w, 1)) '
          '{ p1 { p2 } } } }')
    text = prom.render(mesh.metrics)
    series = prom.parse(text)
    labeled = [k for k in series
               if k.startswith("dgraph_mesh_fallbacks_total")]
    assert labeled, "reason-labeled fallback series missing"
    assert 'reason="facet"' in text


def test_plan_cache_carries_fused_ir(pair):
    """The planner attaches the chain IR to cached plans: replaying the
    same query hits the plan cache and still fuses (one dispatch)."""
    _plain, mesh = pair
    q = '{ q(func: eq(name, "node9")) { p0 { p1 { p2 } } } }'
    c = mesh.metrics.counter("dgraph_mesh_dispatches_total")
    mesh.query(q)
    hits0 = mesh.metrics.counter("dgraph_planner_cache_hits_total").value
    d0 = c.value
    mesh.query(q)
    assert c.value - d0 == 1
    assert mesh.metrics.counter(
        "dgraph_planner_cache_hits_total").value > hits0
