"""Self-driving shard placement (ISSUE 10): the decision core, the
embedded controller loop, the wire replica protocol, and the systest —
an adversarially skewed read-heavy workload on a 3-group cluster
self-heals below the utilization-spread threshold with byte-identical
results throughout, and --no_rebalance reproduces static placement."""

import json
import time

import pytest

from dgraph_tpu.coord.cluster import Cluster
from dgraph_tpu.coord.placement import (PlacementConfig, TabletRate,
                                        diff_rates, plan_action,
                                        tablet_score, utilization)

SCHEMA = """
    name: string @index(exact) .
    age: int @index(int) .
    follows: [uid] @reverse .
"""


# ---------------------------------------------------------------------------
# decision core (pure): scoring, planning, hysteresis inputs
# ---------------------------------------------------------------------------

def _rates(**groups):
    """groups: g0={attr: (reads_s, writes_s)}"""
    out = {}
    for g, tablets in groups.items():
        gi = int(g[1:])
        out[gi] = {a: TabletRate(reads=r, writes=w)
                   for a, (r, w) in tablets.items()}
    return out


def _sizes(rates, size=1 << 20):
    return {g: {a: size for a in tablets}
            for g, tablets in rates.items()}


def test_score_weighs_size_and_rate():
    assert tablet_score(0, 0.0) == 0.0
    hot_small = tablet_score(1 << 10, 100.0)
    hot_big = tablet_score(1 << 30, 100.0)
    assert hot_big > hot_small > 0
    # cold tablets score ~0 regardless of size (the reference's size-only
    # rebalance would have moved them first)
    assert tablet_score(1 << 30, 0.0) == 0.0


def test_spread_zero_when_idle_or_balanced():
    r = _rates(g0={"a": (10, 0)}, g1={"b": (10, 0)})
    spread, per_group, _ = utilization(_sizes(r), r)
    assert spread == pytest.approx(0.0)
    r = _rates(g0={"a": (0, 0)}, g1={"b": (0, 0)})
    spread, _, _ = utilization(_sizes(r), r)
    assert spread == 0.0


def test_plan_none_below_threshold():
    r = _rates(g0={"a": (12, 0)}, g1={"b": (10, 0)}, g2={"c": (9, 0)})
    act, diag = plan_action(_sizes(r), r, {"a": 0, "b": 1, "c": 2}, {},
                            PlacementConfig())
    assert act is None
    assert diag["spread"] < 0.35


def test_plan_replica_for_skew_dominant_read_heavy():
    r = _rates(g0={"hot": (90, 1)}, g1={"b": (9, 0)}, g2={"c": (3, 0)})
    act, diag = plan_action(_sizes(r), r, {"hot": 0, "b": 1, "c": 2}, {},
                            PlacementConfig())
    assert act is not None and act.kind == "add_replica"
    assert act.attr == "hot" and act.dst == 2     # coldest group
    assert diag["spread"] > 0.35


def test_plan_move_for_multi_tablet_imbalance():
    # three comparable tablets on g0, none dominant: a move fitting half
    # the gap (anti-ping-pong) beats replication
    r = _rates(g0={"a": (20, 0), "b": (18, 0), "c": (16, 0)},
               g1={"d": (5, 0)}, g2={"e": (5, 0)})
    act, _ = plan_action(_sizes(r), r,
                         {"a": 0, "b": 0, "c": 0, "d": 1, "e": 2},
                         {}, PlacementConfig())
    assert act is not None and act.kind == "move"
    assert act.attr in ("a", "b", "c") and act.src == 0


def test_plan_write_hot_tablet_never_replicates():
    # a write-dominant skewed tablet cannot be served read-only elsewhere
    # and exceeds the move gap: the controller must do nothing rather
    # than thrash
    r = _rates(g0={"hot": (10, 50)}, g1={"b": (3, 0)}, g2={"c": (3, 0)})
    act, _ = plan_action(_sizes(r), r, {"hot": 0, "b": 1, "c": 2}, {},
                         PlacementConfig())
    assert act is None


def test_plan_respects_max_replicas_and_existing_holders():
    r = _rates(g0={"hot": (90, 0)}, g1={"b": (5, 0)}, g2={"c": (5, 0)})
    tablets = {"hot": 0, "b": 1, "c": 2}
    cfg = PlacementConfig(max_replicas=1)
    act, _ = plan_action(_sizes(r), r, tablets, {"hot": {2: 10}}, cfg)
    assert act is None or act.kind != "add_replica"
    # and never a holder twice
    cfg = PlacementConfig(max_replicas=4)
    act, _ = plan_action(_sizes(r), r, tablets,
                         {"hot": {1: 10, 2: 10}}, cfg)
    assert act is None or (act.kind, act.dst) != ("add_replica", 2)


def test_plan_demotes_cold_replicated_tablet():
    r = _rates(g0={"hot": (0.0, 0)}, g1={"b": (0.0, 0)}, g2={"c": (0, 0)})
    act, _ = plan_action(_sizes(r), r, {"hot": 0, "b": 1, "c": 2},
                         {"hot": {2: 10}}, PlacementConfig())
    assert act is not None and act.kind == "drop_replica"
    assert act.attr == "hot" and act.dst == 2


def test_plan_skips_blocked_tablets():
    r = _rates(g0={"hot": (90, 0)}, g1={"b": (5, 0)}, g2={"c": (5, 0)})
    act, _ = plan_action(_sizes(r), r, {"hot": 0, "b": 1, "c": 2}, {},
                         PlacementConfig(), blocked={"hot"})
    assert act is None or act.attr != "hot"


def test_diff_rates_handles_counter_restart():
    prev = {"a": {"r": 100.0, "w": 10.0}}
    cur = {"a": {"r": 5.0, "w": 1.0}}       # worker restarted
    out = diff_rates(prev, cur, 1.0)
    assert out["a"].reads == 5.0 and out["a"].writes == 1.0
    out = diff_rates({"a": {"r": 10.0}}, {"a": {"r": 30.0}}, 2.0)
    assert out["a"].reads == 10.0


# ---------------------------------------------------------------------------
# embedded controller loop: hysteresis, cooldown, self-healing
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _skewed_cluster():
    """3 groups, one pinned hot read-heavy tablet + two warm ones."""
    c = Cluster(n_groups=3)
    c.alter(SCHEMA)
    c.zero.move_tablet("name", 0)
    c.zero.move_tablet("age", 1)
    c.zero.move_tablet("follows", 2)
    nq = []
    for i in range(24):
        nq.append(f'_:p{i} <name> "p{i}" .')
        nq.append(f'_:p{i} <age> "{20 + i}"^^<xs:int> .')
    for i in range(23):
        nq.append(f"_:p{i} <follows> _:p{i + 1} .")
    c.mutate(set_nquads="\n".join(nq))
    return c


HOT_Q = '{ q(func: eq(name, "p3")) { name } }'
WARM_QS = ['{ q(func: ge(age, 30)) { age } }',
           '{ q(func: has(follows), first: 3) { uid } }']


def _drive(c, hot=40, warm=4):
    for _ in range(hot):
        c.query(HOT_Q)
    for q in WARM_QS:
        for _ in range(warm):
            c.query(q)


def _golden(c):
    out = [json.dumps(c.query(HOT_Q), sort_keys=True)]
    out += [json.dumps(c.query(q), sort_keys=True) for q in WARM_QS]
    return out


def _check_golden(c, golden):
    got = [json.dumps(c.query(HOT_Q), sort_keys=True)]
    got += [json.dumps(c.query(q), sort_keys=True) for q in WARM_QS]
    assert got == golden


def test_embedded_controller_heals_zipfian_skew():
    """The acceptance loop in miniature: a pinned hot read-heavy tablet
    triggers replica placement (not a move — moving only moves the pin),
    utilization spread converges below threshold, and every query during
    and after the transitions is byte-identical to the static answer."""
    c = _skewed_cluster()
    golden = _golden(c)
    clock = FakeClock()
    cfg = PlacementConfig(threshold=0.5, persist_ticks=2, cooldown_s=5.0,
                          max_replicas=2, min_rate=0.5)
    ctl = c.placement_controller(cfg=cfg, clock=clock)

    ctl.tick()                               # primes cumulative counters
    actions = []
    spread_ok = False
    for _tick in range(10):
        _drive(c)
        _check_golden(c, golden)
        clock.advance(10.0)                  # past cooldown each tick
        act = ctl.tick()
        if act is not None:
            actions.append(act)
            _check_golden(c, golden)         # correct THROUGH the action
        if actions and ctl.last_diag.get("spread", 1.0) <= cfg.threshold:
            spread_ok = True
            break
    assert actions, "controller never acted on an adversarial skew"
    assert any(a.kind == "add_replica" and a.attr == "name"
               for a in actions), actions
    assert spread_ok, (ctl.last_diag, actions)
    assert c.zero.replica_holders("name"), "no replica registered"
    _check_golden(c, golden)
    # the decision log journals every action with its reason
    events = [d["event"] for d in ctl.decisions()]
    assert "action" in events
    # controller metrics are live
    assert ctl.metrics.counter(
        "dgraph_placement_replicas_added_total").value >= 1


def test_embedded_controller_hysteresis_and_cooldown():
    """One poll of imbalance never acts (persist_ticks); after an action
    the same tablet is quiet for cooldown_s even under fresh imbalance."""
    c = _skewed_cluster()
    clock = FakeClock()
    cfg = PlacementConfig(threshold=0.3, persist_ticks=2, cooldown_s=30.0,
                          max_replicas=4)
    ctl = c.placement_controller(cfg=cfg, clock=clock)
    ctl.tick()
    _drive(c)
    clock.advance(5.0)
    assert ctl.tick() is None                # streak 1 < persist_ticks
    assert any(d["event"] == "defer" for d in ctl.decisions())
    _drive(c)
    clock.advance(5.0)
    first = ctl.tick()                       # streak 2: acts
    assert first is not None
    # cooldown: same hot tablet, imbalance persists, but no second action
    acted_again = []
    for _ in range(2):
        _drive(c)
        clock.advance(5.0)                   # < cooldown_s from action
        act = ctl.tick()
        if act is not None and act.attr == first.attr:
            acted_again.append(act)
    assert not acted_again, acted_again
    assert ctl.metrics.counter(
        "dgraph_placement_cooldown_skips_total").value >= 1 or \
        any(d["event"] in ("cooldown", "defer") for d in ctl.decisions())


def test_embedded_controller_demotes_when_load_subsides():
    c = _skewed_cluster()
    c.add_replica("name", 2)
    assert c.zero.replica_holders("name")
    clock = FakeClock()
    cfg = PlacementConfig(cooldown_s=1.0)
    ctl = c.placement_controller(cfg=cfg, clock=clock)
    ctl.tick()
    clock.advance(10.0)
    act = ctl.tick()                         # idle tablet -> demote
    assert act is not None and act.kind == "drop_replica", act
    assert not c.zero.replica_holders("name")
    # the copy is gone from the holder's store
    assert "name" not in c.stores[2].predicates()


def test_embedded_move_drops_replicas_first():
    c = _skewed_cluster()
    c.add_replica("name", 1)
    c.move_predicate("name", 1)              # move INTO the holder group
    assert c.zero.tablets()["name"] == 1
    assert not c.zero.replica_holders("name")
    out = c.query(HOT_Q)
    assert out["q"] == [{"name": "p3"}]


def test_no_rebalance_reproduces_static_behavior():
    """Without a controller the maps never change under the same load —
    the --no_rebalance contract."""
    c = _skewed_cluster()
    tablets_before = c.zero.tablets()
    golden = _golden(c)
    for _ in range(3):
        _drive(c)
    assert c.zero.tablets() == tablets_before
    assert c.zero.replicas() == {}
    _check_golden(c, golden)


def test_zero_replica_map_survives_restart(tmp_path):
    """The replica map rides zero_state.json like the tablet map: a
    restarted Zero keeps routing reads to holders it installed."""
    from dgraph_tpu.coord.zero import Zero

    z = Zero(3, dirpath=str(tmp_path))
    assert z.should_serve("name") == 0
    z.add_replica("name", 2, 17)
    z.add_replica("name", 0, 5)              # owner: silently refused
    z2 = Zero(3, dirpath=str(tmp_path))
    assert z2.replica_holders("name") == {2: 17}
    assert z2.state()["replicaMap"] == {"name": [2]}
    z2.set_replica_watermark("name", 2, 23)
    z2.move_tablet("name", 2)                # holder becomes owner
    z3 = Zero(3, dirpath=str(tmp_path))
    assert z3.replica_holders("name") == {}
    assert z3.tablets()["name"] == 2


def test_tablet_load_on_metrics_surfaces():
    """Satellite: per-tablet read/write/bytes counters surface as the
    labeled dgraph_tablet_load{pred,group,stat} series on /metrics and in
    the /debug/metrics tablet_load section — inspectable independently of
    any controller."""
    from dgraph_tpu.api.http import _serving_metrics
    from dgraph_tpu.api.server import Node
    from dgraph_tpu.obs import prom

    node = Node()
    node.alter(schema_text=SCHEMA)
    node.mutate(set_nquads='_:a <name> "x" .\n_:a <age> "30"^^<xs:int> .',
                commit_now=True)
    for _ in range(3):
        node.query('{ q(func: eq(name, "x")) { name age } }')
    try:
        sect = _serving_metrics(node)["tablet_load"]
        assert sect["name"]["r"] >= 1 and sect["name"]["w"] >= 1
        assert {"r", "w", "b", "d"} <= set(sect["name"])
        text = prom.render(node.metrics)
        series = prom.parse(text)
        assert "dgraph_tablet_load" in series
        labels = {tuple(sorted(ls)) for ls, _v in
                  series["dgraph_tablet_load"]}
        assert ("group", "pred", "stat") in labels
        by = {(ls["pred"], ls["stat"]): v
              for ls, v in series["dgraph_tablet_load"]}
        assert by[("name", "reads")] >= 1
        assert by[("name", "writes")] >= 1
        assert by[("name", "bytes")] >= 1
    finally:
        node.close()


# ---------------------------------------------------------------------------
# wire protocol: replica install / staleness routing / delta ship / drop
# ---------------------------------------------------------------------------

@pytest.fixture
def wire3():
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from dgraph_tpu.coord.zero import Zero
    from dgraph_tpu.coord.zero_service import ZeroOps, serve_zero
    from dgraph_tpu.parallel.client import ClusterClient
    from dgraph_tpu.parallel.remote import serve_worker
    from dgraph_tpu.storage.store import Store
    from dgraph_tpu.utils.schema import parse_schema

    zero = Zero(3)
    zero.move_tablet("name", 0)
    zero.move_tablet("age", 1)
    zero.move_tablet("follows", 2)
    zsrv, zport, svc = serve_zero(zero, "localhost:0")
    stores, workers, addrs = [], [], []
    for g in range(3):
        s = Store()
        for e in parse_schema(SCHEMA):
            s.set_schema(e)
        stores.append(s)
        srv, port = serve_worker(s, "localhost:0")
        workers.append(srv)
        addrs.append(f"localhost:{port}")
        svc._members[g] = [addrs[g]]
    client = ClusterClient(f"localhost:{zport}",
                           {g: [addrs[g]] for g in range(3)})
    nq = []
    for i in range(20):
        nq.append(f'_:p{i} <name> "p{i}" .')
        nq.append(f'_:p{i} <age> "{20 + i}"^^<xs:int> .')
    for i in range(19):
        nq.append(f"_:p{i} <follows> _:p{i + 1} .")
    client.mutate(set_nquads="\n".join(nq))
    ops = ZeroOps(svc)
    yield zero, ops, client, workers, stores
    client.close()
    for w in workers:
        w.stop(0)
    zsrv.stop(0)


def _wire_query(client, q):
    client.task_cache.clear()         # force the wire (and the router)
    return json.dumps(client.query(q), sort_keys=True)


def test_wire_replica_serves_and_stale_routes_to_primary(wire3):
    """Satellite: a replica behind the primary's applied watermark must
    route back to the primary (FAILED_PRECONDITION path), never serve
    stale; after the delta ship it serves again."""
    zero, ops, client, workers, stores = wire3
    q = '{ q(func: eq(name, "p3")) { name age } }'
    golden = _wire_query(client, q)
    out = ops.install_replica("name", 2)
    assert out["installed_records"] > 0
    assert 2 in zero.replica_holders("name")

    # spread: the holder serves some 'name' tasks, byte-identical
    for _ in range(8):
        assert _wire_query(client, q) == golden
    holder_loads = workers[2].dgt_svc.tablet_load_snapshot()
    assert holder_loads.get("name", {}).get("r", 0) > 0
    assert client.metrics.counter("dgraph_replica_reads_total").value > 0

    # a write makes the replica stale: reads MUST fall back to the
    # primary and see the new value immediately
    client.mutate(set_nquads='_:x <name> "fresh" .')
    fb0 = client.metrics.counter("dgraph_replica_fallbacks_total").value
    for _ in range(4):
        client.task_cache.clear()
        r = client.query('{ q(func: eq(name, "fresh")) { name } }')
        assert r["q"] == [{"name": "fresh"}], r
    assert client.metrics.counter(
        "dgraph_replica_fallbacks_total").value > fb0

    # freshness ship: the O(Δ) journal rewrite catches the holder up and
    # it serves the NEW value byte-identically
    out = ops.ship_replica_delta("name", 2)
    assert out["shipped_records"] > 0
    new_golden = _wire_query(client, '{ q(func: eq(name, "fresh")) '
                                     '{ name } }')
    r0 = client.metrics.counter("dgraph_replica_reads_total").value
    for _ in range(8):
        assert _wire_query(client, '{ q(func: eq(name, "fresh")) '
                                   '{ name } }') == new_golden
    assert client.metrics.counter(
        "dgraph_replica_reads_total").value > r0

    # demotion: routing collapses to the primary, results unchanged
    assert ops.drop_replica("name", 2)
    assert "name" not in stores[2].predicates()
    assert _wire_query(client, q) != ""      # still answers
    assert zero.replica_holders("name") == {}


def test_wire_move_drops_replicas_first(wire3):
    zero, ops, client, workers, stores = wire3
    q = '{ q(func: eq(name, "p3")) { name } }'
    golden = _wire_query(client, q)
    ops.install_replica("name", 1)
    out = ops.move_tablet("name", 1)         # move INTO the holder group
    assert out["tablet"] == "name"
    assert zero.tablets()["name"] == 1
    assert zero.replica_holders("name") == {}
    assert _wire_query(client, q) == golden


def test_wire_status_carries_tablet_load(wire3):
    zero, ops, client, workers, stores = wire3
    _wire_query(client, '{ q(func: eq(name, "p3")) { name } }')
    from dgraph_tpu.parallel.remote import RemoteWorker

    rw = RemoteWorker(client.replicas[0].addrs[0])
    try:
        st = rw.status()
        loads = json.loads(st.tablet_load_json)
    finally:
        rw.close()
    assert loads.get("name", {}).get("r", 0) >= 1
    assert {"r", "w", "b", "d"} <= set(loads["name"])


def test_wire_systest_zipfian_self_heal(wire3):
    """Acceptance systest: adversarially skewed (Zipfian, read-heavy)
    load on a 3-group wire cluster; the controller converges utilization
    spread below threshold within a bounded number of ticks, with every
    sampled result byte-identical through moves/replica transitions."""
    import random

    from dgraph_tpu.coord.placement import (PlacementController,
                                            ZeroOpsExecutor, wire_collect)

    zero, ops, client, workers, stores = wire3
    rng = random.Random(20260803)
    battery = {
        "name": '{ q(func: eq(name, "p%d")) { name } }',
        "age": '{ q(func: ge(age, %d)) { age } }',
        "follows": '{ q(func: has(follows), first: %d) { uid } }',
    }
    goldens = {}
    for i in range(6):
        goldens[("name", i)] = _wire_query(client, battery["name"] % i)
    goldens[("age", 30)] = _wire_query(client, battery["age"] % 30)
    goldens[("follows", 3)] = _wire_query(client, battery["follows"] % 3)

    def zipf_round(n=60):
        # ~85% of traffic hammers the 'name' tablet (rank-1 of a Zipfian),
        # the rest trickles to the others — the one-hot-predicate shape
        for _ in range(n):
            r = rng.random()
            if r < 0.85:
                i = rng.randrange(6)
                assert _wire_query(client,
                                   battery["name"] % i) == goldens[
                                       ("name", i)]
            elif r < 0.93:
                assert _wire_query(client,
                                   battery["age"] % 30) == goldens[
                                       ("age", 30)]
            else:
                assert _wire_query(client,
                                   battery["follows"] % 3) == goldens[
                                       ("follows", 3)]

    cfg = PlacementConfig(threshold=0.6, persist_ticks=1, cooldown_s=0.0,
                          max_replicas=2, min_rate=0.5)
    ctl = PlacementController(zero, wire_collect(ops),
                              ZeroOpsExecutor(ops), cfg=cfg)
    ctl.tick()                                # primes counters
    actions = []
    healed = False
    for _tick in range(8):
        time.sleep(0.05)                      # a real dt for the rates
        zipf_round()
        act = ctl.tick()
        if act is not None:
            actions.append(act)
        if actions and ctl.last_diag.get("spread", 1.0) <= cfg.threshold:
            healed = True
            break
    assert actions, "controller never acted"
    assert healed, (ctl.last_diag, actions)
    # the hot tablet grew replicas (read-heavy skew => replication, and
    # reads actually spread: holders show serve counts)
    holders = zero.replica_holders("name")
    assert holders, actions
    served = sum(workers[g].dgt_svc.tablet_load_snapshot()
                 .get("name", {}).get("r", 0) for g in holders)
    assert served > 0
    # one more full round stays byte-identical in the healed layout
    zipf_round(30)
