"""Hedged remote reads + background health echo (VERDICT r4 #7).

Reference: worker/task.go:75-132 processWithBackupRequest (grace-period
backup request to a second replica), conn/pool.go:153-186 Echo health loop.
Staleness guard: TaskRequest.min_applied makes a behind follower wait for
its applied per-tablet watermark or refuse (FAILED_PRECONDITION), so a
hedged read can never answer from a replica that missed a commit.
"""

import time

import pytest

grpc = pytest.importorskip("grpc")

from dgraph_tpu.parallel.remote import (HedgedReplicas, RemoteWorker,
                                        WorkerService)
from dgraph_tpu.query import mutation as mut
from dgraph_tpu.query import rdf
from dgraph_tpu.query.task import TaskQuery
from dgraph_tpu.storage.store import Store
from dgraph_tpu.utils.schema import parse_schema


def _serve(svc):
    import concurrent.futures as _f

    server = grpc.server(_f.ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((svc.handler(),))
    port = server.add_insecure_port("localhost:0")
    server.start()
    return server, f"localhost:{port}"


def _mk_pair(nquads):
    """Two identical stores behind live gRPC servers."""
    from dgraph_tpu.coord.zero import UidLease
    from dgraph_tpu.storage.postings import Op

    svcs, servers, addrs = [], [], []
    for _ in range(2):
        s = Store()
        for e in parse_schema("name: string @index(exact) .\nv: int ."):
            s.set_schema(e)
        edges = mut.to_edges(rdf.parse(nquads),
                             mut.assign_uids(rdf.parse(nquads), UidLease()),
                             Op.SET)
        touched, _, _ = mut.apply_mutations(s, edges, 1)
        s.commit(1, 2, touched)
        svc = WorkerService(s)
        server, addr = _serve(svc)
        svcs.append(svc)
        servers.append(server)
        addrs.append(addr)
    return svcs, servers, addrs


NQ = "\n".join(f'<0x{i:x}> <name> "p{i}" .' for i in range(1, 9))


def test_hedge_slow_primary_does_not_stall():
    svcs, servers, addrs = _mk_pair(NQ)
    real = svcs[0].serve_task

    def slow(msg, ctx):
        time.sleep(3.0)
        return real(msg, ctx)

    # handler() captured the bound method at registration — re-serve with
    # the slow wrapper bound first
    for s in servers:
        s.stop(0)
    svcs[0].serve_task = slow
    servers, addrs = [], []
    for svc in svcs:
        server, addr = _serve(svc)
        servers.append(server)
        addrs.append(addr)

    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.15
    try:
        t0 = time.monotonic()
        # min_applied > 0: hedging engages (floor-less reads route to the
        # leader only and never hedge to possibly-stale followers)
        res = hr.process_task(TaskQuery("name", func=("eq", ["p3"])), 5,
                              min_applied=2)
        dt = time.monotonic() - t0
        assert list(res.dest_uids) == [3]
        assert dt < 2.0, f"hedge did not fire (took {dt:.1f}s)"
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_dead_replica_fails_over():
    svcs, servers, addrs = _mk_pair(NQ)
    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.15
    try:
        servers[0].stop(0)        # primary dies
        res = hr.process_task(TaskQuery("name", func=("eq", ["p5"])), 5,
                              min_applied=2)
        assert list(res.dest_uids) == [5]
        # echo loop eventually marks it unhealthy and reroutes directly
        hr._poll_once()
        assert hr._ok == [False, True]
        assert hr._order()[0] == 1
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_min_applied_gate_blocks_behind_replica():
    """A follower missing a commit refuses (or waits out) a gated read."""
    svcs, servers, addrs = _mk_pair(NQ)
    rw = RemoteWorker(addrs[0])
    svcs[0].APPLIED_WAIT = 0.2
    try:
        # both stores applied commit_ts=2; a floor above that must block
        with pytest.raises(grpc.RpcError) as ei:
            rw.process_task(TaskQuery("name", func=("eq", ["p3"])), 5,
                            min_applied=99)
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        # at/below the applied watermark it serves fine
        res = rw.process_task(TaskQuery("name", func=("eq", ["p3"])), 5,
                              min_applied=2)
        assert list(res.dest_uids) == [3]
    finally:
        rw.close()
        for s in servers:
            s.stop(0)


def test_min_applied_gate_unblocks_when_caught_up():
    import threading

    svcs, servers, addrs = _mk_pair(NQ)
    rw = RemoteWorker(addrs[0])
    svcs[0].APPLIED_WAIT = 5.0
    store = svcs[0].store

    def catch_up():
        time.sleep(0.15)
        store.pred_commit_ts["name"] = 50
        # the replica-read gate blocks on the applied WaterMark now, not a
        # poll loop — advance it the way a real commit's _bump_pred_ts does
        store.applied_mark("name").set_done_until(50)

    try:
        threading.Thread(target=catch_up, daemon=True).start()
        res = rw.process_task(TaskQuery("name", func=("eq", ["p7"])), 5,
                              min_applied=50)
        assert list(res.dest_uids) == [7]
    finally:
        rw.close()
        for s in servers:
            s.stop(0)


def test_hedged_both_dead_raises():
    svcs, servers, addrs = _mk_pair(NQ)
    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.1
    try:
        for s in servers:
            s.stop(0)
        with pytest.raises(Exception):
            hr.process_task(TaskQuery("name", func=("eq", ["p1"])), 5,
                            min_applied=2)
    finally:
        hr.close()


def test_floorless_read_routes_to_leader_only():
    """min_applied == 0 (cold cluster / Zero restart): never hedge to a
    follower whose staleness the gate cannot check."""
    svcs, servers, addrs = _mk_pair(NQ)
    svcs[1].is_leader = True      # replica 1 is the (status-visible) leader
    calls = []
    real = svcs[0].serve_task
    svcs[0].serve_task = lambda m, c: calls.append(1) or real(m, c)
    hr = HedgedReplicas(addrs)
    try:
        hr._poll_once()
        res = hr.process_task(TaskQuery("name", func=("eq", ["p2"])), 5)
        assert list(res.dest_uids) == [2]
        assert not calls, "floor-less read touched a non-leader replica"
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_wedged_floor_falls_back_to_leader():
    """Every replica behind an orphaned floor (lost Decide): reads serve
    the leader's best state instead of failing forever."""
    svcs, servers, addrs = _mk_pair(NQ)
    svcs[0].is_leader = True
    for svc in svcs:
        svc.APPLIED_WAIT = 0.1
    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.05
    try:
        hr._poll_once()
        res = hr.process_task(TaskQuery("name", func=("eq", ["p4"])), 5,
                              min_applied=999)   # nobody ever applied this
        assert list(res.dest_uids) == [4]
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_hedge_never_fires_below_grace_budget():
    """ISSUE 7 satellite: with remaining budget < HEDGE_GRACE a hedge
    could never beat the deadline — the backup request must NOT fire
    (sequential failover within the budget instead)."""
    from dgraph_tpu.utils import deadline as dl
    from dgraph_tpu.utils.deadline import DeadlineExceeded

    svcs, servers, addrs = _mk_pair(NQ)
    real = svcs[0].serve_task

    def slow(msg, ctx):
        time.sleep(1.0)
        return real(msg, ctx)

    backup_calls = []
    real1 = svcs[1].serve_task
    for s in servers:
        s.stop(0)
    svcs[0].serve_task = slow
    svcs[1].serve_task = lambda m, c: backup_calls.append(1) or real1(m, c)
    servers, addrs = [], []
    for svc in svcs:
        server, addr = _serve(svc)
        servers.append(server)
        addrs.append(addr)

    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.3
    try:
        t0 = time.monotonic()
        with dl.scope(0.15):          # budget < grace
            with pytest.raises((DeadlineExceeded, grpc.RpcError)):
                hr.process_task(TaskQuery("name", func=("eq", ["p3"])), 5,
                                min_applied=2)
        dt = time.monotonic() - t0
        assert dt < 0.8, f"wait was not deadline-bounded ({dt:.2f}s)"
        assert not backup_calls, "hedge fired below the grace budget"
        assert hr.metrics.counter("dgraph_hedge_fired_total").value == 0
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_hedge_counts_metric_when_it_fires():
    svcs, servers, addrs = _mk_pair(NQ)
    real = svcs[0].serve_task

    def slow(msg, ctx):
        time.sleep(1.0)
        return real(msg, ctx)

    for s in servers:
        s.stop(0)
    svcs[0].serve_task = slow
    servers, addrs = [], []
    for svc in svcs:
        server, addr = _serve(svc)
        servers.append(server)
        addrs.append(addr)
    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.1
    try:
        res = hr.process_task(TaskQuery("name", func=("eq", ["p3"])), 5,
                              min_applied=2)
        assert list(res.dest_uids) == [3]
        assert hr.metrics.counter("dgraph_hedge_fired_total").value == 1
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_breaker_open_replica_is_skipped():
    """ISSUE 7 satellite: a replica whose circuit breaker is OPEN is
    routed around — fan-out does not pay its timeout per request — and
    half-open probes re-admit it once it recovers."""
    from dgraph_tpu.utils.retry import CircuitBreaker

    svcs, servers, addrs = _mk_pair(NQ)
    calls = [0, 0]
    reals = [svc.serve_task for svc in svcs]

    def count(i):
        def h(m, c):
            calls[i] += 1
            return reals[i](m, c)
        return h

    for s in servers:
        s.stop(0)
    for i, svc in enumerate(svcs):
        svc.serve_task = count(i)
    servers, addrs = [], []
    for svc in svcs:
        server, addr = _serve(svc)
        servers.append(server)
        addrs.append(addr)
    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.05
    try:
        # trip replica 0's breaker the way real traffic would: transport
        # failures recorded against it
        for _ in range(hr.BREAKER_FAILS):
            hr._record(0, False, e=ConnectionError("down"))
        assert hr.breakers[0].state == CircuitBreaker.OPEN
        assert hr.metrics.counter("dgraph_breaker_open_total").value == 1
        assert hr.metrics.keyed("dgraph_breaker_state").get(addrs[0]) == 2
        assert hr._order()[0] == 1      # open breaker demoted from primary
        before = calls[0]
        res = hr.process_task(TaskQuery("name", func=("eq", ["p5"])), 5,
                              min_applied=2)
        assert list(res.dest_uids) == [5]
        assert calls[0] == before, "breaker-open replica was still dialed"
        # recovery: after open_s the replica goes half-open (demoted
        # behind closed replicas in routing), and the Status echo loop is
        # the no-traffic probe that closes it
        hr.breakers[0]._opened_at -= (hr.BREAKER_OPEN_S + 1)
        assert hr.breakers[0].state == CircuitBreaker.HALF_OPEN
        assert hr._order()[0] == 1      # half-open: still not primary
        hr._poll_once()                 # echo succeeds -> breaker closes
        assert hr.breakers[0].state == CircuitBreaker.CLOSED
        assert hr._order()[0] == 0      # back to primary (it is idx 0)
        res = hr.process_task(TaskQuery("name", func=("eq", ["p6"])), 5,
                              min_applied=2)
        assert list(res.dest_uids) == [6]
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_deadline_errors_do_not_trip_breaker():
    """Caller-budget exhaustion is the budget's fault, not the
    replica's: neither the typed DeadlineExceeded nor a wire
    DEADLINE_EXCEEDED may open a healthy replica's breaker."""
    from dgraph_tpu.utils.deadline import DeadlineExceeded
    from dgraph_tpu.utils.retry import CircuitBreaker

    hr = HedgedReplicas(["localhost:9"])
    try:
        for _ in range(hr.BREAKER_FAILS + 2):
            hr._record(0, False, e=DeadlineExceeded("budget gone"))
        assert hr.breakers[0].state == CircuitBreaker.CLOSED
        for _ in range(hr.BREAKER_FAILS):
            hr._record(0, False, e=ConnectionError("real fault"))
        assert hr.breakers[0].state == CircuitBreaker.OPEN
    finally:
        hr.close()


def test_behind_replica_does_not_trip_breaker():
    """FAILED_PRECONDITION (replica behind the floor / not leader) is an
    application-level refusal, not a transport fault — it must never open
    the breaker and cut the replica out of routing."""
    from dgraph_tpu.utils.retry import CircuitBreaker

    svcs, servers, addrs = _mk_pair(NQ)
    for svc in svcs:
        svc.APPLIED_WAIT = 0.05
    hr = HedgedReplicas(addrs)
    hr.HEDGE_GRACE = 0.05
    try:
        svcs[0].is_leader = True
        hr._poll_once()
        for _ in range(hr.BREAKER_FAILS + 1):
            res = hr.process_task(TaskQuery("name", func=("eq", ["p2"])),
                                  5, min_applied=999)   # wedged floor
            assert list(res.dest_uids) == [2]           # leader fallback
        assert all(b.state == CircuitBreaker.CLOSED for b in hr.breakers)
    finally:
        hr.close()
        for s in servers:
            s.stop(0)


def test_single_replica_behind_floor_retries_floorless():
    """Satellite regression (PR 3): a SINGLE-replica group whose sole
    replica reports FAILED_PRECONDITION after the applied-wait must get
    the same lost-Decide fallback the multi-replica path has — one retry
    with min_applied=0 — instead of surfacing the error to the client."""
    svcs, servers, addrs = _mk_pair(NQ)
    # sole replica: a one-address group
    svcs[0].APPLIED_WAIT = 0.1
    hr = HedgedReplicas(addrs[:1])
    try:
        res = hr.process_task(TaskQuery("name", func=("eq", ["p3"])), 5,
                              min_applied=999)   # floor nobody applied
        assert list(res.dest_uids) == [3]
    finally:
        hr.close()
        for s in servers:
            s.stop(0)
