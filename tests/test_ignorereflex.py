"""@ignorereflex + the parsed-but-dropped directive audit (VERDICT r4 #6).

Reference semantics (query/query.go:371,433,541): with @ignorereflex a node
never appears in its own subtree — an ancestor stack is checked while
building the response, so self-loops and back-edges to any ancestor are
dropped from the output (the traversal itself is unchanged).
"""

import pytest

from dgraph_tpu.api.server import Node
from dgraph_tpu.query.dql import ParseError


@pytest.fixture()
def tri_node():
    n = Node()
    n.alter(schema_text="name: string .\nfriend: [uid] .")
    quads = [
        "<0x1> <friend> <0x2> .",
        "<0x2> <friend> <0x1> .",     # back-edge to parent
        "<0x2> <friend> <0x3> .",
        "<0x3> <friend> <0x3> .",     # self-loop
        '<0x1> <name> "a" .', '<0x2> <name> "b" .', '<0x3> <name> "c" .',
    ]
    n.mutate(set_nquads="\n".join(quads), commit_now=True)
    return n


def test_ignorereflex_drops_ancestors(tri_node):
    q = """{ q(func: uid(0x1)) @ignorereflex {
        name friend { name friend { name } } } }"""
    out, _ = tri_node.query(q)
    a = out["q"][0]
    assert a["name"] == "a"
    b = a["friend"][0]
    assert b["name"] == "b"
    # b's friends are [a (ancestor), c] — a must be dropped
    assert [x["name"] for x in b["friend"]] == ["c"]


def test_ignorereflex_drops_self_loop(tri_node):
    q = "{ q(func: uid(0x3)) @ignorereflex { name friend { name } } }"
    out, _ = tri_node.query(q)
    c = out["q"][0]
    assert c["name"] == "c"
    assert "friend" not in c      # only friend was itself


def test_without_directive_reflexive_edges_stay(tri_node):
    q = "{ q(func: uid(0x3)) { name friend { name } } }"
    out, _ = tri_node.query(q)
    assert [x["name"] for x in out["q"][0]["friend"]] == ["c"]


def test_ignorereflex_nested_count(tri_node):
    q = """{ q(func: uid(0x1)) @ignorereflex {
        friend { count(uid) friend { uid } } } }"""
    out, _ = tri_node.query(q)
    flist = out["q"][0]["friend"]
    # count object precedes the node objects (dgraph list shape)
    assert flist[0] == {"count": 1}          # a's friends: just b
    # b's subtree drops ancestor a: only the self-loop-free c remains
    assert flist[1]["friend"] == [{"uid": "0x3"}]


def test_unknown_directive_rejected(tri_node):
    with pytest.raises(ParseError, match="unknown directive"):
        tri_node.query("{ q(func: uid(0x1)) @nosuchdirective { name } }")


def test_expand_value_var_nonpredicate_names(tri_node):
    # value-var values that aren't real predicates expand to nothing
    q = """{
      var(func: uid(0x1)) { p as name }
      q(func: uid(0x2)) { expand(p) }
    }"""
    out, _ = tri_node.query(q)
    assert out.get("q", []) in ([], [{}]) or "name" not in out["q"][0]


def test_expand_uid_var_rejected(tri_node):
    q = """{
      var(func: uid(0x1)) { f as friend }
      q(func: uid(0x1)) { expand(f) }
    }"""
    with pytest.raises(Exception, match="expand"):
        tri_node.query(q)


def test_expand_value_var_with_names(tri_node):
    # build a var whose VALUES are predicate names ("name"), then expand it
    n = Node()
    n.alter(schema_text="name: string .\npredname: string .\nfriend: [uid] .")
    n.mutate(set_nquads="\n".join([
        '<0x1> <predname> "name" .',
        '<0x2> <name> "bob" .',
    ]), commit_now=True)
    q = """{
      var(func: uid(0x1)) { p as predname }
      q(func: uid(0x2)) { expand(p) }
    }"""
    out, _ = n.query(q)
    assert out["q"][0]["name"] == "bob"
